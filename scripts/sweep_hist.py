"""Sweep the histogram kernel's (lo, tile_rows) per tree level.

The _lo_factor chooser (ops/histogram.py) minimizes a construction-op
model 5A + 2lo calibrated on v5e at 4M rows; this sweep re-measures the
actual per-level cost at the north-star shape (10M rows) including
lo=256 (hi=1: LHS one-hot degenerates to the node plane) and a 16384 row
tile.  Slope timing over two scan lengths cancels the tunnel's fixed
dispatch+fetch overhead (see profile_pieces.py).

Usage: ``ROWS=10000000 python scripts/sweep_hist.py``.
"""
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from dmlc_core_tpu.ops import histogram as H
from dmlc_core_tpu.ops.quantile import apply_bins, compute_cuts

ROWS = int(os.environ.get("ROWS", 4_000_000))
F = int(os.environ.get("FEATURES", 28))
B = int(os.environ.get("BINS", 256))
DEPTH = int(os.environ.get("DEPTH", 6))
N1 = int(os.environ.get("N1", 5))
N2 = int(os.environ.get("N2", 25))
LOS = [int(x) for x in os.environ.get("LOS", "32,64,128,256").split(",")]
TILES = [int(x) for x in os.environ.get("TILES", "8192,16384").split(",")]

rng = np.random.default_rng(0)
X = rng.normal(size=(ROWS, F)).astype(np.float32)
bins_t = jnp.asarray(np.asarray(
    apply_bins(jnp.asarray(X), compute_cuts(X, B))).T)
g0 = jnp.asarray(rng.normal(size=ROWS).astype(np.float32))
h0 = jnp.abs(g0) + 0.1
np.asarray(bins_t[0, :1])


def tiny(x):
    return jnp.sum(x.ravel()[:4].astype(jnp.float32)) * jnp.float32(1e-30)


def slope(step, *args):
    @partial(jax.jit, static_argnums=(0,))
    def run(n, *a):
        return jax.lax.scan(lambda c, _: (step(c, *a), None),
                            jnp.float32(0.0), None, length=n)[0]

    def once(n):
        np.asarray(run(n, *args))
        t0 = time.perf_counter()
        np.asarray(run(n, *args))
        return time.perf_counter() - t0

    t1, t2 = once(N1), once(N2)
    return (t2 - t1) / (N2 - N1)


results = {}
for level in range(DEPTH):
    n_build = 1 if level == 0 else 1 << (level - 1)
    if level == 0:
        node_h = jnp.zeros(ROWS, jnp.int32)
    else:
        full = jnp.asarray(rng.integers(0, 2 * n_build, ROWS)
                           .astype(np.int32))
        node_h = jnp.where(full % 2 == 0, full >> 1, -1)
    cur = H._lo_factor(n_build, B)
    for lo in LOS:
        if lo > B:
            continue
        for tile in TILES:
            # _lo_factor inside _pallas_ok would override the swept lo;
            # check the swept config's own budget instead
            hi = -(-B // lo)
            nh = n_build * hi
            fp = -(-F // 8) * 8
            acc = fp * 2 * nh * max(lo, 128) * 4
            stack = tile * (fp + 120 + 6 * nh + 2 * lo)
            if acc > 24 << 20 or stack > 15 << 20:
                print(f"L{level} lo={lo} tile={tile}: skipped "
                      f"(vmem budget)", flush=True)
                continue

            def step(c, b_t, nh, gg, hh, lo=lo, tile=tile):
                out = H._hist_pallas(b_t, nh, gg + c, hh, n_build, B,
                                     tile, lo, True)
                return tiny(out)

            dt = slope(step, bins_t, node_h, g0, h0)
            tag = ("  <-- current"
                   if (lo == cur and tile == H._TILE_ROWS) else "")
            print(f"L{level} n_build={n_build:2d} lo={lo:3d} tile={tile:5d} "
                  f"{dt*1e3:9.2f} ms{tag}", flush=True)
            results[f"L{level}/lo{lo}/t{tile}"] = round(dt * 1e3, 3)
print(json.dumps(results))
