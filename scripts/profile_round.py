"""Break down where hist-GBT round time goes on the real chip.

Times each component of a boosting round separately at bench shapes:
histogram per level (pallas + matmul), descent (table_select/row_bin),
leaf sums, grad/hess, and the full fused round_fn.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from dmlc_core_tpu.ops.histogram import build_histogram
from dmlc_core_tpu.ops.quantile import apply_bins, compute_cuts

ROWS = int(os.environ.get("ROWS", 4_000_000))
F = 28
B = 256
DEPTH = 6

rng = np.random.default_rng(0)
X = rng.normal(size=(ROWS, F)).astype(np.float32)
cuts = compute_cuts(X, B)
bins = apply_bins(jnp.asarray(X), cuts)
bins = jax.block_until_ready(bins)
print("bins dtype", bins.dtype, flush=True)

g = jnp.asarray(rng.normal(size=ROWS).astype(np.float32))
h = jnp.abs(g) + 0.1
node_per_level = {}
node = jnp.zeros(ROWS, jnp.int32)
for lvl in range(DEPTH):
    node_per_level[lvl] = node % (1 << lvl)


def timeit(fn, *args, n=5, label=""):
    out = jax.block_until_ready(fn(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(n):
        out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / n
    print(f"{label:40s} {dt*1e3:8.2f} ms", flush=True)
    return dt


total_hist = {}
for method in ("pallas", "matmul"):
    tot = 0.0
    for lvl in range(DEPTH):
        n_nodes = 1 << lvl
        nid = node_per_level[lvl]
        tot += timeit(
            lambda b, nd, gg, hh, nn=n_nodes, m=method: build_histogram(
                b, nd, gg, hh, nn, B, m),
            bins, nid, g, h, label=f"hist[{method}] level {lvl} (N={n_nodes})")
    total_hist[method] = tot
    print(f"  == total hist {method}: {tot*1e3:.1f} ms", flush=True)

# descent cost at deepest level
def descend(bins_l, node, feat, thr):
    n_nodes = feat.shape[0]
    n_iota = jnp.arange(n_nodes, dtype=jnp.int32)[None, :]
    oh = node[:, None] == n_iota
    feat_sel = jnp.sum(jnp.where(oh, feat[None, :], 0), axis=1)
    thr_sel = jnp.sum(jnp.where(oh, thr[None, :], 0), axis=1)
    f_iota = jnp.arange(bins_l.shape[1], dtype=jnp.int32)[None, :]
    row_bin = jnp.sum(
        jnp.where(feat_sel[:, None] == f_iota, bins_l.astype(jnp.int32), 0),
        axis=1)
    return 2 * node + (row_bin > thr_sel).astype(jnp.int32)


feat32 = jnp.zeros(32, jnp.int32)
thr32 = jnp.full(32, 128, jnp.int32)
timeit(jax.jit(descend), bins, node_per_level[5], feat32, thr32,
       label="descend level 5 (N=32)")

def grad_hess(pred, y):
    p = jax.nn.sigmoid(pred)
    return p - y, p * (1.0 - p)


y = jnp.asarray((rng.random(ROWS) > 0.5).astype(np.float32))
pred = jnp.zeros(ROWS, jnp.float32)
timeit(jax.jit(grad_hess), pred, y, label="grad/hess")

# full round via the model
from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.parallel.mesh import local_mesh

model = HistGBT(n_trees=1, max_depth=DEPTH, n_bins=B, mesh=local_mesh())
Xn = np.asarray(X)
yn = np.asarray(y)
model.fit(Xn, yn, warmup_rounds=2)
print(f"full round (model.fit 1 round): {model.last_fit_seconds*1e3:.1f} ms",
      flush=True)
