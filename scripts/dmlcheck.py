#!/usr/bin/env python
"""dmlcheck: project-aware static analysis over one AST parse per file.

Passes (see doc/static_analysis.md for the catalog, suppression
grammar and baseline workflow):

* ``syntax`` / ``unused-import`` / ``style`` — the old scripts/lint.py,
  folded into the shared walker;
* ``lock-discipline`` / ``lock-release`` — shared mutable state outside
  ``with self._lock``, and ``acquire()`` without try/finally;
* ``lock-blocking`` — blocking calls (sleep / socket / HTTP /
  subprocess / untimed wait / join / untimed queue op) while a lock is
  held;
* ``atomicity`` — unlocked read-modify-write / check-then-act on
  attributes the class locks elsewhere;
* ``jit-purity`` — env/clock/RNG/metrics/closure-mutation inside
  jit-traced functions;
* ``knob-registry`` / ``knob-doc`` — every ``DMLC_*`` literal declared
  in base/knobs.py, every declaration documented under doc/;
* ``metric-registry`` / ``metric-doc`` — unique (kind, label-set) per
  ``dmlc_*`` metric name, all documented in doc/observability.md;
* ``resource-leak`` — sockets / subprocesses / tempfiles acquired
  without with/close/ownership-transfer, or stored on a class with no
  teardown method;
* ``thread-lifecycle`` — non-daemon threads never joined, and daemon
  threads whose target takes class locks (they can die mid-critical-
  section at interpreter exit);
* ``collective-discipline`` — collective calls (allreduce / barrier /
  broadcast / commit) under rank-conditional branches, a deadlock by
  construction;
* ``wire-schema`` — every literal ``{"cmd": ...}`` message checked
  against the central registry in base/wire_schemas.py, plus the
  ``DMLC_*`` env-injection ABI for launch/ and tracker/.

Usage:
    python scripts/dmlcheck.py                     # full run, baseline applied
    python scripts/dmlcheck.py --rules style,jit-purity
    python scripts/dmlcheck.py --json /tmp/dmlcheck.json
    python scripts/dmlcheck.py --explain atomicity # pass doc + examples
    python scripts/dmlcheck.py --timings           # per-pass seconds
    python scripts/dmlcheck.py --write-baseline    # grandfather current findings
    python scripts/dmlcheck.py --no-baseline       # show baselined findings too

Exit code 0 = no non-baselined findings AND no stale baseline entries;
1 otherwise (a stale entry means the finding was fixed — remove it so
the baseline shrinks monotonically).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from dmlc_core_tpu.analysis import (  # noqa: E402
    ALL_RULES, analyze, load_baseline, rule_help, write_baseline,
)

DEFAULT_BASELINE = os.path.join(ROOT, "scripts", "dmlcheck_baseline.json")


def _explain(rule: str) -> int:
    try:
        info = rule_help(rule)
    except ValueError as e:
        print(f"dmlcheck: {e} (known: {', '.join(ALL_RULES)})",
              file=sys.stderr)
        return 2
    print(f"[{info['rule']}]  (pass module: {info['module']})")
    print()
    print(info["doc"])
    if info.get("flagged"):
        print("\nflagged:\n")
        for line in info["flagged"].rstrip().splitlines():
            print(f"    {line}")
    if info.get("clean"):
        print("\nclean:\n")
        for line in info["clean"].rstrip().splitlines():
            print(f"    {line}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset "
                         f"(default: all of {', '.join(ALL_RULES)})")
    ap.add_argument("--explain", default=None, metavar="RULE",
                    help="print RULE's pass doc plus a minimal "
                         "flagged/clean example pair, then exit")
    ap.add_argument("--timings", action="store_true",
                    help="print per-pass seconds (always included in "
                         "--json) so the 10s CI budget stays "
                         "attributable")
    ap.add_argument("--json", dest="json_out", default=None, metavar="PATH",
                    help="write the machine-readable report here "
                         "(archived by CI like bench metrics)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default scripts/"
                         "dmlcheck_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--no-cache", action="store_true",
                    help="force a full parse + full pass run, ignoring "
                         "and not writing the incremental cache (the "
                         "repo-must-be-clean test uses this)")
    ap.add_argument("--cache", default=None, metavar="PATH",
                    help="incremental cache file (default <root>/"
                         "scripts/.dmlcheck_cache); per-file (mtime, "
                         "size)-keyed parses plus whole-run finding "
                         "reuse when nothing changed")
    ap.add_argument("--root", default=ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.explain:
        return _explain(args.explain)

    rules = args.rules.split(",") if args.rules else None
    cache_path = None if args.no_cache else (
        args.cache or os.path.join(args.root, "scripts",
                                   ".dmlcheck_cache"))
    t0 = time.perf_counter()
    ctx = analyze(args.root, rules=rules, cache_path=cache_path)
    elapsed = time.perf_counter() - t0

    if args.write_baseline:
        write_baseline(args.baseline, ctx.findings)
        print(f"dmlcheck: baselined {len(ctx.findings)} finding(s) "
              f"into {os.path.relpath(args.baseline, args.root)}")
        return 0

    baseline = (set() if args.no_baseline
                else load_baseline(args.baseline))
    live = [f for f in ctx.findings if f.fingerprint not in baseline]
    grandfathered = len(ctx.findings) - len(live)
    stale = baseline - {f.fingerprint for f in ctx.findings}

    for f in live:
        print(f.render())
    if stale:
        # a stale fingerprint means its finding was FIXED: failing here
        # (not merely noting) is what makes the baseline shrink
        # monotonically instead of fossilizing
        print(f"dmlcheck: FAIL: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} no longer match any "
              "finding — remove me from "
              f"{os.path.relpath(args.baseline, args.root)}:",
              file=sys.stderr)
        for fp in sorted(stale):
            print(f"  - remove me: {fp}", file=sys.stderr)
    print(f"dmlcheck: {len(ctx.files)} files, "
          f"{len(live)} finding(s), {grandfathered} baselined, "
          f"{ctx.suppressed_count} suppressed, {elapsed:.2f}s",
          file=sys.stderr)
    if args.timings:
        order = sorted(ctx.pass_seconds, key=ctx.pass_seconds.get,
                       reverse=True)
        print("dmlcheck: per-pass timings: "
              + ", ".join(f"{n} {ctx.pass_seconds[n]:.2f}s"
                          for n in order),
              file=sys.stderr)
        if ctx.cache_stats:
            cs = ctx.cache_stats
            rate = cs["hits"] / cs["files"] if cs["files"] else 0.0
            print(f"dmlcheck: cache: {cs['hits']}/{cs['files']} parse "
                  f"hits ({rate:.0%}), findings "
                  f"{'reused' if cs['findings_reused'] else 'recomputed'}",
                  file=sys.stderr)

    if args.json_out:
        report = {
            "files_checked": len(ctx.files),
            "elapsed_seconds": round(elapsed, 3),
            "rules": list(rules) if rules else list(ALL_RULES),
            "findings": [
                {"path": f.path, "line": f.line, "rule": f.rule,
                 "message": f.message, "fingerprint": f.fingerprint,
                 "baselined": f.fingerprint in baseline}
                for f in ctx.findings
            ],
            "suppressed": ctx.suppressed_count,
            "stale_baseline": sorted(stale),
            "pass_seconds": {k: round(v, 4)
                             for k, v in ctx.pass_seconds.items()},
            "cache": ctx.cache_stats or None,
        }
        d = os.path.dirname(os.path.abspath(args.json_out))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        print(f"dmlcheck: report -> {args.json_out}", file=sys.stderr)
    return 1 if (live or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
