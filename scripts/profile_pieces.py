"""Trustworthy piecewise profile: chain N iterations of one piece on
device, then force a real D2H fetch; tunnel-proof timing."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from dmlc_core_tpu.ops.histogram import build_histogram
from dmlc_core_tpu.ops.quantile import apply_bins, compute_cuts

ROWS = int(os.environ.get("ROWS", 4_000_000))
F, B, DEPTH = 28, 256, 6
ITERS = int(os.environ.get("ITERS", 10))

rng = np.random.default_rng(0)
X = rng.normal(size=(ROWS, F)).astype(np.float32)
bins = apply_bins(jnp.asarray(X), compute_cuts(X, B))
g0 = jnp.asarray(rng.normal(size=ROWS).astype(np.float32))
h0 = jnp.abs(g0) + 0.1
nid32 = jnp.asarray(rng.integers(0, 32, ROWS).astype(np.int32))
np.asarray(bins[0])  # sync


def timed(label, fn, *args):
    out = fn(*args)
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]  # compile+sync
    t0 = time.perf_counter()
    for _i in range(ITERS):
        out = fn(*args)
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]  # real fetch
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{label:46s} {dt*1e3:9.2f} ms", flush=True)
    return dt


# histogram at each level, pallas
for lvl in (0, 3, 5):
    N = 1 << lvl
    timed(f"hist pallas N={N}",
          lambda b, nd, gg, hh, NN=N: build_histogram(b, nd % NN, gg, hh, NN, B, "pallas"),
          bins, nid32, g0, h0)

# grad/hess
y = jnp.asarray((rng.random(ROWS) > 0.5).astype(np.float32))


@jax.jit
def gh(pred, yy):
    p = jax.nn.sigmoid(pred)
    return p - yy, p * (1 - p)


timed("grad/hess", gh, jnp.zeros(ROWS, jnp.float32), y)


# descent (table_select + row_bin) at level 5
@jax.jit
def descend(bins_l, node, feat, thr):
    n_nodes = feat.shape[0]
    n_iota = jnp.arange(n_nodes, dtype=jnp.int32)[None, :]
    oh = node[:, None] == n_iota
    feat_sel = jnp.sum(jnp.where(oh, feat[None, :], 0), axis=1)
    thr_sel = jnp.sum(jnp.where(oh, thr[None, :], 0), axis=1)
    f_iota = jnp.arange(bins_l.shape[1], dtype=jnp.int32)[None, :]
    row_bin = jnp.sum(
        jnp.where(feat_sel[:, None] == f_iota, bins_l.astype(jnp.int32), 0),
        axis=1)
    return 2 * node + (row_bin > thr_sel).astype(jnp.int32)


feat32 = jnp.zeros(32, jnp.int32)
thr32 = jnp.full(32, 128, jnp.int32)
timed("descend N=32 (table_select+row_bin)", descend,
      bins, nid32, feat32, thr32)


# leaf update: preds + table_select(leaf, node)
@jax.jit
def leafupd(preds, leaf, node):
    n_iota = jnp.arange(leaf.shape[0], dtype=jnp.int32)[None, :]
    oh = node[:, None] == n_iota
    return preds + jnp.sum(jnp.where(oh, leaf[None, :], 0.0), axis=1)


timed("leaf update (table_select 64)", leafupd,
      jnp.zeros(ROWS, jnp.float32), jnp.zeros(64, jnp.float32), nid32)

# full hist sweep: all 6 levels chained (mimics one round's hist work)
@jax.jit
def hist_sweep(b, nd, gg, hh):
    tot = 0.0
    for lvl in range(DEPTH):
        N = 1 << lvl
        hist = build_histogram(b, nd % N, gg, hh, N, B, "pallas")
        tot = tot + hist.sum()
    return tot


timed("hist sweep levels 0-5 (one round's hists)", hist_sweep,
      bins, nid32, g0, h0)
