"""Trustworthy piecewise profile of one hist-GBT boosting round.

Mirrors the per-level structure of HistGBT's round body exactly
(models/histgbt.py round_body): level-0 full histogram, then per level
table_select ×2 + descend (select_feature_bins) + LEFT-child histogram
with n_build = 2^(l-1) (sibling subtraction), plus grad/hess, best-split
and the final descend + leaf update.  The sum of pieces is the
composition floor of one round; compare it against bench.py's measured
steady-state seconds/round to see what the fused round program gains
from XLA overlap, and against the cost-model floor (ops/histogram.py
_lo_factor docstring) to see how much the kernel loses to construction.

Timing method (remote-tunnel-proof): a naive per-dispatch loop is
useless here — per-dispatch latency through the axon tunnel is tens to
hundreds of ms, 10-100× some pieces.  Each piece therefore runs as ONE
jitted ``lax.scan`` of N chained iterations (a scalar carry perturbs an
input each step so loop-invariant code motion cannot collapse the loop),
and the per-iteration time is the SLOPE between two run lengths:
``(t(N2) - t(N1)) / (N2 - N1)`` — fixed dispatch+fetch overhead cancels
exactly.

Output: one line per piece + a JSON summary (sum-of-pieces, hist-only
sum, implied attainable MFU at the bench's flop count).  Run on the TPU
chip: ``ROWS=10000000 python scripts/profile_pieces.py``.
"""
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from dmlc_core_tpu.ops.histogram import (build_histogram, _lo_factor,
                                         select_feature_bins)
from dmlc_core_tpu.ops.quantile import apply_bins, compute_cuts

ROWS = int(os.environ.get("ROWS", 4_000_000))
F = int(os.environ.get("FEATURES", 28))
B = int(os.environ.get("BINS", 256))
DEPTH = int(os.environ.get("DEPTH", 6))
N1 = int(os.environ.get("N1", 5))
N2 = int(os.environ.get("N2", 25))

rng = np.random.default_rng(0)
X = rng.normal(size=(ROWS, F)).astype(np.float32)
bins = apply_bins(jnp.asarray(X), compute_cuts(X, B))
bins_t = jnp.asarray(np.asarray(bins).T)          # [F, n] — round layout
g0 = jnp.asarray(rng.normal(size=ROWS).astype(np.float32))
h0 = jnp.abs(g0) + 0.1
node_ids = {n: jnp.asarray(rng.integers(0, n, ROWS).astype(np.int32))
            for n in [1 << l for l in range(DEPTH)]}
np.asarray(bins_t[0, :1])  # sync upload


def timed(label, step, *args):
    """Per-iteration seconds of ``step(carry, *args) -> carry`` via the
    two-length scan slope.  ``step`` must consume its float carry (so the
    loop body is not invariant) and return a new small-float carry."""

    @partial(jax.jit, static_argnums=(0,))
    def run(n, *a):
        return jax.lax.scan(lambda c, _: (step(c, *a), None),
                            jnp.float32(0.0), None, length=n)[0]

    def once(n):
        out = run(n, *args)
        np.asarray(out)               # real fetch = proof of completion
        t0 = time.perf_counter()
        out = run(n, *args)
        np.asarray(out)
        return time.perf_counter() - t0

    t1, t2 = once(N1), once(N2)
    dt = (t2 - t1) / (N2 - N1)
    print(f"{label:52s} {dt*1e3:9.2f} ms", flush=True)
    return dt


def tiny(x):
    """Carry update: data-dependent but numerically inert (~1e-30)."""
    return jnp.sum(x.ravel()[:4].astype(jnp.float32)) * jnp.float32(1e-30)


pieces = {}

# --- grad/hess (logistic) --------------------------------------------
y = jnp.asarray((rng.random(ROWS) > 0.5).astype(np.float32))


def gh_step(c, yy):
    pred = jnp.full(ROWS, 0.1, jnp.float32) + c   # carry-dependent input
    p = jax.nn.sigmoid(pred)
    g = p - yy
    h = p * (1 - p)
    return tiny(g) + tiny(h)


pieces["grad_hess"] = timed("grad/hess", gh_step, y)


# --- histograms: level 0 full + levels 1..5 left-only ----------------
def hist_step(c, b_t, nh, gg, hh, n_build):
    out = build_histogram(b_t, nh, gg + c, hh, n_build, B, "pallas",
                          transposed=True)
    return tiny(out)


pieces["hist_L0"] = timed(
    f"hist L0 n_build=1 lo={_lo_factor(1, B)}",
    partial(hist_step, n_build=1),
    bins_t, jnp.zeros(ROWS, jnp.int32), g0, h0)

for level in range(1, DEPTH):
    n_prev = 1 << (level - 1)
    node_h = jnp.where(node_ids[2 * n_prev] % 2 == 0,
                       node_ids[2 * n_prev] >> 1, -1)
    pieces[f"hist_L{level}"] = timed(
        f"hist L{level} n_build={n_prev} lo={_lo_factor(n_prev, B)} "
        f"(left only)",
        partial(hist_step, n_build=n_prev),
        bins_t, node_h, g0, h0)


# --- descend: table_select x2 + row_bin + compare --------------------
def table_select(table, node, n_entries):
    n_iota = jnp.arange(n_entries, dtype=jnp.int32)[None, :]
    oh = node[:, None] == n_iota
    return jnp.sum(jnp.where(oh, table[None, :], 0), axis=1)


def descend_step(c, b_t, nd, n_prev):
    # carry perturbs the (tiny) threshold table — O(n_prev) extra work
    ft = jnp.zeros(n_prev, jnp.int32)
    tt = jnp.full(n_prev, B // 2, jnp.int32) + c.astype(jnp.int32)
    fs = table_select(ft, nd, n_prev)
    ts = table_select(tt, nd, n_prev)
    rb = select_feature_bins(b_t, fs)
    nd2 = 2 * nd + (rb > ts).astype(jnp.int32)
    return c * jnp.float32(0.5) + tiny(nd2)


for level in range(1, DEPTH):
    n_prev = 1 << (level - 1)
    pieces[f"descend_L{level}"] = timed(
        f"descend into L{level} (select x2 + row_bin + cmp)",
        partial(descend_step, n_prev=n_prev),
        bins_t, node_ids[n_prev])

# --- best split (all levels, tiny [2,N,F,B] reductions) --------------
from dmlc_core_tpu.models.histgbt import _make_best_split  # noqa: E402

bs = _make_best_split(B, 1.0, 0.0, 1.0)


def best_split_step(c):
    tot = c
    for level in range(DEPTH):
        n_nodes = 1 << level
        hist = jnp.full((2, n_nodes, F, B), 1.0, jnp.float32) + c
        f_, t_, gn = bs(hist, None)
        tot = tot + tiny(gn)
    return tot


pieces["best_split_all"] = timed("best_split all levels", best_split_step)

# --- final descend + leaf update -------------------------------------
half = 1 << (DEPTH - 1)


def final_step(c, b_t, nd):
    leaf = jnp.zeros(2 * half, jnp.float32) + c
    fs = table_select(jnp.zeros(half, jnp.int32), nd, half)
    ts = table_select(jnp.full(half, B // 2, jnp.int32), nd, half)
    rb = select_feature_bins(b_t, fs)
    nd2 = 2 * nd + (rb > ts).astype(jnp.int32)
    preds = jnp.zeros(ROWS, jnp.float32) + table_select(leaf, nd2, 2 * half)
    return tiny(preds)


pieces["final_leaf"] = timed("final descend + leaf update", final_step,
                             bins_t, node_ids[half])

# --- summary ----------------------------------------------------------
hist_sum = sum(v for k, v in pieces.items() if k.startswith("hist_"))
total = sum(pieces.values())
# same flop count bench.py reports (auditable cost model)
mxu_flops = 0
for level in range(DEPTH):
    n_build = 1 if level == 0 else 1 << (level - 1)
    lo = _lo_factor(n_build, B)
    hi = -(-B // lo)
    mxu_flops += 2 * (2 * n_build * hi) * lo * ROWS * F
peak = 197e12 if jax.default_backend() == "tpu" else 0
print("-" * 66)
summary = {
    "rows": ROWS,
    "sum_of_pieces_ms": round(total * 1e3, 2),
    "hist_pieces_ms": round(hist_sum * 1e3, 2),
    "non_hist_ms": round((total - hist_sum) * 1e3, 2),
    "mxu_flops_per_round": mxu_flops,
    "mfu_at_sum_of_pieces": round(mxu_flops / total / peak, 4) if peak else None,
    "mfu_if_hist_only": round(mxu_flops / hist_sum / peak, 4) if peak else None,
    "pieces_ms": {k: round(v * 1e3, 3) for k, v in pieces.items()},
}
print(json.dumps(summary))
