#!/usr/bin/env python
"""Pre-seed the persistent XLA compile cache (scripts/ci.sh stage).

AOT-compiles the flagship boosting-round ladder — the K-rounds-per-
dispatch program (and remainder, when ``rounds % K != 0``) at the bench
config's exact shapes — into ``DMLC_COMPILE_CACHE_DIR``, WITHOUT
materializing any data: ``lower().compile()`` works on
ShapeDtypeStructs, so warming the 10M-row program costs compile time
only.  A later ``bench.py`` (or any fit at the same config) on the same
image then deserializes instead of compiling: ``warmup_seconds`` drops
from the 23-31 s BENCH_r04/r05 measured toward the <5 s ROADMAP target,
and the bench JSON reports ``compile_cache: hit``.

Idempotent and cheap when warm: a second run joins in cache-read time.
Config mirrors bench.py's env (``BENCH_ROWS``/``BENCH_FEATURES``/
``BENCH_ROUNDS``/``BENCH_DEPTH``/``BENCH_BINS``/``BENCH_CHIPS``); the
ladder compiles for the CURRENT backend (run on the TPU host to warm
the TPU cache — a CPU-CI run warms the CPU lanes' shared dir).
``WARM_CACHE_FORCE_CPU=N`` pins N virtual CPU devices first (CI).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("WARM_CACHE_FORCE_CPU"):
    from dmlc_core_tpu.utils import force_cpu_devices
    force_cpu_devices(int(os.environ["WARM_CACHE_FORCE_CPU"]))

import numpy as np  # noqa: E402


def main() -> int:
    rows = int(os.environ.get("BENCH_ROWS", 10_000_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_ROUNDS", 100))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    n_bins = int(os.environ.get("BENCH_BINS", 256))
    chips = int(os.environ.get("BENCH_CHIPS", "0") or 0)

    from dmlc_core_tpu.base import compile_cache as cc
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.models.histgbt import _RoundProgramWarmup
    from dmlc_core_tpu.parallel.mesh import local_mesh

    cc.configure()
    t0 = time.time()
    mesh = local_mesh(chips or None)
    model = HistGBT(n_trees=rounds, max_depth=depth, n_bins=n_bins,
                    learning_rate=0.1, mesh=mesh)
    n_padded = rows + ((-rows) % model._pad_multiple())
    warm = _RoundProgramWarmup(model, feats, n_padded)
    execs = warm.join()
    stats = cc.stats()
    record = {
        "check": "warm_compile_cache",
        "rows": rows, "features": feats, "rounds": rounds,
        "chips": mesh.devices.size,
        "programs": sorted(execs),
        "compile_seconds": round(warm.compile_seconds, 3),
        "wall_seconds": round(time.time() - t0, 3),
        "cache_verdict": warm.cache_verdict or "warm",
        **stats,
    }
    print(json.dumps(record))
    if not execs:
        print("FAIL: no round programs compiled", file=sys.stderr)
        return 1
    if not stats["enabled"]:
        print("FAIL: persistent compile cache is disabled "
              "(DMLC_COMPILE_CACHE=0?)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
