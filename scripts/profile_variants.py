"""Bisect round_body cost: time jitted round variants with components
knocked out (chained iterations, one real fetch at the end)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from dmlc_core_tpu.base.compat import donate_argnums, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlc_core_tpu.models.histgbt import _make_best_split
from dmlc_core_tpu.ops.histogram import build_histogram
from dmlc_core_tpu.ops.quantile import apply_bins, compute_cuts
from dmlc_core_tpu.parallel.mesh import local_mesh

ROWS, F, B, DEPTH = 4_000_000, 28, 256, 6
ITERS = int(os.environ.get("ITERS", 8))

rng = np.random.default_rng(0)
X = rng.normal(size=(ROWS, F)).astype(np.float32)
y = (rng.random(ROWS) > 0.5).astype(np.float32)
mesh = local_mesh()
row_sh = NamedSharding(mesh, P("data"))
mat_sh = NamedSharding(mesh, P("data", None))
bins = apply_bins(jax.device_put(X, mat_sh), compute_cuts(X, B))
y_d = jax.device_put(y, row_sh)
w_d = jax.device_put(np.ones(ROWS, np.float32), row_sh)
preds0 = jax.device_put(np.zeros(ROWS, np.float32), row_sh)

best_split = _make_best_split(B, 1.0, 0.0, 1.0)
best_split_leaf = _make_best_split(B, 1.0, 0.0, 1.0, with_child_sums=True)


def table_select(table, node, n_entries):
    n_iota = jnp.arange(n_entries, dtype=jnp.int32)[None, :]
    oh = node[:, None] == n_iota
    return jnp.sum(jnp.where(oh, table[None, :], 0), axis=1)


def make_round(with_hist=True, with_split=True, with_descend=True,
               with_leaf=True):
    def round_body(bins_l, y_l, w_l, preds_l):
        p = jax.nn.sigmoid(preds_l)
        g = (p - y_l) * w_l
        h = p * (1 - p) * w_l
        node = jnp.zeros(bins_l.shape[0], jnp.int32)
        gsum = jnp.zeros(64, jnp.float32)
        hsum = jnp.ones(64, jnp.float32)
        for level in range(DEPTH):
            n_nodes = 1 << level
            if with_hist:
                hist = build_histogram(bins_l, node, g, h, n_nodes, B, "pallas")
                hist = jax.lax.psum(hist, "data")
            else:
                hist = jnp.zeros((2, n_nodes, F, B), jnp.float32) + g[0]
            if with_split:
                if level == DEPTH - 1:
                    feat, thr, _gn, gsum, hsum = best_split_leaf(hist)
                else:
                    feat, thr, _gn = best_split(hist)
            else:
                feat = jnp.zeros(n_nodes, jnp.int32) + hist[0, 0, 0, 0].astype(jnp.int32) % F
                thr = jnp.full(n_nodes, B // 2, jnp.int32)
            if with_descend:
                feat_sel = table_select(feat, node, n_nodes)
                thr_sel = table_select(thr, node, n_nodes)
                f_iota = jnp.arange(bins_l.shape[1], dtype=jnp.int32)[None, :]
                row_bin = jnp.sum(
                    jnp.where(feat_sel[:, None] == f_iota,
                              bins_l.astype(jnp.int32), 0), axis=1)
                node = 2 * node + (row_bin > thr_sel).astype(jnp.int32)
            else:
                node = (node * 2) % (2 * n_nodes)
        leaf = -gsum / (hsum + 1.0) * 0.1
        if with_leaf:
            preds_new = preds_l + table_select(leaf, node, 64)
        else:
            preds_new = preds_l + leaf[0]
        return preds_new

    mapped = shard_map(round_body, mesh=mesh,
                       in_specs=(P("data", None), P("data"), P("data"), P("data")),
                       out_specs=P("data"), check_vma=False)
    return jax.jit(mapped, donate_argnums=donate_argnums(3))


def timed(label, fn):
    p = fn(bins, y_d, w_d, jnp.copy(preds0))
    np.asarray(p)[:1]
    p = jnp.copy(preds0)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        p = fn(bins, y_d, w_d, p)
    _ = np.asarray(p)[:1]
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{label:42s} {dt*1e3:9.1f} ms/round", flush=True)


timed("full round", make_round())
timed("no hist (split on zeros)", make_round(with_hist=False))
timed("no descend", make_round(with_descend=False))
timed("no split (fixed thr)", make_round(with_split=False))
timed("no leaf update", make_round(with_leaf=False))
timed("hist only (no split/descend/leaf)",
      make_round(with_split=False, with_descend=False, with_leaf=False))
