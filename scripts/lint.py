#!/usr/bin/env python
"""Back-compat shim: the old self-contained linter is now dmlcheck's
``syntax`` / ``unused-import`` / ``style`` passes (one shared AST parse
per file for every pass — see ``dmlc_core_tpu/analysis/`` and
``doc/static_analysis.md``).  ``python scripts/lint.py`` keeps working
and keeps meaning "style checks only"; CI runs the full analyzer via
``python scripts/dmlcheck.py``.
"""

import sys

if __name__ == "__main__":
    from dmlcheck import main
    sys.exit(main(["--rules", "syntax,unused-import,style"]
                  + sys.argv[1:]))
