#!/usr/bin/env python
"""Self-contained linter (stdlib only — this image ships no ruff/flake8).

The reference ships ``scripts/lint.py`` as a cpplint wrapper plus pylint
config (SURVEY.md §2d); this is the same role re-founded on ``ast`` so CI
needs zero external tools.  Checks, per Python file:

* parses (syntax);
* no unused imports (names imported but never referenced — the check the
  repo actually regresses on);
* no tabs in indentation, no trailing whitespace;
* line length ≤ 100 columns (repo style is ~79 soft, 100 hard).

C++ files get the whitespace/length checks only.

Exit code 0 = clean; 1 = findings (printed one per line as
``path:line: message``).
"""

from __future__ import annotations

import ast
import os
import sys

MAX_LINE = 100
PY_DIRS = ("dmlc_core_tpu", "tests", "scripts", "examples")
CPP_DIRS = ("cpp",)
ROOT_FILES = ("bench.py", "__graft_entry__.py", "dmlc-submit")


def iter_files():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for d in PY_DIRS:
        base = os.path.join(root, d)
        for dirpath, _dirnames, filenames in os.walk(base):
            for f in sorted(filenames):
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f), "py"
    for d in CPP_DIRS:
        base = os.path.join(root, d)
        if os.path.isdir(base):
            for f in sorted(os.listdir(base)):
                if f.endswith((".cc", ".h", ".cpp")):
                    yield os.path.join(base, f), "cpp"
    for f in ROOT_FILES:
        p = os.path.join(root, f)
        if os.path.exists(p):
            yield p, "py"


class _ImportUse(ast.NodeVisitor):
    """Collect imported names and every referenced name/attr root."""

    def __init__(self):
        self.imports = {}     # name -> (lineno, asname)
        self.used = set()

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = node.lineno

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def lint_python(path, src, out):
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        out.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
        return
    if os.path.basename(path) == "__init__.py":
        return                       # packages import purely to re-export
    v = _ImportUse()
    v.visit(tree)
    # a module re-exporting via __all__ counts as use; '# noqa' opts out
    exported = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "__all__"
                and isinstance(node.value, (ast.List, ast.Tuple))):
            exported = {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)}
    lines = src.splitlines()
    for name, lineno in sorted(v.imports.items(), key=lambda kv: kv[1]):
        if name in v.used or name in exported:
            continue
        if lineno <= len(lines) and "noqa" in lines[lineno - 1]:
            continue
        out.append(f"{path}:{lineno}: unused import '{name}'")


def lint_text(path, src, out, kind):
    for i, line in enumerate(src.splitlines(), 1):
        stripped = line.rstrip("\n")
        if stripped != stripped.rstrip():
            out.append(f"{path}:{i}: trailing whitespace")
        if kind == "py" and stripped[:len(stripped) - len(stripped.lstrip())].count("\t"):
            out.append(f"{path}:{i}: tab in indentation")
        if len(stripped) > MAX_LINE:
            out.append(f"{path}:{i}: line longer than {MAX_LINE} columns "
                       f"({len(stripped)})")


def main() -> int:
    findings = []
    n = 0
    for path, kind in iter_files():
        n += 1
        with open(path, encoding="utf-8") as f:
            src = f.read()
        if kind == "py":
            lint_python(path, src, findings)
        lint_text(path, src, findings, kind)
    for f in findings:
        print(f)
    print(f"lint: {n} files checked, {len(findings)} finding(s)",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
