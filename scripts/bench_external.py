#!/usr/bin/env python
"""Config-3 at genuinely out-of-core scale (VERDICT r2 #6).

End-to-end: a Criteo-shaped synthetic LibSVM file (default 50M rows x 39
sparse features, ~16GB text, written by the native cpp/gen_libsvm
generator) streams through the REAL external-memory stack — LibSVM
parser -> DiskRowIter binary page cache (#cachefile URI) -> fit_external
sketch + bin passes -> boosting on the chip — with host RSS tracked the
whole way.  Reports:

- parse+cache-build seconds, MB/s, pages/s (pass 1 over the text)
- cached page-replay pages/s (what every later pass pays)
- fit_external(cache_device=True) rounds/s — binned pages resident in
  HBM, the in-core chunked engine over paged data
- fit_external(cache_device=False) page-loop rounds/s on a FEW rounds
  (the truly device-memory-bounded mode; through a remote tunnel its
  O(pages x depth) dispatches per round are latency-dominated, which is
  exactly why cache_device exists — recorded, not hidden)
- peak host RSS (ru_maxrss), proving the 16GB dataset never
  materializes on the host

Usage (50M default needs ~40GB free disk for text + page cache):
    BENCH_EXT_ROWS=50000000 python scripts/bench_external.py
"""
import json
import os
import resource
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

ROWS = int(os.environ.get("BENCH_EXT_ROWS", 50_000_000))
FEATS = int(os.environ.get("BENCH_EXT_FEATURES", 39))
ROUNDS = int(os.environ.get("BENCH_EXT_ROUNDS", 50))
PAGELOOP_ROUNDS = int(os.environ.get("BENCH_EXT_PAGELOOP_ROUNDS", 2))
DEPTH = int(os.environ.get("BENCH_EXT_DEPTH", 6))
BINS = int(os.environ.get("BENCH_EXT_BINS", 256))
WORKDIR = os.environ.get("BENCH_EXT_DIR", "/tmp/dmlc_ext_bench")


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main() -> None:
    # Through the axon tunnel, per-page device dispatches cost seconds
    # each (sketch: measured ~20 s/page); pin the streaming passes to
    # the host CPU backend — the binned matrix still lands on the TPU
    # once, at cached-concat time.  On a locally attached chip these
    # knobs should stay unset.
    os.environ.setdefault("DMLC_TPU_SKETCH_BACKEND", "cpu")
    os.environ.setdefault("DMLC_TPU_BIN_BACKEND", "cpu")
    os.makedirs(WORKDIR, exist_ok=True)
    svm = os.path.join(WORKDIR, f"criteo_{ROWS}x{FEATS}.svm")
    cache = os.path.join(WORKDIR, f"criteo_{ROWS}x{FEATS}.cache")
    gen = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "build", "gen_libsvm")

    out = {"rows": ROWS, "features": FEATS, "depth": DEPTH, "bins": BINS}

    if not os.path.exists(svm):
        t0 = time.perf_counter()
        subprocess.run([gen, str(ROWS), str(FEATS), svm, "7"], check=True,
                       stderr=subprocess.DEVNULL)
        out["gen_seconds"] = round(time.perf_counter() - t0, 1)
    out["text_gb"] = round(os.path.getsize(svm) / 1e9, 2)

    from dmlc_core_tpu.data.iter import RowBlockIter

    # pass 1: parse text -> binary page cache (DiskRowIter ctor)
    for f in (cache, cache + ".part0"):
        if os.path.exists(f):
            os.remove(f)
    t0 = time.perf_counter()
    it = RowBlockIter.create(f"{svm}#{cache}", 0, 1, "libsvm")
    out["parse_cache_seconds"] = round(time.perf_counter() - t0, 1)
    out["parse_mb_per_sec"] = round(
        os.path.getsize(svm) / 1e6 / out["parse_cache_seconds"], 1)
    out["cache_gb"] = round(os.path.getsize(cache) / 1e9, 2)
    out["pages"] = it._num_pages
    out["rss_after_parse_gb"] = round(rss_gb(), 2)

    # cached page replay rate (what the sketch/bin passes and every
    # page-loop level pay to read a page back)
    t0 = time.perf_counter()
    n_pages = n_rows = 0
    for block in it:
        n_pages += 1
        n_rows += block.size
    dt = time.perf_counter() - t0
    assert n_rows == ROWS, (n_rows, ROWS)
    out["replay_pages_per_sec"] = round(n_pages / dt, 2)
    out["replay_rows_per_sec"] = round(n_rows / dt)

    from dmlc_core_tpu.models import HistGBT

    # headline: device-cached external training (binned pages in HBM)
    m = HistGBT(n_trees=ROUNDS, max_depth=DEPTH, n_bins=BINS)
    t0 = time.perf_counter()
    m.fit_external(it, num_col=FEATS, cache_device=True, warmup_rounds=5)
    out["cache_device_total_seconds"] = round(time.perf_counter() - t0, 1)
    out["cache_device_boost_seconds"] = round(m.last_fit_seconds, 2)
    out["cache_device_rounds_per_sec"] = round(
        ROUNDS / m.last_fit_seconds, 3)
    # one chunk-rate implementation repo-wide: the anomaly flag applies
    # to this capture too (same tunnel, same failure mode)
    from bench import chunk_stats
    out.update(chunk_stats(m.last_chunk_times, ROUNDS,
                           m.last_fit_seconds))
    out["rss_after_cached_fit_gb"] = round(rss_gb(), 2)

    # true out-of-core page loop, a few rounds (device memory bounded by
    # one page; per-level host dispatches pay tunnel latency — recorded)
    if PAGELOOP_ROUNDS > 0:
        m2 = HistGBT(n_trees=PAGELOOP_ROUNDS, max_depth=DEPTH, n_bins=BINS)
        t0 = time.perf_counter()
        # r4: cache_device=False is no longer a per-page crawl — it
        # auto-routes to the cached engine under the device budget and
        # to the chunk-streaming engine over it.  warmup keeps compile
        # and the bin-matrix upload out of the timed region, same rule
        # as every other fit here.
        m2.fit_external(it, num_col=FEATS, cuts=m.cuts, cache_device=False,
                        warmup_rounds=5)
        dt = time.perf_counter() - t0
        out["pageloop_rounds"] = PAGELOOP_ROUNDS
        out["pageloop_rounds_per_sec"] = round(
            PAGELOOP_ROUNDS / m2.last_fit_seconds, 4)
        out["pageloop_total_seconds"] = round(dt, 1)
    it.close()
    out["peak_rss_gb"] = round(rss_gb(), 2)
    try:
        import jax
        out["platform"] = jax.devices()[0].platform
    except Exception:  # noqa: BLE001
        out["platform"] = "unknown"
    print(json.dumps(out))


if __name__ == "__main__":
    main()
