#!/usr/bin/env python
"""Fault-injection smoke for CI: kill-and-recover + lossy-wire round trip.

Two drills, both deterministic (wired into ``scripts/ci.sh``):

1. **Checkpoint kill-and-recover** — a child process saves checkpoint v2
   over an existing v1 with ``DMLC_FAULT_INJECT=checkpoint:kill`` active,
   so it is SIGKILLed between payload write and commit.  The parent then
   proves the atomic-write contract: the on-disk checkpoint still loads
   as v1, bit-identical.  A corrupt-and-fallback pass (flip a byte in a
   committed v2, load → retained v1) rides along.

2. **Lossy-wire S3 round trip** — an in-process fake S3 server plus
   ``http:error=503:p=0.35,stream:truncate:p=0.2`` injection; a
   multipart write + ranged read must come back byte-identical, with
   nonzero ``dmlc_retries_total`` and ``dmlc_faults_injected_total`` as
   evidence the chaos actually happened.

Exit 0 = both drills green.  Usage:
    python scripts/check_resilience.py            # run the drills
    python scripts/check_resilience.py --writer URI VERSION   # (internal)
"""

import os
import signal
import subprocess
import sys
import threading
import urllib.parse

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.utils import force_cpu_devices  # noqa: E402

force_cpu_devices(1)

import numpy as np  # noqa: E402


def _state(version):
    rng = np.random.default_rng(version)
    return {"w": rng.standard_normal(512).astype(np.float32),
            "round": version * 10}


def writer_main(uri, version):
    """Child entry: save one checkpoint (the parent may have armed
    DMLC_FAULT_INJECT to SIGKILL us mid-write)."""
    from dmlc_core_tpu.base import metrics_agg
    from dmlc_core_tpu.parallel.checkpoint import checkpoint

    metrics_agg.install_spool("ckpt_writer", version)
    checkpoint(uri, _state(version), version=version)


def _check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def drill_checkpoint(tmpdir):
    from dmlc_core_tpu.parallel.checkpoint import load_checkpoint

    uri = os.path.join(tmpdir, "ck")
    like = _state(0)

    def run_writer(version, fault=""):
        env = dict(os.environ, DMLC_FAULT_INJECT=fault, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--writer", uri,
             str(version)], env=env, capture_output=True, text=True)

    r = subprocess_result = run_writer(1)
    _check(r.returncode == 0, f"clean v1 save (rc={r.returncode})")
    v, st = load_checkpoint(uri, like)
    _check(v == 1 and np.array_equal(st["w"], _state(1)["w"]),
           "v1 loads back bit-identical")

    # kill mid-write of v2: the injector SIGKILLs the child between
    # payload write and commit
    r = run_writer(2, fault="checkpoint:kill")
    _check(r.returncode == -signal.SIGKILL,
           f"v2 writer was SIGKILLed mid-write (rc={r.returncode})")
    v, st = load_checkpoint(uri, like)
    _check(v == 1 and np.array_equal(st["w"], _state(1)["w"]),
           "post-kill load still serves v1 bit-identical")

    # commit v2 for real, corrupt it, load must fall back to v1
    r = run_writer(2)
    _check(r.returncode == 0, "clean v2 save")
    v, _ = load_checkpoint(uri, like)
    _check(v == 2, "v2 visible after clean save")
    with open(uri, "r+b") as f:
        size = os.path.getsize(uri)
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    v, st = load_checkpoint(uri, like)
    _check(v == 1 and np.array_equal(st["w"], _state(1)["w"]),
           "corrupt v2 falls back to retained v1")
    del subprocess_result


class _FakeS3:
    """Minimal S3 fake (objects + multipart) for the lossy-wire drill."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        store, uploads = {}, {}

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, status, body=b"", headers=None):
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _key(self):
                p = urllib.parse.urlsplit(self.path)
                return urllib.parse.unquote(p.path.lstrip("/")), dict(
                    urllib.parse.parse_qsl(p.query, keep_blank_values=True))

            def do_HEAD(self):  # noqa: N802
                key, _ = self._key()
                if key in store:
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(store[key])))
                    self.end_headers()
                else:
                    self._send(404)

            def do_GET(self):  # noqa: N802
                key, _ = self._key()
                blob = store.get(key)
                if blob is None:
                    self._send(404)
                    return
                rng = self.headers.get("Range")
                if rng:
                    lo, _, hi = rng.split("=")[1].partition("-")
                    lo = int(lo)
                    hi = int(hi) if hi else len(blob) - 1
                    self._send(206, blob[lo:hi + 1])
                else:
                    self._send(200, blob)

            def do_PUT(self):  # noqa: N802
                key, q = self._key()
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if "partNumber" in q:
                    uploads.setdefault(q["uploadId"], {})[
                        int(q["partNumber"])] = body
                    self._send(200, b"", {"ETag": f'"p{q["partNumber"]}"'})
                    return
                store[key] = body
                self._send(200)

            def do_POST(self):  # noqa: N802
                key, q = self._key()
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if "uploads" in q:
                    uid = f"up{len(uploads)}"
                    uploads[uid] = {}
                    self._send(200, (
                        f"<InitiateMultipartUploadResult><UploadId>{uid}"
                        f"</UploadId></InitiateMultipartUploadResult>"
                    ).encode())
                    return
                if "uploadId" in q:
                    parts = uploads.pop(q["uploadId"])
                    store[key] = b"".join(parts[i] for i in sorted(parts))
                    self._send(200, b"<CompleteMultipartUploadResult/>")
                    return
                del body
                self._send(400)

        self.store = store
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.endpoint = f"http://127.0.0.1:{self.server.server_address[1]}"


def drill_lossy_wire():
    # at p=0.35 a 4-attempt budget still loses ~1.5% of requests; give
    # the drill the headroom a real lossy-wire deployment would tune in
    os.environ["DMLC_RETRY_MAX_ATTEMPTS"] = "10"
    os.environ["DMLC_RETRY_BASE_S"] = "0.005"
    os.environ.pop("AWS_ACCESS_KEY_ID", None)
    fake = _FakeS3()
    os.environ["S3_ENDPOINT"] = fake.endpoint

    from dmlc_core_tpu.base import faultinject as fi
    from dmlc_core_tpu.base.metrics import default_registry
    from dmlc_core_tpu.io.stream import Stream

    payload = np.random.default_rng(0).bytes(18 << 20)  # > 2 multipart parts
    with fi.inject("http:error=503:p=0.35,stream:truncate:p=0.2", seed=11):
        with Stream.create("s3://bkt/blob.bin", "w") as s:
            s.write(payload)
        with Stream.create("s3://bkt/blob.bin", "r") as s:
            got = s.read_all()
        faults = fi.fired_total()
    _check(got == payload,
           "multipart write + ranged read byte-identical under faults")
    _check(faults > 0, f"faults actually fired ({faults})")
    reg = default_registry()
    retries = reg.counter("retries_total", labels=("op",))
    total = sum(s["value"] for s in retries._snap())
    _check(total > 0, f"retries recorded on the registry ({total})")
    fake.server.shutdown()


def main():
    if len(sys.argv) >= 2 and sys.argv[1] == "--writer":
        writer_main(sys.argv[2], int(sys.argv[3]))
        return
    import tempfile

    # observability plane: parent + writer children spool metrics
    # snapshots into one directory (children inherit the env)
    spool = os.environ.get("DMLC_METRICS_SPOOL") \
        or tempfile.mkdtemp(prefix="dmlc_resilience_spool")
    os.environ["DMLC_METRICS_SPOOL"] = spool
    from dmlc_core_tpu.base import metrics_agg

    spool_writer = metrics_agg.install_spool("drill", 0)
    with tempfile.TemporaryDirectory(prefix="dmlc_resilience") as tmpdir:
        drill_checkpoint(tmpdir)
    drill_lossy_wire()
    if spool_writer is not None:
        spool_writer.close()
    merged, nprocs = metrics_agg.merge_spool(spool)
    metrics_out = os.environ.get("RESILIENCE_METRICS_OUT",
                                 "/tmp/resilience_metrics.json")
    metrics_agg.write_snapshot(metrics_out, merged)
    _check(nprocs >= 2, f"metrics spool merged {nprocs} processes "
                        f"(artifact at {metrics_out})")
    print("RESILIENCE SMOKE GREEN")


if __name__ == "__main__":
    main()
