#!/usr/bin/env python
"""Production-shape transformer benchmark on the real chip (VERDICT r2 #4).

Runs the claimed beyond-parity model paths at REAL shapes — BERT-base
(L12/d768/h12/ff3072, seq 512, 30522 vocab), Switch-MoE at capacity
pressure, and the GPipe PipelineLM with realistic microbatches — on
whatever jax.devices() provides (single-chip mesh: correctness of the
multi-axis shardings is pytest/dryrun-proven on the virtual mesh; this
measures that the shapes COMPILE, FIT and RUN at speed on hardware,
surfacing any VMEM/layout traps toy shapes hide).

Steps are dispatched as lax.scan chunks (BERT.fit_chunked) because
per-step host sync through the axon tunnel would dominate: per-chunk
arrival timestamps are printed as audit evidence, bench.py-style.

One JSON line per model.  ``BENCH_T_MODELS=bert,moe,pipeline`` selects.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from bench import _PEAK_BF16  # noqa: E402 — one platform→peak table repo-wide


def _mfu(flops_per_sec, platform):
    peak = _PEAK_BF16.get(platform, 0)
    return round(flops_per_sec / peak, 4) if peak else None


def _mem_stats(dev):
    try:
        s = dev.memory_stats() or {}
        peak, used = s.get("peak_bytes_in_use"), s.get("bytes_in_use")
        # axon tunnel devices return empty/zero stats — null, not 0.0
        return {"hbm_peak_mb": round(peak / 1e6, 1) if peak else None,
                "hbm_in_use_mb": round(used / 1e6, 1) if used else None}
    except Exception:  # noqa: BLE001 — not all platforms expose stats
        return {"hbm_peak_mb": None, "hbm_in_use_mb": None}


def bench_bert(devs, steps, chunk):
    import jax
    from dmlc_core_tpu.models.bert import BERT
    from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh

    B, S = int(os.environ.get("BENCH_T_BATCH", 8)), 512
    mesh = create_mesh(MeshSpec(data=1), devices=devs[:1])
    model = BERT(mesh=mesh)           # BERT-base defaults
    model.init_params(0)
    n_params = sum(int(np.prod(v.shape)) for v in model.params.values())
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, model.param.vocab_size, size=(B, S))
    loss, secs, chunk_times = model.fit_chunked(
        tokens, tokens.copy(), np.ones((B, S), np.float32),
        n_steps=steps, chunk=chunk)
    flops = 6 * n_params * B * S      # fwd+bwd matmul estimate
    return {
        "model": "bert_base", "layers": 12, "d_model": 768, "seq": S,
        "batch": B, "params_m": round(n_params / 1e6, 1),
        "steps": steps, "seconds": round(secs, 3),
        "steps_per_sec": round(steps / secs, 3),
        "tokens_per_sec": round(B * S * steps / secs),
        "approx_mfu": _mfu(flops * steps / secs, devs[0].platform),
        "final_loss": round(loss, 4),
        "chunk_times": [(d, round(t, 3)) for d, t in chunk_times],
        **_mem_stats(devs[0]),
    }


def bench_moe(devs, steps, chunk):
    import jax
    from dmlc_core_tpu.models.bert import BERT
    from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh

    # capacity-pressure config: tokens/expert ≈ capacity at cf=1.0, so
    # dispatch overflow/padding paths are genuinely exercised
    B, S = int(os.environ.get("BENCH_T_BATCH", 8)), 512
    mesh = create_mesh(MeshSpec(data=1), devices=devs[:1])
    model = BERT(mesh=mesh, n_layers=6, d_model=512, n_heads=8, d_ff=2048,
                 ffn_type="moe", n_experts=8, capacity_factor=1.0)
    model.init_params(0)
    n_params = sum(int(np.prod(v.shape)) for v in model.params.values())
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, model.param.vocab_size, size=(B, S))
    loss, secs, chunk_times = model.fit_chunked(
        tokens, tokens.copy(), np.ones((B, S), np.float32),
        n_steps=steps, chunk=chunk)
    return {
        "model": "switch_moe", "layers": 6, "d_model": 512, "seq": S,
        "batch": B, "experts": 8, "capacity_factor": 1.0,
        "params_m": round(n_params / 1e6, 1),
        "steps": steps, "seconds": round(secs, 3),
        "steps_per_sec": round(steps / secs, 3),
        "tokens_per_sec": round(B * S * steps / secs),
        "final_loss": round(loss, 4),
        "chunk_times": [(d, round(t, 3)) for d, t in chunk_times],
        **_mem_stats(devs[0]),
    }


def bench_pipeline(devs, steps, chunk):
    import jax
    from jax.sharding import Mesh
    from dmlc_core_tpu.parallel.pipeline import PipelineLM

    # realistic microbatching: 8 microbatches through the GPipe scan
    # schedule (pp=1 on a single chip — the schedule, buffers and
    # collective-permute program still run)
    B, S = 16, 512
    mesh = Mesh(np.asarray(devs[:1]).reshape(1, 1), ("data", "pipe"))
    model = PipelineLM(mesh=mesh, n_layers=12, d_model=512, n_heads=8,
                      d_ff=2048, vocab_size=30522, max_len=S, n_micro=8)
    model.init_params(0)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 30522, size=(B, S)).astype(np.int32)
    mask = np.ones((B, S), np.float32)
    loss, secs, chunk_times = model.fit_chunked(
        tokens, tokens.copy(), mask, n_steps=steps, chunk=chunk)
    return {
        "model": "pipeline_lm", "layers": 12, "d_model": 512, "seq": S,
        "batch": B, "n_micro": 8,
        "steps": steps, "seconds": round(secs, 3),
        "steps_per_sec": round(steps / secs, 3),
        "tokens_per_sec": round(B * S * steps / secs),
        "final_loss": round(float(loss), 4),
        "chunk_times": [(d, round(t, 3)) for d, t in chunk_times],
        **_mem_stats(devs[0]),
    }


def main() -> None:
    import jax

    steps = int(os.environ.get("BENCH_T_STEPS", 30))
    chunk = int(os.environ.get("BENCH_T_CHUNK", 10))
    models = os.environ.get("BENCH_T_MODELS", "bert,moe,pipeline").split(",")
    devs = jax.devices()
    fns = {"bert": bench_bert, "moe": bench_moe, "pipeline": bench_pipeline}
    for name in models:
        try:
            out = fns[name.strip()](devs, steps, chunk)
        except Exception as e:  # noqa: BLE001 — report traps, keep going
            out = {"model": name.strip(),
                   "error": f"{type(e).__name__}: {e}"[:600]}
        out["platform"] = devs[0].platform
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
