"""SparseHistGBT bench: synthetic 100k-feature sparse LibSVM workload.

BASELINE config 3's "sparse CSR" seam at its natural scale (VERDICT r4
missing #2): bag-of-words-shaped data — F = 100k, density 0.5% — where
the dense engine's [n, F] bin matrix is impossible (n·F = 10^10 cells)
and the ragged sparse path touches only the nnz present entries.

Prints one JSON line: rows/features/nnz/total_bins, fit seconds,
rounds/s, train accuracy (sanity: the engine must actually learn), and
the predict pass rate.  Env knobs: SPARSE_ROWS (1e5), SPARSE_F (1e5),
SPARSE_DENSITY (0.005), SPARSE_ROUNDS (20), SPARSE_BINS (32),
SPARSE_DEPTH (6), BENCH_CPU=1 to force the virtual-CPU backend.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BENCH_CPU"):
    from dmlc_core_tpu.utils import force_cpu_devices
    force_cpu_devices(1)

import numpy as np  # noqa: E402


def main():
    n = int(float(os.environ.get("SPARSE_ROWS", 100_000)))
    F = int(float(os.environ.get("SPARSE_F", 100_000)))
    density = float(os.environ.get("SPARSE_DENSITY", 0.005))
    rounds = int(os.environ.get("SPARSE_ROUNDS", 20))
    n_bins = int(os.environ.get("SPARSE_BINS", 32))
    depth = int(os.environ.get("SPARSE_DEPTH", 6))
    nnz_per_row = max(2, int(F * density))

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    # power-law feature popularity (bag-of-words shape): stop-word
    # features plus a long tail; features 0/1 carry the label and are
    # present in every row; duplicates within a row are dropped (the
    # engine rejects them — one entry per (row, feature))
    pop = 1.0 / np.arange(1, F - 1) ** 0.7
    pop /= pop.sum()
    draw = rng.choice(F - 2, size=(n, nnz_per_row - 2), p=pop) + 2
    draw.sort(axis=1)
    first = np.concatenate([np.ones((n, 1), bool),
                            draw[:, 1:] != draw[:, :-1]], axis=1)
    sel_idx = draw[first].astype(np.int64)
    sel_val = rng.normal(size=len(sel_idx)).astype(np.float32)
    counts = first.sum(axis=1)
    offset = np.concatenate([[0], np.cumsum(counts + 2)]).astype(np.int64)
    total = int(offset[-1])
    v0 = rng.normal(size=n).astype(np.float32)
    v1 = rng.normal(size=n).astype(np.float32)
    index = np.empty(total, np.int64)
    value = np.empty(total, np.float32)
    starts = offset[:-1]
    index[starts] = 0
    index[starts + 1] = 1
    value[starts] = v0
    value[starts + 1] = v1
    rows_sel = np.repeat(np.arange(n), counts)
    rank = (np.arange(len(sel_idx))
            - np.repeat(np.concatenate([[0], np.cumsum(counts)[:-1]]),
                        counts))
    pos = starts[rows_sel] + 2 + rank
    index[pos] = sel_idx
    value[pos] = sel_val
    y = (v0 + 0.5 * v1 > 0).astype(np.float32)
    gen_s = time.perf_counter() - t0

    from dmlc_core_tpu.models.histgbt_sparse import SparseHistGBT

    kw = dict(max_depth=depth, n_bins=n_bins, learning_rate=0.3)
    # warmup fit: compiles the k-round chunk program (through a
    # remote-compile tunnel that is ~a minute) so the timed fit below
    # measures steady state, not compilation.  Must run the SAME
    # rounds-per-dispatch k as the timed fit — a 1-tree warmup compiles
    # only the k=1 program and the timed fit then pays the k=8 compile
    # inside its wall (measured: 74 s for 40 rounds vs 21 s warm).
    K = int(os.environ.get("DMLC_TPU_SPARSE_ROUNDS_PER_DISPATCH", "8"))
    t0 = time.perf_counter()
    SparseHistGBT(n_trees=min(rounds, K), **kw).fit(
        offset, index, value, y, n_features=F)
    if rounds > K and rounds % K:
        # the tail chunk is its own k (static argname → own program);
        # compile it here or it lands inside the timed fit
        SparseHistGBT(n_trees=rounds % K, **kw).fit(
            offset, index, value, y, n_features=F)
    warmup_s = time.perf_counter() - t0
    m = SparseHistGBT(n_trees=rounds, **kw)
    t0 = time.perf_counter()
    m.fit(offset, index, value, y, n_features=F)
    fit_s = time.perf_counter() - t0
    pred = m.predict(offset, index, value)       # compiles the scan
    t0 = time.perf_counter()
    pred = m.predict(offset, index, value)
    pred_s = time.perf_counter() - t0
    acc = float(((pred > 0.5) == y).mean())

    import jax
    out = {
        "metric": "sparse_histgbt_rounds_per_sec",
        "value": round(rounds / fit_s, 4),
        "unit": "rounds/s",
        "rows": n, "features": F, "nnz": int(offset[-1]),
        "density": round(float(offset[-1]) / (n * F), 5),
        "total_bins": m.cuts.total_bins,
        "dense_bins_would_be": F * n_bins,
        "n_bins": n_bins, "depth": depth, "rounds": rounds,
        "gen_seconds": round(gen_s, 2),
        "warmup_seconds": round(warmup_s, 2),
        "fit_seconds": round(fit_s, 2),
        "predict_seconds": round(pred_s, 2),
        "train_acc": round(acc, 4),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
