#!/usr/bin/env python
"""Multi-chip sharded-ingest parity drill (scripts/ci.sh stage).

Proves, on an 8-device CPU mesh (the tier-1 stand-in for a v5e-8
slice), the three bit-parity contracts of the multi-chip HistGBT data
plane — then archives the evidence as a JSON scaling report (the
CPU-side counterpart of the ``MULTICHIP_r0*.json`` artifacts):

1. **1-chip oracle** — with the deterministic histogram reduction
   (``DMLC_HIST_BLOCKS``), an 8-chip data-parallel fit of the same
   global rows serializes (``save_model``) byte-identically to the
   1-chip fit: sharding changed WHERE rows live, not what was learned.
2. **Sharded ingest** — per-chip slab staging produces a binned matrix
   and ensemble byte-identical to the global-put path on the same mesh
   (odd row count: the last-shard remainder and chunk-tail math).
3. **Out-of-core** — the same rows streamed through
   ``make_device_data_iter`` in tiny ``DMLC_INGEST_CHUNK_ROWS`` slabs
   (DiskRowIter-shaped source, full matrix never materialized) still
   match byte-identically.

Exit 0 = all parities hold; the report lands at ``--out`` /
``MULTICHIP_OUT`` (default /tmp/multichip_scaling.json).
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEV = int(os.environ.get("MULTICHIP_DEVICES", 8))
os.environ["DMLC_HIST_BLOCKS"] = os.environ.get("DMLC_HIST_BLOCKS",
                                                str(N_DEV))

from dmlc_core_tpu.utils import force_cpu_devices  # noqa: E402

force_cpu_devices(N_DEV)

import numpy as np  # noqa: E402


def _save_bytes(model) -> bytes:
    path = tempfile.mktemp(suffix=".gbt")
    try:
        model.save_model(path)
        with open(path, "rb") as f:
            return f.read()
    finally:
        if os.path.exists(path):
            os.remove(path)


def _trees_equal(a, b) -> bool:
    return (len(a.trees) == len(b.trees)
            and all(np.array_equal(ta[k], tb[k])
                    for ta, tb in zip(a.trees, b.trees) for k in ta))


def main() -> int:
    out_path = os.environ.get("MULTICHIP_OUT", "/tmp/multichip_scaling.json")
    for i, a in enumerate(sys.argv):
        if a == "--out" and i + 1 < len(sys.argv):
            out_path = sys.argv[i + 1]

    import jax
    from jax.sharding import Mesh

    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.ops.histogram import hist_psum_bytes_per_round
    from dmlc_core_tpu.ops.quantile import compute_cuts

    devs = np.array(jax.devices())
    assert len(devs) >= N_DEV, (len(devs), N_DEV)

    rng = np.random.default_rng(7)
    n, F = 10_007, 12                    # odd: remainder/tail paths live
    depth, n_bins, rounds = 4, 32, 6
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0).astype(np.float32)
    cuts = compute_cuts(X, n_bins)
    kw = dict(n_trees=rounds, max_depth=depth, n_bins=n_bins,
              learning_rate=0.3)

    report = {"check": "multichip_scaling", "n_devices": N_DEV,
              "rows": n, "features": F, "rounds": rounds,
              "deterministic_hist_blocks":
                  int(os.environ["DMLC_HIST_BLOCKS"]),
              "hist_psum_bytes_per_round":
                  hist_psum_bytes_per_round(depth, F, n_bins),
              "parity": {}, "rounds_per_sec_per_chip": {}}
    failures = []

    def timed_fit(model, *args, **kwargs):
        t0 = time.perf_counter()
        model.fit(*args, **kwargs)
        return time.perf_counter() - t0

    # 1-chip oracle vs N-chip data-parallel fit (same rows, same cuts)
    m1 = HistGBT(mesh=Mesh(devs[:1], ("data",)), **kw)
    t1 = timed_fit(m1, X, y, cuts=cuts)
    mN = HistGBT(mesh=Mesh(devs[:N_DEV], ("data",)), **kw)
    tN = timed_fit(mN, X, y, cuts=cuts)
    oracle_ok = _save_bytes(m1) == _save_bytes(mN)
    report["parity"]["ensemble_bytes_equal_1_vs_n"] = oracle_ok
    report["rounds_per_sec_per_chip"]["1"] = round(rounds / t1, 3)
    report["rounds_per_sec_per_chip"][str(N_DEV)] = round(
        rounds / tN / N_DEV, 3)
    # CPU virtual devices share host cores, so this "efficiency" is an
    # engine-overhead floor, not a hardware claim (the TPU number comes
    # from bench.py chips=N's scaling block)
    report["scaling_efficiency_cpu"] = round(
        (rounds / tN / N_DEV) / (rounds / t1), 4)
    if not oracle_ok:
        failures.append("1-chip oracle ensemble bytes differ")

    # sharded ingest vs global-put staging, same mesh
    os.environ["DMLC_SHARDED_INGEST"] = "0"
    mG = HistGBT(mesh=Mesh(devs[:N_DEV], ("data",)), **kw)
    ddG = mG.make_device_data(X, y, cuts=cuts)
    os.environ["DMLC_SHARDED_INGEST"] = "1"
    mS = HistGBT(mesh=Mesh(devs[:N_DEV], ("data",)), **kw)
    ddS = mS.make_device_data(X, y, cuts=cuts)
    bins_ok = np.array_equal(np.asarray(ddG["bins_t"]),
                             np.asarray(ddS["bins_t"]))
    mG.fit_device(ddG)
    mS.fit_device(ddS)
    ingest_ok = bins_ok and _trees_equal(mG, mS)
    report["parity"]["sharded_ingest_bit_identical"] = ingest_ok
    if not ingest_ok:
        failures.append("sharded ingest diverged from global staging")

    # out-of-core: tiny streamed slabs through make_device_data_iter
    os.environ["DMLC_INGEST_CHUNK_ROWS"] = "1024"
    try:
        def slabs():
            for lo in range(0, n, 1024):
                yield X[lo:lo + 1024], y[lo:lo + 1024], None

        mO = HistGBT(mesh=Mesh(devs[:N_DEV], ("data",)), **kw)
        ddO = mO.make_device_data_iter(slabs, n_features=F,
                                       cuts=cuts, n_rows=n)
        mO.fit_device(ddO)
        ooc_ok = (np.array_equal(np.asarray(ddO["bins_t"]),
                                 np.asarray(ddS["bins_t"]))
                  and _save_bytes(mO) == _save_bytes(mS))
    finally:
        del os.environ["DMLC_INGEST_CHUNK_ROWS"]
    report["parity"]["out_of_core_bit_identical"] = ooc_ok
    if not ooc_ok:
        failures.append("out-of-core streamed ingest diverged")

    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    if failures:
        for msg in failures:
            print(f"FAIL: {msg}", file=sys.stderr)
        return 1
    print(f"multichip parity OK: report archived at {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
