#!/usr/bin/env python
"""Merge per-process trace shards into one Perfetto timeline.

Every process that ran with ``DMLC_TRACE=1`` and a metrics spool
(``DMLC_METRICS_SPOOL``) saved its Chrome-trace shard to
``<spool>/trace-<role>-<rank>-<pid>.json`` at exit (see
``base/metrics_agg.SpoolWriter``).  Each shard's timestamps are relative
to that process's own monotonic zero; the shard's ``otherData.epoch_us``
records the same instant on the wall clock.  This collector:

* normalizes every event onto a shared timeline (offset by the shard's
  epoch relative to the earliest shard's epoch);
* keeps the per-shard ``process_name``/``thread_name`` metadata rows, so
  the merged view shows one labelled row group per process;
* writes one ``chrome://tracing`` / Perfetto JSON file;
* returns a summary keyed by distributed trace id (``base/tracectx``
  stamps ``trace``/``span``/``parent`` into span args), listing the
  pids, roles and span names each request crossed — the artifact the
  fleet drill asserts "one request id crossed >= 3 processes" against.

Usage::

    python scripts/trace_collect.py <spool_dir> [-o merged.json]
"""

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["collect", "load_shards", "main"]


def load_shards(spool_dir: str) -> List[Dict[str, Any]]:
    """Read every ``trace-*.json`` shard in ``spool_dir`` (unparseable
    files are skipped — a crashed writer must not sink the merge)."""
    shards = []
    for path in sorted(glob.glob(os.path.join(spool_dir, "trace-*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list):
            doc["_path"] = path
            shards.append(doc)
    return shards


def _merge_events(shards: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    epochs = [float(s.get("otherData", {}).get("epoch_us", 0.0))
              for s in shards]
    t0 = min(epochs) if epochs else 0.0
    merged: List[Dict[str, Any]] = []
    for shard, epoch in zip(shards, epochs):
        offset = epoch - t0
        for ev in shard["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M" or "ts" not in ev:
                merged.append(ev)  # metadata rows carry no timestamp
            else:
                ev = dict(ev)
                ev["ts"] = float(ev["ts"]) + offset
                merged.append(ev)
    return merged


def _trace_summary(shards: List[Dict[str, Any]]) -> Dict[str, Any]:
    traces: Dict[str, Dict[str, Any]] = {}
    for shard in shards:
        other = shard.get("otherData", {})
        role = str(other.get("role", ""))
        for ev in shard["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            args = ev.get("args") or {}
            tid = args.get("trace")
            if not tid:
                continue
            entry = traces.setdefault(
                str(tid), {"pids": set(), "roles": set(), "spans": set()})
            entry["pids"].add(int(ev.get("pid", other.get("pid", 0))))
            entry["roles"].add(role or "process")
            entry["spans"].add(str(ev.get("name", "")))
    return {tid: {"pids": sorted(e["pids"]),
                  "roles": sorted(e["roles"]),
                  "spans": sorted(e["spans"])}
            for tid, e in traces.items()}


def collect(spool_dir: str, out_path: Optional[str] = None
            ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Merge all trace shards under ``spool_dir``.

    Returns ``(merged_doc, summary)``; ``merged_doc`` is the Perfetto
    JSON (written to ``out_path`` when given), ``summary`` maps each
    distributed trace id to the pids/roles/span names it crossed plus
    top-level ``processes``/``events``/``dropped_events`` totals.
    """
    shards = load_shards(spool_dir)
    events = _merge_events(shards)
    merged = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "shards": [os.path.basename(s["_path"]) for s in shards],
            "dropped_events": sum(
                int(s.get("otherData", {}).get("dropped_events", 0))
                for s in shards),
        },
    }
    summary = {
        "processes": len({int(s.get("otherData", {}).get("pid", i))
                          for i, s in enumerate(shards)}),
        "events": sum(1 for ev in events if ev.get("ph") != "M"),
        "dropped_events": merged["otherData"]["dropped_events"],
        "traces": _trace_summary(shards),
    }
    if out_path:
        d = os.path.dirname(os.path.abspath(out_path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged, summary


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("spool_dir", help="DMLC_METRICS_SPOOL directory "
                                      "holding trace-*.json shards")
    ap.add_argument("-o", "--out", default=None,
                    help="write the merged Perfetto JSON here")
    args = ap.parse_args(argv)
    _, summary = collect(args.spool_dir, args.out)
    json.dump(summary, sys.stdout, indent=2, sort_keys=True)
    print()
    return 0 if summary["processes"] else 1


if __name__ == "__main__":
    sys.exit(main())
