#!/usr/bin/env python
"""Parameter-server chaos drill for CI: SIGKILL a server mid-epoch.

A real multi-process PS job — scheduler (parent-hosted) + 2 server +
3 worker PROCESSES — trains sparse GBLinear over the dist_async
KVStore, twice:

1. **Baseline** — uninterrupted: every worker converges (train
   accuracy on its own shard above the floor) and exits clean.
2. **Kill/restore** — server 1 runs under the deterministic
   ``ps_push:kill`` fault and SIGKILLs itself mid-epoch.  Workers'
   pushes to that shard fail over (re-resolve via the scheduler inside
   ``DMLC_PS_RECONNECT_S``); the parent respawns the SAME server id
   pointed at the SAME ``DMLC_PS_SNAPSHOT_DIR``, which restores the
   shard from the newest atomic snapshot (vector clock included) and
   picks the job back up.  The lost tail between snapshot and kill is
   bounded by snapshot stride + staleness; the drill asserts every
   worker still converges within tolerance of the baseline and that
   the respawned server reports a restore
   (``dmlc_ps_server_restores_total``).

Every process runs under ``DMLC_LOCKCHECK=1`` + ``DMLC_RACECHECK=1``
and verifies zero lock-order cycles; the parent additionally asserts
zero happens-before races and archives the report to
``PS_RACECHECK_OUT`` (default ``/tmp/ps_racecheck.json``), and — under
``DMLC_LEAKCHECK=1`` — zero live resource leaks at exit, archived to
``PS_LEAKCHECK_OUT`` (default ``/tmp/ps_leakcheck.json``).

Exit 0 = both phases green.  Usage:
    python scripts/check_ps.py             # run the drill
    python scripts/check_ps.py --server    # (internal server entry)
    python scripts/check_ps.py --worker    # (internal worker entry)
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SERVERS = 2
N_WORKERS = 3
N_FEATURES = 50_000
ROWS_PER_WORKER = 6_000
NNZ = 16
BATCH_ROWS = 256
EPOCHS = 3
ACC_FLOOR = 0.80          # baseline convergence floor
ACC_TOLERANCE = 0.06      # kill-phase accuracy may trail baseline by this


def _shard_blocks(rank):
    """Deterministic per-worker CSR shard: 64 signal features out of
    50k, shared across ranks so every shard is learnable."""
    import numpy as np

    from dmlc_core_tpu.data.row_block import RowBlock

    sig_rng = np.random.default_rng(7)
    hot = sig_rng.choice(N_FEATURES, 64, replace=False)
    w_true = sig_rng.normal(size=64).astype(np.float32)
    rng = np.random.default_rng(100 + rank)
    blocks = []
    for _ in range(4):
        n = ROWS_PER_WORKER // 4
        idx = rng.integers(0, N_FEATURES, size=(n, NNZ)).astype(np.int64)
        idx[:, :4] = hot[rng.integers(0, 64, size=(n, 4))]
        vals = rng.normal(size=(n, NNZ)).astype(np.float32)
        order = np.argsort(hot)
        pos = order[np.searchsorted(hot[order], idx[:, :4])]
        y = ((vals[:, :4] * w_true[pos]).sum(1) > 0).astype(np.float32)
        off = np.arange(0, n * NNZ + 1, NNZ, dtype=np.int64)
        blocks.append(RowBlock(offset=off, label=y, index=idx.ravel(),
                               value=vals.ravel()))
    return blocks


# ---------------------------------------------------------------------------
# subprocess entries
# ---------------------------------------------------------------------------

def server_main() -> None:
    from dmlc_core_tpu.base import lockcheck
    from dmlc_core_tpu.parallel.ps import PSServer

    port = int(os.environ["PS_SCHED_PORT"])
    srv = PSServer("127.0.0.1", port,
                   server_id=int(os.environ["DMLC_PS_SERVER_ID"]))
    srv.start()
    srv.serve_forever(timeout_s=600)
    out = os.environ.get("PS_SERVER_STATS")
    if out:
        with open(out, "w") as f:
            json.dump({"server_id": srv.server_id,
                       "restored_version": srv.restored_version}, f)
    lockcheck.check()   # zero lock-order cycles, or die loudly


def worker_main() -> None:
    import numpy as np

    from dmlc_core_tpu.base import lockcheck
    from dmlc_core_tpu.models.linear import GBLinear
    from dmlc_core_tpu.parallel.kvstore import DistAsyncKVStore
    from dmlc_core_tpu.parallel.ps import PSClient

    rank = int(os.environ["DMLC_TASK_ID"])
    port = int(os.environ["PS_SCHED_PORT"])
    client = PSClient(root_uri="127.0.0.1", root_port=port, rank=rank)
    kv = DistAsyncKVStore(client, learning_rate=0.5)
    blocks = _shard_blocks(rank)
    model = GBLinear(learning_rate=0.5, reg_lambda=0.0)
    model.fit_ps(blocks, kv, num_col=N_FEATURES,
                 batch_rows=BATCH_ROWS, n_epochs=EPOCHS)
    # convergence: train accuracy on this worker's own shard
    correct = total = 0
    for blk in blocks:
        rows = np.repeat(np.arange(blk.size), np.diff(blk.offset))
        m = np.zeros(blk.size, np.float32)
        np.add.at(m, rows, model.weights[blk.index] * blk.value)
        m += model.bias
        correct += int(((m > 0) == (blk.label > 0.5)).sum())
        total += blk.size
    samples = kv.staleness_samples
    with open(os.path.join(os.environ["PS_OUT"],
                           f"worker-{rank}.json"), "w") as f:
        json.dump({"rank": rank, "accuracy": correct / total,
                   "staleness_max": max(samples) if samples else 0,
                   "pull_rounds": len(samples)}, f)
    kv.close(shutdown_job=False)    # parent owns the scheduler
    lockcheck.check()


# ---------------------------------------------------------------------------
# parent: supervise phases
# ---------------------------------------------------------------------------

def _check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def _launch(role, port, out_dir, snap_dir, server_id=-1, rank=-1,
            fault="", stats=""):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               DMLC_TPU_FORCE_CPU="1",
               DMLC_LOCKCHECK="1",
               DMLC_RACECHECK="1",
               DMLC_FAULT_INJECT=fault,
               DMLC_PS_SNAPSHOT_DIR=snap_dir,
               DMLC_PS_SNAPSHOT_STRIDE="1",
               DMLC_PS_RECONNECT_S="120",
               DMLC_PS_SERVER_ID=str(server_id),
               DMLC_TASK_ID=str(rank),
               PS_SCHED_PORT=str(port),
               PS_OUT=out_dir,
               PS_SERVER_STATS=stats)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), f"--{role}"], env=env)


def _wait(procs, timeout_s, label):
    deadline = time.time() + timeout_s
    for p in procs:
        left = max(1.0, deadline - time.time())
        try:
            p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            _check(False, f"{label}: pid {p.pid} hung")


def _worker_stats(out_dir):
    out = {}
    for rank in range(N_WORKERS):
        path = os.path.join(out_dir, f"worker-{rank}.json")
        with open(path) as f:
            out[rank] = json.load(f)
    return out


def _run_phase(label, tmp, fault_sid=None, fault=""):
    """One full PS job; returns per-worker stats + respawn stats."""
    from dmlc_core_tpu.parallel.ps import PSScheduler

    out_dir = os.path.join(tmp, label)
    snap_dir = os.path.join(tmp, f"{label}-snap")
    os.makedirs(out_dir)
    os.makedirs(snap_dir)
    sched = PSScheduler("127.0.0.1", nworker=N_WORKERS, nserver=N_SERVERS)
    sched.start()
    servers = [
        _launch("server", sched.port, out_dir, snap_dir, server_id=i,
                fault=fault if i == fault_sid else "")
        for i in range(N_SERVERS)]
    workers = [_launch("worker", sched.port, out_dir, snap_dir, rank=r)
               for r in range(N_WORKERS)]

    respawn_stats = None
    if fault_sid is not None:
        victim = servers[fault_sid]
        try:
            victim.wait(timeout=300)
        except subprocess.TimeoutExpired:
            _check(False, f"{label}: victim server never died")
        _check(victim.returncode == -signal.SIGKILL,
               f"{label}: server {fault_sid} SIGKILLed mid-epoch "
               f"(rc={victim.returncode})")
        stats_path = os.path.join(out_dir, "respawn.json")
        replacement = _launch("server", sched.port, out_dir, snap_dir,
                              server_id=fault_sid, stats=stats_path)
        servers = ([s for s in servers if s is not victim]
                   + [replacement])
        _wait(workers + servers, 600, label)
        with open(stats_path) as f:
            respawn_stats = json.load(f)
    else:
        _wait(workers + servers, 600, label)

    _check(all(p.returncode == 0 for p in workers),
           f"{label}: all {N_WORKERS} workers exited clean "
           f"({[p.returncode for p in workers]})")
    _check(all(p.returncode == 0 for p in servers),
           f"{label}: surviving servers exited clean "
           f"({[p.returncode for p in servers]})")
    sched.stop()
    return _worker_stats(out_dir), respawn_stats


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] == "--server":
        server_main()
        return
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        worker_main()
        return

    os.environ.setdefault("DMLC_LOCKCHECK", "1")
    os.environ.setdefault("DMLC_RACECHECK", "1")
    os.environ.setdefault("DMLC_LEAKCHECK", "1")
    # observability plane: scheduler parent, PS servers and workers all
    # spool metrics + trace shards into one directory (children inherit
    # the env through _launch)
    os.environ.setdefault("DMLC_TRACE", "1")
    spool = os.environ.get("DMLC_METRICS_SPOOL") \
        or tempfile.mkdtemp(prefix="dmlc_ps_spool")
    os.environ["DMLC_METRICS_SPOOL"] = spool
    t_drill0 = time.time()
    from dmlc_core_tpu.base import (leakcheck, lockcheck, metrics_agg,
                                    racecheck, slo)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_collect

    spool_writer = metrics_agg.install_spool("drill", 0)
    tmp = tempfile.mkdtemp(prefix="dmlc_ps_drill")
    staleness_bound = int(os.environ.get("DMLC_PS_STALENESS", 4))

    # -- phase 1: uninterrupted baseline --------------------------------
    base, _ = _run_phase("baseline", tmp)
    for rank, st in base.items():
        _check(st["accuracy"] >= ACC_FLOOR,
               f"baseline: worker {rank} converged "
               f"(acc {st['accuracy']:.3f} >= {ACC_FLOOR})")
        _check(st["staleness_max"] <= staleness_bound,
               f"baseline: worker {rank} staleness "
               f"{st['staleness_max']} <= bound {staleness_bound}")

    # -- phase 2: SIGKILL server 1 mid-epoch, respawn + restore ---------
    kill, respawn = _run_phase("kill", tmp, fault_sid=1,
                               fault="ps_push:kill:after=40")
    _check(respawn is not None and respawn["server_id"] == 1,
           "kill: replacement came back as server 1")
    _check(respawn["restored_version"] >= 1,
           f"kill: replacement restored snapshot "
           f"v{respawn['restored_version']} "
           "(dmlc_ps_server_restores_total >= 1)")
    for rank, st in kill.items():
        floor = base[rank]["accuracy"] - ACC_TOLERANCE
        _check(st["accuracy"] >= floor,
               f"kill: worker {rank} reconverged through the restore "
               f"(acc {st['accuracy']:.3f} >= baseline - tol {floor:.3f})")
        _check(st["staleness_max"] <= staleness_bound,
               f"kill: worker {rank} staleness {st['staleness_max']} "
               f"<= bound {staleness_bound}")

    # -- observability plane: merge spools, stitch the trace -------------
    if spool_writer is not None:
        spool_writer.close()    # final parent snapshot + trace shard
    drill_wall_s = time.time() - t_drill0
    merged, nprocs = metrics_agg.merge_spool(spool)
    metrics_out = os.environ.get("PS_METRICS_OUT", "/tmp/ps_metrics.json")
    metrics_agg.write_snapshot(metrics_out, merged)
    _check(nprocs >= 3,
           f"metrics spool merged {nprocs} processes "
           f"(artifact at {metrics_out})")
    t_tc0 = time.time()
    trace_out = os.environ.get("PS_TRACE_OUT", "/tmp/ps_trace.json")
    _, tsummary = trace_collect.collect(spool, trace_out)
    trace_collect_s = time.time() - t_tc0
    cross = {tid: t for tid, t in tsummary["traces"].items()
             if len(t["pids"]) >= 2 and "ps.push" in t["spans"]
             and "ps.server.push" in t["spans"]}
    _check(cross,
           f"{len(cross)} trace(s) followed a push worker -> server "
           f"across processes (merged Perfetto trace at {trace_out})")

    lockcheck.check()
    print("ok: zero lock-order cycles under DMLC_LOCKCHECK=1 (parent)")
    rc_out = os.environ.get("PS_RACECHECK_OUT", "/tmp/ps_racecheck.json")
    rc_report = racecheck.write_report(rc_out)
    racecheck.check()
    print(f"ok: zero happens-before races under DMLC_RACECHECK=1 "
          f"(parent; report at {rc_out})")
    lk_out = os.environ.get("PS_LEAKCHECK_OUT", "/tmp/ps_leakcheck.json")
    lk_report = leakcheck.write_report(lk_out)
    leakcheck.check()
    print(f"ok: zero live resource leaks under DMLC_LEAKCHECK=1 "
          f"(parent; report at {lk_out})")

    # -- SLO scorecard gate ----------------------------------------------
    spec_path = os.environ.get("PS_SLO_SPEC") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "slo", "ps.json")
    evidence = {
        "workers": {
            "min_accuracy": min(st["accuracy"] for st in base.values()),
            "staleness_max": max(st["staleness_max"]
                                 for stats in (base, kill)
                                 for st in stats.values()),
        },
        "respawn": respawn,
        "racecheck": {"races": len(rc_report["races"])},
        "leakcheck": {"leaks": len(lk_report["leaks"])},
    }
    scorecard = slo.evaluate(slo.SLOSpec.load(spec_path), merged, evidence)
    slo_out = os.environ.get("PS_SLO_OUT", "/tmp/ps_slo.json")
    with open(slo_out, "w") as f:
        json.dump(scorecard, f, indent=2)
    for row in scorecard["objectives"]:
        print(f"   slo[{row['name']}]: "
              f"{'pass' if row['pass'] else 'FAIL'} "
              f"(observed {row['observed']} {row['op']} "
              f"{row['threshold']}; {row['evidence']})")
    _check(scorecard["pass"],
           f"SLO scorecard {scorecard['spec']} green "
           f"(spec {spec_path}, scorecard at {slo_out})")
    report_out = os.environ.get("PS_DRILL_OUT", "/tmp/ps_drill.json")
    with open(report_out, "w") as f:
        json.dump({
            "baseline": base, "kill": kill, "respawn": respawn,
            "observability": {
                "spool_processes_merged": nprocs,
                "traces": len(tsummary["traces"]),
                "cross_process_traces": len(cross),
                "trace_collect_s": round(trace_collect_s, 3),
                "drill_wall_s": round(drill_wall_s, 3),
            },
            "slo": scorecard,
        }, f, indent=2)
    print(f"   report archived to {report_out}")
    print("PS CHAOS DRILL GREEN")


if __name__ == "__main__":
    main()
