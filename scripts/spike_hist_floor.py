#!/usr/bin/env python
"""Timeboxed spike (VERDICT r3 #5): attack the L0-L2 histogram floor.

The shipped Pallas kernel is near-roofline at deep levels but pinned at
~9-11 ms/level for L0-L2 (13-21% MXU) by per-feature fixed work that
does not scale with A·lo.  This spike slope-times kernel VARIANTS on
the real chip to (a) attribute the floor among {construction, dot,
accumulate}, (b) test the one untried structural change that is not a
documented dead end: batching each 8-feature group's output
accumulation into one VMEM-carried write (the shipped kernel does a
sublane-padded [1, A, lo] read-modify-write per feature — 8× padded
traffic on the out block).

Documented dead ends NOT re-derived here (BASELINE.md roofline,
memory): subtile packing, fused descend, lo=256, tile 32768/65536,
per-page... Slope method: each timing chains N level-passes inside one
jitted lax.scan with a carry perturbation, two N values cancel the
fixed dispatch overhead exactly.

Usage:  python scripts/spike_hist_floor.py   (on the TPU)
        SPIKE_ROWS=2000000 python scripts/spike_hist_floor.py
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

from dmlc_core_tpu.ops.histogram import (  # noqa: E402
    _TILE_ROWS, _lo_factor)

ROWS = int(os.environ.get("SPIKE_ROWS", 10_000_000))
FEATS = int(os.environ.get("SPIKE_FEATURES", 28))
BINS = 256


def _prep(n_build):
    rng = np.random.default_rng(0)
    bins_t = jnp.asarray(rng.integers(0, BINS, size=(FEATS, ROWS),
                                      dtype=np.uint8))
    node = jnp.asarray(
        rng.integers(0, max(2 * n_build, 1), size=ROWS, dtype=np.int32))
    g = jnp.asarray(rng.normal(size=ROWS).astype(np.float32))
    h = jnp.asarray(rng.random(ROWS).astype(np.float32))
    return bins_t, node, g, h


def _kernel_variant(bins_ref, node_ref, g_ref, h_ref, out_ref, *,
                    n_nodes, hi, lo, variant):
    """Variants of the shipped factored kernel's inner loop.

    shipped   — per-feature [1, A, lo] out accumulate (baseline copy)
    grpacc    — carry the 8-feature group's [8, A, lo] result in VMEM
                values, ONE out write per group
    nodot     — construction only (dot replaced by a cheap reduce) to
                attribute construction vs MXU cost
    noconstr  — dot on REUSED one-hots (construction hoisted out of the
                per-feature loop; wrong results, timing only)
    pack4/8   — r5, the "bin-packed dot" half of VERDICT r3 #5: S
                features share ONE dot ([S·2nh, T]·[S·lo, T] → the
                [2nh, lo] diagonal blocks are the per-feature results,
                cross-feature off-diagonals discarded).  A lo=32 dot
                pads 32 → 128 RHS lanes; packing fills those lanes
                with real work and cuts per-tile dot issues S×.  (The
                int8-MXU half of r3 #5 is analytically out: the LHS
                carries f32 g/h scaling — an int8×int8 dot can only
                COUNT, and the histogram needs weighted sums; also
                Mosaic rejects sub-int32 vector compares on this
                target, so int8 one-hot construction has no path
                either.)
    """
    i = pl.program_id(0)
    node = node_ref[:].astype(jnp.int32)
    g = g_ref[:].astype(jnp.bfloat16)
    h = h_ref[:].astype(jnp.bfloat16)
    F, T = bins_ref.shape
    nh = n_nodes * hi

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    nh_iota = jax.lax.broadcasted_iota(jnp.int32, (nh, T), 0)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (lo, T), 0)
    valid = node >= 0
    t0_node = jnp.where(valid, jnp.where(valid, node, 0) * hi,
                        jnp.int32(-(1 << 20)))

    oh0 = (nh_iota == t0_node).astype(jnp.bfloat16)        # for noconstr
    lhs0 = jnp.concatenate([oh0 * g, oh0 * h], axis=0)
    rhs0 = (lo_iota == 0).astype(jnp.bfloat16)

    def body(fg, carry):
        base = pl.multiple_of(fg * 8, 8)
        blk = bins_ref[pl.ds(base, 8), :].astype(jnp.int32)
        t0s = t0_node + blk // lo
        los = blk % lo
        if variant in ("pack4", "pack8"):
            S = int(variant[4:])
            for j in range(8 // S):
                lhss, rhss = [], []
                for k in range(S):
                    kk = S * j + k
                    oh = (nh_iota == t0s[kk:kk + 1]).astype(jnp.bfloat16)
                    lhss.append(jnp.concatenate([oh * g, oh * h], axis=0))
                    rhss.append((lo_iota == los[kk:kk + 1])
                                .astype(jnp.bfloat16))
                d = jax.lax.dot_general(
                    jnp.concatenate(lhss, axis=0),
                    jnp.concatenate(rhss, axis=0),
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [S·2nh, S·lo]
                acc = jnp.stack(
                    [d[k * 2 * nh:(k + 1) * 2 * nh,
                       k * lo:(k + 1) * lo] for k in range(S)], axis=0)
                idx = (pl.ds(base + S * j, S), slice(None), slice(None))
                out_ref[idx] = out_ref[idx] + acc
            return carry
        if variant == "grpacc":
            # ONE [8, 2nh, lo] write per feature group instead of 8
            # sublane-padded [1, ...] read-modify-writes.  jnp.stack of
            # the statically-unrolled dots (scatter .at[].set does not
            # lower in Mosaic)
            ds = []
            for k in range(8):
                oh = (nh_iota == t0s[k:k + 1]).astype(jnp.bfloat16)
                lhs = jnp.concatenate([oh * g, oh * h], axis=0)
                rhs = (lo_iota == los[k:k + 1]).astype(jnp.bfloat16)
                ds.append(jax.lax.dot_general(
                    lhs, rhs, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32))
            acc = jnp.stack(ds, axis=0)
            idx = (pl.ds(base, 8), slice(None), slice(None))
            out_ref[idx] = out_ref[idx] + acc
            return carry
        for k in range(8):
            if variant == "noconstr":
                lhs, rhs = lhs0, rhs0
            else:
                oh = (nh_iota == t0s[k:k + 1]).astype(jnp.bfloat16)
                lhs = jnp.concatenate([oh * g, oh * h], axis=0)
                rhs = (lo_iota == los[k:k + 1]).astype(jnp.bfloat16)
            if variant == "nodot":
                d = (jnp.sum(lhs, axis=1, keepdims=True)
                     + jnp.sum(rhs, axis=1, keepdims=True)[: 2 * nh]
                     ) * jnp.ones((2 * nh, lo), jnp.float32)
            else:
                d = jax.lax.dot_general(
                    lhs, rhs, dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            idx = (pl.ds(fg * 8 + k, 1), slice(None), slice(None))
            out_ref[idx] = out_ref[idx] + d[None]
        return carry

    jax.lax.fori_loop(0, F // 8, body, 0)


def _run_level(bins_t, node, g, h, n_build, variant):
    lo = _lo_factor(n_build, BINS)
    hi = -(-BINS // lo)
    T = _TILE_ROWS
    n = bins_t.shape[1]
    grid = n // T
    kern = functools.partial(_kernel_variant, n_nodes=n_build, hi=hi,
                             lo=lo, variant=variant)
    fp = FEATS - FEATS % 8  # keep it simple: multiple-of-8 features only

    def one_pass(bins_t, node, g, h):
        if variant == "prod":
            from dmlc_core_tpu.ops.histogram import build_histogram
            return build_histogram(bins_t[:fp], node, g, h,
                                   n_build, BINS, "pallas",
                                   transposed=True)
        return pl.pallas_call(
            kern,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((fp, T), lambda i: (0, i)),
                pl.BlockSpec((1, T), lambda i: (0, i)),
                pl.BlockSpec((1, T), lambda i: (0, i)),
                pl.BlockSpec((1, T), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((fp, 2 * n_build * hi, lo),
                                   lambda i: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((fp, 2 * n_build * hi, lo),
                                           jnp.float32),
        )(bins_t[:fp], node[None], g[None], h[None])

    @functools.partial(jax.jit, static_argnums=(4,))
    def chain(bins_t, node, g, h, reps):
        def step(carry, _):
            # perturb the node input from the carry so LICM cannot
            # collapse the chain to one pass
            out = one_pass(bins_t, jnp.bitwise_and(
                node + carry.astype(jnp.int32)[:1], 0x7fffffff) % max(
                2 * n_build, 1), g, h)
            return out.reshape(-1)[:1].astype(jnp.float32), None

        c, _ = jax.lax.scan(step, jnp.zeros(1, jnp.float32), None,
                            length=reps)
        return c

    def timed(reps):
        t0 = time.perf_counter()
        np.asarray(chain(bins_t, node, g, h, reps))
        return time.perf_counter() - t0

    timed(2)                       # compile both
    timed(12)
    slopes = []
    for _ in range(3):             # median of 3: single tunnel slopes
        t_small, t_big = timed(4), timed(24)   # swing +-2x run to run
        slopes.append((t_big - t_small) / 20.0)
    return sorted(slopes)[1]


def main():
    out = {"rows": ROWS, "features": FEATS, "tile": _TILE_ROWS,
           "platform": jax.devices()[0].platform}
    for n_build in (1, 2):               # the L0-L2 floor levels
        bins_t, node, g, h = _prep(n_build)
        for variant in ("prod", "shipped", "pack4", "pack8"):
            try:
                ms = _run_level(bins_t, node, g, h, n_build, variant) * 1e3
                out[f"nb{n_build}_{variant}_ms"] = round(ms, 3)
            except Exception as e:  # noqa: BLE001
                out[f"nb{n_build}_{variant}_ms"] = (
                    f"FAIL {type(e).__name__}: {e}"[:120])
            print(json.dumps({k: out[k] for k in list(out)[-1:]}),
                  flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
