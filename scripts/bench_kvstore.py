#!/usr/bin/env python
"""Config-4 proxy bench: KVStore dist_sync on a BERT-base-shaped grad set.

Measures the effect of gradient-fusion bucketing (parallel/kvstore.py):
one step = push all keys, pull all keys (allreduce + SGD update).  The
per-key mode is simulated with bucket_bytes=1 (every key its own
collective) — what the store did before bucketing.

Run on the 8-device CPU mesh (the multi-worker proxy BASELINE.md config 4
prescribes for CI):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python scripts/bench_kvstore.py

Prints one JSON line per mode with collective count and steps/s.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def bert_base_shapes(layers: int = 12, hidden: int = 768, vocab: int = 30522):
    """The BERT-base parameter inventory (~110M params, ~200 tensors)."""
    shapes = [("embed.word", (vocab, hidden)),
              ("embed.pos", (512, hidden)),
              ("embed.type", (2, hidden)),
              ("embed.ln.g", (hidden,)), ("embed.ln.b", (hidden,))]
    for i in range(layers):
        p = f"l{i}."
        shapes += [
            (p + "q.w", (hidden, hidden)), (p + "q.b", (hidden,)),
            (p + "k.w", (hidden, hidden)), (p + "k.b", (hidden,)),
            (p + "v.w", (hidden, hidden)), (p + "v.b", (hidden,)),
            (p + "o.w", (hidden, hidden)), (p + "o.b", (hidden,)),
            (p + "ln1.g", (hidden,)), (p + "ln1.b", (hidden,)),
            (p + "ffn1.w", (hidden, 4 * hidden)), (p + "ffn1.b", (4 * hidden,)),
            (p + "ffn2.w", (4 * hidden, hidden)), (p + "ffn2.b", (hidden,)),
            (p + "ln2.g", (hidden,)), (p + "ln2.b", (hidden,)),
        ]
    shapes += [("pool.w", (hidden, hidden)), ("pool.b", (hidden,))]
    return shapes


def main() -> None:
    # the axon TPU plugin overrides JAX_PLATFORMS; force the CPU mesh
    # explicitly (the same hook tests/conftest.py uses)
    ndev = int(os.environ.get("BENCH_KV_DEVICES", 8))
    if ndev > 1:
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(ndev)
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.parallel.kvstore import KVStore
    from dmlc_core_tpu.parallel.mesh import local_mesh

    steps = int(os.environ.get("BENCH_KV_STEPS", 5))
    mesh = local_mesh()
    W = mesh.devices.size
    sharding1 = NamedSharding(mesh, P("data"))
    # full BERT-base hidden=768 (110M params) on real chips; the CI proxy
    # shrinks hidden/vocab (the contrast under test is collective COUNT,
    # which depends only on the 199-key structure, not tensor width —
    # 8 virtual CPU devices on one core can't move 437MB/step)
    hidden = int(os.environ.get("BENCH_KV_HIDDEN", 128))
    vocab = int(os.environ.get("BENCH_KV_VOCAB", 4000))
    shapes = bert_base_shapes(hidden=hidden, vocab=vocab)
    n_params = sum(int(np.prod(s)) for _, s in shapes)
    rng = np.random.default_rng(0)
    grads = {k: jax.device_put(
        rng.normal(size=(W, *s)).astype(np.float32) / W, sharding1)
        for k, s in shapes}

    # BASELINE config 4's target line is BUS BANDWIDTH: for a ring-style
    # allreduce of S bytes over n workers every worker moves
    # 2·(n-1)/n · S bytes over its links (the NCCL busbw convention), so
    # achieved bus GB/s = that / sync seconds.  Meaningless at W=1 (the
    # psum is a no-op) → null.
    bus_bytes = 2 * (W - 1) / W * n_params * 4

    for label, bucket_bytes in (("per-key", 1), ("bucketed", 64 << 20)):
        kv = KVStore.create("dist_sync", mesh=mesh, learning_rate=0.01,
                            bucket_bytes=bucket_bytes)
        kv.init([k for k, _ in shapes],
                [np.zeros(s, np.float32) for _, s in shapes])
        # warm the jit caches
        kv.push([k for k, _ in shapes], [grads[k] for k, _ in shapes])
        kv.pull([k for k, _ in shapes])

        # sync-only timing (the collective itself, no SGD update): the
        # number the bus-bandwidth target compares against
        flat_grads = {k: grads[k] for k, _ in shapes}
        sync_out = kv._sync_bucketed(dict(flat_grads))     # warm
        jax.block_until_ready(list(sync_out.values()))
        t0 = time.perf_counter()
        for _ in range(steps):
            sync_out = kv._sync_bucketed(dict(flat_grads))
        jax.block_until_ready(list(sync_out.values()))
        dt_sync = (time.perf_counter() - t0) / steps

        kv.stats = {"sync_calls": 0, "keys_synced": 0}
        t0 = time.perf_counter()
        for _ in range(steps):
            kv.push([k for k, _ in shapes], [grads[k] for k, _ in shapes])
            out = kv.pull([k for k, _ in shapes])
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        print(json.dumps({
            "mode": label,
            "keys": len(shapes),
            "params": n_params,
            "workers": W,
            "collectives_per_step": kv.stats["sync_calls"] // steps,
            "steps_per_sec": round(steps / dt, 3),
            "grad_mb_per_step": round(n_params * 4 / 1e6, 1),
            "sync_ms": round(dt_sync * 1e3, 2),
            "allreduce_bus_mb_per_step": round(bus_bytes / 1e6, 1),
            "bus_gbps": (round(bus_bytes / dt_sync / 1e9, 3)
                         if W > 1 else None),
            "bus_gbps_incl_update": (round(bus_bytes * steps / dt / 1e9, 3)
                                     if W > 1 else None),
            "platform": jax.devices()[0].platform,
        }))


if __name__ == "__main__":
    main()
