#!/usr/bin/env python
"""Multi-host launch drill for CI: supervised respawn on a fake cluster.

Proves the launch subsystem end to end WITHOUT any real SSH/k8s — the
"cluster" is a :class:`~dmlc_core_tpu.launch.FakeTransport` of 3 virtual
hosts whose failures are scripted through the ``base/faultinject``
grammar:

1. **Elastic fit under host death** — an
   :class:`~dmlc_core_tpu.parallel.recovery.ElasticLauncher` (tracker +
   supervised JobSet) runs a 4-rank data-parallel fit over the 3 fake
   hosts.  Mid-round, ``launch_host:kill=h1`` downs host ``h1``:
   SIGKILLs its worker and refuses further spawns there.  The JobSet
   must respawn the lost rank on a SURVIVING host; the replacement
   reclaims its tracker rank inside the grace window, rolls back to the
   recovery floor and replays — and every finished ensemble must be
   byte-identical to an uninterrupted baseline run.
2. **Fleet scale-out over fake hosts** — a
   :class:`~dmlc_core_tpu.serve.fleet.LauncherScaler` (JobSet-backed
   autoscale backend) grows a serving fleet from 2 to 4 replicas placed
   across the fake hosts while a closed-loop verified load generator
   runs through the transition: zero dropped, zero wrong.

The whole drill runs under ``DMLC_LOCKCHECK=1`` + ``DMLC_RACECHECK=1``
with zero findings required; the racecheck report is archived to
``LAUNCH_RACECHECK_OUT`` (default ``/tmp/launch_racecheck.json``), and
``DMLC_LEAKCHECK=1`` gates GREEN on zero live resource leaks at exit
(``LAUNCH_LEAKCHECK_OUT``, default ``/tmp/launch_leakcheck.json``).
Exit 0 = drill green.  Usage:
    python scripts/check_launch.py            # run the drill
    python scripts/check_launch.py --worker   # (internal worker entry)
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_WORKERS = 4
TOTAL_ROUNDS = 10
STRIDE = 2
N_ROWS, N_FEAT = 1500, 8
HOSTS = ["h0", "h1", "h2"]
LOAD_S = 6.0


def _dataset():
    import numpy as np

    rng = np.random.default_rng(7)
    X = rng.normal(size=(N_ROWS, N_FEAT)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] - 0.5 * X[:, 3] > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# worker entry (subprocess, spawned by the JobSet)
# ---------------------------------------------------------------------------

def worker_main() -> None:
    from dmlc_core_tpu.utils import force_cpu_devices

    force_cpu_devices(1)

    from dmlc_core_tpu.base import lockcheck
    from dmlc_core_tpu.data.iter import ArrayRowIter
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.parallel.recovery import (ElasticSession,
                                                 ElasticTrainer)

    # the launch ABI is the whole bootstrap: tracker address from
    # slave_envs(), rank pinned to DMLC_TASK_ID so a respawned attempt
    # reclaims the rank it replaces
    port = int(os.environ["DMLC_TRACKER_PORT"])
    rank = int(os.environ["DMLC_TASK_ID"])
    out_dir = os.environ["LAUNCH_OUT"]
    # DMLC_METRICS_SPOOL arrives via JobSet.worker_env's observability
    # overlay — the spool install exercises that injection path
    from dmlc_core_tpu.base import metrics_agg
    metrics_agg.install_spool("launch_worker", rank)
    X, y = _dataset()

    sess = ElasticSession(os.environ["DMLC_TRACKER_URI"], port, rank=rank)
    model = HistGBT(n_trees=TOTAL_ROUNDS, max_depth=3, n_bins=16,
                    learning_rate=0.3)
    trainer = ElasticTrainer(model, TOTAL_ROUNDS)  # stride/dir via knobs
    trainer.run(sess,
                lambda lo, hi: ArrayRowIter(X[lo:hi], y[lo:hi]),
                N_ROWS, join_timeout_s=300)
    model.save_model(os.path.join(out_dir, f"model-rank{sess.grank}.gbt"))
    sess.shutdown()
    lockcheck.check()   # zero lock-order cycles, or die loudly


# ---------------------------------------------------------------------------
# parent: drive the fake cluster
# ---------------------------------------------------------------------------

def _check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def _read_models(out_dir):
    out = {}
    for name in sorted(os.listdir(out_dir)):
        if name.startswith("model-rank") and name.endswith(".gbt"):
            with open(os.path.join(out_dir, name), "rb") as f:
                out[name] = f.read()
    return out


def _metric_total(counter, **labels):
    return sum(s["value"] for s in counter._snap()
               if all(s["labels"].get(k) == v for k, v in labels.items()))


def _elastic_fit(tmp, tag, fault=""):
    """One supervised 4-rank fit over the fake cluster; returns
    (model bytes by rank file, launcher) after asserting clean exits."""
    from dmlc_core_tpu.base import faultinject
    from dmlc_core_tpu.launch import FakeTransport
    from dmlc_core_tpu.parallel.recovery import ElasticLauncher

    out_dir = os.path.join(tmp, f"out-{tag}")
    rec_dir = os.path.join(tmp, f"rec-{tag}")
    os.makedirs(out_dir)
    transport = FakeTransport(hosts=list(HOSTS),
                              log_dir=os.path.join(tmp, f"logs-{tag}"))
    launcher = ElasticLauncher(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        N_WORKERS, transport=transport, grace_s=120.0,
        envs={"JAX_PLATFORMS": "cpu", "DMLC_TPU_FORCE_CPU": "1",
              "DMLC_LOCKCHECK": "1", "DMLC_RACECHECK": "1",
              "DMLC_RECOVERY_DIR": rec_dir,
              "DMLC_RECOVERY_STRIDE": str(STRIDE),
              "DMLC_FAULT_INJECT": "",      # children never inherit ours
              "LAUNCH_OUT": out_dir},
        restart_limit=2, monitor_s=0.05, name=f"elastic-{tag}")
    with faultinject.inject(fault):
        codes = launcher.run(timeout=900)
    _check(codes == [0] * N_WORKERS,
           f"{tag}: all {N_WORKERS} ranks finished clean ({codes})")
    models = _read_models(out_dir)
    _check(len(models) == N_WORKERS, f"{tag}: {N_WORKERS} ensembles saved")
    blobs = list(models.values())
    _check(all(b == blobs[0] for b in blobs),
           f"{tag}: ensembles byte-identical across ranks")
    return blobs[0], launcher, transport


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        worker_main()
        return

    os.environ.setdefault("DMLC_LOCKCHECK", "1")
    os.environ.setdefault("DMLC_RACECHECK", "1")
    os.environ.setdefault("DMLC_LEAKCHECK", "1")
    # observability plane: JobSet children inherit the spool through
    # worker_env's injection; the parent spools its own registry too
    spool = os.environ.get("DMLC_METRICS_SPOOL") \
        or tempfile.mkdtemp(prefix="dmlc_launch_spool")
    os.environ["DMLC_METRICS_SPOOL"] = spool
    from dmlc_core_tpu.utils import force_cpu_devices

    force_cpu_devices(1)

    import numpy as np

    from dmlc_core_tpu.base import (leakcheck, lockcheck, metrics_agg,
                                    racecheck)
    from dmlc_core_tpu.launch import launch_metrics

    spool_writer = metrics_agg.install_spool("drill", 0)

    tmp = tempfile.mkdtemp(prefix="dmlc_launch")

    # -- stage 1a: uninterrupted baseline on the fake cluster -----------
    baseline, launcher, _ = _elastic_fit(tmp, "baseline")
    _check(launcher.jobset.respawns() == 0, "baseline: zero respawns")
    st = launcher.jobset.stats()
    _check(st["backend"] == "fake" and st["spawns"] == N_WORKERS,
           f"baseline: {N_WORKERS} spawns over the fake transport")

    # -- stage 1b: host h1 dies mid-round; JobSet respawns the rank -----
    blob, launcher, transport = _elastic_fit(
        tmp, "chaos", fault="launch_host:kill=h1:after=60:n=1")
    _check(transport.down_hosts() == ["h1"],
           "chaos: fake host h1 was downed by the injected fault")
    _check(launcher.jobset.respawns() >= 1,
           f"chaos: JobSet respawned the lost rank "
           f"({launcher.jobset.respawns()} respawns)")
    ranks = launcher.jobset.stats()["ranks"]
    _check(ranks[1]["host"] in ("h0", "h2"),
           f"chaos: rank 1 relanded on a surviving host "
           f"({ranks[1]['host']})")
    kinds = [e["event"] for e in launcher.jobset.events()]
    _check("respawn" in kinds and "exit" in kinds,
           "chaos: lifecycle events recorded (exit → respawn)")
    _check(_metric_total(launch_metrics()["respawns"]) >= 1,
           "chaos: dmlc_launch_respawns_total counted")
    _check(blob == baseline,
           "chaos: recovered ensembles byte-identical to the "
           "uninterrupted baseline")

    # -- stage 2: fleet 2 -> 4 replicas across fake hosts under load ----
    from dmlc_core_tpu.launch import FakeTransport
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.serve import checkpoint_model
    from dmlc_core_tpu.serve.fleet import (FleetRouter, FleetTracker,
                                           LauncherScaler, run_loadgen)

    X, y = _dataset()
    m1 = HistGBT(n_trees=4, max_depth=3, n_bins=16).fit(X, y)
    v1_uri = f"file://{tmp}/v1.ckpt"
    checkpoint_model(v1_uri, m1, version=1)
    expected_npz = os.path.join(tmp, "expected.npz")
    np.savez(expected_npz, X=X, v1=m1.predict(X))

    child_env = {"JAX_PLATFORMS": "cpu", "DMLC_TPU_FORCE_CPU": "1",
                 "DMLC_LOCKCHECK": "1", "DMLC_RACECHECK": "1",
                 "DMLC_FAULT_INJECT": ""}
    tracker = FleetTracker(nworker=8)
    tracker.start()
    fleet_tr = FakeTransport(hosts=["f0", "f1"],
                             log_dir=os.path.join(tmp, "logs-fleet"))
    scaler = LauncherScaler(tracker, v1_uri, transport=fleet_tr,
                            initial=2, spawn_env=child_env)
    router = None

    def _wait(pred, timeout_s, label):
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if pred():
                return
            time.sleep(0.1)
        _check(False, f"timed out waiting for {label}")

    try:
        _wait(lambda: len(tracker.serve_endpoints()) == 2,
              180, "initial replica registration")
        _check(True, "fleet: 2 launcher-backed replicas registered")
        router = FleetRouter(tracker, probe_s=0.2).start()

        load = {}

        def _loadgen_bg():
            load.update(run_loadgen(
                router.url, expected_npz, duration_s=LOAD_S, procs=2,
                threads=3, base_qps=60.0, timeout_ms=10_000,
                workdir=tmp, env=child_env))

        t = threading.Thread(target=_loadgen_bg)
        t.start()
        time.sleep(LOAD_S / 4.0)
        scaler.scale(1)
        scaler.scale(1)
        _wait(lambda: len(tracker.serve_endpoints()) == 4,
              180, "scaled-out replica registration")
        _check(True, "fleet: scaled 2 -> 4 replicas through the JobSet")
        t.join(timeout=LOAD_S + 180)
        _check(not t.is_alive(), "fleet: load generator finished")
        _check(load.get("dropped") == 0 and load.get("wrong") == 0,
               f"fleet: zero dropped / zero wrong through the scale-out "
               f"({load.get('ok')} ok of {load.get('count')})")
        st = scaler.jobset.stats()
        hosts_used = sorted({r["host"] for r in st["ranks"].values()
                             if r["host"]})
        _check(hosts_used == ["f0", "f1"],
               f"fleet: replicas placed across fake hosts {hosts_used}")
        _check(st["spawn_ms_p95"] > 0,
               f"fleet: spawn latency recorded "
               f"(p95 {st['spawn_ms_p95']:.1f} ms)")
    finally:
        if router is not None:
            router.close()
        scaler.reap(timeout=15)
        tracker.stop()

    if spool_writer is not None:
        spool_writer.close()
    merged, nprocs = metrics_agg.merge_spool(spool)
    metrics_out = os.environ.get("LAUNCH_METRICS_OUT",
                                 "/tmp/launch_metrics.json")
    metrics_agg.write_snapshot(metrics_out, merged)
    _check(nprocs >= 2,
           f"metrics spool merged {nprocs} processes (JobSet children "
           f"joined via worker_env injection; artifact at {metrics_out})")

    lockcheck.check()
    print("ok: zero lock-order cycles under DMLC_LOCKCHECK=1 (parent)")
    rc_out = os.environ.get("LAUNCH_RACECHECK_OUT",
                            "/tmp/launch_racecheck.json")
    racecheck.write_report(rc_out)
    racecheck.check()
    print(f"ok: zero happens-before races under DMLC_RACECHECK=1 "
          f"(parent; report at {rc_out})")
    lk_out = os.environ.get("LAUNCH_LEAKCHECK_OUT",
                            "/tmp/launch_leakcheck.json")
    leakcheck.write_report(lk_out)
    leakcheck.check()
    print(f"ok: zero live resource leaks under DMLC_LEAKCHECK=1 "
          f"(parent; report at {lk_out})")
    print("LAUNCH DRILL GREEN")


if __name__ == "__main__":
    main()
