#!/usr/bin/env python
"""Histogram-kernel CI drill (scripts/ci.sh stage).

Two halves, one JSON artifact (``CHECK_HIST_OUT``, default
``/tmp/hist_kernel.json``):

* **Cross-method parity sweep** — every histogram engine (segment /
  matmul / pallas-interpret) must produce the BIT-IDENTICAL
  ``[2, N, F, B]`` histogram on the same inputs, at odd row counts with
  masked (``node_id < 0``) rows, through an int4-packed
  :class:`~dmlc_core_tpu.ops.binlayout.BinLayout` (compact remap), and
  through a feature BUNDLE (unbundled via ``tot − Σseg``).  Gradients
  are drawn from {±1, ±0.5} and hessians from {0.5, 1} so every f32
  partial sum is exact regardless of reduction order — ``array_equal``
  is the assertion, not allclose.  Any mismatch fails the stage.
* **Timed micro-bench** — per-method ns/row on a jitted plain build and
  on the packed-layout build, archived so a kernel regression shows up
  as a number in the artifact chain rather than only as a slower BENCH
  headline.  Timing is evidence, never a gate (CPU CI timing is noisy;
  the bench owns the perf bar).

Knobs: ``CHECK_HIST_ROWS`` (micro-bench rows, default 50_000),
``CHECK_HIST_REPS`` (default 3).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.utils import force_cpu_devices  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    force_cpu_devices(1)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from dmlc_core_tpu.ops import binlayout as bl  # noqa: E402
from dmlc_core_tpu.ops.histogram import (build_histogram,  # noqa: E402
                                         fused_round,
                                         select_feature_bins)

METHODS = ("segment", "matmul", "pallas")


def _exact_gh(rng, n):
    """bf16-exact gradient/hessian draws: sums are exact in f32 for any
    reduction order, so cross-method comparisons can be bit-level."""
    g = rng.choice(np.array([-1.0, -0.5, 0.5, 1.0], np.float32), size=n)
    h = rng.choice(np.array([0.5, 1.0], np.float32), size=n)
    return g, h


def _node_ids(rng, n, n_nodes):
    nid = rng.integers(0, n_nodes, size=n).astype(np.int32)
    nid[rng.random(n) < 0.1] = -1          # masked rows contribute nothing
    return nid


def _spread_bins(rng, n, F, B, narrow):
    """[F, n] uint8 bins; ``narrow`` features use 2-6 SPREAD bin ids (the
    quantile-cut eps-bump shape that defeats width-based packing and
    requires the compact remap), the rest sweep all B bins."""
    rows = []
    for f in range(F):
        if f in narrow:
            k = 2 + (f % 5)
            ids = np.sort(rng.choice(B, size=k, replace=False))
            rows.append(ids[rng.integers(0, k, n)])
        else:
            rows.append((np.arange(n) + f) % B)
    return np.ascontiguousarray(np.stack(rows).astype(np.uint8))


def _exclusive_bins(rng, n, B):
    """3 features: one wide + two near-one-hot mutually exclusive ones
    (defaults 5 and 7; off-default rows never overlap) — the EFB shape."""
    onehot = rng.integers(0, 3, size=n)    # 0 = both default
    b0 = ((np.arange(n) * 7) % B).astype(np.uint8)
    b1 = np.where(onehot == 1, 20, 5).astype(np.uint8)
    b2 = np.where(onehot == 2, 25, 7).astype(np.uint8)
    return np.ascontiguousarray(np.stack([b0, b1, b2]))


def _build(bins_t, nid, g, h, n_nodes, n_bins, method, layout=None):
    fn = jax.jit(lambda b, i, gg, hh: build_histogram(
        b, i, gg, hh, n_nodes, n_bins, method, transposed=True,
        layout=layout))
    return np.asarray(fn(bins_t, nid, g, h))


def _parity_case(name, bins_t, layout, n_nodes, n_bins, rng):
    """All engines vs the plain segment reference; packed/bundled builds
    go through ``unbundle_hist`` back to ``[2, N, F, B]`` first."""
    n = bins_t.shape[1]
    g, h = _exact_gh(rng, n)
    nid = _node_ids(rng, n, n_nodes)
    ref = _build(bins_t, nid, g, h, n_nodes, n_bins, "segment")
    phys = (np.asarray(bl.pack_matrix(bins_t, layout))
            if layout is not None else None)
    mismatches = []
    for m in METHODS:
        if layout is None:
            got = _build(bins_t, nid, g, h, n_nodes, n_bins, m)
        else:
            st = _build(phys, nid, g, h, n_nodes, n_bins, m, layout=layout)
            got = np.asarray(bl.unbundle_hist(st, layout, n_bins))
        if not np.array_equal(got, ref):
            bad = int(np.sum(got != ref))
            mismatches.append(f"{m}: {bad} cells differ")
    return {"case": name, "rows": n, "methods": list(METHODS),
            "layout": (None if layout is None else
                       f"{layout.n_features}F->{layout.phys_rows}phys"),
            "ok": not mismatches, "mismatches": mismatches}


def _fused_parity_case(name, bins_t, layout, n_prev, n_bins, rng,
                       tile_rows=256):
    """Fused round kernel (interpret mode off-TPU) vs the unfused
    segment sequence: descend + left-child build + sibling subtraction
    must agree bit-for-bit on BOTH outputs — the stacked child
    histograms and the advanced node ids.  A small ``tile_rows`` at odd
    row counts exercises the multi-tile VMEM-resident accumulation."""
    n = bins_t.shape[1]
    F = layout.n_features if layout is not None else bins_t.shape[0]
    g, h = _exact_gh(rng, n)
    nid = _node_ids(rng, n, n_prev)
    feat_tab = rng.integers(0, F, n_prev).astype(np.int32)
    thr_tab = rng.integers(0, n_bins, n_prev).astype(np.int32)
    safe = np.where(nid >= 0, nid, 0)
    feat_sel = feat_tab[safe]
    thr_sel = thr_tab[safe]
    phys = (np.asarray(bl.pack_matrix(bins_t, layout))
            if layout is not None else bins_t)
    prev = _build(phys, nid, g, h, n_prev, n_bins, "segment",
                  layout=layout)
    # unfused reference: select + compare descend, left build, parent −
    # left in storage space
    row_bin = np.asarray(select_feature_bins(
        jnp.asarray(phys), jnp.asarray(feat_sel), layout=layout))
    new_ref = np.where(nid >= 0, 2 * nid + (row_bin > thr_sel), -1)
    node_h = np.where((nid >= 0) & (new_ref % 2 == 0),
                      new_ref >> 1, -1).astype(np.int32)
    left = _build(phys, node_h, g, h, n_prev, n_bins, "segment",
                  layout=layout)
    hist_ref = np.stack([left, prev - left], axis=2).reshape(
        2, 2 * n_prev, left.shape[2], left.shape[3])
    new_f, hist_f, _ = fused_round(
        jnp.asarray(phys), jnp.asarray(nid), jnp.asarray(feat_sel),
        jnp.asarray(thr_sel), jnp.asarray(g), jnp.asarray(h),
        jnp.asarray(prev), n_prev, n_bins, tile_rows=tile_rows,
        layout=layout)
    mismatches = []
    if not np.array_equal(np.asarray(new_f), new_ref):
        mismatches.append(
            f"node: {int(np.sum(np.asarray(new_f) != new_ref))} "
            "rows differ")
    if not np.array_equal(np.asarray(hist_f), hist_ref):
        mismatches.append(
            f"hist: {int(np.sum(np.asarray(hist_f) != hist_ref))} "
            "cells differ")
    return {"case": name, "rows": n, "methods": ["fused_round"],
            "layout": (None if layout is None else
                       f"{layout.n_features}F->{layout.phys_rows}phys"),
            "ok": not mismatches, "mismatches": mismatches}


def _microbench(rows, reps):
    """Per-method ns/row on a jitted plain build (F=28, B=64, 8 nodes)
    plus the packed-layout pallas read path (28 narrow features -> 14
    int4 pairs).  Warm call excluded; median of ``reps`` timed calls."""
    F, B, n_nodes = 28, 64, 8
    rng = np.random.default_rng(3)
    g, h = _exact_gh(rng, rows)
    nid = _node_ids(rng, rows, n_nodes)
    out = {}

    def timed(tag, bins_t, method, layout=None):
        fn = jax.jit(lambda b, i, gg, hh: build_histogram(
            b, i, gg, hh, n_nodes, B, method, transposed=True,
            layout=layout))
        fn(bins_t, nid, g, h).block_until_ready()      # compile outside
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(bins_t, nid, g, h).block_until_ready()
            ts.append(time.perf_counter() - t0)
        out[tag] = round(sorted(ts)[len(ts) // 2] / rows * 1e9, 2)

    plain = _spread_bins(rng, rows, F, B, narrow=())
    for m in METHODS:
        timed(m, plain, m)
    narrow = _spread_bins(rng, rows, F, B, narrow=tuple(range(F)))
    layout = bl.compute_layout(bl.bin_counts(narrow, B), F, B, pack=True)
    if layout is not None:
        phys = np.asarray(bl.pack_matrix(narrow, layout))
        timed("pallas_packed", phys, "pallas", layout=layout)

    # fused round kernel: descend + build + sibling subtraction in one
    # program (interpret mode on CPU — relative drift is the signal)
    n_prev = n_nodes >> 1
    prev = _build(plain, _node_ids(rng, rows, n_prev), g, h, n_prev, B,
                  "segment")
    feat_sel = rng.integers(0, F, rows).astype(np.int32)
    thr_sel = rng.integers(0, B, rows).astype(np.int32)
    nid4 = _node_ids(rng, rows, n_prev)
    fused_fn = jax.jit(lambda b, i, fs, ts, gg, hh, pv: fused_round(
        b, i, fs, ts, gg, hh, pv, n_prev, B))
    args = (plain, nid4, feat_sel, thr_sel, g, h, prev)
    jax.block_until_ready(fused_fn(*args))          # compile outside
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fused_fn(*args))
        ts.append(time.perf_counter() - t0)
    out["fused_round"] = round(sorted(ts)[len(ts) // 2] / rows * 1e9, 2)
    return out


def main() -> int:
    rng = np.random.default_rng(11)
    B = 32
    results = []

    # 1. plain engines, odd rows, masked nodes
    results.append(_parity_case(
        "plain_odd", _spread_bins(rng, 1021, 9, B, narrow=()), None, 4,
        B, rng))

    # 2. int4-packed compact-remap layout (narrow SPREAD bins)
    bins_n = _spread_bins(rng, 777, 9, B, narrow=(1, 4, 7, 8))
    lay_n = bl.compute_layout(bl.bin_counts(bins_n, B), 9, B, pack=True)
    assert lay_n is not None and lay_n.pairs, "packed layout must fire"
    results.append(_parity_case("packed_remap", bins_n, lay_n, 4, B, rng))

    # 3. feature bundle (mutually exclusive near-one-hot pair)
    bins_b = _exclusive_bins(rng, 1003, B)
    counts_b = bl.bin_counts(bins_b, B)
    bundles = bl.detect_bundles(bins_b, counts_b, B)
    assert bundles, "EFB detection must fire on the exclusive pair"
    lay_b = bl.compute_layout(counts_b, 3, B, pack=True, bundles=bundles)
    assert lay_b is not None and lay_b.has_bundles
    results.append(_parity_case("bundled", bins_b, lay_b, 2, B, rng))

    # 4-6. fused round kernel (ISSUE 18): one Pallas program doing
    # descend + accumulate + sibling subtraction, vs the unfused
    # segment sequence — plain, packed-remap and bundled layouts
    results.append(_fused_parity_case(
        "fused_plain", _spread_bins(rng, 1021, 9, B, narrow=()), None,
        4, B, rng))
    results.append(_fused_parity_case(
        "fused_packed", bins_n, lay_n, 4, B, rng))
    results.append(_fused_parity_case(
        "fused_bundled", bins_b, lay_b, 2, B, rng, tile_rows=512))

    rows = int(os.environ.get("CHECK_HIST_ROWS", 50_000))
    reps = int(os.environ.get("CHECK_HIST_REPS", 3))
    t0 = time.perf_counter()
    ns_per_row = _microbench(rows, reps)
    record = {
        "check": "hist_kernel",
        "platform": jax.default_backend(),
        "parity": results,
        "microbench": {"rows": rows, "reps": reps,
                       "ns_per_row": ns_per_row,
                       "wall_s": round(time.perf_counter() - t0, 2)},
    }
    out_path = os.environ.get("CHECK_HIST_OUT", "/tmp/hist_kernel.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))

    bad = [r for r in results if not r["ok"]]
    if bad:
        print(f"FAIL: histogram engines disagree: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
