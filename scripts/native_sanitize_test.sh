#!/bin/sh
# Build and run the native test harness under each sanitizer — the
# reference's CMake USE_SANITIZER story (SURVEY.md §4-5): TSan is the
# race detector for the lock-free queue/spinlock, ASan+LSan catch
# leaks/overflows in the recordio/parse buffers, UBSan the arithmetic.
#
# Usage: scripts/native_sanitize_test.sh [address|thread|undefined ...]
set -e
cd "$(dirname "$0")/.."
SANS="${*:-address thread undefined}"
SRCS="cpp/test_native.cc cpp/mpmc_queue.cc cpp/recordio.cc cpp/fastparse.cc cpp/prefetch.cc"
for san in $SANS; do
  out="build/native_test_$san"
  mkdir -p build
  echo "== $san =="
  g++ -std=c++17 -O1 -g -fno-omit-frame-pointer -fsanitize="$san" \
      $SRCS -o "$out" -lpthread
  "./$out"
done
echo "ALL SANITIZER RUNS PASSED"
