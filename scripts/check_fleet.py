#!/usr/bin/env python
"""Fleet-serving chaos drill for CI: kill, reroute, rescale, rollout.

Stands up the full fleet topology — FleetTracker + 3 subprocess
replicas (v1 checkpoint) + in-process consistent-hash router — then
drives it through the incidents the fleet tier exists to absorb, with
closed-loop verified load running THROUGH every incident:

1. **Kill** — SIGKILL one replica mid-traffic.  The router must fail
   predicts over to surviving replicas (zero dropped, zero wrong), the
   tracker must record the death, and the victim must leave the
   routable set.
2. **Rescale** — the local autoscale backend spawns a replacement
   replica; it registers and joins the routable set.
3. **Rollout** — staged v1→v2 deploy (wave size 1) under load: every
   response bit-matches the version it claims, no request is dropped,
   each replica's observed version sequence is monotone, and the fleet
   converges on v2.

The JSON report (counts, latencies, per-phase verdicts) is archived to
``FLEET_OUT`` (default ``/tmp/fleet_drill.json``) for CI artifacts.
Parent runs under ``DMLC_LOCKCHECK=1`` + ``DMLC_RACECHECK=1`` and
verifies zero lock-order cycles AND zero happens-before races across
the whole drill; the racecheck report is archived to
``FLEET_RACECHECK_OUT`` (default ``/tmp/fleet_racecheck.json``), and
``DMLC_LEAKCHECK=1`` gates GREEN on zero live resource leaks at exit
(``FLEET_LEAKCHECK_OUT``, default ``/tmp/fleet_leakcheck.json``), and
``DMLC_JITCHECK=1`` gates GREEN on zero steady-state XLA compiles after
the routed warmup predict (``FLEET_JITCHECK_OUT``, default
``/tmp/fleet_jitcheck.json``).
Exit 0 = drill green.  Usage:
    python scripts/check_fleet.py
"""

import json
import os
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_REPLICAS = 3
N_ROWS, N_FEAT = 400, 8
LOAD_S = 6.0


def _check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def _wait(pred, timeout_s, label):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    _check(False, f"timed out waiting for {label}")


def main() -> None:
    os.environ.setdefault("DMLC_LOCKCHECK", "1")
    os.environ.setdefault("DMLC_RACECHECK", "1")
    os.environ.setdefault("DMLC_LEAKCHECK", "1")
    os.environ.setdefault("DMLC_JITCHECK", "1")
    # observability plane: every process (parent router, replicas,
    # loadgen workers) spools metrics + trace shards into one directory
    os.environ.setdefault("DMLC_TRACE", "1")
    spool = os.environ.get("DMLC_METRICS_SPOOL") \
        or tempfile.mkdtemp(prefix="dmlc_fleet_spool")
    os.environ["DMLC_METRICS_SPOOL"] = spool
    t_drill0 = time.time()
    from dmlc_core_tpu.utils import force_cpu_devices

    force_cpu_devices(1)

    import numpy as np

    from dmlc_core_tpu.base import (jitcheck, leakcheck, lockcheck,
                                    metrics_agg, racecheck, slo)
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.serve import checkpoint_model
    from dmlc_core_tpu.serve.fleet import (FleetRouter, FleetTracker,
                                           HttpFleetAdmin,
                                           LocalProcessScaler, Rollout,
                                           run_loadgen, spawn_replica)
    from dmlc_core_tpu.serve.client import ResilientClient

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_collect

    spool_writer = metrics_agg.install_spool("drill", 0)
    out_path = os.environ.get("FLEET_OUT", "/tmp/fleet_drill.json")
    report = {"phases": {}}
    tmp = tempfile.mkdtemp(prefix="dmlc_fleet")

    rng = np.random.default_rng(42)
    X = rng.normal(size=(N_ROWS, N_FEAT)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    m1 = HistGBT(n_trees=4, max_depth=3, n_bins=16).fit(X, y)
    m2 = HistGBT(n_trees=8, max_depth=3, n_bins=16).fit(X, y)
    v1_uri = f"file://{tmp}/v1.ckpt"
    v2_uri = f"file://{tmp}/v2.ckpt"
    checkpoint_model(v1_uri, m1, version=1)
    checkpoint_model(v2_uri, m2, version=2)
    expected_npz = os.path.join(tmp, "expected.npz")
    np.savez(expected_npz, X=X, v1=m1.predict(X), v2=m2.predict(X))

    child_env = {"JAX_PLATFORMS": "cpu", "DMLC_TPU_FORCE_CPU": "1",
                 "DMLC_LOCKCHECK": "1", "DMLC_RACECHECK": "1",
                 "DMLC_TRACE": "1", "DMLC_METRICS_SPOOL": spool}
    tracker = FleetTracker(nworker=8)
    tracker.start()
    procs = [spawn_replica("127.0.0.1", tracker.port, model_uri=v1_uri,
                           max_batch=32, extra_env=child_env)
             for _ in range(N_REPLICAS)]
    scaler = LocalProcessScaler(tracker, v1_uri, spawn_env=child_env)
    router = None
    try:
        _wait(lambda: len(tracker.serve_endpoints()) == N_REPLICAS,
              180, "replica registration")
        _check(True, f"{N_REPLICAS} replicas registered with the tracker")
        router = FleetRouter(tracker, probe_s=0.2).start()

        client = ResilientClient(router.url)
        preds, ver = client.predict(X[:8])
        _check(ver == 1 and np.array_equal(preds, m1.predict(X)[:8]),
               "routed predict bit-identical to direct v1 predict")
        # the parent's jax work (oracle fits + predicts above) ends here;
        # everything that follows is HTTP/subprocess/loadgen — any further
        # XLA compile in this process is a steady-state stall and fails
        # jitcheck.check() below
        jitcheck.steady()

        def _loadgen_bg(result, duration):
            result.update(run_loadgen(
                router.url, expected_npz, duration_s=duration, procs=2,
                threads=3, base_qps=60.0, timeout_ms=10_000,
                workdir=tmp, env=child_env))

        # -- phase 1: SIGKILL one replica mid-traffic --------------------
        load1 = {}
        t1 = threading.Thread(target=_loadgen_bg, args=(load1, LOAD_S))
        t1.start()
        time.sleep(LOAD_S / 3.0)
        victim = procs[1]
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=30)
        _check(victim.returncode == -signal.SIGKILL,
               f"victim replica SIGKILLed (rc={victim.returncode})")
        _wait(lambda: len(tracker.serve_endpoints()) == N_REPLICAS - 1,
              60, "tracker dropping the dead endpoint")
        _check(tracker.dead_workers,
               f"tracker recorded the death (ranks {tracker.dead_workers})")
        t1.join(timeout=LOAD_S + 180)
        _check(not t1.is_alive(), "kill-phase load generator finished")
        _check(load1.get("dropped") == 0 and load1.get("wrong") == 0,
               f"kill under load: zero dropped / zero wrong "
               f"({load1.get('ok')} ok of {load1.get('count')})")
        router.probe_now()
        docs = router.replica_docs()
        healthy = sorted(r for r, d in docs.items() if d["healthy"])
        _check(len(healthy) == N_REPLICAS - 1,
               f"router routable set shrank to survivors {healthy}")
        report["phases"]["kill"] = {"load": load1,
                                    "dead": list(tracker.dead_workers)}

        # -- phase 2: autoscale backend spawns a replacement -------------
        scaler.scale(1)
        _wait(lambda: len(tracker.serve_endpoints()) == N_REPLICAS,
              180, "scaled-out replica registration")
        router.probe_now()
        healthy = sorted(r for r, d in router.replica_docs().items()
                         if d["healthy"])
        _check(len(healthy) == N_REPLICAS,
               f"autoscale spawn path restored the fleet {healthy}")
        report["phases"]["rescale"] = {"healthy": healthy}

        # -- phase 3: staged rollout v1 -> v2 under load ------------------
        endpoints = dict(tracker.serve_endpoints())
        versions_seen = {r: [] for r in endpoints}
        stop_watch = threading.Event()

        def _watch():
            cs = {r: ResilientClient(u) for r, u in endpoints.items()}
            while not stop_watch.is_set():
                for r, c in cs.items():
                    try:
                        v = c.healthz().get("version")
                        if v is not None:
                            versions_seen[r].append(int(v))
                    except Exception:  # noqa: BLE001 — probe best-effort
                        pass
                time.sleep(0.05)

        watcher = threading.Thread(target=_watch, daemon=True)
        watcher.start()
        load2 = {}
        t2 = threading.Thread(target=_loadgen_bg, args=(load2, LOAD_S))
        t2.start()
        time.sleep(LOAD_S / 4.0)
        rollout = Rollout(HttpFleetAdmin(endpoints), wave_size=1,
                          settle_s=0.3).run(v2_uri)
        _check(rollout["outcome"] == "activated",
               f"staged rollout activated v{rollout['version']} in "
               f"{len(rollout['waves'])} waves of 1")
        t2.join(timeout=LOAD_S + 180)
        _check(not t2.is_alive(), "rollout-phase load generator finished")
        stop_watch.set()
        watcher.join(timeout=30)
        _check(load2.get("dropped") == 0 and load2.get("wrong") == 0,
               f"rollout under load: zero dropped / zero wrong "
               f"({load2.get('ok')} ok of {load2.get('count')})")
        _check("2" in load2.get("by_version", {}),
               f"v2 served live traffic ({load2.get('by_version')})")
        for r, seq in versions_seen.items():
            _check(seq == sorted(seq),
                   f"replica {r} version sequence monotone "
                   f"({seq[0] if seq else '?'}→{seq[-1] if seq else '?'})")
        final = {r: ResilientClient(u).healthz().get("version")
                 for r, u in endpoints.items()}
        _check(all(v == rollout["version"] for v in final.values()),
               f"fleet converged on v{rollout['version']} ({final})")
        report["phases"]["rollout"] = {"load": load2, "rollout": rollout,
                                       "final_versions": final}
    finally:
        if router is not None:
            router.close()
        scaler.reap(timeout=15)
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=15)
                except Exception:  # noqa: BLE001
                    p.kill()
        tracker.stop()

    # -- observability plane: merge spools, stitch the trace -------------
    if spool_writer is not None:
        spool_writer.close()    # final parent snapshot + trace shard
    drill_wall_s = time.time() - t_drill0
    merged, nprocs = metrics_agg.merge_spool(spool)
    metrics_out = os.environ.get("FLEET_METRICS_OUT",
                                 "/tmp/fleet_metrics.json")
    metrics_agg.write_snapshot(metrics_out, merged)
    _check(nprocs >= 3,
           f"metrics spool merged {nprocs} processes "
           f"(artifact at {metrics_out})")
    # merged request counters must equal the per-process sum EXACTLY
    shard_sum = 0.0
    for fname in merged["spool_files"]:
        with open(os.path.join(spool, fname)) as f:
            snap = json.load(f)
        m = (snap.get("metrics") or {}).get("dmlc_serve_requests_total")
        shard_sum += sum(s["value"] for s in (m or {}).get("series", ()))
    merged_m = merged["metrics"].get("dmlc_serve_requests_total", {})
    merged_sum = sum(s["value"] for s in merged_m.get("series", ()))
    _check(merged_sum == shard_sum and merged_sum > 0,
           f"merged dmlc_serve_requests_total == per-process sum "
           f"({merged_sum:.0f})")

    t_tc0 = time.time()
    trace_out = os.environ.get("FLEET_TRACE_OUT", "/tmp/fleet_trace.json")
    _, tsummary = trace_collect.collect(spool, trace_out)
    trace_collect_s = time.time() - t_tc0
    cross = {tid: t for tid, t in tsummary["traces"].items()
             if len(t["pids"]) >= 3 and "fleet.route" in t["spans"]
             and "batcher.submit" in t["spans"]
             and any(s.startswith("http./predict") for s in t["spans"])}
    _check(cross,
           f"{len(cross)} request trace(s) crossed router -> replica -> "
           f"batcher spans over >= 3 processes (merged Perfetto trace "
           f"at {trace_out})")
    report["observability"] = {
        "spool_processes_merged": nprocs,
        "traces": len(tsummary["traces"]),
        "cross_process_traces": len(cross),
        "trace_collect_s": round(trace_collect_s, 3),
        "drill_wall_s": round(drill_wall_s, 3),
    }

    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"   report archived to {out_path}")
    lockcheck.check()
    print("ok: zero lock-order cycles under DMLC_LOCKCHECK=1 (parent)")
    rc_out = os.environ.get("FLEET_RACECHECK_OUT",
                            "/tmp/fleet_racecheck.json")
    rc_report = racecheck.write_report(rc_out)
    racecheck.check()
    print(f"ok: zero happens-before races under DMLC_RACECHECK=1 "
          f"(parent; report at {rc_out})")
    lk_out = os.environ.get("FLEET_LEAKCHECK_OUT",
                            "/tmp/fleet_leakcheck.json")
    lk_report = leakcheck.write_report(lk_out)
    leakcheck.check()
    print(f"ok: zero live resource leaks under DMLC_LEAKCHECK=1 "
          f"(parent; report at {lk_out})")
    jc_out = os.environ.get("FLEET_JITCHECK_OUT",
                            "/tmp/fleet_jitcheck.json")
    jc_report = jitcheck.write_report(jc_out)
    jitcheck.check()
    print(f"ok: zero steady-state XLA compiles under DMLC_JITCHECK=1 "
          f"(parent; report at {jc_out})")

    # -- SLO scorecard gate ----------------------------------------------
    spec_path = os.environ.get("FLEET_SLO_SPEC") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "slo", "fleet.json")
    evidence = {
        "loadgen": report["phases"]["rollout"]["load"],
        "racecheck": {"races": len(rc_report["races"])},
        "leakcheck": {"leaks": len(lk_report["leaks"])},
        "jitcheck": {"recompiles_steady": jc_report["compiles_steady"]},
    }
    scorecard = slo.evaluate(slo.SLOSpec.load(spec_path), merged, evidence)
    slo_out = os.environ.get("FLEET_SLO_OUT", "/tmp/fleet_slo.json")
    with open(slo_out, "w") as f:
        json.dump(scorecard, f, indent=2)
    for row in scorecard["objectives"]:
        print(f"   slo[{row['name']}]: "
              f"{'pass' if row['pass'] else 'FAIL'} "
              f"(observed {row['observed']} {row['op']} "
              f"{row['threshold']}; {row['evidence']})")
    _check(scorecard["pass"],
           f"SLO scorecard {scorecard['spec']} green "
           f"(spec {spec_path}, scorecard at {slo_out})")
    print("FLEET CHAOS DRILL GREEN")


if __name__ == "__main__":
    main()
