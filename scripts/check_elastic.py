#!/usr/bin/env python
"""Elastic-recovery chaos drill for CI: SIGKILL a worker mid-boost.

Three phases over n=4 local worker PROCESSES coordinated by an
ElasticTracker (tracker-hub host collectives — the rabit wire role —
because multiprocess XLA collectives don't exist on the CPU backend):

1. **Baseline** — an uninterrupted 4-worker data-parallel ``fit_external``
   run over row shards; all four ensembles must agree byte-for-byte.
2. **Rejoin** — same job, but one worker is SIGKILLed mid-round by the
   deterministic ``allreduce:kill`` fault.  Survivors abort the in-flight
   round and roll back to the recovery floor; the parent relaunches the
   dead rank, which catches up from the floor checkpoint; the finished
   ensembles must be byte-identical to the baseline (bounded loss = ZERO
   loss: the deterministic fold makes the replay byte-stable).
3. **Evict** — elastic mode, short grace: the victim dies at a commit
   boundary (``worker:kill``) and is NOT replaced.  Once its grace
   lapses the tracker re-forms the epoch over the 3 survivors,
   ``shard_row_ranges`` re-cuts the rows, and the job converges with
   eval loss within 1% of the baseline.

Every process (parent + workers) runs under ``DMLC_LOCKCHECK=1`` +
``DMLC_RACECHECK=1`` and verifies zero lock-order cycles; the parent
additionally asserts zero happens-before races and archives the
racecheck report to ``ELASTIC_RACECHECK_OUT`` (default
``/tmp/elastic_racecheck.json``).  ``DMLC_LEAKCHECK=1`` additionally
gates GREEN on zero live resource leaks at exit, archived to
``ELASTIC_LEAKCHECK_OUT`` (default ``/tmp/elastic_leakcheck.json``).
Recovery metrics
(``dmlc_worker_deaths_total{outcome}``, ``dmlc_elastic_reshards_total``,
``dmlc_recovery_floor_round``) are asserted on the tracker registry.

Exit 0 = all phases green.  Usage:
    python scripts/check_elastic.py            # run the drill
    python scripts/check_elastic.py --worker   # (internal worker entry)
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_WORKERS = 4
TOTAL_ROUNDS = 12
STRIDE = 3
N_ROWS, N_FEAT = 2000, 8


def _model_kw():
    return dict(n_trees=TOTAL_ROUNDS, max_depth=3, n_bins=16,
                learning_rate=0.3)


def _dataset():
    import numpy as np

    rng = np.random.default_rng(42)
    X = rng.normal(size=(N_ROWS, N_FEAT)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] - 0.5 * X[:, 3] > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# worker entry (subprocess)
# ---------------------------------------------------------------------------

def worker_main() -> None:
    from dmlc_core_tpu.utils import force_cpu_devices

    force_cpu_devices(1)

    from dmlc_core_tpu.base import lockcheck
    from dmlc_core_tpu.data.iter import ArrayRowIter
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.parallel.recovery import (ElasticSession,
                                                 ElasticTrainer)

    port = int(os.environ["ELASTIC_TRACKER_PORT"])
    out_dir = os.environ["ELASTIC_OUT"]
    rank = int(os.environ.get("ELASTIC_RANK", "-1"))
    from dmlc_core_tpu.base import metrics_agg
    metrics_agg.install_spool("elastic_worker", max(rank, 0))
    X, y = _dataset()

    sess = ElasticSession("127.0.0.1", port, rank=rank)
    model = HistGBT(**_model_kw())
    trainer = ElasticTrainer(model, TOTAL_ROUNDS)  # stride/dir via knobs
    trainer.run(sess,
                lambda lo, hi: ArrayRowIter(X[lo:hi], y[lo:hi]),
                N_ROWS, join_timeout_s=300)
    model.save_model(os.path.join(out_dir, f"model-rank{sess.grank}.gbt"))
    with open(os.path.join(out_dir, f"stats-rank{sess.grank}.json"),
              "w") as f:
        json.dump({"rounds_replayed": trainer.rounds_replayed,
                   "resumed_from": trainer.resumed_from}, f)
    sess.shutdown()
    lockcheck.check()   # zero lock-order cycles, or die loudly


# ---------------------------------------------------------------------------
# parent: supervise phases
# ---------------------------------------------------------------------------

def _check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def _launch(port, out_dir, rec_dir, rank=-1, fault=""):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               DMLC_TPU_FORCE_CPU="1",
               DMLC_LOCKCHECK="1",
               DMLC_RACECHECK="1",
               DMLC_RECOVERY_DIR=rec_dir,
               DMLC_RECOVERY_STRIDE=str(STRIDE),
               DMLC_FAULT_INJECT=fault,
               ELASTIC_TRACKER_PORT=str(port),
               ELASTIC_OUT=out_dir,
               ELASTIC_RANK=str(rank))
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"], env=env)


def _wait(procs, timeout_s, label):
    deadline = time.time() + timeout_s
    for p in procs:
        left = max(1.0, deadline - time.time())
        try:
            p.wait(timeout=left)
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            _check(False, f"{label}: worker pid {p.pid} hung")


def _read_models(out_dir):
    out = {}
    for name in sorted(os.listdir(out_dir)):
        if name.startswith("model-rank") and name.endswith(".gbt"):
            with open(os.path.join(out_dir, name), "rb") as f:
                out[name] = f.read()
    return out


def _loss_of(blob):
    import jax.numpy as jnp

    from dmlc_core_tpu.io.stream import Stream
    from dmlc_core_tpu.models import HistGBT

    uri = f"mem://elastic/{time.time_ns()}"
    with Stream.create(uri, "w") as s:
        s.write(blob)
    m = HistGBT.load_model(uri)
    X, y = _dataset()
    margins = m.predict(X, output_margin=True)
    return float(m._obj.metric(jnp.asarray(margins), jnp.asarray(y)))


def _metric_total(counter, **labels):
    return sum(s["value"] for s in counter._snap()
               if all(s["labels"].get(k) == v for k, v in labels.items()))


def main() -> None:
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        worker_main()
        return

    os.environ.setdefault("DMLC_LOCKCHECK", "1")
    os.environ.setdefault("DMLC_RACECHECK", "1")
    os.environ.setdefault("DMLC_LEAKCHECK", "1")
    # observability plane: parent + worker subprocesses spool metrics
    # snapshots into one directory (children inherit the env)
    spool = os.environ.get("DMLC_METRICS_SPOOL") \
        or tempfile.mkdtemp(prefix="dmlc_elastic_spool")
    os.environ["DMLC_METRICS_SPOOL"] = spool
    from dmlc_core_tpu.utils import force_cpu_devices

    force_cpu_devices(1)

    from dmlc_core_tpu.base import (leakcheck, lockcheck, metrics_agg,
                                    racecheck)
    from dmlc_core_tpu.base.metrics import default_registry
    from dmlc_core_tpu.parallel.recovery import ElasticTracker

    spool_writer = metrics_agg.install_spool("drill", 0)
    reg = default_registry()
    deaths = reg.counter("worker_deaths_total", labels=("outcome",))
    reshards = reg.counter("elastic_reshards_total")
    tmp = tempfile.mkdtemp(prefix="dmlc_elastic")

    # -- phase 1: uninterrupted baseline --------------------------------
    out1, rec1 = os.path.join(tmp, "out1"), os.path.join(tmp, "rec1")
    os.makedirs(out1)
    tracker = ElasticTracker(nworker=N_WORKERS, grace_s=120.0)
    tracker.start()
    procs = [_launch(tracker.port, out1, rec1) for _ in range(N_WORKERS)]
    _wait(procs, 600, "baseline")
    tracker.stop()
    _check(all(p.returncode == 0 for p in procs),
           f"baseline: all {N_WORKERS} workers exited clean "
           f"({[p.returncode for p in procs]})")
    models = _read_models(out1)
    _check(len(models) == N_WORKERS, f"baseline: {N_WORKERS} ensembles")
    blobs = list(models.values())
    _check(all(b == blobs[0] for b in blobs),
           "baseline: ensembles byte-identical across workers")
    baseline = blobs[0]
    base_loss = _loss_of(baseline)
    print(f"   baseline eval loss {base_loss:.5f}")

    # -- phase 2: SIGKILL mid-round, rejoin, byte parity ----------------
    out2, rec2 = os.path.join(tmp, "out2"), os.path.join(tmp, "rec2")
    os.makedirs(out2)
    tracker = ElasticTracker(nworker=N_WORKERS, grace_s=120.0)
    tracker.start()
    procs = [_launch(tracker.port, out2, rec2,
                     fault="allreduce:kill:after=37" if i == 1 else "")
             for i in range(N_WORKERS)]
    victim = procs[1]
    try:
        victim.wait(timeout=300)
    except subprocess.TimeoutExpired:
        _check(False, "rejoin: victim was never killed")
    _check(victim.returncode == -signal.SIGKILL,
           f"rejoin: victim SIGKILLed mid-round (rc={victim.returncode})")
    deadline = time.time() + 60
    while time.time() < deadline and not tracker.lost_ranks():
        time.sleep(0.05)
    lost = tracker.lost_ranks()
    _check(len(lost) == 1, f"rejoin: tracker holds rank {lost} in grace")
    replacement = _launch(tracker.port, out2, rec2, rank=lost[0])
    _wait([p for p in procs if p is not victim] + [replacement],
          600, "rejoin")
    tracker.stop()
    rcs = [p.returncode for p in procs if p is not victim] + [
        replacement.returncode]
    _check(all(rc == 0 for rc in rcs),
           f"rejoin: survivors + rejoiner exited clean ({rcs})")
    models = _read_models(out2)
    _check(len(models) == N_WORKERS,
           f"rejoin: all {N_WORKERS} ranks finished")
    _check(all(b == baseline for b in models.values()),
           "rejoin: recovered ensembles byte-identical to baseline")
    _check(tracker.recovery_floor() == TOTAL_ROUNDS,
           f"rejoin: recovery floor reached {TOTAL_ROUNDS}")
    _check(_metric_total(deaths, outcome="rejoined") >= 1,
           "rejoin: dmlc_worker_deaths_total{outcome=rejoined} counted")

    # -- phase 3: SIGKILL at a commit, evict + elastic re-shard ----------
    out3, rec3 = os.path.join(tmp, "out3"), os.path.join(tmp, "rec3")
    os.makedirs(out3)
    tracker = ElasticTracker(nworker=N_WORKERS, grace_s=1.5, elastic=True)
    tracker.start()
    procs = [_launch(tracker.port, out3, rec3,
                     fault="worker:kill:after=2" if i == 2 else "")
             for i in range(N_WORKERS)]
    victim = procs[2]
    victim.wait(timeout=300)
    _check(victim.returncode == -signal.SIGKILL,
           f"evict: victim SIGKILLed at a commit (rc={victim.returncode})")
    _wait([p for p in procs if p is not victim], 600, "evict")
    tracker.stop()
    _check(all(p.returncode == 0 for p in procs if p is not victim),
           "evict: survivors exited clean")
    models = _read_models(out3)
    _check(len(models) == N_WORKERS - 1,
           f"evict: {N_WORKERS - 1} survivor ensembles")
    blobs = list(models.values())
    _check(all(b == blobs[0] for b in blobs),
           "evict: survivors agree byte-for-byte after the re-shard")
    evict_loss = _loss_of(blobs[0])
    rel = abs(evict_loss - base_loss) / max(base_loss, 1e-9)
    _check(rel < 0.01,
           f"evict: loss {evict_loss:.5f} within 1% of baseline "
           f"{base_loss:.5f} (rel {rel:.4f})")
    _check(_metric_total(reshards) >= 1,
           "evict: dmlc_elastic_reshards_total counted")
    _check(_metric_total(deaths, outcome="evicted") >= 1,
           "evict: dmlc_worker_deaths_total{outcome=evicted} counted")

    if spool_writer is not None:
        spool_writer.close()
    merged, nprocs = metrics_agg.merge_spool(spool)
    metrics_out = os.environ.get("ELASTIC_METRICS_OUT",
                                 "/tmp/elastic_metrics.json")
    metrics_agg.write_snapshot(metrics_out, merged)
    _check(nprocs >= 1, f"metrics spool merged {nprocs} processes "
                        f"(artifact at {metrics_out})")

    lockcheck.check()
    print("ok: zero lock-order cycles under DMLC_LOCKCHECK=1 (parent)")
    rc_out = os.environ.get("ELASTIC_RACECHECK_OUT",
                            "/tmp/elastic_racecheck.json")
    racecheck.write_report(rc_out)
    racecheck.check()
    print(f"ok: zero happens-before races under DMLC_RACECHECK=1 "
          f"(parent; report at {rc_out})")
    lk_out = os.environ.get("ELASTIC_LEAKCHECK_OUT",
                            "/tmp/elastic_leakcheck.json")
    leakcheck.write_report(lk_out)
    leakcheck.check()
    print(f"ok: zero live resource leaks under DMLC_LEAKCHECK=1 "
          f"(parent; report at {lk_out})")
    print("ELASTIC CHAOS DRILL GREEN")


if __name__ == "__main__":
    main()
