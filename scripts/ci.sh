#!/usr/bin/env bash
# Single-entry CI: reproduces the full green state from a fresh checkout.
# (The reference ships lint.py + travis/github-actions scripts — SURVEY.md
# §2d; this is that layer for an image with no external lint tools.)
#
#   scripts/ci.sh            # lint + native build + full pytest + sanitizers
#   scripts/ci.sh quick      # lint + pytest only (no native rebuild/sanitizers)
#
# Sanitizer stage: builds the native test binary under ASan/UBSan/TSan and
# runs the queue/parse/recordio stress suite under each (the reference's
# CMake USE_SANITIZER story, SURVEY.md §5 race detection).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== hygiene =="
# setuptools bdist leftovers duplicate the package on disk (build/lib is
# a full copy of dmlc_core_tpu) — they double naive LoC counts and can
# shadow the real package in tooling; keep only the native outputs
rm -rf build/lib build/bdist.* ./*.egg-info

echo "== lint =="
python scripts/lint.py

echo "== api docs =="
# regenerate doc/api/ and FAIL on undocumented __all__ exports
# (SURVEY.md §2d's generated-API-reference role); then fail if the
# committed pages are stale vs the source
python scripts/gen_api_docs.py
git diff --exit-code -- doc/api \
    || { echo "doc/api is stale: commit the regenerated pages"; exit 1; }

if [[ "${1:-}" != "quick" ]]; then
    echo "== native build =="
    make -C cpp -j"$(nproc)"
fi

echo "== pytest =="
python -m pytest tests/ -q -x

if [[ "${1:-}" != "quick" ]]; then
    echo "== native sanitizers =="
    scripts/native_sanitize_test.sh
fi

echo "CI GREEN"
