#!/usr/bin/env bash
# Single-entry CI: reproduces the full green state from a fresh checkout.
# (The reference ships lint.py + travis/github-actions scripts — SURVEY.md
# §2d; this is that layer for an image with no external lint tools.)
#
#   scripts/ci.sh            # lint + native build + full pytest + sanitizers
#   scripts/ci.sh quick      # lint + pytest only (no native rebuild/sanitizers)
#
# Sanitizer stage: builds the native test binary under ASan/UBSan/TSan and
# runs the queue/parse/recordio stress suite under each (the reference's
# CMake USE_SANITIZER story, SURVEY.md §5 race detection).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== hygiene =="
# setuptools bdist leftovers duplicate the package on disk (build/lib is
# a full copy of dmlc_core_tpu) — they double naive LoC counts and can
# shadow the real package in tooling; keep only the native outputs
rm -rf build/lib build/bdist.* ./*.egg-info

echo "== dmlcheck =="
# project-aware static analysis (lock discipline, jit purity, the jax
# trio — recompile-hazard / donation-discipline / transfer-discipline —
# knob / metric registries, resource/thread lifecycles, collective
# discipline, wire schemas, style) over one AST parse per file; runs
# in BOTH lanes (quick included), budgeted <= 10s over the whole repo
# (the incremental cache at scripts/.dmlcheck_cache keeps warm re-runs
# under 2s), and the JSON report is archived like bench metrics.
# doc/static_analysis.md documents passes, suppressions and the
# baseline workflow.
DMLCHECK_OUT="${DMLCHECK_OUT:-/tmp/dmlcheck.json}"
t0=$SECONDS
python scripts/dmlcheck.py --json "$DMLCHECK_OUT"
if (( SECONDS - t0 > 10 )); then
    echo "dmlcheck blew its 10s budget ($((SECONDS - t0))s)"
    exit 1
fi

echo "== interleave model check (schedule exploration) =="
# cooperative-scheduler model checker (analysis/interleave): proves the
# four serving-stack concurrency invariants — circuit-breaker single
# probe, rollout state machine, batcher flush/drain, registry hot-swap
# — over DMLC_INTERLEAVE_SCHEDULES (default 200) DISTINCT schedules
# each, mixing bounded-exhaustive DFS with seeded random walks.  Runs
# in BOTH lanes (quick included) — pure CPU, seconds, no devices.
env JAX_PLATFORMS=cpu DMLC_TPU_FORCE_CPU=1 \
    python -m dmlc_core_tpu.analysis.interleave

echo "== histogram kernel drill (cross-method + fused-round parity, ns/row archive) =="
# every histogram engine (segment / matmul / pallas-interpret) must be
# BIT-identical — including through the int4-packed compact-remap layout
# and through a feature bundle's tot-minus-segments reconstruction — on
# odd row counts with masked rows; the fused-round cases additionally
# prove the single-program descend+accumulate+sibling-subtract kernel
# bit-identical to the staged reference through the same layouts
# (doc/performance.md "Fused round kernel"); the timed half archives
# per-method ns/row JSON so kernel regressions land in the artifact
# chain (doc/performance.md "Packed narrow bins").
env JAX_PLATFORMS=cpu CHECK_HIST_OUT="${CHECK_HIST_OUT:-/tmp/hist_kernel.json}" \
    python scripts/check_hist_kernel.py

echo "== api docs =="
# regenerate doc/api/ + doc/configuration.md (knob table from
# base/knobs.py) and FAIL on undocumented __all__ exports (SURVEY.md
# §2d's generated-API-reference role); then fail if the committed
# pages are stale vs the source
python scripts/gen_api_docs.py
# modified pages AND brand-new untracked pages both fail the gate
if ! git diff --exit-code -- doc/api doc/configuration.md \
        || [[ -n "$(git status --porcelain -- doc/api doc/configuration.md)" ]]; then
    echo "doc/api or doc/configuration.md is stale: commit the regenerated pages"
    exit 1
fi

echo "== compile cache pre-seed (one warm dir for lanes + bench) =="
# Persistent cache dir shared by BOTH pytest lanes (conftest honors the
# env var), the multichip stage, and any later bench.py on this image:
# scripts/warm_compile_cache.py AOT-compiles the flagship round ladder
# at the bench config's exact shapes into it (ShapeDtypeStructs — no
# data), so bench warmup_seconds collapses from the 23-31 s of
# BENCH_r04/r05 toward the <5 s ROADMAP target and the bench JSON says
# compile_cache: hit.  Idempotent: a warm rerun joins in cache-read time.
# The dir MUST default to the library default (~/.cache/...): a bench
# launched later in a fresh shell carries no env var, so pre-seeding a
# /tmp dir warms a cache nobody reads (the BENCH_r05 31 s warmup bug).
export DMLC_COMPILE_CACHE_DIR="${DMLC_COMPILE_CACHE_DIR:-$HOME/.cache/dmlc_core_tpu/xla_compile_cache}"
mkdir -p "$DMLC_COMPILE_CACHE_DIR"
python scripts/warm_compile_cache.py

echo "== multichip dryrun (sharded-ingest parity + scaling report) =="
# 8-device CPU mesh: 1-chip-oracle ensemble byte parity (deterministic
# histogram reduction), sharded-ingest == global-staging bit identity,
# and out-of-core streamed-slab bit identity; the JSON scaling report
# is archived next to the MULTICHIP_r0*.json evidence chain.
env JAX_PLATFORMS=cpu python scripts/check_multichip.py \
    --out "${MULTICHIP_OUT:-/tmp/multichip_scaling.json}"

echo "== compile cache (cold -> warm wiring) =="
# two PROCESSES against one temp cache dir: the first must compile and
# write (miss), the second must deserialize from disk (hit).  Guards
# the persistent-cache wiring (config names, cache-key scheme, jax
# monitoring event names) against jax-version drift — the cold-start
# contract of doc/performance.md.
CC_DIR="$(mktemp -d)"
trap 'rm -rf "$CC_DIR"' EXIT
env JAX_PLATFORMS=cpu DMLC_COMPILE_CACHE_DIR="$CC_DIR" \
    DMLC_COMPILE_CACHE_EXPECT=miss python scripts/check_compile_cache.py
env JAX_PLATFORMS=cpu DMLC_COMPILE_CACHE_DIR="$CC_DIR" \
    DMLC_COMPILE_CACHE_EXPECT=hit python scripts/check_compile_cache.py

echo "== stream smoke (append -> tail -> boost -> publish -> serve) =="
# the continuous train->serve loop end to end (doc/streaming.md): a
# bounded synthetic event stream must yield >= 2 published model
# versions and a final registry that answers HTTP /predict — the
# examples/stream_gbt.py --smoke assertions
env JAX_PLATFORMS=cpu DMLC_TPU_FORCE_CPU=2 python examples/stream_gbt.py --smoke

echo "== resilience smoke (kill-and-recover + lossy wire) =="
# deterministic fault-injection drills: SIGKILL a checkpoint writer
# mid-write and prove the previous version survives bit-identically,
# then push an S3 round-trip through injected 503s/truncations and
# prove byte identity + retry/fault evidence on the metrics registry
# (the doc/robustness.md contract).  The drill also merges its metrics
# spool (parent + checkpoint-writer children) into one archived fleet
# snapshot.
env JAX_PLATFORMS=cpu \
    RESILIENCE_METRICS_OUT="${RESILIENCE_METRICS_OUT:-/tmp/resilience_metrics.json}" \
    python scripts/check_resilience.py

echo "== elastic recovery chaos drill (die / rejoin / catch-up + evict) =="
# n=4 local worker processes co-training over tracker-hub collectives;
# k=1 is SIGKILLed mid-boost by the deterministic fault injector.  The
# rejoin path must reproduce the uninterrupted run's save_model bytes
# exactly (recovery floor + deterministic fold); the elastic-evict path
# re-shards onto the survivors and must converge within 1% eval loss.
# Every process runs under DMLC_LOCKCHECK=1 + DMLC_RACECHECK=1 with
# zero order cycles and zero happens-before races, and DMLC_LEAKCHECK=1
# gates GREEN on zero live resource leaks at exit; the racecheck and
# leakcheck JSON are archived like the drill report (doc/robustness.md
# "Distributed recovery").
# The merged cross-process metrics snapshot is archived next to them.
env JAX_PLATFORMS=cpu \
    ELASTIC_RACECHECK_OUT="${ELASTIC_RACECHECK_OUT:-/tmp/elastic_racecheck.json}" \
    ELASTIC_LEAKCHECK_OUT="${ELASTIC_LEAKCHECK_OUT:-/tmp/elastic_leakcheck.json}" \
    ELASTIC_METRICS_OUT="${ELASTIC_METRICS_OUT:-/tmp/elastic_metrics.json}" \
    python scripts/check_elastic.py

echo "== fleet serving chaos drill (kill / reroute / rescale / rollout) =="
# 3 subprocess replicas behind the consistent-hash router with verified
# closed-loop load running through every incident: SIGKILL one replica
# (router fails over, tracker records the death, zero dropped / zero
# wrong), the local autoscale backend respawns it, then a staged v1->v2
# rollout under load must keep per-replica versions monotone and land
# the whole fleet on v2 — still zero dropped / zero wrong.  The JSON
# report is archived; parent runs under DMLC_LOCKCHECK=1 +
# DMLC_RACECHECK=1 + DMLC_LEAKCHECK=1 + DMLC_JITCHECK=1 with zero order
# cycles, zero happens-before races, zero live resource leaks and zero
# steady-state XLA compiles at exit; the racecheck, leakcheck and
# jitcheck JSON are archived alongside
# (doc/serving.md "Fleet serving").
# The observability plane rides the same run: every process spools its
# metrics + trace shard, the drill merges them (exact counter sums,
# one request id crossing >= 3 pids) and gates GREEN on the committed
# SLO scorecard (scripts/slo/fleet.json); merged metrics, the Perfetto
# trace and the scorecard are archived next to the race/leak reports.
env JAX_PLATFORMS=cpu \
    FLEET_RACECHECK_OUT="${FLEET_RACECHECK_OUT:-/tmp/fleet_racecheck.json}" \
    FLEET_LEAKCHECK_OUT="${FLEET_LEAKCHECK_OUT:-/tmp/fleet_leakcheck.json}" \
    FLEET_JITCHECK_OUT="${FLEET_JITCHECK_OUT:-/tmp/fleet_jitcheck.json}" \
    FLEET_METRICS_OUT="${FLEET_METRICS_OUT:-/tmp/fleet_metrics.json}" \
    FLEET_TRACE_OUT="${FLEET_TRACE_OUT:-/tmp/fleet_trace.json}" \
    FLEET_SLO_OUT="${FLEET_SLO_OUT:-/tmp/fleet_slo.json}" \
    python scripts/check_fleet.py
# trace-collection cost budget: merging the shards must stay under 5%
# of the drill's wall time, or the plane is taxing the thing it watches
python - "${FLEET_OUT:-/tmp/fleet_drill.json}" <<'EOF'
import json, sys
obs = json.load(open(sys.argv[1]))["observability"]
frac = obs["trace_collect_s"] / max(obs["drill_wall_s"], 1e-9)
print(f"trace collect: {obs['trace_collect_s']:.2f}s "
      f"of {obs['drill_wall_s']:.1f}s drill wall ({frac:.1%})")
sys.exit(1 if frac > 0.05 else 0)
EOF

echo "== parameter-server chaos drill (kill server / respawn / restore) =="
# scheduler + 2 server + 3 worker processes training sparse GBLinear
# over the dist_async KVStore; server 1 is SIGKILLed mid-epoch by the
# deterministic ps_push fault.  Workers fail over through the
# scheduler, the parent respawns the same server id against the same
# DMLC_PS_SNAPSHOT_DIR, and the shard restores from the atomic
# snapshot (vector clock included) — every worker must reconverge
# within tolerance of the uninterrupted baseline and SSP staleness
# must stay within DMLC_PS_STALENESS.  All processes run under
# DMLC_LOCKCHECK=1 + DMLC_RACECHECK=1 with zero order cycles and zero
# happens-before races, plus DMLC_LEAKCHECK=1 zero-leak gating in the
# parent (doc/distributed.md "Parameter server").
# Observability plane: worker ps.push -> server ps.server.push traces
# across pids, merged fleet metrics, and the committed SLO gate
# (scripts/slo/ps.json) — artifacts archived alongside.
env JAX_PLATFORMS=cpu \
    PS_RACECHECK_OUT="${PS_RACECHECK_OUT:-/tmp/ps_racecheck.json}" \
    PS_LEAKCHECK_OUT="${PS_LEAKCHECK_OUT:-/tmp/ps_leakcheck.json}" \
    PS_METRICS_OUT="${PS_METRICS_OUT:-/tmp/ps_metrics.json}" \
    PS_TRACE_OUT="${PS_TRACE_OUT:-/tmp/ps_trace.json}" \
    PS_SLO_OUT="${PS_SLO_OUT:-/tmp/ps_slo.json}" \
    python scripts/check_ps.py
python - "${PS_DRILL_OUT:-/tmp/ps_drill.json}" <<'EOF'
import json, sys
obs = json.load(open(sys.argv[1]))["observability"]
frac = obs["trace_collect_s"] / max(obs["drill_wall_s"], 1e-9)
print(f"trace collect: {obs['trace_collect_s']:.2f}s "
      f"of {obs['drill_wall_s']:.1f}s drill wall ({frac:.1%})")
sys.exit(1 if frac > 0.05 else 0)
EOF

echo "== multi-host launch drill (fake cluster / host death / respawn) =="
# supervised launch over a FakeTransport "cluster" of 3 virtual hosts:
# an ElasticLauncher (tracker + JobSet) runs a 4-rank elastic fit;
# launch_host:kill=h1 downs one host mid-round, the JobSet respawns
# the lost rank on a surviving host, the replacement reclaims its
# tracker rank and replays — result must be byte-identical to an
# uninterrupted baseline.  Stage 2 scales a LauncherScaler-backed
# serving fleet 2 -> 4 replicas across fake hosts with zero dropped
# loadgen requests.  Everything runs under DMLC_LOCKCHECK=1 +
# DMLC_RACECHECK=1 with zero order cycles and zero happens-before
# races, plus DMLC_LEAKCHECK=1 zero-leak gating; racecheck and
# leakcheck JSON archived (doc/distributed.md "Multi-host launch").
# Spool delivery to JobSet children goes through worker_env injection;
# the merged metrics snapshot is archived next to the race/leak reports.
env JAX_PLATFORMS=cpu \
    LAUNCH_RACECHECK_OUT="${LAUNCH_RACECHECK_OUT:-/tmp/launch_racecheck.json}" \
    LAUNCH_LEAKCHECK_OUT="${LAUNCH_LEAKCHECK_OUT:-/tmp/launch_leakcheck.json}" \
    LAUNCH_METRICS_OUT="${LAUNCH_METRICS_OUT:-/tmp/launch_metrics.json}" \
    python scripts/check_launch.py

echo "== multi-tenant serving drill (poisoned publish / surge / paging) =="
# many models, one fleet: 6 Zipf-weighted tenants on 3 tenancy-enabled
# replicas (residency cap 4) behind the tenant-aware router.  A
# mid-traffic poisoned publish for ONE tenant must be rolled back by
# its eval gate with every other tenant untouched; a hot-bronze surge
# against a tight admission envelope must shed bronze (429) before
# gold sees queueing; LRU paging churn must warm-restore bit-identical
# predictions.  Runs under lockcheck+racecheck+leakcheck (reports
# archived) and gates GREEN on the committed per-tenant SLO scorecard
# scripts/slo/tenancy.json (doc/serving.md "Multi-tenant serving").
env JAX_PLATFORMS=cpu \
    TENANCY_OUT="${TENANCY_OUT:-/tmp/tenancy_drill.json}" \
    TENANCY_RACECHECK_OUT="${TENANCY_RACECHECK_OUT:-/tmp/tenancy_racecheck.json}" \
    TENANCY_LEAKCHECK_OUT="${TENANCY_LEAKCHECK_OUT:-/tmp/tenancy_leakcheck.json}" \
    TENANCY_METRICS_OUT="${TENANCY_METRICS_OUT:-/tmp/tenancy_metrics.json}" \
    TENANCY_TRACE_OUT="${TENANCY_TRACE_OUT:-/tmp/tenancy_trace.json}" \
    TENANCY_SLO_OUT="${TENANCY_SLO_OUT:-/tmp/tenancy_slo.json}" \
    python scripts/check_tenancy.py

echo "== production-day simulation (whole-stack chaos, one SLO scorecard) =="
# one composed run: live event stream -> OnlineTrainer with tenant-scoped
# rollout refreshes, sparse-CTR fit_ps on a real PS fleet, and a
# multi-tenant replica fleet on a fake 6-host cluster serving diurnal
# Zipf load — while the deterministic chaos schedule (at=/every=
# wall-clock triggers, DMLC_FAULT_SEED) faults EVERY tier mid-run:
# replica SIGKILL, PS server SIGKILL (respawn + snapshot restore), a
# spot-preemption wave downing 30% of hosts at once, corrupt stream
# shard bytes (tailer resync), and a poisoned tenant publish (eval gate
# rollback, tenant-scoped).  GREEN gates on >= 99% availability with
# zero dropped / zero wrong, cause-fair respawn budgets, zero
# lock/race/leak findings, zero steady-state XLA compiles in the
# stream lane (DMLC_JITCHECK), and the ONE committed SLO scorecard
# scripts/slo/prodsim.json (doc/robustness.md "Production-day
# simulation").  CI runs the smoke window; the archived PRODSIM_r0*.json
# evidence chain uses the full DMLC_PRODSIM_SECONDS default.
env JAX_PLATFORMS=cpu \
    DMLC_PRODSIM_SECONDS="${DMLC_PRODSIM_SECONDS:-12}" \
    PRODSIM_OUT="${PRODSIM_OUT:-/tmp/prodsim_drill.json}" \
    PRODSIM_RACECHECK_OUT="${PRODSIM_RACECHECK_OUT:-/tmp/prodsim_racecheck.json}" \
    PRODSIM_LEAKCHECK_OUT="${PRODSIM_LEAKCHECK_OUT:-/tmp/prodsim_leakcheck.json}" \
    PRODSIM_JITCHECK_OUT="${PRODSIM_JITCHECK_OUT:-/tmp/prodsim_jitcheck.json}" \
    PRODSIM_METRICS_OUT="${PRODSIM_METRICS_OUT:-/tmp/prodsim_metrics.json}" \
    PRODSIM_TRACE_OUT="${PRODSIM_TRACE_OUT:-/tmp/prodsim_trace.json}" \
    PRODSIM_SLO_OUT="${PRODSIM_SLO_OUT:-/tmp/prodsim_slo.json}" \
    python scripts/check_prodsim.py

if [[ "${1:-}" != "quick" ]]; then
    echo "== native build =="
    make -C cpp -j"$(nproc)"
fi

echo "== pytest (two lanes: fast + slow) =="
# Full coverage, split into two lanes (xdist is unavailable offline;
# this is the VERDICT r3 #8 two-lane split).  Each lane keeps -x; both
# exit codes are enforced.  The lanes overlap ONLY on multi-core hosts:
# on one core, two concurrent pytest processes each running 8-virtual-
# device XLA CPU collectives can starve a cross-device rendezvous past
# XLA's internal timeout — observed as a spurious SIGABRT inside an
# otherwise-green ring-attention test — so a 1-core host runs the
# lanes sequentially instead.
run_lane() {  # $1 = marker expression, $2 = log path
    python -m pytest tests/ -q -x -m "$1" > "$2" 2>&1
}
FAST_RC=0; SLOW_RC=0
if [[ "$(nproc)" -ge 2 ]]; then
    run_lane "not slow" /tmp/ci_fast_lane.log &
    FAST_PID=$!
    run_lane "slow" /tmp/ci_slow_lane.log &
    SLOW_PID=$!
    wait "$FAST_PID" || FAST_RC=$?
    wait "$SLOW_PID" || SLOW_RC=$?
else
    run_lane "not slow" /tmp/ci_fast_lane.log || FAST_RC=$?
    run_lane "slow" /tmp/ci_slow_lane.log || SLOW_RC=$?
fi
tail -3 /tmp/ci_fast_lane.log
tail -3 /tmp/ci_slow_lane.log
if [[ $FAST_RC -ne 0 || $SLOW_RC -ne 0 ]]; then
    echo "pytest lanes failed (fast=$FAST_RC slow=$SLOW_RC); full logs:"
    [[ $FAST_RC -ne 0 ]] && cat /tmp/ci_fast_lane.log
    [[ $SLOW_RC -ne 0 ]] && cat /tmp/ci_slow_lane.log
    exit 1
fi

if [[ "${1:-}" != "quick" ]]; then
    echo "== native sanitizers =="
    scripts/native_sanitize_test.sh

    echo "== examples (forced-CPU smoke) =="
    bash scripts/run_examples.sh
fi

echo "CI GREEN"
