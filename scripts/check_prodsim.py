#!/usr/bin/env python
"""Production-day simulation drill for CI: whole-stack chaos, one scorecard.

Runs ``bench.py --prodsim`` in-process — one composed run where a live
event feed streams into an OnlineTrainer (refreshes published through
tenant-scoped staged rollouts), a sparse-CTR ``fit_ps`` lane trains on
a real multi-process PS fleet, and a multi-tenant replica fleet (fake
6-host cluster under a LauncherScaler JobSet) serves diurnal Zipf
loadgen — while the deterministic chaos schedule (``at=``/``every=``
wall-clock triggers, ``DMLC_FAULT_SEED``) injects one fault in EVERY
tier mid-run:

* replica SIGKILL, * PS server SIGKILL (respawn + snapshot restore),
* spot-preemption wave downing 30% of hosts at once, * corrupt stream
shard bytes (tailer resync), * poisoned tenant publish (eval gate trips,
rollback stays tenant-scoped).

GREEN requires: availability >= 99% with zero dropped / zero wrong, all
five tiers faulted, host-death respawns charged to the host (not the
rank budget), the PS replacement restoring a snapshot, the stream lane
resyncing and its live tenant staying bit-verified, only the poisoned
tenant rolling back, zero lock-order cycles / races / leaks, and the
committed SLO scorecard ``scripts/slo/prodsim.json`` passing end to
end.  Artifacts: report at ``PRODSIM_OUT``, merged metrics at
``PRODSIM_METRICS_OUT``, stitched trace at ``PRODSIM_TRACE_OUT``,
race/leak/jit reports at ``PRODSIM_RACECHECK_OUT`` /
``PRODSIM_LEAKCHECK_OUT`` / ``PRODSIM_JITCHECK_OUT`` (the latter gates
zero steady-state XLA compiles in the stream lane's steady window),
scorecard at ``PRODSIM_SLO_OUT``.
Exit 0 = drill green.  Usage:
    python scripts/check_prodsim.py
"""

import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def main() -> None:
    os.environ.setdefault("DMLC_LOCKCHECK", "1")
    os.environ.setdefault("DMLC_RACECHECK", "1")
    os.environ.setdefault("DMLC_LEAKCHECK", "1")
    os.environ.setdefault("DMLC_JITCHECK", "1")
    os.environ.setdefault("DMLC_TRACE", "1")
    os.environ.setdefault("BENCH_FORCE_CPU", "1")
    spool = os.environ.get("DMLC_METRICS_SPOOL") \
        or tempfile.mkdtemp(prefix="dmlc_prodsim_spool")
    os.environ["DMLC_METRICS_SPOOL"] = spool
    t_drill0 = time.time()
    from dmlc_core_tpu.utils import force_cpu_devices

    force_cpu_devices(1)

    from dmlc_core_tpu.base import (jitcheck, leakcheck, lockcheck,
                                    metrics_agg, racecheck, slo)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_collect

    import bench

    spool_writer = metrics_agg.install_spool("drill", 0)
    record = bench._prodsim_bench()

    # -- the composed run's own evidence ---------------------------------
    chaos = record["chaos"]
    _check(chaos["tiers_faulted"] >= 5,
           f"chaos touched every tier "
           f"({chaos['tiers']} — schedule {chaos['schedule']!r}, "
           f"seed {chaos['seed']})")
    _check(all(r["fires"] >= 1 for r in chaos["rules"]),
           f"every scheduled chaos rule fired "
           f"({[(r['point'], r['kind'], r['fires']) for r in chaos['rules']]})")
    hosts = int(record["hosts"])
    want_wave = max(1, math.ceil(0.3 * hosts))
    _check(len(chaos["wave_hosts"]) >= want_wave,
           f"spot-preemption wave downed {len(chaos['wave_hosts'])}/{hosts} "
           f"hosts at once (>= 30%: {chaos['wave_hosts']})")
    _check(record["availability"] >= 0.99,
           f"availability {record['availability']:.5f} >= 0.99 through "
           f"all faults ({record['loadgen']['ok']} ok of "
           f"{record['loadgen']['count']})")
    _check(record["dropped"] == 0 and record["wrong"] == 0,
           f"zero dropped / zero wrong across the whole day "
           f"(shed {record['loadgen']['shed']})")

    launch = record["launch"]
    _check(launch["respawns_by_cause"].get("host_death", 0) >= 1,
           f"host deaths respawned without burning rank budgets "
           f"(by cause: {launch['respawns_by_cause']}, per host: "
           f"{launch['host_faults']})")
    _check(launch["giveups"] == 0,
           "no rank gave up: cause-fair budgets absorbed the kills")

    ps = record["ps"]
    _check(ps["victim_sigkilled"] == 1,
           f"PS server 1 SIGKILLed mid-stream (rc={ps['victim_rc']})")
    _check((ps["restored_version"] or 0) >= 1,
           f"PS replacement restored snapshot v{ps['restored_version']} "
           "as the same server id")
    _check(ps["rcs"]["workers"] == [0, 0]
           and all(rc == 0 for rc in ps["rcs"]["servers"]),
           f"PS workers + surviving servers exited clean ({ps['rcs']})")

    stream = record["stream"]
    _check(stream["resyncs"] >= 1,
           f"tailer resynced past the corrupt shard bytes "
           f"({stream['resyncs']} resync(s), "
           f"{stream['events_consumed']} events consumed)")
    _check(stream["live_verified"] == 1,
           f"live tenant v{stream['live_version']} bit-verified after "
           f"{stream['activated']} stream-refresh rollouts")

    rb = record["rollback"]
    _check(rb["poisoned"] == 1,
           f"poisoned publish rolled back by the eval gate "
           f"(waves: {rb['poison_waves']})")
    _check(rb["isolated"] == 1 and rb["static_rollbacks"] == 0,
           "rollback stayed tenant-scoped: every other tenant untouched")

    # -- observability plane: merge spools, stitch the trace -------------
    if spool_writer is not None:
        spool_writer.close()
    drill_wall_s = time.time() - t_drill0
    merged, nprocs = metrics_agg.merge_spool(spool)
    metrics_out = os.environ.get("PRODSIM_METRICS_OUT",
                                 "/tmp/prodsim_metrics.json")
    metrics_agg.write_snapshot(metrics_out, merged)
    _check(nprocs >= 8,
           f"metrics spool merged {nprocs} processes across all lanes "
           f"(artifact at {metrics_out})")
    trace_out = os.environ.get("PRODSIM_TRACE_OUT",
                               "/tmp/prodsim_trace.json")
    _, tsummary = trace_collect.collect(spool, trace_out)
    cross = {tid: t for tid, t in tsummary["traces"].items()
             if len(t["pids"]) >= 3 and "fleet.route" in t["spans"]
             and "tenant.predict" in t["spans"]}
    _check(cross,
           f"{len(cross)} trace(s) crossed loadgen -> router -> replica "
           f"tenant.predict over >= 3 processes (merged trace at "
           f"{trace_out})")

    lockcheck.check()
    print("ok: zero lock-order cycles under DMLC_LOCKCHECK=1 (parent)")
    rc_out = os.environ.get("PRODSIM_RACECHECK_OUT",
                            "/tmp/prodsim_racecheck.json")
    rc_report = racecheck.write_report(rc_out)
    racecheck.check()
    print(f"ok: zero happens-before races under DMLC_RACECHECK=1 "
          f"(parent; report at {rc_out})")
    lk_out = os.environ.get("PRODSIM_LEAKCHECK_OUT",
                            "/tmp/prodsim_leakcheck.json")
    lk_report = leakcheck.write_report(lk_out)
    leakcheck.check()
    print(f"ok: zero live resource leaks under DMLC_LEAKCHECK=1 "
          f"(parent; report at {lk_out})")
    jc_out = os.environ.get("PRODSIM_JITCHECK_OUT",
                            "/tmp/prodsim_jitcheck.json")
    jc_report = jitcheck.write_report(jc_out)
    jitcheck.check()
    print(f"ok: zero steady-state XLA compiles under DMLC_JITCHECK=1 "
          f"(stream lane steady window; report at {jc_out})")

    # -- the ONE SLO scorecard gate ---------------------------------------
    spec_path = os.environ.get("PRODSIM_SLO_SPEC") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "slo", "prodsim.json")
    evidence = dict(record)
    evidence["racecheck"] = {"races": len(rc_report["races"])}
    evidence["leakcheck"] = {"leaks": len(lk_report["leaks"])}
    evidence["jitcheck"] = {
        "recompiles_steady": jc_report["compiles_steady"]}
    scorecard = slo.evaluate(slo.SLOSpec.load(spec_path), merged, evidence)
    slo_out = os.environ.get("PRODSIM_SLO_OUT", "/tmp/prodsim_slo.json")
    with open(slo_out, "w") as f:
        json.dump(scorecard, f, indent=2)
    for row in scorecard["objectives"]:
        print(f"   slo[{row['name']}]: "
              f"{'pass' if row['pass'] else 'FAIL'} "
              f"(observed {row['observed']} {row['op']} "
              f"{row['threshold']}; {row['evidence']})")
    _check(scorecard["pass"],
           f"SLO scorecard {scorecard['spec']} green "
           f"(spec {spec_path}, scorecard at {slo_out})")

    report_out = os.environ.get("PRODSIM_OUT", "/tmp/prodsim_drill.json")
    with open(report_out, "w") as f:
        json.dump({
            "record": record,
            "observability": {
                "spool_processes_merged": nprocs,
                "traces": len(tsummary["traces"]),
                "cross_process_traces": len(cross),
                "drill_wall_s": round(drill_wall_s, 3),
            },
            "slo": scorecard,
        }, f, indent=2)
    print(f"   report archived to {report_out}")
    print("PRODSIM DRILL GREEN")


if __name__ == "__main__":
    main()
