"""Isolate descent cost; compare formulations (chained, one fetch)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from dmlc_core_tpu.ops.quantile import apply_bins, compute_cuts

ROWS, F, B, DEPTH = 4_000_000, 28, 256, 6
ITERS = int(os.environ.get("ITERS", 8))

rng = np.random.default_rng(0)
X = rng.normal(size=(ROWS, F)).astype(np.float32)
bins = apply_bins(jnp.asarray(X), compute_cuts(X, B))
np.asarray(bins[0])
feats = jnp.asarray(rng.integers(0, F, (DEPTH, 32)).astype(np.int32))
thrs = jnp.asarray(rng.integers(0, B, (DEPTH, 32)).astype(np.int32))


def table_select(table, node, n_entries):
    n_iota = jnp.arange(n_entries, dtype=jnp.int32)[None, :]
    oh = node[:, None] == n_iota
    return jnp.sum(jnp.where(oh, table[None, :], 0), axis=1)


@jax.jit
def six_descents_select(bins_l, feats, thrs):
    node = jnp.zeros(bins_l.shape[0], jnp.int32)
    for level in range(DEPTH):
        n_nodes = 1 << level
        feat = feats[level, :n_nodes]
        thr = thrs[level, :n_nodes]
        feat_sel = table_select(feat, node, n_nodes)
        thr_sel = table_select(thr, node, n_nodes)
        f_iota = jnp.arange(bins_l.shape[1], dtype=jnp.int32)[None, :]
        row_bin = jnp.sum(
            jnp.where(feat_sel[:, None] == f_iota,
                      bins_l.astype(jnp.int32), 0), axis=1)
        node = 2 * node + (row_bin > thr_sel).astype(jnp.int32)
    return node


@jax.jit
def six_descents_gather(bins_l, feats, thrs):
    node = jnp.zeros(bins_l.shape[0], jnp.int32)
    for level in range(DEPTH):
        n_nodes = 1 << level
        feat = feats[level, :n_nodes]
        thr = thrs[level, :n_nodes]
        f = feat[node]
        t = thr[node]
        row_bin = jnp.take_along_axis(
            bins_l, f[:, None], axis=1)[:, 0].astype(jnp.int32)
        node = 2 * node + (row_bin > t).astype(jnp.int32)
    return node


@jax.jit
def one_descent_select(bins_l, feats, thrs, node):
    n_nodes = 32
    feat = feats[5, :n_nodes]
    thr = thrs[5, :n_nodes]
    feat_sel = table_select(feat, node, n_nodes)
    thr_sel = table_select(thr, node, n_nodes)
    f_iota = jnp.arange(bins_l.shape[1], dtype=jnp.int32)[None, :]
    row_bin = jnp.sum(
        jnp.where(feat_sel[:, None] == f_iota,
                  bins_l.astype(jnp.int32), 0), axis=1)
    return 2 * node + (row_bin > thr_sel).astype(jnp.int32)


def timed(label, fn, *args):
    out = fn(*args)
    np.asarray(out)[:1]
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    _ = np.asarray(out)[:1]
    print(f"{label:40s} {(time.perf_counter()-t0)/ITERS*1e3:9.1f} ms",
          flush=True)


nid = jnp.asarray(rng.integers(0, 32, ROWS).astype(np.int32))
timed("6 descents (table_select)", six_descents_select, bins, feats, thrs)
timed("6 descents (gather)", six_descents_gather, bins, feats, thrs)
timed("1 descent lvl5 (table_select)", one_descent_select, bins, feats, thrs, nid)
