#!/usr/bin/env python
"""Multi-tenant serving drill for CI: many models, one fleet.

Stands up FleetTracker + 3 tenancy-enabled subprocess replicas (six
tenants, each with its own v1 HistGBT, residency cap 4 so paging is
guaranteed) + in-process tenant-aware router, then drives the incidents
the tenancy tier exists to absorb, with closed-loop bit-verified Zipf
tenant load running THROUGH every incident:

1. **Poisoned publish** — mid-traffic, a tenant-scoped staged rollout
   deploys a model trained on permuted labels for ONE tenant.  The
   per-wave eval gate (holdout MSE vs the v1 baseline, scored against
   the replica actually serving the new version) must trip, the rollout
   must roll back, and every OTHER tenant's current pointer and p99 must
   be untouched — zero dropped, zero wrong across the event.
2. **Hot-tenant surge** — a second router with a tight admission
   envelope (low in-flight cap, bronze sheds at 12.5%) takes a Zipf
   surge whose head is a bronze tenant.  Bronze must shed (429) while
   gold never class-sheds and nobody drops: overload lands on the class
   that bought the cheap SLO, not the long tail.
3. **Paging churn** — round-robin direct predicts over all six tenants
   on every replica force LRU evictions and compile-cache-backed warm
   restores; every answer must stay bit-identical to the expected v1
   predictions.

The JSON report is archived to ``TENANCY_OUT`` (default
``/tmp/tenancy_drill.json``).  Parent runs under ``DMLC_LOCKCHECK=1`` +
``DMLC_RACECHECK=1`` + ``DMLC_LEAKCHECK=1`` (reports at
``TENANCY_RACECHECK_OUT`` / ``TENANCY_LEAKCHECK_OUT``); every process
spools metrics + trace shards (merged snapshot at
``TENANCY_METRICS_OUT``, stitched trace at ``TENANCY_TRACE_OUT``), and
GREEN additionally requires the committed per-tenant SLO scorecard
(``scripts/slo/tenancy.json``, scorecard at ``TENANCY_SLO_OUT``).
Exit 0 = drill green.  Usage:
    python scripts/check_tenancy.py
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_REPLICAS = 3
N_ROWS, N_FEAT = 400, 8
TENANTS = ["t0", "t1", "t2", "t3", "t4", "t5"]
CLASSES = "gold:t0;bronze:t4,t5"
POISON = "t2"                      # the tenant whose v2 is poisoned
RESIDENT_CAP = 4                   # < len(TENANTS): paging guaranteed
LOAD_S = 6.0


def _check(cond, msg):
    if not cond:
        print(f"FAIL: {msg}")
        sys.exit(1)
    print(f"ok: {msg}")


def _wait(pred, timeout_s, label):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    _check(False, f"timed out waiting for {label}")


def main() -> None:
    os.environ.setdefault("DMLC_LOCKCHECK", "1")
    os.environ.setdefault("DMLC_RACECHECK", "1")
    os.environ.setdefault("DMLC_LEAKCHECK", "1")
    os.environ.setdefault("DMLC_TRACE", "1")
    spool = os.environ.get("DMLC_METRICS_SPOOL") \
        or tempfile.mkdtemp(prefix="dmlc_tenancy_spool")
    os.environ["DMLC_METRICS_SPOOL"] = spool
    t_drill0 = time.time()
    from dmlc_core_tpu.utils import force_cpu_devices

    force_cpu_devices(1)

    import numpy as np

    from dmlc_core_tpu.base import (leakcheck, lockcheck, metrics_agg,
                                    racecheck, slo)
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.serve.client import ResilientClient
    from dmlc_core_tpu.serve.fleet import (FleetRouter, FleetTracker,
                                           HttpFleetAdmin, Rollout,
                                           run_loadgen, spawn_replica)
    from dmlc_core_tpu.serve.tenancy import (TenantPolicy,
                                             checkpoint_tenant_model)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_collect

    spool_writer = metrics_agg.install_spool("drill", 0)
    out_path = os.environ.get("TENANCY_OUT", "/tmp/tenancy_drill.json")
    report = {"phases": {}}
    tmp = tempfile.mkdtemp(prefix="dmlc_tenancy")

    # -- six tenants, six different v1 models (all HistGBT: the tree
    # engine is bit-exact across batch shapes, so the loadgen's
    # bit-equality oracle holds through padding AND paging) -------------
    rng = np.random.default_rng(42)
    X = rng.normal(size=(N_ROWS, N_FEAT)).astype(np.float32)
    models, npz = {}, {"X": X}
    for i, t in enumerate(TENANTS):
        y = (X[:, i % N_FEAT] + X[:, (i + 1) % N_FEAT]
             * X[:, (i + 2) % N_FEAT] > 0).astype(np.float32)
        m = HistGBT(n_trees=3 + i, max_depth=3, n_bins=16).fit(X, y)
        models[t] = (m, y)
        npz[f"{t}__v1"] = m.predict(X)
        checkpoint_tenant_model(f"file://{tmp}/{t}_v1.ckpt", t, m,
                                version=1)
    # the poisoned v2: same family, labels permuted — a model that
    # trains fine and serves fine but predicts garbage
    y_poison = np.random.default_rng(7).permutation(models[POISON][1])
    m_poison = HistGBT(n_trees=4, max_depth=3, n_bins=16).fit(X, y_poison)
    poison_uri = f"file://{tmp}/{POISON}_v2.ckpt"
    checkpoint_tenant_model(poison_uri, POISON, m_poison, version=2)
    npz[f"{POISON}__v2"] = m_poison.predict(X)   # transient v2 answers
    expected_npz = os.path.join(tmp, "expected.npz")
    np.savez(expected_npz, **npz)

    X_hold, y_hold = X[:64], models[POISON][1][:64]
    base_mse = float(np.mean(
        (models[POISON][0].predict(X_hold) - y_hold) ** 2))

    child_env = {"JAX_PLATFORMS": "cpu", "DMLC_TPU_FORCE_CPU": "1",
                 "DMLC_LOCKCHECK": "1", "DMLC_RACECHECK": "1",
                 "DMLC_TRACE": "1", "DMLC_METRICS_SPOOL": spool,
                 "DMLC_TENANT_RESIDENT_CAP": str(RESIDENT_CAP)}
    tracker = FleetTracker(nworker=8)
    tracker.start()
    procs = [spawn_replica("127.0.0.1", tracker.port, max_batch=32,
                           tenancy=True, extra_env=child_env)
             for _ in range(N_REPLICAS)]
    router = surge_router = None
    try:
        _wait(lambda: len(tracker.serve_endpoints()) == N_REPLICAS,
              180, "replica registration")
        endpoints = dict(tracker.serve_endpoints())
        admin = HttpFleetAdmin(endpoints)
        for rank in endpoints:
            for t in TENANTS:
                v = admin.load(rank, f"file://{tmp}/{t}_v1.ckpt",
                               activate=True, tenant=t)
                assert v == 1
        _check(True, f"{len(TENANTS)} tenants loaded at v1 on "
                     f"{N_REPLICAS} replicas (residency cap "
                     f"{RESIDENT_CAP})")
        for rank in endpoints:
            tdoc = admin.health(rank).get("tenants", {})
            _check(sorted(tdoc) == TENANTS
                   and all(d["version"] == 1 for d in tdoc.values()),
                   f"replica {rank} heartbeats all tenants at v1")
            _check(sum(d["resident"] for d in tdoc.values())
                   <= RESIDENT_CAP,
                   f"replica {rank} resident count within cap")

        # steady-state policy: generous admission, gold hedges almost
        # always (1ms budget) so the hedge path runs under racecheck
        policy = TenantPolicy(classes=CLASSES, default_class="silver",
                              quota=0, max_inflight=256,
                              shed_fraction=0.5, hedge_ms=1)
        router = FleetRouter(tracker, probe_s=0.2,
                             policy=policy).start()
        client = ResilientClient(router.url)
        preds, ver = client.predict(X[:8], tenant="t1")
        _check(ver == 1 and np.array_equal(preds,
                                           npz["t1__v1"][:8]),
               "routed tenant predict bit-identical to direct v1 predict")

        # -- phase 1: poisoned publish for ONE tenant under Zipf load ----
        def _loadgen_bg(result, duration, **kw):
            result.update(run_loadgen(
                router.url, expected_npz, duration_s=duration, procs=2,
                threads=3, base_qps=60.0, timeout_ms=20_000,
                workdir=tmp, env=child_env, tenants=TENANTS, **kw))

        def eval_gate(version):
            # honest gate: score the holdout against each replica that
            # actually serves the candidate version for the tenant
            for rank, url in endpoints.items():
                tdoc = admin.health(rank).get("tenants", {}).get(
                    POISON, {})
                if tdoc.get("version") != version:
                    continue
                p, v = ResilientClient(url).predict(X_hold,
                                                    tenant=POISON)
                if v != version:
                    continue
                mse = float(np.mean((p - y_hold) ** 2))
                print(f"   gate: replica {rank} {POISON} v{version} "
                      f"holdout mse {mse:.4f} (v1 baseline "
                      f"{base_mse:.4f})")
                if mse > 2.0 * base_mse + 1e-6:
                    return False
            return True

        load1 = {}
        t1 = threading.Thread(target=_loadgen_bg, args=(load1, LOAD_S))
        t1.start()
        time.sleep(LOAD_S / 3.0)
        rollout = Rollout(admin, wave_size=1, settle_s=0.3,
                          eval_gate=eval_gate,
                          tenant=POISON).run(poison_uri)
        _check(rollout["outcome"] == "rolled_back",
               f"poisoned v2 publish for {POISON} rolled back by the "
               f"eval gate (waves: {rollout['waves']})")
        t1.join(timeout=LOAD_S + 300)
        _check(not t1.is_alive(), "poison-phase load generator finished")
        _check(load1.get("dropped") == 0 and load1.get("wrong") == 0,
               f"poisoned publish under load: zero dropped / zero wrong "
               f"({load1.get('ok')} ok of {load1.get('count')})")
        _check(load1.get("shed") == 0,
               "steady-state admission shed nothing")
        for rank in endpoints:
            tdoc = admin.health(rank).get("tenants", {})
            _check(all(tdoc[t]["version"] == 1 for t in TENANTS),
                   f"replica {rank}: every tenant back on v1 "
                   f"(rollback isolated to {POISON})")
        per_t = load1.get("by_tenant", {})
        _check(sorted(per_t) == TENANTS
               and all(per_t[t]["ok"] > 0 for t in TENANTS),
               f"Zipf mix served every tenant "
               f"({ {t: per_t[t]['ok'] for t in sorted(per_t)} })")
        report["phases"]["poison"] = {"load": load1, "rollout": rollout,
                                      "base_mse": base_mse}

        # -- phase 2: hot-bronze surge against a tight envelope ----------
        # a second router with its own injected policy (the envelope is
        # constructor state, so no instrumented attrs mutate mid-run):
        # in-flight cap 8, bronze sheds at 12.5% => any concurrency
        tight = TenantPolicy(classes=CLASSES, default_class="silver",
                             quota=0, max_inflight=8,
                             shed_fraction=0.125, hedge_ms=0)
        surge_router = FleetRouter(tracker, probe_s=0.2,
                                   policy=tight).start()
        surge = {}
        surge.update(run_loadgen(
            surge_router.url, expected_npz, duration_s=LOAD_S, procs=2,
            threads=3, base_qps=300.0, timeout_ms=20_000, workdir=tmp,
            # two attempts only: a bronze 429 that persists across one
            # honored Retry-After becomes a terminal shed quickly
            env=dict(child_env, DMLC_RETRY_MAX_ATTEMPTS="2"),
            tenants=["t4"] + [t for t in TENANTS if t != "t4"],
            zipf_a=1.3))
        _check(surge.get("dropped") == 0 and surge.get("wrong") == 0,
               f"surge: zero dropped / zero wrong "
               f"({surge.get('ok')} ok, {surge.get('shed')} shed of "
               f"{surge.get('count')})")
        sb = surge.get("by_tenant", {})
        _check(sb.get("t4", {}).get("shed", 0) >= 1,
               f"hot bronze tenant shed first "
               f"(t4 shed {sb.get('t4', {}).get('shed')})")
        _check(sb.get("t0", {}).get("shed", 0) == 0
               and sb.get("t0", {}).get("ok", 0) > 0,
               f"gold rode through the surge unshed "
               f"(t0 ok {sb.get('t0', {}).get('ok')})")
        report["phases"]["surge"] = {"load": surge}

        # -- phase 3: paging churn with bit-exact restores ---------------
        restore_clients = {r: ResilientClient(u)
                           for r, u in endpoints.items()}
        for _round in range(2):
            for rank, c in restore_clients.items():
                for t in TENANTS:
                    p, v = c.predict(X[:16], tenant=t)
                    _check(v == 1 and np.array_equal(
                        p, npz[f"{t}__v1"][:16]),
                        f"replica {rank} {t} round {_round}: "
                        f"restore bit-identical at v1")
        for rank in endpoints:
            tdoc = admin.health(rank).get("tenants", {})
            _check(sum(d["resident"] for d in tdoc.values())
                   <= RESIDENT_CAP,
                   f"replica {rank} stayed within residency cap "
                   f"after churn")
        report["phases"]["paging"] = {
            rank: admin.health(rank).get("tenants", {})
            for rank in endpoints}
    finally:
        for r in (router, surge_router):
            if r is not None:
                r.close()
        for p in procs:
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=15)
                except Exception:  # noqa: BLE001
                    p.kill()
        tracker.stop()

    # -- observability plane: merge spools, stitch the trace -------------
    if spool_writer is not None:
        spool_writer.close()
    drill_wall_s = time.time() - t_drill0
    merged, nprocs = metrics_agg.merge_spool(spool)
    metrics_out = os.environ.get("TENANCY_METRICS_OUT",
                                 "/tmp/tenancy_metrics.json")
    metrics_agg.write_snapshot(metrics_out, merged)
    _check(nprocs >= N_REPLICAS + 1,
           f"metrics spool merged {nprocs} processes "
           f"(artifact at {metrics_out})")
    ev = merged["metrics"].get("dmlc_tenant_evictions_total", {})
    ev_total = sum(s["value"] for s in ev.get("series", ()))
    _check(ev_total >= 1,
           f"replicas paged tenants out under the cap "
           f"(dmlc_tenant_evictions_total = {ev_total:.0f})")
    rs = merged["metrics"].get("dmlc_tenant_restore_seconds", {})
    rs_count = sum(s.get("count", 0) for s in rs.get("series", ()))
    _check(rs_count >= 1,
           f"paged-out tenants warm-restored on demand "
           f"(dmlc_tenant_restore_seconds count = {rs_count:.0f})")

    trace_out = os.environ.get("TENANCY_TRACE_OUT",
                               "/tmp/tenancy_trace.json")
    _, tsummary = trace_collect.collect(spool, trace_out)
    cross = {tid: t for tid, t in tsummary["traces"].items()
             if len(t["pids"]) >= 3 and "fleet.route" in t["spans"]
             and "tenant.predict" in t["spans"]}
    _check(cross,
           f"{len(cross)} tenant trace(s) crossed loadgen -> router -> "
           f"replica tenant.predict over >= 3 processes (merged trace "
           f"at {trace_out})")
    report["observability"] = {
        "spool_processes_merged": nprocs,
        "traces": len(tsummary["traces"]),
        "cross_process_tenant_traces": len(cross),
        "evictions_total": ev_total,
        "restores_total": rs_count,
        "drill_wall_s": round(drill_wall_s, 3),
    }

    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"   report archived to {out_path}")
    lockcheck.check()
    print("ok: zero lock-order cycles under DMLC_LOCKCHECK=1 (parent)")
    rc_out = os.environ.get("TENANCY_RACECHECK_OUT",
                            "/tmp/tenancy_racecheck.json")
    rc_report = racecheck.write_report(rc_out)
    racecheck.check()
    print(f"ok: zero happens-before races under DMLC_RACECHECK=1 "
          f"(parent; report at {rc_out})")
    lk_out = os.environ.get("TENANCY_LEAKCHECK_OUT",
                            "/tmp/tenancy_leakcheck.json")
    lk_report = leakcheck.write_report(lk_out)
    leakcheck.check()
    print(f"ok: zero live resource leaks under DMLC_LEAKCHECK=1 "
          f"(parent; report at {lk_out})")

    # -- per-tenant SLO scorecard gate ------------------------------------
    spec_path = os.environ.get("TENANCY_SLO_SPEC") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "slo", "tenancy.json")
    evidence = {
        "loadgen": report["phases"]["poison"]["load"],
        "surge": report["phases"]["surge"]["load"],
        "racecheck": {"races": len(rc_report["races"])},
        "leakcheck": {"leaks": len(lk_report["leaks"])},
    }
    scorecard = slo.evaluate(slo.SLOSpec.load(spec_path), merged, evidence)
    slo_out = os.environ.get("TENANCY_SLO_OUT", "/tmp/tenancy_slo.json")
    with open(slo_out, "w") as f:
        json.dump(scorecard, f, indent=2)
    for row in scorecard["objectives"]:
        print(f"   slo[{row['name']}]: "
              f"{'pass' if row['pass'] else 'FAIL'} "
              f"(observed {row['observed']} {row['op']} "
              f"{row['threshold']}; {row['evidence']})")
    _check(scorecard["pass"],
           f"SLO scorecard {scorecard['spec']} green "
           f"(spec {spec_path}, scorecard at {slo_out})")
    print("TENANCY DRILL GREEN")


if __name__ == "__main__":
    main()
