"""Config-2 at its written scale: 224x224x3 records -> ResNet-50 feed.

BASELINE config 2 names an ImageNet-shard-scale pipeline (sharded
RecordIO -> DeviceFeed -> a ResNet-50-class consumer at batch 256);
round 4 proved the machinery at 32x32/ResNet-18 scale.  This bench runs
the REAL shape and — because a remotely-tunneled chip cannot absorb
38 MB/batch (tunnel H2D is 5-17 MB/s; a local PCIe/direct attachment
moves GB/s) — it decomposes the claim into independently measured
parts, each tagged with its basis:

1. ``host_pipeline_records_per_sec`` — the data plane alone (sharded
   RecordIO read -> record unpack -> batch assembly) at 224^3.  This is
   the part config 2 actually claims (the feed is never the
   bottleneck); it is tunnel-independent.
2. ``device_step_seconds`` / ``device_records_per_sec`` — the
   ResNet-50 train step at batch 256 on resident data (device-bound
   ceiling; FLOP-checked against the 3.1 TFLOP/step estimate).
3. ``h2d_mbps`` — the measured tunnel transfer rate for one batch.
4. ``e2e_*`` — the honest end-to-end run through DeviceFeed with its
   stall fraction, which on a TUNNEL is transfer-bound by (3), not by
   (1): the stall verdict for local attachment is
   ``host_pipeline >= device rate``, emitted as ``feed_keeps_up``.

Env knobs: RESNET_RECORDS (1536), RESNET_BATCH (256), RESNET_STEPS (8),
RESNET_HW (224), RESNET_VARIANT (resnet50), BENCH_CPU=1.
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BENCH_CPU"):
    from dmlc_core_tpu.utils import force_cpu_devices
    force_cpu_devices(1)

import numpy as np  # noqa: E402


def write_shards(root, n_records, hw, n_shards=4):
    from dmlc_core_tpu.data.image_record import pack_image_record
    from dmlc_core_tpu.io.recordio import RecordIOWriter

    rng = np.random.default_rng(0)
    per = n_records // n_shards
    for s in range(n_shards):
        with RecordIOWriter(os.path.join(root, f"part-{s}.rec")) as w:
            for _ in range(per):
                label = int(rng.integers(0, 1000))
                img = rng.integers(0, 256, size=(hw, hw, 3),
                                   dtype=np.uint8)
                img[..., 0] = (img[..., 0] // 4
                               + (label % 10) * 25).astype(np.uint8)
                w.write_record(pack_image_record(img, label))
    return per * n_shards


def main():
    n_records = int(os.environ.get("RESNET_RECORDS", 1536))
    batch = int(os.environ.get("RESNET_BATCH", 256))
    steps = int(os.environ.get("RESNET_STEPS", 8))
    hw = int(os.environ.get("RESNET_HW", 224))
    variant = os.environ.get("RESNET_VARIANT", "resnet50")

    import jax

    from dmlc_core_tpu.data.image_record import batch_iterator
    from dmlc_core_tpu.models.resnet import ResNetTrainer

    root = tempfile.mkdtemp(prefix="resnet_feed_")
    t0 = time.perf_counter()
    total = write_shards(root, n_records, hw)
    write_s = time.perf_counter() - t0
    uri = os.path.join(root, "part-*.rec")

    # 1. host pipeline alone (the config-2 claim's own leg)
    t0 = time.perf_counter()
    host_recs = 0
    for images, labels in batch_iterator(uri, 0, 1, batch, (hw, hw, 3)):
        host_recs += len(labels)
    host_s = time.perf_counter() - t0
    host_rate = host_recs / host_s

    # 2. device step on resident data (the consumption ceiling)
    trainer = ResNetTrainer(variant=variant, num_classes=1000,
                            learning_rate=0.05)
    trainer.init((hw, hw, 3))
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, size=(batch, hw, hw, 3), dtype=np.uint8)
    lbls = rng.integers(0, 1000, size=batch).astype(np.int32)
    import jax.numpy as jnp
    di, dl = jnp.asarray(imgs), jnp.asarray(lbls)
    loss, acc = trainer.train_step(di, dl)          # compile
    np.asarray(loss)                                # tunnel-proof sync:
    # block_until_ready returns EARLY through the remote tunnel (see
    # doc/benchmarking.md) — an unsynced loop here measured 1.7 ms/step
    # = 9x the chip's peak FLOP rate, i.e. nothing at all
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, acc = trainer.train_step(di, dl)
    np.asarray(loss)                                # one scalar fetch
    step_s = (time.perf_counter() - t0) / steps
    device_rate = batch / step_s
    # ResNet-50 fwd ~4.1 GFLOP/img at 224^3; train ~3x
    tflop_step = 3 * 4.1e9 * batch / 1e12 if hw == 224 else None

    # 3. tunnel/interconnect H2D for one batch (fetch a corner of each
    # transferred buffer so the transfer provably completed)
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(jax.device_put(imgs).ravel()[:1])
    h2d_mbps = 3 * imgs.nbytes / (time.perf_counter() - t0) / 1e6

    # 4. honest end-to-end through DeviceFeed
    e2e = trainer.fit_from_records(uri, batch_size=batch,
                                   image_shape=(hw, hw, 3), epochs=1)

    out = {
        "metric": "resnet_feed_224",
        "records": total, "batch": batch, "hw": hw, "variant": variant,
        "write_seconds": round(write_s, 2),
        "host_pipeline_records_per_sec": round(host_rate, 1),
        "host_pipeline_mbps": round(host_rate * hw * hw * 3 / 1e6, 1),
        "device_step_seconds": round(step_s, 4),
        "device_records_per_sec": round(device_rate, 1),
        "est_tflop_per_step": tflop_step,
        "h2d_mbps": round(h2d_mbps, 1),
        "e2e_records_per_sec": round(e2e["records_per_sec"], 1),
        "e2e_stall_fraction": round(e2e["infeed_stall_fraction"], 4),
        "e2e_basis": "through the remote tunnel the feed is H2D-bound "
                     "(h2d_mbps vs 38 MB/batch), not host-pipeline-"
                     "bound; locally attached chips move GB/s",
        "feed_keeps_up": bool(host_rate >= device_rate),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
