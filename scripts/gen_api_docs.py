#!/usr/bin/env python
"""Generate the per-symbol API reference into doc/api/ (SURVEY.md §2d's
Doxygen role, stdlib-only).

Walks every module under ``dmlc_core_tpu``, emits one markdown file per
module (module docstring, then each public symbol's signature +
docstring; classes include their public methods), plus an index.

CI contract (wired into scripts/ci.sh): any symbol exported via a
module's ``__all__`` that lacks a docstring FAILS the run — the API
surface a module declares is the surface it must document.  Symbols
that are merely public-by-convention are documented when possible but
not enforced.

Usage:
    python scripts/gen_api_docs.py          # write doc/api/, enforce
    python scripts/gen_api_docs.py --check  # enforce only, write nothing
"""

import importlib
import inspect
import os
import pkgutil
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.utils import force_cpu_devices  # noqa: E402

force_cpu_devices(1)   # never let a doc build touch (or hang on) real TPUs

import dmlc_core_tpu  # noqa: E402

OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "doc", "api")


def _iter_modules():
    yield "dmlc_core_tpu", dmlc_core_tpu
    prefix = dmlc_core_tpu.__name__ + "."
    for info in pkgutil.walk_packages(dmlc_core_tpu.__path__, prefix):
        leaf = info.name.rsplit(".", 1)[-1]
        if leaf.startswith("_"):
            continue
        yield info.name, importlib.import_module(info.name)


def _public_symbols(mod):
    """(name, obj, enforced) for the module's documented surface."""
    declared = getattr(mod, "__all__", None)
    if declared is not None:
        for name in declared:
            yield name, getattr(mod, name), True
        return
    for name, obj in sorted(vars(mod).items()):
        if name.startswith("_"):
            continue
        if not (inspect.isclass(obj) or inspect.isfunction(obj)):
            continue
        if getattr(obj, "__module__", None) != mod.__name__:
            continue   # re-exports are documented where they are defined
        yield name, obj, False


def _signature(obj):
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # default-value reprs of library sentinels embed process-specific
    # memory addresses (e.g. flax's `_Sentinel object at 0x7f...`) —
    # strip them or the staleness gate flaps on every run
    return re.sub(r" at 0x[0-9a-fA-F]+", "", sig)


def _doc_block(obj):
    doc = inspect.getdoc(obj)
    return doc.strip() if doc else ""


def _render_symbol(name, obj, out, missing, enforced, qualifier=""):
    title = f"{qualifier}{name}"
    doc = _doc_block(obj)
    if inspect.isclass(obj):
        out.append(f"### class `{title}{_signature(obj)}`\n")
        if doc:
            out.append(doc + "\n")
        elif enforced:
            missing.append(title)
        for mname, mobj in sorted(vars(obj).items()):
            if mname.startswith("_") and mname != "__init__":
                continue
            if not (inspect.isfunction(mobj)
                    or isinstance(mobj, (classmethod, staticmethod,
                                         property))):
                continue
            raw = mobj
            if isinstance(mobj, (classmethod, staticmethod)):
                raw = mobj.__func__
            if isinstance(mobj, property):
                mdoc = _doc_block(mobj)
                out.append(f"- **`{mname}`** *(property)* — "
                           f"{mdoc.splitlines()[0] if mdoc else ''}\n")
                continue
            mdoc = _doc_block(raw)
            first = mdoc.splitlines()[0] if mdoc else ""
            out.append(f"- **`{mname}{_signature(raw)}`** — {first}\n")
    elif inspect.isfunction(obj) or inspect.isbuiltin(obj):
        out.append(f"### `{title}{_signature(obj)}`\n")
        if doc:
            out.append(doc + "\n")
        elif enforced:
            missing.append(title)
    else:
        out.append(f"### `{title}`\n")
        if doc and doc != _doc_block(type(obj)):
            out.append(doc + "\n")
        out.append(f"*constant of type `{type(obj).__name__}`*\n")


def main() -> int:
    check_only = "--check" in sys.argv
    missing = []
    index = []
    pages = {}
    for modname, mod in sorted(_iter_modules()):
        out = [f"# `{modname}`\n"]
        mdoc = _doc_block(mod)
        if mdoc:
            out.append(mdoc + "\n")
        n_syms = 0
        for name, obj, enforced in _public_symbols(mod):
            _render_symbol(name, obj, out, missing, enforced,
                           qualifier=f"{modname}.")
            n_syms += 1
        if n_syms == 0 and not mdoc:
            continue
        fname = modname.replace(".", "_") + ".md"
        pages[fname] = "\n".join(out) + "\n"
        first = mdoc.splitlines()[0] if mdoc else ""
        index.append(f"- [`{modname}`]({fname}) — {first}")

    if not check_only:
        os.makedirs(OUT_DIR, exist_ok=True)
        for old in os.listdir(OUT_DIR):
            if old.endswith(".md"):
                os.remove(os.path.join(OUT_DIR, old))
        for fname, text in pages.items():
            with open(os.path.join(OUT_DIR, fname), "w") as f:
                f.write(text)
        with open(os.path.join(OUT_DIR, "README.md"), "w") as f:
            f.write("# API reference\n\nGenerated by "
                    "`scripts/gen_api_docs.py` (run it after changing any "
                    "public surface; CI regenerates and fails on "
                    "undocumented `__all__` exports).\n\n"
                    + "\n".join(index) + "\n")
        print(f"gen_api_docs: wrote {len(pages)} module pages to doc/api/")

    if missing:
        print("gen_api_docs: MISSING DOCSTRINGS on __all__ exports:",
              file=sys.stderr)
        for m in missing:
            print(f"  - {m}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
