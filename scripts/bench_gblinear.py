#!/usr/bin/env python
"""GBLinear at out-of-core scale (VERDICT r3 #4 — the 50M×39 H2D story).

Streams the Criteo-shaped LibSVM page cache (shared with
bench_external.py) through ``GBLinear.fit_iter``: CSR pages densify into
a bounded staging slab and land on the chip via donated
``dynamic_update_slice`` writes — the full dense matrix NEVER exists on
the host — with ``feature_dtype=bfloat16`` (default here) halving both
the tunnel bytes and HBM residency (7.8 → 3.9 GB at 50M×39).

Reports one JSON line: assembly (stream+upload) seconds, boost rounds/s
with per-chunk evidence, peak host RSS.

    BENCH_GBLIN_ROWS=50000000 python scripts/bench_gblinear.py
    BENCH_GBLIN_DTYPE=float32  # f32 comparison run
"""
import json
import os
import resource
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("BENCH_GBLIN_ROWS", 50_000_000))
FEATS = int(os.environ.get("BENCH_GBLIN_FEATURES", 39))
ROUNDS = int(os.environ.get("BENCH_GBLIN_ROUNDS", 50))
DTYPE = os.environ.get("BENCH_GBLIN_DTYPE", "bfloat16")
WORKDIR = os.environ.get("BENCH_EXT_DIR", "/tmp/dmlc_ext_bench")


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main() -> None:
    os.makedirs(WORKDIR, exist_ok=True)
    svm = os.path.join(WORKDIR, f"criteo_{ROWS}x{FEATS}.svm")
    cache = os.path.join(WORKDIR, f"criteo_{ROWS}x{FEATS}.cache")
    gen = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "build", "gen_libsvm")
    out = {"rows": ROWS, "features": FEATS, "rounds": ROUNDS,
           "feature_dtype": DTYPE}

    if not os.path.exists(svm):
        t0 = time.perf_counter()
        subprocess.run([gen, str(ROWS), str(FEATS), svm, "7"], check=True,
                       stderr=subprocess.DEVNULL)
        out["gen_seconds"] = round(time.perf_counter() - t0, 1)

    from dmlc_core_tpu.data.iter import RowBlockIter
    from dmlc_core_tpu.models.linear import GBLinear

    t0 = time.perf_counter()
    it = RowBlockIter.create(f"{svm}#{cache}", 0, 1, "libsvm")
    out["open_or_parse_seconds"] = round(time.perf_counter() - t0, 1)

    m = GBLinear(n_rounds=ROUNDS, objective="binary:logistic",
                 feature_dtype=DTYPE)
    t0 = time.perf_counter()
    m.fit_iter(it, num_col=FEATS, warmup_rounds=3)
    total = time.perf_counter() - t0
    it.close()

    matrix_gb = ROWS * FEATS * (2 if DTYPE == "bfloat16" else 4) / 1e9
    out.update({
        "total_seconds": round(total, 1),
        "assembly_seconds": round(
            total - m.last_warmup_seconds - m.last_fit_seconds, 1),
        "matrix_gb_on_device": round(matrix_gb, 2),
        "assembly_mb_per_sec": round(matrix_gb * 1e3 / max(
            total - m.last_warmup_seconds - m.last_fit_seconds, 1e-9), 1),
        "warmup_seconds": round(m.last_warmup_seconds, 1),
        "boost_seconds": round(m.last_fit_seconds, 2),
        "rounds_per_sec": round(ROUNDS / m.last_fit_seconds, 2),
        "peak_rss_gb": round(rss_gb(), 2),
        "weight_norm": round(float((m.weights ** 2).sum() ** 0.5), 4),
        "bias": round(m.bias, 5),
    })
    from bench import chunk_stats
    out.update(chunk_stats(m.last_chunk_times, ROUNDS, m.last_fit_seconds))
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
