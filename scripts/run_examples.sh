#!/usr/bin/env bash
# Smoke-run every example on forced CPU devices (DMLC_TPU_FORCE_CPU —
# the package-level env hook), so the examples cannot rot silently and
# never touch a real TPU from CI.  Each must exit 0.
set -euo pipefail
cd "$(dirname "$0")/.."

export DMLC_TPU_FORCE_CPU="${DMLC_TPU_FORCE_CPU:-2}"

log=$(mktemp)
trap 'rm -f "$log"' EXIT
fail=0
for ex in examples/*.py; do
    echo "== $ex =="
    if ! timeout 300 python "$ex" > "$log" 2>&1; then
        echo "EXAMPLE FAILED: $ex"
        tail -20 "$log"
        fail=1
    else
        tail -2 "$log"
    fi
done
exit $fail
