#!/usr/bin/env python
"""Compile-cache wiring check (scripts/ci.sh stage).

Runs one tiny in-core GBT fit with the persistent XLA compile cache
pointed at ``DMLC_COMPILE_CACHE_DIR`` and prints the cache evidence as
one JSON line.  ``DMLC_COMPILE_CACHE_EXPECT`` asserts the outcome:

* ``miss`` — fresh dir: something must have been compiled AND written;
* ``hit``  — second process against the same dir: at least one program
  must have been served from disk, i.e. the wiring survives jax-version
  drift (cache key scheme, config names, event names).

ci.sh runs this twice against one mktemp dir — cold then warm — so the
cold-start contract (`doc/performance.md`) is guarded by CI, not only
by the in-process unit tests.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.utils import force_cpu_devices  # noqa: E402

force_cpu_devices(2)

import numpy as np  # noqa: E402


def main() -> int:
    expect = os.environ.get("DMLC_COMPILE_CACHE_EXPECT", "")
    cache_dir = os.environ.get("DMLC_COMPILE_CACHE_DIR", "")
    if not cache_dir:
        print("DMLC_COMPILE_CACHE_DIR must be set", file=sys.stderr)
        return 2

    from dmlc_core_tpu.base import compile_cache as cc
    from dmlc_core_tpu.models import HistGBT

    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    model = HistGBT(n_trees=2, max_depth=2, n_bins=8)
    model.fit(X, y)
    model.predict(X[:8])

    stats = cc.stats()
    entries = (len(os.listdir(cache_dir))
               if os.path.isdir(cache_dir) else 0)
    record = {"check": "compile_cache", "expect": expect,
              "cache_entries": entries, **stats}
    print(json.dumps(record))

    if stats["dir"] != cache_dir:
        print(f"FAIL: cache dir {stats['dir']!r} != requested "
              f"{cache_dir!r}", file=sys.stderr)
        return 1
    if expect == "miss" and not (stats["misses"] > 0 and entries > 0):
        print("FAIL: expected compile-cache misses + written entries "
              "on a cold dir", file=sys.stderr)
        return 1
    if expect == "hit" and not stats["hits"] > 0:
        print("FAIL: expected compile-cache hits on a warm dir "
              "(persistent cache wiring broken?)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
