"""Expert-parallel MoE tests (parallel/moe.py).

Oracles: the sharded all_to_all dispatch must equal a dense per-token
loop applying each token's expert (exact when capacity is loose); the
capacity rule must drop overflow tokens to zero; gradients must flow
(a toy routing problem learns).  SURVEY.md §2e lists EP absent upstream;
this is the beyond-parity row."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from dmlc_core_tpu.base.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_core_tpu.parallel.moe import moe_ffn, reference_moe_ffn


def _weights(rng, E, D, F):
    return (rng.normal(size=(D, E)).astype(np.float32) * 0.5,
            rng.normal(size=(E, D, F)).astype(np.float32) * 0.2,
            np.zeros((E, F), np.float32),
            rng.normal(size=(E, F, D)).astype(np.float32) * 0.2,
            np.zeros((E, D), np.float32))


def _run_sharded(x, wr, w1, b1, w2, b2, ep, cf):
    mesh = Mesh(np.asarray(jax.devices()[:ep]).reshape(ep), ("expert",))

    def fn(x, wr, w1, b1, w2, b2):
        y, aux = moe_ffn(x, wr, w1, b1, w2, b2, "expert", cf)
        return y, lax.pmean(aux, "expert")

    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P(), P(), P("expert"), P("expert"), P("expert"),
                  P("expert")),
        out_specs=(P(), P()), check_vma=False))(
        jnp.asarray(x), jnp.asarray(wr), jnp.asarray(w1),
        jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2))


class TestMoE:
    @pytest.mark.parametrize("ep", [2, 4])
    def test_matches_dense_oracle(self, rng, ep):
        T, D, F, E = 32, 8, 16, 8
        x = rng.normal(size=(T, D)).astype(np.float32)
        wr, w1, b1, w2, b2 = _weights(rng, E, D, F)
        y, aux = _run_sharded(x, wr, w1, b1, w2, b2, ep, cf=100.0)
        want = reference_moe_ffn(x, wr, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(y), want, rtol=2e-4,
                                   atol=1e-5)
        assert float(aux) > 0

    def test_unsharded_matches_oracle(self, rng):
        T, D, F, E = 24, 6, 12, 4
        x = rng.normal(size=(T, D)).astype(np.float32)
        wr, w1, b1, w2, b2 = _weights(rng, E, D, F)
        y, _ = moe_ffn(jnp.asarray(x), jnp.asarray(wr), jnp.asarray(w1),
                       jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2),
                       axis=None, capacity_factor=100.0)
        want = reference_moe_ffn(x, wr, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(y), want, rtol=2e-4,
                                   atol=1e-5)

    def test_capacity_drops_match_oracle(self, rng):
        # route EVERYTHING to expert 0 via a biased router: with
        # cf·T/E = 2 slots, all but 2 tokens must drop to exactly zero
        T, D, F, E = 16, 4, 8, 4
        x = np.abs(rng.normal(size=(T, D))).astype(np.float32)
        wr, w1, b1, w2, b2 = _weights(rng, E, D, F)
        wr = np.zeros_like(wr)
        wr[:, 0] = 1.0                      # expert 0 wins every token
        cf = 0.5                            # cap = ceil(0.5·16/4) = 2
        y, _ = moe_ffn(jnp.asarray(x), jnp.asarray(wr), jnp.asarray(w1),
                       jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2),
                       axis=None, capacity_factor=cf)
        want = reference_moe_ffn(x, wr, w1, b1, w2, b2, capacity_factor=cf)
        np.testing.assert_allclose(np.asarray(y), want, rtol=2e-4,
                                   atol=1e-5)
        assert np.all(np.asarray(y)[2:] == 0)     # dropped → zeros
        assert np.any(np.asarray(y)[:2] != 0)

    def test_gradients_flow_and_learn(self, rng):
        # toy: tokens in 2 clusters, target = cluster-specific linear
        # map; a 2-expert MoE must beat its starting loss by a lot
        T, D, F, E, ep = 32, 4, 8, 2, 2
        mesh = Mesh(np.asarray(jax.devices()[:ep]).reshape(ep), ("expert",))
        x = rng.normal(size=(T, D)).astype(np.float32)
        x[: T // 2] += 3.0
        A0 = rng.normal(size=(D, D)).astype(np.float32)
        A1 = -A0
        target = np.concatenate([x[: T // 2] @ A0, x[T // 2:] @ A1])
        params = dict(zip("rabcd", (
            jnp.asarray(rng.normal(size=(D, E)).astype(np.float32) * 0.1),
            jnp.asarray(rng.normal(size=(E, D, F)).astype(np.float32) * 0.3),
            jnp.zeros((E, F)),
            jnp.asarray(rng.normal(size=(E, F, D)).astype(np.float32) * 0.3),
            jnp.zeros((E, D)))))

        def loss_fn(ps, x, t):
            y, aux = moe_ffn(x, ps["r"], ps["a"], ps["b"], ps["c"],
                             ps["d"], "expert", 4.0)
            return jnp.mean((y - t) ** 2) + 0.01 * aux

        step = jax.jit(shard_map(
            lambda ps, x, t: jax.tree.map(
                lambda p, g: p - 0.05 * g, ps,
                jax.grad(lambda q: lax.pmean(loss_fn(q, x, t), "expert")
                         )(ps)),
            mesh=mesh,
            in_specs=({"r": P(), "a": P("expert"), "b": P("expert"),
                       "c": P("expert"), "d": P("expert")}, P(), P()),
            out_specs={"r": P(), "a": P("expert"), "b": P("expert"),
                       "c": P("expert"), "d": P("expert")},
            check_vma=False))

        eval_loss = jax.jit(shard_map(
            lambda ps, x, t: lax.pmean(loss_fn(ps, x, t), "expert"),
            mesh=mesh,
            in_specs=({"r": P(), "a": P("expert"), "b": P("expert"),
                       "c": P("expert"), "d": P("expert")}, P(), P()),
            out_specs=P(), check_vma=False))
        xj, tj = jnp.asarray(x), jnp.asarray(target)
        first = last = None
        for _ in range(60):
            cur = float(eval_loss(params, xj, tj))
            first = cur if first is None else first
            last = cur
            params = step(params, xj, tj)
        assert last < first * 0.5, (first, last)
