"""GBLinear (linear booster) tests.

Oracles: near-recovery of a known linear model; logistic accuracy on
separable data; L1 soft-threshold zeroing noise features; 8-device-mesh
vs 1-device exact equivalence (the psum'd [F] reductions are the only
collectives); checkpoint round-trip."""

import numpy as np

import jax
from jax.sharding import Mesh

from dmlc_core_tpu.models import GBLinear
from dmlc_core_tpu.parallel.mesh import local_mesh


def _linear_problem(n=4000, F=8, seed=0, noise=0.05):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    w = np.linspace(1.0, -1.0, F).astype(np.float32)
    yc = X @ w + 0.3 + noise * rng.normal(size=n)
    return X, yc.astype(np.float32), w


class TestGBLinear:
    def test_regression_recovers_weights(self):
        X, yc, w = _linear_problem()
        m = GBLinear(n_rounds=200, objective="reg:squarederror",
                     reg_lambda=1e-3, learning_rate=0.5)
        m.fit(X, yc)
        np.testing.assert_allclose(m.weights, w, atol=0.05)
        assert abs(m.bias - 0.3) < 0.05
        r2 = 1 - np.var(yc - m.predict(X)) / np.var(yc)
        assert r2 > 0.99, r2

    def test_logistic_separable(self):
        X, yc, _ = _linear_problem(noise=0.0)
        y = (yc > 0.3).astype(np.float32)
        m = GBLinear(n_rounds=150, objective="binary:logistic")
        m.fit(X, y)
        acc = float(((m.predict(X) > 0.5) == (y > 0.5)).mean())
        assert acc > 0.97, acc

    def test_l1_zeroes_noise_features(self):
        rng = np.random.default_rng(1)
        n = 4000
        X = rng.normal(size=(n, 6)).astype(np.float32)
        yc = (2.0 * X[:, 0] - 1.5 * X[:, 1]).astype(np.float32)  # 4 dead cols
        m = GBLinear(n_rounds=300, objective="reg:squarederror",
                     reg_alpha=50.0, reg_lambda=1e-3)
        m.fit(X, yc)
        assert np.all(np.abs(m.weights[2:]) < 1e-3), m.weights
        assert abs(m.weights[0]) > 1.0 and abs(m.weights[1]) > 1.0

    def test_dead_column_with_zero_lambda(self):
        # all-zero feature + reg_lambda=0 → per-coordinate denom is 0;
        # the coordinate must stay put (XGBoost's vanishing-hessian
        # guard), not poison the model with NaN
        X, yc, _ = _linear_problem(n=1000, F=4)
        X = np.concatenate([X, np.zeros((len(X), 1), np.float32)], axis=1)
        m = GBLinear(n_rounds=50, objective="reg:squarederror",
                     reg_lambda=0.0)
        m.fit(X, yc)
        assert np.isfinite(m.weights).all(), m.weights
        assert m.weights[-1] == 0.0
        r2 = 1 - np.var(yc - m.predict(X)) / np.var(yc)
        assert r2 > 0.99, r2

    def test_weighted_rows(self):
        # rows with weight 0 must not influence the fit
        X, yc, _ = _linear_problem(n=2000)
        X2 = np.concatenate([X, 100 * np.ones((50, X.shape[1]), np.float32)])
        y2 = np.concatenate([yc, -100 * np.ones(50, np.float32)])
        w2 = np.concatenate([np.ones(len(yc), np.float32),
                             np.zeros(50, np.float32)])
        m_ref = GBLinear(n_rounds=60, objective="reg:squarederror")
        m_ref.fit(X, yc)
        m_w = GBLinear(n_rounds=60, objective="reg:squarederror")
        m_w.fit(X2, y2, weight=w2)
        np.testing.assert_allclose(m_w.weights, m_ref.weights, atol=1e-5)

    def test_mesh_matches_single_device(self):
        X, yc, _ = _linear_problem(n=2048)
        y = (yc > 0.3).astype(np.float32)
        kw = dict(n_rounds=30, objective="binary:logistic")
        m8 = GBLinear(mesh=local_mesh(), **kw)   # conftest: 8 devices
        m8.fit(X, y)
        m1 = GBLinear(mesh=Mesh(np.asarray(jax.devices()[:1]), ("data",)),
                      **kw)
        m1.fit(X, y)
        np.testing.assert_allclose(m8.weights, m1.weights, rtol=2e-4,
                                   atol=2e-6)
        np.testing.assert_allclose(m8.bias, m1.bias, rtol=2e-4, atol=2e-6)

    def test_fit_iter_matches_in_core(self, tmp_path):
        # LibSVM pages through RowBlockIter must train the same model
        # as the dense in-core path
        from dmlc_core_tpu.data.iter import RowBlockIter

        X, yc, _ = _linear_problem(n=1200, F=4)
        y = (yc > 0.3).astype(np.float32)
        svm = tmp_path / "lin.svm"
        with open(svm, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(4))
                f.write(f"{int(y[i])} {feats}\n")
        it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
        m_it = GBLinear(n_rounds=40, objective="binary:logistic")
        m_it.fit_iter(it, num_col=4)
        it.close()
        m_core = GBLinear(n_rounds=40, objective="binary:logistic")
        m_core.fit(X, y)
        np.testing.assert_allclose(m_it.weights, m_core.weights,
                                   rtol=1e-4, atol=1e-5)

    def test_fit_iter_small_slabs_match_one_put(self, tmp_path):
        """Streaming device assembly (rows_per_upload smaller than a
        page, forcing many donated slab writes incl. a partial tail)
        must produce the exact model of the one-put dense path."""
        from dmlc_core_tpu.data.iter import RowBlockIter

        X, yc, _ = _linear_problem(n=1100, F=5)
        y = (yc > 0.3).astype(np.float32)
        svm = tmp_path / "lin.svm"
        with open(svm, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(5))
                f.write(f"{int(y[i])} {feats}\n")
        it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
        m_it = GBLinear(n_rounds=30, objective="binary:logistic")
        m_it.fit_iter(it, num_col=5, rows_per_upload=256)  # 4 full + tail
        it.close()
        m_core = GBLinear(n_rounds=30, objective="binary:logistic")
        m_core.fit(X, y)
        np.testing.assert_allclose(m_it.weights, m_core.weights,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(m_it.bias, m_core.bias,
                                   rtol=1e-4, atol=1e-5)

    def test_bfloat16_features_match_f32_oracle(self):
        """feature_dtype=bfloat16 (half the H2D bytes at 50M scale) must
        land within the damped coordinate step's tolerance of the f32
        fit: same support/signs, close weights, matching predictions."""
        X, yc, _ = _linear_problem(n=4000, F=6)
        y = (yc > 0.3).astype(np.float32)
        f32 = GBLinear(n_rounds=60, objective="binary:logistic")
        f32.fit(X, y)
        bf16 = GBLinear(n_rounds=60, objective="binary:logistic",
                        feature_dtype="bfloat16")
        bf16.fit(X, y)
        np.testing.assert_allclose(bf16.weights, f32.weights,
                                   rtol=0.05, atol=0.02)
        agree = ((bf16.predict(X) > 0.5) == (f32.predict(X) > 0.5)).mean()
        assert agree > 0.99, agree

    def test_save_load_roundtrip(self, tmp_path):
        X, yc, _ = _linear_problem(n=1000)
        m = GBLinear(n_rounds=20, objective="reg:squarederror")
        m.fit(X, yc)
        uri = str(tmp_path / "lin.ckpt")
        m.save_model(uri)
        m2 = GBLinear.load_model(uri)
        np.testing.assert_allclose(m2.predict(X), m.predict(X), rtol=1e-6)

    def test_chunk_evidence_recorded(self):
        X, yc, _ = _linear_problem(n=512)
        m = GBLinear(n_rounds=30, objective="reg:squarederror")
        m.fit(X, yc, warmup_rounds=1)
        assert m.last_chunk_times[-1][0] == 30
        assert m.last_warmup_seconds > 0


class TestScalePosWeightLinear:
    def test_equals_explicit_weights_exactly(self):
        from dmlc_core_tpu.models.linear import GBLinear

        rng = np.random.default_rng(3)
        X = rng.normal(size=(1500, 6)).astype(np.float32)
        y = (X[:, 0] > np.quantile(X[:, 0], 0.9)).astype(np.float32)
        spw = 9.0
        a = GBLinear(n_rounds=30, scale_pos_weight=spw)
        a.fit(X, y)
        b = GBLinear(n_rounds=30)
        b.fit(X, y, weight=np.where(y == 1.0, np.float32(spw),
                                    np.float32(1.0)))
        np.testing.assert_allclose(a.weights, b.weights, rtol=1e-6)
        assert a.bias == b.bias

    def test_rejected_for_regression(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models.linear import GBLinear

        rng = np.random.default_rng(3)
        X = rng.normal(size=(200, 3)).astype(np.float32)
        y = X[:, 0].astype(np.float32)
        m = GBLinear(n_rounds=5, objective="reg:squarederror",
                     scale_pos_weight=2.0)
        with pytest.raises(Error):
            m.fit(X, y)
