"""Resilience layer: retry policies, circuit breaking, deterministic
fault injection, checkpoint durability, producer restart, tracker grace
— and the chaos soak that runs train + serve traffic under live faults.

The contract under test (doc/robustness.md): with faults active the
system may retry, shed or fall back, but it must never return a WRONG
answer — and every absorbed fault must leave metric evidence.
"""

import os
import threading
import time

import numpy as np
import pytest

from dmlc_core_tpu.base import faultinject as fi
from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.base.metrics import default_registry
from dmlc_core_tpu.base.resilience import (CircuitBreaker, CircuitOpenError,
                                           RetryPolicy)
from dmlc_core_tpu.io.threaded_iter import ThreadedIter
from dmlc_core_tpu.parallel.checkpoint import checkpoint, load_checkpoint
from dmlc_core_tpu.tracker.tracker import RabitTracker, WorkerSession


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("base_backoff_s", 0.001)
        kw.setdefault("sleep", lambda s: None)
        return RetryPolicy(**kw)

    def test_retries_until_success(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionResetError("blip")
            return "ok"

        p = self._policy(max_attempts=5,
                         retryable=lambda e: isinstance(e, ConnectionError))
        assert p.run(flaky, op="t") == "ok"
        assert calls["n"] == 3

    def test_non_retryable_raises_immediately(self):
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise ValueError("logic bug")

        p = self._policy(max_attempts=5,
                         retryable=lambda e: isinstance(e, ConnectionError))
        with pytest.raises(ValueError):
            p.run(bad)
        assert calls["n"] == 1

    def test_budget_exhaustion_reraises_last_error_unwrapped(self):
        def always():
            raise ConnectionResetError("down hard")

        p = self._policy(max_attempts=3, retryable=lambda e: True)
        with pytest.raises(ConnectionResetError, match="down hard"):
            p.run(always)

    def test_full_jitter_bounds_and_growth(self):
        import random
        p = RetryPolicy(base_backoff_s=0.1, max_backoff_s=1.0,
                        rng=random.Random(0))
        for attempt in range(1, 12):
            cap = min(1.0, 0.1 * 2 ** (attempt - 1))
            for _ in range(50):
                d = p.backoff_for(attempt)
                assert 0.0 <= d <= cap

    def test_retry_after_overrides_backoff(self):
        p = self._policy(retry_after_cap_s=2.0)
        assert p.backoff_for(1, retry_after=0.5) == 0.5
        assert p.backoff_for(1, retry_after=100.0) == 2.0  # capped
        assert p.backoff_for(1, retry_after=-3.0) == 0.0   # clamped

    def test_retry_after_attribute_consumed(self):
        slept = []
        calls = {"n": 0}

        class Hinted(IOError):
            retry_after = 0.123

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise Hinted()
            return 1

        p = RetryPolicy(max_attempts=3, base_backoff_s=0.001,
                        sleep=slept.append, retryable=lambda e: True)
        assert p.run(flaky) == 1
        assert slept == [0.123]

    def test_deadline_caps_total_time(self):
        def always():
            raise IOError("x")

        # huge attempt budget but a deadline that the first backoff blows
        p = RetryPolicy(max_attempts=10_000, deadline_s=0.0,
                        base_backoff_s=10.0, sleep=lambda s: None,
                        retryable=lambda e: True)
        calls = {"n": 0}

        def counting():
            calls["n"] += 1
            raise IOError("x")

        with pytest.raises(IOError):
            p.run(counting)
        assert calls["n"] <= 2

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("DMLC_RETRY_MAX_ATTEMPTS", "7")
        monkeypatch.setenv("DMLC_RETRY_DEADLINE_S", "3.5")
        p = RetryPolicy.from_env()
        assert p.max_attempts == 7 and p.deadline_s == 3.5
        # explicit overrides win over env
        assert RetryPolicy.from_env(max_attempts=2).max_attempts == 2

    def test_metrics_evidence(self):
        reg = default_registry()
        c = reg.counter("retries_total", labels=("op",))
        before = c.value(op="evidence_op")
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise IOError("x")
            return 1

        self._policy(max_attempts=5, retryable=lambda e: True).run(
            flaky, op="evidence_op")
        assert c.value(op="evidence_op") == before + 2


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_trips_after_threshold_and_sheds(self):
        cb = CircuitBreaker("t1", failure_threshold=3, reset_timeout_s=100)

        def boom():
            raise IOError("down")

        for _ in range(3):
            with pytest.raises(IOError):
                cb.call(boom)
        assert cb.state == "open"
        with pytest.raises(CircuitOpenError):
            cb.call(lambda: 1)

    def test_half_open_probe_closes_on_success(self):
        t = {"now": 0.0}
        cb = CircuitBreaker("t2", failure_threshold=1, reset_timeout_s=5.0,
                            clock=lambda: t["now"])
        with pytest.raises(IOError):
            cb.call(lambda: (_ for _ in ()).throw(IOError("x")))
        assert cb.state == "open"
        t["now"] = 6.0
        assert cb.state == "half_open"
        assert cb.call(lambda: 42) == 42
        assert cb.state == "closed"

    def test_half_open_probe_failure_reopens(self):
        t = {"now": 0.0}
        cb = CircuitBreaker("t3", failure_threshold=1, reset_timeout_s=5.0,
                            clock=lambda: t["now"])
        with pytest.raises(IOError):
            cb.call(lambda: (_ for _ in ()).throw(IOError("x")))
        t["now"] = 6.0
        with pytest.raises(IOError):
            cb.call(lambda: (_ for _ in ()).throw(IOError("y")))
        assert cb.state == "open"
        # a second window is required before the next probe
        with pytest.raises(CircuitOpenError):
            cb.call(lambda: 1)

    def test_success_resets_consecutive_count(self):
        cb = CircuitBreaker("t4", failure_threshold=2, reset_timeout_s=100)
        for _ in range(5):
            with pytest.raises(IOError):
                cb.call(lambda: (_ for _ in ()).throw(IOError("x")))
            cb.call(lambda: 1)  # success between failures
        assert cb.state == "closed"

    def test_state_gauge_published(self):
        reg = default_registry()
        g = reg.gauge("circuit_state", labels=("circuit",))
        cb = CircuitBreaker("gauge_t", failure_threshold=1,
                            reset_timeout_s=100)
        assert g.value(circuit="gauge_t") == 0
        with pytest.raises(IOError):
            cb.call(lambda: (_ for _ in ()).throw(IOError("x")))
        assert g.value(circuit="gauge_t") == 1


# ---------------------------------------------------------------------------
# faultinject
# ---------------------------------------------------------------------------

class TestFaultInject:
    def test_spec_parsing_and_fields(self):
        with fi.inject("http:error=503:p=0.5:n=3:after=2"):
            rule = fi._RULES[0]
            assert (rule.point, rule.kind, rule.value) == ("http", "error",
                                                           "503")
            assert (rule.p, rule.n, rule.after) == (0.5, 3, 2)

    def test_bad_spec_raises(self):
        with pytest.raises(ValueError):
            fi.configure("http")  # no kind
        with pytest.raises(ValueError):
            fi.configure("http:error:bogus=1")
        fi.configure("")  # restore

    def test_deterministic_given_seed(self):
        with fi.inject("x:error:p=0.3", seed=42):
            a = [fi.check("x") is not None for _ in range(50)]
        with fi.inject("x:error:p=0.3", seed=42):
            b = [fi.check("x") is not None for _ in range(50)]
        assert a == b and 0 < sum(a) < 50

    def test_n_and_after_budgets(self):
        with fi.inject("pt:error:n=2:after=3"):
            fires = [fi.check("pt") is not None for _ in range(10)]
        assert fires == [False] * 3 + [True, True] + [False] * 5

    def test_point_isolation_and_counter(self):
        reg = default_registry()
        c = reg.counter("faults_injected_total", labels=("point", "kind"))
        before = c.value(point="only_this", kind="error")
        with fi.inject("only_this:error"):
            assert fi.check("other_point") is None
            assert fi.check("only_this") is not None
            assert fi.fired_total() == 1
        assert c.value(point="only_this", kind="error") == before + 1

    def test_env_driven_configuration(self, monkeypatch):
        monkeypatch.setenv("DMLC_FAULT_INJECT", "envpt:error=500")
        assert fi.active()
        f = fi.check("envpt")
        assert f is not None and f.int_value(0) == 500
        monkeypatch.delenv("DMLC_FAULT_INJECT")
        assert not fi.active()
        assert fi.check("envpt") is None

    def test_nested_inject_restores(self):
        with fi.inject("a:error"):
            with fi.inject("b:error"):
                assert fi.check("a") is None
                assert fi.check("b") is not None
            assert fi.check("a") is not None


class TestChaosSchedule:
    """Wall-clock chaos scheduling (``at=``/``every=``): a rule arms at
    an absolute offset from configure(), ``every=`` re-arms it once per
    wave window, and the whole schedule is a pure function of
    ``(spec, seed)`` — the prodsim drill's determinism contract."""

    @pytest.fixture(autouse=True)
    def _fake_clock(self):
        self.now = {"t": 100.0}
        fi.set_clock(lambda: self.now["t"])
        yield
        fi.set_clock(None)

    def _advance(self, dt):
        self.now["t"] += dt

    def test_grammar_roundtrip_via_rules(self):
        with fi.inject("launch_host:wave=0.3:at=5:every=2.5:n=3:p=0.5"):
            (r,) = fi.rules()
        assert (r["point"], r["kind"], r["value"]) == ("launch_host",
                                                       "wave", "0.3")
        assert (r["at"], r["every"], r["n"], r["p"]) == (5.0, 2.5, 3, 0.5)
        assert (r["checked"], r["fires"]) == (0, 0)

    def test_bad_at_every_raise(self):
        for spec in ("p:kill:at=soon", "p:kill:at=-1",
                     "p:kill:every=never", "p:kill:every=0"):
            with pytest.raises(ValueError):
                fi.configure(spec)
        fi.configure("")  # restore

    def test_at_gates_on_wall_clock(self):
        with fi.inject("p:kill:at=2:n=1"):
            assert fi.check("p") is None         # t=0: not armed yet
            self._advance(1.9)
            assert fi.check("p") is None         # t=1.9: still early
            self._advance(0.2)
            assert fi.check("p") is not None     # t=2.1: armed
            assert fi.check("p") is None         # n=1 budget spent

    def test_every_draws_once_per_wave(self):
        with fi.inject("p:kill:at=1:every=2:n=3"):
            assert fi.check("p") is None         # before at=
            self._advance(1.0)
            assert fi.check("p") is not None     # wave 0 fires
            assert fi.check("p") is None         # same wave: ONE draw
            self._advance(2.0)
            assert fi.check("p") is not None     # wave 1
            self._advance(2.0)
            assert fi.check("p") is not None     # wave 2
            self._advance(2.0)
            assert fi.check("p") is None         # n=3 budget exhausted
            (r,) = fi.rules()
            assert r["fires"] == 3 and r["last_wave"] >= 2

    def test_same_seed_same_schedule(self):
        def run(seed):
            fired = []
            with fi.inject("p:kill:p=0.5:every=1", seed=seed):
                for _ in range(40):
                    self._advance(1.0)
                    fired.append(fi.check("p") is not None)
            return fired

        a, b, c = run(7), run(7), run(8)
        assert a == b and 0 < sum(a) < 40       # deterministic, not flat
        assert a != c                           # seed actually matters

    def test_inject_restores_epoch(self):
        with fi.inject("outer:kill:at=5"):
            self._advance(10.0)
            with fi.inject("inner:kill:at=100"):
                # inner anchors its OWN epoch at entry: nothing elapsed
                assert fi.check("inner") is None
                assert fi.check("outer") is None
            # outer epoch restored: 10s elapsed >= at=5
            assert fi.check("outer") is not None


# ---------------------------------------------------------------------------
# ThreadedIter producer restart
# ---------------------------------------------------------------------------

class TestProducerRestart:
    def test_default_propagates_exactly_as_before(self):
        def next_fn(_cell):
            raise ValueError("producer blew up")

        it = ThreadedIter()
        it.init(next_fn)
        with pytest.raises(ValueError, match="producer blew up"):
            it.next()
        it.destroy()

    def test_bounded_restart_absorbs_flaky_reads(self):
        state = {"i": 0}

        def next_fn(_cell):
            state["i"] += 1
            if state["i"] in (2, 4):     # two transient failures
                raise IOError("flaky read")
            if state["i"] > 6:
                return None
            return state["i"]

        it = ThreadedIter(max_capacity=2, name="restart_t", max_restarts=2)
        it.init(next_fn)
        # failed items are skipped, the stream continues to its end
        assert list(it) == [1, 3, 5, 6]
        reg = default_registry()
        c = reg.counter("threaded_iter_producer_restarts_total",
                        labels=("iter",))
        assert c.value(iter="restart_t") == 2
        it.destroy()

    def test_restart_budget_exhaustion_propagates(self):
        def next_fn(_cell):
            raise IOError("always broken")

        it = ThreadedIter(max_restarts=3)
        it.init(next_fn)
        with pytest.raises(IOError, match="always broken"):
            it.next()
        it.destroy()

    def test_env_default(self, monkeypatch):
        monkeypatch.setenv("DMLC_ITER_PRODUCER_RESTARTS", "5")
        assert ThreadedIter().max_restarts == 5
        monkeypatch.delenv("DMLC_ITER_PRODUCER_RESTARTS")
        assert ThreadedIter().max_restarts == 0

    def test_iter_fault_point(self):
        state = {"i": 0}

        def next_fn(_cell):
            state["i"] += 1
            return state["i"] if state["i"] <= 4 else None

        with fi.inject("iter:error:n=1"):
            it = ThreadedIter(max_capacity=2, name="fault_t", max_restarts=1)
            it.init(next_fn)
            out = list(it)
            it.destroy()
        # one injected producer fault was absorbed; no items were lost
        # (the fault fires before next_fn runs, so no source item burns)
        assert out == [1, 2, 3, 4]


# ---------------------------------------------------------------------------
# checkpoint durability
# ---------------------------------------------------------------------------

class TestCheckpointDurability:
    def _like(self):
        return {"w": np.zeros(16, np.float32), "round": 0}

    def _state(self, k):
        return {"w": np.full(16, float(k), np.float32), "round": k}

    def test_abort_mid_write_preserves_previous(self, tmp_path):
        uri = str(tmp_path / "ck")
        checkpoint(uri, self._state(1), version=1)
        with fi.inject("checkpoint:abort"):
            with pytest.raises(IOError, match="fault injected"):
                checkpoint(uri, self._state(2), version=2)
        v, st = load_checkpoint(uri, self._like())
        assert v == 1 and st["round"] == 1
        assert np.array_equal(st["w"], self._state(1)["w"])

    def test_corrupt_primary_falls_back_to_prev(self, tmp_path):
        uri = str(tmp_path / "ck")
        checkpoint(uri, self._state(1), version=1)
        checkpoint(uri, self._state(2), version=2)
        reg = default_registry()
        fb = reg.counter("checkpoint_fallbacks_total")
        before = fb.value()
        with open(uri, "r+b") as f:
            size = os.path.getsize(uri)
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
        v, st = load_checkpoint(uri, self._like())
        assert v == 1 and st["round"] == 1
        assert fb.value() == before + 1

    def test_injected_corruption_detected_by_crc(self, tmp_path):
        uri = str(tmp_path / "ck")
        checkpoint(uri, self._state(1), version=1)
        with fi.inject("checkpoint-post:corrupt"):
            checkpoint(uri, self._state(2), version=2)
        v, st = load_checkpoint(uri, self._like())
        assert v == 1 and st["round"] == 1

    def test_all_candidates_corrupt_raises(self, tmp_path):
        uri = str(tmp_path / "ck")
        checkpoint(uri, self._state(1), version=1)
        for path in (uri, uri + ".prev"):
            if os.path.exists(path):
                with open(path, "r+b") as f:
                    f.seek(0)
                    f.write(b"\x00\x00\x00\x00")
        with pytest.raises(Error, match="no valid version"):
            load_checkpoint(uri, self._like())

    def test_missing_is_still_version_zero(self, tmp_path):
        v, st = load_checkpoint(str(tmp_path / "never"), self._like())
        assert v == 0 and st["round"] == 0

    def test_keep_disabled_no_prev(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DMLC_CKPT_KEEP", "0")
        uri = str(tmp_path / "ck")
        checkpoint(uri, self._state(1), version=1)
        checkpoint(uri, self._state(2), version=2)
        assert not os.path.exists(uri + ".prev")
        v, _ = load_checkpoint(uri, self._like())
        assert v == 2

    def test_no_tmp_litter_after_clean_save(self, tmp_path):
        uri = str(tmp_path / "ck")
        checkpoint(uri, self._state(1), version=1)
        litter = [f for f in os.listdir(tmp_path) if ".tmp" in f]
        assert litter == []

    def test_sidecar_travels_with_prev(self, tmp_path):
        uri = str(tmp_path / "ck")
        checkpoint(uri, self._state(1), version=1)
        checkpoint(uri, self._state(2), version=2)
        assert os.path.exists(uri + ".crc")
        assert os.path.exists(uri + ".prev.crc")

    def test_mem_backend_fallback(self):
        from dmlc_core_tpu.io.filesystem import MemoryFileSystem

        uri = "mem:///resil/ck"
        like = self._like()
        checkpoint(uri, self._state(1), version=1)
        checkpoint(uri, self._state(2), version=2)
        blob = MemoryFileSystem._files["/resil/ck"]
        blob[len(blob) // 2] ^= 0xFF
        v, st = load_checkpoint(uri, like)
        assert v == 1 and st["round"] == 1


# ---------------------------------------------------------------------------
# tracker reconnect grace
# ---------------------------------------------------------------------------

class TestTrackerGrace:
    def _wait_for(self, cond, timeout=5.0):
        t0 = time.time()
        while time.time() - t0 < timeout:
            if cond():
                return True
            time.sleep(0.02)
        return False

    def test_reconnect_within_grace_is_not_a_death(self):
        tracker = RabitTracker(nworker=2, grace_s=30.0)
        tracker.start()
        w0 = WorkerSession("127.0.0.1", tracker.port, host="h0")
        rank = w0.info["rank"]
        w0.close()  # crash without shutdown
        assert self._wait_for(lambda: tracker.lost_ranks() == [rank])
        assert tracker.dead_workers == []
        # a NEW worker must not be handed the reserved rank
        other = WorkerSession("127.0.0.1", tracker.port, host="h1")
        assert other.info["rank"] != rank
        # the restarted worker reclaims it
        back = WorkerSession("127.0.0.1", tracker.port, cmd="recover",
                             rank=rank, host="h0")
        assert back.info["rank"] == rank
        assert tracker.lost_ranks() == []
        assert tracker.dead_workers == []
        tracker.stop()

    def test_grace_expiry_frees_rank(self):
        tracker = RabitTracker(nworker=2, grace_s=0.15)
        tracker.start()
        w0 = WorkerSession("127.0.0.1", tracker.port, host="h0")
        rank = w0.info["rank"]
        w0.close()
        assert self._wait_for(lambda: tracker.lost_ranks() == [rank],
                              timeout=0.1) or True
        time.sleep(0.3)
        assert tracker.lost_ranks() == []
        assert tracker.dead_workers == [rank]
        # rank now genuinely free: a new start inherits it
        w1 = WorkerSession("127.0.0.1", tracker.port, host="h1")
        assert w1.info["rank"] == rank
        tracker.stop()

    def test_zero_grace_is_immediate_death(self):
        tracker = RabitTracker(nworker=1, grace_s=0.0)
        tracker.start()
        w0 = WorkerSession("127.0.0.1", tracker.port)
        rank = w0.info["rank"]
        w0.close()
        assert self._wait_for(lambda: tracker.dead_workers == [rank])
        assert tracker.lost_ranks() == []
        tracker.stop()

    def test_env_default(self, monkeypatch):
        monkeypatch.setenv("DMLC_TRACKER_GRACE_S", "12.5")
        t = RabitTracker(nworker=1)
        assert t.grace_s == 12.5
        t.stop()


# ---------------------------------------------------------------------------
# chaos soak: train + serve under live fault injection (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_train_and_serve():
    """Train HistGBT, serve it over HTTP with the ``serve`` fault point
    firing 503s, drive concurrent ResilientClients: every answered
    request must be bit-identical to ``model.predict`` (zero wrong
    answers — retried/shed only), and the fault counter must be > 0.

    The soak doubles as the validation workload for the dynamic
    lock-order verifier (``base/lockcheck`` — what dmlcheck's static
    ``lock-discipline`` pass claims, this observes): every lock created
    during the run joins the cross-thread order graph, and the run must
    finish with ZERO cycles.  ``DMLC_LOCKCHECK=1`` pre-installs the
    verifier at import and widens coverage to import-time singletons;
    otherwise it is installed here for the soak's duration.

    The happens-before race detector (``base/racecheck``) rides the
    same workload: registry hot-swap state, batcher queue handoffs and
    client threads all cross under faults, and the run must finish with
    ZERO unordered shared-attribute access pairs.

    The resource-leak tracer (``base/leakcheck``) rides it too: every
    socket/thread/subprocess/tempfile the soak creates must be dead by
    teardown (the report is archived to ``SOAK_LEAKCHECK_OUT``,
    default ``/tmp/soak_leakcheck.json``)."""
    from dmlc_core_tpu.base import leakcheck, lockcheck, racecheck
    from dmlc_core_tpu.models.histgbt import HistGBT
    from dmlc_core_tpu.serve import ModelRegistry, ResilientClient, \
        ServeFrontend

    we_installed = not lockcheck.installed()
    if we_installed:
        lockcheck.install()
    rc_installed = not racecheck.installed()
    if rc_installed:
        racecheck.install()
    lc_installed = not leakcheck.installed()
    if lc_installed:
        leakcheck.install()
    leakcheck.reset()

    rng = np.random.default_rng(0)
    X = rng.standard_normal((512, 8)).astype(np.float32)
    y = (X[:, 0] * 1.5 - X[:, 3] + rng.standard_normal(512) * 0.1
         ).astype(np.float32)
    model = HistGBT(n_trees=8, max_depth=3, n_bins=32)
    model.fit(X, y)

    reg = ModelRegistry("chaos", max_batch=64)
    reg.publish(model)
    _, runner = reg.current()

    queries = [rng.standard_normal((k % 5 + 1, 8)).astype(np.float32)
               for k in range(40)]
    expected = [np.asarray(runner.predict(q)) for q in queries]

    wrong, answered, shed = [], [0], [0]
    lock = threading.Lock()

    with ServeFrontend(reg, max_batch=64, max_delay=0.001) as fe:
        policy = RetryPolicy(max_attempts=8, base_backoff_s=0.005,
                             deadline_s=30.0)
        with fi.inject("serve:error=503:p=0.25", seed=99):
            def worker(idx0):
                client = ResilientClient(fe.url, policy=policy)
                for i in range(idx0, len(queries), 4):
                    try:
                        preds, _version = client.predict(queries[i])
                    except Exception:  # noqa: BLE001 — shed, not wrong
                        with lock:
                            shed[0] += 1
                        continue
                    with lock:
                        answered[0] += 1
                        if not np.array_equal(preds.astype(np.float32),
                                              expected[i].astype(np.float32)):
                            wrong.append(i)

            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            faults = fi.fired_total()

    race_list = racecheck.races()
    leakcheck.write_report(os.environ.get("SOAK_LEAKCHECK_OUT",
                                          "/tmp/soak_leakcheck.json"))
    leak_list = leakcheck.leaks()
    leakcheck.reset()
    if lc_installed:
        leakcheck.uninstall()
    if rc_installed:
        racecheck.uninstall()
    if we_installed:
        lockcheck.uninstall()
    assert lockcheck.violations() == [], (
        f"lock-order cycles under chaos: {lockcheck.violations()}")
    assert race_list == [], (
        f"happens-before races under chaos: {race_list}")
    assert leak_list == [], (
        f"live resource leaks under chaos: {leak_list}")
    assert wrong == [], f"wrong answers under chaos: {wrong}"
    assert faults > 0, "chaos soak injected nothing"
    assert answered[0] > 0, "every request shed — retry layer is dead"
    c = default_registry().counter("faults_injected_total",
                                   labels=("point", "kind"))
    assert c.value(point="serve", kind="error") > 0
