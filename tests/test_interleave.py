"""interleave (schedule-exploration model checker) contracts.

Two layers of trust: the SCHEDULER itself must be deterministic,
replayable and deadlock-aware (else "explored N schedules" means
nothing), and the four built-in models must both PASS on today's code
and FAIL when the code is deliberately re-broken — the re-broken
CircuitBreaker probe race (PR 5's bug, reintroduced via monkeypatch)
is the canary proving the explorer actually reaches the interleavings
that matter.
"""

from __future__ import annotations

import logging

import pytest

from dmlc_core_tpu.analysis import interleave as ilv
from dmlc_core_tpu.base.logging import _logger as _dmlc_logger


@pytest.fixture(autouse=True)
def _quiet_models():
    """Hundreds of runs per test: breaker OPEN warnings and registry
    publish INFO lines would drown the report."""
    before = _dmlc_logger.level
    _dmlc_logger.setLevel(logging.ERROR)
    yield
    _dmlc_logger.setLevel(before)


# ---------------------------------------------------------------------------
# the scheduler itself
# ---------------------------------------------------------------------------

def _two_incrementers(locked):
    """Model: two tasks increment a shared counter; with the lock the
    invariant holds on EVERY schedule, without it some schedule loses
    an update."""
    def model(sched):
        lock = ilv.CoopLock(sched)
        box = {"n": 0}

        def bump():
            if locked:
                with lock:
                    v = box["n"]
                    sched.point()       # the racy window, made explicit
                    box["n"] = v + 1
            else:
                v = box["n"]
                sched.point()
                box["n"] = v + 1

        sched.spawn(bump)
        sched.spawn(bump)
        sched.go()
        assert box["n"] == 2, f"lost update: {box['n']}"
    return model


def test_locked_increment_holds_on_every_schedule():
    r = ilv.explore(_two_incrementers(locked=True), schedules=64,
                    mode="dfs")
    assert r.failures == [] and r.runs >= 1


def test_unlocked_increment_fails_some_schedule():
    r = ilv.explore(_two_incrementers(locked=False), schedules=64,
                    mode="dfs")
    assert r.failures, "explorer missed the seeded lost-update"
    with pytest.raises(ilv.InvariantViolation) as ei:
        ilv.verify(_two_incrementers(locked=False), schedules=64,
                   mode="dfs")
    assert ei.value.trace    # the failing schedule is replayable


def test_replay_is_deterministic():
    model = _two_incrementers(locked=False)
    r = ilv.explore(model, schedules=64, mode="dfs")
    trace = r.failures[0]["trace"]
    # re-running under the exact failing trace reproduces the failure
    _, _, err = ilv._run_once(
        model, ilv._replay_pick(tuple(trace)), max_steps=20000)
    assert isinstance(err, AssertionError)


def test_dfs_exhausts_a_small_tree():
    def model(sched):
        a = sched.choose(2)
        b = sched.choose(3)
        assert (a, b) is not None

    r = ilv.explore(model, schedules=50, mode="dfs")
    assert r.exhausted and r.distinct == 6      # 2 * 3 leaves


def test_deadlock_is_a_finding_not_a_hang():
    def model(sched):
        l1, l2 = ilv.CoopLock(sched), ilv.CoopLock(sched)

        def ab():
            with l1:
                sched.point()
                with l2:
                    pass

        def ba():
            with l2:
                sched.point()
                with l1:
                    pass

        sched.spawn(ab)
        sched.spawn(ba)
        sched.go()

    r = ilv.explore(model, schedules=64, mode="dfs")
    assert any(isinstance(f["error"], ilv.Deadlock) for f in r.failures)


def test_schedule_limit_stops_runaway_models():
    def model(sched):
        while True:
            sched.choose(2)

    _, _, err = ilv._run_once(
        model, lambda step, n: 0, max_steps=50)
    assert isinstance(err, ilv.ScheduleLimit)


def test_logical_time_fires_timeouts_deterministically():
    def model(sched):
        ev = ilv.CoopEvent(sched)
        outcomes = []

        def waiter():
            outcomes.append(ev.wait(timeout=0.5))

        sched.spawn(waiter)
        sched.go()
        assert outcomes == [False]      # timed out at logical t=0.5
        assert sched.now >= 0.5

    r = ilv.explore(model, schedules=8, mode="dfs")
    assert r.failures == []


def test_env_schedules_default_and_override(monkeypatch):
    monkeypatch.delenv("DMLC_INTERLEAVE_SCHEDULES", raising=False)
    assert ilv.env_schedules() == 200
    monkeypatch.setenv("DMLC_INTERLEAVE_SCHEDULES", "37")
    assert ilv.env_schedules() == 37


# ---------------------------------------------------------------------------
# the four built-in models: pass today, >= 200 distinct schedules each
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(ilv.builtin_models()))
def test_builtin_model_proves_invariant_over_200_schedules(name):
    r = ilv.explore(ilv.builtin_models()[name], schedules=200,
                    mode="mixed")
    assert r.failures == [], (
        f"{name}: {len(r.failures)} schedule(s) violate the invariant; "
        f"first: {r.failures[0]['error']!r} "
        f"trace={r.failures[0]['trace']}" if r.failures else "")
    assert r.exhausted or r.distinct >= 200, (
        f"{name}: only {r.distinct} distinct schedules explored")


# ---------------------------------------------------------------------------
# the canary: re-break PR 5's CircuitBreaker probe race, expect failures
# ---------------------------------------------------------------------------

def test_rebroken_circuit_breaker_race_is_caught(monkeypatch):
    """Reintroduce the unlocked ``_probing`` check-then-act that PR 5
    fixed; the explorer MUST find a schedule admitting two probes."""
    from dmlc_core_tpu.base.resilience import CircuitBreaker

    def broken_allow(self):
        with self._lock:
            self._maybe_half_open_locked()
            state = self._state
        if state == CircuitBreaker.CLOSED:
            return True
        if state == CircuitBreaker.OPEN:
            return False
        if self._probing:           # check ... [preemption window] ...
            return False
        self._probing = True        # ... act: two probers both pass
        return True

    monkeypatch.setattr(CircuitBreaker, "allow", broken_allow)
    r = ilv.explore(ilv.model_circuit_breaker, schedules=200,
                    mode="mixed")
    assert r.failures, (
        "explorer failed to catch the re-broken single-probe invariant")
    assert any("probes" in str(f["error"]) for f in r.failures)
