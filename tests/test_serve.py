"""Serving subsystem: runner bucket padding + parity, dynamic batcher
contracts (coalescing, deadline flush, backpressure, timeout/cancel,
drain), versioned registry with hot-swap, serve-bench percentile math.

The HTTP frontend has its own module (tests/test_serve_http.py); these
tests stay socket-free so batcher/runner failures localize."""

import logging
import threading
import time

import numpy as np
import pytest

import bench as bench_mod
from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.base import metrics as M
from dmlc_core_tpu.serve import (BatcherClosedError, DynamicBatcher,
                                 ModelRegistry, ModelRunner, QueueFullError,
                                 checkpoint_model, load_model_checkpoint)


def _make_data(n=600, F=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


def _fit_histgbt(X, y):
    from dmlc_core_tpu.models import HistGBT

    return HistGBT(n_trees=3, max_depth=3, n_bins=16).fit(X, y)


def _fit_sparse(X, y):
    from dmlc_core_tpu.models import SparseHistGBT

    n, F = X.shape
    offset = np.arange(0, n * F + 1, F, dtype=np.int64)
    index = np.tile(np.arange(F, dtype=np.int64), n)
    m = SparseHistGBT(n_trees=3, max_depth=3, n_bins=16)
    m.fit(offset, index, X.reshape(-1).copy(), y, n_features=F)
    # direct-prediction oracle for the runner's dense-as-present CSR
    m._dense_oracle = lambda Z: m.predict(
        np.arange(0, len(Z) * F + 1, F, dtype=np.int64),
        np.tile(np.arange(F, dtype=np.int64), len(Z)),
        np.ascontiguousarray(Z.reshape(-1), np.float32))
    return m


def _fit_linear(X, y):
    from dmlc_core_tpu.models import GBLinear

    return GBLinear(n_rounds=5).fit(X, y)


def _fit_sk_classifier(X, y):
    from dmlc_core_tpu.models.sklearn import GBTClassifier

    est = GBTClassifier(n_estimators=3, max_depth=3, n_bins=16)
    est.fit(X, y)
    est._dense_oracle = lambda Z: np.asarray(est._predict_native(Z))
    return est


def _fit_sk_regressor(X, y):
    from dmlc_core_tpu.models.sklearn import GBTRegressor

    est = GBTRegressor(n_estimators=3, max_depth=3, n_bins=16,
                       booster="gblinear")
    est.fit(X, np.asarray(y, np.float32))
    est._dense_oracle = lambda Z: np.asarray(est._predict_native(Z))
    return est


def _oracle(model, Z):
    fn = getattr(model, "_dense_oracle", None)
    return fn(Z) if fn is not None else np.asarray(model.predict(Z))


class TestModelRunner:
    def test_bucket_ladder(self):
        X, y = _make_data(64)
        r = ModelRunner(_fit_linear(X, y), max_batch=64, min_bucket=8)
        assert r.bucket_for(1) == 8
        assert r.bucket_for(8) == 8
        assert r.bucket_for(9) == 16
        assert r.bucket_for(64) == 64
        assert r.shape_bound == 4            # 8, 16, 32, 64
        with pytest.raises(Error):
            r.bucket_for(65)
        with pytest.raises(Error):
            ModelRunner(_fit_linear(X, y), max_batch=48)  # not pow2

    @pytest.mark.parametrize("fit,exact_cross_shape", [
        (_fit_histgbt, True), (_fit_sparse, True), (_fit_linear, False),
        (_fit_sk_classifier, True), (_fit_sk_regressor, False),
    ], ids=["histgbt", "sparse", "linear", "sk_clf", "sk_reg_linear"])
    def test_padding_parity(self, fit, exact_cross_shape):
        """Padding must not change real-row outputs.  The EXACT claim is
        within a bucket: the same rows at the same compiled shape give
        bit-identical results whether the tail is zero padding or real
        rows.  Cross-shape (padded bucket vs the model's own unpadded
        shape) is also exact for the tree engines (per-row bin + descend
        has no cross-row reduction); dense matmul models may differ by
        BLAS summation order across shapes, so those get a tight
        allclose."""
        X, y = _make_data(200)
        model = fit(X, y)
        r = ModelRunner(model, max_batch=64, min_bucket=8)
        # exact within-bucket: rows 0..36 through bucket 64, tail = zero
        # padding vs tail = real rows — identical shape, identical rows
        np.testing.assert_array_equal(r.predict(X[:37]),
                                      r.predict(X[:64])[:37])
        # cross-shape vs the model's own direct prediction
        direct = _oracle(model, X[:37])
        assert_fn = (np.testing.assert_array_equal if exact_cross_shape
                     else lambda a, b: np.testing.assert_allclose(
                         a, b, rtol=1e-6, atol=1e-7))
        assert_fn(r.predict(X[:37]), direct)
        for i in (0, 3, 36):                      # single rows pad 1 -> 8
            assert_fn(r.predict(X[i:i + 1]), direct[i:i + 1])

    def test_chunks_oversized_batches(self):
        X, y = _make_data(300)
        model = _fit_histgbt(X, y)
        r = ModelRunner(model, max_batch=64, min_bucket=8)
        np.testing.assert_array_equal(r.predict(X[:300]),
                                      _oracle(model, X[:300]))

    def test_compiled_shape_bound_and_log(self, caplog):
        """Randomized request sizes land in <= log2(max_batch)+1 shapes,
        and every new bucket leaves an auditable log line."""
        X, y = _make_data(300)
        r = ModelRunner(_fit_histgbt(X, y), max_batch=256, min_bucket=8)
        rng = np.random.default_rng(1)
        with caplog.at_level(logging.INFO, logger="dmlc"):
            for _ in range(40):
                k = int(rng.integers(1, 257))
                r.predict(X[:k])
        assert len(r.compiled_shapes) <= r.shape_bound
        assert r.shape_bound <= 256 .bit_length()     # log2(max)+1 = 9
        lines = [m for m in caplog.messages if "new batch bucket" in m]
        assert len(lines) == len(r.compiled_shapes)
        assert "bound log2(max_batch)+1" in lines[0]


def _echo_execute(X):
    """Deterministic per-row function so split results are checkable."""
    return X[:, 0] * 2.0 + X[:, 1]


def _req(v0, v1=0.0, k=1):
    out = np.zeros((k, 2), np.float32)
    out[:, 0] = v0
    out[:, 1] = v1
    return out


class TestDynamicBatcher:
    def test_concurrent_producers_get_their_own_rows(self):
        with DynamicBatcher(_echo_execute, max_batch=32, max_delay=0.005,
                            max_queue=512, name="t-conc") as b:
            results = {}
            lock = threading.Lock()

            def producer(tid):
                futs = []
                for i in range(25):
                    futs.append((i, b.submit(_req(tid, i, k=1 + i % 3))))
                for i, f in futs:
                    preds, _ = f.result(timeout=10)
                    with lock:
                        results[(tid, i)] = preds

            threads = [threading.Thread(target=producer, args=(t,))
                       for t in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(results) == 8 * 25
            for (tid, i), preds in results.items():
                np.testing.assert_allclose(preds, tid * 2.0 + i)
                assert len(preds) == 1 + i % 3

    def test_deadline_flush_fires_partial_batch(self):
        M.default_registry().reset()
        with DynamicBatcher(_echo_execute, max_batch=1024, max_delay=0.05,
                            max_queue=8, name="t-deadline") as b:
            t0 = time.monotonic()
            f = b.submit(_req(3.0, k=2))
            preds, _ = f.result(timeout=5)
            waited = time.monotonic() - t0
        np.testing.assert_allclose(preds, 6.0)
        assert waited >= 0.04                 # held for the deadline...
        assert waited < 2.0                   # ...not for max_batch rows
        h = M.default_registry().histogram("serve_batch_rows",
                                           labels=("batcher",))
        assert h.count(batcher="t-deadline") == 1

    def test_backpressure_rejects_when_queue_full(self):
        gate = threading.Event()

        def blocked(X):
            gate.wait(10)
            return _echo_execute(X)

        b = DynamicBatcher(blocked, max_batch=4, max_delay=0.0,
                           max_queue=2, name="t-full")
        try:
            first = b.submit(_req(1.0))       # picked up by flush thread
            time.sleep(0.15)                  # ensure it's mid-execute
            b.submit(_req(2.0))
            b.submit(_req(3.0))               # queue now full (2)
            with pytest.raises(QueueFullError):
                b.submit(_req(4.0))
        finally:
            gate.set()
            b.close()
        assert first.result(timeout=5)[0] is not None

    def test_timeout_cancels_stuck_request(self):
        gate = threading.Event()

        def blocked(X):
            gate.wait(10)
            return _echo_execute(X)

        b = DynamicBatcher(blocked, max_batch=4, max_delay=0.0,
                           max_queue=8, name="t-timeout")
        try:
            b.submit(_req(1.0))               # occupies the flush thread
            time.sleep(0.15)
            stuck = b.submit(_req(2.0), timeout=0.01)
            time.sleep(0.1)                   # expire while queued
            gate.set()
            with pytest.raises(TimeoutError):
                stuck.result(timeout=5)
        finally:
            gate.set()
            b.close()

    def test_cancelled_future_never_executes(self):
        gate = threading.Event()
        seen = []

        def blocked(X):
            gate.wait(10)
            seen.append(len(X))
            return _echo_execute(X)

        b = DynamicBatcher(blocked, max_batch=4, max_delay=0.0,
                           max_queue=8, name="t-cancel")
        try:
            b.submit(_req(1.0))
            time.sleep(0.15)
            victim = b.submit(_req(2.0))
            assert victim.cancel()            # still queued -> cancellable
            gate.set()
            b.close()
            assert victim.cancelled()
            assert sum(seen) == 1             # only the first row ran
        finally:
            gate.set()
            b.close()

    def test_drain_on_close_completes_in_flight_futures(self):
        def slowish(X):
            time.sleep(0.01)
            return _echo_execute(X)

        b = DynamicBatcher(slowish, max_batch=2, max_delay=0.0,
                           max_queue=128, name="t-drain")
        futs = [b.submit(_req(float(i))) for i in range(40)]
        b.close(drain=True)
        for i, f in enumerate(futs):
            preds, _ = f.result(timeout=1)    # already resolved by close
            np.testing.assert_allclose(preds, i * 2.0)
        with pytest.raises(BatcherClosedError):
            b.submit(_req(0.0))

    def test_execute_failure_fails_the_batch_not_the_batcher(self):
        calls = []

        def flaky(X):
            calls.append(len(X))
            if len(calls) == 1:
                raise ValueError("boom")
            return _echo_execute(X)

        with DynamicBatcher(flaky, max_batch=4, max_delay=0.0,
                            max_queue=8, name="t-flaky") as b:
            bad = b.submit(_req(1.0))
            with pytest.raises(ValueError, match="boom"):
                bad.result(timeout=5)
            good = b.submit(_req(2.0))
            np.testing.assert_allclose(good.result(timeout=5)[0], 4.0)


class _FakeModel:
    """predict-only stand-in (registry publish does not serialize)."""

    def __init__(self, scale):
        self.scale = scale

    def predict(self, X):
        return X[:, 0] * self.scale


class TestModelRegistry:
    def test_publish_monotonic_and_rollback(self):
        reg = ModelRegistry(name="t-reg", max_batch=8, min_bucket=1)
        assert reg.current_version() is None
        v1 = reg.publish(_FakeModel(1.0))
        v2 = reg.publish(_FakeModel(2.0))
        assert (v1, v2) == (1, 2)
        assert reg.current_version() == 2
        with pytest.raises(Error):
            reg.publish(_FakeModel(3.0), version=2)    # stale version
        reg.activate(1)                                # rollback
        assert reg.current_version() == 1
        assert reg.versions() == [1, 2]
        with pytest.raises(Error):
            reg.activate(99)

    def test_inflight_batch_finishes_on_old_version(self):
        """The hot-swap contract: a batch that resolved current() before
        the swap completes on THAT version; the next batch sees the new
        one."""
        reg = ModelRegistry(name="t-swap", max_batch=8, min_bucket=1)
        reg.publish(_FakeModel(10.0))
        entered = threading.Event()
        gate = threading.Event()

        def execute(X):
            version, runner = reg.current()
            entered.set()
            gate.wait(10)                # swap happens while in flight
            return runner.predict(X), version

        with DynamicBatcher(execute, max_batch=4, max_delay=0.0,
                            max_queue=8, name="t-swap") as b:
            f1 = b.submit(_req(1.0))
            assert entered.wait(5)
            reg.publish(_FakeModel(100.0))             # hot-swap
            gate.set()
            preds1, v_1 = f1.result(timeout=5)
            preds2, v_2 = b.submit(_req(1.0)).result(timeout=5)
        assert (v_1, v_2) == (1, 2)
        np.testing.assert_allclose(preds1, 10.0)       # old model finished
        np.testing.assert_allclose(preds2, 100.0)      # new model serves

    def test_checkpoint_load_save_round_trip(self):
        X, y = _make_data(200)
        model = _fit_histgbt(X, y)
        checkpoint_model("mem:///serve-reg/v7", model, version=7)
        reg = ModelRegistry(name="t-ckpt", max_batch=16, min_bucket=4)
        assert reg.load("mem:///serve-reg/v7") == 7
        _, runner = reg.current()
        np.testing.assert_array_equal(runner.predict(X[:5]),
                                      model.predict(X[:5]))
        reg.save("mem:///serve-reg/resaved")
        v, again = load_model_checkpoint("mem:///serve-reg/resaved")
        assert v == 7
        np.testing.assert_array_equal(again.predict(X[:5]),
                                      model.predict(X[:5]))
        with pytest.raises(Error):
            reg.load("mem:///serve-reg/never-written")  # absent is loud
        with pytest.raises(Error):
            checkpoint_model("mem:///serve-reg/v0", model, version=0)


class TestServeBenchHelpers:
    def test_latency_summary_percentiles(self):
        lats = [i / 1000.0 for i in range(1, 101)]     # 1..100 ms
        s = bench_mod.latency_summary(lats)
        assert s["latency_p50_ms"] == pytest.approx(50.0, abs=1.5)
        assert s["latency_p95_ms"] == pytest.approx(95.0, abs=1.5)
        assert s["latency_p99_ms"] == pytest.approx(99.0, abs=1.5)
        assert s["latency_mean_ms"] == pytest.approx(50.5, abs=0.1)

    def test_latency_summary_empty(self):
        assert bench_mod.latency_summary([])["latency_p50_ms"] is None
