"""bench.py survivability: the official record must exist no matter how
the process dies (VERDICT r3 #1 — two consecutive rounds produced an
empty/blind official capture).

Each test launches bench.py as a real subprocess (BENCH_FORCE_CPU pins
it off any TPU plugin), kills it at a chosen point, and asserts the LAST
stdout line — the driver's parse target — is a complete JSON record with
a usable rate.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)        # conftest's 8-device forcing would
    env.pop("JAX_PLATFORMS", None)    # fight BENCH_FORCE_CPU's own setup
    env.update({
        "BENCH_FORCE_CPU": "1",
        "BENCH_ROWS": "20000",
        "BENCH_FEATURES": "28",
        "BENCH_WARMUP": "1",
        "BENCH_DEPTH": "6",
        "BENCH_BINS": "256",
    })
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _spawn(**extra):
    return subprocess.Popen(
        [sys.executable, _BENCH], env=_env(**extra),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=_REPO)


def _read_until_chunk(proc, timeout=240):
    """Collect stdout lines until one carries timed-chunk evidence."""
    lines = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("chunks_so_far"):
            return lines, rec
    raise AssertionError(
        f"no timed-chunk line within {timeout}s; got: {lines[-3:]}")


def _drain(proc, timeout=60):
    try:
        rest, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        rest, _ = proc.communicate()
    return rest


def _last_record(all_text):
    lines = [ln for ln in all_text.splitlines() if ln.strip()]
    assert lines, "no stdout at all"
    return json.loads(lines[-1])


@pytest.mark.slow
class TestBenchSurvivesKill:
    def test_sigterm_mid_fit_flushes_record(self):
        # enough rounds that the fit is still going when we fire
        proc = _spawn(BENCH_ROUNDS=500, BENCH_TIME_BUDGET=600)
        lines, _ = _read_until_chunk(proc)
        proc.send_signal(signal.SIGTERM)
        rest = _drain(proc)
        rec = _last_record("".join(lines) + rest)
        assert rec["metric"] == "histgbt_rounds_per_sec_per_chip"
        assert rec["terminated"] == "SIGTERM"
        assert rec["value"] > 0           # evidence-so-far, not empty
        assert rec["unit"] == "rounds/s/chip"
        assert "vs_baseline" in rec

    def test_sigkill_mid_fit_leaves_valid_last_line(self):
        # SIGKILL cannot be handled: the per-chunk provisional lines ARE
        # the survival mechanism here
        proc = _spawn(BENCH_ROUNDS=500, BENCH_TIME_BUDGET=600)
        lines, rec_seen = _read_until_chunk(proc)
        proc.kill()
        rest = _drain(proc)
        rec = _last_record("".join(lines) + rest)
        assert rec["metric"] == "histgbt_rounds_per_sec_per_chip"
        assert rec["value"] > 0
        assert rec["provisional"] is True
        assert rec_seen["chunks_so_far"]

    def test_budget_exhaustion_flushes_and_exits_zero(self):
        # budget expires mid-fit; the watchdog thread must flush and
        # exit 0 well before the outer 240s cap.  BENCH_NO_FALLBACK pins
        # the 2000-round config — otherwise _pick_config would shrink
        # rounds to fit the budget and a fast machine could finish
        # cleanly before the watchdog fires
        proc = _spawn(BENCH_ROUNDS=2000, BENCH_TIME_BUDGET=30,
                      BENCH_NO_FALLBACK=1)
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise AssertionError("watchdog did not enforce the budget")
        rec = _last_record(out)
        assert rec["terminated"] == "budget_exhausted"
        assert proc.returncode == 0

    def test_clean_run_final_line(self):
        proc = _spawn(BENCH_ROUNDS=50, BENCH_WARMUP=2,
                      BENCH_TIME_BUDGET=220)
        out, _ = proc.communicate(timeout=240)
        rec = _last_record(out)
        assert rec["provisional"] is False
        assert rec["phase"] == "done"
        assert rec["value"] > 0
        assert rec["anomaly"] is False
        # configs 2/4 smoke fields present (value or explicit null)
        assert "infeed_stall_frac" in rec
        assert "kvstore_sync_ms" in rec
        assert proc.returncode == 0
