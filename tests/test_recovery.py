"""Elastic fault-tolerant training: round-versioned commits, recovery
floor semantics, the tracker consensus (epochs, commit barrier,
collective hub), die → rejoin → catch-up, and elastic re-shard.

The multi-worker tests run the REAL protocol in-process: one
ElasticTracker plus one thread per worker, each with its own
ElasticSession installed as that thread's host-collective transport —
the same code path the subprocess chaos drill
(``scripts/check_elastic.py``) exercises with SIGKILL.
"""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from dmlc_core_tpu.base import faultinject as fi
from dmlc_core_tpu.base.metrics import default_registry
from dmlc_core_tpu.data.iter import ArrayRowIter
from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.ops.quantile import compute_cuts
from dmlc_core_tpu.parallel import collectives as coll
from dmlc_core_tpu.parallel.kvstore import KVStore
from dmlc_core_tpu.parallel.recovery import (
    ElasticSession, ElasticTracker, ElasticTrainer,
    RoundCheckpointer, WorkerAborted, fold_parts, truncate_to_round)
from dmlc_core_tpu.tracker.tracker import RabitTracker, WorkerSession


def _save_bytes(model) -> bytes:
    path = tempfile.mktemp(suffix=".gbt")
    try:
        model.save_model(path)
        with open(path, "rb") as f:
            return f.read()
    finally:
        if os.path.exists(path):
            os.remove(path)


def _synth(n, F, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# deterministic fold
# ---------------------------------------------------------------------------

class TestFoldParts:
    def test_matches_fixed_pairwise_tree(self):
        parts = [np.random.default_rng(i).normal(size=7).astype(np.float32)
                 for i in range(8)]
        expect = ((parts[0] + parts[1]) + (parts[2] + parts[3])) + (
            (parts[4] + parts[5]) + (parts[6] + parts[7]))
        np.testing.assert_array_equal(fold_parts(parts), expect)

    def test_odd_count_carries_tail(self):
        parts = [np.float32(x) for x in (1, 2, 4)]
        # ((1+2), 4) -> (3+4): the tail joins one level up, and the
        # order is fixed — same value every run
        assert fold_parts(parts) == np.float32(7)

    def test_subtree_composability(self):
        # a contiguous aligned half folds to the exact subtree value the
        # full fold uses — what lets a worker pre-fold its own shard
        parts = [np.random.default_rng(i).normal(size=5).astype(np.float32)
                 for i in range(4)]
        full = fold_parts(parts)
        np.testing.assert_array_equal(
            fold_parts([fold_parts(parts[:2]), fold_parts(parts[2:])]),
            full)


# ---------------------------------------------------------------------------
# round-versioned checkpoints
# ---------------------------------------------------------------------------

class TestRoundCheckpointer:
    def _model(self, X, y, rounds=3):
        m = HistGBT(n_trees=rounds, max_depth=3, n_bins=16,
                    learning_rate=0.3)
        m.fit(X, y)
        return m

    def test_commit_restore_roundtrip(self, tmp_path):
        X, y = _synth(400, 5, seed=2)
        m = self._model(X, y)
        ck = RoundCheckpointer(str(tmp_path), rank=0)
        ck.commit(m, 3, cursor={"rounds": 3})
        version, loaded, cursor = ck.restore_model(HistGBT, mesh=m.mesh)
        assert version == 3 and cursor == {"rounds": 3}
        assert _save_bytes(loaded) == _save_bytes(m)

    def test_cold_start_is_round_zero(self, tmp_path):
        ck = RoundCheckpointer(str(tmp_path), rank=0)
        version, blob, cursor = ck.restore()
        assert version == 0 and blob is None and cursor == {}

    def test_sibling_scan_catches_up_a_diskless_replacement(self, tmp_path):
        X, y = _synth(400, 5, seed=2)
        m = self._model(X, y)
        RoundCheckpointer(str(tmp_path), rank=2).commit(m, 6)
        # rank 0 never wrote a file but the floor says 6: adopt rank 2's
        ck0 = RoundCheckpointer(str(tmp_path), rank=0)
        version, blob, _ = ck0.restore(floor=6)
        assert version == 6 and blob is not None

    def test_truncate_to_round_rolls_back_and_clears_margins(self):
        X, y = _synth(400, 5, seed=2)
        m = self._model(X, y, rounds=4)
        assert m._train_preds is not None
        truncate_to_round(m, 2)
        assert len(m.trees) == 2 and m._train_preds is None


# ---------------------------------------------------------------------------
# tracker: deadline-driven grace expiry (regression) + floor tracking
# ---------------------------------------------------------------------------

class TestTrackerGraceDeadline:
    def test_silent_cluster_expires_grace_without_traffic(self):
        """Lazy expiry only ran on message arrival: with zero tracker
        traffic a lapsed deadline went unnoticed.  The deadline timer
        must flush it — observable on ``dead_workers`` directly, no
        ``lost_ranks()`` poke allowed."""
        tracker = RabitTracker(nworker=1, grace_s=0.3)
        tracker.start()
        try:
            ws = WorkerSession("127.0.0.1", tracker.port, host="h0")
            rank = ws.info["rank"]
            ws.close()  # no shutdown: abnormal death
            deadline = time.time() + 5.0
            while time.time() < deadline and not tracker.dead_workers:
                time.sleep(0.05)  # NO tracker messages in this window
            assert tracker.dead_workers == [rank]
            with tracker._lock:
                assert not tracker._pending_death
        finally:
            tracker.stop()

    def test_reconnect_cancels_pending_expiry(self):
        tracker = RabitTracker(nworker=1, grace_s=30.0)
        tracker.start()
        try:
            ws = WorkerSession("127.0.0.1", tracker.port, host="h0")
            rank = ws.info["rank"]
            ws.close()
            deadline = time.time() + 5.0
            while time.time() < deadline and not tracker.lost_ranks():
                time.sleep(0.02)
            back = WorkerSession("127.0.0.1", tracker.port, cmd="recover",
                                 rank=rank)
            assert back.info["rank"] == rank
            assert tracker.lost_ranks() == []
            assert tracker.dead_workers == []
            back.shutdown()
        finally:
            tracker.stop()

    def test_commit_cmd_tracks_floor(self):
        tracker = RabitTracker(nworker=2, grace_s=0.0)
        # floor = min over expected ranks; one rank committing alone
        # cannot advance it
        assert tracker.record_commit(0, 5) == 0
        assert tracker.record_commit(1, 3) == 3
        assert tracker.record_commit(1, 5) == 5
        assert tracker.recovery_floor() == 5
        # the commit command reports the same floor over the wire
        reply = tracker._handle({"cmd": "commit", "rank": 0, "round": 7})
        assert reply == {"floor": 5}


# ---------------------------------------------------------------------------
# collectives transport hook
# ---------------------------------------------------------------------------

class _FakeTransport:
    rank = 3
    world = 7

    def allreduce(self, x, op="sum"):
        return x * 10

    def allgather(self, x):
        return np.stack([x, x])

    def broadcast(self, v, root=0):
        return ("bcast", v, root)

    def barrier(self, name="dmlc"):
        self.barriered = name


class TestHostTransportHook:
    def test_thread_local_override_and_clear(self):
        t = _FakeTransport()
        coll.set_host_transport(t)
        try:
            assert coll.rank() == 3 and coll.world_size() == 7
            assert coll.is_distributed()
            np.testing.assert_array_equal(
                coll.allreduce(np.ones(3)), np.ones(3) * 10)
            assert coll.allgather(np.ones(2)).shape == (2, 2)
            assert coll.broadcast("x", root=2) == ("bcast", "x", 2)
            coll.barrier("sync")
            assert t.barriered == "sync"
            out = coll.allreduce_device(jnp.ones(4))
            np.testing.assert_array_equal(np.asarray(out), np.ones(4) * 10)
        finally:
            coll.set_host_transport(None)
        assert coll.rank() == 0 and coll.world_size() == 1

    def test_other_threads_unaffected(self):
        coll.set_host_transport(_FakeTransport())
        seen = {}
        try:
            th = threading.Thread(
                target=lambda: seen.update(w=coll.world_size()))
            th.start()
            th.join()
        finally:
            coll.set_host_transport(None)
        assert seen["w"] == 1


# ---------------------------------------------------------------------------
# single-worker crash-safe loop: checkpoint-floor property
# ---------------------------------------------------------------------------

class TestCheckpointFloorProperty:
    @pytest.mark.parametrize("stride,after", [(2, 2), (3, 4), (3, 7)])
    def test_kill_at_round_r_resumes_from_floor(self, tmp_path, monkeypatch,
                                                stride, after):
        """For a kill at round r and commit stride K, recovery resumes
        from floor(r/K)·K and the finished ensemble's save_model bytes
        equal the uninterrupted run's (deterministic fold)."""
        monkeypatch.setenv("DMLC_HIST_BLOCKS", "8")
        monkeypatch.setenv("DMLC_TPU_ROUNDS_PER_DISPATCH", "1")
        X, y = _synth(601, 6, seed=3)
        cuts = compute_cuts(X, 16)
        total = 8
        kw = dict(n_trees=total, max_depth=3, n_bins=16, learning_rate=0.3)

        base = HistGBT(**kw)
        base.fit(X, y, cuts=cuts)
        base_bytes = _save_bytes(base)

        d = str(tmp_path)
        m1 = HistGBT(**kw)
        tr1 = ElasticTrainer(m1, total, recovery_dir=d, stride=stride)
        dd1 = m1.make_device_data(X, y, cuts=cuts)
        with fi.inject(f"worker:abort:after={after}"):
            with pytest.raises(WorkerAborted):
                tr1.run_device(dd1)
        r = tr1.rounds_trained
        assert r >= 1

        m2 = HistGBT(**kw)
        tr2 = ElasticTrainer(m2, total, recovery_dir=d, stride=stride)
        dd2 = m2.make_device_data(X, y, cuts=cuts)
        tr2.run_device(dd2)
        expected_floor = (r // stride) * stride
        assert (tr2.resumed_from or 0) == expected_floor
        assert _save_bytes(m2) == base_bytes

    def test_clean_run_commits_and_is_bit_identical(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("DMLC_HIST_BLOCKS", "8")
        X, y = _synth(601, 6, seed=3)
        cuts = compute_cuts(X, 16)
        kw = dict(n_trees=6, max_depth=3, n_bins=16, learning_rate=0.3)
        base = HistGBT(**kw)
        base.fit(X, y, cuts=cuts)
        m = HistGBT(**kw)
        tr = ElasticTrainer(m, 6, recovery_dir=str(tmp_path), stride=2)
        tr.run_device(m.make_device_data(X, y, cuts=cuts))
        assert _save_bytes(m) == _save_bytes(base)
        version, blob, cursor = RoundCheckpointer(str(tmp_path)).restore()
        assert version == 6 and blob is not None


# ---------------------------------------------------------------------------
# resumable engines
# ---------------------------------------------------------------------------

class TestEngineResume:
    def test_fit_device_resume_carried_vs_replayed_bits(self):
        X, y = _synth(601, 6, seed=4)
        cuts = compute_cuts(X, 16)
        kw = dict(n_trees=6, max_depth=3, n_bins=16, learning_rate=0.3)
        base = HistGBT(**kw)
        base.fit(X, y, cuts=cuts)
        for clear_carry in (False, True):
            m = HistGBT(**kw)
            dd = m.make_device_data(X, y, cuts=cuts)
            done = 0
            while done < 6:
                k = min(2, 6 - done)
                m.param.n_trees = k
                if clear_carry:
                    m._train_preds = None  # force the replay route
                m.fit_device(dd, resume=done > 0)
                done += k
            for t_base, t_m in zip(base.trees, m.trees):
                for key in t_base:
                    np.testing.assert_array_equal(t_base[key], t_m[key])

    def test_fit_external_continues_from_trees(self):
        X, y = _synth(900, 5, seed=5)
        kw = dict(n_trees=6, max_depth=3, n_bins=16, learning_rate=0.3,
                  hist_method="segment")
        base = HistGBT(**kw)
        base.fit_external(ArrayRowIter(X, y))
        cuts = base.cuts
        m = HistGBT(**kw)
        m.param.n_trees = 2
        m.fit_external(ArrayRowIter(X, y), cuts=cuts)
        assert len(m.trees) == 2
        m.param.n_trees = 4
        m.fit_external(ArrayRowIter(X, y), cuts=cuts)
        assert len(m.trees) == 6
        for t_base, t_m in zip(base.trees, m.trees):
            np.testing.assert_array_equal(t_base["feat"], t_m["feat"])
            np.testing.assert_array_equal(t_base["thr"], t_m["thr"])
            np.testing.assert_allclose(t_base["leaf"], t_m["leaf"],
                                       rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# distributed protocol (in-process workers: one thread per rank)
# ---------------------------------------------------------------------------

N_ROWS, N_FEAT, TOTAL, STRIDE = 1501, 6, 6, 2
_KW = dict(n_trees=TOTAL, max_depth=3, n_bins=16, learning_rate=0.3)
_DATA = _synth(N_ROWS, N_FEAT, seed=1)


def _make_worker(tracker, directory, out, errs, rank=-1,
                 die_after_faults=None):
    X, y = _DATA

    def worker():
        sess = None
        try:
            sess = ElasticSession("127.0.0.1", tracker.port, rank=rank)
            m = HistGBT(**_KW)
            tr = ElasticTrainer(m, TOTAL, recovery_dir=directory,
                                stride=STRIDE)
            if die_after_faults is not None:
                calls = [0]

                def fault():
                    calls[0] += 1
                    if calls[0] > die_after_faults:
                        raise WorkerAborted("simulated death")
                tr._worker_fault = fault
            tr.run(sess,
                   lambda lo, hi: ArrayRowIter(X[lo:hi], y[lo:hi]),
                   N_ROWS, join_timeout_s=90)
            out[sess.grank] = (_save_bytes(m), tr.rounds_replayed, m)
            sess.shutdown()
        except WorkerAborted:
            sess.close()  # socket closes WITHOUT shutdown == death
        except Exception as e:  # noqa: BLE001 — surfaced by the test
            errs.append(repr(e))
    return threading.Thread(target=worker, daemon=True)


def _run_clean(directory, nworker=3):
    tracker = ElasticTracker(nworker=nworker, grace_s=30.0)
    tracker.start()
    out, errs = {}, []
    try:
        threads = [_make_worker(tracker, directory, out, errs)
                   for _ in range(nworker)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
    finally:
        tracker.stop()
    assert not errs, errs
    assert sorted(out) == list(range(nworker))
    return out


@pytest.fixture(scope="module")
def clean_blob():
    """One uninterrupted 3-worker run — the byte oracle every chaos
    variant must reproduce."""
    with tempfile.TemporaryDirectory(prefix="dmlc_rec") as d:
        out = _run_clean(d)
        blobs = [v[0] for v in out.values()]
        assert all(b == blobs[0] for b in blobs), \
            "workers disagree on the clean ensemble"
        yield blobs[0]


class TestElasticProtocol:
    def test_clean_run_trains_and_agrees(self, clean_blob):
        assert len(clean_blob) > 0

    def test_injected_allreduce_abort_replays_bit_identical(self,
                                                            clean_blob):
        with tempfile.TemporaryDirectory(prefix="dmlc_rec") as d:
            with fi.inject("allreduce:abort:after=25:n=1"):
                out = _run_clean(d)
            assert fi.fired_total() == 0  # scoped injector restored
            for blob, replayed, _m in out.values():
                assert blob == clean_blob
            assert any(v[1] > 0 for v in out.values()), \
                "abort fired but nobody replayed rounds"

    def test_die_and_rejoin_is_bit_identical(self, clean_blob):
        with tempfile.TemporaryDirectory(prefix="dmlc_rec") as d:
            tracker = ElasticTracker(nworker=3, grace_s=60.0)
            tracker.start()
            out, errs = {}, []
            try:
                threads = [
                    _make_worker(tracker, d, out, errs,
                                 die_after_faults=1 if i == 1 else None)
                    for i in range(3)]
                for t in threads:
                    t.start()
                deadline = time.time() + 60
                while time.time() < deadline and not tracker.lost_ranks():
                    time.sleep(0.05)
                lost = tracker.lost_ranks()
                assert len(lost) == 1
                rejoin = _make_worker(tracker, d, out, errs, rank=lost[0])
                rejoin.start()
                for t in threads:
                    t.join(timeout=240)
                rejoin.join(timeout=240)
            finally:
                tracker.stop()
            assert not errs, errs
            assert sorted(out) == [0, 1, 2]
            for blob, _replayed, _m in out.values():
                assert blob == clean_blob
            # the rejoiner caught up from the floor checkpoint;
            # survivors replayed their aborted leg
            assert tracker.recovery_floor() == TOTAL

    def test_evict_reshards_over_survivors(self):
        X, y = _DATA
        reshards = default_registry().counter("elastic_reshards_total")
        before = sum(s["value"] for s in reshards._snap())
        with tempfile.TemporaryDirectory(prefix="dmlc_rec") as d:
            tracker = ElasticTracker(nworker=3, grace_s=0.6, elastic=True)
            tracker.start()
            out, errs = {}, []
            try:
                threads = [
                    _make_worker(tracker, d, out, errs,
                                 die_after_faults=1 if i == 2 else None)
                    for i in range(3)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=240)
            finally:
                tracker.stop()
            assert not errs, errs
            assert len(out) == 2 and len(tracker.dead_workers) == 1
            blobs = [v[0] for v in out.values()]
            assert blobs[0] == blobs[1], \
                "survivors disagree after the re-shard"
            model = next(iter(out.values()))[2]
            assert len(model.trees) == TOTAL
            # converged: eval loss within a few percent of a plain fit
            base = HistGBT(**_KW)
            base.fit(X, y)
            def loss(m):
                p = m.predict(X, output_margin=True)
                return float(m._obj.metric(jnp.asarray(p), jnp.asarray(y)))
            lb, le = loss(base), loss(model)
            assert abs(le - lb) / lb < 0.05, (lb, le)
        after = sum(s["value"] for s in reshards._snap())
        assert after == before + 1

    def test_late_joiner_after_shrink_is_evicted(self):
        from dmlc_core_tpu.parallel.recovery import EvictedError
        tracker = ElasticTracker(nworker=2, grace_s=0.2, elastic=True)
        tracker.start()
        try:
            s0 = ElasticSession("127.0.0.1", tracker.port)
            s1 = ElasticSession("127.0.0.1", tracker.port)
            r0 = {}
            t0 = threading.Thread(target=lambda: r0.update(s0.join()))
            t0.start()
            s1.join(timeout_s=30)
            t0.join(timeout=30)
            assert r0["world"] == 2
            # rank 1 dies; grace lapses; rank 0 re-forms alone (in the
            # trainer flow a survivor re-joins only after its abort —
            # mirror that by waiting for the tracker to see the death)
            dead_rank = s1.grank
            s1.close()
            deadline = time.time() + 10
            while time.time() < deadline and not tracker.dead_workers:
                time.sleep(0.05)
            assert tracker.dead_workers == [dead_rank]
            info = s0.join(timeout_s=30)
            assert info["world"] == 1
            # the dead rank's replacement knocks after the shrink
            s2 = ElasticSession("127.0.0.1", tracker.port, rank=dead_rank)
            with pytest.raises(EvictedError):
                s2.join(timeout_s=5)
            s2.close()
            s0.close()
        finally:
            tracker.stop()


# ---------------------------------------------------------------------------
# KVStore bounded-staleness recovery
# ---------------------------------------------------------------------------

class TestKVStoreRecovery:
    def test_snapshot_every_stride_and_restore(self, tmp_path):
        uri = str(tmp_path / "kv.ckpt")
        kv = KVStore.create("local", learning_rate=0.5)
        kv.init(["w", "b"], [np.ones(4, np.float32),
                             np.zeros(2, np.float32)])
        kv.enable_recovery(uri, stride=2)
        snap_at_4 = None
        for step in range(5):
            kv.push(["w", "b"], [np.full(4, 0.1, np.float32),
                                 np.full(2, 0.2, np.float32)])
            kv.pull(["w", "b"])
            if step == 3:
                snap_at_4 = [np.asarray(kv.pull("w")),
                             np.asarray(kv.pull("b"))]
        # 5 pulls, stride 2 → newest snapshot is pull-round 4
        kv2 = KVStore.create("local", learning_rate=0.5)
        kv2.init(["w", "b"], [np.ones(4, np.float32),
                              np.zeros(2, np.float32)])
        version = kv2.restore_recovery(uri)
        assert version == 4
        np.testing.assert_array_equal(np.asarray(kv2.pull("w")),
                                      snap_at_4[0])
        np.testing.assert_array_equal(np.asarray(kv2.pull("b")),
                                      snap_at_4[1])

    def test_restore_without_snapshot_is_version_zero(self, tmp_path):
        kv = KVStore.create("local")
        kv.init("w", np.ones(3, np.float32))
        assert kv.restore_recovery(str(tmp_path / "none.ckpt")) == 0
