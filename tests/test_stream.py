"""stream/ subsystem: Dataset, tailer, online trainer, publisher.

Contracts pinned here (doc/streaming.md):
* Dataset is the one staging path — ``data/iter.iter_dense_slabs`` is an
  adapter over it, slabs/bounds/weights behave exactly as before.
* The tailer delivers complete records exactly once in-process, holds
  back torn tails until the append completes, resyncs past corruption,
  and resumes from its committed cursor after a SIGKILL — including a
  SIGKILL *during* the cursor commit itself (checkpoint:kill).
* Warm-start parity: OnlineTrainer(window_chunks=1, decay=1.0) over
  chunks A then B is bit-identical to ``fit(A); fit(B)``.
* The publisher stages (publish without activate), eval-gates, and
  rolls back a poisoned refresh with traffic still on the old version.
* Slow soak: append → tail → boost → hot-swap → HTTP predict with zero
  dropped requests, and the surviving checkpointed version reloads to
  bit-identical predictions.
"""

import json
import os
import struct
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from dmlc_core_tpu.io.recordio import encode_records
from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.serve import ModelRegistry
from dmlc_core_tpu.stream import (Dataset, ModelPublisher, OnlineTrainer,
                                  RecordIOTailer, TailCursor,
                                  decode_dense_events, encode_dense_event,
                                  encode_dense_events)

N_F = 6


def _make_xy(n, seed, flip=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, N_F)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0).astype(np.float32)
    if flip:
        y = 1.0 - y
    return X, y


def _write_events(path, X, y, mode="ab"):
    with open(path, mode) as f:
        f.write(encode_records(encode_dense_events(X, y)))


def _small_model(**kw):
    args = dict(n_trees=3, max_depth=3, n_bins=16, learning_rate=0.3)
    args.update(kw)
    return HistGBT(**args)


# ---------------------------------------------------------------------------
# Dataset
# ---------------------------------------------------------------------------

class TestDataset:
    def _libsvm_file(self, tmp_path, n=40):
        rng = np.random.default_rng(3)
        lines = []
        dense = np.zeros((n, 4), np.float32)
        labels = np.zeros(n, np.float32)
        for i in range(n):
            labels[i] = float(i % 2)
            feats = []
            for j in range(4):
                v = round(float(rng.normal()), 3)
                dense[i, j] = v
                feats.append(f"{j}:{v}")
            lines.append(f"{labels[i]} " + " ".join(feats))
        path = os.path.join(tmp_path, "data.svm")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return path, dense, labels

    def test_from_uri_dense_slabs(self, tmp_path):
        path, dense, labels = self._libsvm_file(tmp_path)
        ds = Dataset.from_uri(path, format="libsvm").dense_slabs(4, 16)
        got_x, got_y = [], []
        for X, y, w in ds:
            got_x.append(X.copy())       # slabs are views — copy
            got_y.append(y.copy())
            assert np.all(w == 1.0)
            assert len(X) <= 16
        np.testing.assert_array_equal(np.concatenate(got_x), dense)
        np.testing.assert_array_equal(np.concatenate(got_y), labels)

    def test_rewind_and_map(self, tmp_path):
        path, dense, _ = self._libsvm_file(tmp_path)
        ds = Dataset.from_uri(path, format="libsvm").map(
            lambda b: b.size)
        first = list(ds)
        second = list(ds)                # re-iterate → parser rewinds
        assert first == second
        assert sum(first) == len(dense)

    def test_prefetch_preserves_order(self):
        items = list(range(57))
        ds = Dataset.from_iterable(lambda: iter(items)).prefetch(4)
        assert list(ds) == items
        assert list(ds) == items         # per-iteration ThreadedIter

    def test_iter_dense_slabs_adapter(self, tmp_path):
        # the batch-path entry point is now an adapter over Dataset —
        # same slabs, same bounded staging
        from dmlc_core_tpu.data.iter import RowBlockIter, iter_dense_slabs

        path, dense, labels = self._libsvm_file(tmp_path)
        it = RowBlockIter.create(path + "?format=libsvm")
        outs = [(X.copy(), y.copy())
                for X, y, _ in iter_dense_slabs(it, 4, 7)]
        assert all(len(x) <= 7 for x, _ in outs)
        np.testing.assert_array_equal(
            np.concatenate([x for x, _ in outs]), dense)
        np.testing.assert_array_equal(
            np.concatenate([y for _, y in outs]), labels)

    def test_event_codec_round_trip(self):
        X, y = _make_xy(33, seed=5)
        recs = encode_dense_events(X, y)
        assert recs[0] == encode_dense_event(X[0], y[0])
        X2, y2 = decode_dense_events(recs, N_F)
        np.testing.assert_array_equal(X, X2)
        np.testing.assert_array_equal(y, y2)


# ---------------------------------------------------------------------------
# Tailer
# ---------------------------------------------------------------------------

class TestTailer:
    def test_tail_growing_shard_set(self, tmp_path):
        d = os.path.join(tmp_path, "events")
        os.makedirs(d)
        X1, y1 = _make_xy(64, 1)
        _write_events(os.path.join(d, "part-000.rec"), X1, y1)
        t = RecordIOTailer(d, name="grow")
        assert len(t.poll()) == 64
        assert t.poll() == []            # nothing new
        # append to the existing shard AND add a new one
        X2, y2 = _make_xy(32, 2)
        _write_events(os.path.join(d, "part-000.rec"), X2, y2)
        X3, y3 = _make_xy(16, 3)
        _write_events(os.path.join(d, "part-001.rec"), X3, y3)
        got = t.poll()
        assert len(got) == 48
        Xg, _ = decode_dense_events(got, N_F)
        np.testing.assert_array_equal(Xg, np.concatenate([X2, X3]))
        t.close()

    def test_torn_tail_held_back_until_complete(self, tmp_path):
        path = os.path.join(tmp_path, "s.rec")
        X, y = _make_xy(8, 4)
        blob = encode_records(encode_dense_events(X, y))
        cut = len(blob) - 13             # mid-record tear
        with open(path, "wb") as f:
            f.write(blob[:cut])
        t = RecordIOTailer(path, name="torn")
        assert len(t.poll()) == 7        # the torn 8th is held back
        assert t.poll() == []            # stable: no re-delivery, no error
        with open(path, "ab") as f:
            f.write(blob[cut:])          # writer finishes the append
        got = t.poll()
        assert len(got) == 1
        Xg, _ = decode_dense_events(got, N_F)
        np.testing.assert_array_equal(Xg, X[7:8])

    def test_resync_past_corruption(self, tmp_path):
        path = os.path.join(tmp_path, "s.rec")
        X, y = _make_xy(4, 5)
        good = encode_records(encode_dense_events(X, y))
        with open(path, "wb") as f:
            f.write(good + b"\x00" * 16 + good)
        t = RecordIOTailer(path, name="corrupt")
        got = t.poll()
        assert len(got) == 8
        assert t.resyncs >= 1

    def test_cursor_commit_and_resume(self, tmp_path):
        path = os.path.join(tmp_path, "s.rec")
        cursor = os.path.join(tmp_path, "cursor.ckpt")
        X, y = _make_xy(100, 6)
        _write_events(path, X, y, mode="wb")
        t = RecordIOTailer(path, cursor_uri=cursor, name="cur")
        assert len(t.poll()) == 100
        v = t.commit()
        assert v == 1
        # a new process (fresh tailer) resumes after the committed 100
        t2 = RecordIOTailer(path, cursor_uri=cursor, name="cur2")
        assert t2.records_seen == 100
        assert t2.poll() == []
        X2, y2 = _make_xy(10, 7)
        _write_events(path, X2, y2)
        got = t2.poll()
        Xg, _ = decode_dense_events(got, N_F)
        np.testing.assert_array_equal(Xg, X2)
        assert t2.commit() == 2          # version stays monotone

    def test_wait_records_timeout_and_stop(self, tmp_path):
        path = os.path.join(tmp_path, "s.rec")
        X, y = _make_xy(5, 8)
        _write_events(path, X, y, mode="wb")
        t = RecordIOTailer(path, poll_s=0.01, name="wait")
        t0 = time.monotonic()
        got = t.wait_records(10, timeout=0.3)
        assert len(got) == 5             # returns what arrived
        assert time.monotonic() - t0 >= 0.28
        stop = threading.Event()
        stop.set()
        assert t.wait_records(10, timeout=5.0, stop=stop.is_set) == []

    def test_cursor_round_trip(self):
        c = TailCursor({"/a/b.rec": 1234}, records=77)
        c2 = TailCursor.from_leaf(c.to_leaf())
        assert c2.offsets == {"/a/b.rec": 1234}
        assert c2.records == 77


_KILL_CHILD = r"""
import os, struct, sys
os.environ.setdefault("DMLC_TPU_FORCE_CPU", "1")
sys.path.insert(0, sys.argv[4])
from dmlc_core_tpu.stream import RecordIOTailer

shard, cursor, log = sys.argv[1], sys.argv[2], sys.argv[3]
t = RecordIOTailer(shard, cursor_uri=cursor, name="victim")
out = open(log, "a")
while True:
    recs = t.wait_records(100, timeout=5.0)
    if not recs:
        break
    seqs = [struct.unpack("<q", r)[0] for r in recs]
    out.write("delivered %d %d\n" % (seqs[0], seqs[-1]))
    out.flush()
    t.commit()                       # the 3rd commit SIGKILLs mid-write
    out.write("committed %d\n" % t.records_seen)
    out.flush()
print("CLEAN EXIT")                   # must never be reached
"""


class TestSigkillResume:
    def test_resume_after_sigkill_during_commit(self, tmp_path):
        """SIGKILL fired INSIDE the cursor checkpoint write
        (base/faultinject checkpoint:kill): the atomic write leaves the
        previous cursor intact, and a restarted tailer re-delivers
        exactly the records after the last durable commit — no loss, no
        skip."""
        shard = os.path.join(tmp_path, "events.rec")
        cursor = os.path.join(tmp_path, "cursor.ckpt")
        log = os.path.join(tmp_path, "progress.log")
        with open(shard, "wb") as f:
            f.write(encode_records(
                [struct.pack("<q", i) for i in range(500)]))
        child = os.path.join(tmp_path, "child.py")
        with open(child, "w") as f:
            f.write(_KILL_CHILD)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu", DMLC_TPU_FORCE_CPU="1",
                   DMLC_FAULT_INJECT="checkpoint:kill:after=2")
        proc = subprocess.run(
            [sys.executable, child, shard, cursor, log, repo],
            env=env, capture_output=True, text=True, timeout=240)
        assert proc.returncode == -9, \
            f"expected SIGKILL, got {proc.returncode}: {proc.stderr[-500:]}"
        assert "CLEAN EXIT" not in proc.stdout
        lines = open(log).read().splitlines()
        committed = [int(l.split()[1]) for l in lines
                     if l.startswith("committed")]
        assert committed == [100, 200], lines  # 3rd commit died mid-write
        # restart: resumes from the durable cursor (200), not from the
        # records the victim delivered-but-never-committed
        t = RecordIOTailer(shard, cursor_uri=cursor, name="resumed")
        assert t.records_seen == 200
        recs = t.poll()
        seqs = [struct.unpack("<q", r)[0] for r in recs]
        assert seqs == list(range(200, 500))
        assert t.commit() == 3           # version continues past the crash


# ---------------------------------------------------------------------------
# OnlineTrainer
# ---------------------------------------------------------------------------

class TestWarmStartParity:
    def test_online_equals_sequential_continued_fits(self, tmp_path):
        """The documented continuation contract: OnlineTrainer with
        window_chunks=1, decay=1.0 over chunks A then B produces
        bit-identical predictions to fit(A); fit(B) on the same
        parameterization (online learning IS repeated continued fits)."""
        XA, yA = _make_xy(256, 11)
        XB, yB = _make_xy(256, 12)
        Xq, _ = _make_xy(128, 13)

        manual = _small_model()
        manual.fit(XA, yA)
        manual.fit(XB, yB)               # warm start: cuts kept, margins replayed

        shard = os.path.join(tmp_path, "events.rec")
        _write_events(shard, XA, yA, mode="wb")
        _write_events(shard, XB, yB)
        online = _small_model()
        trainer = OnlineTrainer(online, RecordIOTailer(shard, name="par"),
                                n_features=N_F, chunk_rows=256,
                                window_chunks=1, decay=1.0,
                                commit_cursor=False)
        outs = trainer.run(max_refreshes=4, timeout=0.2)
        assert [o["rows"] for o in outs] == [256, 256]
        assert len(online.trees) == len(manual.trees) == 6
        np.testing.assert_array_equal(manual.predict(Xq),
                                      online.predict(Xq))

    def test_decay_weights_window(self, tmp_path):
        """decay < 1 trains each refresh on the concatenated window with
        decay^age sample weights — equivalent to a manual weighted
        continued fit."""
        XA, yA = _make_xy(128, 21)
        XB, yB = _make_xy(128, 22)
        Xq, _ = _make_xy(64, 23)

        manual = _small_model()
        manual.fit(XA, yA, weight=None)  # refresh 1 (single chunk, decay
        # weights all 1 would differ; trainer passes the decayed vector)

        shard = os.path.join(tmp_path, "events.rec")
        _write_events(shard, XA, yA, mode="wb")
        _write_events(shard, XB, yB)
        online = _small_model()
        trainer = OnlineTrainer(online, RecordIOTailer(shard, name="dec"),
                                n_features=N_F, chunk_rows=128,
                                window_chunks=2, decay=0.5,
                                commit_cursor=False)
        outs = trainer.run(max_refreshes=4, timeout=0.2)
        assert [o["window_rows"] for o in outs] == [128, 256]

        manual2 = _small_model()
        w1 = np.full(128, 1.0, np.float32)        # single-chunk window
        manual2.fit(XA, yA, weight=w1)
        w2 = np.concatenate([np.full(128, 0.5, np.float32),
                             np.full(128, 1.0, np.float32)])
        manual2.fit(np.concatenate([XA, XB]), np.concatenate([yA, yB]),
                    weight=w2)
        np.testing.assert_array_equal(manual2.predict(Xq),
                                      online.predict(Xq))

    def test_partial_chunk_held_not_fitted(self, tmp_path):
        """Fixed fit shapes: a timeout-starved partial gather never
        trains (it recompiled the whole round-program set mid-stream
        before the full-chunk policy) — it stays pending, uncommitted,
        completes into the next full chunk, and flush() trains a
        finite stream's tail explicitly."""
        XA, yA = _make_xy(256, 61)
        shard = os.path.join(tmp_path, "events.rec")
        _write_events(shard, XA[:100], yA[:100], mode="wb")
        online = _small_model()
        tailer = RecordIOTailer(
            shard, cursor_uri=os.path.join(tmp_path, "cursor.ckpt"),
            name="part")
        trainer = OnlineTrainer(online, tailer, n_features=N_F,
                                chunk_rows=256, window_chunks=1,
                                decay=1.0)
        # 100 of 256 available: held, no fit, no trees, no commit
        assert trainer.refresh(timeout=0.2) is None
        assert len(getattr(online, "trees", ())) == 0
        assert RecordIOTailer(shard, cursor_uri=os.path.join(
            tmp_path, "cursor.ckpt"), name="replay").records_seen == 0
        # the rest of the chunk arrives: pending + fresh = one full fit
        _write_events(shard, XA[100:], yA[100:])
        r = trainer.refresh(timeout=5.0)
        assert r is not None and r["window_rows"] == 256
        assert r["rows"] == 256
        # a finite tail is trained only on explicit flush()
        _write_events(shard, XA[:64], yA[:64])
        assert trainer.refresh(timeout=0.2) is None
        trees_before = len(online.trees)
        f = trainer.flush()
        assert f is not None and f["rows"] == 64
        assert len(online.trees) > trees_before
        assert trainer.flush() is None               # nothing pending


# ---------------------------------------------------------------------------
# Publisher
# ---------------------------------------------------------------------------

class TestPublisher:
    def test_staged_publish_leaves_current_untouched(self):
        X, y = _make_xy(256, 31)
        m1 = _small_model().fit(X, y)
        reg = ModelRegistry(max_batch=64, min_bucket=8)
        v1 = reg.publish(m1, source="base")          # active
        m2 = _small_model(n_trees=5).fit(X, y)
        v2 = reg.publish(m2, source="staged", activate=False)
        assert reg.current_version() == v1           # pointer never moved
        assert reg.versions() == [v1, v2]            # ...but v2 retained
        reg.activate(v2)
        assert reg.current_version() == v2

    def test_snapshot_isolated_from_live_model(self):
        X, y = _make_xy(256, 32)
        model = _small_model().fit(X, y)
        reg = ModelRegistry(max_batch=64, min_bucket=8)
        pub = ModelPublisher(reg, name="iso")        # no holdout: always on
        pub.publish(model)
        _, runner = reg.current()
        before = np.asarray(runner.predict(X[:16]))
        model.fit(X, y)                              # mutate the live model
        after = np.asarray(runner.predict(X[:16]))
        np.testing.assert_array_equal(before, after)  # served copy frozen

    def test_rollback_on_poisoned_refresh(self):
        """A refresh trained on poisoned data regresses on the holdout;
        the publisher stages it but never activates — traffic stays on
        the old version, bit-identically."""
        X, y = _make_xy(512, 33)
        Xh, yh = _make_xy(512, 34)
        model = _small_model()
        model.fit(X, y)
        reg = ModelRegistry(max_batch=64, min_bucket=8)
        pub = ModelPublisher(reg, holdout=(Xh, yh), gate=0.1,
                             name="gate")
        r1 = pub.publish(model)
        assert r1["activated"] and reg.current_version() == r1["version"]
        _, runner = reg.current()
        good_preds = np.asarray(runner.predict(Xh[:32]))

        Xp, yp = _make_xy(512, 35, flip=True)        # poisoned labels
        model.fit(Xp, yp)
        model.fit(Xp, yp)
        r2 = pub.publish(model)
        assert not r2["activated"], (r1, r2)
        assert r2["score"] > r1["score"]
        assert reg.current_version() == r1["version"]
        assert r2["version"] in reg.versions()       # kept for postmortem
        _, runner = reg.current()
        np.testing.assert_array_equal(
            np.asarray(runner.predict(Xh[:32])), good_preds)
        assert pub.rollbacks == 1 and pub.activations == 1

    def test_checkpointed_version_survives(self, tmp_path):
        X, y = _make_xy(256, 36)
        model = _small_model().fit(X, y)
        reg = ModelRegistry(max_batch=64, min_bucket=8)
        ckpt = os.path.join(tmp_path, "model.ckpt")
        pub = ModelPublisher(reg, checkpoint_uri=ckpt, name="ck")
        r = pub.publish(model)
        _, runner = reg.current()
        want = np.asarray(runner.predict(X[:16]))
        # a fresh process restores the surviving version bit-identically
        reg2 = ModelRegistry(max_batch=64, min_bucket=8)
        assert reg2.load(ckpt) == r["version"]
        _, runner2 = reg2.current()
        np.testing.assert_array_equal(
            np.asarray(runner2.predict(X[:16])), want)


# ---------------------------------------------------------------------------
# end-to-end soak (slow lane)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestStreamSoak:
    def test_append_tail_boost_swap_serve_zero_drops(self, tmp_path):
        """Live loop under HTTP traffic: a writer appends chunks while
        the trainer refreshes and hot-swaps versions; concurrent HTTP
        clients must see zero dropped requests, and the checkpointed
        surviving version must reload bit-identically."""
        from dmlc_core_tpu.serve import ServeFrontend

        d = os.path.join(tmp_path, "events")
        os.makedirs(d)
        chunk = 384
        n_chunks = 3
        done_writing = threading.Event()

        def writer():
            for i in range(n_chunks):
                X, y = _make_xy(chunk, 41 + i)
                _write_events(os.path.join(d, f"p-{i:02d}.rec"), X, y)
                time.sleep(0.3)
            done_writing.set()

        Xh, yh = _make_xy(1024, 40)
        reg = ModelRegistry(max_batch=128, min_bucket=8)
        ckpt = os.path.join(tmp_path, "model.ckpt")
        pub = ModelPublisher(reg, holdout=(Xh, yh),
                             checkpoint_uri=ckpt, name="soak")
        model = _small_model()
        tailer = RecordIOTailer(
            d, cursor_uri=os.path.join(tmp_path, "cursor.ckpt"),
            name="soak")
        trainer = OnlineTrainer(model, tailer, n_features=N_F,
                                chunk_rows=chunk, window_chunks=2,
                                decay=1.0, publisher=pub, name="soak")

        results = {"ok": 0, "errors": []}
        stop_clients = threading.Event()

        def client(tid):
            body = json.dumps({"rows": Xh[:4].tolist()}).encode()
            while not stop_clients.is_set():
                try:
                    req = urllib.request.Request(
                        url + "/predict", data=body,
                        headers={"Content-Type": "application/json"})
                    resp = json.loads(
                        urllib.request.urlopen(req, timeout=30).read())
                    assert len(resp["predictions"]) == 4
                    results["ok"] += 1
                except Exception as e:  # noqa: BLE001
                    results["errors"].append(f"{tid}: {e}")
                time.sleep(0.02)

        threading.Thread(target=writer, daemon=True).start()
        with ServeFrontend(reg, max_batch=128, max_delay=0.002) as fe:
            url = fe.url
            # first refresh publishes v1, then clients start
            first = trainer.refresh(timeout=60.0)
            assert first is not None and first["activated"]
            clients = [threading.Thread(target=client, args=(i,),
                                        daemon=True) for i in range(2)]
            for c in clients:
                c.start()
            deadline = time.time() + 120
            while time.time() < deadline:
                r = trainer.refresh(timeout=5.0)
                if r is None and done_writing.is_set() \
                        and tailer.records_seen >= chunk * n_chunks:
                    break
            stop_clients.set()
            for c in clients:
                c.join(timeout=10)
            # zero dropped requests across every hot-swap
            assert results["errors"] == []
            assert results["ok"] > 0
            assert len(reg.versions()) >= 2
            cur_v, runner = reg.current()
            want = np.asarray(runner.predict(Xh[:16]))
        # the surviving version reloads bit-identically (crash-restart
        # consistency: the publisher checkpointed every activation)
        reg2 = ModelRegistry(max_batch=128, min_bucket=8)
        assert reg2.load(ckpt) == cur_v
        _, runner2 = reg2.current()
        np.testing.assert_array_equal(
            np.asarray(runner2.predict(Xh[:16])), want)
        tailer.close()


@pytest.mark.slow
class TestStreamBenchMode:
    def test_bench_stream_emits_staleness_json(self, tmp_path):
        """bench.py --stream's contract: final JSON carries
        staleness_seconds {p50,p95,p99} and refreshes_published."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FORCE_CPU="2",
                   STREAM_SECONDS="4", STREAM_EVENTS_PER_SEC="600",
                   STREAM_CHUNK_ROWS="256", STREAM_TREES="2",
                   BENCH_FEATURES="6",
                   BENCH_METRICS_OUT=os.path.join(tmp_path, "m.json"))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"), "--stream"],
            env=env, capture_output=True, text=True, timeout=300,
            cwd=repo)
        assert proc.returncode == 0, proc.stderr[-800:]
        final = json.loads(proc.stdout.strip().splitlines()[-1])
        assert final["metric"] == "stream_staleness_seconds"
        assert set(final["staleness_seconds"]) == {"p50", "p95", "p99"}
        assert final["staleness_seconds"]["p95"] is not None
        assert final["refreshes_published"] >= 1
        assert final["events_served"] > 0
        assert os.path.exists(os.path.join(tmp_path, "m.json"))
