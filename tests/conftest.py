"""Test harness config.

Tests run on CPU with a virtual 8-device mesh so the multi-chip sharding path
(shard_map / psum over a named Mesh) is exercised without TPU hardware — the
TPU-world analogue of the reference's ``dmlc_tracker/local.py`` multi-process
testing pattern (SURVEY.md §4).  Env vars must be set before jax imports.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
