"""Test harness config.

Tests run on CPU with a virtual 8-device mesh so the multi-chip sharding
path (shard_map / psum over a named Mesh) is exercised without TPU hardware
— the TPU-world analogue of the reference's ``dmlc_tracker/local.py``
multi-process testing pattern (SURVEY.md §4).

Platform forcing must happen BEFORE any jax backend init, and must go
through jax.config as well as env vars: the axon TPU tunnel's site hook
overrides JAX_PLATFORMS, and touching the real chip from tests both skews
results and (when the tunnel is busy) hangs.  See
dmlc_core_tpu.utils.platform.
"""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.utils import force_cpu_devices

force_cpu_devices(8)

import jax  # noqa: E402

# Persistent XLA compilation cache under .pytest_cache (gitignored):
# the suite's wall time is dominated by first-compiles of a few dozen
# distinct programs, so a warm rerun — the dev loop — skips nearly all
# of it.  Cold CI/judge runs are unaffected (empty dir).  Threshold 0:
# on the CPU backend most programs report sub-second compile times and
# the default 1 s floor would cache almost nothing.
# DMLC_COMPILE_CACHE_DIR overrides: scripts/ci.sh exports one pre-seeded
# dir (scripts/warm_compile_cache.py) shared by BOTH pytest lanes and
# later bench runs, so compiles are paid once per image, not per lane.
_CACHE_DIR = os.environ.get("DMLC_COMPILE_CACHE_DIR") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "..", ".pytest_cache", "jax_compilation_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
