"""Test harness config.

Tests run on CPU with a virtual 8-device mesh so the multi-chip sharding
path (shard_map / psum over a named Mesh) is exercised without TPU hardware
— the TPU-world analogue of the reference's ``dmlc_tracker/local.py``
multi-process testing pattern (SURVEY.md §4).

Platform forcing must happen BEFORE any jax backend init, and must go
through jax.config as well as env vars: the axon TPU tunnel's site hook
overrides JAX_PLATFORMS, and touching the real chip from tests both skews
results and (when the tunnel is busy) hangs.  See
dmlc_core_tpu.utils.platform.
"""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.utils import force_cpu_devices

force_cpu_devices(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
