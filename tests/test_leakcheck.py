"""leakcheck (dynamic resource-leak tracer) contracts.

The static passes prove acquisition shape; these tests prove the
dynamic half: every resource kind is traced with its repo creation
stack, a genuinely leaked resource is reported as such, a clean
shutdown is silent, and with the env gate off nothing is patched at
all (creation paths run at original speed).

This file lives under tests/ on purpose: leakcheck only records
creations whose stack passes through the repo, and the test file IS
the repo frame.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading

import pytest

from dmlc_core_tpu.base import leakcheck


@pytest.fixture
def traced():
    installed_before = leakcheck.installed()
    if not installed_before:
        leakcheck.install()
    leakcheck.reset()
    yield
    leakcheck.reset()
    if not installed_before:
        leakcheck.uninstall()


def _leaks(kind=None):
    got = leakcheck.leaks()
    return [x for x in got if kind is None or x["kind"] == kind]


# ---------------------------------------------------------------------------
# the seeded leak: a socket left open inside a worker thread
# ---------------------------------------------------------------------------

def test_seeded_socket_leak_reported_with_creation_stack(traced):
    holder = {}

    def worker():
        holder["sock"] = socket.socket()        # opened, never closed

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    got = _leaks("socket")
    assert len(got) == 1, got
    # the creation stack must name THIS file and the worker function —
    # that's what makes the report actionable
    assert "tests/test_leakcheck.py" in got[0]["site"]
    assert "(worker)" in got[0]["site"]
    with pytest.raises(leakcheck.LeakError, match="socket"):
        leakcheck.check()
    holder["sock"].close()
    assert _leaks("socket") == []
    leakcheck.check()                           # now silent


# ---------------------------------------------------------------------------
# every resource kind traced, and released resources drop off lazily
# ---------------------------------------------------------------------------

def test_socket_traced_and_close_clears(traced):
    s = socket.socket()
    assert len(_leaks("socket")) == 1
    s.close()
    assert _leaks("socket") == []


def test_thread_traced_while_alive_only(traced):
    gate = threading.Event()
    t = threading.Thread(target=gate.wait)
    t.start()
    try:
        assert any("thread" == x["kind"] for x in _leaks())
    finally:
        gate.set()
        t.join()
    assert _leaks("thread") == []


def test_subprocess_zombie_stays_leaked_until_waited(traced):
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    # wait for the child to EXIT without reaping it (WNOWAIT): zombie
    os.waitid(os.P_PID, p.pid, os.WEXITED | os.WNOWAIT)
    assert len(_leaks("subprocess")) == 1, \
        "an exited-but-unwaited child must still count as leaked"
    p.wait(timeout=10)
    assert _leaks("subprocess") == []


def test_named_tempfile_traced(traced):
    f = tempfile.NamedTemporaryFile()
    assert len(_leaks("tempfile")) == 1
    f.close()
    assert _leaks("tempfile") == []


def test_mkstemp_fd_traced_and_fd_recycling_not_confused(traced):
    fd, path = tempfile.mkstemp()
    try:
        assert len(_leaks("tempfile")) == 1
        assert f"fd={fd}" in _leaks("tempfile")[0]["detail"]
    finally:
        os.close(fd)
        os.unlink(path)
    # recycle the fd number onto a different inode: must NOT re-live
    fd2 = os.open(os.devnull, os.O_RDONLY)
    try:
        assert _leaks("tempfile") == []
    finally:
        os.close(fd2)


# ---------------------------------------------------------------------------
# clean shutdown is silent; reports archive the counts
# ---------------------------------------------------------------------------

def test_clean_shutdown_is_silent(traced):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait(timeout=10)
    assert leakcheck.leaks() == []
    leakcheck.check()                           # must not raise


def test_write_report_counts_created_and_leaks(traced, tmp_path):
    import json

    s = socket.socket()
    out = str(tmp_path / "leak" / "report.json")
    rep = leakcheck.write_report(out)
    assert rep["enabled"] and rep["created"]["socket"] == 1
    assert len(rep["leaks"]) == 1
    with open(out) as f:
        assert json.load(f) == rep
    s.close()
    assert leakcheck.write_report(out)["leaks"] == []


def test_third_party_creations_ignored(traced):
    """A creation whose stack never passes through the repo is not ours
    to police — create the socket on a thread whose entire call stack
    is non-repo code (the thread bootstrap plus an exec'd module)."""
    src = ("import socket\n"
           "def make(holder):\n"
           "    holder['s'] = socket.socket()\n")
    code = compile(src, "/no/such/place/elsewhere.py", "exec")
    ns = {}
    exec(code, ns)
    holder = {}
    t = threading.Thread(target=ns["make"], args=(holder,))
    t.start()
    t.join()
    try:
        assert _leaks("socket") == []
    finally:
        holder["s"].close()


# ---------------------------------------------------------------------------
# the env gate: off means NOTHING is patched
# ---------------------------------------------------------------------------

def test_env_gate():
    for v, want in (("1", True), ("true", True), ("raise", True),
                    ("0", False), ("off", False)):
        os.environ["DMLC_LEAKCHECK"] = v
        assert leakcheck.env_enabled() is want
    os.environ.pop("DMLC_LEAKCHECK", None)


def test_disabled_means_unpatched():
    """DMLC_LEAKCHECK=0 must be zero-cost: the stdlib creation points
    are the originals, not wrappers."""
    assert not leakcheck.installed()
    assert socket.socket.__name__ == "socket"
    assert "leakcheck" not in getattr(threading.Thread.start,
                                      "__module__", "")
    assert tempfile.mkstemp is not leakcheck._traced_mkstemp
    s = socket.socket()
    s.close()
    assert leakcheck.leaks() == []              # nothing recorded


def test_install_uninstall_restores_originals():
    before = (socket.socket, threading.Thread.start,
              subprocess.Popen.__init__, tempfile.NamedTemporaryFile,
              tempfile.mkstemp)
    leakcheck.install()
    try:
        assert leakcheck.installed()
        assert socket.socket is leakcheck._TracedSocket
        leakcheck.install()                     # idempotent
    finally:
        leakcheck.uninstall()
        leakcheck.uninstall()                   # idempotent
        leakcheck.reset()
    after = (socket.socket, threading.Thread.start,
             subprocess.Popen.__init__, tempfile.NamedTemporaryFile,
             tempfile.mkstemp)
    assert after == before
