"""Native I/O fast paths (cpp/recordio.cc, cpp/prefetch.cc) vs pure Python.

The native library is built into build/libdmlctpu.so; these tests assert
byte-identical behavior between the native and Python implementations —
RecordIO framing (incl. escaped embedded magics), chunk decode, and the
threaded prefetch chunk reader feeding the byte-range sharding oracle.
"""

import os
import struct

import pytest

from dmlc_core_tpu.io import _native_io
from dmlc_core_tpu.io.filesystem import TemporaryDirectory
from dmlc_core_tpu.io.input_split import InputSplit
from dmlc_core_tpu.io.memory_io import MemoryStringStream
from dmlc_core_tpu.io.recordio import (
    RECORDIO_MAGIC_BYTES,
    RecordIOChunkReader,
    RecordIOWriter,
)

pytestmark = pytest.mark.skipif(
    not _native_io.native_io_available(), reason="native library not built"
)


def _py_encode(records):
    buf = MemoryStringStream()
    w = RecordIOWriter(buf)
    for r in records:
        w.write_record(r)
    return bytes(buf.data)


RECORD_SETS = [
    [b"hello", b"world", b""],
    [b"x" * 4096, b"y" * 3, b"z" * 1],
    # records with embedded magic at aligned and unaligned offsets
    [RECORDIO_MAGIC_BYTES * 3, b"ab" + RECORDIO_MAGIC_BYTES + b"cd",
     b"a" + RECORDIO_MAGIC_BYTES, RECORDIO_MAGIC_BYTES + b"tail"],
    [struct.pack("<I", 0xCED7230A) + b"\x00" * 11 + RECORDIO_MAGIC_BYTES],
]


@pytest.mark.parametrize("records", RECORD_SETS)
def test_encode_matches_python(records):
    assert _native_io.recordio_encode(records) == _py_encode(records)


@pytest.mark.parametrize("records", RECORD_SETS)
def test_decode_matches_python_and_roundtrips(records):
    stream = _py_encode(records)
    native = _native_io.recordio_decode(stream)
    assert native == list(RecordIOChunkReader(stream))
    assert native == records


def test_decode_rejects_corrupt():
    with pytest.raises(ValueError):
        _native_io.recordio_decode(b"\x00" * 16)
    with pytest.raises(ValueError):
        _native_io.recordio_decode(RECORDIO_MAGIC_BYTES)  # truncated header


def test_prefetch_reads_segments():
    with TemporaryDirectory() as tmp:
        p1 = os.path.join(tmp.path, "a.bin")
        p2 = os.path.join(tmp.path, "b.bin")
        blob1 = bytes(range(256)) * 64
        blob2 = b"Q" * 10_000
        with open(p1, "wb") as f:
            f.write(blob1)
        with open(p2, "wb") as f:
            f.write(blob2)
        r = _native_io.NativeChunkReader(
            [(p1, 100, len(blob1)), (p2, 0, 5000)], chunk_size=1000)
        seen = {0: b"", 1: b""}
        while True:
            item = r.next()
            if item is None:
                break
            seen[item[0]] += item[1]
        r.close()
        assert seen[0] == blob1[100:]
        assert seen[1] == blob2[:5000]


def test_prefetch_error_on_missing_file():
    r = _native_io.NativeChunkReader([("/nonexistent/xyz", 0, 10)], 100)
    with pytest.raises(IOError):
        r.next()
    r.close()


def _write_lines(path, n, prefix):
    with open(path, "wb") as f:
        for i in range(n):
            f.write(f"{prefix}-{i}-{'v' * (i % 37)}\n".encode())


def test_sharding_oracle_native_vs_python(monkeypatch):
    """Same records, same shards, native prefetch on vs off."""
    with TemporaryDirectory() as tmp:
        for k in range(3):
            _write_lines(os.path.join(tmp.path, f"part-{k}"), 211, f"f{k}")

        def collect(nparts):
            out = []
            for part in range(nparts):
                s = InputSplit.create(tmp.path, part, nparts, "text",
                                      threaded=False)
                out.append(list(s))
                # native reader starts lazily on the first read
                assert (s._native is not None) == (
                    os.environ.get("DMLC_TPU_NATIVE_IO", "1") != "0"
                    and _native_io.native_io_available())
                s.close()
            return out

        native = collect(4)
        monkeypatch.setenv("DMLC_TPU_NATIVE_IO", "0")
        monkeypatch.setattr(_native_io, "_lib", None)
        monkeypatch.setattr(_native_io, "_load_failed", False)
        python = collect(4)
        assert native == python
        flat = [r for part in native for r in part]
        assert len(flat) == 3 * 211 and len(set(flat)) == len(flat)
