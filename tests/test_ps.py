"""Tests for the sharded parameter server (parallel/ps/): partition
math properties, wire framing, the dist_sync fused pull + batched-init
satellites, and the in-process scheduler/server/client triad behind
the dist_async KVStore."""

import socket
import threading

import numpy as np
import pytest

from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.parallel.kvstore import DistAsyncKVStore, KVStore
from dmlc_core_tpu.parallel.mesh import local_mesh
from dmlc_core_tpu.parallel.ps import (
    PSClient,
    PSScheduler,
    PSServer,
    rebalance_plan,
    route_hashed,
    server_of,
    server_ranges,
    split_by_server,
)
from dmlc_core_tpu.parallel.ps import wire


# ---------------------------------------------------------------------------
# partition properties (satellite: property tests)
# ---------------------------------------------------------------------------

class TestPartition:
    @pytest.mark.parametrize("n_keys", [0, 1, 7, 100, 10_007])
    @pytest.mark.parametrize("nservers", [1, 2, 3, 5, 7, 13])
    def test_ranges_tile_exactly(self, n_keys, nservers):
        """Contiguous, gap-free, and balanced to ±1 — for EVERY count,
        including odd ones that don't divide n_keys."""
        ranges = server_ranges(n_keys, nservers)
        assert len(ranges) == nservers
        assert ranges[0][0] == 0
        assert ranges[-1][1] == n_keys
        sizes = []
        for (lo, hi), (lo2, _hi2) in zip(ranges, ranges[1:]):
            assert hi == lo2            # gap-free
            assert lo <= hi
            sizes.append(hi - lo)
        sizes.append(ranges[-1][1] - ranges[-1][0])
        assert max(sizes) - min(sizes) <= 1

    @pytest.mark.parametrize("n_keys,nservers", [(100, 3), (7, 5), (64, 8)])
    def test_server_of_matches_ranges(self, n_keys, nservers):
        ranges = server_ranges(n_keys, nservers)
        ids = np.arange(n_keys, dtype=np.int64)
        owner = server_of(ids, n_keys, nservers)
        for k, (lo, hi) in enumerate(ranges):
            np.testing.assert_array_equal(owner[lo:hi], k)

    def test_split_by_server_partitions_positions(self):
        n_keys, nservers = 1000, 7
        rng = np.random.default_rng(0)
        ids = rng.integers(0, n_keys, size=500).astype(np.int64)
        parts = split_by_server(ids, n_keys, nservers)
        seen = np.concatenate([pos for pos in parts.values()])
        # every position exactly once, and routed to its range owner
        assert sorted(seen.tolist()) == list(range(len(ids)))
        for sid, pos in parts.items():
            lo, hi = server_ranges(n_keys, nservers)[sid]
            assert ((ids[pos] >= lo) & (ids[pos] < hi)).all()

    @pytest.mark.parametrize("old,new", [(3, 5), (5, 3), (1, 7), (4, 4),
                                         (2, 9)])
    def test_rebalance_preserves_every_key(self, old, new):
        """Replaying the move plan over per-key ownership must land
        every key exactly where the new tiling says, losing none."""
        n_keys = 101
        owner = np.empty(n_keys, np.int64)
        for k, (lo, hi) in enumerate(server_ranges(n_keys, old)):
            owner[lo:hi] = k
        for src, dst, lo, hi in rebalance_plan(n_keys, old, new):
            assert (owner[lo:hi] == src).all()      # moves come from src
            owner[lo:hi] = dst
        for k, (lo, hi) in enumerate(server_ranges(n_keys, new)):
            np.testing.assert_array_equal(owner[lo:hi], k)

    def test_rebalance_same_count_is_empty(self):
        assert rebalance_plan(1000, 4, 4) == []

    def test_route_hashed_stable_and_balanced(self):
        ids = np.arange(100_000, dtype=np.int64)
        a = route_hashed(ids, 7)
        b = route_hashed(ids.copy(), 7)
        np.testing.assert_array_equal(a, b)          # deterministic
        assert a.min() >= 0 and a.max() < 7
        counts = np.bincount(a, minlength=7)
        # multiplicative hash on uniform ids: within 10% of even
        assert counts.min() > 0.9 * ids.size / 7
        assert counts.max() < 1.1 * ids.size / 7


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------

class TestWire:
    def test_round_trip_mixed_dtypes(self):
        a, b = socket.socketpair()
        try:
            fa, fb = a.makefile("rwb"), b.makefile("rwb")
            arrays = [np.arange(5, dtype=np.int64),
                      np.zeros((2, 3), np.float32),
                      np.array([1.5], np.float64)]
            wire.send_msg(fa, {"cmd": "x", "k": 1}, arrays)
            header, out = wire.recv_msg(fb)
            assert header == {"cmd": "x", "k": 1}
            assert len(out) == len(arrays)
            for got, want in zip(out, arrays):
                assert got.dtype == want.dtype
                assert got.shape == want.shape
                np.testing.assert_array_equal(got, want)
        finally:
            a.close()
            b.close()

    def test_eof_raises_connection_error(self):
        a, b = socket.socketpair()
        fa = a.makefile("rwb")
        b.close()
        a.shutdown(socket.SHUT_RD)
        with pytest.raises(ConnectionError):
            wire.recv_msg(fa)
        a.close()


# ---------------------------------------------------------------------------
# dist_sync satellites: batched init broadcast + fused pull identity
# ---------------------------------------------------------------------------

class TestDistSyncSatellites:
    def test_multi_key_init_single_broadcast(self, monkeypatch):
        """Initializing a whole list of keys must cost ONE broadcast,
        not one per key — and round-trip values/dtypes exactly."""
        from dmlc_core_tpu.parallel import kvstore as kvmod

        calls = []
        real = kvmod.coll.broadcast

        def counting_broadcast(x, root=0):
            calls.append(np.asarray(x).nbytes)
            return real(x, root)

        monkeypatch.setattr(kvmod.coll, "broadcast", counting_broadcast)
        kv = KVStore("dist_sync")
        # dtypes that survive jnp canonicalization (f64 would downcast)
        vals = [np.arange(6, dtype=np.float32),
                np.ones((2, 4), np.float32) * 1.5,
                np.array([7, 8, 9], np.int32)]
        kv.init(["a", "b", "c"], vals)
        assert len(calls) == 1
        for k, want in zip(["a", "b", "c"], vals):
            got = np.asarray(kv.pull(k))
            assert got.dtype == want.dtype
            np.testing.assert_array_equal(got, want)

    def test_fused_pull_bit_identical_to_eager(self):
        """The donated fused reducer must be BITWISE identical to the
        pre-fusion pipeline (concat-psum + eager updater)."""
        mesh = local_mesh()
        W = mesh.devices.size
        rng = np.random.default_rng(3)
        keys = [f"k{i}" for i in range(12)]
        vals = [rng.normal(size=(3 + i % 4,)).astype(np.float32)
                for i in range(len(keys))]
        # mesh dist_sync contract: grads carry a leading worker dim
        grads1 = [rng.normal(size=(W, *v.shape)).astype(np.float32)
                  for v in vals]
        grads2 = [rng.normal(size=(W, *v.shape)).astype(np.float32)
                  for v in vals]

        fused = KVStore("dist_sync", learning_rate=0.25, mesh=mesh)
        fused.init(keys, [v.copy() for v in vals])
        eager = KVStore("dist_sync", learning_rate=0.25, mesh=mesh)
        eager.init(keys, [v.copy() for v in vals])
        eager.set_updater(lambda k, g, v: v - 0.25 * g)  # forces old path

        for kv in (fused, eager):
            kv.push(keys, grads1)
            # half the keys accumulate a second push (owned buffers)
            kv.push(keys[:6], grads2[:6])
        out_f = fused.pull(keys)
        out_e = eager.pull(keys)
        for f, e in zip(out_f, out_e):
            np.testing.assert_array_equal(np.asarray(f), np.asarray(e))
        assert fused.stats["sync_calls"] == 1

    def test_fused_pull_does_not_donate_caller_arrays(self):
        """First-push arrays are caller-owned: they must stay readable
        (and reusable) after the fused pull donates its own buffers."""
        mesh = local_mesh()
        W = mesh.devices.size
        lr = 1.0 / (2 * W)          # worker-dim sum of ones → step 0.5
        kv = KVStore("dist_sync", learning_rate=lr, mesh=mesh)
        kv.init("w", np.zeros(16, np.float32))
        g = np.ones((W, 16), np.float32)
        kv.push("w", g)
        kv.pull("w")
        np.testing.assert_array_equal(g, 1.0)        # still intact
        kv.push("w", g)                              # and reusable
        out = np.asarray(kv.pull("w"))
        np.testing.assert_allclose(out, -1.0, rtol=1e-6)


# ---------------------------------------------------------------------------
# scheduler + servers + client (in-process triad)
# ---------------------------------------------------------------------------

class _Fleet:
    """In-process PS fleet for tests: scheduler + N server threads."""

    def __init__(self, nworker=1, nserver=2, snapshot_dir=""):
        self.sched = PSScheduler("127.0.0.1", nworker=nworker,
                                 nserver=nserver)
        self.sched.start()
        self.servers = [
            PSServer("127.0.0.1", self.sched.port, server_id=i,
                     snapshot_dir=snapshot_dir,
                     snapshot_stride=1 if snapshot_dir else 0)
            for i in range(nserver)]
        for s in self.servers:
            s.start()
        self.threads = [threading.Thread(target=s.serve_forever,
                                         daemon=True)
                        for s in self.servers]
        for t in self.threads:
            t.start()

    def client(self, rank=0, **kw):
        return PSClient(root_uri="127.0.0.1", root_port=self.sched.port,
                        rank=rank, **kw)

    def join(self):
        for t in self.threads:
            t.join(timeout=30)
        self.sched.join(timeout=30)


class TestPSTriad:
    def test_push_pull_across_shards(self):
        fleet = _Fleet(nworker=1, nserver=3)
        c = fleet.client(staleness=4)
        c.init("w", n_keys=100, lr=1.0)
        # duplicate ids in one batch must accumulate exactly
        ids = np.array([0, 50, 99, 50, 7], np.int64)
        c.push("w", ids, np.ones(5, np.float32), wait=True)
        got = c.pull("w", np.arange(100, dtype=np.int64))
        want = np.zeros(100, np.float32)
        np.add.at(want, ids, -1.0)                   # server: w -= lr*g
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(c.pull_dense("w"), want)
        c.close()
        fleet.join()

    def test_init_value_and_width(self):
        fleet = _Fleet(nworker=1, nserver=2)
        c = fleet.client()
        v = np.arange(20, dtype=np.float32).reshape(10, 2)
        c.init("emb", n_keys=10, width=(2,), value=v)
        got = c.pull("emb", np.arange(10, dtype=np.int64))
        np.testing.assert_array_equal(got, v)
        # idempotent: second init (another worker's) is a no-op
        c.init("emb", n_keys=10, width=(2,), value=v * 7)
        np.testing.assert_array_equal(
            c.pull("emb", np.arange(10, dtype=np.int64)), v)
        c.close()
        fleet.join()

    def test_server_side_normal_init_deterministic(self):
        """init_scale draws are a pure function of (seed, range): two
        independent fleets must hold identical factor matrices."""
        dense = []
        for _ in range(2):
            fleet = _Fleet(nworker=1, nserver=3)
            c = fleet.client()
            c.init("v", n_keys=50, width=(4,), init_scale=0.01, seed=9)
            dense.append(c.pull_dense("v"))
            c.close()
            fleet.join()
        assert dense[0].std() > 0                    # actually random
        np.testing.assert_array_equal(dense[0], dense[1])

    def test_dist_async_kvstore_surface(self):
        fleet = _Fleet(nworker=1, nserver=2)
        kv = DistAsyncKVStore(fleet.client(), learning_rate=0.5)
        kv.init("w", np.zeros(8, np.float32))
        kv.push("w", np.ones(8, np.float32))
        kv.flush()
        out = np.asarray(kv.pull("w"))
        np.testing.assert_allclose(out, -0.5, rtol=1e-6)
        with pytest.raises(Error):
            kv.set_updater(lambda k, g, v: v)
        with pytest.raises(Error):
            kv.pull("nope")
        assert kv.num_workers == 1
        kv.close()
        fleet.join()

    def test_fit_ps_learns(self):
        """End-to-end sparse CTR: GBLinear.fit_ps over the triad must
        beat chance comfortably on its own training shard."""
        from dmlc_core_tpu.data.row_block import RowBlock
        from dmlc_core_tpu.models.linear import GBLinear

        rng = np.random.default_rng(1)
        F, n, nnz = 5000, 2000, 8
        hot = rng.choice(F, 32, replace=False)
        w_true = rng.normal(size=32).astype(np.float32)
        idx = rng.integers(0, F, size=(n, nnz)).astype(np.int64)
        idx[:, :3] = hot[rng.integers(0, 32, size=(n, 3))]
        vals = rng.normal(size=(n, nnz)).astype(np.float32)
        order = np.argsort(hot)
        pos = order[np.searchsorted(hot[order], idx[:, :3])]
        y = ((vals[:, :3] * w_true[pos]).sum(1) > 0).astype(np.float32)
        off = np.arange(0, n * nnz + 1, nnz, dtype=np.int64)
        blocks = [RowBlock(offset=off, label=y, index=idx.ravel(),
                           value=vals.ravel())]

        fleet = _Fleet(nworker=1, nserver=2)
        kv = DistAsyncKVStore(fleet.client(staleness=4),
                              learning_rate=0.5)
        model = GBLinear(learning_rate=0.5, reg_lambda=0.0)
        model.fit_ps(blocks, kv, num_col=F, batch_rows=256, n_epochs=8)
        assert model.weights is not None and len(model.weights) == F
        rows = np.repeat(np.arange(n), nnz)
        m = np.zeros(n, np.float32)
        np.add.at(m, rows, model.weights[idx.ravel()] * vals.ravel())
        m += model.bias
        acc = ((m > 0) == (y > 0.5)).mean()
        assert acc > 0.8, acc
        assert max(kv.staleness_samples) <= 4
        kv.close()
        fleet.join()


class TestCsrMinibatches:
    def test_splits_and_passes_through(self):
        from dmlc_core_tpu.data.iter import iter_csr_minibatches
        from dmlc_core_tpu.data.row_block import RowBlock

        def block(n, nnz_per_row):
            off = np.arange(0, n * nnz_per_row + 1, nnz_per_row,
                            dtype=np.int64)
            return RowBlock(offset=off, label=np.zeros(n, np.float32),
                            index=np.arange(n * nnz_per_row,
                                            dtype=np.int64),
                            value=None)

        out = list(iter_csr_minibatches([block(10, 2), block(3, 1)], 4))
        assert [b.size for b in out] == [4, 4, 2, 3]
        # row contents preserved across the split
        all_idx = np.concatenate([b.index for b in out[:3]])
        np.testing.assert_array_equal(all_idx, np.arange(20))
