"""Tests for device ops (histogram, quantile) and the hist-GBT flagship.

Oracles: numpy reference histogram; monotone loss decrease; near-perfect
fit on separable synthetic data; sharded-vs-single-device equivalence
(the histogram psum correctness check — BASELINE config 1's semantics)."""

import numpy as np
import pytest

import jax.numpy as jnp

from dmlc_core_tpu.base.compat import donation_safe
from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.ops.histogram import build_histogram, reference_histogram
from dmlc_core_tpu.ops.quantile import apply_bins, compute_cuts, local_summary, merge_summaries
from dmlc_core_tpu.parallel.mesh import local_mesh


class TestHistogram:
    @pytest.mark.parametrize("method", ["segment", "matmul"])
    def test_matches_numpy_oracle(self, method, rng):
        n, F, B, N = 500, 7, 16, 4
        bins = rng.integers(0, B, size=(n, F)).astype(np.int32)
        node = rng.integers(0, N, size=n).astype(np.int32)
        g = rng.normal(size=n).astype(np.float32)
        h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
        out = np.asarray(build_histogram(
            jnp.asarray(bins), jnp.asarray(node), jnp.asarray(g), jnp.asarray(h),
            N, B, method))
        ref = reference_histogram(bins, node, g, h, N, B)
        atol = 2e-2 if method == "matmul" else 1e-4  # bf16 accumulation
        np.testing.assert_allclose(out, ref, atol=atol, rtol=1e-2)

    def test_pallas_matches_numpy_oracle(self, rng):
        # n_bins must be lane-aligned (%128) for the kernel; off-TPU the
        # pallas_call runs in interpret mode so the kernel logic (iota
        # compares, masking, grid accumulation) is exercised in CI
        from dmlc_core_tpu.ops.histogram import _pallas_ok

        n, F, B, N = 1100, 3, 128, 4   # n not a tile multiple → pad path
        assert _pallas_ok(B, F, N)
        bins = rng.integers(0, B, size=(n, F)).astype(np.int32)
        node = rng.integers(0, N, size=n).astype(np.int32)
        node[::5] = -1                 # padding/pruned rows must drop out
        g = rng.normal(size=n).astype(np.float32)
        h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
        out = np.asarray(build_histogram(
            jnp.asarray(bins), jnp.asarray(node), jnp.asarray(g), jnp.asarray(h),
            N, B, "pallas"))
        ref = reference_histogram(bins, node, g, h, N, B)
        np.testing.assert_allclose(out, ref, atol=2e-2, rtol=1e-2)  # bf16 dot

    def test_lo_factor_table_and_model(self):
        # n_bins=256 answers come from the v5e sweep table; other bin
        # counts from the 5A+2lo op model. The MXU work A*lo is invariant
        # in lo, so any answer must keep lo*ceil(B/lo) >= B (coverage).
        from dmlc_core_tpu.ops.histogram import _LO_MEASURED_256, _lo_factor

        for n_build, want in _LO_MEASURED_256.items():
            assert _lo_factor(n_build, 256) == want
        for n_nodes in (1, 2, 4, 32, 64):
            for n_bins in (64, 128, 512):
                lo = _lo_factor(n_nodes, n_bins)
                assert lo <= max(n_bins, 8)
                assert lo * (-(-n_bins // lo)) >= n_bins

    def test_pallas_ok_vmem_guard(self):
        # calibrated VMEM-stack guard: the default tile passes at every
        # default level; tile 65536 (measured 16MB scoped-vmem OOM on
        # v5e at 10M rows) must be rejected so build_histogram falls
        # back to matmul instead of failing compilation
        from dmlc_core_tpu.ops.histogram import _TILE_ROWS, _pallas_ok

        for n_build in (1, 2, 4, 8, 16):
            assert _pallas_ok(256, 28, n_build, 1, _TILE_ROWS)
        assert not _pallas_ok(256, 28, 1, 1, 65536)
        # int32 bins (>256 bin counts) scale the tile budget too
        assert _pallas_ok(512, 28, 1, 4, _TILE_ROWS)

    def test_pallas_subtile_packing(self, rng, monkeypatch):
        # S>1 subtile packing (ops/histogram.py _pack_factor) is disabled
        # on v5e (measured slower) but the plumbing is a documented seam
        # for other hardware — keep it correct: force pack=2 and check
        # the packed kernel against the numpy oracle in interpret mode.
        # tile_rows=256 is a unique static arg so the jit cache can't
        # serve a pack=1 trace from another test.
        import dmlc_core_tpu.ops.histogram as H

        monkeypatch.setattr(H, "_pack_factor", lambda n_nodes, n_bins: 2)
        n, F, B, N = 700, 3, 128, 2    # pad path + 3 partial tiles
        bins = rng.integers(0, B, size=(n, F)).astype(np.int32)
        node = rng.integers(0, N, size=n).astype(np.int32)
        node[::7] = -1                 # masked rows must drop out
        g = rng.normal(size=n).astype(np.float32)
        h = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
        out = np.asarray(H._hist_pallas(
            jnp.asarray(bins), jnp.asarray(node), jnp.asarray(g),
            jnp.asarray(h), N, B, 256))
        ref = reference_histogram(bins, node, g, h, N, B)
        np.testing.assert_allclose(out, ref, atol=2e-2, rtol=1e-2)

    def test_fused_descend_matches_two_pass(self, rng):
        # the fused Pallas descend+histogram (off by default on v5e, env
        # knob DMLC_TPU_FUSED_DESCEND) must stay in lockstep with the
        # two-pass form: exact node routing, bf16-tolerance histograms.
        # Interpret mode off-TPU exercises the kernel logic in CI.
        from dmlc_core_tpu.ops.histogram import (_fused_pallas,
                                                 fused_descend_histogram)

        n, F, B, N = 9000, 6, 128, 4   # crosses the 8192 row tile
        bins_t = jnp.asarray(rng.integers(0, B, size=(F, n)).astype(np.uint8))
        node = rng.integers(0, N, size=n).astype(np.int32)
        node[::7] = -1                 # padding rows stay -1 and drop out
        node_d = jnp.asarray(node)
        fs = jnp.asarray(rng.integers(0, F, size=n).astype(np.int32))
        ts = jnp.asarray(rng.integers(0, B - 1, size=n).astype(np.int32))
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        h = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
        hist_f, node_f = _fused_pallas(bins_t, node_d, fs, ts, g, h, N, B)
        hist_u, node_u = fused_descend_histogram(
            bins_t, node_d, fs, ts, g, h, N, B, "segment", fuse=False)
        np.testing.assert_array_equal(np.asarray(node_f), np.asarray(node_u))
        np.testing.assert_allclose(np.asarray(hist_f), np.asarray(hist_u),
                                   atol=3e-2, rtol=1e-2)
        # padding rows must remain -1 after the descend
        assert np.all(np.asarray(node_f)[::7] == -1)

    def test_pallas_guard(self):
        from dmlc_core_tpu.ops.histogram import _pallas_ok

        # the factored kernel handles any n_bins (incl. unaligned); only a
        # VMEM blow-up (huge F·N·B accumulator) must be rejected
        assert _pallas_ok(32, 8)
        assert _pallas_ok(128, 8)
        assert _pallas_ok(200, 5)      # unaligned bins OK now
        assert _pallas_ok(256, 28)     # HIGGS shape
        assert _pallas_ok(256, 28, n_nodes=32)
        assert not _pallas_ok(256, 512, n_nodes=64)  # accumulator >> VMEM

    def test_negative_node_rows_ignored(self, rng):
        n, F, B, N = 100, 3, 8, 2
        bins = rng.integers(0, B, size=(n, F)).astype(np.int32)
        node = rng.integers(0, N, size=n).astype(np.int32)
        node[::3] = -1
        g = np.ones(n, np.float32)
        h = np.ones(n, np.float32)
        out = np.asarray(build_histogram(
            jnp.asarray(bins), jnp.asarray(node), jnp.asarray(g), jnp.asarray(h), N, B))
        assert out[0].sum() == pytest.approx((node >= 0).sum() * F)


class TestQuantile:
    def test_cuts_monotone_and_binning_balanced(self, rng):
        x = rng.normal(size=(10000, 3)).astype(np.float32)
        cuts = compute_cuts(x, n_bins=16)
        c = np.asarray(cuts)
        assert c.shape == (3, 15)
        assert (np.diff(c, axis=1) > 0).all()
        bins = np.asarray(apply_bins(jnp.asarray(x), cuts))
        assert bins.min() >= 0 and bins.max() <= 15
        # roughly uniform occupancy on smooth data
        counts = np.bincount(bins[:, 0], minlength=16)
        assert counts.min() > 10000 / 16 * 0.5

    def test_weighted_summary_shifts(self):
        x = np.linspace(0, 1, 1000).astype(np.float32)[:, None]
        w = np.where(x[:, 0] > 0.9, 100.0, 1.0).astype(np.float32)
        s_unw = np.asarray(local_summary(jnp.asarray(x), None, 16))
        s_w = np.asarray(local_summary(jnp.asarray(x), jnp.asarray(w), 16))
        assert np.median(s_w) > np.median(s_unw)  # mass pulled to the tail

    def test_merge_matches_global(self, rng):
        # splitting rows over "workers" then merging ≈ global quantiles
        x = rng.normal(size=(8000, 2)).astype(np.float32)
        parts = np.split(x, 4)
        summaries = jnp.stack([local_summary(jnp.asarray(p), None, 256) for p in parts])
        cuts_merged = np.asarray(merge_summaries(summaries, 16))
        cuts_global = np.asarray(compute_cuts(x, n_bins=16))
        np.testing.assert_allclose(cuts_merged, cuts_global, atol=0.05)

    def test_constant_feature_ok(self):
        x = np.ones((100, 2), np.float32)
        cuts = compute_cuts(x, n_bins=8)
        bins = np.asarray(apply_bins(jnp.asarray(x), cuts))
        assert (bins >= 0).all() and (bins < 8).all()

    def test_atom_dominated_cuts_strictly_increase(self):
        # A sparse column densified to 0.0 puts a RUN of quantile targets
        # on one atom; the guard must fan the whole run apart (the old
        # single-pass bump left runs >= 3 non-strict).
        x = np.zeros((1000, 2), np.float32)
        x[:30, 0] = np.linspace(1, 2, 30)
        x[:, 1] = np.linspace(-1, 1, 1000)
        cuts = np.asarray(compute_cuts(x, n_bins=32))
        assert (np.diff(cuts, axis=1) > 0).all()
        # the fanned copies stay below the next real value: rows at the
        # atom and rows at 1.0 must still separate
        bins = np.asarray(apply_bins(jnp.asarray(x), jnp.asarray(cuts)))
        assert bins[:30, 0].min() > bins[31:, 0].max()

    def test_missing_all_nan_on_one_shard(self, rng):
        # A feature entirely NaN on ONE worker but finite globally must
        # not poison the merged cuts (round-4 advisor finding: the NaN
        # sentinel row used to propagate through jnp.quantile and
        # collapse the feature to bin 0 on every worker).
        x0 = rng.normal(size=(500, 3)).astype(np.float32)
        x0[:, 1] = np.nan                      # worker 0: f1 all missing
        x1 = rng.normal(size=(500, 3)).astype(np.float32)
        s0 = local_summary(jnp.asarray(x0), None, 128, True)
        s1 = local_summary(jnp.asarray(x1), None, 128, True)
        assert np.isnan(np.asarray(s0)[1]).all()      # sentinel row
        assert np.isfinite(np.asarray(s0)[[0, 2]]).all()
        cuts = np.asarray(merge_summaries(jnp.stack([s0, s1]), 16))
        assert np.isfinite(cuts).all()
        assert (np.diff(cuts, axis=1) > 0).all()
        # f1's cuts must equal what worker 1 alone would produce: the
        # NaN row contributes zero points to the merge
        solo = np.asarray(merge_summaries(s1[None], 16))
        np.testing.assert_allclose(cuts[1], solo[1], rtol=1e-6)
        # and the same end to end through compute_cuts + a fake gather
        def gather(s):
            return np.stack([np.asarray(local_summary(
                jnp.asarray(x0), None, s.shape[1], True)), s])
        cuts2 = np.asarray(compute_cuts(
            x1, n_bins=16, n_summary=128, allgather_fn=gather, missing=True))
        assert np.isfinite(cuts2).all()

    def test_missing_all_nan_everywhere_degrades_finite(self, rng):
        # Globally all-NaN features are rejected by callers up front;
        # the merge itself must still emit finite increasing cuts (not
        # NaN, which would silently bin every value to 0 downstream).
        x = np.full((50, 2), np.nan, np.float32)
        x[:, 0] = rng.normal(size=50)
        s = local_summary(jnp.asarray(x), None, 64, True)
        cuts = np.asarray(merge_summaries(s[None], 8))
        assert np.isfinite(cuts).all()
        assert (np.diff(cuts, axis=1) > 0).all()


def _synthetic(n=2000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    margin = 2.0 * X[:, 0] - 1.5 * X[:, 1] + X[:, 2] * X[:, 3]
    y = (margin + 0.1 * rng.normal(size=n) > 0).astype(np.float32)
    return X, y


class TestHistGBT:
    def test_loss_decreases_and_fits(self):
        X, y = _synthetic()
        model = HistGBT(n_trees=20, max_depth=4, learning_rate=0.5, n_bins=64)
        model.fit(X, y)
        p10 = model.predict(X, n_trees=10)
        p20 = model.predict(X)
        def logloss(p):
            eps = 1e-7
            return -np.mean(y * np.log(p + eps) + (1 - y) * np.log(1 - p + eps))
        assert logloss(p20) < logloss(p10) < np.log(2)  # better than chance, improving
        acc = ((p20 > 0.5) == y).mean()
        assert acc > 0.93, acc

    def test_regression_objective(self):
        rng = np.random.default_rng(1)
        X = rng.uniform(-1, 1, size=(1500, 5)).astype(np.float32)
        ytrue = 3.0 * X[:, 0] + np.sin(3 * X[:, 1])
        model = HistGBT(n_trees=30, max_depth=4, learning_rate=0.3,
                        objective="reg:squarederror", n_bins=64)
        model.fit(X, ytrue.astype(np.float32))
        pred = model.predict(X)
        rmse = np.sqrt(np.mean((pred - ytrue) ** 2))
        assert rmse < 0.35, rmse

    def test_sharded_equals_replicated(self):
        """THE DP-correctness oracle: training on the 8-device mesh (psum
        histogram sync) must produce the same trees as a 1-device mesh."""
        X, y = _synthetic(n=1024, f=6, seed=3)
        m8 = HistGBT(n_trees=5, max_depth=3, n_bins=32, mesh=local_mesh())
        m1 = HistGBT(n_trees=5, max_depth=3, n_bins=32, mesh=local_mesh(1))
        m8.fit(X, y)
        m1.fit(X, y)
        for t8, t1 in zip(m8.trees, m1.trees):
            np.testing.assert_array_equal(t8["feat"], t1["feat"])
            np.testing.assert_array_equal(t8["thr"], t1["thr"])
            np.testing.assert_allclose(t8["leaf"], t1["leaf"], rtol=1e-4, atol=1e-5)

    def test_uneven_rows_padded(self):
        X, y = _synthetic(n=1001, f=4, seed=4)  # not divisible by 8
        model = HistGBT(n_trees=3, max_depth=3, n_bins=32)
        model.fit(X, y)
        assert model.predict(X).shape == (1001,)

    @pytest.mark.xfail(
        not donation_safe(),
        reason="legacy jax CPU codegen orders the histogram reduction "
               "differently for the weighted vs replicated shapes — a "
               "one-ulp near-tie split flips; exactness holds on the "
               "supported runtime", strict=False)
    def test_weights_respected(self):
        # duplicate a subpopulation via weights: with identical binning, a
        # weighted fit must equal a fit on physically replicated rows
        X, y = _synthetic(n=400, f=4, seed=5)
        w = np.ones(400, np.float32)
        w[:50] = 3.0
        cuts = compute_cuts(X, n_bins=32)
        mw = HistGBT(n_trees=5, max_depth=3, n_bins=32, mesh=local_mesh(1))
        mw.fit(X, y, weight=w, cuts=cuts)
        Xr = np.concatenate([X[:50]] * 3 + [X[50:]])
        yr = np.concatenate([y[:50]] * 3 + [y[50:]])
        mr = HistGBT(n_trees=5, max_depth=3, n_bins=32, mesh=local_mesh(1))
        mr.fit(Xr, yr, cuts=cuts)
        for tw, tr in zip(mw.trees, mr.trees):
            np.testing.assert_array_equal(tw["feat"], tr["feat"])
            np.testing.assert_array_equal(tw["thr"], tr["thr"])
            np.testing.assert_allclose(tw["leaf"], tr["leaf"], rtol=1e-4, atol=1e-5)

    def test_matmul_method_trains(self):
        X, y = _synthetic(n=512, f=4, seed=6)
        model = HistGBT(n_trees=3, max_depth=3, n_bins=32, hist_method="matmul")
        model.fit(X, y)
        assert ((model.predict(X) > 0.5) == y).mean() > 0.8

    def test_margin_output_and_base_score(self):
        X, y = _synthetic(n=256, f=4, seed=7)
        model = HistGBT(n_trees=2, max_depth=2, n_bins=16, base_score=0.5)
        model.fit(X, y)
        margin = model.predict(X, output_margin=True)
        prob = model.predict(X)
        np.testing.assert_allclose(prob, 1 / (1 + np.exp(-margin)), rtol=1e-5)

    def test_param_validation(self):
        from dmlc_core_tpu.base.logging import Error

        with pytest.raises(Error):
            HistGBT(max_depth=50)
        with pytest.raises(Error):
            HistGBT(objective="multi:softmax")


class TestGBTExtras:
    def _data(self, n=6000, F=6, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, F)).astype(np.float32)
        y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0).astype(np.float32)
        return X, y

    def test_save_load_round_trip(self, tmp_path):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        m = HistGBT(n_trees=8, max_depth=3, n_bins=32)
        m.fit(X, y)
        uri = str(tmp_path / "model.bin")
        m.save_model(uri)
        m2 = HistGBT.load_model(uri)
        np.testing.assert_array_equal(m2.predict(X, output_margin=True),
                                      m.predict(X, output_margin=True))
        assert m2.param.n_trees == 8 and m2.param.max_depth == 3

    def test_load_rejects_garbage(self, tmp_path):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        bad = tmp_path / "bad.bin"
        bad.write_bytes(b"NOTAMODELxxxx")
        with pytest.raises(Error):
            HistGBT.load_model(str(bad))

    @pytest.mark.slow
    def test_subsample_colsample_train(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        m = HistGBT(n_trees=25, max_depth=4, n_bins=32,
                    subsample=0.7, colsample_bytree=0.7, seed=3,
                    learning_rate=0.3)
        m.fit(X, y)
        acc = ((m.predict(X) > 0.5) == y).mean()
        assert acc > 0.85, acc
        # same seed → identical model
        m2 = HistGBT(n_trees=25, max_depth=4, n_bins=32,
                     subsample=0.7, colsample_bytree=0.7, seed=3,
                     learning_rate=0.3)
        m2.fit(X, y)
        np.testing.assert_array_equal(m.predict(X, output_margin=True),
                                      m2.predict(X, output_margin=True))

    def test_colsample_restricts_features(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(F=8)
        m = HistGBT(n_trees=10, max_depth=3, n_bins=32,
                    colsample_bytree=0.25, seed=1)
        m.fit(X, y)
        # ⌈0.25·8⌉ = 2 features available per tree → per-tree split
        # features must come from ≤2 distinct features
        B = m.param.n_bins
        for tree in m.trees:
            used = set()
            for level in range(tree["feat"].shape[0]):
                n_nodes = 1 << level
                feat = tree["feat"][level][:n_nodes]
                thr = tree["thr"][level][:n_nodes]
                used.update(feat[thr < B - 1].tolist())
            assert len(used) <= 2, used

    def test_early_stopping(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=4000)
        Xv, yv = self._data(n=2000, seed=9)
        m = HistGBT(n_trees=200, max_depth=3, n_bins=32, learning_rate=0.5)
        m.fit(X, y, eval_set=(Xv, yv), early_stopping_rounds=10)
        assert m.best_iteration is not None and m.best_score is not None
        assert len(m.trees) < 200            # actually stopped early
        # default predict uses best_iteration+1 trees
        pd_best = m.predict(Xv, output_margin=True)
        pd_explicit = m.predict(Xv, output_margin=True,
                                n_trees=m.best_iteration + 1)
        np.testing.assert_array_equal(pd_best, pd_explicit)

    def test_host_binned_fit_matches_device_binned(self, rng, monkeypatch):
        """DMLC_TPU_BIN_BACKEND=cpu bins in-core fits on the host backend
        (uint8 upload instead of f32 — 4x less tunnel transfer); same
        cuts → same bins → identical trees.  conftest pins CPU, so both
        branches compute on one backend and exactness is deterministic."""
        X = rng.normal(size=(800, 6)).astype(np.float32)
        y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
        models = {}
        for pinned in (False, True):
            if pinned:
                monkeypatch.setenv("DMLC_TPU_BIN_BACKEND", "cpu")
            else:
                monkeypatch.delenv("DMLC_TPU_BIN_BACKEND", raising=False)
            m = HistGBT(n_trees=5, max_depth=3, n_bins=32)
            m.fit(X, y)
            models[pinned] = m
        for t0, t1 in zip(models[False].trees, models[True].trees):
            np.testing.assert_array_equal(t0["feat"], t1["feat"])
            np.testing.assert_array_equal(t0["thr"], t1["thr"])
            np.testing.assert_allclose(t0["leaf"], t1["leaf"], rtol=1e-5)

    def test_predict_leaf_reconstructs_margins(self, rng):
        """pred_leaf oracle: summing each tree's leaf value at the
        reported leaf index must reproduce predict(output_margin=True)
        exactly — the leaf indices ARE the descent predict performs."""
        X = rng.normal(size=(300, 5)).astype(np.float32)
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
        m = HistGBT(n_trees=6, max_depth=3, n_bins=16)
        m.fit(X, y)
        leaves = m.predict_leaf(X)
        assert leaves.shape == (300, 6)
        assert leaves.min() >= 0 and leaves.max() < 2 ** 3
        margin = np.full(300, m.param.base_score, np.float32)
        for t, tree in enumerate(m.trees):
            margin += tree["leaf"][leaves[:, t]]
        np.testing.assert_allclose(
            margin, m.predict(X, output_margin=True), rtol=1e-5,
            atol=1e-6)

    def test_predict_leaf_multiclass(self, rng):
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32) + (X[:, 1] > 0)
        m = HistGBT(n_trees=3, max_depth=2, n_bins=16,
                    objective="multi:softmax", num_class=3)
        m.fit(X, y)
        leaves = m.predict_leaf(X)
        assert leaves.shape == (200, 3, 3)          # [n, T, K]
        margin = np.full((200, 3), m.param.base_score, np.float32)
        for t, tree in enumerate(m.trees):
            for c in range(3):
                margin[:, c] += tree["leaf"][c][leaves[:, t, c]]
        np.testing.assert_allclose(
            margin, m.predict(X, output_margin=True), rtol=1e-5,
            atol=1e-6)

    def test_dump_model_text(self):
        """The text dump is structurally faithful: 2^depth leaves per
        tree whose values equal the stored leaf array, split thresholds
        are real cut values for the named feature, and a hand-descent
        of the dumped rules reproduces predict() on a probe row."""
        import re
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        m = HistGBT(n_trees=3, max_depth=3, n_bins=32, learning_rate=0.3)
        m.fit(X, y)
        dump = m.dump_model(with_stats=True)
        assert dump.count("booster[") == 3
        cuts = np.asarray(m.cuts)
        for ti, tree in enumerate(m.trees):
            sec = dump.split(f"booster[{ti}]:")[1].split("booster[")[0]
            leaves = re.findall(r"(\d+):leaf=([-\d.e+]+)", sec)
            assert len(leaves) == 8
            np.testing.assert_allclose(
                [float(v) for _, v in leaves], tree["leaf"],
                rtol=1e-4, atol=1e-6)
            for f, thr in re.findall(r"\[f(\d+)<([-\d.e+]+)\]", sec):
                f, thr = int(f), float(thr)
                assert np.isclose(cuts[f], thr, rtol=1e-3,
                                  atol=1e-5).any(), (f, thr)
        # hand-descend the dumped rules for one row, tree 0
        sec = dump.split("booster[0]:")[1].split("booster[")[0]
        nodes = {}
        for line in sec.strip().splitlines():
            line = line.strip()
            mm = re.match(r"(\d+):\[f(\d+)<([-\d.e+]+)\] yes=(\d+),no=(\d+)",
                          line)
            if mm:
                nodes[int(mm.group(1))] = (
                    int(mm.group(2)), float(mm.group(3)),
                    int(mm.group(4)), int(mm.group(5)))
                continue
            mm = re.match(r"(\d+):passthrough yes=(\d+),no=(\d+)", line)
            if mm:
                nodes[int(mm.group(1))] = (None, None,
                                           int(mm.group(2)),
                                           int(mm.group(3)))
                continue
            mm = re.match(r"(\d+):leaf=([-\d.e+]+)", line)
            nodes[int(mm.group(1))] = ("leaf", float(mm.group(2)))
        row = X[7]
        nid = 0
        while nodes[nid][0] != "leaf":
            f, thr, yes, no = nodes[nid]
            nid = yes if (f is None or row[f] < thr) else no
        margin1 = nodes[nid][1]
        # predict with ONLY tree 0: margin = base + leaf contribution
        got = m.predict(row[None], output_margin=True, n_trees=1)[0]
        np.testing.assert_allclose(got, m.param.base_score + margin1,
                                   rtol=1e-4, atol=1e-6)
        # multiclass dump: per-class sections with full leaf layers
        rng = np.random.default_rng(3)
        Xm = rng.normal(size=(600, 4)).astype(np.float32)
        ym = (Xm[:, 0] > 0).astype(np.float32) + (Xm[:, 1] > 0.7)
        mm3 = HistGBT(n_trees=2, max_depth=2, n_bins=16, num_class=3,
                      objective="multi:softmax")
        mm3.fit(Xm, ym)
        d3 = mm3.dump_model()
        assert d3.count("class[") == 6          # 2 trees x 3 classes
        assert d3.count(":leaf=") == 6 * 4      # 2^2 leaves per section
        # feature_names replaces the f<N> placeholders (fmap role) and
        # validates its length
        names = [f"col_{i}" for i in range(X.shape[1])]
        dn = m.dump_model(feature_names=names)
        assert "[col_" in dn
        assert "[f0<" not in dn
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error):
            m.dump_model(feature_names=["just_one"])

    def test_feature_importances(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(F=6)
        m = HistGBT(n_trees=15, max_depth=3, n_bins=32)
        m.fit(X, y)
        imp = m.feature_importances()
        assert imp.shape == (6,)
        # informative features (0,1,2) must dominate the noise ones
        assert imp[:3].sum() > imp[3:].sum()

    @pytest.mark.slow
    def test_continue_training(self, tmp_path):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        full = HistGBT(n_trees=20, max_depth=3, n_bins=32, learning_rate=0.3)
        full.fit(X, y)

        half = HistGBT(n_trees=10, max_depth=3, n_bins=32, learning_rate=0.3)
        half.fit(X, y)
        uri = str(tmp_path / "half.bin")
        half.save_model(uri)
        cont = HistGBT.load_model(uri)
        cont.param.init({"n_trees": 10})
        cont.fit(X, y)                       # 10 more rounds on top
        assert len(cont.trees) == 20
        np.testing.assert_allclose(
            cont.predict(X, output_margin=True),
            full.predict(X, output_margin=True), rtol=1e-4, atol=1e-5)

    def test_early_stop_state_survives_save_load(self, tmp_path):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=4000)
        Xv, yv = self._data(n=2000, seed=9)
        m = HistGBT(n_trees=200, max_depth=3, n_bins=32, learning_rate=0.5)
        m.fit(X, y, eval_set=(Xv, yv), early_stopping_rounds=10)
        uri = str(tmp_path / "es.bin")
        m.save_model(uri)
        m2 = HistGBT.load_model(uri)
        assert m2.best_iteration == m.best_iteration
        np.testing.assert_array_equal(m2.predict(Xv, output_margin=True),
                                      m.predict(Xv, output_margin=True))

    def test_subsample_zero_rejected(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        with pytest.raises(Error):
            HistGBT(subsample=0.0)

    def test_external_memory_sampling(self, tmp_path):
        from dmlc_core_tpu.data.iter import RowBlockIter
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=2000, F=6)
        svm = tmp_path / "t.svm"
        with open(svm, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.5f}" for j in range(6))
                f.write(f"{y[i]:.0f} {feats}\n")
        it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
        m = HistGBT(n_trees=10, max_depth=3, n_bins=32,
                    colsample_bytree=0.34, seed=5)
        m.fit_external(it, num_col=6)
        B = m.param.n_bins
        for tree in m.trees:                 # ≤ ⌈0.34·6⌉ = 3 features/tree
            used = set()
            for level in range(tree["feat"].shape[0]):
                n_nodes = 1 << level
                feat = tree["feat"][level][:n_nodes]
                thr = tree["thr"][level][:n_nodes]
                used.update(np.asarray(feat)[np.asarray(thr) < B - 1].tolist())
            assert len(used) <= 3, used


class TestMulticlass:
    def _data(self, n=6000, F=6, K=3, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, F)).astype(np.float32)
        # separable blobs along features 0/1 — centers FIXED across calls
        # so train/validation draws come from the same distribution
        centers = np.random.default_rng(42).normal(scale=3.0, size=(K, 2))
        y = rng.integers(0, K, n)
        X[:, :2] += centers[y]
        return X, y.astype(np.float32)

    def test_train_predict(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        m = HistGBT(n_trees=15, max_depth=4, n_bins=32,
                    objective="multi:softmax", num_class=3,
                    learning_rate=0.5)
        m.fit(X, y)
        pred = m.predict(X)
        assert pred.shape == (len(y),)
        acc = (pred == y).mean()
        assert acc > 0.9, acc
        proba = m.predict_proba(X)
        assert proba.shape == (len(y), 3)
        np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)
        assert (proba.argmax(1) == pred).all()

    @pytest.mark.slow
    def test_save_load_and_continue(self, tmp_path):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=3000)
        m = HistGBT(n_trees=6, max_depth=3, n_bins=32,
                    objective="multi:softmax", num_class=3)
        m.fit(X, y)
        uri = str(tmp_path / "mc.bin")
        m.save_model(uri)
        m2 = HistGBT.load_model(uri)
        np.testing.assert_array_equal(m2.predict(X), m.predict(X))
        m2.param.init({"n_trees": 4})
        m2.fit(X, y)                         # continue training
        assert len(m2.trees) == 10
        acc = (m2.predict(X) == y).mean()
        assert acc > 0.85, acc

    def test_early_stopping_multiclass(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=3000)
        Xv, yv = self._data(n=1500, seed=5)
        m = HistGBT(n_trees=100, max_depth=3, n_bins=32,
                    objective="multi:softmax", num_class=3,
                    learning_rate=0.5)
        m.fit(X, y, eval_set=(Xv, yv), early_stopping_rounds=10)
        assert m.best_iteration is not None

    def test_num_class_objective_consistency(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        with pytest.raises(Error):
            HistGBT(objective="multi:softmax")           # num_class missing
        with pytest.raises(Error):
            HistGBT(num_class=3)                         # objective not multi

    @pytest.mark.slow
    def test_sharded_equals_replicated_multiclass(self):
        from dmlc_core_tpu.models import HistGBT
        from dmlc_core_tpu.parallel.mesh import local_mesh

        X, y = self._data(n=1024, F=5)
        m8 = HistGBT(n_trees=4, max_depth=3, n_bins=32, mesh=local_mesh(),
                     objective="multi:softmax", num_class=3)
        m1 = HistGBT(n_trees=4, max_depth=3, n_bins=32, mesh=local_mesh(1),
                     objective="multi:softmax", num_class=3)
        m8.fit(X, y)
        m1.fit(X, y)
        for t8, t1 in zip(m8.trees, m1.trees):
            np.testing.assert_array_equal(t8["feat"], t1["feat"])
            np.testing.assert_array_equal(t8["thr"], t1["thr"])
            np.testing.assert_allclose(t8["leaf"], t1["leaf"],
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_continue_then_early_stop_offsets_best_iteration(self, tmp_path):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=3000)
        Xv, yv = self._data(n=1500, seed=5)
        m = HistGBT(n_trees=6, max_depth=3, n_bins=32,
                    objective="multi:softmax", num_class=3)
        m.fit(X, y)
        uri = str(tmp_path / "c.bin")
        m.save_model(uri)
        m2 = HistGBT.load_model(uri)
        m2.param.init({"n_trees": 50, "learning_rate": 0.5})
        m2.fit(X, y, eval_set=(Xv, yv), early_stopping_rounds=10)
        # best_iteration must index into the COMBINED tree list (≥ priors)
        assert m2.best_iteration is not None and m2.best_iteration >= 6
        pd = m2.predict(Xv)
        acc = (pd == yv).mean()
        assert acc > 0.85, acc          # old trees not dropped

    def test_bad_labels_rejected(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=500)
        y[0] = 3.0                      # out of [0, 3)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16,
                    objective="multi:softmax", num_class=3)
        with pytest.raises(Error):
            m.fit(X, y)

    def test_predict_proba_rejects_regression(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        rng = np.random.default_rng(0)
        X = rng.normal(size=(500, 4)).astype(np.float32)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16,
                    objective="reg:squarederror")
        m.fit(X, X[:, 0])
        with pytest.raises(Error):
            m.predict_proba(X)


class TestEvalMetrics:
    def _data(self, n, seed):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 6)).astype(np.float32)
        y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0).astype(np.float32)
        return X, y

    def test_auc_early_stopping_maximizes(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(4000, 0)
        Xv, yv = self._data(2000, 9)
        m = HistGBT(n_trees=150, max_depth=3, n_bins=32, learning_rate=0.5,
                    eval_metric="auc")
        m.fit(X, y, eval_set=(Xv, yv), early_stopping_rounds=10)
        assert m.best_score is not None and 0.9 < m.best_score <= 1.0

    def test_auc_matches_sklearn_style_oracle(self):
        from dmlc_core_tpu.models.histgbt import _metric_auc
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        y = (rng.random(500) > 0.5).astype(np.float32)
        s = rng.normal(size=500).astype(np.float32) + y  # informative score
        # O(n^2) oracle: P(score_pos > score_neg)
        pos = s[y == 1][:, None]
        neg = s[y == 0][None, :]
        want = (pos > neg).mean() + 0.5 * (pos == neg).mean()
        got = float(_metric_auc(jnp.asarray(s), jnp.asarray(y)))
        assert abs(got - want) < 1e-3, (got, want)

    def test_error_metric(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(3000, 1)
        Xv, yv = self._data(1000, 2)
        m = HistGBT(n_trees=30, max_depth=3, n_bins=32, eval_metric="error")
        m.fit(X, y, eval_set=(Xv, yv))
        assert m.best_score is not None and m.best_score < 0.1

    def test_auc_midranks_on_ties(self):
        from dmlc_core_tpu.models.histgbt import _metric_auc
        import jax.numpy as jnp

        # all-tied margins must give exactly 0.5 regardless of label order
        y = np.array([1, 1, 1, 0, 0, 0], np.float32)
        s = np.zeros(6, np.float32)
        assert float(_metric_auc(jnp.asarray(s), jnp.asarray(y))) == 0.5
        # single-class validation set: neutral 0.5, not NaN
        y1 = np.ones(6, np.float32)
        assert float(_metric_auc(jnp.asarray(s), jnp.asarray(y1))) == 0.5

    def test_eval_metric_objective_mismatch_rejected(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        with pytest.raises(Error):
            HistGBT(eval_metric="merror")          # binary obj, multi metric
        with pytest.raises(Error):
            HistGBT(objective="reg:squarederror", eval_metric="auc")


def test_gain_importance():
    X, y = _synthetic(n=4000, f=6)
    m = HistGBT(n_trees=12, max_depth=4, n_bins=32, learning_rate=0.5)
    m.fit(X, y)
    w = m.feature_importances("weight")
    g = m.feature_importances("gain")
    assert g.shape == (6,)
    assert (g >= 0).all() and g.sum() > 0
    # informative features (0..3 in _synthetic's margin) dominate by gain
    assert g[:4].sum() > g[4:].sum()
    # trees carry gains; weight importance unchanged by the addition
    assert all("gain" in t for t in m.trees)
    assert w.sum() > 0


def test_gain_importance_multiclass(tmp_path):
    rng = np.random.default_rng(0)
    K = 3
    centers = np.random.default_rng(42).normal(scale=3.0, size=(K, 2))
    yl = rng.integers(0, K, 3000)
    X = rng.normal(size=(3000, 5)).astype(np.float32)
    X[:, :2] += centers[yl]
    m = HistGBT(n_trees=6, max_depth=3, n_bins=32,
                objective="multi:softmax", num_class=K)
    m.fit(X, yl.astype(np.float32))
    g = m.feature_importances("gain")
    assert g[:2].sum() > g[2:].sum()
    # survives save/load
    uri = str(tmp_path / "g.bin")
    m.save_model(uri)
    g2 = HistGBT.load_model(uri).feature_importances("gain")
    np.testing.assert_allclose(g2, g)


def test_predict_batching_consistent(monkeypatch):
    X, y = _synthetic(n=5000, f=5)
    m = HistGBT(n_trees=5, max_depth=3, n_bins=32)
    m.fit(X, y)
    whole = m.predict(X, output_margin=True)
    monkeypatch.setattr(HistGBT, "_PREDICT_BATCH", 1234)  # force 5 batches
    batched = m.predict(X, output_margin=True)
    np.testing.assert_array_equal(whole, batched)


def test_predict_empty_input():
    X, y = _synthetic(n=500, f=4)
    m = HistGBT(n_trees=2, max_depth=2, n_bins=16)
    m.fit(X, y)
    assert m.predict(np.zeros((0, 4), np.float32)).shape == (0,)


class TestMonotoneConstraints:
    def _data(self, n=6000, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 4)).astype(np.float32)
        # true relationship increasing in x0 but with noise that tempts
        # locally-decreasing splits; x1 genuinely non-monotone
        y = (X[:, 0] + np.sin(3 * X[:, 1]) +
             0.5 * rng.normal(size=n)).astype(np.float32)
        return X, y

    def _sweep_margins(self, m, X, feature, n_grid=64):
        """Margins along a grid of one feature, others at fixed rows."""
        base = X[:50].copy()
        grid = np.linspace(X[:, feature].min(), X[:, feature].max(), n_grid)
        out = np.empty((50, n_grid), np.float32)
        for j, v in enumerate(grid):
            Xs = base.copy()
            Xs[:, feature] = v
            out[:, j] = m.predict(Xs, output_margin=True)
        return out

    @pytest.mark.slow
    def test_increasing_constraint_enforced(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        m = HistGBT(n_trees=25, max_depth=4, n_bins=64, learning_rate=0.3,
                    objective="reg:squarederror",
                    monotone_constraints=[1, 0, 0, 0])
        m.fit(X, y)
        sweep = self._sweep_margins(m, X, 0)
        diffs = np.diff(sweep, axis=1)
        assert (diffs >= -1e-5).all(), diffs.min()   # globally non-decreasing
        # and the model still fits: rmse clearly better than predicting mean
        rmse = np.sqrt(np.mean((m.predict(X) - y) ** 2))
        assert rmse < np.std(y) * 0.8, rmse

    def test_unconstrained_would_violate(self):
        """Sanity: without the constraint the same data produces local
        decreases along x0 (so the previous test is non-vacuous)."""
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        m = HistGBT(n_trees=25, max_depth=4, n_bins=64, learning_rate=0.3,
                    objective="reg:squarederror")
        m.fit(X, y)
        sweep = self._sweep_margins(m, X, 0)
        assert (np.diff(sweep, axis=1) < -1e-4).any()

    def test_decreasing_constraint(self):
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data()
        m = HistGBT(n_trees=15, max_depth=3, n_bins=32, learning_rate=0.3,
                    objective="reg:squarederror",
                    monotone_constraints=[0, 0, 0, -1])
        m.fit(X, y)
        sweep = self._sweep_margins(m, X, 3)
        assert (np.diff(sweep, axis=1) <= 1e-5).all()

    def test_no_constraints_trees_unchanged(self):
        """monotone_constraints of all zeros must not change training."""
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=2000)
        a = HistGBT(n_trees=5, max_depth=3, n_bins=32,
                    objective="reg:squarederror")
        b = HistGBT(n_trees=5, max_depth=3, n_bins=32,
                    objective="reg:squarederror",
                    monotone_constraints=[0, 0, 0, 0])
        a.fit(X, y)
        b.fit(X, y, cuts=a.cuts)
        for ta, tb in zip(a.trees, b.trees):
            np.testing.assert_array_equal(ta["feat"], tb["feat"])
            np.testing.assert_array_equal(ta["thr"], tb["thr"])

    def test_bad_constraints_rejected(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=500)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16,
                    objective="reg:squarederror",
                    monotone_constraints=[1, 0])       # wrong length
        with pytest.raises(Error):
            m.fit(X, y)

    def test_noninteger_constraints_rejected(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        X, y = self._data(n=500)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16,
                    objective="reg:squarederror",
                    monotone_constraints=[0.5, 0, 0, 0])
        with pytest.raises(Error):
            m.fit(X, y)


class TestRoundProgramCache:
    """The process-wide compiled-round-program cache
    (histgbt._ROUND_FN_CACHE) must share programs across instances
    without leaking one instance's live param mutations into another's
    cached program."""

    def test_identical_config_shares_program_and_trees(self):
        from dmlc_core_tpu.models import HistGBT
        from dmlc_core_tpu.models import histgbt as hg

        X, y = _synthetic(n=1024, f=6, seed=11)
        m1 = HistGBT(n_trees=4, max_depth=3, n_bins=32)
        m1.fit(X, y)
        key = m1._round_fn_cache_key(6, 4)
        assert key in hg._ROUND_FN_CACHE
        m2 = HistGBT(n_trees=4, max_depth=3, n_bins=32)
        m2.fit(X, y)
        assert m1._round_fn is m2._round_fn
        for a, b in zip(m1.trees, m2.trees):
            np.testing.assert_array_equal(a["feat"], b["feat"])
            np.testing.assert_allclose(a["leaf"], b["leaf"], rtol=1e-6)

    def test_param_mutation_does_not_poison_cache(self):
        """Mutating instance A's param AFTER its fit must not change
        what a fresh same-config instance B trains with — the cached
        program snapshots every param at build time, and a RETRACE at a
        new input shape must not re-read A's live (mutated) values."""
        from dmlc_core_tpu.models import HistGBT

        X, y = _synthetic(n=1024, f=6, seed=12)
        a = HistGBT(n_trees=3, max_depth=2, n_bins=16, subsample=0.8)
        a.fit(X, y)
        a.param.subsample = 0.1          # hostile live mutation
        b = HistGBT(n_trees=3, max_depth=2, n_bins=16, subsample=0.8)
        # different row count -> padded shape differs -> jax retraces
        # the cached closure; the retrace must see 0.8, not A's 0.1
        X2, y2 = _synthetic(n=1360, f=6, seed=12)
        b.fit(X2, y2)
        # oracle: same fit through a CLEAN cache (a poisoned retrace
        # would have trained b with 0.1 — comparing b against another
        # hit of the same cached program would hide that)
        from dmlc_core_tpu.models import histgbt as hg
        hg._ROUND_FN_CACHE.clear()
        c = HistGBT(n_trees=3, max_depth=2, n_bins=16, subsample=0.8)
        c.fit(X2, y2)
        for tb, tc in zip(b.trees, c.trees):
            np.testing.assert_array_equal(tb["feat"], tc["feat"])
            np.testing.assert_allclose(tb["leaf"], tc["leaf"], rtol=1e-6)


class TestMissingValues:
    """NaN-as-missing with LEARNED default direction (XGBoost
    semantics).  The oracle is MNAR masking: a feature is masked
    exactly where its value was positive, so only a model that routes
    missing rows to the learned side can recover the signal — aliasing
    NaN into an extreme bin (the pre-feature behavior) or any fixed
    direction caps masked-row accuracy near chance."""

    @staticmethod
    def _mnar_problem(n=1500, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        Xm = X.copy()
        mask = X[:, 0] > 0
        Xm[mask, 0] = np.nan
        return X, Xm, y, mask

    def test_learned_direction_recovers_mnar_signal(self):
        from dmlc_core_tpu.models import HistGBT

        _, Xm, y, mask = self._mnar_problem()
        m = HistGBT(n_trees=10, max_depth=4, n_bins=64)
        m.fit(Xm, y)
        assert m._missing and "dir" in m.trees[0]
        pred = m.predict(Xm) > 0.5
        assert (pred == y).mean() > 0.95
        assert (pred[mask] == y[mask]).mean() > 0.95   # the masked rows

    def test_nan_free_data_unchanged(self):
        """No NaN -> no missing mode, no dir arrays: the default path
        (and its compiled program) is byte-identical to before."""
        from dmlc_core_tpu.models import HistGBT

        X, _, y, _ = self._mnar_problem()
        m = HistGBT(n_trees=5, max_depth=3, n_bins=32)
        m.fit(X, y)
        assert not m._missing
        assert "dir" not in m.trees[0]

    def test_sharded_equals_replicated_with_nan(self):
        """DP-correctness oracle extended to missing mode: the psum'd
        histograms carry the missing-bin mass, so the 8-device mesh must
        choose identical splits AND directions as 1 device."""
        from dmlc_core_tpu.models import HistGBT

        _, Xm, y, _ = self._mnar_problem(n=1024, seed=3)
        m8 = HistGBT(n_trees=5, max_depth=3, n_bins=32, mesh=local_mesh())
        m1 = HistGBT(n_trees=5, max_depth=3, n_bins=32,
                     mesh=local_mesh(1))
        m8.fit(Xm, y)
        m1.fit(Xm, y)
        for t8, t1 in zip(m8.trees, m1.trees):
            np.testing.assert_array_equal(t8["feat"], t1["feat"])
            np.testing.assert_array_equal(t8["thr"], t1["thr"])
            np.testing.assert_array_equal(t8["dir"], t1["dir"])

    def test_nan_rejected_on_non_missing_model(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        X, Xm, y, _ = self._mnar_problem(n=800)
        m = HistGBT(n_trees=3, max_depth=3, n_bins=32)
        m.fit(X, y)                       # NaN-free fit
        with pytest.raises(Error):
            m.predict(Xm)
        with pytest.raises(Error):
            m.fit(Xm, y)                  # continued fit with NaN

    def test_eval_set_early_stopping_with_nan(self):
        from dmlc_core_tpu.models import HistGBT

        _, Xm, y, _ = self._mnar_problem(n=1200, seed=5)
        m = HistGBT(n_trees=10, max_depth=3, n_bins=32,
                    eval_metric="logloss")
        m.fit(Xm[:900], y[:900], eval_set=(Xm[900:], y[900:]),
              early_stopping_rounds=5)
        assert m.best_score is not None

    def test_multiclass_with_nan(self):
        from dmlc_core_tpu.models import HistGBT

        rng = np.random.default_rng(7)
        X = rng.normal(size=(800, 5)).astype(np.float32)
        y = np.clip(np.digitize(X[:, 0], [-0.5, 0.5]), 0, 2).astype(
            np.float32)
        Xm = X.copy()
        Xm[X[:, 0] > 0.5, 0] = np.nan     # masks exactly class 2
        m = HistGBT(n_trees=5, max_depth=3, n_bins=32,
                    objective="multi:softmax", num_class=3)
        m.fit(Xm, y)
        acc = (m.predict(Xm) == y).mean()
        assert acc > 0.9, acc

    def test_dump_save_load_roundtrip(self, tmp_path):
        from dmlc_core_tpu.models import HistGBT

        _, Xm, y, _ = self._mnar_problem(n=800, seed=9)
        m = HistGBT(n_trees=4, max_depth=3, n_bins=32)
        m.fit(Xm, y)
        assert "missing=" in m.dump_model()
        uri = str(tmp_path / "miss.ckpt")
        m.save_model(uri)
        m2 = HistGBT.load_model(uri)
        assert m2._missing
        np.testing.assert_allclose(m2.predict(Xm), m.predict(Xm),
                                   rtol=1e-6)
        leaves = m2.predict_leaf(Xm[:64])
        assert leaves.shape == (64, 4)

    def test_external_memory_rejects_nan(self, tmp_path):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.data.iter import RowBlockIter
        from dmlc_core_tpu.models import HistGBT

        path = tmp_path / "nan.libsvm"
        with open(path, "w") as f:
            f.write("1 0:nan 1:2.0\n0 0:1.0 1:3.0\n")
        it = RowBlockIter.create(str(path), 0, 1, "libsvm")
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16)
        with pytest.raises(Error):
            m.fit_external(it, num_col=2)
        # explicit cuts= skips the sketch pass — the page-binning pass
        # must still reject NaN (it would otherwise silently alias into
        # the top value bin)
        cuts = jnp.asarray(np.tile(np.linspace(-1, 1, 15,
                                               dtype=np.float32), (2, 1)))
        it2 = RowBlockIter.create(str(path), 0, 1, "libsvm")
        m2 = HistGBT(n_trees=2, max_depth=2, n_bins=16)
        with pytest.raises(Error):
            m2.fit_external(it2, num_col=2, cuts=cuts)

    def test_sticky_missing_model_rejects_fit_external(self, tmp_path):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.data.iter import RowBlockIter
        from dmlc_core_tpu.models import HistGBT

        _, Xm, y, _ = self._mnar_problem(n=600, seed=11)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16)
        m.fit(Xm, y)                      # missing mode now sticky
        path = tmp_path / "clean.libsvm"
        with open(path, "w") as f:
            f.write("1 0:1.0\n0 0:2.0\n")
        it = RowBlockIter.create(str(path), 0, 1, "libsvm")
        with pytest.raises(Error):        # standard cuts would misread
            m.fit_external(it, num_col=1)  # the top value bin as missing

    def test_cuts_width_validated_against_mode(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models import HistGBT

        X, Xm, y, _ = self._mnar_problem(n=600, seed=13)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16)
        m.fit(Xm, y)
        m.trees.clear()                   # force the fresh-fit path
        # standard-width cuts [F, n_bins-1] into a missing-mode model
        # must fail loudly (the NaN bin would fall outside the histogram)
        bad = np.sort(np.random.default_rng(0).normal(
            size=(X.shape[1], 15)).astype(np.float32), axis=1)
        with pytest.raises(Error):
            m.fit(Xm, y, cuts=jnp.asarray(bad))



    def test_missing_with_sampling(self):
        """Missing mode composes with subsample/colsample (the sampled
        round program threads dir through the scan carry)."""
        from dmlc_core_tpu.models import HistGBT

        _, Xm, y, mask = self._mnar_problem(n=1500, seed=21)
        m = HistGBT(n_trees=10, max_depth=3, n_bins=32,
                    subsample=0.8, colsample_bytree=0.8, seed=3)
        m.fit(Xm, y)
        assert m._missing and "dir" in m.trees[0]
        pred = m.predict(Xm) > 0.5
        assert (pred[mask] == y[mask]).mean() > 0.85
        # deterministic across cached instances (same seed)
        m2 = HistGBT(n_trees=10, max_depth=3, n_bins=32,
                     subsample=0.8, colsample_bytree=0.8, seed=3)
        m2.fit(Xm, y)
        for a, b in zip(m.trees, m2.trees):
            np.testing.assert_array_equal(a["feat"], b["feat"])
            np.testing.assert_array_equal(a["dir"], b["dir"])


class TestRegAlpha:
    """reg_alpha (XGBoost L1 on leaf weights): gradient sums are
    soft-thresholded before weights and gains — ThresholdL1(G, a) =
    sign(G) * max(|G| - a, 0)."""

    def test_leaf_weights_shrink_toward_zero(self):
        X, y = _synthetic(n=2000, f=5, seed=17)
        base = HistGBT(n_trees=5, max_depth=3, n_bins=32)
        base.fit(X, y)
        l1 = HistGBT(n_trees=5, max_depth=3, n_bins=32, reg_alpha=2.0)
        l1.fit(X, y)
        m0 = np.mean([np.abs(t["leaf"]).mean() for t in base.trees])
        m1 = np.mean([np.abs(t["leaf"]).mean() for t in l1.trees])
        assert m1 < m0, (m1, m0)
        # huge alpha kills every leaf: |G| can never exceed it
        dead = HistGBT(n_trees=2, max_depth=3, n_bins=32,
                       reg_alpha=1e9)
        dead.fit(X, y)
        for t in dead.trees:
            np.testing.assert_allclose(t["leaf"], 0.0, atol=1e-7)

    def test_first_tree_root_leaf_matches_formula(self):
        """Depth-1 single tree: the two leaf weights must equal
        -eta * T(G_child, a) / (H_child + lam) computed by hand from
        the logistic gradients at the base margin."""
        rng = np.random.default_rng(23)
        X = rng.normal(size=(4096, 3)).astype(np.float32)
        y = (X[:, 0] > 0.2).astype(np.float32)
        a, lam, eta = 5.0, 1.0, 1.0
        m = HistGBT(n_trees=1, max_depth=1, n_bins=32, learning_rate=eta,
                    reg_lambda=lam, reg_alpha=a)
        m.fit(X, y)
        # logistic grads at margin 0: g = 0.5 - y, h = 0.25
        g = 0.5 - y
        h = np.full_like(y, 0.25)
        feat = int(m.trees[0]["feat"][0][0])
        thr = int(m.trees[0]["thr"][0][0])
        cuts = np.asarray(m.cuts)
        bins = np.searchsorted(cuts[feat], X[:, feat], side="right")
        left = bins <= thr
        def w(mask):
            G, H = g[mask].sum(), h[mask].sum()
            T = np.sign(G) * max(abs(G) - a, 0.0)
            return -eta * T / (H + lam)
        np.testing.assert_allclose(
            m.trees[0]["leaf"], [w(left), w(~left)], rtol=2e-3, atol=1e-4)

    def test_external_chunked_applies_alpha(self, tmp_path, monkeypatch):
        from dmlc_core_tpu.data.iter import RowBlockIter

        X, y = _synthetic(n=1500, f=4, seed=19)
        path = tmp_path / "a.libsvm"
        with open(path, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(4))
                f.write(f"{int(y[i])} {feats}\n")
        monkeypatch.setenv("DMLC_TPU_EXTERNAL_DEVICE_BUDGET", "40000")
        e0 = HistGBT(n_trees=3, max_depth=3, n_bins=16)
        e0.fit_external(
            RowBlockIter.create(str(path), 0, 1, "libsvm"), num_col=4)
        e1 = HistGBT(n_trees=3, max_depth=3, n_bins=16, reg_alpha=3.0)
        e1.fit_external(
            RowBlockIter.create(str(path), 0, 1, "libsvm"), num_col=4)
        m0 = np.mean([np.abs(t["leaf"]).mean() for t in e0.trees])
        m1 = np.mean([np.abs(t["leaf"]).mean() for t in e1.trees])
        assert m1 < m0, (m1, m0)

    def test_mono_plus_alpha_rejected(self):
        import pytest as pt
        from dmlc_core_tpu.base.logging import Error

        X, y = _synthetic(n=512, f=4, seed=3)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16, reg_alpha=0.5,
                    monotone_constraints=[1, 0, 0, 0])
        with pt.raises(Error):
            m.fit(X, y)


class TestScalePosWeight:
    """scale_pos_weight (XGBoost's imbalanced-data knob): positives'
    grad/hess scale by the factor — definitionally an instance weight,
    so the exactness oracle is tree-for-tree equality with an explicit
    weight vector."""

    @staticmethod
    def _imbalanced(n=2000, pos_frac=0.05, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 5)).astype(np.float32)
        y = (X[:, 0] > np.quantile(X[:, 0], 1 - pos_frac)).astype(
            np.float32)
        return X, y

    def test_equals_explicit_weights_exactly(self):
        X, y = self._imbalanced()
        spw = float((y == 0).sum() / (y == 1).sum())
        a = HistGBT(n_trees=5, max_depth=3, n_bins=32,
                    scale_pos_weight=spw)
        a.fit(X, y)
        b = HistGBT(n_trees=5, max_depth=3, n_bins=32)
        b.fit(X, y, weight=np.where(y == 1.0, np.float32(spw),
                                    np.float32(1.0)))
        for ta, tb in zip(a.trees, b.trees):
            np.testing.assert_array_equal(ta["feat"], tb["feat"])
            np.testing.assert_array_equal(ta["thr"], tb["thr"])
            np.testing.assert_allclose(ta["leaf"], tb["leaf"], rtol=1e-6)

    def test_fit_device_path_applies_it(self):
        """The make_device_data -> fit_device handle path must honor the
        knob too (it builds w_d itself)."""
        X, y = self._imbalanced(n=1200, seed=4)
        spw = 20.0
        a = HistGBT(n_trees=4, max_depth=3, n_bins=32,
                    scale_pos_weight=spw)
        dd = a.make_device_data(X, y)
        a.fit_device(dd)
        b = HistGBT(n_trees=4, max_depth=3, n_bins=32,
                    scale_pos_weight=spw)
        b.fit(X, y)
        for ta, tb in zip(a.trees, b.trees):
            np.testing.assert_array_equal(ta["feat"], tb["feat"])
            np.testing.assert_allclose(ta["leaf"], tb["leaf"], rtol=1e-6)

    def test_external_memory_matches_explicit_weights(self, tmp_path):
        """The streaming path's cuts AND trees must match the explicit
        weight vector equivalent (sketch pass sees scaled weights)."""
        from dmlc_core_tpu.data.iter import RowBlockIter

        X, y = self._imbalanced(n=600, seed=6)
        spw = 10.0
        path = tmp_path / "imb.libsvm"
        with open(path, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(5))
                f.write(f"{int(y[i])} {feats}\n")
        a = HistGBT(n_trees=4, max_depth=3, n_bins=32,
                    scale_pos_weight=spw)
        a.fit_external(RowBlockIter.create(str(path), 0, 1, "libsvm"),
                       num_col=5)
        b = HistGBT(n_trees=4, max_depth=3, n_bins=32)
        b.fit(X, y, weight=np.where(y == 1.0, np.float32(spw),
                                    np.float32(1.0)))
        # cuts come from different estimators (streaming sketch vs
        # in-core quantiles) so trees can differ at boundaries; the
        # predictions must agree
        agree = ((a.predict(X) > 0.5) == (b.predict(X) > 0.5)).mean()
        assert agree > 0.97, agree

    def test_improves_recall_on_imbalanced(self):
        X, y = self._imbalanced(n=3000, pos_frac=0.03, seed=2)
        plain = HistGBT(n_trees=10, max_depth=3, n_bins=32)
        plain.fit(X, y)
        spw = HistGBT(n_trees=10, max_depth=3, n_bins=32,
                      scale_pos_weight=30.0)
        spw.fit(X, y)
        pos = y == 1
        rec_plain = ((plain.predict(X) > 0.5)[pos]).mean()
        rec_spw = ((spw.predict(X) > 0.5)[pos]).mean()
        assert rec_spw >= rec_plain
        assert rec_spw > 0.9, rec_spw

    def test_rejected_for_non_binary_objectives(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error

        X, y = self._imbalanced(n=500)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16,
                    objective="reg:squarederror", scale_pos_weight=3.0)
        with pytest.raises(Error):
            m.fit(X, y)

    def test_sklearn_passthrough(self):
        from dmlc_core_tpu.models.sklearn import GBTClassifier

        X, y = self._imbalanced(n=1500)
        est = GBTClassifier(n_estimators=5, max_depth=3, n_bins=32,
                            scale_pos_weight=10.0)
        est.fit(X, y)
        assert est.model.param.scale_pos_weight == 10.0
        # GridSearchCV path: set_params must validate + route it
        est2 = GBTClassifier(n_estimators=2).set_params(
            scale_pos_weight=4.0)
        assert est2.get_params()["scale_pos_weight"] == 4.0
