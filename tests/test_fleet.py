"""Fleet serving: consistent-hash stability, router failover
bit-parity, staged rollout/rollback (pure), admission control honored
by ResilientClient, autoscale policy hysteresis, and a slow-marked
3-replica soak with one replica SIGKILLed mid-traffic."""

import json
import os
import signal
import tempfile
import threading
import time

import numpy as np
import pytest

from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.base.resilience import RetryPolicy
from dmlc_core_tpu.serve import ResilientClient, checkpoint_model
from dmlc_core_tpu.serve.fleet import (AutoscalePolicy, FleetAdmin,
                                       FleetRouter, FleetTracker, HashRing,
                                       Replica, Rollout, RolloutController,
                                       diurnal_qps, plan_waves, sample_size,
                                       spawn_replica)

F = 6


def _make_data(n, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


class TestHashRing:
    def test_deterministic_and_complete(self):
        keys = [f"key-{i}".encode() for i in range(500)]
        r1 = HashRing([0, 1, 2, 3], vnodes=64)
        r2 = HashRing([3, 2, 1, 0], vnodes=64)   # order-independent
        assert [r1.lookup(k) for k in keys] == [r2.lookup(k) for k in keys]
        # every node owns SOME keys (vnodes spread the ring)
        owners = {r1.lookup(k) for k in keys}
        assert owners == {0, 1, 2, 3}

    def test_bounded_key_movement_on_membership_change(self):
        """Removing one of n nodes moves ONLY the keys it owned (~1/n);
        every other key keeps its owner — the property that makes the
        ring worth having over hash-mod-n."""
        keys = [f"req-{i}".encode() for i in range(4000)]
        full = HashRing([0, 1, 2, 3, 4], vnodes=64)
        down = HashRing([0, 1, 2, 3], vnodes=64)
        moved = 0
        for k in keys:
            before, after = full.lookup(k), down.lookup(k)
            if before != after:
                moved += 1
                assert before == 4          # only the dead node's keys move
        # ~1/5 of keys lived on node 4; generous slack for hash variance
        assert 0.05 < moved / len(keys) < 0.40

    def test_sequence_is_distinct_failover_order(self):
        ring = HashRing(["a", "b", "c"], vnodes=32)
        for i in range(50):
            seq = ring.sequence(f"k{i}".encode())
            assert seq[0] == ring.lookup(f"k{i}".encode())
            assert sorted(seq) == ["a", "b", "c"]    # all, no dupes

    def test_empty_ring(self):
        assert HashRing([]).sequence(b"x") == []


class _FakeAdmin(FleetAdmin):
    """Pure in-memory fleet: per-rank version registries, optional
    fail-health injection after a given activation count."""

    def __init__(self, ranks, fail_on_activation=None):
        self._ranks = list(ranks)
        self.active = {r: 1 for r in ranks}
        self.staged = {r: [1] for r in ranks}
        self.log = []
        self._fail_on = fail_on_activation      # rank whose health lies
        self._next_version = {r: 2 for r in ranks}

    def replicas(self):
        return {r: f"fake://{r}" for r in self._ranks}

    def load(self, rank, uri, activate=False):
        v = self._next_version[rank]
        self._next_version[rank] += 1
        self.staged[rank].append(v)
        self.log.append(("load", rank, v, activate))
        if activate:
            self.active[rank] = v
        return v

    def activate(self, rank, version):
        assert version in self.staged[rank]
        self.active[rank] = version
        self.log.append(("activate", rank, version))

    def health(self, rank):
        status = "ok"
        if self._fail_on is not None and rank == self._fail_on \
                and self.active[rank] != 1:
            status = "unhealthy"
        return {"status": status, "version": self.active[rank]}


class TestRolloutPure:
    def test_plan_waves(self):
        assert plan_waves([1, 2, 3, 4, 5], 2) == [[1, 2], [3, 4], [5]]
        assert plan_waves([7], 3) == [[7]]
        assert plan_waves([], 1) == []
        with pytest.raises(Exception):
            plan_waves([1], 0)

    def test_controller_happy_path(self):
        ctrl = RolloutController([0, 1, 2, 3, 4], wave_size=2)
        ctrl.staged()
        seen = []
        while (wave := ctrl.next_wave()) is not None:
            seen.append(wave)
            ctrl.wave_ok()
        assert seen == [[0, 1], [2, 3], [4]]
        assert ctrl.state == RolloutController.DONE
        assert ctrl.activated == [0, 1, 2, 3, 4]
        assert ctrl.next_wave() is None          # idempotent when done

    def test_controller_rollback_targets(self):
        ctrl = RolloutController([0, 1, 2, 3], wave_size=1)
        ctrl.staged()
        ctrl.next_wave(); ctrl.wave_ok()         # 0 activated
        ctrl.next_wave(); ctrl.wave_ok()         # 1 activated
        ctrl.next_wave()                          # 2 activating...
        targets = ctrl.wave_failed()
        # failed wave included, most recent first
        assert targets == [2, 1, 0]
        assert ctrl.state == RolloutController.ROLLED_BACK

    def test_rollout_driver_activates_in_waves(self):
        admin = _FakeAdmin([0, 1, 2])
        report = Rollout(admin, wave_size=2, settle_s=0.0).run("fake://v2")
        assert report["outcome"] == "activated"
        assert [w["replicas"] for w in report["waves"]] == [[0, 1], [2]]
        assert admin.active == {0: 2, 1: 2, 2: 2}
        # staged on ALL replicas before the FIRST activation
        first_activate = admin.log.index(("activate", 0, 2))
        loads = [e for e in admin.log[:first_activate] if e[0] == "load"]
        assert len(loads) == 3 and all(not e[3] for e in loads)

    def test_rollout_rolls_back_on_health_regression(self):
        admin = _FakeAdmin([0, 1, 2], fail_on_activation=1)
        report = Rollout(admin, wave_size=1, settle_s=0.0).run("fake://v2")
        assert report["outcome"] == "rolled_back"
        assert report["rolled_back"] == [1, 0]   # reverse activation order
        assert admin.active == {0: 1, 1: 1, 2: 1}   # all back on v1

    def test_rollout_eval_gate_rejection(self):
        admin = _FakeAdmin([0, 1])
        r = Rollout(admin, wave_size=2, settle_s=0.0,
                    eval_gate=lambda v: False)
        report = r.run("fake://v2")
        assert report["outcome"] == "rolled_back"
        assert admin.active == {0: 1, 1: 1}


class TestAutoscalePolicy:
    def test_patience_hysteresis(self):
        p = AutoscalePolicy(high_s=0.1, low_s=0.01, patience=3,
                            min_replicas=1, max_replicas=8)
        assert p.observe(0.5, 3) == 0            # streak 1
        assert p.observe(0.5, 3) == 0            # streak 2
        assert p.observe(0.005, 3) == 0          # opposite sample resets
        assert p.observe(0.5, 3) == 0
        assert p.observe(0.5, 3) == 0
        assert p.observe(0.5, 3) == 1            # 3 consecutive highs
        assert p.observe(0.5, 3) == 0            # recommendation consumed

    def test_bounds_and_idle(self):
        p = AutoscalePolicy(high_s=0.1, low_s=0.01, patience=1,
                            min_replicas=2, max_replicas=3)
        assert p.observe(None, 2) == 0           # no signal: hold
        assert p.observe(0.5, 3) == 0            # at ceiling: no +1
        assert p.observe(0.001, 2) == 0          # at floor: no -1
        assert p.observe(0.001, 3) == -1
        assert p.observe(0.5, 2) == 1

    def test_in_band_resets(self):
        p = AutoscalePolicy(high_s=0.1, low_s=0.01, patience=2)
        assert p.observe(0.5, 1) == 0
        assert p.observe(0.05, 1) == 0           # in-band: reset
        assert p.observe(0.5, 1) == 0
        assert p.observe(0.5, 1) == 1


class TestLoadgenPure:
    def test_sample_size_bounds_and_tail(self):
        rng = np.random.default_rng(7)
        sizes = [sample_size(rng, alpha=1.2, max_size=32)
                 for _ in range(5000)]
        assert min(sizes) >= 1 and max(sizes) <= 32
        small = sum(1 for s in sizes if s <= 4)
        big = sum(1 for s in sizes if s >= 16)
        assert small > len(sizes) * 0.5          # mostly small...
        assert big > 0                           # ...with a real tail

    def test_diurnal_qps_envelope(self):
        qs = [diurnal_qps(t, 100.0, amplitude=0.5, period_s=10.0)
              for t in np.linspace(0, 10, 101)]
        assert max(qs) == pytest.approx(150.0, rel=0.01)
        assert min(qs) >= 10.0                   # floored
        assert qs[0] == pytest.approx(100.0)


class _FleetHarness:
    """3 in-process replicas + tracker + router over real sockets."""

    def __init__(self, tmp, n=3, **router_kw):
        X, y = _make_data(400)
        self.X = X
        m1 = HistGBT(n_trees=3, max_depth=3, n_bins=16).fit(X, y)
        m2 = HistGBT(n_trees=5, max_depth=3, n_bins=16).fit(X, y)
        self.direct = {1: m1.predict(X), 2: m2.predict(X)}
        self.v1 = f"file://{tmp}/v1.ckpt"
        self.v2 = f"file://{tmp}/v2.ckpt"
        checkpoint_model(self.v1, m1, version=1)
        checkpoint_model(self.v2, m2, version=2)
        self.tracker = FleetTracker(nworker=8)
        self.tracker.start()
        self.replicas = [
            Replica("127.0.0.1", self.tracker.port, model_uri=self.v1,
                    max_batch=32, heartbeat_s=0.1) for _ in range(n)]
        self.router = FleetRouter(self.tracker, probe_s=0.1,
                                  **router_kw).start()

    def close(self):
        self.router.close()
        for r in self.replicas:
            try:
                r.close()
            except Exception:
                pass
        self.tracker.stop()


class TestFleetRouter:
    def test_failover_bit_parity_vs_direct(self):
        """Predicts through the router are bit-identical to direct
        model.predict — including after a replica dies uncleanly and
        traffic reroutes."""
        with tempfile.TemporaryDirectory() as tmp:
            h = _FleetHarness(tmp)
            try:
                client = ResilientClient(
                    h.router.url, policy=RetryPolicy(max_attempts=6,
                                                     base_backoff_s=0.01))
                for lo, k in ((0, 1), (7, 5), (100, 17), (390, 9)):
                    preds, ver = client.predict(h.X[lo:lo + k])
                    assert ver == 1
                    assert np.array_equal(preds, h.direct[1][lo:lo + k])
                # unclean death: socket drops, no shutdown cmd
                h.replicas[0].close(clean=False)
                h.router.probe_now()
                assert 0 in h.tracker.dead_workers
                for lo, k in ((3, 4), (55, 8), (200, 3), (301, 12)):
                    preds, ver = client.predict(h.X[lo:lo + k])
                    assert np.array_equal(preds, h.direct[1][lo:lo + k])
                docs = h.router.replica_docs()
                assert sum(1 for d in docs.values() if d["healthy"]) == 2
            finally:
                h.close()

    def test_admission_control_503_honored_by_client(self):
        """A fleet-wide queue-bound shed answers 503 + Retry-After; the
        ResilientClient retries (spaced by the hint) and succeeds once
        the bound lifts — no caller-visible failure."""
        with tempfile.TemporaryDirectory() as tmp:
            h = _FleetHarness(tmp, max_queue=-1)   # every predict sheds
            try:
                client = ResilientClient(
                    h.router.url,
                    policy=RetryPolicy(max_attempts=8, base_backoff_s=0.01,
                                       retry_after_cap_s=0.2))
                lifted = threading.Event()

                def lift():
                    time.sleep(0.4)
                    h.router.max_queue = 10_000
                    lifted.set()

                threading.Thread(target=lift, daemon=True).start()
                t0 = time.monotonic()
                preds, ver = client.predict(h.X[:4])
                assert lifted.is_set()            # success only after lift
                assert time.monotonic() - t0 >= 0.2   # spaced, not hammered
                assert np.array_equal(preds, h.direct[ver][:4])
            finally:
                h.close()

    def test_staged_rollout_under_light_traffic(self):
        """v1→v2 rollout with wave_size=1 while predicts flow: every
        response bit-matches the version it claims, final state all-v2,
        zero hard failures."""
        from dmlc_core_tpu.serve.fleet import HttpFleetAdmin, Rollout

        with tempfile.TemporaryDirectory() as tmp:
            h = _FleetHarness(tmp)
            try:
                client = ResilientClient(
                    h.router.url, policy=RetryPolicy(max_attempts=6,
                                                     base_backoff_s=0.01))
                out, stop = [], threading.Event()

                def loop(seed):
                    rng = np.random.default_rng(seed)
                    while not stop.is_set():
                        k = int(rng.integers(1, 9))
                        lo = int(rng.integers(0, len(h.X) - k))
                        try:
                            preds, ver = client.predict(h.X[lo:lo + k])
                            out.append((ver, bool(np.array_equal(
                                preds, h.direct[ver][lo:lo + k]))))
                        except Exception as e:
                            out.append(("error", repr(e)))

                threads = [threading.Thread(target=loop, args=(s,))
                           for s in range(3)]
                for t in threads:
                    t.start()
                time.sleep(0.3)
                admin = HttpFleetAdmin(h.tracker.serve_endpoints())
                report = Rollout(admin, wave_size=1,
                                 settle_s=0.1).run(h.v2)
                time.sleep(0.3)
                stop.set()
                for t in threads:
                    t.join(timeout=30)
                assert report["outcome"] == "activated"
                errors = [o for o in out if o[0] == "error"]
                assert not errors, errors[:3]
                assert all(match for _, match in out)
                assert {v for v, _ in out} == {1, 2}  # both served traffic
                for r in h.replicas:
                    assert r.registry.current_version() == 2
            finally:
                h.close()


class TestFrontendDrain:
    def test_drain_stops_admission_finishes_inflight(self):
        """Regression for graceful shutdown: /drain flips healthz,
        sheds NEW predicts with 503 + Retry-After, while queued and
        in-flight requests complete correctly; close() then returns
        with nothing dropped."""
        import urllib.request

        from dmlc_core_tpu.serve import ModelRegistry, ServeFrontend

        class _Slow:
            def predict(self, Z):
                time.sleep(0.25)
                return Z[:, 0]

        reg = ModelRegistry(name="drain-test", max_batch=4, min_bucket=1)
        reg.publish(_Slow())
        fe = ServeFrontend(reg, max_batch=4, max_delay=0.0, max_queue=64,
                           request_timeout=10.0)
        fe.start()
        results = []

        def hit(lo):
            body = json.dumps(
                {"rows": [[float(lo)] * F]}).encode()
            req = urllib.request.Request(
                fe.url + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    results.append((r.status, json.loads(r.read())))
            except urllib.error.HTTPError as e:
                results.append((e.code, json.loads(e.read() or b"{}")))

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.1)                  # in-flight inside the batcher
        st, body = _post_raw(fe.url + "/drain")
        assert st == 200 and body["status"] == "draining"
        # new work is refused with the backpressure contract
        st, body, headers = _post_predict_raw(fe.url, [[1.0] * F])
        assert st == 503 and "retry-after" in headers
        st, health = _get_json(fe.url + "/healthz")
        assert health["status"] == "draining"
        for t in threads:
            t.join(timeout=30)
        fe.close()
        assert len(results) == 3
        for st, body in results:
            assert st == 200                     # in-flight all completed
        # after close the socket is gone
        with pytest.raises(Exception):
            _get_json(fe.url + "/healthz", timeout=2)


def _get_json(url, timeout=10):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _post_raw(url):
    import urllib.request

    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _post_predict_raw(url, rows):
    import urllib.request

    body = json.dumps({"rows": np.asarray(rows).tolist()}).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        hdrs = {k.lower(): v for k, v in e.headers.items()}
        return e.code, json.loads(e.read() or b"{}"), hdrs


@pytest.mark.slow
class TestFleetSoak:
    def test_sigkill_one_replica_zero_dropped_zero_wrong(self):
        """3 subprocess replicas behind the router; SIGKILL one mid-
        traffic.  The router fails predicts over, its breaker opens, the
        tracker records the death — and NOT ONE client request is
        dropped or answered wrong."""
        with tempfile.TemporaryDirectory() as tmp:
            X, y = _make_data(400)
            m1 = HistGBT(n_trees=3, max_depth=3, n_bins=16).fit(X, y)
            direct = {1: m1.predict(X)}
            v1 = f"file://{tmp}/v1.ckpt"
            checkpoint_model(v1, m1, version=1)
            tracker = FleetTracker(nworker=8)
            tracker.start()
            env = {"JAX_PLATFORMS": "cpu", "DMLC_TPU_FORCE_CPU": "1"}
            procs = [spawn_replica("127.0.0.1", tracker.port,
                                   model_uri=v1, max_batch=32,
                                   extra_env=env) for _ in range(3)]
            router = None
            try:
                deadline = time.time() + 120
                while len(tracker.serve_endpoints()) < 3:
                    assert time.time() < deadline, "replicas never joined"
                    time.sleep(0.2)
                router = FleetRouter(tracker, probe_s=0.1).start()
                client_policy = RetryPolicy(max_attempts=8, base_backoff_s=0.02,
                                            deadline_s=30.0)
                out, stop = [], threading.Event()

                def loop(seed):
                    c = ResilientClient(router.url, policy=client_policy)
                    rng = np.random.default_rng(seed)
                    while not stop.is_set():
                        k = int(rng.integers(1, 9))
                        lo = int(rng.integers(0, len(X) - k))
                        try:
                            preds, ver = c.predict(X[lo:lo + k],
                                                   timeout_ms=10_000)
                            out.append(("ok", bool(np.array_equal(
                                preds, direct[ver][lo:lo + k]))))
                        except Exception as e:
                            out.append(("dropped", repr(e)))

                threads = [threading.Thread(target=loop, args=(s,))
                           for s in range(4)]
                for t in threads:
                    t.start()
                time.sleep(1.0)
                victim = procs[1]
                os.kill(victim.pid, signal.SIGKILL)
                victim.wait(timeout=10)
                time.sleep(2.0)
                stop.set()
                for t in threads:
                    t.join(timeout=60)

                dropped = [o for o in out if o[0] == "dropped"]
                oks = [o for o in out if o[0] == "ok"]
                assert not dropped, f"dropped: {dropped[:3]}"
                assert len(oks) > 50
                assert all(m for _, m in oks), "wrong answers"
                assert tracker.dead_workers, "tracker missed the death"
                assert len(tracker.serve_endpoints()) == 2
            finally:
                if router is not None:
                    router.close()
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                        try:
                            p.wait(timeout=15)
                        except Exception:
                            p.kill()
                tracker.stop()
