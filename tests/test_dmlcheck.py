"""dmlcheck (static analyzer) + lockcheck (dynamic verifier) contracts.

Each static pass gets golden fixture snippets: at least one that MUST
flag and one that must stay clean, so a pass that silently dies (or
silently over-matches) fails here before it lies in CI.  Fixtures are
written into a throwaway mini-repo layout (the walker scans the same
directory names as the real one) — nothing is imported, only parsed.
"""

from __future__ import annotations

import os
import textwrap
import threading
import time

import pytest

from dmlc_core_tpu.analysis import analyze, load_baseline, write_baseline
from dmlc_core_tpu.base import lockcheck


def _mini_repo(tmp_path, files, docs=None, knobs=()):
    """Lay out {relpath: source} plus an optional doc set and a knob
    registry; returns the root to hand to analyze()."""
    root = tmp_path / "repo"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    knob_lines = ["def declare(*a, **k):\n    pass\n"] + [
        f'declare("{name}", "", "doc")\n' for name in knobs]
    kp = root / "dmlc_core_tpu" / "base" / "knobs.py"
    if not kp.exists():
        kp.parent.mkdir(parents=True, exist_ok=True)
        kp.write_text("".join(knob_lines))
    for rel, text in (docs or {}).items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return str(root)


def _findings(ctx, rule=None):
    return [f for f in ctx.findings if rule is None or f.rule == rule]


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_LOCKED_CLASS_BAD = """
    import threading

    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def add(self, v):
            with self._lock:
                self._items.append(v)

        def peek(self):
            return self._items[-1]      # unguarded read of locked state
"""

_LOCKED_CLASS_GOOD = """
    import threading

    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._config = 3            # never locked -> never flagged

        def add(self, v):
            with self._lock:
                self._items.append(v)

        def peek(self):
            with self._lock:
                return self._items[-1]

        def _drain_locked(self):
            # *_locked convention: caller holds the lock
            out = list(self._items)
            self._items.clear()
            return out

        def scale(self):
            return self._config * 2
"""


def test_lock_discipline_flags_unguarded_access(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _LOCKED_CLASS_BAD}),
                  rules=["lock-discipline"])
    got = _findings(ctx, "lock-discipline")
    assert len(got) == 1 and "Shared._items" in got[0].message
    assert got[0].key == "Shared._items:peek"


def test_lock_discipline_clean_class_and_locked_convention(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _LOCKED_CLASS_GOOD}),
                  rules=["lock-discipline"])
    assert _findings(ctx) == []


def test_lock_discipline_ignores_code_outside_package(tmp_path):
    # the pass hunts product code, not test fixtures/scripts
    ctx = analyze(_mini_repo(tmp_path,
                             {"scripts/tool.py": _LOCKED_CLASS_BAD}),
                  rules=["lock-discipline"])
    assert _findings(ctx) == []


# ---------------------------------------------------------------------------
# lock-release
# ---------------------------------------------------------------------------

_ACQUIRE_BAD = """
    import threading
    _lk = threading.Lock()

    def leaky():
        _lk.acquire()
        do_work()
        _lk.release()
"""

_ACQUIRE_GOOD = """
    import threading
    _lk = threading.Lock()

    def safe():
        _lk.acquire()
        try:
            do_work()
        finally:
            _lk.release()
"""


def test_lock_release_flags_missing_try_finally(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _ACQUIRE_BAD}),
                  rules=["lock-release"])
    got = _findings(ctx, "lock-release")
    assert len(got) == 1 and "try/finally" in got[0].message


def test_lock_release_accepts_try_finally(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _ACQUIRE_GOOD}),
                  rules=["lock-release"])
    assert _findings(ctx) == []


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

_JIT_BAD = """
    import os
    import time
    import jax

    def _helper(x):
        return x * float(os.environ.get("SCALE", "1"))

    @jax.jit
    def kernel(x):
        return _helper(x) + time.time()

    _log = []

    def stepper(x):
        _log.append(1)
        return x + 1

    step = jax.jit(stepper)
"""

_JIT_GOOD = """
    import os
    import jax
    import jax.numpy as jnp

    CFG = float(os.environ.get("SCALE", "1"))   # read at import, fine

    @jax.jit
    def kernel(x):
        def inner(c, v):
            return c + v * CFG, None
        total, _ = jax.lax.scan(inner, jnp.zeros(()), x)
        return total
"""


def test_jit_purity_flags_env_clock_and_closure_mutation(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _JIT_BAD}),
                  rules=["jit-purity"])
    msgs = [f.message for f in _findings(ctx, "jit-purity")]
    assert any("os.environ" in m and "via _helper" in m for m in msgs), msgs
    assert any("clock" in m for m in msgs), msgs
    assert any("mutates closed-over '_log'" in m for m in msgs), msgs


def test_jit_purity_clean_kernel(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _JIT_GOOD}),
                  rules=["jit-purity"])
    assert _findings(ctx) == []


# ---------------------------------------------------------------------------
# knob registry
# ---------------------------------------------------------------------------

_KNOB_USE = """
    import os
    FLAG = os.environ.get("DMLC_FIXTURE_FLAG", "0")
"""


def test_knob_registry_flags_undeclared(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _KNOB_USE}),
                  rules=["knob-registry"])
    got = _findings(ctx, "knob-registry")
    assert len(got) == 1 and got[0].key == "DMLC_FIXTURE_FLAG"


def test_knob_registry_and_doc_clean_when_declared_and_documented(tmp_path):
    root = _mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": _KNOB_USE},
                      docs={"doc/configuration.md":
                            "| `DMLC_FIXTURE_FLAG` | ... |\n"},
                      knobs=["DMLC_FIXTURE_FLAG"])
    ctx = analyze(root, rules=["knob-registry", "knob-doc"])
    assert _findings(ctx) == []


def test_knob_doc_flags_undocumented_declaration(tmp_path):
    root = _mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": _KNOB_USE},
                      knobs=["DMLC_FIXTURE_FLAG"])
    ctx = analyze(root, rules=["knob-doc"])
    got = _findings(ctx, "knob-doc")
    assert len(got) == 1 and got[0].path.endswith("knobs.py")


# ---------------------------------------------------------------------------
# metric registry
# ---------------------------------------------------------------------------

_METRIC_A = """
    def mod_metrics(r):
        return r.counter("widget_total", "widgets", labels=("kind",))
"""

_METRIC_B_CONFLICT = """
    def other_metrics(r):
        return r.counter("widget_total", "widgets", labels=("color",))
"""


def test_metric_registry_flags_label_conflict(tmp_path):
    root = _mini_repo(tmp_path, {
        "dmlc_core_tpu/a.py": _METRIC_A,
        "dmlc_core_tpu/b.py": _METRIC_B_CONFLICT,
    }, docs={"doc/observability.md": "`dmlc_widget_total`\n"})
    ctx = analyze(root, rules=["metric-registry", "metric-doc"])
    got = _findings(ctx, "metric-registry")
    assert len(got) == 1 and "re-declared" in got[0].message
    assert _findings(ctx, "metric-doc") == []


def test_metric_registry_identical_redeclaration_ok_and_doc_flags(tmp_path):
    root = _mini_repo(tmp_path, {
        "dmlc_core_tpu/a.py": _METRIC_A,
        "dmlc_core_tpu/b.py": _METRIC_A.replace("mod_", "other_"),
    })
    ctx = analyze(root, rules=["metric-registry", "metric-doc"])
    assert _findings(ctx, "metric-registry") == []
    got = _findings(ctx, "metric-doc")
    assert len(got) == 1 and got[0].key == "dmlc_widget_total"


# ---------------------------------------------------------------------------
# style / unused imports (the folded lint.py)
# ---------------------------------------------------------------------------

def test_style_and_unused_import(tmp_path):
    src = ("import os\n"
           "import sys  # noqa\n"
           "X = 1   \n")
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["unused-import", "style", "syntax"])
    rules = sorted(f.rule for f in ctx.findings)
    assert rules == ["style", "unused-import"]   # noqa respected
    assert any("trailing whitespace" in f.message for f in ctx.findings)


def test_syntax_error_reported_not_crashed(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": "def broken(:\n"}))
    got = _findings(ctx, "syntax")
    assert len(got) == 1


# ---------------------------------------------------------------------------
# suppression + baseline round-trip
# ---------------------------------------------------------------------------

def test_inline_suppression(tmp_path):
    src = _LOCKED_CLASS_BAD.replace(
        "return self._items[-1]      # unguarded read of locked state",
        "return self._items[-1]  # dmlcheck: off:lock-discipline")
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["lock-discipline"])
    assert _findings(ctx) == []
    assert ctx.suppressed_count == 1


def test_file_level_suppression(tmp_path):
    src = "# dmlcheck: off\n" + textwrap.dedent(_LOCKED_CLASS_BAD)
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["lock-discipline"])
    assert _findings(ctx) == [] and ctx.suppressed_count == 1


def test_unknown_suppression_rule_is_loud(tmp_path):
    src = "x = 1  # dmlcheck: off:not-a-rule\n"
    with pytest.raises(ValueError, match="unknown dmlcheck rule"):
        analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}))


def test_docstring_mentioning_grammar_does_not_suppress(tmp_path):
    src = '"""Docs: use ``# dmlcheck: off`` to suppress."""\n' \
          + textwrap.dedent(_LOCKED_CLASS_BAD)
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["lock-discipline"])
    assert len(_findings(ctx, "lock-discipline")) == 1


def test_baseline_round_trip_and_line_drift(tmp_path):
    root = _mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": _LOCKED_CLASS_BAD})
    ctx = analyze(root, rules=["lock-discipline"])
    assert len(ctx.findings) == 1
    bp = str(tmp_path / "baseline.json")
    write_baseline(bp, ctx.findings)
    baseline = load_baseline(bp)
    assert [f for f in ctx.findings
            if f.fingerprint not in baseline] == []
    # insert lines ABOVE the finding: lineno moves, fingerprint must not
    mod = os.path.join(root, "dmlc_core_tpu", "mod.py")
    with open(mod) as f:
        drifted = "# a comment\n# another\n" + f.read()
    with open(mod, "w") as f:
        f.write(drifted)
    ctx2 = analyze(root, rules=["lock-discipline"])
    assert len(ctx2.findings) == 1
    assert ctx2.findings[0].line != ctx.findings[0].line
    assert ctx2.findings[0].fingerprint in baseline


def test_repo_is_clean_under_committed_baseline():
    """The acceptance gate itself: the real repo, the real baseline."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ctx = analyze(root)
    baseline = load_baseline(
        os.path.join(root, "scripts", "dmlcheck_baseline.json"))
    live = [f for f in ctx.findings if f.fingerprint not in baseline]
    assert live == [], "\n".join(f.render() for f in live)
    # baseline discipline: base/, serve/, tracker/ must not be
    # grandfathered — their findings get FIXED (ISSUE 5 satellite)
    for fp in baseline:
        assert not fp.startswith(("dmlc_core_tpu/base/",
                                  "dmlc_core_tpu/serve/",
                                  "dmlc_core_tpu/tracker/")), fp


# ---------------------------------------------------------------------------
# lockcheck: the dynamic side
# ---------------------------------------------------------------------------

@pytest.fixture
def traced():
    installed_before = lockcheck.installed()
    if not installed_before:
        lockcheck.install()
    yield
    if not installed_before:
        lockcheck.uninstall()
    lockcheck.reset()


def test_lockcheck_detects_inverted_pair(traced):
    a = threading.Lock()
    b = threading.Lock()

    def ab():
        with a:
            time.sleep(0.005)
            with b:
                pass

    def ba():
        with b:
            time.sleep(0.005)
            with a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    assert lockcheck.violations(), "inverted lock order not detected"
    with pytest.raises(lockcheck.LockOrderError):
        lockcheck.check()


def test_lockcheck_consistent_order_is_clean(traced):
    a = threading.Lock()
    b = threading.Lock()

    def ab():
        with a:
            with b:
                pass

    ts = [threading.Thread(target=ab) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert lockcheck.violations() == []
    lockcheck.check()   # must not raise


def test_lockcheck_condition_queue_integration(traced):
    """Traced plain Locks must survive Condition wait/notify — the
    ConcurrentBlockingQueue path every producer/consumer rides."""
    from dmlc_core_tpu.io.concurrency import ConcurrentBlockingQueue

    q = ConcurrentBlockingQueue(max_size=2)
    got = []

    def consumer():
        for _ in range(20):
            got.append(q.pop(timeout=5.0))

    t = threading.Thread(target=consumer)
    t.start()
    for i in range(20):
        q.push(i, timeout=5.0)
    t.join()
    assert got == list(range(20))
    assert lockcheck.violations() == []


def test_lockcheck_rlock_condition_wait(traced):
    """Default Condition() (RLock inside) exercises the
    _release_save/_acquire_restore protocol on the traced wrapper."""
    cond = threading.Condition()
    ready = []

    def waiter():
        with cond:
            while not ready:
                cond.wait(timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.02)
    with cond:
        ready.append(1)
        cond.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert lockcheck.violations() == []


def test_lockcheck_env_gate(monkeypatch):
    monkeypatch.setenv("DMLC_LOCKCHECK", "1")
    assert lockcheck.env_enabled()
    monkeypatch.setenv("DMLC_LOCKCHECK", "0")
    assert not lockcheck.env_enabled()


# ---------------------------------------------------------------------------
# lock-blocking (ISSUE 11): blocking calls while a lock is held
# ---------------------------------------------------------------------------

_BLOCKING_BAD = """
    import threading
    import time

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._done = threading.Event()
            self._jobs = []

        def step(self):
            with self._lock:
                time.sleep(1.0)         # world stops with you

        def push_locked(self, sock):
            data = sock.recv(4096)      # network time under the lock
            self._jobs.append(data)

        def drain(self, work_queue):
            with self._lock:
                return work_queue.get()     # untimed queue op

        def settle(self):
            with self._lock:
                self._done.wait()       # Event.wait releases NOTHING
"""

_BLOCKING_GOOD = """
    import threading
    import time

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            self._jobs = []

        def step(self):
            with self._lock:
                jobs = list(self._jobs)
            time.sleep(0.1)             # sleep OUTSIDE the lock
            return jobs

        def wait_ready(self):
            with self._cv:
                self._cv.wait()         # own condvar: releases monitor

        def bounded(self, work_queue, ev):
            with self._lock:
                item = work_queue.get(timeout=1.0)   # bounded
                ev.wait(0.5)                         # bounded
                return item
"""


def test_lock_blocking_flags_sleep_socket_queue_wait(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _BLOCKING_BAD}),
                  rules=["lock-blocking"])
    got = _findings(ctx, "lock-blocking")
    whats = sorted(f.key.split(":")[-1] for f in got)
    assert whats == ["queue.get", "socket.recv", "time.sleep", "wait"]


def test_lock_blocking_clean_patterns(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _BLOCKING_GOOD}),
                  rules=["lock-blocking"])
    assert _findings(ctx, "lock-blocking") == []


def test_lock_blocking_skips_lockless_classes(tmp_path):
    src = """
        import time

        class Free:
            def nap(self):
                time.sleep(1.0)     # no lock attrs -> out of scope
    """
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["lock-blocking"])
    assert _findings(ctx, "lock-blocking") == []


# ---------------------------------------------------------------------------
# atomicity (ISSUE 11): unlocked compounds on mixed-locking attributes
# ---------------------------------------------------------------------------

_ATOMICITY_BAD = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._open = False

        def snapshot(self):
            with self._lock:
                return self._n, self._open

        def bump(self):
            self._n += 1            # unlocked RMW: updates lost

        def close_once(self):
            if self._open:
                self._open = False  # unlocked check-then-act
"""

_ATOMICITY_GOOD = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._hits = 0          # never locked -> lock-free by design

        def snapshot(self):
            with self._lock:
                return self._n

        def bump(self):
            with self._lock:
                self._n += 1        # compound under the lock

        def hit(self):
            self._hits += 1

        def _drain_locked(self):
            self._n += 1            # *_locked: caller holds the lock
"""


def test_atomicity_flags_unlocked_rmw_and_check_then_act(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _ATOMICITY_BAD}),
                  rules=["atomicity"])
    got = _findings(ctx, "atomicity")
    kinds = sorted((f.key.split(":")[0], f.key.split(":")[-1])
                   for f in got)
    assert kinds == [("Counter._n", "rmw"),
                     ("Counter._open", "check-then-act")]
    assert all("not atomic" in f.message for f in got)


def test_atomicity_clean_locked_compounds_and_lockfree_attrs(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _ATOMICITY_GOOD}),
                  rules=["atomicity"])
    assert _findings(ctx, "atomicity") == []


def test_atomicity_suppression(tmp_path):
    src = _ATOMICITY_BAD.replace(
        "self._n += 1            # unlocked RMW: updates lost",
        "self._n += 1  # dmlcheck: off:atomicity")
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["atomicity"])
    assert len(_findings(ctx, "atomicity")) == 1    # the other one
    assert ctx.suppressed_count == 1


# ---------------------------------------------------------------------------
# resource-leak (ISSUE 15): acquisition shapes for OS handles
# ---------------------------------------------------------------------------

_RESOURCE_BAD = """
    import socket
    import subprocess
    import tempfile

    def probe(host):
        s = socket.socket()
        s.connect((host, 80))
        data = s.recv(1)        # s never closed/transferred
        return data

    def fire():
        subprocess.Popen(["sleep", "1"])    # bare: only handle discarded

    def scratch(blob):
        fd, path = tempfile.mkstemp()
        record(path, blob)      # fd leaks (path escaped, fd did not)

    class Holder:
        def start(self):
            self._sock = socket.create_connection(("h", 80))
        # no close/stop/shutdown/__del__ anywhere in the class
"""

_RESOURCE_GOOD = """
    import socket
    import subprocess
    import os
    import tempfile

    def probe(host):
        with socket.create_connection((host, 80)) as s:
            return s.recv(1)

    def connect(host):
        s = socket.socket()
        s.connect((host, 80))
        return s                # ownership transferred to the caller

    def spawn(cmd, registry):
        p = subprocess.Popen(cmd)
        registry.track(p)       # handed to an owner
        return p.pid

    def scratch(blob):
        fd, path = tempfile.mkstemp()
        os.close(fd)
        return path

    class Holder:
        def start(self):
            self._sock = socket.create_connection(("h", 80))

        def close(self):        # registered teardown owns self._sock
            self._sock.close()
"""


def test_resource_leak_flags_unreleased_shapes(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _RESOURCE_BAD}),
                  rules=["resource-leak"])
    keys = sorted(f.key for f in _findings(ctx, "resource-leak"))
    assert keys == ["Holder.start:self._sock", "fire:bare-subprocess",
                    "probe:s", "scratch:fd"]
    assert any("declares no teardown" in f.message for f in ctx.findings)


def test_resource_leak_clean_lifecycle_shapes(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _RESOURCE_GOOD}),
                  rules=["resource-leak"])
    assert _findings(ctx) == []


def test_resource_leak_suppression(tmp_path):
    src = _RESOURCE_BAD.replace(
        'subprocess.Popen(["sleep", "1"])    # bare: only handle discarded',
        'subprocess.Popen(["sleep", "1"])  # dmlcheck: off:resource-leak')
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["resource-leak"])
    assert len(_findings(ctx, "resource-leak")) == 3
    assert ctx.suppressed_count == 1


# ---------------------------------------------------------------------------
# thread-lifecycle (ISSUE 15): joinable-and-joined, or daemon-and-lockfree
# ---------------------------------------------------------------------------

_THREAD_BAD = """
    import threading

    class Server:
        def __init__(self):
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._loop)
            self._t.start()     # no method of Server ever joins it

        def _loop(self):
            pass

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()

        def kick(self):
            t = threading.Thread(target=self._work, daemon=True)
            t.start()           # daemon, but _work takes self._lock

        def _work(self):
            with self._lock:
                pass

    def fire_and_forget(fn):
        threading.Thread(target=fn).start()     # never joinable
"""

_THREAD_GOOD = """
    import threading

    class Server:
        def __init__(self):
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._loop)
            self._t.start()

        def close(self):
            self._t.join(timeout=2.0)   # bounded join in teardown

        def _loop(self):
            pass

    class Beacon:
        def kick(self):
            t = threading.Thread(target=self._ping, daemon=True)
            t.start()           # daemon AND lock-free: allowed

        def _ping(self):
            pass

    def batch(fns):
        ts = [threading.Thread(target=f) for f in fns]
        for t in ts:
            t.start()
        for t in ts:
            t.join()            # comp joined via the loop var
        return ts
"""


def test_thread_lifecycle_flags_unjoined_and_daemon_lockers(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _THREAD_BAD}),
                  rules=["thread-lifecycle"])
    keys = sorted(f.key for f in _findings(ctx, "thread-lifecycle"))
    assert keys == ["Pool.kick:t", "Server.start:self._t",
                    "fire_and_forget:chain-thread"]
    assert any("acquires the class's locks" in f.message
               for f in ctx.findings)


def test_thread_lifecycle_clean_join_daemon_and_comp_shapes(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _THREAD_GOOD}),
                  rules=["thread-lifecycle"])
    assert _findings(ctx) == []


# ---------------------------------------------------------------------------
# collective-discipline (ISSUE 15): rank-invariant collective order
# ---------------------------------------------------------------------------

_COLLECTIVE_BAD = """
    def save(coll, rank, model):
        if rank == 0:
            write(model)
            coll.barrier("ckpt")    # ranks != 0 never arrive
"""

_COLLECTIVE_GOOD = """
    def save(coll, rank, model):
        if rank == 0:
            write(model)
        coll.barrier("ckpt")        # every rank arrives

    def broadcast(coll, rank, v):
        # transport implementations branch on rank by definition
        if rank == 0:
            coll.bcast(v)
        return coll.recv()

    def report(rank, log):
        if rank == 0:
            log.commit_msg()        # commit_msg is not 'commit'
"""


def test_collective_discipline_flags_rank_conditional_barrier(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _COLLECTIVE_BAD}),
                  rules=["collective-discipline"])
    got = _findings(ctx, "collective-discipline")
    assert len(got) == 1 and got[0].key == "save:barrier"
    assert "rank-conditional" in got[0].message


def test_collective_discipline_clean_hoisted_and_transport_exempt(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _COLLECTIVE_GOOD}),
                  rules=["collective-discipline"])
    assert _findings(ctx) == []


def test_collective_discipline_suppression_with_rationale(tmp_path):
    src = _COLLECTIVE_BAD.replace(
        'coll.barrier("ckpt")    # ranks != 0 never arrive',
        'coll.barrier("ckpt")  # dmlcheck: off:collective-discipline')
    ctx = analyze(_mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": src}),
                  rules=["collective-discipline"])
    assert _findings(ctx) == [] and ctx.suppressed_count == 1


# ---------------------------------------------------------------------------
# wire-schema (ISSUE 15): the registry is the wire contract
# ---------------------------------------------------------------------------

_WIRE_REGISTRY = """
    COMMANDS = {
        "ping": frozenset({"cmd", "token"}),
        "bye": frozenset({"cmd"}),
    }
    WIRE_FRAMING = frozenset({"arrays"})
    ENV_ABI = frozenset({"DMLC_TASK_ID"})
"""

_WIRE_BAD = """
    def send(conn, tok, c):
        conn.request({"cmd": "ping", "token": tok, "extra": 1})
        conn.request({"cmd": "nope"})
        conn.request({"cmd": c, "mystery": tok})
"""

_WIRE_GOOD = """
    def send(conn, tok, c, blob):
        conn.request({"cmd": "ping", "token": tok})
        conn.request({"cmd": "bye", "arrays": blob})    # framing key
        conn.request({"cmd": c, "token": tok})          # dynamic, in vocab
        route({"command": "free-form"})  # no "cmd" key: not a wire dict
"""


def _wire_repo(tmp_path, files, registry=_WIRE_REGISTRY):
    files = dict(files)
    if registry is not None:
        files["dmlc_core_tpu/base/wire_schemas.py"] = registry
    return _mini_repo(tmp_path, files)


def test_wire_schema_flags_unknown_cmd_key_and_dynamic(tmp_path):
    ctx = analyze(_wire_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _WIRE_BAD}),
                  rules=["wire-schema"])
    keys = sorted(f.key for f in _findings(ctx, "wire-schema"))
    assert keys == ["cmd:nope", "dynamic.mystery", "ping.extra"]


def test_wire_schema_clean_declared_framing_and_dynamic(tmp_path):
    ctx = analyze(_wire_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _WIRE_GOOD}),
                  rules=["wire-schema"])
    assert _findings(ctx) == []


def test_wire_schema_missing_registry_is_loud(tmp_path):
    ctx = analyze(_wire_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _WIRE_GOOD},
                             registry=None),
                  rules=["wire-schema"])
    got = _findings(ctx, "wire-schema")
    assert got and all(f.key == "registry-missing" for f in got)


_ENV_INJECT = """
    def inject(env):
        env["DMLC_TASK_ID"] = "0"           # declared in ENV_ABI
        env["DMLC_FIXTURE_ROGUE"] = "1"
        env.setdefault("DMLC_FIXTURE_LAZY", "2")
"""


def test_wire_schema_env_abi_only_in_launch_and_tracker(tmp_path):
    ctx = analyze(_wire_repo(tmp_path, {
        "dmlc_core_tpu/launch/envs.py": _ENV_INJECT,
        "dmlc_core_tpu/mod.py": _ENV_INJECT,     # out of ABI scope
    }), rules=["wire-schema"])
    keys = sorted(f.key for f in _findings(ctx, "wire-schema"))
    assert keys == ["env:DMLC_FIXTURE_LAZY", "env:DMLC_FIXTURE_ROGUE"]
    assert all(f.path.endswith("launch/envs.py") for f in ctx.findings)


# ---------------------------------------------------------------------------
# CLI satellites: --explain, stale-baseline FAIL, per-pass timings
# ---------------------------------------------------------------------------

def test_rule_help_has_doc_and_example_pair():
    from dmlc_core_tpu.analysis import rule_help

    for rule in ("lock-blocking", "atomicity", "resource-leak",
                 "thread-lifecycle", "collective-discipline",
                 "wire-schema"):
        info = rule_help(rule)
        assert info["rule"] == rule
        assert info["doc"] and info["flagged"] and info["clean"]
    with pytest.raises(ValueError, match="unknown dmlcheck rule"):
        rule_help("not-a-rule")


def _run_cli(args):
    import subprocess
    import sys as _sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [_sys.executable, os.path.join(root, "scripts", "dmlcheck.py"),
         *args], capture_output=True, text=True)


def test_cli_explain_prints_pass_doc():
    r = _run_cli(["--explain", "atomicity"])
    assert r.returncode == 0
    assert "[atomicity]" in r.stdout
    assert "flagged:" in r.stdout and "clean:" in r.stdout
    r2 = _run_cli(["--explain", "nope"])
    assert r2.returncode == 2
    assert "unknown dmlcheck rule" in r2.stderr


def test_cli_stale_baseline_entry_fails_with_remove_me(tmp_path):
    import json as _json

    root = _mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": "x = 1\n"})
    bp = tmp_path / "baseline.json"
    bp.write_text(_json.dumps(
        {"findings": ["dmlc_core_tpu/gone.py::atomicity::X._n:bump:rmw"]}))
    r = _run_cli(["--root", root, "--baseline", str(bp)])
    assert r.returncode == 1
    assert "stale baseline" in r.stderr and "remove me" in r.stderr


def test_cli_timings_reports_new_passes(tmp_path):
    root = _mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": "x = 1\n"})
    bp = tmp_path / "baseline.json"
    r = _run_cli(["--root", root, "--baseline", str(bp), "--timings"])
    assert r.returncode == 0
    assert "per-pass timings" in r.stderr
    assert "blocking" in r.stderr and "atomicity" in r.stderr
    assert "resources" in r.stderr and "protocol" in r.stderr


# ---------------------------------------------------------------------------
# recompile-hazard (analysis/jaxpass)
# ---------------------------------------------------------------------------

_RECOMPILE_BAD = """
    import os
    import jax
    from functools import partial

    @partial(jax.jit, static_argnums=(1,))
    def kernel(x, cfg):
        return x

    class Model:
        def step(self, x):
            return jax.jit(self._impl)(x)       # fresh wrapper per call

        def steps(self, xs):
            fns = []
            for x in xs:
                fns.append(jax.jit(self._impl)) # rebuilt per iteration
            return fns

        def predict(self, x):
            return kernel(x, f"k-{x.shape}")    # fresh static key per call

        def _round_fn_cache_key(self):
            return (os.environ.get("DMLC_FIXTURE_FLAG", "0"),)
"""

_RECOMPILE_GOOD = """
    import jax
    from functools import partial

    _EXEC_CACHE = {}

    @partial(jax.jit, static_argnums=(1,))
    def kernel(x, depth):
        return x

    class Model:
        def __init__(self):
            self._impl_jit = jax.jit(self._impl)   # built once

        def step(self, x):
            return self._impl_jit(x)

        def warm(self, shapes):
            for s in shapes:
                _EXEC_CACHE[s] = jax.jit(self._impl)  # parked in a cache

        def predict(self, x, depth):
            return kernel(x, depth)                # hashable static

        def _round_fn_cache_key(self):
            return (knobs.value("DMLC_FIXTURE_FLAG"),)
"""


def test_recompile_hazard_flags_unstable_shapes(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _RECOMPILE_BAD},
                             knobs=["DMLC_FIXTURE_FLAG"]),
                  rules=["recompile-hazard"])
    msgs = [f.message for f in _findings(ctx, "recompile-hazard")]
    assert any("fresh jax.jit wrapper per call" in m for m in msgs), msgs
    assert any("inside a loop" in m for m in msgs), msgs
    assert any("static position 1" in m for m in msgs), msgs
    assert any("compile-cache key" in m and "knobs" in m
               for m in msgs), msgs


def test_recompile_hazard_clean_idioms(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _RECOMPILE_GOOD},
                             knobs=["DMLC_FIXTURE_FLAG"]),
                  rules=["recompile-hazard"])
    assert _findings(ctx) == []


# ---------------------------------------------------------------------------
# donation-discipline (analysis/jaxpass)
# ---------------------------------------------------------------------------

_DONATION_BAD = """
    import jax

    def update(state, grads):
        return state

    step = jax.jit(update, donate_argnums=(0,))   # ungated literal

    def train(state, grads):
        new = step(state, grads)
        print(state)                              # read after donation
        return new
"""

_DONATION_GOOD = """
    import jax
    from dmlc_core_tpu.base.compat import donate_argnums

    def update(state, grads):
        return state

    step = jax.jit(update, donate_argnums=donate_argnums(0))

    def train(state, grads):
        state = step(state, grads)     # rebinding kills the old name
        return state
"""


def test_donation_discipline_flags_ungated_and_use_after(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _DONATION_BAD}),
                  rules=["donation-discipline"])
    msgs = [f.message for f in _findings(ctx, "donation-discipline")]
    assert any("base/compat.py gate" in m for m in msgs), msgs
    assert any("reads 'state' after donating" in m for m in msgs), msgs


def test_donation_discipline_clean_gated_and_rebound(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _DONATION_GOOD}),
                  rules=["donation-discipline"])
    assert _findings(ctx) == []


# ---------------------------------------------------------------------------
# transfer-discipline (analysis/jaxpass)
# ---------------------------------------------------------------------------

_TRANSFER_BAD = """
    import jax
    import numpy as np

    @jax.jit
    def kernel(x):
        return np.asarray(x).sum()      # host transfer inside trace

    round_fn = jax.jit(lambda p: p)

    def fit(preds, table, n):
        done = 0
        while done < n:
            cfg = jax.device_put(table)   # re-uploaded per round
            preds = round_fn(preds)
            done += preds.item()          # device sync per round
        return preds
"""

_TRANSFER_GOOD = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        return jnp.asarray(x).sum()

    round_fn = jax.jit(lambda p: p)

    def fit(preds, table, n):
        cfg = jax.device_put(table)       # ingest: once, outside
        for _ in range(n):
            preds = round_fn(jax.device_put(preds))  # feeding the call
        return float(preds.sum())          # one sync after the loop
"""


def test_transfer_discipline_flags_traced_and_roundloop(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _TRANSFER_BAD}),
                  rules=["transfer-discipline"])
    msgs = [f.message for f in _findings(ctx, "transfer-discipline")]
    assert any("np.asarray" in m for m in msgs), msgs
    assert any("device_put inside its round loop" in m for m in msgs), msgs
    assert any(".item() inside its round loop" in m for m in msgs), msgs


def test_transfer_discipline_clean_ingest_and_jnp(tmp_path):
    ctx = analyze(_mini_repo(tmp_path,
                             {"dmlc_core_tpu/mod.py": _TRANSFER_GOOD}),
                  rules=["transfer-discipline"])
    assert _findings(ctx) == []


def test_jax_rule_help_has_doc_and_example_pair():
    from dmlc_core_tpu.analysis import rule_help

    for rule in ("recompile-hazard", "donation-discipline",
                 "transfer-discipline"):
        info = rule_help(rule)
        assert info["rule"] == rule
        assert info["doc"] and info["flagged"] and info["clean"]


# ---------------------------------------------------------------------------
# incremental cache
# ---------------------------------------------------------------------------

def test_cache_full_hit_reuses_findings(tmp_path):
    root = _mini_repo(tmp_path,
                      {"dmlc_core_tpu/mod.py": _DONATION_BAD})
    cache = tmp_path / "cache.bin"
    ctx1 = analyze(root, rules=["donation-discipline"],
                   cache_path=str(cache))
    assert ctx1.cache_stats == {"files": len(ctx1.files), "hits": 0,
                                "findings_reused": False}
    assert cache.exists()
    ctx2 = analyze(root, rules=["donation-discipline"],
                   cache_path=str(cache))
    assert ctx2.cache_stats["hits"] == ctx2.cache_stats["files"]
    assert ctx2.cache_stats["findings_reused"] is True
    assert [f.fingerprint for f in ctx2.findings] == \
        [f.fingerprint for f in ctx1.findings]
    assert ctx2.suppressed_count == ctx1.suppressed_count


def test_cache_invalidates_on_edit_and_rule_change(tmp_path):
    root = _mini_repo(tmp_path,
                      {"dmlc_core_tpu/mod.py": _DONATION_BAD,
                       "dmlc_core_tpu/other.py": "x = 1\n"})
    cache = tmp_path / "cache.bin"
    analyze(root, rules=["donation-discipline"], cache_path=str(cache))
    # a rules change must not reuse the previous run's findings
    ctx_r = analyze(root, rules=["style"], cache_path=str(cache))
    assert ctx_r.cache_stats["findings_reused"] is False
    assert _findings(ctx_r, "donation-discipline") == []
    # an edited file re-parses (one miss), findings recompute
    analyze(root, rules=["donation-discipline"], cache_path=str(cache))
    mod = os.path.join(root, "dmlc_core_tpu", "mod.py")
    with open(mod, "a") as f:
        f.write("\nY = 2\n")
    ctx3 = analyze(root, rules=["donation-discipline"],
                   cache_path=str(cache))
    assert ctx3.cache_stats["findings_reused"] is False
    assert ctx3.cache_stats["hits"] == ctx3.cache_stats["files"] - 1
    assert _findings(ctx3, "donation-discipline")


def test_cache_corrupt_file_is_cold_run(tmp_path):
    root = _mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": "x = 1\n"})
    cache = tmp_path / "cache.bin"
    cache.write_bytes(b"not a pickle")
    ctx = analyze(root, cache_path=str(cache))
    assert ctx.cache_stats["findings_reused"] is False
    assert ctx.findings == []


def test_cli_no_cache_and_hit_rate(tmp_path):
    root = _mini_repo(tmp_path, {"dmlc_core_tpu/mod.py": "x = 1\n"})
    os.makedirs(os.path.join(root, "scripts"), exist_ok=True)
    bp = tmp_path / "baseline.json"
    r1 = _run_cli(["--root", root, "--baseline", str(bp), "--timings"])
    assert r1.returncode == 0
    assert "cache:" in r1.stderr and "findings recomputed" in r1.stderr
    r2 = _run_cli(["--root", root, "--baseline", str(bp), "--timings"])
    assert "findings reused" in r2.stderr
    assert "(100%)" in r2.stderr
    r3 = _run_cli(["--root", root, "--baseline", str(bp), "--timings",
                   "--no-cache"])
    assert r3.returncode == 0
    assert "cache:" not in r3.stderr
