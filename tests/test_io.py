"""Tests for the I/O layer: streams, serializer, JSON helpers, recordio,
threaded iterator, input splits.  Mirrors the reference's unittest_serializer
/ unittest_json / unittest_threaditer(_exc_handling) / unittest_inputsplit
coverage (SURVEY.md §4)."""

import io
import os
import struct

import numpy as np
import pytest

from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.io import (
    ConcurrentBlockingQueue,
    InputSplit,
    MemoryFixedSizeStream,
    MemoryStringStream,
    RecordIOChunkReader,
    RecordIOReader,
    RecordIOWriter,
    Stream,
    TemporaryDirectory,
    ThreadedIter,
    URI,
)
from dmlc_core_tpu.io import serializer as ser
from dmlc_core_tpu.io.concurrency import QueueKilled
from dmlc_core_tpu.io.filesystem import MemoryFileSystem
from dmlc_core_tpu.io.json_io import JSONObjectReadHelper, JSONReader, JSONWriter
from dmlc_core_tpu.io.recordio import RECORDIO_MAGIC_BYTES


class TestURI:
    def test_bare_path(self):
        u = URI("/a/b.txt")
        assert u.protocol == "" and u.name == "/a/b.txt"

    def test_file_proto(self):
        u = URI("file:///a/b.txt")
        assert u.protocol == "file://" and u.name == "/a/b.txt"

    def test_hosted_proto(self):
        u = URI("s3://bucket/key/x")
        assert u.protocol == "s3://" and u.host == "bucket" and u.name == "/key/x"


class TestMemoryStreams:
    def test_string_stream_round_trip(self):
        s = MemoryStringStream()
        s.write(b"hello ")
        s.write(b"world")
        s.seek(0)
        assert s.read(-1) == b"hello world"
        assert s.tell() == 11

    def test_fixed_stream_overflow_fatal(self):
        buf = bytearray(4)
        s = MemoryFixedSizeStream(buf)
        s.write(b"abcd")
        with pytest.raises(Error, match="overflow"):
            s.write(b"x")
        s.seek(0)
        assert s.read(2) == b"ab"

    def test_read_exact_eof_fatal(self):
        s = MemoryStringStream(bytearray(b"ab"))
        with pytest.raises(Error, match="EOF"):
            s.read_exact(3)


class TestStreamCreate:
    def test_local_file_round_trip(self):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "f.bin")
            with Stream.create(path, "w") as s:
                s.write(b"data123")
            with Stream.create(path, "r") as s:
                assert s.read_all() == b"data123"
            # seekable read
            s = Stream.create_for_read(path)
            s.seek(4)
            assert s.read(-1) == b"123"
            s.close()

    def test_file_uri(self):
        with TemporaryDirectory() as tmp:
            uri = "file://" + os.path.join(tmp.path, "g.bin")
            with Stream.create(uri, "w") as s:
                s.write(b"x")
            with Stream.create(uri, "r") as s:
                assert s.read_all() == b"x"

    def test_mem_uri(self):
        MemoryFileSystem.reset()
        with Stream.create("mem:///k", "w") as s:
            s.write(b"v1")
        with Stream.create("mem:///k", "a") as s:
            s.write(b"v2")
        with Stream.create("mem:///k", "r") as s:
            assert s.read_all() == b"v1v2"

    def test_allow_null(self):
        assert Stream.create("/definitely/missing/file", "r", allow_null=True) is None
        with pytest.raises(Error):
            Stream.create("/definitely/missing/file", "r")

    def test_unknown_protocol(self):
        with pytest.raises(Error, match="no filesystem"):
            Stream.create("gopher://x/y", "r")


class TestSerializer:
    def test_scalars(self):
        s = MemoryStringStream()
        ser.write_uint32(s, 7)
        ser.write_int64(s, -5)
        ser.write_float32(s, 1.5)
        ser.write_bool(s, True)
        s.seek(0)
        assert ser.read_uint32(s) == 7
        assert ser.read_int64(s) == -5
        assert ser.read_float32(s) == 1.5
        assert ser.read_bool(s) is True

    def test_string_and_vector(self):
        s = MemoryStringStream()
        ser.write_string(s, "héllo")
        ser.write_vector(s, [1, 2, 3], ser.write_int32)
        s.seek(0)
        assert ser.read_string(s) == "héllo"
        assert ser.read_vector(s, ser.read_int32) == [1, 2, 3]

    def test_nested_stl_equivalent(self):
        # the reference's "vector<pair<map,...>> just works" case
        obj = [
            {"a": [1, 2], "b": (3.5, "x")},
            {"c": {1: b"bytes"}, "d": None},
            {"e": {7, 8}},
        ]
        s = MemoryStringStream()
        ser.write_obj(s, obj)
        s.seek(0)
        assert ser.read_obj(s) == obj

    def test_ndarray_round_trip(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        s = MemoryStringStream()
        ser.write_ndarray(s, arr)
        ser.write_ndarray(s, np.array(5, dtype=np.int64))
        s.seek(0)
        out = ser.read_ndarray(s)
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == np.float32
        assert ser.read_ndarray(s) == 5

    def test_big_endian_input_canonicalized(self):
        arr = np.arange(4, dtype=">u4")
        s = MemoryStringStream()
        ser.write_ndarray(s, arr)
        s.seek(0)
        out = ser.read_ndarray(s)
        np.testing.assert_array_equal(out.astype(np.uint32), np.arange(4))


class TestJSON:
    def test_round_trip(self):
        s = MemoryStringStream()
        JSONWriter(s).write({"a": 1, "b": [1, 2]})
        s.seek(0)
        assert JSONReader(s).read() == {"a": 1, "b": [1, 2]}

    def test_parse_error_has_position(self):
        s = MemoryStringStream(bytearray(b'{"a": }'))
        with pytest.raises(Error, match="line 1"):
            JSONReader(s).read()

    def test_object_read_helper(self):
        helper = JSONObjectReadHelper()
        got = {}
        helper.declare_field("name", str, setter=lambda v: got.update(name=v))
        helper.declare_optional_field("count", int)
        out = helper.read_all_fields({"name": "x", "count": 3})
        assert out == {"name": "x", "count": 3} and got == {"name": "x"}
        with pytest.raises(Error, match="missing"):
            helper.read_all_fields({"count": 1})
        with pytest.raises(Error, match="unknown field"):
            helper.read_all_fields({"name": "x", "bogus": 1})
        with pytest.raises(Error, match="expected"):
            helper.read_all_fields({"name": 42})


class TestBlockingQueue:
    def test_fifo_and_bound(self):
        q = ConcurrentBlockingQueue(max_size=2)
        q.push(1)
        q.push(2)
        assert q.size() == 2
        assert q.pop() == 1 and q.pop() == 2

    def test_kill_unblocks(self):
        q = ConcurrentBlockingQueue()
        q.signal_for_kill()
        with pytest.raises(QueueKilled):
            q.pop()
        with pytest.raises(QueueKilled):
            q.push(1)

    def test_priority(self):
        q = ConcurrentBlockingQueue(priority=True)
        q.push("low", priority=5)
        q.push("high", priority=1)
        assert q.pop() == "high"


class TestThreadedIter:
    def test_produce_consume_all(self):
        data = list(range(100))
        state = {"i": 0}

        def next_fn(_cell):
            if state["i"] >= len(data):
                return None
            v = data[state["i"]]
            state["i"] += 1
            return v

        it = ThreadedIter(max_capacity=4)
        it.init(next_fn)
        assert list(it) == data
        assert it.next() is None  # repeated next after end doesn't block
        it.destroy()

    def test_exception_propagates_to_consumer(self):
        # the unittest_threaditer_exc_handling case
        def next_fn(_cell):
            raise ValueError("producer blew up")

        it = ThreadedIter()
        it.init(next_fn)
        with pytest.raises(ValueError, match="producer blew up"):
            it.next()
        it.destroy()

    def test_exception_mid_stream(self):
        state = {"i": 0}

        def next_fn(_cell):
            state["i"] += 1
            if state["i"] > 5:
                raise RuntimeError("late failure")
            return state["i"]

        it = ThreadedIter(max_capacity=2)
        it.init(next_fn)
        seen = []
        with pytest.raises(RuntimeError, match="late failure"):
            while True:
                v = it.next()
                if v is None:
                    break
                seen.append(v)
        assert seen == [1, 2, 3, 4, 5]

    def test_recycle_reuses_cells(self):
        reused = []

        state = {"i": 0}

        def next_fn(cell):
            if state["i"] >= 20:
                return None
            state["i"] += 1
            if cell is not None:
                reused.append(id(cell))
                cell[0] = state["i"]
                return cell
            return [state["i"]]

        it = ThreadedIter(max_capacity=2)
        it.init(next_fn)
        out = []
        while True:
            item = it.next()
            if item is None:
                break
            out.append(item[0])
            it.recycle(item)
        assert out == list(range(1, 21))
        assert reused  # at least some buffers were recycled
        it.destroy()

    def test_before_first_rewinds(self):
        state = {"i": 0}

        def next_fn(_cell):
            if state["i"] >= 5:
                return None
            state["i"] += 1
            return state["i"]

        def rewind():
            state["i"] = 0

        it = ThreadedIter(max_capacity=2)
        it.init(next_fn, rewind)
        assert list(it) == [1, 2, 3, 4, 5]
        it.before_first()
        assert list(it) == [1, 2, 3, 4, 5]
        it.destroy()


def _encode_lrec_header(cflag, length):
    return RECORDIO_MAGIC_BYTES + struct.pack("<I", (cflag << 29) | length)


class TestRecordIO:
    def test_round_trip_simple(self):
        s = MemoryStringStream()
        w = RecordIOWriter(s)
        records = [b"hello", b"", b"world!!", b"x" * 1000]
        for r in records:
            w.write_record(r)
        s.seek(0)
        assert list(RecordIOReader(s)) == records

    def test_magic_escaping_round_trip(self):
        # records containing the magic at aligned offsets must round-trip
        evil = [
            RECORDIO_MAGIC_BYTES * 3,
            b"abcd" + RECORDIO_MAGIC_BYTES + b"efgh",
            RECORDIO_MAGIC_BYTES,
            b"ab" + RECORDIO_MAGIC_BYTES + b"cd",  # unaligned magic: no escape
            b"abcd" + RECORDIO_MAGIC_BYTES,  # magic at tail
        ]
        s = MemoryStringStream()
        w = RecordIOWriter(s)
        for r in evil:
            w.write_record(r)
        assert w.except_counter >= 5
        s.seek(0)
        assert list(RecordIOReader(s)) == evil

    def test_alignment_padding(self):
        s = MemoryStringStream()
        RecordIOWriter(s).write_record(b"abc")  # 3 bytes → 1 pad byte
        assert len(s.data) == 12  # 4 magic + 4 lrec + 3 data + 1 pad

    def test_chunk_reader_matches_stream_reader(self):
        s = MemoryStringStream()
        w = RecordIOWriter(s)
        records = [os.urandom(n) for n in (5, 64, 0, 333)]
        records += [RECORDIO_MAGIC_BYTES + b"tail"]
        for r in records:
            w.write_record(r)
        assert list(RecordIOChunkReader(bytes(s.data))) == records

    def test_garbage_only_stream_is_empty(self):
        # pure garbage: no records, counted as a resync, no raise (the
        # tolerant-reader contract — doc/streaming.md)
        s = MemoryStringStream(bytearray(b"\x00" * 8))
        r = RecordIOReader(s)
        assert r.next_record() is None
        assert r.resyncs == 1

    def _encoded(self, records):
        s = MemoryStringStream()
        w = RecordIOWriter(s)
        for rec in records:
            w.write_record(rec)
        return bytes(s.data)

    def test_torn_final_record_truncated_mid_payload(self):
        # a writer SIGKILLed mid-append leaves a partial tail: the
        # reader must deliver every complete record and treat the torn
        # one as EOF instead of raising
        records = [b"alpha", b"beta" * 50, b"gamma" * 9]
        blob = self._encoded(records)
        for cut in (1, 3, 5, 9, 15):   # header, lrec and payload tears
            last_start = len(self._encoded(records[:-1]))
            torn = blob[:last_start + cut]
            r = RecordIOReader(MemoryStringStream(bytearray(torn)))
            assert list(r) == records[:-1]
            assert r.torn_tail

    def test_torn_tail_multipart_record(self):
        # escaped-magic records span multiple parts; tearing between
        # parts must drop the whole partial record
        records = [b"ok1", RECORDIO_MAGIC_BYTES * 4 + b"tail"]
        blob = self._encoded(records)
        first = len(self._encoded(records[:1]))
        torn = blob[:first + 14]       # inside the multi-part record
        r = RecordIOReader(MemoryStringStream(bytearray(torn)))
        assert list(r) == [b"ok1"]
        assert r.torn_tail

    def test_resync_past_corrupt_bytes(self):
        # corruption between two valid records: resync on the aligned
        # magic marker and keep reading (instead of raising)
        good = self._encoded([b"first-record"])
        rest = self._encoded([b"second", b"third!!!"])
        blob = good + b"\xde\xad\xbe\xef" * 3 + rest
        r = RecordIOReader(MemoryStringStream(bytearray(blob)))
        assert list(r) == [b"first-record", b"second", b"third!!!"]
        assert r.resyncs == 1

    def test_clean_stream_unaffected_by_tolerance(self):
        records = [os.urandom(n) for n in (0, 1, 7, 128)]
        r = RecordIOReader(MemoryStringStream(bytearray(
            self._encoded(records))))
        assert list(r) == records
        assert r.resyncs == 0 and not r.torn_tail


def _write_lines(path, lines):
    with open(path, "wb") as f:
        for ln in lines:
            f.write(ln + b"\n")


class TestInputSplitText:
    def make_files(self, tmp, nfiles=3, lines_per_file=57):
        all_lines = []
        for i in range(nfiles):
            lines = [f"file{i}-line{j}-{'x' * (j % 13)}".encode() for j in range(lines_per_file)]
            _write_lines(os.path.join(tmp, f"part-{i:03d}"), lines)
            all_lines += lines
        return all_lines

    @pytest.mark.parametrize("nparts", [1, 2, 3, 5, 8])
    def test_coverage_no_overlap(self, nparts):
        # THE sharding oracle: union over parts == all records, no overlap
        with TemporaryDirectory() as tmp:
            expected = self.make_files(tmp.path)
            seen = []
            for part in range(nparts):
                with InputSplit.create(tmp.path, part, nparts, "text") as split:
                    seen += list(split)
            assert sorted(seen) == sorted(expected)
            assert len(seen) == len(expected)

    def test_small_chunk_size(self):
        with TemporaryDirectory() as tmp:
            expected = self.make_files(tmp.path, nfiles=2, lines_per_file=23)
            seen = []
            for part in range(4):
                split = InputSplit.create(tmp.path, part, 4, "text", threaded=False)
                split.hint_chunk_size(1)  # clamps to 4096 floor; stress small reads
                seen += list(split)
                split.close()
            assert sorted(seen) == sorted(expected)

    def test_single_file_no_trailing_newline(self):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "f.txt")
            with open(path, "wb") as f:
                f.write(b"a\nbb\nccc")  # no trailing \n
            recs = []
            for part in range(2):
                recs += list(InputSplit.create(path, part, 2, "text"))
            assert sorted(recs) == [b"a", b"bb", b"ccc"]

    def test_crlf_stripped(self):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "f.txt")
            with open(path, "wb") as f:
                f.write(b"a\r\nb\r\n")
            assert list(InputSplit.create(path, 0, 1, "text")) == [b"a", b"b"]

    def test_before_first_replays(self):
        with TemporaryDirectory() as tmp:
            self.make_files(tmp.path, nfiles=1, lines_per_file=10)
            split = InputSplit.create(tmp.path, 0, 1, "text")
            first = list(split)
            split.before_first()
            assert list(split) == first

    def test_reset_partition(self):
        with TemporaryDirectory() as tmp:
            expected = self.make_files(tmp.path, nfiles=2, lines_per_file=20)
            split = InputSplit.create(tmp.path, 0, 2, "text", threaded=False)
            part0 = list(split)
            split.reset_partition(1, 2)
            part1 = list(split)
            assert sorted(part0 + part1) == sorted(expected)


class TestInputSplitRecordIO:
    def make_rec_files(self, tmp, nfiles=2, recs_per_file=41):
        rng = np.random.default_rng(42)
        all_recs = []
        for i in range(nfiles):
            path = os.path.join(tmp, f"data-{i:02d}.rec")
            with Stream.create(path, "w") as s:
                w = RecordIOWriter(s)
                for j in range(recs_per_file):
                    n = int(rng.integers(0, 200))
                    rec = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
                    if j % 7 == 0:  # sprinkle embedded magics
                        rec = RECORDIO_MAGIC_BYTES + rec + RECORDIO_MAGIC_BYTES
                    w.write_record(rec)
                    all_recs.append(rec)
        return all_recs

    @pytest.mark.parametrize("nparts", [1, 2, 4, 7])
    def test_coverage_no_overlap(self, nparts):
        with TemporaryDirectory() as tmp:
            expected = self.make_rec_files(tmp.path)
            seen = []
            for part in range(nparts):
                with InputSplit.create(tmp.path, part, nparts, "recordio") as split:
                    seen += list(split)
            assert sorted(seen) == sorted(expected)
            assert len(seen) == len(expected)

    def test_glob_uri(self):
        with TemporaryDirectory() as tmp:
            expected = self.make_rec_files(tmp.path, nfiles=3, recs_per_file=10)
            pattern = os.path.join(tmp.path, "data-*.rec")
            seen = list(InputSplit.create(pattern, 0, 1, "recordio"))
            assert sorted(seen) == sorted(expected)


class TestIndexedRecordIO:
    def make_indexed(self, tmp, n=30):
        path = os.path.join(tmp, "d.rec")
        offsets = []
        recs = []
        with Stream.create(path, "w") as s:
            w = RecordIOWriter(s)
            pos = 0
            for j in range(n):
                rec = f"record-{j:04d}".encode() * (j % 3 + 1)
                offsets.append(len(s.data) if hasattr(s, "data") else pos)
                # track via tell on local file stream
                recs.append(rec)
                w.write_record(rec)
        # rebuild offsets by re-reading (robust for any backend)
        with Stream.create(path, "r") as s:
            data = s.read_all()
        offs, pos = [], 0
        reader = RecordIOChunkReader(data)
        while True:
            start = reader._pos
            if reader.next_record() is None:
                break
            offs.append(start)
        with open(path + ".idx", "w") as f:
            for j, off in enumerate(offs):
                f.write(f"{j}\t{off}\n")
        return path, recs

    @pytest.mark.parametrize("nparts", [1, 3])
    def test_partition_coverage(self, nparts):
        with TemporaryDirectory() as tmp:
            path, recs = self.make_indexed(tmp.path)
            seen = []
            for part in range(nparts):
                split = InputSplit.create(path, part, nparts, "indexed_recordio")
                seen += list(split)
                split.close()
            assert sorted(seen) == sorted(recs)

    def test_shuffled_deterministic(self):
        from dmlc_core_tpu.io.input_split import IndexedRecordIOSplit

        with TemporaryDirectory() as tmp:
            path, recs = self.make_indexed(tmp.path)
            s1 = IndexedRecordIOSplit(path, 0, 1, shuffle=True, seed=7)
            order1 = list(s1)
            s1.before_first()
            order2 = list(s1)
            assert sorted(order1) == sorted(recs)
            assert order1 != order2  # epoch advances the shuffle
            s2 = IndexedRecordIOSplit(path, 0, 1, shuffle=True, seed=7)
            assert list(s2) == order1  # same seed, same first epoch
            s1.close(); s2.close()


class TestShuffleAndCache:
    def test_shuffle_decorator(self):
        with TemporaryDirectory() as tmp:
            lines = [f"l{i}".encode() for i in range(50)]
            _write_lines(os.path.join(tmp.path, "f"), lines)
            split = InputSplit.create(tmp.path, 0, 1, "text", shuffle_buffer=16, seed=3)
            out = list(split)
            assert sorted(out) == sorted(lines)
            assert out != lines  # shuffled

    def test_cached_recordio_split(self):
        # regression: cache replay must use the base format's record framing
        with TemporaryDirectory() as tmp:
            recs = [RECORDIO_MAGIC_BYTES + os.urandom(n) for n in (3, 50, 0, 17)]
            path = os.path.join(tmp.path, "d.rec")
            with Stream.create(path, "w") as s:
                w = RecordIOWriter(s)
                for r in recs:
                    w.write_record(r)
            cache = os.path.join(tmp.path, "c.bin")
            split = InputSplit.create(path, 0, 1, "recordio", cache_file=cache)
            assert list(split) == recs  # pass 1 (tee)
            split.before_first()
            assert list(split) == recs  # pass 2 (replay from cache)
            split.close()

    def test_mem_glob_uses_backend_namespace(self):
        # regression: glob must match the backend's own files, not the OS fs
        MemoryFileSystem.reset()
        for i in range(3):
            with Stream.create(f"mem:///g/data-{i}.rec", "w") as s:
                RecordIOWriter(s).write_record(f"r{i}".encode())
        seen = list(InputSplit.create("mem:///g/data-*.rec", 0, 1, "recordio"))
        assert sorted(seen) == [b"r0", b"r1", b"r2"]

    def test_stdin_partitioned_fatal(self):
        with pytest.raises(Error, match="partition"):
            InputSplit.create("stdin", 1, 2, "text")

    def test_cached_split_replay(self):
        with TemporaryDirectory() as tmp:
            lines = [f"line{i}".encode() for i in range(30)]
            _write_lines(os.path.join(tmp.path, "f"), lines)
            cache = os.path.join(tmp.path, "cache.bin")
            split = InputSplit.create(
                os.path.join(tmp.path, "f"), 0, 1, "text", cache_file=cache
            )
            pass1 = list(split)
            assert pass1 == lines
            split.before_first()
            pass2 = list(split)  # now served from cache
            assert pass2 == lines
            assert os.path.exists(cache)
            split.close()


class TestStreamAsFile:
    def test_pickle_through_stream(self, tmp_path):
        import pickle
        from dmlc_core_tpu.io.stream import Stream

        path = str(tmp_path / "obj.pkl")
        obj = {"a": [1, 2, 3], "b": "hello"}
        with Stream.create(path, "w") as s:
            pickle.dump(obj, s.as_file())
        with Stream.create(path, "r") as s:
            back = pickle.load(io.BufferedReader(s.as_file()))
        assert back == obj

    def test_text_wrapper_and_seek(self):
        import io as _io
        from dmlc_core_tpu.io.memory_io import MemoryStringStream

        buf = MemoryStringStream()
        f = _io.TextIOWrapper(buf.as_file(), encoding="utf-8")
        f.write("line1\nline2\n")
        f.flush()
        rd = MemoryStringStream(buf.data)
        ff = rd.as_file()
        assert ff.seekable()
        data = bytes(rd.read_all())
        assert data == b"line1\nline2\n"


def test_input_split_semicolon_multipath(tmp_path):
    from dmlc_core_tpu.io.input_split import InputSplit

    files = []
    want = set()
    for k in range(3):
        fp = tmp_path / f"f{k}.txt"
        lines = [f"row-{k}-{i}" for i in range(100)]
        want.update(lines)
        fp.write_text("\n".join(lines) + "\n")
        files.append(str(fp))
    uri = ";".join(files)
    got = set()
    for part in range(2):
        sp = InputSplit.create(uri, part, 2, "text")
        while (rec := sp.next_record()) is not None:
            got.add(bytes(rec).decode())
        sp.close()
    assert got == want


def test_split_multi_uri_url_query_semicolons():
    from dmlc_core_tpu.io.input_split import _split_multi_uri

    # query-string ';' rejoined; real multi-URL lists still split
    assert _split_multi_uri("https://h/f.bin?a=1;b=2") == \
        ["https://h/f.bin?a=1;b=2"]
    assert _split_multi_uri("https://h/a.rec;https://h/b.rec?x=1;y=2") == \
        ["https://h/a.rec", "https://h/b.rec?x=1;y=2"]
    assert _split_multi_uri("/a.txt;/b.txt") == ["/a.txt", "/b.txt"]


def test_as_file_close_after_stream_closed(tmp_path):
    from dmlc_core_tpu.io.stream import Stream

    path = str(tmp_path / "x.bin")
    s = Stream.create(path, "w")
    f = s.as_file()
    f.write(b"data")
    s.close()
    f.close()          # must not raise despite IOBase.close() → flush()
