"""Tests for the distributed layer: mesh, collectives (in-jit + host),
topology oracle, KVStore, checkpoint, tracker service, local multi-process
launch (the reference's local.py testing pattern, SURVEY.md §4)."""

import os
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.io import TemporaryDirectory
from dmlc_core_tpu.parallel import (
    KVStore,
    MeshSpec,
    allreduce,
    allgather,
    broadcast,
    create_mesh,
    data_sharding,
    rank,
    world_size,
)
from dmlc_core_tpu.parallel.checkpoint import checkpoint, load_checkpoint
from dmlc_core_tpu.parallel.collectives import (
    device_allgather,
    device_allreduce,
    find_share_ring,
    get_link_map,
    get_tree,
)
from dmlc_core_tpu.parallel.mesh import local_mesh
from jax.sharding import NamedSharding, PartitionSpec as P
from dmlc_core_tpu.tracker.tracker import (
    RabitTracker,
    WorkerSession,
    submit as tracker_submit,
)


class TestTopologyOracle:
    @pytest.mark.parametrize("n", [1, 2, 3, 7, 8, 16, 33])
    def test_tree_properties(self, n):
        parent, children = get_tree(n)
        assert parent[0] == -1
        for r in range(1, n):
            assert parent[r] == (r - 1) // 2
            assert r in children[parent[r]]
        # every non-root reachable from root
        seen = set()
        stack = [0]
        while stack:
            r = stack.pop()
            seen.add(r)
            stack.extend(children[r])
        assert seen == set(range(n))

    @pytest.mark.parametrize("n", [1, 2, 5, 8, 13])
    def test_ring_is_dfs_permutation(self, n):
        parent, children = get_tree(n)
        ring = find_share_ring(children)
        assert sorted(ring) == list(range(n))
        assert ring[0] == 0

    @pytest.mark.parametrize("n", [2, 6, 9])
    def test_link_map_consistent(self, n):
        links = get_link_map(n)
        for r, link in links.items():
            # ring closes: next of prev is me
            assert links[link["ring_next"]]["ring_prev"] == r
            assert links[link["ring_prev"]]["ring_next"] == r
            for c in link["children"]:
                assert links[c]["parent"] == r


class TestMesh:
    def test_spec_resolve_wildcard(self):
        spec = MeshSpec()
        assert spec.resolve(8) == {"data": 8, "model": 1, "pipe": 1, "seq": 1, "expert": 1}
        spec = MeshSpec(data=-1, model=2)
        assert spec.resolve(8)["data"] == 4

    def test_spec_mismatch_fatal(self):
        with pytest.raises(Error):
            MeshSpec(data=3, model=1).resolve(8)

    def test_create_mesh_all_devices(self):
        mesh = create_mesh()
        assert mesh.devices.size == len(jax.devices())
        assert mesh.axis_names == ("data", "model", "pipe", "seq", "expert")

    def test_data_sharding_places_shards(self):
        mesh = local_mesh()
        n = len(jax.devices())
        x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        arr = jax.device_put(x, data_sharding(mesh, ndim=2))
        assert len(arr.addressable_shards) == n
        np.testing.assert_array_equal(np.asarray(arr), x)


class TestDeviceCollectives:
    def test_device_allreduce_sum(self):
        mesh = local_mesh()
        n = len(jax.devices())
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        arr = jax.device_put(x, data_sharding(mesh, ndim=2))
        out = device_allreduce(arr, mesh, "sum")
        np.testing.assert_allclose(np.asarray(out), x.sum(axis=0))

    def test_device_allreduce_max_min(self):
        mesh = local_mesh()
        n = len(jax.devices())
        x = np.random.default_rng(0).normal(size=(n, 5)).astype(np.float32)
        arr = jax.device_put(x, data_sharding(mesh, ndim=2))
        np.testing.assert_allclose(
            np.asarray(device_allreduce(arr, mesh, "max")), x.max(axis=0)
        )
        np.testing.assert_allclose(
            np.asarray(device_allreduce(arr, mesh, "min")), x.min(axis=0)
        )

    def test_device_allgather(self):
        mesh = local_mesh()
        n = len(jax.devices())
        x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
        arr = jax.device_put(x, data_sharding(mesh, ndim=2))
        out = device_allgather(arr, mesh)
        np.testing.assert_array_equal(np.asarray(out), x)

    def test_unknown_op_fatal(self):
        mesh = local_mesh()
        with pytest.raises(Error):
            device_allreduce(jnp.zeros((8, 2)), mesh, "median")


class TestHostCollectivesSingleProcess:
    def test_identity_paths(self):
        x = np.arange(5.0)
        np.testing.assert_array_equal(allreduce(x, "sum"), x)
        np.testing.assert_array_equal(broadcast(x), x)
        assert allgather(x).shape == (1, 5)
        assert rank() == 0 and world_size() == 1

    def test_bad_op(self):
        with pytest.raises(Error):
            allreduce(np.zeros(3), "xor")


class TestKVStore:
    def test_local_push_pull_sgd(self):
        kv = KVStore.create("local", learning_rate=0.5)
        kv.init(3, np.ones(4, np.float32))
        kv.push(3, np.full(4, 2.0, np.float32))
        out = np.asarray(kv.pull(3))
        np.testing.assert_allclose(out, 1.0 - 0.5 * 2.0)

    def test_push_accumulates(self):
        kv = KVStore.create("local", learning_rate=1.0)
        kv.init("w", np.zeros(2, np.float32))
        kv.push("w", np.ones(2, np.float32))
        kv.push("w", np.ones(2, np.float32))
        np.testing.assert_allclose(np.asarray(kv.pull("w")), -2.0)

    def test_list_keys_and_custom_updater(self):
        kv = KVStore.create("dist_sync")
        kv.init(["a", "b"], [np.zeros(2), np.ones(2)])
        kv.set_updater(lambda k, g, v: v + g)
        kv.push(["a", "b"], [np.ones(2), np.ones(2)])
        a, b = kv.pull(["a", "b"])
        np.testing.assert_allclose(np.asarray(a), 1.0)
        np.testing.assert_allclose(np.asarray(b), 2.0)

    def test_mesh_dist_sync_bucketed_one_collective(self):
        """Many keys pulled together must fuse into ONE allreduce launch
        (config 4: per-key launches can't reach bus-bandwidth targets),
        with results identical to the per-key math."""
        mesh = local_mesh()
        W = mesh.devices.size
        kv = KVStore.create("dist_sync", mesh=mesh, learning_rate=1.0)
        rng = np.random.default_rng(0)
        keys = [f"p{i}" for i in range(12)]
        vals = {k: rng.normal(size=(3 + i % 4,)).astype(np.float32)
                for i, k in enumerate(keys)}
        kv.init(list(keys), [vals[k] for k in keys])
        grads = {k: rng.normal(size=(W, *vals[k].shape)).astype(np.float32)
                 for k in keys}
        sharding = NamedSharding(mesh, P("data"))
        kv.push(list(keys), [jax.device_put(grads[k], sharding)
                             for k in keys])
        out = kv.pull(list(keys))
        assert kv.stats["sync_calls"] == 1, kv.stats
        assert kv.stats["keys_synced"] == len(keys)
        for k, o in zip(keys, out):
            np.testing.assert_allclose(
                np.asarray(o), vals[k] - grads[k].sum(axis=0),
                rtol=1e-5, atol=1e-5)

    def test_duplicate_key_in_pull_batch(self):
        kv = KVStore.create("dist_sync", learning_rate=1.0)
        kv.init("a", np.zeros(2, np.float32))
        kv.push("a", np.ones(2, np.float32))
        o1, o2 = kv.pull(["a", "a"])   # must not KeyError; one sync
        np.testing.assert_allclose(np.asarray(o1), -1.0)
        np.testing.assert_allclose(np.asarray(o2), -1.0)

    def test_dist_sync_batch_digest_check(self, monkeypatch):
        """DMLC_KVSTORE_CHECK=1 cross-checks that every worker pulled the
        same (key, shape, dtype) batch before fused reduction; a skewed
        batch must fail fast instead of silently corrupting gradients.
        Simulated two-worker world: allreduce echo = digests agree;
        perturbed max = digests differ -> fatal."""
        from dmlc_core_tpu.parallel import kvstore as kvmod

        monkeypatch.setenv("DMLC_KVSTORE_CHECK", "1")
        monkeypatch.setattr(kvmod.coll, "world_size", lambda: 2)
        calls = []

        def echo_allreduce(x, op="sum"):
            calls.append(op)
            return np.asarray(x)

        monkeypatch.setattr(kvmod.coll, "allreduce", echo_allreduce)
        kv = KVStore.create("dist_sync", learning_rate=1.0)
        kv.init("w", np.zeros(2, np.float32))
        kv.push("w", np.ones(2, np.float32))
        kv.pull("w")                      # identical batches: passes
        assert calls[:2] == ["min", "max"]

        def skewed_allreduce(x, op="sum"):
            x = np.asarray(x)
            return x + 1 if op == "max" else x   # min != max -> divergence

        monkeypatch.setattr(kvmod.coll, "allreduce", skewed_allreduce)
        kv.push("w", np.ones(2, np.float32))
        with pytest.raises(Error, match="DIFFERENT key batches"):
            kv.pull("w")

    def test_bucket_cap_splits_collectives(self):
        mesh = local_mesh()
        W = mesh.devices.size
        # 4-byte cap → every key in its own bucket
        kv = KVStore.create("dist_sync", mesh=mesh, bucket_bytes=4)
        kv.init(["a", "b", "c"], [np.zeros(2, np.float32)] * 3)
        sharding = NamedSharding(mesh, P("data"))
        kv.push(["a", "b", "c"],
                [jax.device_put(np.ones((W, 2), np.float32), sharding)] * 3)
        kv.pull(["a", "b", "c"])
        assert kv.stats["sync_calls"] == 3, kv.stats

    def test_uninitialized_key_fatal(self):
        kv = KVStore.create("local")
        with pytest.raises(Error):
            kv.push("missing", np.zeros(1))

    def test_double_init_fatal(self):
        kv = KVStore.create("local")
        kv.init("k", np.zeros(1))
        with pytest.raises(Error):
            kv.init("k", np.zeros(1))


class TestCheckpoint:
    def test_round_trip_pytree(self):
        with TemporaryDirectory() as tmp:
            uri = os.path.join(tmp.path, "ckpt.bin")
            state = {
                "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros(3)},
                "step": 42,
            }
            checkpoint(uri, state, version=7)
            like = {
                "params": {"w": jnp.zeros((2, 3)), "b": jnp.ones(3)},
                "step": 0,
            }
            version, loaded = load_checkpoint(uri, like)
            assert version == 7
            np.testing.assert_allclose(np.asarray(loaded["params"]["w"]),
                                       np.arange(6.0).reshape(2, 3))
            assert loaded["step"] == 42

    def test_missing_returns_version_zero(self):
        like = {"x": jnp.zeros(2)}
        version, state = load_checkpoint("/nonexistent/path/ckpt", like)
        assert version == 0 and state is like

    def test_versioned_round_trip_memory_uri(self):
        """The (version, state) contract over the mem:// backend that
        the serve registry's hot-swap rides: the version number written
        round-trips EXACTLY (not approximately, not re-derived), and
        successive saves to the same URI supersede cleanly."""
        like = {"w": jnp.zeros(3), "step": 0}
        for v in (1, 2, 9):                    # monotone publish history
            checkpoint("mem:///ckpt/versioned",
                       {"w": jnp.full(3, float(v)), "step": v}, version=v)
            version, state = load_checkpoint("mem:///ckpt/versioned", like)
            assert version == v
            np.testing.assert_array_equal(np.asarray(state["w"]),
                                          np.full(3, v, np.float32))
            assert state["step"] == v

    def test_version_zero_when_absent_memory_uri(self):
        """Cold-start contract on mem:// too: no checkpoint ⇒ version 0
        and the caller's ``like`` handed back untouched."""
        like = {"w": jnp.zeros(2)}
        version, state = load_checkpoint("mem:///ckpt/never-written", like)
        assert version == 0 and state is like

    def test_sharded_arrays_preserve_sharding(self):
        with TemporaryDirectory() as tmp:
            uri = os.path.join(tmp.path, "ck.bin")
            mesh = local_mesh()
            n = len(jax.devices())
            x = jax.device_put(
                np.arange(n * 2.0, dtype=np.float32).reshape(n, 2),
                data_sharding(mesh, ndim=2),
            )
            checkpoint(uri, {"x": x}, version=1)
            like = {"x": jax.device_put(jnp.zeros((n, 2)), data_sharding(mesh, ndim=2))}
            _, loaded = load_checkpoint(uri, like)
            np.testing.assert_array_equal(
                np.asarray(loaded["x"]), np.arange(n * 2.0).reshape(n, 2)
            )
            assert loaded["x"].sharding == like["x"].sharding


class TestRabitTracker:
    def test_rank_assignment_and_topology(self):
        tracker = RabitTracker(nworker=5)
        tracker.start()
        replies = [
            RabitTracker.worker_connect("127.0.0.1", tracker.port, host=f"h{i}")
            for i in range(5)
        ]
        ranks = sorted(r["rank"] for r in replies)
        assert ranks == [0, 1, 2, 3, 4]
        links = get_link_map(5)
        for r in replies:
            assert r["parent"] == links[r["rank"]]["parent"]
            assert r["ring_next"] == links[r["rank"]]["ring_next"]
            assert r["num_worker"] == 5
        for _ in range(5):
            RabitTracker.worker_connect("127.0.0.1", tracker.port, cmd="shutdown")
        tracker.join(timeout=5)
        assert tracker._done.is_set()
        tracker.stop()

    def test_recover_keeps_rank(self):
        tracker = RabitTracker(nworker=3)
        tracker.start()
        first = RabitTracker.worker_connect("127.0.0.1", tracker.port, host="a")
        RabitTracker.worker_connect("127.0.0.1", tracker.port, host="b")
        again = RabitTracker.worker_connect(
            "127.0.0.1", tracker.port, cmd="recover", rank=first["rank"]
        )
        assert again["rank"] == first["rank"]
        tracker.stop()

    def test_too_many_workers_rejected(self):
        tracker = RabitTracker(nworker=1)
        tracker.start()
        RabitTracker.worker_connect("127.0.0.1", tracker.port)
        reply = RabitTracker.worker_connect("127.0.0.1", tracker.port)
        assert "error" in reply
        tracker.stop()

    def _wait_for(self, cond, timeout=5.0):
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(0.02)
        return False

    def test_dead_worker_detected_and_rank_freed(self):
        # VERDICT round-1 item 7: a worker dying mid-job (socket closes
        # without 'shutdown') must be noticed, its rank freed, and a
        # replacement worker must inherit that rank.
        tracker = RabitTracker(nworker=2)
        tracker.start()
        w0 = WorkerSession("127.0.0.1", tracker.port, host="h0")
        w1 = WorkerSession("127.0.0.1", tracker.port, host="h1")
        assert self._wait_for(lambda: tracker.alive_ranks() == [0, 1])
        dead_rank = w1.info["rank"]
        w1.close()  # simulated crash: no shutdown sent
        assert self._wait_for(lambda: tracker.dead_workers == [dead_rank])
        assert tracker.alive_ranks() == [w0.info["rank"]]
        # replacement (different host) inherits the freed rank
        w2 = WorkerSession("127.0.0.1", tracker.port, host="h2")
        assert w2.info["rank"] == dead_rank
        assert self._wait_for(lambda: tracker.alive_ranks() == [0, 1])
        w0.shutdown()
        w2.shutdown()
        assert tracker.join(timeout=5) is True
        tracker.stop()

    def test_join_timeout_on_partial_shutdown(self):
        tracker = RabitTracker(nworker=2)
        tracker.start()
        w0 = WorkerSession("127.0.0.1", tracker.port)
        WorkerSession("127.0.0.1", tracker.port)
        w0.shutdown()  # only one of two workers exits cleanly
        assert tracker.join(timeout=0.3) is False
        tracker.stop()

    def test_recover_reclaims_freed_rank_exclusively(self):
        # rank freed by death, then reclaimed via recover: a later start
        # must NOT be handed the same rank from the free list
        tracker = RabitTracker(nworker=2)
        tracker.start()
        w0 = WorkerSession("127.0.0.1", tracker.port, host="h0")
        dead = w0.info["rank"]
        w0.close()
        assert self._wait_for(lambda: dead in tracker.dead_workers)
        back = WorkerSession("127.0.0.1", tracker.port, cmd="recover", rank=dead)
        assert back.info["rank"] == dead
        other = WorkerSession("127.0.0.1", tracker.port)
        assert other.info["rank"] != dead
        tracker.stop()

    def test_garbled_line_is_not_a_death(self):
        tracker = RabitTracker(nworker=1)
        tracker.start()
        w = WorkerSession("127.0.0.1", tracker.port)
        # inject a non-JSON line on the live socket; the worker must stay alive
        w._sock.sendall(b"this is not json\n")
        w.print_msg("still here")
        assert self._wait_for(lambda: tracker.alive_ranks() == [0])
        assert tracker.dead_workers == []
        w.shutdown()
        assert tracker.join(timeout=5) is True
        tracker.stop()

    def test_clean_session_shutdown_not_counted_dead(self):
        tracker = RabitTracker(nworker=1)
        tracker.start()
        with WorkerSession("127.0.0.1", tracker.port) as ws:
            ws.print_msg("hello from worker")
            ws.shutdown()
        assert tracker.join(timeout=5) is True
        assert self._wait_for(lambda: tracker.alive_ranks() == [])
        assert tracker.dead_workers == []
        tracker.stop()


WORKER_SCRIPT = textwrap.dedent(
    """
    from dmlc_core_tpu.utils import force_cpu_devices
    force_cpu_devices(1)
    import os
    import numpy as np
    from dmlc_core_tpu.parallel import collectives as coll

    coll.init()
    r, w = coll.rank(), coll.world_size()
    assert w == int(os.environ["DMLC_NUM_WORKER"]), (w, os.environ["DMLC_NUM_WORKER"])
    out = coll.allreduce(np.full(4, float(r + 1), np.float32), "sum")
    expected = sum(range(1, w + 1))
    assert np.allclose(out, expected), (out, expected)
    mx = coll.allreduce(np.array([float(r)]), "max")
    assert mx[0] == w - 1
    got = coll.broadcast(np.array([7.5]) if r == 0 else np.array([0.0]), root=0)
    assert got[0] == 7.5, got
    # device-resident allreduce (the external-memory hist-sync path):
    # result must stay a device array and equal the host-path sum
    import jax.numpy as jnp
    dev = coll.allreduce_device(jnp.full((2, 3), float(r + 1)))
    assert hasattr(dev, "devices"), type(dev)
    assert np.allclose(np.asarray(dev), expected), np.asarray(dev)
    print(f"worker {r}/{w} OK", flush=True)
    """
)


@pytest.mark.slow
class TestMultiProcessLocal:
    def test_local_launch_allreduce(self, tmp_path):
        """The reference's local.py pattern: real processes, real collectives.

        Two CPU processes form a jax.distributed cluster via the DMLC env
        ABI and run sum/max allreduce + broadcast.
        """
        script = tmp_path / "worker.py"
        script.write_text(WORKER_SCRIPT)
        from dmlc_core_tpu.tracker import local as local_backend

        codes = []

        def fun_submit(n, envs):
            env = dict(envs)
            env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            codes.extend(
                local_backend.launch(2, [sys.executable, str(script)], env, timeout=120)
            )

        tracker_submit(2, 0, fun_submit, host_ip="127.0.0.1")
        assert codes == [0, 0]

    def test_local_launch_histgbt_training_parity(self, tmp_path):
        """Train a bundled MODEL across real processes (VERDICT r3 #2).

        Two CPU processes form a jax.distributed cluster through the
        tracker ABI + local backend; each fits HistGBT over the
        PROCESS-SPANNING global mesh (the in-round histogram psum rides
        the cross-process Gloo backend — the rabit-allreduce seam) and
        asserts tree-for-tree parity against a single-device fit of the
        same data.  Shared explicit cuts isolate the comparison to the
        boosting engine.  This closes the last untested seam between
        the tracker env ABI and the training engines."""
        script = tmp_path / "gbt_worker.py"
        script.write_text(textwrap.dedent(
            """
            from dmlc_core_tpu.utils import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            from dmlc_core_tpu.parallel import collectives as coll
            coll.init()
            import jax
            from jax.sharding import Mesh
            from dmlc_core_tpu.models import HistGBT
            from dmlc_core_tpu.ops.quantile import compute_cuts

            r, w = coll.rank(), coll.world_size()
            assert w == 2, w
            rng = np.random.default_rng(42)
            X = rng.normal(size=(512, 8)).astype(np.float32)
            y = (X[:, 0] * X[:, 1] + 0.3 * X[:, 2] > 0).astype(np.float32)

            cuts = compute_cuts(X, 32)
            kw = dict(n_trees=6, max_depth=3, n_bins=32, learning_rate=0.5)
            dist = HistGBT(mesh=Mesh(np.array(jax.devices()), ("data",)), **kw)
            dist.fit(X, y, cuts=cuts)
            local = HistGBT(
                mesh=Mesh(np.array(jax.local_devices()), ("data",)), **kw)
            local.fit(X, y, cuts=cuts)

            assert len(dist.trees) == len(local.trees) == 6
            for i, (td, tl) in enumerate(zip(dist.trees, local.trees)):
                assert np.array_equal(td["feat"], tl["feat"]), (r, i)
                assert np.array_equal(td["thr"], tl["thr"]), (r, i)
                np.testing.assert_allclose(td["leaf"], tl["leaf"],
                                           rtol=2e-5, atol=2e-6)
            np.testing.assert_allclose(dist.predict(X), local.predict(X),
                                       rtol=1e-4, atol=1e-5)
            acc = ((dist.predict(X) > 0.5) == y).mean()
            assert acc > 0.9, acc
            print(f"worker {r}/{w}: HistGBT parity OK", flush=True)
            """
        ))
        from dmlc_core_tpu.tracker import local as local_backend

        codes = []

        def fun_submit(n, envs):
            env = dict(envs)
            env["PYTHONPATH"] = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            codes.extend(local_backend.launch(
                2, [sys.executable, str(script)], env, timeout=240))

        tracker_submit(2, 0, fun_submit, host_ip="127.0.0.1")
        assert codes == [0, 0]

    def test_elastic_recovery_drill(self, tmp_path):
        """The reference's distinctive distributed capability, composed
        end to end (VERDICT r4 #1): a 2-process HistGBT fit with
        per-segment checkpoints; worker 1 SIGKILLed MID-FIT on attempt
        0; the tracker notices both deaths and frees the ranks; the
        AM loop gang-kills the survivor, bumps DMLC_NUM_ATTEMPT, and
        relaunches; the restarted workers reclaim ranks via `recover`,
        resume from the last durable checkpoint, and finish.  The final
        model must be BIT-EXACT against the same 2-process job run
        uninterrupted (see examples/elastic_recovery.py, which this
        drives)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "elastic_recovery_example",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "examples", "elastic_recovery.py"))
        drill = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(drill)

        killed_dir = tmp_path / "killed"
        clean_dir = tmp_path / "clean"
        report = drill.run_drill(str(killed_dir), kill=True, timeout=300)
        # attempt 0 must actually have died (worker 1 SIGKILL -9, the
        # survivor gang-killed) and attempt 1 must have finished clean
        assert report["recovered"], report
        assert len(report["attempts"]) == 2, report
        assert -9 in report["attempts"][0]["codes"], report
        assert report["attempts"][1]["codes"] == [0, 0], report
        assert report["dead_seen"] == [0, 1], report

        clean = drill.run_drill(str(clean_dir), kill=False, timeout=300)
        assert clean["attempts"] == [{"attempt": 0, "codes": [0, 0]}]

        from dmlc_core_tpu.models import HistGBT
        recovered = HistGBT.load_model(report["final_model"])
        ref = HistGBT.load_model(clean["final_model"])
        assert (len(recovered.trees) == len(ref.trees)
                == drill.SEGS * drill.SEG_TREES)
        for i, (tr, tf) in enumerate(zip(recovered.trees, ref.trees)):
            assert np.array_equal(tr["feat"], tf["feat"]), i
            assert np.array_equal(tr["thr"], tf["thr"]), i
            np.testing.assert_array_equal(tr["leaf"], tf["leaf"])
        X, y = drill.make_data()
        np.testing.assert_array_equal(recovered.predict(X),
                                      ref.predict(X))

    def test_local_launch_sparse_histgbt_parity(self, tmp_path):
        """Distributed SparseHistGBT across real processes (r5): each
        worker holds its OWN disjoint row shard; global cuts come from
        the candidate-matrix allgather and per-level histograms / node
        totals allreduce across workers.  With the SAME injected cuts,
        the 2-shard distributed fit must match a single-process fit of
        the full data tree-for-tree (the sparse engine's rabit-allreduce
        seam, like the dense parity test)."""
        script = tmp_path / "sparse_worker.py"
        script.write_text(textwrap.dedent(
            """
            from dmlc_core_tpu.utils import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            from dmlc_core_tpu.parallel import collectives as coll
            coll.init()
            from dmlc_core_tpu.models.histgbt_sparse import SparseHistGBT
            from dmlc_core_tpu.ops.sparse_hist import build_sparse_cuts

            r, w = coll.rank(), coll.world_size()
            assert w == 2, w
            rng = np.random.default_rng(17)
            n, F = 600, 30
            mask = rng.random((n, F)) < 0.2
            mask[:, 0] |= rng.random(n) < 0.5
            vals = rng.normal(size=(n, F)).astype(np.float32)
            score = np.where(mask[:, 0], vals[:, 0], -0.5)
            y = (score > 0).astype(np.float32)
            offset = np.concatenate([[0], np.cumsum(mask.sum(axis=1))])
            index = np.nonzero(mask)[1]
            value = vals[mask]
            # one shared cut grid isolates the histogram-allreduce seam
            cuts = build_sparse_cuts(index, value, F, 16)

            def shard(lo, hi):
                keep = slice(offset[lo], offset[hi])
                off = offset[lo:hi + 1] - offset[lo]
                return off, index[keep], value[keep], y[lo:hi]

            half = n // 2
            mine = shard(0, half) if r == 0 else shard(half, n)
            # 2 rounds at moderate lr: by round 3 this easy
            # problem's gradients shrink to near-ties, where f32
            # summation order (allreduce vs single-pass) can flip a
            # threshold or a missing-direction flag — the same property
            # as the dense engine's psum rounding (see
            # test_local_launch_histgbt_training_parity); the early
            # rounds are the exact-parity window
            kw = dict(n_trees=2, max_depth=3, n_bins=16,
                      learning_rate=0.3)
            dist = SparseHistGBT(**kw)
            dist.fit(*mine, n_features=F, cuts=cuts)
            solo = SparseHistGBT(**kw)
            solo.fit(offset, index, value, y, n_features=F, cuts=cuts,
                     distributed=False)
            assert len(dist.trees) == len(solo.trees) == 2
            for i, (td, ts) in enumerate(zip(dist.trees, solo.trees)):
                assert np.array_equal(td["feat"], ts["feat"]), (r, i)
                assert np.array_equal(td["thr"], ts["thr"]), (r, i)
                assert np.array_equal(td["dir"], ts["dir"]), (r, i)
                np.testing.assert_allclose(td["leaf"], ts["leaf"],
                                           rtol=2e-5, atol=2e-6)
            # and the distributed model scores the FULL data like
            # the solo model, well above chance
            pred = dist.predict(offset, index, value)
            np.testing.assert_allclose(
                pred, solo.predict(offset, index, value),
                rtol=1e-5, atol=1e-6)
            acc = ((pred > 0.5) == y).mean()
            assert acc > 0.85, (r, acc)

            # the DEFAULT distributed path (no injected cuts): global
            # cuts from the candidate-matrix allgather-merge; workers
            # must agree bit-for-bit (checked via allreduce min==max)
            auto = SparseHistGBT(**kw)
            auto.fit(*mine, n_features=F)
            flat = np.concatenate(
                [t[k].astype(np.float32).ravel()
                 for t in auto.trees for k in ("feat", "thr", "leaf")])
            mn = coll.allreduce(flat, op="min")
            mx = coll.allreduce(flat, op="max")
            np.testing.assert_array_equal(mn, mx)
            acc2 = ((auto.predict(offset, index, value) > 0.5)
                    == y).mean()
            assert acc2 > 0.85, (r, acc2)
            print(f"worker {r}/{w}: sparse distributed parity OK",
                  flush=True)
            """
        ))
        from dmlc_core_tpu.tracker import local as local_backend

        codes = []

        def fun_submit(n_, envs):
            env = dict(envs)
            env["PYTHONPATH"] = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            codes.extend(local_backend.launch(
                2, [sys.executable, str(script)], env, timeout=240))

        tracker_submit(2, 0, fun_submit, host_ip="127.0.0.1")
        assert codes == [0, 0]

    def test_local_launch_histgbt_missing_mode(self, tmp_path):
        """Missing-value training across real processes: NaN rows all
        land in rank 0's addressable shard, so rank 1 sees no local NaN
        on device — mode selection (allreduce-OR of NaN presence), the
        missing-aware cut allgather (fixed-shape zero-weight NaN knots),
        the missing-bin histogram psum, and per-node direction choice
        must all agree across the cluster, and both ranks must learn
        the MNAR signal (only recoverable via the learned direction).

        Feature 5 is additionally ALL-NaN on rank 0's shard (finite on
        rank 1's): its local summary is the NaN sentinel row and the
        merged cuts must come out finite from rank 1's contribution
        alone (round-4 advisor finding — this used to NaN-poison the
        feature's cuts on every worker)."""
        script = tmp_path / "gbt_missing_worker.py"
        script.write_text(textwrap.dedent(
            """
            from dmlc_core_tpu.utils import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            from dmlc_core_tpu.parallel import collectives as coll
            coll.init()
            import jax
            from jax.sharding import Mesh
            from dmlc_core_tpu.models import HistGBT

            r, w = coll.rank(), coll.world_size()
            assert w == 2, w
            rng = np.random.default_rng(7)
            X = rng.normal(size=(512, 6)).astype(np.float32)
            y = (X[:, 0] > 0).astype(np.float32)
            # MNAR mask confined to the FIRST half = rank 0's shard
            Xm = X.copy()
            mask = np.zeros(512, bool)
            mask[:256] = X[:256, 0] > 0
            Xm[mask, 0] = np.nan
            Xm[:256, 5] = np.nan   # all-NaN on rank 0's shard only

            kw = dict(n_trees=6, max_depth=3, n_bins=32,
                      learning_rate=0.5)
            dist = HistGBT(mesh=Mesh(np.array(jax.devices()),
                                     ("data",)), **kw)
            dist.fit(Xm, y)
            assert dist._missing, "mode must be ON on every rank"
            assert np.isfinite(np.asarray(dist.cuts)).all(), \\
                "all-NaN-on-one-shard feature poisoned the merged cuts"
            local = HistGBT(
                mesh=Mesh(np.array(jax.local_devices()), ("data",)),
                **kw)
            local.fit(Xm, y)
            for i, (td, tl) in enumerate(zip(dist.trees, local.trees)):
                assert np.array_equal(td["feat"], tl["feat"]), (r, i)
                assert np.array_equal(td["thr"], tl["thr"]), (r, i)
                assert np.array_equal(td["dir"], tl["dir"]), (r, i)
            pred = dist.predict(Xm) > 0.5
            acc_masked = (pred[mask] == y[mask]).mean()
            assert acc_masked > 0.9, (r, acc_masked)
            print(f"worker {r}/{w}: missing-mode parity OK", flush=True)
            """
        ))
        from dmlc_core_tpu.tracker import local as local_backend

        codes = []

        def fun_submit(n, envs):
            env = dict(envs)
            env["PYTHONPATH"] = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            codes.extend(local_backend.launch(
                2, [sys.executable, str(script)], env, timeout=240))

        tracker_submit(2, 0, fun_submit, host_ip="127.0.0.1")
        assert codes == [0, 0]

    def test_local_launch_bert_training_parity(self, tmp_path):
        """A bundled TRANSFORMER trained across real processes: the
        fused in-step grad psum rides the cross-process Gloo backend on
        a global mesh; three optimizer steps must match the
        single-device fit loss-for-loss and parameter-for-parameter.
        With the HistGBT twin above, both model families' training
        engines are proven over the real tracker + jax.distributed
        seam, not just the virtual mesh."""
        script = tmp_path / "bert_worker.py"
        script.write_text(textwrap.dedent(
            """
            from dmlc_core_tpu.utils import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            from dmlc_core_tpu.parallel import collectives as coll
            coll.init()
            import jax
            from jax.sharding import Mesh
            from dmlc_core_tpu.models.bert import BERT

            r, w = coll.rank(), coll.world_size()
            assert w == 2, w
            cfg = dict(n_layers=2, d_model=32, n_heads=2, d_ff=64,
                       vocab_size=64, max_len=16, learning_rate=1e-2)
            rng = np.random.default_rng(5)
            B, S = 8, 16
            tokens = rng.integers(0, 64, size=(B, S)).astype(np.int32)
            labels = rng.integers(0, 64, size=(B, S)).astype(np.int32)
            mask = (rng.random((B, S)) < 0.3).astype(np.float32)

            dist = BERT(mesh=Mesh(np.array(jax.devices()), ("data",)),
                        **cfg)
            dist.init_params(0)
            d_losses = [dist.train_step(tokens, labels, mask)
                        for _ in range(3)]
            local = BERT(
                mesh=Mesh(np.array(jax.local_devices()), ("data",)), **cfg)
            local.init_params(0)
            l_losses = [local.train_step(tokens, labels, mask)
                        for _ in range(3)]
            np.testing.assert_allclose(d_losses, l_losses,
                                       rtol=2e-5, atol=2e-6)
            for k in dist.params:
                np.testing.assert_allclose(
                    np.asarray(dist.params[k]),
                    np.asarray(local.params[k]), rtol=2e-4, atol=2e-5)
            assert d_losses[0] > d_losses[-1], d_losses
            print(f"worker {r}/{w}: BERT parity OK", flush=True)
            """
        ))
        from dmlc_core_tpu.tracker import local as local_backend

        codes = []

        def fun_submit(n, envs):
            env = dict(envs)
            env["PYTHONPATH"] = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            codes.extend(local_backend.launch(
                2, [sys.executable, str(script)], env, timeout=300))

        tracker_submit(2, 0, fun_submit, host_ip="127.0.0.1")
        assert codes == [0, 0]

    def test_local_launch_fit_external_sharded_parity(self, tmp_path):
        """Distributed OUT-OF-CORE training across real processes: each
        worker parses its InputSplit shard (part=rank, nparts=2) and
        fit_external syncs per-level histograms with allreduce_device
        over the cross-process backend.  With shared explicit cuts the
        distributed trees must equal a single-process fit_external over
        the full data tree-for-tree; the no-cuts run additionally
        exercises the cross-worker sketch allgather (loose oracle:
        the model still learns)."""
        import numpy as np

        rng = np.random.default_rng(17)
        X = rng.normal(size=(2000, 6)).astype(np.float32)
        y = (X[:, 0] * X[:, 1] + 0.3 * X[:, 2] > 0).astype(np.float32)
        data = tmp_path / "shard.libsvm"
        with open(data, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(6))
                f.write(f"{y[i]:.0f} {feats}\n")

        # single-process oracle over the FULL data, fixed cuts
        from dmlc_core_tpu.data.iter import RowBlockIter
        from dmlc_core_tpu.models import HistGBT
        from dmlc_core_tpu.ops.quantile import compute_cuts

        cuts = np.asarray(compute_cuts(X, 32))
        np.save(tmp_path / "cuts.npy", cuts)
        it = RowBlockIter.create(str(data), 0, 1, "libsvm")
        oracle = HistGBT(n_trees=5, max_depth=3, n_bins=32,
                         hist_method="segment")
        oracle.fit_external(it, num_col=6, cuts=cuts)
        it.close()
        np.savez(tmp_path / "expected.npz",
                 feat=np.stack([t["feat"] for t in oracle.trees]),
                 thr=np.stack([t["thr"] for t in oracle.trees]),
                 leaf=np.stack([t["leaf"] for t in oracle.trees]))

        script = tmp_path / "ext_worker.py"
        script.write_text(textwrap.dedent(
            """
            import os
            from dmlc_core_tpu.utils import force_cpu_devices
            force_cpu_devices(1)
            import numpy as np
            from dmlc_core_tpu.parallel import collectives as coll
            coll.init()
            from dmlc_core_tpu.data.iter import RowBlockIter
            from dmlc_core_tpu.models import HistGBT

            r, w = coll.rank(), coll.world_size()
            base = os.environ["TEST_DIR"]
            cuts = np.load(os.path.join(base, "cuts.npy"))
            exp = np.load(os.path.join(base, "expected.npz"))

            it = RowBlockIter.create(
                os.path.join(base, "shard.libsvm"), r, w, "libsvm")
            m = HistGBT(n_trees=5, max_depth=3, n_bins=32,
                        hist_method="segment")
            m.fit_external(it, num_col=6, cuts=cuts)
            it.close()
            np.testing.assert_array_equal(
                np.stack([t["feat"] for t in m.trees]), exp["feat"])
            np.testing.assert_array_equal(
                np.stack([t["thr"] for t in m.trees]), exp["thr"])
            np.testing.assert_allclose(
                np.stack([t["leaf"] for t in m.trees]), exp["leaf"],
                rtol=2e-4, atol=2e-5)

            # no-cuts path: cross-worker sketch allgather merges the
            # shard summaries; the model must still learn
            it = RowBlockIter.create(
                os.path.join(base, "shard.libsvm"), r, w, "libsvm")
            m2 = HistGBT(n_trees=10, max_depth=3, n_bins=32,
                         hist_method="segment")
            m2.fit_external(it, num_col=6)
            it.close()
            Xl = np.load(os.path.join(base, "X.npy"))
            yl = np.load(os.path.join(base, "y.npy"))
            acc = ((m2.predict(Xl) > 0.5) == yl).mean()
            assert acc > 0.88, acc
            print(f"worker {r}/{w}: sharded fit_external parity OK "
                  f"(sketch-merged acc {acc:.3f})", flush=True)
            """
        ))
        np.save(tmp_path / "X.npy", X)
        np.save(tmp_path / "y.npy", y)
        from dmlc_core_tpu.tracker import local as local_backend

        codes = []

        def fun_submit(n, envs):
            env = dict(envs)
            env["PYTHONPATH"] = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
            env["TEST_DIR"] = str(tmp_path)
            codes.extend(local_backend.launch(
                2, [sys.executable, str(script)], env, timeout=300))

        tracker_submit(2, 0, fun_submit, host_ip="127.0.0.1")
        assert codes == [0, 0]


class TestReduceScatter:
    def test_sum_matches_allreduce_slice(self):
        import jax
        import numpy as np
        from dmlc_core_tpu.parallel import collectives as coll
        from dmlc_core_tpu.parallel.mesh import local_mesh

        mesh = local_mesh()
        k = mesh.shape["data"]
        x = jnp.asarray(np.arange(8 * k * 3, dtype=np.float32).reshape(k * 4, 6))
        out = coll.device_reduce_scatter(x, mesh, "sum")
        # replicated input ⇒ reduce over axis = k·x; each shard holds its slice
        want = np.asarray(x) * k
        got = np.asarray(out)
        np.testing.assert_allclose(got, want)

    def test_max(self):
        import numpy as np
        from dmlc_core_tpu.parallel import collectives as coll
        from dmlc_core_tpu.parallel.mesh import local_mesh

        mesh = local_mesh()
        k = mesh.shape["data"]
        x = jnp.asarray(np.random.default_rng(0).normal(size=(k * 2, 4)).astype(np.float32))
        got = np.asarray(coll.device_reduce_scatter(x, mesh, "max"))
        np.testing.assert_allclose(got, np.asarray(x))  # max of replicas = x

    def test_indivisible_rejected(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.parallel import collectives as coll
        from dmlc_core_tpu.parallel.mesh import local_mesh

        mesh = local_mesh()
        if mesh.shape["data"] == 1:
            pytest.skip("needs >1 device")
        bad = jnp.zeros((mesh.shape["data"] + 1, 2))
        with pytest.raises(Error):
            coll.device_reduce_scatter(bad, mesh)


class TestZeroAdam:
    def test_matches_replicated_adam(self):
        """ZeRO-sharded Adam must produce the same trajectory as plain
        replicated Adam on the globally-summed gradients."""

        import jax
        from dmlc_core_tpu.base.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from dmlc_core_tpu.parallel.mesh import local_mesh
        from dmlc_core_tpu.parallel.zero import ZeroAdam

        mesh = local_mesh()
        Pn = mesh.shape["data"]
        rng = np.random.default_rng(0)
        # parameter sizes deliberately NOT multiples of P (padding path)
        params = {"w": rng.normal(size=(13, 3)).astype(np.float32),
                  "b": rng.normal(size=(5,)).astype(np.float32)}
        # per-device local gradients: global grad = mean over devices
        gw = rng.normal(size=(Pn, 13, 3)).astype(np.float32)
        gb = rng.normal(size=(Pn, 5)).astype(np.float32)

        opt = ZeroAdam(lr=0.1)

        def train(params, gw_shard, gb_shard):
            state = opt.init(params)
            for _ in range(3):
                params, state = opt.step(
                    params, {"w": gw_shard[0], "b": gb_shard[0]}, state)
            return params

        fn = jax.jit(shard_map(
            train, mesh=mesh,
            in_specs=(P(), P("data"), P("data")), out_specs=P(),
            check_vma=False))
        out = jax.tree.map(np.asarray, fn(params, gw, gb))

        # replicated-Adam oracle on the mean gradients
        def adam_oracle(p, g, steps=3, lr=0.1, b1=0.9, b2=0.999, eps=1e-8):
            mu = np.zeros_like(p); nu = np.zeros_like(p)
            for t in range(1, steps + 1):
                mu = b1 * mu + (1 - b1) * g
                nu = b2 * nu + (1 - b2) * g * g
                p = p - lr * (mu / (1 - b1**t)) / (
                    np.sqrt(nu / (1 - b2**t)) + eps)
            return p
        want_w = adam_oracle(params["w"], gw.mean(0))
        want_b = adam_oracle(params["b"], gb.mean(0))
        np.testing.assert_allclose(out["w"], want_w, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(out["b"], want_b, rtol=1e-4, atol=1e-5)

    def test_state_is_sharded(self):
        import jax
        from dmlc_core_tpu.base.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from dmlc_core_tpu.parallel.mesh import local_mesh
        from dmlc_core_tpu.parallel.zero import ZeroAdam

        mesh = local_mesh()
        Pn = mesh.shape["data"]
        params = {"w": np.zeros((16, 4), np.float32)}
        opt = ZeroAdam()

        def init_only(params):
            st = opt.init(params)
            return st.mu["w"].shape[0]

        fn = jax.jit(shard_map(lambda p: jnp.asarray(init_only(p)),
                               mesh=mesh, in_specs=(P(),), out_specs=P(),
                               check_vma=False))
        per_dev = int(np.asarray(fn(params)))
        assert per_dev == 64 // Pn      # each device holds 1/P of the state

    def test_nested_pytree_params(self):
        import jax
        from dmlc_core_tpu.base.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from dmlc_core_tpu.parallel.mesh import local_mesh
        from dmlc_core_tpu.parallel.zero import ZeroAdam

        mesh = local_mesh()
        params = {"layer": {"w": np.ones((4, 2), np.float32)},
                  "head": np.ones(3, np.float32)}
        grads = jax.tree.map(np.ones_like, params)
        opt = ZeroAdam(lr=0.1)

        def one(p, g):
            st = opt.init(p)
            p2, _ = opt.step(p, g, st)
            return p2

        fn = jax.jit(shard_map(one, mesh=mesh, in_specs=(P(), P()),
                               out_specs=P(), check_vma=False))
        out = jax.tree.map(np.asarray, fn(params, grads))
        np.testing.assert_allclose(out["layer"]["w"], 0.9, atol=1e-5)
        np.testing.assert_allclose(out["head"], 0.9, atol=1e-5)
