"""Multi-chip data-parallel HistGBT: sharded ingest + oracle parity.

The ISSUE 7 contracts, pinned on the 8-virtual-device CPU mesh the
whole suite runs under (conftest):

* row-range math tiles exactly for ANY odd size (the input_split
  contract lifted to rows, plus the slab→shard tail math);
* sharded per-chip ingest is byte-identical to the global staging path;
* with the deterministic histogram reduction (``DMLC_HIST_BLOCKS``) an
  N-chip fit serializes byte-identically to the 1-chip oracle;
* out-of-core streamed ingest (``make_device_data_iter``, tiny chunk
  slabs, DiskRowIter-backed) matches the in-core ensemble bit-exactly;
* the histogram-psum traffic metric matches the analytic model.
"""

import os
import sys

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.data.iter import (RowBlockIter, iter_dense_slabs,  # noqa: E402
                                     slab_shard_slices)
from dmlc_core_tpu.models import HistGBT  # noqa: E402
from dmlc_core_tpu.models.histgbt import _tree_fold  # noqa: E402
from dmlc_core_tpu.ops.histogram import hist_psum_bytes_per_round  # noqa: E402
from dmlc_core_tpu.ops.quantile import compute_cuts  # noqa: E402
from dmlc_core_tpu.parallel.mesh import (device_count, local_mesh,  # noqa: E402
                                         row_shard_layout,
                                         shard_row_ranges)

KW = dict(n_trees=3, max_depth=3, n_bins=16, learning_rate=0.3)


def _make_xy(n, F=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0).astype(np.float32)
    return X, y


def _trees_equal(a, b):
    return (len(a) == len(b)
            and all(np.array_equal(ta[k], tb[k])
                    for ta, tb in zip(a, b) for k in ta))


class TestRowRangeMath:
    def test_shard_row_ranges_tile_exactly(self):
        # property sweep over odd sizes: disjoint, ordered, union exact
        for n in (0, 1, 2, 7, 8, 9, 63, 64, 65, 1000, 1013, 4097):
            for k in (1, 2, 3, 5, 7, 8, 16, 1001):
                ranges = shard_row_ranges(n, k)
                assert len(ranges) == k
                pos = 0
                for lo, hi in ranges:
                    assert lo == pos and hi >= lo
                    pos = hi
                assert pos == n
                # remainder spreads: no part exceeds ceil(n/k)
                assert max(hi - lo for lo, hi in ranges) <= -(-n // k) \
                    if n else True

    def test_slab_shard_slices_cover_every_row_once(self):
        # simulate the sharded ingest scatter over odd chunk/tail combos
        rng = np.random.default_rng(3)
        for n, chunk, ndev in [(1013, 96, 8), (64, 64, 8), (100, 7, 4),
                               (8, 3, 8), (4096, 1000, 8), (17, 100, 2)]:
            n_padded, S = row_shard_layout(n, local_mesh(ndev))
            seen = np.zeros(n, np.int32)
            dest = np.full(n, -1, np.int64)
            for lo in range(0, n, chunk):
                length = min(chunk, n - lo)
                pieces = slab_shard_slices(lo, length, S)
                covered = 0
                for k, s_lo, s_hi, dst in pieces:
                    assert 0 <= k < ndev
                    assert 0 <= dst and dst + (s_hi - s_lo) <= S
                    seen[lo + s_lo:lo + s_hi] += 1
                    dest[lo + s_lo:lo + s_hi] = np.arange(
                        k * S + dst, k * S + dst + (s_hi - s_lo))
                    covered += s_hi - s_lo
                assert covered == length
            assert (seen == 1).all(), "a row was dropped or duplicated"
            # global placement is the identity: row i lands at offset i
            assert np.array_equal(dest, np.arange(n))

    def test_row_shard_layout_padding(self):
        mesh = local_mesh(8)
        n_padded, S = row_shard_layout(1013, mesh)
        assert n_padded % 8 == 0 and n_padded >= 1013 and S == n_padded // 8
        # coarser pad multiple (deterministic blocks): lcm honored
        n_padded2, S2 = row_shard_layout(1013, mesh, pad_multiple=32)
        assert n_padded2 % 32 == 0 and S2 * 8 == n_padded2

    def test_tree_fold_composition(self):
        # the fold over C leaves must equal per-shard folds of aligned
        # sub-ranges folded again — the property 1-vs-N parity rests on
        rng = np.random.default_rng(5)
        parts = [rng.normal(size=(4, 3)).astype(np.float32)
                 for _ in range(16)]
        full = _tree_fold(list(parts))
        for nshard in (2, 4, 8, 16):
            per = len(parts) // nshard
            partials = [_tree_fold(parts[i * per:(i + 1) * per])
                        for i in range(nshard)]
            again = _tree_fold(partials)
            assert np.array_equal(full, again), f"nshard={nshard}"


class TestInputSplitOddSizes:
    def test_recordio_parts_tile_exactly(self, tmp_path):
        # property-style: odd record counts/sizes across several files;
        # for every nparts the union over parts is the full record set,
        # no overlap, order preserved within parts
        from dmlc_core_tpu.io.input_split import InputSplit
        from dmlc_core_tpu.io.recordio import encode_records

        rng = np.random.default_rng(11)
        records = []
        for fi, count in enumerate((17, 1, 23, 8)):
            recs = [bytes(rng.integers(0, 256, size=int(sz), dtype=np.uint8))
                    for sz in rng.integers(1, 200, size=count)]
            (tmp_path / f"part-{fi}.rec").write_bytes(encode_records(recs))
            records.extend(recs)
        uri = str(tmp_path / "part-*.rec")
        # glob isn't a thing here: list files explicitly via ';'
        uri = ";".join(str(tmp_path / f"part-{fi}.rec") for fi in range(4))
        for nparts in (1, 2, 3, 5, 8, 11):
            got = []
            for part in range(nparts):
                with InputSplit.create(uri, part, nparts, "recordio",
                                       threaded=False) as sp:
                    got.extend(iter(sp))
            assert got == records, f"nparts={nparts}"


class TestShardedIngestParity:
    def test_sharded_vs_global_staging_bit_identical(self, monkeypatch):
        X, y = _make_xy(1013)
        cuts = compute_cuts(X, KW["n_bins"])
        mesh = local_mesh(8)
        monkeypatch.setenv("DMLC_SHARDED_INGEST", "0")
        m_gl = HistGBT(mesh=mesh, **KW)
        dd_gl = m_gl.make_device_data(X, y, cuts=cuts)
        monkeypatch.setenv("DMLC_SHARDED_INGEST", "1")
        m_sh = HistGBT(mesh=mesh, **KW)
        dd_sh = m_sh.make_device_data(X, y, cuts=cuts)
        assert np.array_equal(np.asarray(dd_gl["bins_t"]),
                              np.asarray(dd_sh["bins_t"]))
        assert np.array_equal(np.asarray(dd_gl["y_d"]),
                              np.asarray(dd_sh["y_d"]))
        assert np.array_equal(np.asarray(dd_gl["w_d"]),
                              np.asarray(dd_sh["w_d"]))
        m_gl.fit_device(dd_gl)
        m_sh.fit_device(dd_sh)
        assert _trees_equal(m_gl.trees, m_sh.trees)

    def test_sharded_ingest_host_bin_route(self, monkeypatch):
        # DMLC_TPU_BIN_BACKEND=cpu (the bench staging mode) through the
        # per-chip placement must match the device-bin route exactly
        X, y = _make_xy(519, seed=2)
        cuts = compute_cuts(X, KW["n_bins"])
        m_dev = HistGBT(mesh=local_mesh(8), **KW)
        dd_dev = m_dev.make_device_data(X, y, cuts=cuts)
        monkeypatch.setenv("DMLC_TPU_BIN_BACKEND", "cpu")
        m_cpu = HistGBT(mesh=local_mesh(8), **KW)
        dd_cpu = m_cpu.make_device_data(X, y, cuts=cuts)
        assert np.array_equal(np.asarray(dd_dev["bins_t"]),
                              np.asarray(dd_cpu["bins_t"]))

    def test_chunked_sharded_ingest_matches_single_slab(self, monkeypatch):
        # nrows % (chips * chunk) != 0: the streamed tail must place
        # identically to a one-slab ingest
        X, y = _make_xy(1111, seed=4)
        cuts = compute_cuts(X, KW["n_bins"])
        m_one = HistGBT(mesh=local_mesh(8), **KW)
        dd_one = m_one.make_device_data(X, y, cuts=cuts)
        monkeypatch.setenv("DMLC_INGEST_CHUNK_ROWS", "96")
        m_chk = HistGBT(mesh=local_mesh(8), **KW)
        dd_chk = m_chk.make_device_data(X, y, cuts=cuts)
        assert np.array_equal(np.asarray(dd_one["bins_t"]),
                              np.asarray(dd_chk["bins_t"]))

    def test_external_cached_sharded_staging(self, monkeypatch, tmp_path):
        # the auto-residency external route (host pages) through the
        # per-chip staging == the global-put staging, tree for tree
        X, y = _make_xy(333, F=5, seed=6)
        path = tmp_path / "data.libsvm"
        with open(path, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(5))
                f.write(f"{y[i]:.0f} {feats}\n")

        def fit_one():
            m = HistGBT(mesh=local_mesh(8), **KW)
            m.fit_external(RowBlockIter.create(str(path)), num_col=5)
            return m

        monkeypatch.setenv("DMLC_SHARDED_INGEST", "0")
        m_gl = fit_one()
        monkeypatch.setenv("DMLC_SHARDED_INGEST", "1")
        m_sh = fit_one()
        assert _trees_equal(m_gl.trees, m_sh.trees)


class TestOneChipOracle:
    def test_nchip_fit_matches_1chip_oracle_bytes(self, monkeypatch,
                                                  tmp_path):
        # THE flagship contract: same global rows => identical ensemble
        # bytes, 1 chip vs 8 chips, via the deterministic histogram
        # reduction (DMLC_HIST_BLOCKS; plain psum's accumulation order
        # varies with mesh shape and CAN flip a near-tie split)
        monkeypatch.setenv("DMLC_HIST_BLOCKS", "8")
        X, y = _make_xy(1003, F=7, seed=1)
        cuts = compute_cuts(X, KW["n_bins"])
        devs = np.array(jax.devices())
        m1 = HistGBT(mesh=Mesh(devs[:1], ("data",)), **KW)
        m1.fit(X, y, cuts=cuts)
        m8 = HistGBT(mesh=Mesh(devs[:8], ("data",)), **KW)
        m8.fit(X, y, cuts=cuts)
        p1, p8 = tmp_path / "m1.gbt", tmp_path / "m8.gbt"
        m1.save_model(str(p1))
        m8.save_model(str(p8))
        assert p1.read_bytes() == p8.read_bytes()
        # and a third mesh shape for the invariance claim
        m2 = HistGBT(mesh=Mesh(devs[:2], ("data",)), **KW)
        m2.fit(X, y, cuts=cuts)
        assert _trees_equal(m1.trees, m2.trees)

    def test_nchip_oracle_survives_new_knobs(self, monkeypatch):
        # the ISSUE 12 levers must preserve the mesh-shape-invariant
        # fold: packed storage derives the SAME layout on every mesh
        # (occupancy counts are row-order independent) and lossguide
        # mirrors the per-block deterministic reduction
        monkeypatch.setenv("DMLC_HIST_BLOCKS", "8")
        monkeypatch.setenv("DMLC_BIN_PACK", "1")
        monkeypatch.setenv("DMLC_GROW_POLICY", "lossguide")
        rng = np.random.default_rng(5)
        n = 1003
        X = rng.normal(size=(n, 7)).astype(np.float32)
        X[:, 2] = rng.integers(0, 3, n).astype(np.float32)
        X[:, 5] = rng.integers(0, 4, n).astype(np.float32)
        y = (X[:, 0] + X[:, 2] > 0.5).astype(np.float32)
        cuts = compute_cuts(X, KW["n_bins"])
        devs = np.array(jax.devices())
        m1 = HistGBT(mesh=Mesh(devs[:1], ("data",)), **KW)
        m1.fit(X, y, cuts=cuts)
        m8 = HistGBT(mesh=Mesh(devs[:8], ("data",)), **KW)
        m8.fit(X, y, cuts=cuts)
        assert m1._bin_layout is not None
        assert m1._bin_layout == m8._bin_layout   # identical layout
        assert _trees_equal(m1.trees, m8.trees)

    def test_fused_round_falls_back_under_hist_blocks(self, monkeypatch,
                                                      tmp_path):
        # ISSUE 18: the fused round kernel accumulates in pallas tile
        # order, which would break the per-block deterministic fold —
        # so the eligibility gate excludes DMLC_HIST_BLOCKS (and any
        # multi-chip mesh) even when the knob FORCES fused.  An N-chip
        # deterministic fit must therefore serialize byte-identically
        # with the knob on or off.
        monkeypatch.setenv("DMLC_HIST_BLOCKS", "8")
        X, y = _make_xy(1003, F=7, seed=1)
        cuts = compute_cuts(X, KW["n_bins"])
        devs = np.array(jax.devices())

        def fit_bytes(path, fused):
            monkeypatch.setenv("DMLC_FUSED_ROUND", fused)
            m = HistGBT(mesh=Mesh(devs[:8], ("data",)), **KW)
            m.fit(X, y, cuts=cuts)
            m.save_model(str(path))
            return path.read_bytes()

        assert fit_bytes(tmp_path / "off.gbt", "0") \
            == fit_bytes(tmp_path / "on.gbt", "1")

    def test_deterministic_mode_prediction_parity(self, monkeypatch):
        # deterministic-mode trees predict identically from either mesh
        monkeypatch.setenv("DMLC_HIST_BLOCKS", "8")
        X, y = _make_xy(520, seed=9)
        cuts = compute_cuts(X, KW["n_bins"])
        devs = np.array(jax.devices())
        m1 = HistGBT(mesh=Mesh(devs[:1], ("data",)), **KW)
        m1.fit(X, y, cuts=cuts)
        m8 = HistGBT(mesh=Mesh(devs[:8], ("data",)), **KW)
        m8.fit(X, y, cuts=cuts)
        np.testing.assert_array_equal(
            m1.predict(X, output_margin=True),
            m8.predict(X, output_margin=True))


class TestOutOfCore:
    def test_iter_ingest_matches_incore_bytes(self, monkeypatch, tmp_path):
        # streamed tiny slabs (out-of-core shape) == in-core fit,
        # ensemble serialized byte-identically
        monkeypatch.setenv("DMLC_INGEST_CHUNK_ROWS", "128")
        X, y = _make_xy(1013, seed=5)
        n = len(y)
        m_it = HistGBT(mesh=local_mesh(8), **KW)

        def slabs():
            for lo in range(0, n, 160):    # misaligned with chunk AND S
                yield X[lo:lo + 160], y[lo:lo + 160], None

        dd = m_it.make_device_data_iter(slabs)
        m_it.fit_device(dd)
        m_ic = HistGBT(mesh=local_mesh(8), **KW)
        m_ic.fit(X, y, cuts=m_it.cuts)
        pa, pb = tmp_path / "it.gbt", tmp_path / "ic.gbt"
        m_it.save_model(str(pa))
        m_ic.save_model(str(pb))
        assert pa.read_bytes() == pb.read_bytes()

    def test_disk_row_iter_out_of_core(self, tmp_path):
        # the DiskRowIter/input_split page pipeline end to end: libsvm
        # -> #cache pages -> dense slabs -> sharded device ingest; the
        # handle must train and predict without X ever being needed
        X, y = _make_xy(801, F=5, seed=8)
        path = tmp_path / "big.libsvm"
        with open(path, "w") as f:
            for i in range(len(y)):
                feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(5))
                f.write(f"{y[i]:.0f} {feats}\n")
        uri = f"{path}#{tmp_path}/cache.bin"

        def slabs():
            it = RowBlockIter.create(uri)
            return iter_dense_slabs(it, 5, 96)

        m = HistGBT(mesh=local_mesh(8), **KW)
        dd = m.make_device_data_iter(slabs, n_features=5)
        m.fit_device(dd)
        assert dd["n"] == 801 and dd["n_padded"] % 8 == 0
        assert len(m.trees) == KW["n_trees"]
        # same rows in-core with the sketch cuts => identical trees
        # (compare against the PARSED values: the libsvm text round
        # trip is not f32-exact, the oracle must see what disk saw)
        Xp = np.concatenate([np.array(xb) for xb, _, _ in slabs()])
        yp = np.concatenate([np.array(yb) for _, yb, _ in slabs()])
        m2 = HistGBT(mesh=local_mesh(8), **KW)
        m2.fit(Xp, yp, cuts=m.cuts)
        assert _trees_equal(m.trees, m2.trees)

    def test_iter_ingest_rejects_nan(self):
        X, y = _make_xy(64)
        X[3, 1] = np.nan
        m = HistGBT(mesh=local_mesh(8), **KW)
        with pytest.raises(Exception, match="NaN"):
            m.make_device_data_iter(lambda: iter([(X, y, None)]))


class TestPsumTraffic:
    def test_analytic_model_shape(self):
        # depth-1 tree: root only — [2, 1, F, B] f32
        assert hist_psum_bytes_per_round(1, 28, 256) == 2 * 28 * 256 * 4
        # sibling subtraction: each extra level adds 2 * 2^(l-1) * F * B * 4
        d6 = hist_psum_bytes_per_round(6, 28, 256)
        assert d6 == sum((2 * (1 if l == 0 else 1 << (l - 1))
                          * 28 * 256 * 4) for l in range(6))

    def test_counter_matches_model(self):
        from dmlc_core_tpu.base.metrics import default_registry

        X, y = _make_xy(512, seed=12)
        mesh = local_mesh(8)

        def psum_total():
            snap = default_registry().snapshot()["metrics"]
            m = snap.get("dmlc_histogram_psum_bytes_total")
            return (sum(s["value"] for s in m["series"]
                        if s["labels"].get("engine") == "incore")
                    if m else 0.0)

        before = psum_total()
        m8 = HistGBT(mesh=mesh, **KW)
        m8.fit(X, y)
        expect = KW["n_trees"] * hist_psum_bytes_per_round(
            KW["max_depth"], X.shape[1], KW["n_bins"])
        assert psum_total() - before == expect

    def test_counter_matches_model_packed(self, monkeypatch):
        # packed layout: the analytic model (and therefore the counter)
        # must price the STORAGE shape the psum actually syncs
        from dmlc_core_tpu.base.metrics import default_registry

        rng = np.random.default_rng(21)
        n, F = 512, 6
        X = rng.normal(size=(n, F)).astype(np.float32)
        X[:, 1] = rng.integers(0, 3, n).astype(np.float32)
        X[:, 3] = rng.integers(0, 2, n).astype(np.float32)
        X[:, 4] = rng.integers(0, 4, n).astype(np.float32)
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)

        def psum_total():
            snap = default_registry().snapshot()["metrics"]
            m = snap.get("dmlc_histogram_psum_bytes_total")
            return (sum(s["value"] for s in m["series"]
                        if s["labels"].get("engine") == "incore")
                    if m else 0.0)

        monkeypatch.setenv("DMLC_BIN_PACK", "1")
        before = psum_total()
        m8 = HistGBT(mesh=local_mesh(8), **KW)
        m8.fit(X, y)
        assert m8._bin_layout is not None      # the lever actually fired
        expect = KW["n_trees"] * hist_psum_bytes_per_round(
            KW["max_depth"], F, KW["n_bins"], layout=m8._bin_layout)
        assert psum_total() - before == expect

    def test_counter_matches_model_lossguide(self, monkeypatch):
        from dmlc_core_tpu.base.metrics import default_registry

        X, y = _make_xy(512, seed=14)

        def psum_total():
            snap = default_registry().snapshot()["metrics"]
            m = snap.get("dmlc_histogram_psum_bytes_total")
            return (sum(s["value"] for s in m["series"]
                        if s["labels"].get("engine") == "incore")
                    if m else 0.0)

        monkeypatch.setenv("DMLC_GROW_POLICY", "lossguide")
        monkeypatch.setenv("DMLC_MAX_LEAVES", "4")
        before = psum_total()
        m8 = HistGBT(mesh=local_mesh(8), **KW)
        m8.fit(X, y)
        expect = KW["n_trees"] * hist_psum_bytes_per_round(
            KW["max_depth"], X.shape[1], KW["n_bins"],
            grow_policy="lossguide", max_leaves=4)
        assert psum_total() - before == expect
        # the lever's win shows at depth: a budgeted deep tree syncs
        # far fewer built nodes than level-batched growth
        assert hist_psum_bytes_per_round(
            6, 28, 256, grow_policy="lossguide", max_leaves=8
        ) < hist_psum_bytes_per_round(6, 28, 256)

    def test_counter_silent_on_one_chip(self):
        from dmlc_core_tpu.base.metrics import default_registry

        X, y = _make_xy(256, seed=13)

        def psum_total():
            snap = default_registry().snapshot()["metrics"]
            m = snap.get("dmlc_histogram_psum_bytes_total")
            return (sum(s["value"] for s in m["series"]) if m else 0.0)

        before = psum_total()
        m1 = HistGBT(mesh=local_mesh(1), **KW)
        m1.fit(X, y)
        assert psum_total() == before      # no cross-chip traffic

    def test_device_count_helper(self):
        assert device_count(local_mesh(8)) == 8
        assert device_count(local_mesh(1)) == 1
