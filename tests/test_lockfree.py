"""Lock-free MPMC queue + Spinlock tests.

Mirrors the reference's ``test/unittest/unittest_lockfree.cc`` strategy
(SURVEY.md §4): N producers × M consumers hammer one queue; every pushed
token must be popped exactly once; blocking ops honor timeouts and
SignalForKill.
"""

import threading
import time

import pytest

from dmlc_core_tpu.io.lockfree import (
    BlockingConcurrentQueue,
    ConcurrentQueue,
    QueueKilledError,
    Spinlock,
    native_queue_available,
)


def test_native_engine_is_live():
    # When libdmlctpu.so is built (`make -C cpp`; it is not checked in),
    # the lock-free engine must be the real one, not the pure-Python
    # fallback — unless the env explicitly disables it
    # (DMLC_TPU_NATIVE_IO=0 re-runs this suite on the fallback).
    import os

    if os.environ.get("DMLC_TPU_NATIVE_IO", "1") == "0":
        pytest.skip("native engine disabled via DMLC_TPU_NATIVE_IO=0")
    so = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "build", "libdmlctpu.so")
    if not os.path.exists(so):
        pytest.skip("native lib not built (make -C cpp)")
    assert native_queue_available()


def test_try_enqueue_dequeue_fifo_single_thread():
    q = ConcurrentQueue(capacity=8)
    for i in range(8):
        assert q.try_enqueue(("item", i))
    assert not q.try_enqueue("overflow")
    got = []
    while True:
        ok, v = q.try_dequeue()
        if not ok:
            break
        got.append(v)
    assert got == [("item", i) for i in range(8)]


def test_size_approx():
    q = ConcurrentQueue(capacity=16)
    for i in range(5):
        q.try_enqueue(i)
    assert q.size_approx() == 5


def test_mpmc_stress_every_token_once():
    n_producers, n_consumers, per_producer = 4, 4, 2000
    q = BlockingConcurrentQueue(capacity=64)
    seen = []
    seen_lock = threading.Lock()

    def produce(pid):
        for i in range(per_producer):
            assert q.enqueue((pid, i))

    def consume():
        local = []
        while True:
            ok, v = q.dequeue(timeout=0.5)
            if not ok:
                break
            if v is None:  # sentinel
                break
            local.append(v)
        with seen_lock:
            seen.extend(local)

    consumers = [threading.Thread(target=consume) for _ in range(n_consumers)]
    producers = [threading.Thread(target=produce, args=(p,)) for p in range(n_producers)]
    for t in consumers + producers:
        t.start()
    for t in producers:
        t.join()
    for _ in range(n_consumers):
        q.enqueue(None)
    for t in consumers:
        t.join()

    assert len(seen) == n_producers * per_producer
    assert set(seen) == {(p, i) for p in range(n_producers) for i in range(per_producer)}


def test_blocking_dequeue_timeout():
    q = BlockingConcurrentQueue(capacity=4)
    t0 = time.monotonic()
    ok, _ = q.dequeue(timeout=0.2)
    dt = time.monotonic() - t0
    assert not ok
    assert dt >= 0.15


def test_blocking_enqueue_timeout_when_full():
    q = BlockingConcurrentQueue(capacity=2)
    assert q.enqueue("a")
    assert q.enqueue("b")
    assert not q.enqueue("c", timeout=0.2)


def test_enqueue_unblocks_blocked_dequeue():
    q = BlockingConcurrentQueue(capacity=4)
    result = {}

    def consumer():
        result["v"] = q.dequeue(timeout=5.0)

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.1)
    q.enqueue("wake")
    t.join(timeout=5.0)
    assert result["v"] == (True, "wake")


def test_kill_wakes_blocked_consumers():
    # works on both engines: native kill futex-wakes; the fallback delegates
    # to ConcurrentBlockingQueue.signal_for_kill
    q = BlockingConcurrentQueue(capacity=4)
    errs = []

    def consumer():
        try:
            q.dequeue(timeout=None)
        except QueueKilledError:
            errs.append(True)

    threads = [threading.Thread(target=consumer) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.1)
    q.kill()
    for t in threads:
        t.join(timeout=5.0)
    assert errs == [True, True, True]
    with pytest.raises(QueueKilledError):
        q.enqueue("after-kill")


def test_spinlock_mutual_exclusion():
    lock = Spinlock()
    counter = {"v": 0}

    def worker():
        for _ in range(10000):
            with lock:
                counter["v"] += 1

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counter["v"] == 40000


def test_spinlock_trylock():
    lock = Spinlock()
    assert lock.try_acquire()
    assert not lock.try_acquire()
    lock.release()
    assert lock.try_acquire()
    lock.release()
