"""Tests for the image-record codec, DeviceFeed infeed, and ResNet trainer
(BASELINE config 2's pipeline: RecordIO shard → host parse → async
device staging → jitted data-parallel train step)."""

import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlc_core_tpu.data.device_feed import DeviceFeed
from dmlc_core_tpu.data.image_record import (
    batch_iterator, pack_image_record, unpack_image_record)
from dmlc_core_tpu.io.recordio import RecordIOWriter
from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.models.resnet import RESNET_STAGES, ResNet, ResNetTrainer
from dmlc_core_tpu.parallel.mesh import local_mesh


def _write_rec(path, n, shape=(8, 8, 3), seed=0):
    rng = np.random.default_rng(seed)
    labels = []
    with RecordIOWriter(Stream.create(path, "w")) as w:
        for i in range(n):
            img = rng.integers(0, 256, size=shape, dtype=np.uint8)
            label = i % 4
            labels.append(label)
            w.write_record(pack_image_record(img, label, record_id=i))
    return labels


class TestImageRecord:
    def test_pack_unpack_round_trip(self, rng):
        img = rng.integers(0, 256, size=(12, 10, 3), dtype=np.uint8)
        rec = pack_image_record(img, 7.0, record_id=42)
        out, label, rid = unpack_image_record(rec)
        np.testing.assert_array_equal(out, img)
        assert label == 7.0 and rid == 42

    def test_batch_iterator_shards_cover_all(self, tmp_path):
        path = os.path.join(tmp_path, "img.rec")
        _write_rec(path, 64)
        seen = []
        for part in range(4):
            for images, labels in batch_iterator(path, part, 4, 4, (8, 8, 3)):
                assert images.shape == (4, 8, 8, 3) and labels.shape == (4,)
                seen.extend(labels.tolist())
        assert len(seen) == 64  # full coverage, no overlap
        assert sorted(set(seen)) == [0, 1, 2, 3]

    def test_drop_last_and_partial(self, tmp_path):
        path = os.path.join(tmp_path, "img.rec")
        _write_rec(path, 10)
        full = list(batch_iterator(path, 0, 1, 4, (8, 8, 3), drop_last=True))
        assert len(full) == 2
        both = list(batch_iterator(path, 0, 1, 4, (8, 8, 3), drop_last=False))
        assert len(both) == 3 and both[-1][0].shape[0] == 2


class TestDeviceFeed:
    def test_yields_sharded_arrays_and_rewinds(self):
        mesh = local_mesh()
        sh = NamedSharding(mesh, P("data"))

        def host_iter():
            for i in range(5):
                yield np.full(16, i, np.float32)

        with DeviceFeed(host_iter, sh, depth=2) as feed:
            vals = [float(np.asarray(b)[0]) for b in feed]
            assert vals == [0, 1, 2, 3, 4]
            assert feed.stats.batches == 5
            assert feed.stats.bytes == 5 * 16 * 4
            # second epoch after rewind
            vals2 = [float(np.asarray(b)[0]) for b in feed]
            assert vals2 == vals

    def test_pytree_batches_with_mesh_shorthand(self):
        mesh = local_mesh()

        def host_iter():
            yield (np.zeros((8, 4), np.float32), np.arange(8, dtype=np.int32))

        with DeviceFeed(host_iter, mesh) as feed:
            x, y = next(iter(feed))
            assert x.sharding.spec == P("data", None)
            assert np.asarray(y).tolist() == list(range(8))

    def test_producer_exception_propagates(self):
        mesh = local_mesh()

        def host_iter():
            yield np.zeros(8, np.float32)
            raise ValueError("boom in parser")

        with DeviceFeed(host_iter, mesh) as feed, pytest.raises(ValueError):
            for _ in feed:
                pass


class TestResNet:
    @pytest.mark.slow
    def test_forward_shapes_all_variants_config(self):
        # construct (not run) every variant; run the micro one
        for name, (stages, bottleneck) in RESNET_STAGES.items():
            m = ResNet(stage_sizes=stages, bottleneck=bottleneck, num_classes=10)
            assert m.stage_sizes == stages
        m = ResNet(stage_sizes=(1, 1), bottleneck=False, num_classes=4,
                   num_filters=8)
        x = np.zeros((2, 16, 16, 3), np.uint8)
        variables = m.init(jax.random.key(0), x, train=False)
        logits = m.apply(variables, x, train=False)
        assert logits.shape == (2, 4)
        assert logits.dtype == np.float32

    @pytest.mark.slow
    def test_end_to_end_training_from_recordio(self, tmp_path):
        """Config 2 in miniature: labels are recoverable from the images
        (label encoded in pixel intensity), loss must fall."""
        path = os.path.join(tmp_path, "train.rec")
        rng = np.random.default_rng(3)
        with RecordIOWriter(Stream.create(path, "w")) as w:
            for i in range(128):
                label = i % 4
                img = np.clip(rng.normal(label * 60 + 30, 10, size=(8, 8, 3)),
                              0, 255).astype(np.uint8)
                w.write_record(pack_image_record(img, label))
        tr = ResNetTrainer(variant="resnet-micro", num_classes=4,
                           learning_rate=0.05, mesh=local_mesh())
        tr.init((8, 8, 3))
        first = None
        for _ in range(3):
            stats = tr.fit_from_records(path, batch_size=16,
                                        image_shape=(8, 8, 3))
            if first is None:
                first = stats["last_loss"]
        assert stats["steps"] == 8
        assert stats["records"] == 128
        assert stats["records_per_sec"] > 0
        assert 0.0 <= stats["infeed_stall_fraction"] <= 1.0
        assert stats["last_loss"] < first, (first, stats["last_loss"])

    def test_param_validation(self):
        from dmlc_core_tpu.base.logging import Error

        with pytest.raises(Error):
            ResNetTrainer(variant="resnet9000")
