"""SparseHistGBT: ragged sparse histogram engine.

Oracles: (a) the cut builder and the grouped binning against naive
per-feature loops; (b) the WHOLE first tree (split choice, default
directions, leaf weights) against a brute-force numpy grower that
enumerates every (feature, threshold, direction) — exact comparison is
legitimate because the first boosting round's logistic gradients are
±0.5 / 0.25 (dyadic, exact in f32 under any summation order); (c)
semantic agreement with the DENSE missing-mode engine (absent ≡ NaN) on
densified data; (d) learning + persistence round trips.
"""

import numpy as np
import pytest

from dmlc_core_tpu.models.histgbt_sparse import SparseHistGBT
from dmlc_core_tpu.ops.sparse_hist import (bin_sparse_entries,
                                           build_sparse_cuts, csr_rows)


def _sparse_problem(n=400, F=40, density=0.15, seed=0, signal=3):
    """CSR rows; label = sign of a sparse linear score over the first
    ``signal`` features (present-vs-absent and value both carry
    information — exactly the MNAR structure default directions
    exploit)."""
    rng = np.random.default_rng(seed)
    mask = rng.random((n, F)) < density
    mask[:, :signal] |= rng.random((n, signal)) < 0.3
    vals = rng.normal(size=(n, F)).astype(np.float32)
    score = np.where(mask[:, :signal], vals[:, :signal], -0.4).sum(axis=1)
    y = (score > np.median(score)).astype(np.float32)
    offset = np.concatenate([[0], np.cumsum(mask.sum(axis=1))])
    index = np.nonzero(mask)[1]
    value = vals[mask]
    return offset, index, value, y, mask, vals


class TestSparseCutsAndBins:
    def test_cuts_match_naive_per_feature(self):
        rng = np.random.default_rng(1)
        F, nnz, max_bins = 17, 900, 8
        cols = rng.integers(0, F, nnz)
        cols[cols == 5] = 6                  # leave feature 5 empty
        vals = np.round(rng.normal(size=nnz), 1).astype(np.float32)  # ties
        cuts = build_sparse_cuts(cols, vals, F, max_bins)
        nb = max_bins - 1
        for f in range(F):
            s = np.sort(vals[cols == f])
            m = len(s)
            got = cuts.cut_vals[cuts.cut_ptr[f]:cuts.cut_ptr[f + 1]]
            if m == 0:
                assert len(got) == 0
                assert cuts.bin_ptr[f + 1] - cuts.bin_ptr[f] == 1
                continue
            cand = [s[min(int(np.ceil(k * m / (nb + 1))), m - 1)]
                    for k in range(1, nb + 1)]
            naive = []
            for c in cand:
                if not naive or c > naive[-1]:
                    naive.append(c)
            np.testing.assert_array_equal(got, np.asarray(naive, np.float32))
            assert (np.diff(got) > 0).all()
            assert cuts.bin_ptr[f + 1] - cuts.bin_ptr[f] == len(got) + 1
        assert cuts.total_bins == int(cuts.bin_ptr[-1])

    def test_binning_matches_searchsorted(self):
        rng = np.random.default_rng(2)
        F, nnz = 9, 700
        cols = rng.integers(0, F, nnz)
        vals = np.round(rng.normal(size=nnz), 1).astype(np.float32)
        cuts = build_sparse_cuts(cols, vals, F, 16)
        gb = bin_sparse_entries(cols, vals, cuts)
        for e in rng.integers(0, nnz, 80):
            f = cols[e]
            cf = cuts.cut_vals[cuts.cut_ptr[f]:cuts.cut_ptr[f + 1]]
            local = int(np.searchsorted(cf, vals[e], side="right"))
            assert gb[e] == cuts.bin_ptr[f] + local, (e, f, vals[e])

    def test_csr_rows(self):
        assert csr_rows(np.array([0, 2, 2, 5])).tolist() == [0, 0, 2, 2, 2]


def _brute_first_tree(bins, present, y, widths, *, lam, gamma, mcw,
                      depth, eta, base_score=0.0):
    """Enumerate every (feature, threshold, both directions) per node in
    the engine's scan order; logistic first-round gradients."""
    n, F = bins.shape
    p = 1.0 / (1.0 + np.exp(-base_score))
    g = (p - y).astype(np.float64)
    h = np.full(n, p * (1 - p), np.float64)
    node = np.zeros(n, int)
    levels = []
    for level in range(depth):
        nn = 1 << level
        feat = np.zeros(nn, int)
        thr = np.zeros(nn, int)
        dirv = np.ones(nn, bool)
        for nd in range(nn):
            rows = node == nd
            gt, ht = g[rows].sum(), h[rows].sum()

            def score(G, H):
                return G * G / (H + lam)

            best_gain, best = -np.inf, None
            for f in range(F):
                pr = rows & present[:, f]
                gp, hp = g[pr].sum(), h[pr].sum()
                miss_g, miss_h = gt - gp, ht - hp
                for t in range(widths[f] - 1):
                    lp = pr & (bins[:, f] <= t)
                    gl, hl = g[lp].sum(), h[lp].sum()
                    cands = []
                    for miss_left in (False, True):
                        gL = gl + (miss_g if miss_left else 0.0)
                        hL = hl + (miss_h if miss_left else 0.0)
                        gR, hR = gt - gL, ht - hL
                        if hL >= mcw and hR >= mcw:
                            gn = (score(gL, hL) + score(gR, hR)
                                  - score(gt, ht))
                        else:
                            gn = -np.inf
                        cands.append(gn)
                    gn = max(cands)
                    ml = cands[1] > cands[0]
                    if gn > best_gain:            # strict: first wins
                        best_gain, best = gn, (f, t, ml)
            # XGBoost convention (matching gbt_split.py and the fixed
            # sparse_best_split): gamma gates HALF the score-sum gain
            if 0.5 * best_gain > gamma:
                feat[nd], thr[nd], dirv[nd] = best
            else:
                feat[nd], thr[nd], dirv[nd] = 0, widths[0] - 1, True
        levels.append((feat.copy(), thr.copy(), dirv.copy()))
        nxt = np.empty(n, int)
        for r in range(n):
            f, t, ml = feat[node[r]], thr[node[r]], dirv[node[r]]
            if present[r, f]:
                side = int(bins[r, f] > t)
            else:
                side = 0 if ml else 1
            nxt[r] = 2 * node[r] + side
        node = nxt
    leaf = np.zeros(1 << depth)
    for nd in range(1 << depth):
        rows = node == nd
        leaf[nd] = -g[rows].sum() / (h[rows].sum() + lam) * eta
    return levels, leaf, node


class TestCandidateMerge:
    def test_w1_merge_is_identity_with_build(self):
        rng = np.random.default_rng(3)
        from dmlc_core_tpu.ops.sparse_hist import (
            build_sparse_cuts, merge_sparse_cut_candidates,
            sparse_cut_candidates)
        cols = rng.integers(0, 23, 600)
        vals = np.round(rng.normal(size=600), 1).astype(np.float32)
        a = build_sparse_cuts(cols, vals, 23, 8)
        b = merge_sparse_cut_candidates(
            sparse_cut_candidates(cols, vals, 23, 8)[None])
        np.testing.assert_array_equal(a.cut_vals, b.cut_vals)
        np.testing.assert_array_equal(a.cut_ptr, b.cut_ptr)

    def test_two_shard_merge_approximates_global(self):
        rng = np.random.default_rng(5)
        from dmlc_core_tpu.ops.sparse_hist import (
            build_sparse_cuts, merge_sparse_cut_candidates,
            sparse_cut_candidates)
        F, nnz = 11, 4000
        cols = rng.integers(0, F, nnz)
        cols[cols == 4] = 5               # feature 4 globally empty
        vals = rng.normal(size=nnz).astype(np.float32)
        halves = [slice(0, nnz // 2), slice(nnz // 2, nnz)]
        cands = np.stack([
            sparse_cut_candidates(cols[s], vals[s], F, 16)
            for s in halves])
        merged = merge_sparse_cut_candidates(cands)
        solo = build_sparse_cuts(cols, vals, F, 16)
        assert merged.n_features == F
        assert merged.bin_ptr[5] - merged.bin_ptr[4] == 1   # empty feat
        for f in range(F):
            mg = merged.cut_vals[merged.cut_ptr[f]:merged.cut_ptr[f + 1]]
            sg = solo.cut_vals[solo.cut_ptr[f]:solo.cut_ptr[f + 1]]
            assert (np.diff(mg) > 0).all()
            if len(sg) and len(mg):
                # merged cuts track the global quantile grid closely
                lm = min(len(sg), len(mg))
                assert np.abs(np.interp(
                    np.linspace(0, 1, lm), np.linspace(0, 1, len(mg)),
                    mg) - np.interp(
                    np.linspace(0, 1, lm), np.linspace(0, 1, len(sg)),
                    sg)).max() < 0.35


class TestSparseEngineOracle:
    @pytest.mark.parametrize("depth,mcw,gamma", [(3, 1.0, 0.0),
                                                 (2, 4.0, 0.05)])
    def test_first_tree_matches_brute_force(self, depth, mcw, gamma):
        offset, index, value, y, mask, vals = _sparse_problem(
            n=300, F=14, density=0.25, seed=7)
        kw = dict(n_trees=1, max_depth=depth, n_bins=8, learning_rate=0.7,
                  reg_lambda=1.0, min_child_weight=mcw, gamma=gamma)
        m = SparseHistGBT(**kw)
        m.fit(offset, index, value, y)
        cuts = m.cuts
        widths = np.diff(cuts.bin_ptr).astype(int)
        # densify to LOCAL bins for the brute grower
        n, F = mask.shape
        bins = np.zeros((n, F), int)
        for f in range(F):
            cf = cuts.cut_vals[cuts.cut_ptr[f]:cuts.cut_ptr[f + 1]]
            bins[:, f] = np.searchsorted(cf, vals[:, f], side="right")
        levels, leaf, node = _brute_first_tree(
            bins, mask, y, widths, lam=1.0, gamma=gamma, mcw=mcw,
            depth=depth, eta=0.7)
        tree = m.trees[0]
        for lv, (bf, bt, bd) in enumerate(levels):
            nn = 1 << lv
            np.testing.assert_array_equal(tree["feat"][lv][:nn], bf,
                                          err_msg=f"feat level {lv}")
            np.testing.assert_array_equal(tree["thr"][lv][:nn], bt,
                                          err_msg=f"thr level {lv}")
            np.testing.assert_array_equal(tree["dir"][lv][:nn], bd,
                                          err_msg=f"dir level {lv}")
        np.testing.assert_allclose(tree["leaf"], leaf, rtol=1e-5,
                                   atol=1e-7)

    def test_matches_dense_missing_engine_semantics(self):
        # absent ≡ NaN: the dense missing-mode engine on densified data
        # must agree with the sparse engine on what it LEARNS (cut grids
        # differ — dense sketches all rows with NaN knots, sparse
        # quantiles present values — so trees need not be identical;
        # predictions and accuracy must agree)
        from dmlc_core_tpu.models import HistGBT

        offset, index, value, y, mask, vals = _sparse_problem(
            n=500, F=12, density=0.3, seed=3)
        Xd = np.where(mask, vals, np.nan).astype(np.float32)
        kw = dict(n_trees=12, max_depth=3, n_bins=16, learning_rate=0.4)
        sp = SparseHistGBT(**kw).fit(offset, index, value, y)
        dn = HistGBT(**kw)
        dn.fit(Xd, y)
        ps = sp.predict(offset, index, value)
        pd_ = dn.predict(Xd)
        acc_s = ((ps > 0.5) == y).mean()
        acc_d = ((pd_ > 0.5) == y).mean()
        assert acc_s > 0.9, acc_s
        assert abs(acc_s - acc_d) < 0.06, (acc_s, acc_d)
        # scores correlate strongly: same information, same semantics
        corr = np.corrcoef(ps, pd_)[0, 1]
        assert corr > 0.9, corr


class TestSparseModel:
    def test_learns_and_loss_decreases(self):
        offset, index, value, y, _, _ = _sparse_problem(seed=11)
        m = SparseHistGBT(n_trees=20, max_depth=3, n_bins=16,
                          learning_rate=0.4)
        m.fit(offset, index, value, y)
        p5 = m.predict(offset, index, value, n_trees=5)
        p20 = m.predict(offset, index, value)
        eps = 1e-7

        def logloss(p):
            return float(-np.mean(y * np.log(p + eps)
                                  + (1 - y) * np.log(1 - p + eps)))

        assert logloss(p20) < logloss(p5) < logloss(
            np.full_like(y, 0.5))
        assert ((p20 > 0.5) == y).mean() > 0.93

    def test_high_dimensional_fit(self):
        # F = 20k, density ~0.1% — the dense bin matrix would be
        # 20k x 2000 = 40M cells; the sparse path touches only ~40k
        # entries and its ragged bin space stays data-sized
        rng = np.random.default_rng(5)
        n, F, nnz_per_row = 2000, 20_000, 20
        index = np.concatenate([
            np.concatenate([[0, 1], rng.choice(np.arange(2, F),
                                               nnz_per_row - 2,
                                               replace=False)])
            for _ in range(n)]).astype(np.int64)
        offset = np.arange(0, (n + 1) * nnz_per_row, nnz_per_row)
        value = rng.normal(size=n * nnz_per_row).astype(np.float32)
        v0 = value[offset[:-1]]              # feature 0's value per row
        y = (v0 > 0).astype(np.float32)
        m = SparseHistGBT(n_trees=8, max_depth=3, n_bins=16,
                          learning_rate=0.5)
        m.fit(offset, index, value, y, n_features=F)
        # ragged bins track data content (~2-3 bins per sparse feature:
        # each feature holds only ~2 present values), not F x max_bins
        assert m.cuts.total_bins < 4 * F
        assert m.cuts.total_bins < F * 16 / 4
        acc = ((m.predict(offset, index, value) > 0.5) == y).mean()
        assert acc > 0.95, acc

    def test_regression_objective(self):
        offset, index, value, y, mask, vals = _sparse_problem(seed=19)
        target = np.where(mask[:, 0], vals[:, 0], -1.0).astype(np.float32)
        m = SparseHistGBT(n_trees=25, max_depth=3, n_bins=32,
                          learning_rate=0.3,
                          objective="reg:squarederror")
        m.fit(offset, index, value, target)
        pred = m.predict(offset, index, value)
        rmse = float(np.sqrt(np.mean((pred - target) ** 2)))
        assert rmse < 0.45 * target.std(), rmse

    def test_save_load_roundtrip(self, tmp_path):
        offset, index, value, y, _, _ = _sparse_problem(seed=23)
        m = SparseHistGBT(n_trees=6, max_depth=3, n_bins=16)
        m.fit(offset, index, value, y)
        uri = str(tmp_path / "sparse.bin")
        m.save_model(uri)
        m2 = SparseHistGBT.load_model(uri)
        np.testing.assert_array_equal(
            m.predict(offset, index, value, output_margin=True),
            m2.predict(offset, index, value, output_margin=True))

    def test_unseen_features_at_predict_are_absent(self):
        offset, index, value, y, _, _ = _sparse_problem(seed=29)
        m = SparseHistGBT(n_trees=4, max_depth=2, n_bins=16)
        m.fit(offset, index, value, y)
        base = m.predict(offset, index, value, output_margin=True)
        # append an entry with a feature id beyond the training space
        offset2 = offset.copy()
        offset2[-1] += 1
        # insert at the END of the last row
        index2 = np.concatenate([index, [m.n_features + 7]])
        value2 = np.concatenate([value, [3.3]]).astype(np.float32)
        out = m.predict(offset2, index2, value2, output_margin=True)
        np.testing.assert_array_equal(out, base)

    def test_rejects_unsupported(self):
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error, match="binary:logistic"):
            SparseHistGBT(objective="multi:softmax", num_class=3)
        with pytest.raises(Error, match="monotone"):
            SparseHistGBT(monotone_constraints=[1, 0])

    def test_nan_values_rejected_fit_and_predict(self):
        from dmlc_core_tpu.base.logging import Error
        offset = np.array([0, 2])
        index = np.array([0, 1])
        value = np.array([1.0, np.nan], np.float32)
        with pytest.raises(Error, match="finite"):
            SparseHistGBT(n_trees=1).fit(offset, index, value,
                                         np.zeros(1, np.float32))
        # predict must reject NaN too: it would otherwise silently bin
        # as the feature's largest value instead of routing by the
        # learned missing direction
        o, i, v, y, _, _ = _sparse_problem(seed=31)
        m = SparseHistGBT(n_trees=2, max_depth=2).fit(o, i, v, y)
        bad = v.copy()
        bad[3] = np.nan
        with pytest.raises(Error, match="finite"):
            m.predict(o, i, bad)

    def test_duplicate_row_feature_rejected(self):
        from dmlc_core_tpu.base.logging import Error
        offset = np.array([0, 3])
        index = np.array([2, 2, 5])          # feature 2 twice in row 0
        value = np.array([1.0, 2.0, 3.0], np.float32)
        with pytest.raises(Error, match="duplicate"):
            SparseHistGBT(n_trees=1).fit(offset, index, value,
                                         np.zeros(1, np.float32))

    def test_unsupported_knobs_fail_loudly(self):
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error, match="colsample"):
            SparseHistGBT(colsample_bytree=0.5)
        with pytest.raises(Error, match="subsample"):
            SparseHistGBT(subsample=0.0)

    def test_scale_pos_weight_shifts_predictions(self):
        offset, index, value, y, _, _ = _sparse_problem(seed=37)
        kw = dict(n_trees=10, max_depth=3, n_bins=16, learning_rate=0.3)
        base = SparseHistGBT(**kw).fit(offset, index, value, y)
        up = SparseHistGBT(scale_pos_weight=8.0, **kw).fit(
            offset, index, value, y)
        # up-weighting positives must raise mean predicted probability
        assert (up.predict(offset, index, value).mean()
                > base.predict(offset, index, value).mean() + 0.02)

    def test_split_scan_precision_rare_feature_after_heavy_mass(self):
        # The split scan's per-feature prefixes must NOT ride a global
        # f32 cumsum: with ~1e7 of g/h mass in earlier bins (f32 ulp
        # ~1.0 there), a rare later feature's mass (~tens) would drown
        # in prefix rounding.  The segmented scan keeps per-feature
        # error bounded by the feature's OWN mass.
        import jax.numpy as jnp
        from dmlc_core_tpu.ops.sparse_hist import sparse_best_split
        F_heavy, B = 2000, 8
        TB = F_heavy * B + 4                 # + tiny feature (4 bins)
        rng = np.random.default_rng(0)
        hist = np.zeros((2, 1, TB), np.float32)
        hist[:, 0, :F_heavy * B] = rng.random((2, F_heavy * B)) * 1e4
        tiny = np.array([13.0, 7.0, 29.0, 5.0], np.float32)
        hist[0, 0, F_heavy * B:] = tiny
        hist[1, 0, F_heavy * B:] = tiny / 2
        widths = np.full(F_heavy + 1, B, np.int64)
        widths[-1] = 4
        bin_ptr = np.concatenate([[0], np.cumsum(widths)])
        fob = np.repeat(np.arange(F_heavy + 1, dtype=np.int32), widths)
        last = np.isin(np.arange(TB), bin_ptr[1:] - 1)
        totals = np.asarray(hist.sum(axis=2) * 1.5, np.float32)
        b_max = int(widths.max())
        dense_pos = (fob.astype(np.int64) * b_max
                     + np.arange(TB) - bin_ptr[fob])
        feat, thr, dirv, gain = sparse_best_split(
            jnp.asarray(hist), jnp.asarray(totals),
            jnp.asarray(bin_ptr), jnp.asarray(fob), jnp.asarray(last),
            jnp.asarray(dense_pos), n_dense=(F_heavy + 1) * b_max,
            b_max=b_max, lam=1.0, gamma=0.0, mcw=1.0)
        # reconstruct the tiny feature's left-masses from the same code
        # path via a probe: run the scan on JUST the tiny feature and
        # compare the chosen gain's inputs indirectly — cheapest honest
        # probe: the scan must place the tiny feature's cumulative
        # masses exactly (we recompute the gain for its best threshold
        # in f64 and check the engine found a gain at least that good
        # minus a tiny-mass-scale tolerance)
        g64 = tiny.astype(np.float64)
        h64 = (tiny / 2).astype(np.float64)
        gt, ht = float(totals[0, 0]), float(totals[1, 0])

        def gain64(t, miss_left):
            gl, hl = g64[:t + 1].sum(), h64[:t + 1].sum()
            if miss_left:
                gl += gt - g64.sum()
                hl += ht - h64.sum()
            gr, hr = gt - gl, ht - hl
            if hl < 1.0 or hr < 1.0:
                return -np.inf
            return gl * gl / (hl + 1) + gr * gr / (hr + 1) \
                - gt * gt / (ht + 1)

        best_tiny = max(gain64(t, ml) for t in range(3)
                        for ml in (False, True))
        assert float(gain[0]) >= best_tiny - 1e-3 * abs(best_tiny)

    def test_block_api_and_negative_index(self):
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.data.row_block import RowBlock
        offset, index, value, y, _, _ = _sparse_problem(seed=43)
        blk = RowBlock(offset=offset, label=y, index=index, value=value)
        m = SparseHistGBT(n_trees=4, max_depth=2, n_bins=16)
        m.fit_block(blk)
        np.testing.assert_array_equal(
            m.predict_block(blk, output_margin=True),
            m.predict(offset, index, value, output_margin=True))
        bad = index.copy()
        bad[5] = -1
        with pytest.raises(Error, match="negative"):
            m.predict(offset, bad, value)

    def test_subsample_trains(self):
        offset, index, value, y, _, _ = _sparse_problem(seed=41)
        m = SparseHistGBT(n_trees=15, max_depth=3, n_bins=16,
                          learning_rate=0.4, subsample=0.7)
        m.fit(offset, index, value, y)
        acc = ((m.predict(offset, index, value) > 0.5) == y).mean()
        assert acc > 0.85, acc


class TestGammaParityWithDense:
    """ADVICE r5 medium finding: sparse_best_split used the RAW score
    sum for both the gamma test and the stored gain, while the dense
    chooser (gbt_split.py) and XGBoost use half of it — the same gamma
    was 2x looser in SparseHistGBT and reported gains 2x the dense
    values, behind sklearn wrappers that route by input type.  Both
    engines must agree on gamma semantics and reported gains."""

    @staticmethod
    def _dense_and_sparse(n=400, F=6, seed=11, **kw):
        from dmlc_core_tpu.models import HistGBT

        # fully-present, few distinct integer values per feature: both
        # engines derive the same candidate partitions, so first-tree
        # split gains are directly comparable
        rng = np.random.default_rng(seed)
        vals = rng.integers(0, 4, size=(n, F)).astype(np.float32)
        y = ((vals[:, 0] >= 2) ^ (vals[:, 1] < 1)
             ^ (rng.random(n) < 0.1)).astype(np.float32)
        offset = np.arange(n + 1, dtype=np.int64) * F
        index = np.tile(np.arange(F, dtype=np.int64), n)
        value = vals.reshape(-1).copy()
        params = dict(n_trees=1, max_depth=2, n_bins=8,
                      learning_rate=0.5, reg_lambda=1.0, **kw)
        ms = SparseHistGBT(**params)
        ms.fit(offset, index, value, y)
        md = HistGBT(**params)
        md.fit(vals, y)
        return ms, md

    def test_reported_gains_match_dense(self):
        ms, md = self._dense_and_sparse(gamma=0.0)
        g_sparse = np.asarray(ms.trees[0]["gain"])
        g_dense = np.asarray(md.trees[0]["gain"])
        # root split: identical candidate partitions -> identical best
        # gain under the shared 0.5*score-sum convention (pre-fix the
        # sparse value was exactly 2x)
        np.testing.assert_allclose(g_sparse[0][0], g_dense[0][0],
                                   rtol=1e-4)
        np.testing.assert_allclose(g_sparse.sum(), g_dense.sum(),
                                   rtol=1e-3)
        # and the importance surface built on the gains agrees too
        np.testing.assert_allclose(
            ms.feature_importances("gain"),
            md.feature_importances("gain"), rtol=1e-3, atol=1e-6)

    def test_gamma_acceptance_agrees_with_dense(self):
        ms0, md0 = self._dense_and_sparse(gamma=0.0)
        root_gain = float(np.asarray(md0.trees[0]["gain"])[0][0])
        B = md0.param.n_bins

        def sparse_degenerate(m):
            t = m.trees[0]
            widths = np.diff(m.cuts.bin_ptr).astype(int)
            return (t["feat"][0][0] == 0
                    and t["thr"][0][0] == widths[0] - 1)

        def dense_degenerate(m):
            return np.asarray(m.trees[0]["thr"])[0][0] == B - 1

        # gamma in (reported, 2*reported): the pre-fix sparse engine
        # (raw-gain test) would still split here while dense refuses
        ms_hi, md_hi = self._dense_and_sparse(gamma=1.5 * root_gain)
        assert dense_degenerate(md_hi)
        assert sparse_degenerate(ms_hi), (
            "sparse engine accepted a split dense rejects: gamma "
            "semantics diverged")
        # gamma safely below the gain: both engines must split
        ms_lo, md_lo = self._dense_and_sparse(gamma=0.5 * root_gain)
        assert not dense_degenerate(md_lo)
        assert not sparse_degenerate(ms_lo)
