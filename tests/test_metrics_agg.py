"""Cross-process metrics aggregation (base/metrics_agg) contracts.

The merge is the trust boundary of the fleet observability plane: the
drills assert merged counters equal per-process sums EXACTLY, so the
properties here are stated as equalities, not tolerances — counter-sum
associativity, histogram bucket-merge == observing the union, label-set
collisions resolving per series, and the ``DMLC_METRICS=0`` snapshot
merging as a no-op.  The spool half (write/install/merge_spool) runs
against a real tmp directory.
"""

import json
import os

import pytest

from dmlc_core_tpu.base import metrics as M
from dmlc_core_tpu.base import metrics_agg as A

BUCKETS = (0.1, 1.0, 10.0)


@pytest.fixture(autouse=True)
def _isolation(monkeypatch):
    """Enabled collection, a clean default registry, and no ambient
    spool; the process-wide install singleton is reset afterwards."""
    monkeypatch.delenv("DMLC_METRICS_SPOOL", raising=False)
    M.set_enabled(True)
    M.default_registry().reset()
    yield
    installed = A.installed_spool()
    if installed is not None:
        installed.close()
    A._installed = None
    M.set_enabled(True)
    M.default_registry().reset()


def _snap(fill):
    """Snapshot of a fresh registry after ``fill(registry)`` ran."""
    r = M.MetricsRegistry(namespace="dmlc")
    fill(r)
    return r.snapshot()


def _counter_value(snapshot, name, **labels):
    for s in snapshot["metrics"][name]["series"]:
        if s["labels"] == labels:
            return s["value"]
    return None


class TestCounterMerge:
    def test_sum_is_exact_and_associative(self):
        def fill(v):
            def go(r):
                r.counter("reqs_total", labels=("path",)).inc(v, path="/p")
            return go

        a, b, c = _snap(fill(3)), _snap(fill(5)), _snap(fill(11))
        left = A.merge_snapshots([A.merge_snapshots([a, b]), c])
        right = A.merge_snapshots([a, A.merge_snapshots([b, c])])
        assert _counter_value(left, "dmlc_reqs_total", path="/p") == 19
        assert left["metrics"] == right["metrics"]

    def test_label_collisions_resolve_per_series(self):
        def fill_a(r):
            ctr = r.counter("reqs_total", labels=("path", "code"))
            ctr.inc(2, path="/p", code="200")
            ctr.inc(1, path="/p", code="500")

        def fill_b(r):
            ctr = r.counter("reqs_total", labels=("path", "code"))
            ctr.inc(7, path="/p", code="200")
            ctr.inc(4, path="/q", code="200")

        merged = A.merge_snapshots([_snap(fill_a), _snap(fill_b)])
        assert _counter_value(merged, "dmlc_reqs_total",
                              path="/p", code="200") == 9
        assert _counter_value(merged, "dmlc_reqs_total",
                              path="/p", code="500") == 1
        assert _counter_value(merged, "dmlc_reqs_total",
                              path="/q", code="200") == 4
        assert len(merged["metrics"]["dmlc_reqs_total"]["series"]) == 3

    def test_kind_conflict_raises(self):
        a = _snap(lambda r: r.counter("depth").inc(1))
        b = _snap(lambda r: r.gauge("depth").set(1))
        with pytest.raises(ValueError, match="declared as"):
            A.merge_snapshots([a, b])


class TestGaugeMerge:
    def test_last_write_wins_by_ts(self):
        a = _snap(lambda r: r.gauge("workers").set(3))
        b = _snap(lambda r: r.gauge("workers").set(8))
        # b's snapshot was taken later, so its ts is strictly larger
        merged = A.merge_snapshots([a, b])
        assert merged["metrics"]["dmlc_workers"]["series"][0]["value"] == 8
        # order of the input list must not matter — the ts decides
        merged = A.merge_snapshots([b, a])
        assert merged["metrics"]["dmlc_workers"]["series"][0]["value"] == 8


class TestHistogramMerge:
    def test_bucket_merge_equals_observing_union(self):
        xs = [0.05, 0.5, 0.5, 5.0]
        ys = [0.07, 2.0, 50.0]

        def observing(values):
            def go(r):
                h = r.histogram("wait_seconds", buckets=BUCKETS)
                for v in values:
                    h.observe(v)
            return go

        merged = A.merge_snapshots([_snap(observing(xs)),
                                    _snap(observing(ys))])
        union = _snap(observing(xs + ys))
        got = merged["metrics"]["dmlc_wait_seconds"]["series"][0]
        want = union["metrics"]["dmlc_wait_seconds"]["series"][0]
        assert got["buckets"] == want["buckets"]
        assert got["count"] == want["count"]
        assert got["sum"] == pytest.approx(want["sum"])
        assert got["min"] == want["min"]
        assert got["max"] == want["max"]

    def test_bucket_bounds_mismatch_raises(self):
        a = _snap(lambda r: r.histogram("h", buckets=(1.0,)).observe(0.5))
        b = _snap(lambda r: r.histogram("h", buckets=(2.0,)).observe(0.5))
        with pytest.raises(ValueError, match="bucket bounds"):
            A.merge_snapshots([a, b])

    def test_merge_is_deterministic(self):
        def observing(seed):
            def go(r):
                h = r.histogram("h", buckets=BUCKETS)
                for i in range(200):
                    h.observe((i * seed % 97) / 10.0)
            return go

        snaps = [_snap(observing(3)), _snap(observing(7))]
        once = A.merge_snapshots(snaps)
        twice = A.merge_snapshots(snaps)
        assert once == twice   # reservoir resampling is seeded


class TestDisabledNoOp:
    def test_disabled_process_snapshot_merges_as_noop(self):
        real = _snap(lambda r: r.counter("reqs_total").inc(6))
        M.set_enabled(False)
        empty = M.MetricsRegistry(namespace="dmlc")
        empty.counter("reqs_total").inc(100)     # no-op while disabled
        dark = empty.snapshot()
        M.set_enabled(True)
        merged = A.merge_snapshots([real, dark])
        assert _counter_value(merged, "dmlc_reqs_total") == 6

    def test_install_spool_noop_without_env(self):
        assert A.install_spool("tester", 0) is None
        assert A.installed_spool() is None


class TestSpool:
    def test_write_install_merge_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DMLC_METRICS_SPOOL", str(tmp_path))
        M.default_registry().counter("reqs_total").inc(4)
        writer = A.install_spool("tester", 2)
        assert writer is not None
        assert A.install_spool("other", 9) is writer   # first call wins
        writer.flush()
        name = os.path.basename(writer.path)
        assert name.startswith("tester-2-") and name.endswith(".json")
        merged, nprocs = A.merge_spool(str(tmp_path))
        assert nprocs == 1 and merged["spool_files"] == [name]
        assert _counter_value(merged, "dmlc_reqs_total") == 4
        # the spool instruments itself: at least the initial + explicit
        # flushes are counted, and the counter rides the same snapshot
        assert _counter_value(merged, "dmlc_spool_writes_total",
                              role="tester") >= 2
        writer.close()
        A._installed = None

    def test_merge_spool_skips_foreign_and_trace_files(self, tmp_path):
        A.write_snapshot(str(tmp_path / "w-0-1.json"),
                         _snap(lambda r: r.counter("n_total").inc(1)))
        (tmp_path / "trace-w-0-1.json").write_text(
            json.dumps({"traceEvents": []}))
        (tmp_path / "merged_artifact.json").write_text("[1, 2]")
        (tmp_path / "garbage.json").write_text("{not json")
        merged, nprocs = A.merge_spool(str(tmp_path))
        assert nprocs == 1
        assert _counter_value(merged, "dmlc_n_total") == 1

    def test_disabled_metrics_spools_nothing(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DMLC_METRICS_SPOOL", str(tmp_path))
        M.set_enabled(False)
        writer = A.SpoolWriter(str(tmp_path), "dark", 0, period_s=0)
        writer.start()
        writer.close()
        assert not [n for n in os.listdir(tmp_path)
                    if not n.startswith("trace-")]
