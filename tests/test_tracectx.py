"""Distributed trace context (base/tracectx) + shard merge contracts.

The propagation layer has to be trustworthy at its edges: wire encoding
round-trips, hostile headers degrade to None, the ``DMLC_TRACE=0``
discipline holds (span yields None, no tracer writes), children inherit
their parent's trace id but mint fresh span ids, and the
``DMLC_TRACE_CTX`` env overlay makes a launched process join its
launcher's trace.  The trace_collect half runs against hand-built
shards with known epochs so the cross-clock normalization is asserted
numerically.
"""

import json
import os
import sys
import threading

import pytest

from dmlc_core_tpu.base import tracectx
from dmlc_core_tpu.utils.profiler import (Tracer, global_tracer,
                                          set_tracing, tracing_enabled)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))
import trace_collect  # noqa: E402


@pytest.fixture(autouse=True)
def _trace_isolation(monkeypatch):
    """Tracing off by default, no ambient env context, thread-local
    state cleared, and the global tracer's buffer drained afterwards."""
    monkeypatch.delenv(tracectx.ENV_KEY, raising=False)
    was = tracing_enabled()
    if hasattr(tracectx._tls, "ctx"):
        del tracectx._tls.ctx
    yield
    set_tracing(was)
    if hasattr(tracectx._tls, "ctx"):
        del tracectx._tls.ctx
    global_tracer().clear()


class TestEncoding:
    def test_roundtrip(self):
        ctx = tracectx.TraceContext("ab" * 16, "cd" * 8)
        assert tracectx.decode(ctx.encode()) == ctx

    @pytest.mark.parametrize("bad", [
        "", "garbage", "00-short-cd" * 3,
        "00-" + "g" * 32 + "-" + "c" * 16 + "-01",       # non-hex
        "00-" + "a" * 31 + "-" + "c" * 16 + "-01",       # short trace id
        "00-" + "a" * 32 + "-" + "c" * 15 + "-01",       # short span id
        "00-" + "a" * 32 + "-" + "c" * 16,               # missing flags
    ])
    def test_garbage_decodes_to_none(self, bad):
        assert tracectx.decode(bad) is None

    def test_decode_normalizes_case_and_whitespace(self):
        enc = " 00-" + "AB" * 16 + "-" + "CD" * 8 + "-01 "
        ctx = tracectx.decode(enc)
        assert ctx == tracectx.TraceContext("ab" * 16, "cd" * 8)


class TestDisabledDiscipline:
    def test_span_yields_none_and_writes_nothing(self):
        set_tracing(False)
        before = len(global_tracer().events())
        with tracectx.span("op") as ctx:
            assert ctx is None
        assert tracectx.current() is None
        assert tracectx.current_header() is None
        assert len(global_tracer().events()) == before

    def test_attach_yields_none_when_off(self):
        set_tracing(False)
        enc = tracectx.TraceContext("ab" * 16, "cd" * 8).encode()
        with tracectx.attach(enc) as ctx:
            assert ctx is None


class TestSpanParenting:
    def test_edge_span_mints_fresh_trace(self):
        set_tracing(True)
        with tracectx.span("edge") as ctx:
            assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        assert tracectx.current() is None   # restored after the block

    def test_child_inherits_trace_id_not_span_id(self):
        set_tracing(True)
        with tracectx.span("parent") as parent:
            with tracectx.span("child") as child:
                assert child.trace_id == parent.trace_id
                assert child.span_id != parent.span_id
            assert tracectx.current() == parent

    def test_span_events_carry_trace_span_parent_args(self):
        set_tracing(True)
        with tracectx.span("outer") as outer:
            with tracectx.span("inner"):
                pass
        by_name = {e["name"]: e for e in global_tracer().events()}
        assert by_name["outer"]["args"]["parent"] == ""
        assert by_name["inner"]["args"]["parent"] == outer.span_id
        assert (by_name["inner"]["args"]["trace"]
                == by_name["outer"]["args"]["trace"] == outer.trace_id)

    def test_attach_adopts_and_restores(self):
        set_tracing(True)
        inbound = tracectx.TraceContext("ab" * 16, "cd" * 8)
        with tracectx.attach(inbound.encode()) as got:
            assert got == inbound
            with tracectx.span("handler") as ctx:
                assert ctx.trace_id == inbound.trace_id
        assert tracectx.current() is None

    def test_attach_malformed_changes_nothing(self):
        set_tracing(True)
        with tracectx.span("outer") as outer:
            with tracectx.attach("not-a-context"):
                assert tracectx.current() == outer

    def test_env_overlay_adopted_per_thread(self, monkeypatch):
        set_tracing(True)
        inbound = tracectx.TraceContext("ab" * 16, "cd" * 8)
        monkeypatch.setenv(tracectx.ENV_KEY, inbound.encode())
        seen = {}

        def child():
            seen["ctx"] = tracectx.current()
            with tracectx.span("work") as ctx:
                seen["span"] = ctx

        t = threading.Thread(target=child)
        t.start()
        t.join()
        assert seen["ctx"] == inbound
        assert seen["span"].trace_id == inbound.trace_id


class TestTracerMetadata:
    def test_save_emits_process_metadata_and_epoch(self, tmp_path):
        tracer = Tracer()
        tracer.set_meta(role="replica", rank=3)
        with tracer.scope("op", trace="t" * 32):
            pass
        path = tracer.save(str(tmp_path / "shard.json"))
        doc = json.load(open(path))
        meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
        names = {e["name"] for e in meta}
        assert {"process_name", "thread_name"} <= names
        proc = next(e for e in meta if e["name"] == "process_name")
        assert "replica" in proc["args"]["name"]
        other = doc["otherData"]
        assert other["role"] == "replica" and other["rank"] == 3
        assert other["pid"] == os.getpid()
        assert other["epoch_us"] > 0


def _shard(path, pid, role, epoch_us, events):
    doc = {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": role}},
            *events,
        ],
        "otherData": {"epoch_us": epoch_us, "pid": pid, "role": role,
                      "rank": 0, "dropped_events": 0},
    }
    with open(path, "w") as f:
        json.dump(doc, f)


class TestTraceCollect:
    def test_epoch_normalization_and_summary(self, tmp_path):
        tid = "ab" * 16
        # shard A started 2.5 s (wall) before shard B; both events sit
        # at local ts=100us, so B's must land 2.5e6 us after A's
        _shard(tmp_path / "trace-router-0-11.json", 11, "router",
               1_000_000.0,
               [{"name": "fleet.route", "ph": "X", "ts": 100.0,
                 "dur": 50.0, "pid": 11, "tid": 1,
                 "args": {"trace": tid, "span": "aa" * 8}}])
        _shard(tmp_path / "trace-replica-0-22.json", 22, "replica",
               3_500_000.0,
               [{"name": "http./predict", "ph": "X", "ts": 100.0,
                 "dur": 20.0, "pid": 22, "tid": 1,
                 "args": {"trace": tid, "span": "bb" * 8}}])
        out = tmp_path / "merged.json"
        merged, summary = trace_collect.collect(str(tmp_path), str(out))
        ts = {e["name"]: e["ts"] for e in merged["traceEvents"]
              if e.get("ph") == "X"}
        assert ts["fleet.route"] == 100.0
        assert ts["http./predict"] == 100.0 + 2_500_000.0
        assert summary["processes"] == 2
        assert summary["events"] == 2
        trace = summary["traces"][tid]
        assert trace["pids"] == [11, 22]
        assert trace["roles"] == ["replica", "router"]
        assert set(trace["spans"]) == {"fleet.route", "http./predict"}
        # the written artifact is the same doc, loadable Perfetto JSON
        assert json.load(open(out))["traceEvents"]

    def test_unparseable_shard_skipped(self, tmp_path):
        (tmp_path / "trace-bad-0-1.json").write_text("{torn")
        _shard(tmp_path / "trace-ok-0-2.json", 2, "ok", 0.0, [])
        _, summary = trace_collect.collect(str(tmp_path))
        assert summary["processes"] == 1
