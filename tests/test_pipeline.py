"""Pipeline parallelism tests (parallel/pipeline.py).

Oracle strategy: the pipelined program must match the UNPIPELINED same
math exactly — same loss trajectory, same per-parameter updates — on the
8-device CPU mesh (dp×pp), plus a generic pipeline_apply check against
sequential stage application.  SURVEY.md §2e lists PP absent upstream;
this is the beyond-parity row."""

import pytest
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from dmlc_core_tpu.base.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_core_tpu.parallel.pipeline import PipelineLM, pipeline_apply


def _mesh(dp, pp):
    devs = np.asarray(jax.devices()[: dp * pp]).reshape(dp, pp)
    return Mesh(devs, ("data", "pipe"))


class TestPipelineApply:
    def test_matches_sequential_stages(self, rng):
        """4 affine stages via the schedule == applying them in order."""
        pp, M, mb, d = 4, 3, 2, 8
        mesh = _mesh(1, pp)
        W = rng.normal(size=(pp, d, d)).astype(np.float32) * 0.3
        x = rng.normal(size=(M, mb, d)).astype(np.float32)

        def stage_fn(w, h):
            return jnp.tanh(h @ w[0])

        def run(w_all, xm):
            return pipeline_apply(stage_fn, w_all, xm, "pipe")

        out = jax.jit(shard_map(
            run, mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P(),
            check_vma=False))(jnp.asarray(W), jnp.asarray(x))
        want = x
        for s in range(pp):
            want = np.tanh(want @ W[s])
        np.testing.assert_allclose(np.asarray(out), want, rtol=2e-5,
                                   atol=2e-6)

    def test_gradients_match_sequential(self, rng):
        pp, M, mb, d = 2, 2, 2, 6
        mesh = _mesh(1, pp)
        W = rng.normal(size=(pp, d, d)).astype(np.float32) * 0.3
        x = rng.normal(size=(M, mb, d)).astype(np.float32)

        def stage_fn(w, h):
            return jnp.tanh(h @ w[0])

        def piped_loss(w_all, xm):
            y = pipeline_apply(stage_fn, w_all, xm, "pipe")
            return lax.psum(jnp.sum(y ** 2), "pipe") / pp

        gp = jax.jit(shard_map(
            jax.grad(piped_loss), mesh=mesh, in_specs=(P("pipe"), P()),
            out_specs=P("pipe"), check_vma=False))(jnp.asarray(W),
                                                   jnp.asarray(x))

        def seq_loss(w_all, xm):
            y = xm
            for s in range(pp):
                y = jnp.tanh(y @ w_all[s])
            return jnp.sum(y ** 2)

        gs = jax.grad(seq_loss)(jnp.asarray(W), jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                                   rtol=1e-4, atol=1e-6)


class TestPipelineLM:
    KW = dict(n_layers=4, d_model=32, n_heads=2, d_ff=64,
              vocab_size=64, max_len=16, n_micro=4)

    def _data(self, rng, B=8, S=16, V=64):
        return (rng.integers(0, V, size=(B, S)).astype(np.int32),
                rng.integers(0, V, size=(B, S)).astype(np.int32),
                np.ones((B, S), np.float32))

    @pytest.mark.slow
    def test_matches_unpipelined_exactly(self, rng):
        tokens, labels, mask = self._data(rng)
        m1 = PipelineLM(mesh=_mesh(2, 4), **self.KW)
        m1.init_params(0)
        m0 = PipelineLM(mesh=Mesh(np.asarray(jax.devices()[:1]).reshape(1),
                                  ("data",)), **self.KW)
        m0.init_params(0)
        for _ in range(3):
            l1 = m1.train_step(tokens, labels, mask)
            l0 = m0.train_step(tokens, labels, mask)
            assert abs(l1 - l0) < 1e-4, (l1, l0)
        # per-parameter states stay in lockstep too
        for k in m1.params:
            np.testing.assert_allclose(np.asarray(m1.params[k]),
                                       np.asarray(m0.params[k]),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_learns(self, rng):
        tokens, labels, mask = self._data(rng)
        m = PipelineLM(mesh=_mesh(2, 2), learning_rate=0.05, **self.KW)
        m.init_params(1)
        losses = [m.train_step(tokens, labels, mask) for _ in range(8)]
        assert losses[-1] < losses[0] - 0.1, losses

    @pytest.mark.slow
    def test_save_load_roundtrip_across_pipe_widths(self, rng, tmp_path):
        """A checkpoint written from a pipelined mesh must load onto a
        plain data mesh (pipe-sharded slabs gather on save) and keep the
        exact loss trajectory."""
        tokens, labels, mask = self._data(rng)
        m = PipelineLM(mesh=_mesh(2, 4), **self.KW)
        m.init_params(2)
        m.train_step(tokens, labels, mask)
        uri = str(tmp_path / "plm.ckpt")
        m.save_model(uri)
        m2 = PipelineLM.load_model(
            uri, mesh=Mesh(np.asarray(jax.devices()[:2]).reshape(2),
                           ("data",)))
        l_orig = m.train_step(tokens, labels, mask)
        l_load = m2.train_step(tokens, labels, mask)
        np.testing.assert_allclose(l_load, l_orig, rtol=1e-4)

    @pytest.mark.slow
    def test_fit_chunked_matches_per_step(self, rng):
        """The scan-chunked program (tunnel bench path) must reproduce
        the per-step trajectory exactly on the pipelined mesh."""
        tokens, labels, mask = self._data(rng)
        mesh = _mesh(2, 2)
        m1 = PipelineLM(mesh=mesh, **self.KW)
        m1.init_params(4)
        per_step = [m1.train_step(tokens, labels, mask) for _ in range(4)]
        m2 = PipelineLM(mesh=mesh, **self.KW)
        m2.init_params(4)
        fn = m2._make_multi(4)
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P("data"))
        t = jax.device_put(np.asarray(tokens, np.int32), sh)
        y = jax.device_put(np.asarray(labels, np.int32), sh)
        mk = jax.device_put(np.asarray(mask, np.float32), sh)
        _, losses = fn(m2.params, t, y, mk)
        np.testing.assert_allclose(np.asarray(losses), per_step, rtol=1e-5)
        # public wrapper: bookkeeping + finiteness
        m3 = PipelineLM(mesh=mesh, **self.KW)
        m3.init_params(4)
        loss, secs, chunk_times = m3.fit_chunked(
            tokens, labels, mask, n_steps=4, chunk=2)
        assert np.isfinite(loss) and secs > 0
        assert chunk_times[-1][0] == 4
