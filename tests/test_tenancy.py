"""Multi-tenant serving tier: namespaced registry (per-tenant monotone
versions, isolated rollback, concurrent publish), LRU paging with
bit-identical warm restore, (tenant, version) checkpoint round trips
across every model family, admission policy math, and the Zipf tenant
sampler the tenancy drill drives load with.

Socket-free on purpose — the router/replica integration runs in
scripts/check_tenancy.py under lockcheck/racecheck/leakcheck."""

import threading

import numpy as np
import pytest

from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.serve.fleet.loadgen import sample_tenant, zipf_weights
from dmlc_core_tpu.serve.tenancy import (TenantPolicy, TenantRegistry,
                                         checkpoint_tenant_model,
                                         load_tenant_checkpoint)


def _make_data(n=200, F=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


def _fit_linear(X, y):
    from dmlc_core_tpu.models import GBLinear

    return GBLinear(n_rounds=3).fit(X, y)


@pytest.fixture(scope="module")
def data():
    return _make_data()


class TestTenantRegistry:
    def test_per_tenant_monotone_versions(self, data):
        """Each tenant owns its version counter: publishing under one
        namespace never advances (or constrains) another's."""
        X, y = data
        reg = TenantRegistry(max_batch=8, min_bucket=1)
        m = _fit_linear(X, y)
        assert reg.publish("alpha", m) == 1
        assert reg.publish("alpha", m) == 2
        assert reg.publish("beta", m) == 1          # own counter
        assert reg.publish("beta", m, version=7) == 7
        assert reg.publish("beta", m) == 8
        with pytest.raises(Error):
            reg.publish("beta", m, version=3)       # stale within beta
        assert reg.publish("alpha", m) == 3         # alpha unaffected
        assert reg.versions("alpha") == [1, 2, 3]
        assert reg.versions("beta") == [1, 7, 8]
        with pytest.raises(KeyError):
            reg.current("nobody")

    def test_rollback_is_isolated(self, data):
        """Rolling alpha back to v1 must not move beta's pointer — the
        tenancy contract the fleet rollout leans on."""
        X, y = data
        reg = TenantRegistry(max_batch=8, min_bucket=1)
        m1, m2 = _fit_linear(X, y), _fit_linear(X, 1.0 - y)
        for t in ("alpha", "beta"):
            reg.publish(t, m1)
            reg.publish(t, m2)
        _, rb_before = reg.current("beta")
        beta_before = np.asarray(rb_before.predict(X[:8]))
        reg.activate("alpha", 1)                    # alpha-only rollback
        assert reg.current_version("alpha") == 1
        assert reg.current_version("beta") == 2
        v_a, r_a = reg.current("alpha")
        np.testing.assert_array_equal(r_a.predict(X[:8]),
                                      np.asarray(m1.predict(X[:8])))
        _, r_b = reg.current("beta")
        np.testing.assert_array_equal(np.asarray(r_b.predict(X[:8])),
                                      beta_before)

    def test_concurrent_publish_two_tenants(self, data):
        """Interleaved publishes from two tenants keep both counters
        monotone and both namespaces intact."""
        X, y = data
        reg = TenantRegistry(max_batch=8, min_bucket=1)
        model = _fit_linear(X, y)
        n_each, errs = 8, []

        def worker(tenant):
            try:
                for _ in range(n_each):
                    reg.publish(tenant, model)
            except BaseException as e:  # noqa: BLE001 — surface in main
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in ("alpha", "beta")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs
        for tenant in ("alpha", "beta"):
            assert reg.versions(tenant) == list(range(1, n_each + 1))
            assert reg.current_version(tenant) == n_each

    def test_eviction_and_warm_restore_bit_parity(self, data):
        """Over the residency cap the LRU tenant is paged out; its next
        resolve rebuilds from retained bytes and predicts bit-identically
        to before the eviction."""
        X, y = data
        reg = TenantRegistry(resident_cap=1, max_batch=8, min_bucket=1)
        reg.publish("alpha", _fit_linear(X, y))
        _, r = reg.current("alpha")
        before = np.asarray(r.predict(X[:8]))
        reg.publish("beta", _fit_linear(X, 1.0 - y))   # evicts alpha
        assert reg.resident() == ["beta"]
        assert reg.evictions >= 1
        v, r2 = reg.current("alpha")                   # warm restore
        assert v == 1
        assert reg.restores == 1
        np.testing.assert_array_equal(np.asarray(r2.predict(X[:8])),
                                      before)
        assert reg.resident() == ["alpha"]             # beta paged out
        assert reg.summary()["beta"] == {"version": 1, "resident": False}

    def test_load_rejects_cross_tenant_checkpoint(self, data):
        X, y = data
        reg = TenantRegistry(max_batch=8, min_bucket=1)
        checkpoint_tenant_model("mem:///tenancy/cross", "alpha",
                                _fit_linear(X, y), version=3)
        assert reg.load("alpha", "mem:///tenancy/cross") == 3
        with pytest.raises(Error):                     # wrong namespace
            reg.load("beta", "mem:///tenancy/cross")
        with pytest.raises(Error):                     # absent is loud
            reg.load("alpha", "mem:///tenancy/never-written")


def _fit_histgbt(X, y):
    from dmlc_core_tpu.models import HistGBT

    return HistGBT(n_trees=3, max_depth=3, n_bins=16).fit(X, y)


def _fit_sparse(X, y):
    from dmlc_core_tpu.models import SparseHistGBT

    n, F = X.shape
    offset = np.arange(0, n * F + 1, F, dtype=np.int64)
    index = np.tile(np.arange(F, dtype=np.int64), n)
    m = SparseHistGBT(n_trees=3, max_depth=3, n_bins=16)
    m.fit(offset, index, X.reshape(-1).copy(), y, n_features=F)
    return m


def _fit_fm(X, y):
    from dmlc_core_tpu.models.fm import FM

    return FM(n_factors=4, n_epochs=2, seed=0).fit(X, y)


def _fit_sk(X, y):
    from dmlc_core_tpu.models.sklearn import GBTClassifier

    return GBTClassifier(n_estimators=3, max_depth=3, n_bins=16).fit(X, y)


def _score(model, X):
    """Family-agnostic raw predictions: sparse models score a
    dense-as-present CSR; sklearn wrappers score via the native model
    (their save_model payload IS the inner model)."""
    fn = getattr(model, "_predict_native", None)
    if fn is not None:
        return np.asarray(fn(X))
    if hasattr(model, "fit_block"):                    # SparseHistGBT
        n, F = X.shape
        return np.asarray(model.predict(
            np.arange(0, n * F + 1, F, dtype=np.int64),
            np.tile(np.arange(F, dtype=np.int64), n),
            np.ascontiguousarray(X.reshape(-1), np.float32)))
    return np.asarray(model.predict(X))


class TestTenantCheckpointRoundTrip:
    @pytest.mark.parametrize("fit", [
        _fit_histgbt, _fit_sparse, _fit_linear, _fit_fm, _fit_sk,
    ], ids=["histgbt", "sparse", "gblinear", "fm", "sklearn"])
    def test_bit_parity_per_family(self, fit, data):
        """(tenant, version) checkpoints round-trip every family with
        bit-identical predictions — the guarantee paging leans on."""
        X, y = data
        model = fit(X, y)
        uri = f"mem:///tenancy/rt-{fit.__name__}"
        checkpoint_tenant_model(uri, "alpha", model, version=5)
        tenant, version, again = load_tenant_checkpoint(uri)
        assert (tenant, version) == ("alpha", 5)
        np.testing.assert_array_equal(_score(again, X[:16]),
                                      _score(model, X[:16]))

    def test_absent_checkpoint_sentinel(self):
        assert load_tenant_checkpoint("mem:///tenancy/absent") == \
            ("", 0, None)

    def test_version_zero_rejected(self, data):
        X, y = data
        with pytest.raises(Error):
            checkpoint_tenant_model("mem:///tenancy/v0", "alpha",
                                    _fit_linear(X, y), version=0)


class TestTenantPolicy:
    def test_class_parsing_and_thresholds(self):
        pol = TenantPolicy(classes="gold: vip ; bronze: batch,scrape",
                           default_class="silver", quota=4,
                           max_inflight=40, shed_fraction=0.25,
                           hedge_ms=10)
        assert pol.class_of("vip") == "gold"
        assert pol.class_of("batch") == "bronze"
        assert pol.class_of("anyone-else") == "silver"
        assert pol.shed_threshold("batch") == 10       # 0.25 * 40
        assert pol.shed_threshold("vip") == 40
        assert pol.shed_threshold("anyone-else") == 40
        assert pol.hedges("vip") and not pol.hedges("anyone-else")

    def test_hedging_needs_budget(self):
        pol = TenantPolicy(classes="gold:vip", default_class="silver",
                           quota=0, max_inflight=8, shed_fraction=0.5,
                           hedge_ms=0)
        assert not pol.hedges("vip")                   # hedge_ms == 0

    def test_bad_specs_are_loud(self):
        with pytest.raises(Error):
            TenantPolicy(classes="platinum:x", default_class="silver",
                         quota=0, max_inflight=8, shed_fraction=0.5,
                         hedge_ms=0)
        with pytest.raises(Error):
            TenantPolicy(classes="", default_class="silver", quota=0,
                         max_inflight=8, shed_fraction=1.5, hedge_ms=0)


class TestZipfTenantSampler:
    def test_cumulative_weights(self):
        cum = zipf_weights(4, 1.0)
        assert cum[-1] == pytest.approx(1.0)
        probs = np.diff(np.concatenate([[0.0], cum]))
        assert np.all(probs[:-1] > probs[1:])          # strictly skewed

    def test_hot_head_long_tail(self):
        tenants = [f"t{i}" for i in range(6)]
        cum = zipf_weights(len(tenants), 1.1)
        rng = np.random.default_rng(7)
        draws = [sample_tenant(rng, tenants, cum) for _ in range(2000)]
        counts = [draws.count(t) for t in tenants]
        assert counts[0] == max(counts)                # head is hottest
        assert min(counts) > 0                         # tail still served
