"""racecheck (vector-clock happens-before race detector) contracts.

The dynamic third layer of the concurrency suite (ISSUE 11): a seeded
unlocked write/read pair MUST be reported (with both stacks), and each
edge of the traced-sync vocabulary — lock pairs, Event set→wait,
Thread fork/join, ConcurrentBlockingQueue handoffs — MUST silence the
same access pattern.  False negatives here mean the drills' zero-race
assertions are vacuous; false positives would make them flaky.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from dmlc_core_tpu.base import racecheck
from dmlc_core_tpu.io.concurrency import ConcurrentBlockingQueue


@racecheck.instrument_class
class _Shared:
    """Minimal opt-in class: one `_x` slot in the instance dict."""

    _racecheck_exempt = frozenset({"_exempted"})

    def __init__(self):
        self._lock = threading.Lock()
        self._x = 0
        self._exempted = 0


@pytest.fixture
def rc():
    installed_before = racecheck.installed()
    if not installed_before:
        racecheck.install()
    racecheck.reset()
    yield racecheck
    racecheck.reset()
    if not installed_before:
        racecheck.uninstall()


def _run_threads(*fns):
    threads = [threading.Thread(target=fn) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()


# ---------------------------------------------------------------------------
# the positive case: an unlocked cross-thread pair IS a race
# ---------------------------------------------------------------------------

def test_unlocked_write_read_is_reported_with_both_stacks(rc):
    obj = _Shared()

    def writer():
        obj._x = 1

    def reader():
        time.sleep(0.05)        # a sleep is NOT a happens-before edge
        _ = obj._x

    _run_threads(writer, reader)
    got = rc.races()
    assert got, "seeded race not detected"
    r = got[0]
    assert r["class"] == "_Shared" and r["attr"] == "_x"
    assert r["kind"] in ("write-read", "read-write", "write-write")
    # both halves carry a repo-relative stack naming this test file
    for half in ("prior", "current"):
        assert "test_racecheck.py" in r[half]["stack"]
        assert r[half]["thread"] > 0
    assert r["prior"]["thread"] != r["current"]["thread"]
    with pytest.raises(racecheck.RaceError, match="_Shared._x"):
        rc.check()


def test_exempt_attr_is_not_tracked(rc):
    obj = _Shared()

    def writer():
        obj._exempted = 1

    def reader():
        time.sleep(0.05)
        _ = obj._exempted

    _run_threads(writer, reader)
    assert rc.races() == []


# ---------------------------------------------------------------------------
# each traced-sync edge silences the same pattern
# ---------------------------------------------------------------------------

def test_lock_pair_orders_accesses(rc):
    obj = _Shared()

    def bump():
        for _ in range(50):
            with obj._lock:
                obj._x += 1

    _run_threads(bump, bump)
    assert rc.races() == []
    with obj._lock:
        assert obj._x == 100
    rc.check()      # must not raise


def test_event_set_wait_is_an_hb_edge(rc):
    obj = _Shared()
    ready = threading.Event()

    def writer():
        obj._x = 7
        ready.set()             # publishes the writer's clock

    def reader():
        assert ready.wait(timeout=30)
        assert obj._x == 7      # joined the clock: ordered, no race

    _run_threads(writer, reader)
    assert rc.races() == []


def test_thread_fork_and_join_edges(rc):
    obj = _Shared()
    obj._x = 10                 # parent write BEFORE start: fork edge

    def child():
        assert obj._x == 10
        obj._x = 11

    t = threading.Thread(target=child)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive()
    assert obj._x == 11         # parent read AFTER join: join edge
    assert rc.races() == []


def test_queue_handoff_orders_producer_and_consumer(rc):
    obj = _Shared()
    q: ConcurrentBlockingQueue[int] = ConcurrentBlockingQueue(max_size=4)
    got = []

    def producer():
        for i in range(100):
            obj._x = i          # write, then hand off through the queue
            q.push(i)

    def consumer():
        for _ in range(100):
            got.append(q.pop(timeout=30))
            _ = obj._x          # ordered by the queue's monitor

    _run_threads(producer, consumer)
    assert got == list(range(100))
    assert [r for r in rc.races() if r["attr"] == "_x"] == []


# ---------------------------------------------------------------------------
# reporting surface
# ---------------------------------------------------------------------------

def test_write_report_schema(rc, tmp_path):
    obj = _Shared()

    def writer():
        obj._x = 1

    def reader():
        time.sleep(0.05)
        _ = obj._x

    _run_threads(writer, reader)
    path = tmp_path / "racecheck.json"
    report = rc.write_report(str(path))
    assert report["enabled"] is True
    assert report["tracked_accesses"] > 0
    assert "_Shared" in report["instrumented_classes"]
    assert report["races"]
    on_disk = json.loads(path.read_text())
    assert on_disk["races"] == report["races"]


def test_reset_clears_history(rc):
    obj = _Shared()

    def writer():
        obj._x = 1

    def reader():
        time.sleep(0.05)
        _ = obj._x

    _run_threads(writer, reader)
    assert rc.races()
    rc.reset()
    assert rc.races() == []
    rc.check()      # clean slate


def test_env_gate(monkeypatch):
    monkeypatch.setenv("DMLC_RACECHECK", "1")
    assert racecheck.env_enabled()
    monkeypatch.setenv("DMLC_RACECHECK", "0")
    assert not racecheck.env_enabled()


def test_disabled_by_default_costs_nothing():
    """Without install(), instrumented classes run on the ORIGINAL
    attribute protocol (no wrappers applied)."""
    if racecheck.installed():
        pytest.skip("racecheck force-installed for this session")
    obj = _Shared()
    obj._x = 5
    assert obj._x == 5
    assert type(obj).__getattribute__ is object.__getattribute__
