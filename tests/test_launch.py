"""Tests for the multi-host launch subsystem (``dmlc_core_tpu.launch``).

Transports are exercised with real local subprocesses (LocalTransport,
FakeTransport), a stub ``ssh`` binary (SSHTransport — the remote command
line is what matters, not a network), and dry-run manifests
(K8sTransport).  JobSet supervision — respawn budgets, targeted kill,
scale-out, teardown — runs against those same transports, so everything
here proves the exact code paths ``scripts/check_launch.py`` drills.
"""

import os
import signal
import stat
import sys
import time

import pytest

from dmlc_core_tpu.base import faultinject
from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.launch import (FakeTransport, JobSet, K8sTransport,
                                  LaunchTimeout, LocalTransport,
                                  SSHTransport, TransportError,
                                  jobset_from_opts, transport_from_opts)
from dmlc_core_tpu.launch.transport import WorkerHandle
from dmlc_core_tpu.tracker.opts import get_opts

PY = sys.executable
ENVS = {"DMLC_TRACKER_URI": "10.0.0.1", "DMLC_TRACKER_PORT": "9091",
        "DMLC_NUM_WORKER": "4"}


def _wait_code(transport, handle, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        code = transport.poll(handle)
        if code is not None:
            return code
        time.sleep(0.02)
    raise AssertionError(f"worker {handle} never exited")


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------

class TestLocalTransport:
    def test_spawn_env_overlay_and_log_tail(self, tmp_path):
        tr = LocalTransport(log_dir=str(tmp_path))
        h = tr.spawn([PY, "-c", "import os; print('X is', os.environ['X'])"],
                     {"X": "42"}, "localhost", label="w0")
        assert _wait_code(tr, h) == 0
        assert tr.env_of(h) == {"X": "42"}          # overlay, not os.environ
        assert "X is 42" in tr.log_tail(h)
        assert h.log_path == str(tmp_path / "w0.log")

    def test_signal_terminates(self, tmp_path):
        tr = LocalTransport(log_dir=str(tmp_path))
        h = tr.spawn([PY, "-c", "import time; time.sleep(30)"], {},
                     "localhost")
        assert tr.poll(h) is None
        tr.signal(h, signal.SIGTERM)
        assert _wait_code(tr, h) == -signal.SIGTERM

    def test_pdeathsig_kills_orphans(self, tmp_path):
        """The fire-and-forget fix: a worker whose spawning process is
        SIGKILLed must die too (PR_SET_PDEATHSIG), not leak."""
        if not sys.platform.startswith("linux"):
            pytest.skip("pdeathsig is Linux-only")
        pidfile = tmp_path / "worker.pid"
        # middle process spawns a sleeper through LocalTransport, writes
        # its pid, then blocks forever; we SIGKILL the middle process and
        # the sleeper must disappear with it
        middle = tmp_path / "middle.py"
        middle.write_text(
            "import sys, time\n"
            f"sys.path.insert(0, {os.getcwd()!r})\n"
            "from dmlc_core_tpu.launch import LocalTransport\n"
            f"tr = LocalTransport(log_dir={str(tmp_path)!r})\n"
            f"h = tr.spawn([{PY!r}, '-c', 'import time; time.sleep(60)'],\n"
            "              {}, 'localhost')\n"
            f"open({str(pidfile)!r}, 'w').write(str(h.pid))\n"
            "time.sleep(60)\n")
        import subprocess
        mid = subprocess.Popen([PY, str(middle)])
        deadline = time.time() + 15
        while not pidfile.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert pidfile.exists(), "middle process never spawned the worker"
        worker_pid = int(pidfile.read_text())
        os.kill(worker_pid, 0)                      # alive
        mid.kill()
        mid.wait(timeout=10)
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                os.kill(worker_pid, 0)
            except ProcessLookupError:
                return                              # orphan died: fixed
            time.sleep(0.05)
        os.kill(worker_pid, signal.SIGKILL)
        raise AssertionError("worker leaked past its dead parent")


class TestSSHTransport:
    def test_build_argv_shape(self):
        tr = SSHTransport(["h0", "h1"], cwd="/work dir", ssh_binary="ssh")
        argv = tr.build_argv("h1", ["python", "t.py", "--a b"],
                            {"DMLC_TASK_ID": "1", "V": "x y"})
        assert argv[0] == "ssh" and argv[1] == "-tt"
        assert argv[-2] == "h1"
        remote = argv[-1]
        assert remote.startswith("cd '/work dir' && env ")
        assert "DMLC_TASK_ID=1" in remote and "V='x y'" in remote
        assert remote.endswith("python t.py '--a b'")
        assert "BatchMode=yes" in argv

    def test_stub_ssh_runs_remote_command(self, tmp_path):
        """A stub ``ssh`` that execs its last argument locally proves the
        whole spawn path (env overlay travels inside the command line)."""
        stub = tmp_path / "ssh"
        stub.write_text('#!/bin/bash\nexec bash -c "${@: -1}"\n')
        stub.chmod(stub.stat().st_mode | stat.S_IXUSR)
        out = tmp_path / "out.txt"
        tr = SSHTransport(["hostA"], cwd=str(tmp_path),
                          ssh_binary=str(stub), log_dir=str(tmp_path))
        h = tr.spawn([PY, "-c",
                      f"import os; open({str(out)!r}, 'w')"
                      ".write(os.environ['X'] + ' ' + os.getcwd())"],
                     {"X": "7"}, "hostA", label="r0")
        assert _wait_code(tr, h) == 0
        val, cwd = out.read_text().split(" ", 1)
        assert val == "7"
        assert os.path.realpath(cwd) == os.path.realpath(str(tmp_path))

    def _handle(self, tmp_path, host, text):
        log = tmp_path / f"{host}.log"
        log.write_text(text)
        return WorkerHandle(host, "w", {}, log_path=str(log))

    def test_classify_connect_error_255_is_host_death(self, tmp_path):
        tr = SSHTransport(["h0", "h1"])
        h = self._handle(tmp_path, "h0",
                         "ssh: connect to host h0 port 22: "
                         "Connection refused\r\n")
        assert tr.classify_exit(h, 255) == "host_death"
        assert not tr.host_alive("h0") and tr.down_hosts() == ["h0"]
        # once marked dead, ANY exit on that host classifies host_death
        h2 = self._handle(tmp_path, "h0", "Traceback: boom\n")
        assert tr.classify_exit(h2, 1) == "host_death"
        tr.restore_host("h0")
        assert tr.host_alive("h0") and tr.down_hosts() == []

    def test_classify_silent_255_is_host_death(self, tmp_path):
        # connect died before the remote shell spoke: no output at all
        tr = SSHTransport(["h0"])
        h = self._handle(tmp_path, "h0", "")
        assert tr.classify_exit(h, 255) == "host_death"
        assert tr.down_hosts() == ["h0"]

    def test_classify_remote_255_with_output_is_crash(self, tmp_path):
        # the remote COMMAND exited 255 (it printed real output): the
        # host is fine and must stay in the placement pool
        tr = SSHTransport(["h0"])
        h = self._handle(tmp_path, "h0", "remote job: exploding now\n")
        assert tr.classify_exit(h, 255) == "crash"
        assert tr.host_alive("h0") and tr.down_hosts() == []

    def test_classify_ordinary_exit_is_crash(self, tmp_path):
        tr = SSHTransport(["h0"])
        h = self._handle(tmp_path, "h0",
                         "ssh: connect to host h0: Connection refused\n")
        # non-255 exits never consult the log: ssh itself succeeded
        assert tr.classify_exit(h, 1) == "crash"
        assert tr.host_alive("h0")


class TestFakeTransport:
    def test_fail_host_kills_and_refuses(self, tmp_path):
        tr = FakeTransport(hosts=["h0", "h1"], log_dir=str(tmp_path))
        h = tr.spawn([PY, "-c", "import time; time.sleep(30)"], {}, "h0")
        tr.fail_host("h0")
        assert _wait_code(tr, h) == -signal.SIGKILL
        assert not tr.host_alive("h0") and tr.down_hosts() == ["h0"]
        with pytest.raises(TransportError, match="down"):
            tr.spawn([PY, "-c", "pass"], {}, "h0")
        tr.restore_host("h0")
        assert tr.host_alive("h0")

    def test_injected_spawn_error(self, tmp_path):
        tr = FakeTransport(log_dir=str(tmp_path))
        with faultinject.inject("launch_spawn:error:n=1"):
            with pytest.raises(TransportError, match="injected spawn"):
                tr.spawn([PY, "-c", "pass"], {}, "h0")
            h = tr.spawn([PY, "-c", "pass"], {}, "h0")   # n=1: once only
        assert _wait_code(tr, h) == 0

    def test_injected_host_kill_on_tick(self, tmp_path):
        tr = FakeTransport(hosts=["h0", "h1"], log_dir=str(tmp_path))
        h = tr.spawn([PY, "-c", "import time; time.sleep(30)"], {}, "h1")
        with faultinject.inject("launch_host:kill=h1:n=1"):
            tr.tick()
        assert _wait_code(tr, h) == -signal.SIGKILL
        assert tr.down_hosts() == ["h1"]

    def test_preempt_wave_downs_fraction_at_once(self, tmp_path):
        tr = FakeTransport(hosts=["h0", "h1", "h2", "h3", "h4", "h5"],
                           log_dir=str(tmp_path))
        h = tr.spawn([PY, "-c", "import time; time.sleep(30)"], {}, "h0")
        downed = tr.preempt_wave(0.3)
        assert downed == ["h0", "h1"]        # ceil(0.3 * 6) = 2, in order
        assert tr.down_hosts() == ["h0", "h1"]
        assert _wait_code(tr, h) == -signal.SIGKILL
        with pytest.raises(TransportError, match="down"):
            tr.spawn([PY, "-c", "pass"], {}, "h1")
        # a second wave preempts from the SURVIVORS only
        assert tr.preempt_wave(0.3) == ["h2", "h3"]
        for host in ("h0", "h1", "h2", "h3"):
            tr.restore_host(host)
        assert tr.down_hosts() == []

    def test_injected_wave_on_tick(self, tmp_path):
        tr = FakeTransport(hosts=["h0", "h1", "h2", "h3"],
                           log_dir=str(tmp_path))
        h = tr.spawn([PY, "-c", "import time; time.sleep(30)"], {}, "h0")
        with faultinject.inject("launch_host:wave=0.5:n=1"):
            tr.tick()
        # wave downs ceil(0.5*4)=2 alive hosts in host-list order
        assert tr.down_hosts() == ["h0", "h1"]
        assert _wait_code(tr, h) == -signal.SIGKILL


class TestK8sTransport:
    def test_manifest_snapshot(self):
        tr = K8sTransport("img:1", jobname="My Job", dry_run=True,
                          worker_cores=2, worker_memory_mb=512)
        m = tr.render(["python", "t.py"], {"DMLC_TASK_ID": "0"}, "J-r0-a0")
        assert m["kind"] == "Job"
        assert m["metadata"]["name"] == "my-job-j-r0-a0"   # RFC-1123
        spec = m["spec"]
        assert spec["completions"] == 1 and spec["parallelism"] == 1
        # the JobSet is the one restart authority
        assert spec["backoffLimit"] == 0
        c = spec["template"]["spec"]["containers"][0]
        assert c["image"] == "img:1" and c["command"] == ["python", "t.py"]
        assert {"name": "DMLC_TASK_ID", "value": "0"} in c["env"]
        assert c["resources"]["requests"]["memory"] == "512Mi"

    def test_dry_run_lifecycle(self):
        tr = K8sTransport("img:1", dry_run=True, slots=3)
        assert tr.hosts() == ["k8s"] * 3
        h = tr.spawn(["python", "t.py"], {}, "k8s", label="r0")
        assert tr.poll(h) == 0 and len(tr.manifests) == 1

    def test_dry_run_signal_records_code(self):
        tr = K8sTransport("img:1", dry_run=True)
        h = tr.spawn(["x"], {}, "k8s")
        h.extra.pop("exit_code")        # pretend the job is still running
        tr.signal(h, signal.SIGTERM)
        assert tr.poll(h) == 128 + signal.SIGTERM


# ---------------------------------------------------------------------------
# JobSet supervision
# ---------------------------------------------------------------------------

class TestJobSet:
    def test_worker_env_overlay(self):
        js = JobSet(["x"], 4, envs=ENVS, name="j")
        env = js.worker_env(2, attempt=1)
        assert env == {**ENVS, "DMLC_TASK_ID": "2", "DMLC_ROLE": "worker",
                       "DMLC_NUM_ATTEMPT": "1"}
        js2 = JobSet(["x"], 3, env_for=lambda r, a: {"EXTRA": f"{r}.{a}"})
        env2 = js2.worker_env(1)
        assert env2["DMLC_NUM_WORKER"] == "3" and env2["EXTRA"] == "1.0"

    def test_run_happy_path(self, tmp_path):
        js = JobSet([PY, "-c", "import os; exit(int(os.environ"
                     "['DMLC_TASK_ID']) > 2)"], 3,
                    transport=LocalTransport(log_dir=str(tmp_path)),
                    monitor_s=0.05)
        assert js.run(timeout=30) == [0, 0, 0]
        kinds = [e["event"] for e in js.events()]
        assert kinds.count("spawn") == 3 and kinds[-1] == "teardown"
        st = js.stats()
        assert st["backend"] == "local" and st["respawns"] == 0
        assert st["spawns"] == 3 and st["spawn_ms_p95"] > 0

    def test_respawn_then_success(self, tmp_path):
        prog = ("import os, sys; "
                "sys.exit(0 if int(os.environ['DMLC_NUM_ATTEMPT']) >= 1 "
                "else 3)")
        js = JobSet([PY, "-c", prog], 2,
                    transport=LocalTransport(log_dir=str(tmp_path)),
                    restart_limit=2, monitor_s=0.05)
        assert js.run(timeout=30) == [0, 0]
        assert js.respawns() == 2

    def test_restart_budget_gives_up(self, tmp_path):
        js = JobSet([PY, "-c", "raise SystemExit(5)"], 1,
                    transport=LocalTransport(log_dir=str(tmp_path)),
                    restart_limit=1, monitor_s=0.05)
        assert js.run(timeout=30) == [5]
        kinds = [e["event"] for e in js.events()]
        assert "giveup" in kinds and js.respawns() == 1

    def test_targeted_kill_no_respawn(self, tmp_path):
        js = JobSet([PY, "-c", "import time; time.sleep(30)"], 2,
                    transport=LocalTransport(log_dir=str(tmp_path)),
                    restart_limit=3, monitor_s=0.05)
        js.launch()
        try:
            js.kill(1)                          # intentional stop
            deadline = time.time() + 10
            while js.alive_count() > 1 and time.time() < deadline:
                time.sleep(0.05)
            time.sleep(0.3)                     # would-be respawn window
            assert js.respawns() == 0
            assert js.alive_count() == 1
        finally:
            js.shutdown()

    def test_targeted_kill_with_respawn(self, tmp_path):
        js = JobSet([PY, "-c", "import time; time.sleep(30)"], 1,
                    transport=LocalTransport(log_dir=str(tmp_path)),
                    restart_limit=3, monitor_s=0.05)
        js.launch()
        try:
            first = js.rank_host(0)
            js.kill(0, sig=signal.SIGKILL, respawn=True)
            deadline = time.time() + 15
            while js.respawns() == 0 and time.time() < deadline:
                time.sleep(0.05)
            assert js.respawns() == 1 and js.rank_host(0) == first
        finally:
            js.shutdown()

    def test_add_rank_scale_out(self, tmp_path):
        js = JobSet([PY, "-c", "import time; time.sleep(30)"], 1,
                    transport=LocalTransport(log_dir=str(tmp_path)),
                    monitor_s=0.05)
        js.launch()
        try:
            assert js.add_rank() == 1
            assert js.add_rank() == 2
            deadline = time.time() + 10
            while js.alive_count() < 3 and time.time() < deadline:
                time.sleep(0.05)
            assert js.alive_count() == 3
        finally:
            js.shutdown()
        assert js.stats()["ranks"][2]["done"]

    def test_wait_timeout(self, tmp_path):
        js = JobSet([PY, "-c", "import time; time.sleep(30)"], 1,
                    transport=LocalTransport(log_dir=str(tmp_path)),
                    monitor_s=0.05)
        js.launch()
        try:
            with pytest.raises(LaunchTimeout):
                js.wait(timeout=0.3)
        finally:
            js.shutdown()

    def test_host_death_respawns_on_survivor(self, tmp_path):
        tr = FakeTransport(hosts=["h0", "h1", "h2"], log_dir=str(tmp_path))
        with faultinject.inject("launch_host:kill=h1:after=3:n=1"):
            js = JobSet([PY, "-c", "import time; time.sleep(0.6)"], 4,
                        transport=tr, restart_limit=2, monitor_s=0.05)
            codes = js.run(timeout=60)
        assert codes == [0, 0, 0, 0]
        assert js.respawns() >= 1 and tr.down_hosts() == ["h1"]
        # rank 1 was placed on h1; its replacement must be elsewhere
        assert js.stats()["ranks"][1]["host"] in ("h0", "h2")

    def test_spawn_error_consumes_budget_then_recovers(self, tmp_path):
        tr = FakeTransport(hosts=["a", "b"], log_dir=str(tmp_path))
        with faultinject.inject("launch_spawn:error:n=1"):
            js = JobSet([PY, "-c", "pass"], 2, transport=tr,
                        restart_limit=2, monitor_s=0.05)
            codes = js.run(timeout=30)
        assert codes == [0, 0]
        kinds = [e["event"] for e in js.events()]
        assert "spawn_error" in kinds and "respawn" in kinds

    def test_host_death_spares_rank_crash_budget(self, tmp_path):
        """Cause-fair budgets: a host death charges the HOST, not the
        rank — a rank chased off two dying hosts still has its full
        crash budget left (the prodsim spot-preemption contract)."""
        tr = FakeTransport(hosts=["h0", "h1", "h2"], log_dir=str(tmp_path))
        js = JobSet([PY, "-c", "import time; time.sleep(30)"], 1,
                    transport=tr, restart_limit=1, monitor_s=0.05)
        js.launch()
        try:
            for _ in range(2):              # two successive host deaths
                host = js.rank_host(0)
                n = js.respawns()
                tr.fail_host(host)
                deadline = time.time() + 15
                while js.respawns() == n and time.time() < deadline:
                    time.sleep(0.05)
                assert js.respawns() == n + 1
            st = js.stats()
            assert st["respawns_by_cause"]["host_death"] == 2
            assert sum(st["host_faults"].values()) == 2
            assert st["ranks"][0]["crashes"] == 0   # budget untouched
            # full crash budget intact: a real SIGKILL still respawns
            # (restart_limit=1) instead of giving up
            n = js.respawns()
            js.kill(0, sig=signal.SIGKILL, respawn=True)
            deadline = time.time() + 15
            while js.respawns() == n and time.time() < deadline:
                time.sleep(0.05)
            st = js.stats()
            assert st["respawns_by_cause"]["crash"] == 1
            assert st["ranks"][0]["crashes"] == 1
            events = js.events()
            assert "giveup" not in [e["event"] for e in events]
            causes = [e.get("cause") for e in events
                      if e["event"] == "exit"]
            assert causes.count("host_death") == 2
            assert causes.count("crash") == 1
        finally:
            js.shutdown()


# ---------------------------------------------------------------------------
# slot-aware placement (bin-packing over the slot-expanded host file)
# ---------------------------------------------------------------------------

class TestSlotAwarePlacement:
    """``JobSet._place`` packs by FREE slots — declared slots from the
    slot-expanded host file minus ranks already resident — instead of the
    old ``rank % len(hosts)`` round-robin that ignored both."""

    @staticmethod
    def _occupy(js, rank, host):
        from dmlc_core_tpu.launch.jobset import _Rank
        from dmlc_core_tpu.launch.transport import WorkerHandle

        st = _Rank(rank)
        st.handle = WorkerHandle(host, f"r{rank}", {})
        js._ranks[rank] = st

    def test_slot_counts_beat_round_robin(self, tmp_path):
        # "b" declares 3 slots, "a" one.  Round-robin would put rank 0
        # on "a"; bin-packing puts it on the host with capacity.
        tr = FakeTransport(hosts=["a", "b", "b", "b"], log_dir=str(tmp_path))
        js = JobSet([PY, "-c", "pass"], 2, transport=tr, monitor_s=0.05)
        assert js._place(0) == "b"
        self._occupy(js, 0, "b")
        assert js._place(1) == "b"          # b still has 2 free vs a's 1

    def test_occupancy_spills_to_free_host(self, tmp_path):
        tr = FakeTransport(hosts=["a", "a", "b"], log_dir=str(tmp_path))
        js = JobSet([PY, "-c", "pass"], 3, transport=tr, monitor_s=0.05)
        self._occupy(js, 0, "a")
        self._occupy(js, 1, "a")            # a's two slots saturated
        assert js._place(2) == "b"
        # a respawn doesn't count its own old placement as load: with
        # rank 1 excluded a is back to one free slot and wins the tie
        # on host-file order (blind counting would send it to b)
        assert js._place(1) == "a"

    def test_dead_hosts_excluded(self, tmp_path):
        tr = FakeTransport(hosts=["a", "b", "b", "b"], log_dir=str(tmp_path))
        js = JobSet([PY, "-c", "pass"], 1, transport=tr, monitor_s=0.05)
        tr.fail_host("b")
        assert js._place(0) == "a"
        tr.fail_host("a")
        with pytest.raises(TransportError):
            js._place(0)

    def test_live_spawns_pack_by_slots(self, tmp_path):
        tr = FakeTransport(hosts=["a", "b", "b", "b"], log_dir=str(tmp_path))
        js = JobSet([PY, "-c", "import time; time.sleep(5)"], 4,
                    transport=tr, monitor_s=0.05)
        js.launch()
        try:
            hosts = sorted(js.rank_host(r) for r in range(4))
            assert hosts.count("b") == 3 and hosts.count("a") == 1
        finally:
            js.shutdown()


# ---------------------------------------------------------------------------
# dmlc-submit options → JobSet configurations (golden per backend)
# ---------------------------------------------------------------------------

class TestSubmitConfigs:
    def test_local_golden_env(self):
        opts, cmd = get_opts(["--cluster", "local", "-n", "2", "--",
                              "python", "t.py"])
        js = jobset_from_opts(opts, cmd, ENVS)
        assert js.transport.name == "local"
        assert js.worker_env(0) == {**ENVS, "DMLC_TASK_ID": "0",
                                    "DMLC_ROLE": "worker",
                                    "DMLC_NUM_ATTEMPT": "0"}

    def test_ssh_golden_env_and_slots(self, tmp_path):
        hf = tmp_path / "hosts"
        hf.write_text("# fleet\nh0:2\nh1\n")
        opts, cmd = get_opts(["--cluster", "ssh", "-n", "3",
                              "--host-file", str(hf), "--", "python", "t.py"])
        js = jobset_from_opts(opts, cmd, ENVS)
        assert js.transport.name == "ssh"
        assert js.transport.hosts() == ["h0", "h0", "h1"]
        assert js.worker_env(1) == {**ENVS, "DMLC_TASK_ID": "1",
                                    "DMLC_ROLE": "worker",
                                    "DMLC_NUM_ATTEMPT": "0"}

    def test_ssh_requires_host_file(self):
        opts, cmd = get_opts(["--cluster", "ssh", "-n", "1", "--", "x"])
        with pytest.raises(Error, match="host-file"):
            transport_from_opts(opts)

    def test_kubernetes_golden_env_and_manifest(self):
        opts, cmd = get_opts(["--cluster", "kubernetes", "-n", "2",
                              "--image", "img:1", "--jobname", "train",
                              "--worker-cores", "4", "--worker-memory",
                              "2048", "--max-attempts", "2", "--dry-run",
                              "--", "python", "t.py"])
        js = jobset_from_opts(opts, cmd, ENVS,
                              extra_env={"JAX_PLATFORMS": "tpu"})
        tr = js.transport
        assert tr.name == "k8s" and tr.dry_run
        env0 = js.worker_env(0)
        assert env0 == {**ENVS, "JAX_PLATFORMS": "tpu",
                        "DMLC_TASK_ID": "0", "DMLC_ROLE": "worker",
                        "DMLC_NUM_ATTEMPT": "0"}
        m = tr.render(cmd, env0, "train-r0-a0")
        assert m["metadata"]["name"] == "train-train-r0-a0"
        assert m["spec"]["backoffLimit"] == 0
        c = m["spec"]["template"]["spec"]["containers"][0]
        assert c["resources"]["requests"]["cpu"] == "4"
        # --max-attempts 2 → 1 JobSet respawn (attempt 0 is the launch)
        assert js._restart_limit == 1

    def test_unsupervised_cluster_rejected(self):
        opts, _ = get_opts(["--cluster", "slurm", "-n", "1", "--", "x"])
        with pytest.raises(ValueError, match="not JobSet-supervised"):
            transport_from_opts(opts)
