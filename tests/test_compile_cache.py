"""Cold-start pipeline tests: persistent compile cache wiring,
overlapped warmup correctness, streamed ingest parity, serve pre-warm.

The correctness bar everywhere is BIT-identity: the overlap/streaming
machinery is an optimization layered on the inline jit path, so any
divergence in trees, margins or eval curves is a bug, not noise.
"""

import os

import numpy as np
import pytest

import jax

from dmlc_core_tpu.base import compile_cache as cc
from dmlc_core_tpu.base import metrics as base_metrics
from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.models.histgbt import (_AOT_EXEC_CACHE,
                                          _ROUND_FN_CACHE,
                                          _rounds_schedule)


def _tiny_fit(n_trees=2, depth=2, rows=160, feats=4, seed=0, **kw):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, feats)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] > 0).astype(np.float32)
    m = HistGBT(n_trees=n_trees, max_depth=depth, n_bins=8, **kw)
    m.fit(X, y, warmup_rounds=1)
    return m, X, y


def _trees(m):
    return [{k: np.asarray(v) for k, v in t.items()} for t in m.trees]


def _assert_same_trees(a, b):
    assert len(a) == len(b)
    for ta, tb in zip(a, b):
        assert set(ta) == set(tb)
        for k in ta:
            np.testing.assert_array_equal(ta[k], tb[k], err_msg=k)


@pytest.fixture
def tmp_cache(tmp_path):
    """Redirect the persistent cache to a fresh dir; restore the test
    harness's dir (conftest.py) afterwards so other tests keep their
    warm cache."""
    prev = jax.config.jax_compilation_cache_dir
    d = str(tmp_path / "xla_cache")
    cc.set_cache_dir(d)
    try:
        yield d
    finally:
        cc.set_cache_dir(prev)


class TestCompileCache:
    def test_dir_respected_and_hit_on_second_fit(self, tmp_cache):
        mark = cc.marker()
        _tiny_fit(rows=192)
        hits0, misses0 = cc.marker()
        # fresh dir: programs were compiled and WRITTEN there
        assert misses0 - mark[1] > 0
        assert os.path.isdir(tmp_cache) and len(os.listdir(tmp_cache)) > 0
        assert cc.stats()["dir"] == tmp_cache

        # drop every in-memory executable so the same-shape refit must
        # go back to XLA — which must now read the persistent cache
        jax.clear_caches()
        _ROUND_FN_CACHE.clear()
        _AOT_EXEC_CACHE.clear()
        _tiny_fit(rows=192)
        hits1, misses1 = cc.marker()
        assert hits1 - hits0 > 0, "second same-shape fit must hit"
        # and the hit/miss counters surface in the metrics registry
        reg = base_metrics.default_registry().snapshot()["metrics"]
        ev = reg.get("dmlc_compile_cache_events_total")
        if base_metrics.enabled():
            labels = {s["labels"]["event"] for s in ev["series"]}
            assert "hit" in labels and "miss" in labels

    def test_disabled_is_noop(self, monkeypatch):
        monkeypatch.setenv("DMLC_COMPILE_CACHE", "0")
        before = jax.config.jax_compilation_cache_dir
        assert cc.configure() is False
        assert cc.stats()["enabled"] is False
        assert jax.config.jax_compilation_cache_dir == before

    def test_verdict_classification(self):
        assert cc.verdict(cc.marker()) is None   # no traffic since mark

    def test_configure_adopts_existing_dir(self, monkeypatch):
        # no env override → the already-configured dir survives
        monkeypatch.delenv("DMLC_COMPILE_CACHE_DIR", raising=False)
        before = jax.config.jax_compilation_cache_dir
        assert cc.configure() is True
        assert jax.config.jax_compilation_cache_dir == before


class TestOverlapParity:
    def test_overlap_bit_identical_to_inline(self, monkeypatch):
        m1, X, y = _tiny_fit(n_trees=3, seed=1)          # overlap (default)
        assert m1.last_compile_seconds is not None or \
            m1.last_compile_cache is None   # handle consumed or cache-warm
        monkeypatch.setenv("DMLC_COLDSTART_OVERLAP", "0")
        m2 = HistGBT(n_trees=3, max_depth=2, n_bins=8)
        m2.fit(X, y, warmup_rounds=1)
        assert m2.last_compile_seconds is None           # inline path
        _assert_same_trees(_trees(m1), _trees(m2))
        np.testing.assert_array_equal(m1.predict(X), m2.predict(X))

    def test_overlap_with_sampling_and_eval_set(self, monkeypatch):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(300, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        Xv, yv = X[:80], y[:80]
        kw = dict(n_trees=4, max_depth=3, n_bins=16, subsample=0.7,
                  colsample_bytree=0.8, seed=7)
        m1 = HistGBT(**kw)
        m1.fit(X, y, warmup_rounds=1, eval_set=(Xv, yv))
        monkeypatch.setenv("DMLC_COLDSTART_OVERLAP", "0")
        m2 = HistGBT(**kw)
        m2.fit(X, y, warmup_rounds=1, eval_set=(Xv, yv))
        _assert_same_trees(_trees(m1), _trees(m2))
        assert m1.eval_history == m2.eval_history

    def test_warmup_handle_ignored_on_param_drift(self):
        # a handle warmed for one config must not serve another: mutate
        # n_trees between make_device_data (kickoff) and the fit
        rng = np.random.default_rng(3)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=8)
        dd = m.make_device_data(X, y)
        assert m._pending_warmup is not None
        m.param.n_trees = 3                  # drift: K/rem change
        m.fit_device(dd, warmup_rounds=1)
        assert len(m.trees) == 3             # inline fallback, correct
        assert m.last_compile_seconds is None

    def test_schedule_helper(self):
        assert _rounds_schedule(100) == (25, 0)
        assert _rounds_schedule(30) == (25, 5)
        assert _rounds_schedule(100, eval_every=7) == (7, 2)
        assert _rounds_schedule(3) == (3, 0)


class TestStreamedIngest:
    def test_chunked_bins_bit_identical(self, monkeypatch):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(500, 6)).astype(np.float32)
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
        Xv = rng.normal(size=(200, 6)).astype(np.float32)
        yv = (Xv[:, 0] + Xv[:, 1] > 0).astype(np.float32)
        m1 = HistGBT(n_trees=3, max_depth=3, n_bins=16)
        m1.fit(X, y, eval_set=(Xv, yv))
        # tiny chunks force the streamed path for train AND eval ingest
        monkeypatch.setenv("DMLC_INGEST_CHUNK_ROWS", "96")
        m2 = HistGBT(n_trees=3, max_depth=3, n_bins=16)
        m2.fit(X, y, eval_set=(Xv, yv))
        _assert_same_trees(_trees(m1), _trees(m2))
        assert m1.eval_history == m2.eval_history
        np.testing.assert_array_equal(m1.predict(Xv), m2.predict(Xv))

    def test_chunked_missing_mode(self, monkeypatch):
        rng = np.random.default_rng(5)
        X = rng.normal(size=(400, 5)).astype(np.float32)
        X[rng.random(X.shape) < 0.1] = np.nan
        y = (np.nan_to_num(X[:, 0]) > 0).astype(np.float32)
        m1 = HistGBT(n_trees=3, max_depth=2, n_bins=16)
        m1.fit(X, y)
        assert m1._missing
        monkeypatch.setenv("DMLC_INGEST_CHUNK_ROWS", "64")
        m2 = HistGBT(n_trees=3, max_depth=2, n_bins=16)
        m2.fit(X, y)
        _assert_same_trees(_trees(m1), _trees(m2))

    def test_streaming_disabled_by_zero(self, monkeypatch):
        monkeypatch.setenv("DMLC_INGEST_CHUNK_ROWS", "0")
        m, X, _ = _tiny_fit(seed=6)
        assert len(m.trees) == 2             # whole-matrix path still fine


class TestColdStartEvidence:
    def test_breakdown_fields_populated(self):
        m, X, y = _tiny_fit(n_trees=3, rows=256, seed=8)
        assert m.last_bin_seconds is not None and m.last_bin_seconds >= 0
        assert m.last_warm_dispatch_seconds is not None
        assert m.last_warmup_seconds >= m.last_warm_dispatch_seconds
        # fit_device on a fresh handle reuses the process-wide AOT
        # executables: zero compile on the critical path
        dd = m.make_device_data(X, y)
        m2 = HistGBT(n_trees=3, max_depth=2, n_bins=8)
        m2.fit_device(dd, warmup_rounds=1)
        assert len(m2.trees) == 3


class TestServePrewarm:
    def test_env_gated_prewarm_and_gauge(self, monkeypatch):
        from dmlc_core_tpu.serve import ModelRunner
        from dmlc_core_tpu.serve.instruments import serve_metrics

        m, X, _ = _tiny_fit(seed=9)
        monkeypatch.setenv("DMLC_SERVE_PREWARM", "1")
        r = ModelRunner(m, max_batch=32, min_bucket=8, name="prewarm-t")
        assert r.compiled_shapes == {8, 16, 32}
        if base_metrics.enabled():
            g = serve_metrics()["compiled_shapes"]
            assert g.value(runner="prewarm-t") == r.shape_bound
        # pre-warmed runner scores identically to the bare model
        np.testing.assert_array_equal(r.predict(X[:5]), m.predict(X[:5]))

    def test_warmup_needs_feature_width(self):
        from dmlc_core_tpu.serve import ModelRunner
        from dmlc_core_tpu.base.logging import Error

        class Opaque:
            def predict(self, X):
                return np.zeros(len(X), np.float32)

        r = ModelRunner(Opaque(), max_batch=16, min_bucket=8)
        with pytest.raises(Error):
            r.warmup()
        assert r.warmup(n_features=3) >= 0.0
        assert r.compiled_shapes == {8, 16}
