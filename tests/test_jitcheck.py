"""jitcheck (dynamic XLA-compile tracer) contracts.

The static ``recompile-hazard`` pass proves cache keys are stable
shapes; these tests prove the dynamic half: every compilation is
recorded with its phase tag and repo call site, a compile seeded after
``steady()`` raises :class:`JitCompileError` with an actionable stack,
warmup compiles never fail ``check()``, install/uninstall cycles
restore the true jax entry point, and with the env gate off nothing is
patched at all.

This file lives under tests/ on purpose: the recorded site must name
the repo frame that triggered the compile, and the test file IS the
repo frame.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import pytest

from dmlc_core_tpu.base import jitcheck


@pytest.fixture
def traced():
    installed_before = jitcheck.installed()
    if not installed_before:
        jitcheck.install()
    jitcheck.reset()
    yield
    jitcheck.reset()
    if not installed_before:
        jitcheck.uninstall()


def _fresh_compile(salt: float) -> None:
    """Force one real XLA compilation: a brand-new jitted closure is
    never in jax's in-process jit cache, whatever earlier tests ran."""
    fn = jax.jit(lambda x: x * salt + salt)
    fn(jnp.arange(4.0)).block_until_ready()


# ---------------------------------------------------------------------------
# the seeded violation: a compile after steady() fails check()
# ---------------------------------------------------------------------------

def test_seeded_steady_compile_raises_with_repo_site(traced):
    _fresh_compile(2.0)                       # legitimate warmup compile
    jitcheck.steady()
    _fresh_compile(3.0)                       # the seeded violation
    bad = jitcheck.compiles("steady")
    assert len(bad) == 1, bad
    # the site must name THIS file and the seeding helper — that's
    # what makes a steady-state stall actionable from the drill log
    assert "tests/test_jitcheck.py" in bad[0]["site"]
    assert "(_fresh_compile)" in bad[0]["site"]
    assert bad[0]["seconds"] >= 0
    with pytest.raises(jitcheck.JitCompileError,
                       match="steady-state XLA compilation"):
        jitcheck.check()


def test_warmup_compiles_are_exempt(traced):
    _fresh_compile(5.0)
    _fresh_compile(7.0)
    assert jitcheck.current_phase() == "warmup"
    recs = jitcheck.compiles()
    assert len(recs) >= 2
    assert all(r["phase"] == "warmup" for r in recs)
    jitcheck.steady()
    jitcheck.check()                          # no steady records: silent


def test_warmup_reentry_between_sections(traced):
    jitcheck.steady()
    jitcheck.warmup()                         # new drill section begins
    _fresh_compile(11.0)
    jitcheck.steady()
    jitcheck.check()                          # that compile was warmup


# ---------------------------------------------------------------------------
# report artifact (the drills' *_JITCHECK_OUT JSON)
# ---------------------------------------------------------------------------

def test_write_report_counts_phases(traced, tmp_path):
    _fresh_compile(13.0)
    jitcheck.steady()
    _fresh_compile(17.0)
    out = tmp_path / "jitcheck.json"
    report = jitcheck.write_report(str(out))
    assert report["enabled"] is True
    assert report["phase"] == "steady"
    assert report["compiles_steady"] == 1
    assert report["compiles_total"] >= 2
    on_disk = json.loads(out.read_text())
    assert on_disk["compiles_steady"] == 1
    assert on_disk["compiles"][0]["module"]


def test_reset_clears_records_and_phase(traced):
    _fresh_compile(19.0)
    jitcheck.steady()
    jitcheck.reset()
    assert jitcheck.compiles() == []
    assert jitcheck.current_phase() == "warmup"


# ---------------------------------------------------------------------------
# lifecycle: idempotent cycles restore the true entry point
# ---------------------------------------------------------------------------

def test_install_uninstall_idempotent_and_restoring():
    from jax._src import compiler as _compiler

    original = _compiler.compile_or_get_cached
    was_installed = jitcheck.installed()
    if was_installed:
        jitcheck.uninstall()
        original = _compiler.compile_or_get_cached
    try:
        jitcheck.install()
        patched = _compiler.compile_or_get_cached
        assert patched is not original
        jitcheck.install()                    # second install: no-op
        assert _compiler.compile_or_get_cached is patched
        jitcheck.uninstall()
        assert _compiler.compile_or_get_cached is original
        jitcheck.uninstall()                  # second uninstall: no-op
        assert _compiler.compile_or_get_cached is original
        # a full second cycle must save/restore the TRUE entry point,
        # not a stale wrapper from the first cycle
        jitcheck.install()
        jitcheck.uninstall()
        assert _compiler.compile_or_get_cached is original
    finally:
        if was_installed and not jitcheck.installed():
            jitcheck.install()


# ---------------------------------------------------------------------------
# env gate off: nothing is patched, dispatch runs untouched
# ---------------------------------------------------------------------------

def test_env_gate_off_means_no_patch(monkeypatch):
    monkeypatch.delenv("DMLC_JITCHECK", raising=False)
    assert jitcheck.env_enabled() is False
    if not jitcheck.installed():
        from jax._src import compiler as _compiler

        # the gate was off at import, so the entry point is jax's own
        assert _compiler.compile_or_get_cached is not jitcheck._traced_compile
        before = len(jitcheck.compiles())
        _fresh_compile(23.0)
        assert len(jitcheck.compiles()) == before


@pytest.mark.parametrize("val,expect", [
    ("1", True), ("true", True), ("on", True), ("raise", True),
    ("0", False), ("off", False), ("", False),
])
def test_env_enabled_parsing(monkeypatch, val, expect):
    monkeypatch.setenv("DMLC_JITCHECK", val)
    assert jitcheck.env_enabled() is expect
