"""Tests for the cluster launch backends (SURVEY.md §2c inventory).

All backends expose a pure command/script/manifest builder that is
asserted here without needing the cluster manager installed — the same
way the reference's backends are thin cmdline generators over the
``DMLC_*`` env ABI.
"""

import json
import pathlib
import shlex

REPO = pathlib.Path(__file__).resolve().parent.parent

import pytest

from dmlc_core_tpu.tracker import kubernetes as k8s
from dmlc_core_tpu.tracker import launcher, mesos, mpi, sge, slurm, yarn
from dmlc_core_tpu.tracker.opts import CLUSTERS, get_opts

ENVS = {"DMLC_TRACKER_URI": "10.0.0.1", "DMLC_TRACKER_PORT": "9091",
        "DMLC_NUM_WORKER": "4"}
CMD = ["python", "worker.py", "--lr", "0.1"]


class TestMPI:
    def test_openmpi_exports_keys(self):
        cmd = mpi.build_command(4, CMD, ENVS, flavor="openmpi")
        assert cmd[:3] == ["mpirun", "-n", "4"]
        assert "-x" in cmd and "DMLC_TRACKER_URI" in cmd
        assert cmd[-len(CMD):] == CMD

    def test_mpich_inlines_values(self):
        cmd = mpi.build_command(2, CMD, ENVS, flavor="mpich")
        i = cmd.index("DMLC_TRACKER_PORT")
        assert cmd[i - 1] == "-env" and cmd[i + 1] == "9091"

    def test_hostfile_flag(self):
        cmd = mpi.build_command(2, CMD, ENVS, host_file="hosts.txt", flavor="openmpi")
        assert "--hostfile" in cmd
        cmd = mpi.build_command(2, CMD, ENVS, host_file="hosts.txt", flavor="mpich")
        assert "-f" in cmd


class TestSlurm:
    def test_srun_line(self):
        cmd = slurm.build_command(8, CMD, ENVS, queue="tpu", jobname="j1",
                                  worker_cores=4, worker_memory_mb=2048)
        assert "--ntasks=8" in cmd and "--partition=tpu" in cmd
        exports = [c for c in cmd if c.startswith("--export=")]
        assert len(exports) == 1
        assert "DMLC_TRACKER_URI=10.0.0.1" in exports[0]
        assert "DMLC_ROLE=worker" in exports[0]
        assert cmd[-len(CMD):] == CMD


class TestSGE:
    def test_script_structure(self):
        script = sge.build_script(4, CMD, ENVS, queue="all.q", jobname="j2")
        assert "#$ -t 1-4" in script
        assert "#$ -q all.q" in script
        assert "export DMLC_TRACKER_URI=10.0.0.1" in script
        assert "DMLC_TASK_ID=$((SGE_TASK_ID - 1))" in script
        assert shlex.join(CMD) in script or " ".join(CMD) in script


class TestYarn:
    def test_command_resources_and_env(self):
        cmd = yarn.build_command(4, CMD, ENVS, queue="prod", worker_cores=2,
                                 worker_memory_mb=4096, app_jar="/x/ds.jar")
        assert "-num_containers" in cmd and cmd[cmd.index("-num_containers") + 1] == "4"
        assert "-container_vcores" in cmd and "-container_memory" in cmd
        assert "-queue" in cmd
        joined = " ".join(cmd)
        assert "DMLC_TRACKER_URI=10.0.0.1" in joined


class TestMesos:
    def test_command_env_json(self):
        cmd = mesos.build_command(3, CMD, ENVS, master="m:5050", worker_cores=2,
                                  worker_memory_mb=512)
        env_arg = next(c for c in cmd if c.startswith("--env="))
        env = json.loads(env_arg[len("--env="):])
        kv = {e["name"]: e["value"] for e in env["variables"]}
        assert kv["DMLC_TASK_ID"] == "3"
        assert kv["DMLC_ROLE"] == "worker"
        assert "--resources=cpus:2;mem:512" in cmd


class TestKubernetes:
    def test_manifest_indexed_job(self):
        m = k8s.build_manifest(8, CMD, ENVS, image="img:1", jobname="j3",
                               worker_cores=4, worker_memory_mb=8192,
                               tpu_topology="2x4",
                               tpu_accelerator="tpu-v5-lite-podslice")
        assert m["kind"] == "Job"
        spec = m["spec"]
        assert spec["completions"] == 8 and spec["parallelism"] == 8
        assert spec["completionMode"] == "Indexed"
        pod = spec["template"]["spec"]
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x4"
        c = pod["containers"][0]
        assert c["command"] == CMD
        names = [e["name"] for e in c["env"]]
        assert "DMLC_TRACKER_URI" in names and "DMLC_TASK_ID" in names
        assert c["resources"]["requests"]["memory"] == "8192Mi"
        json.dumps(m)  # must be serializable for kubectl apply -f -


class TestLauncher:
    def test_task_id_each_rank_var(self):
        # every cluster-manager rank variable resolves on its own
        for var in launcher._RANK_VARS:
            assert launcher.task_id_from_env({var: "6"}) == 6, var

    def test_task_id_priority(self):
        assert launcher.task_id_from_env({"DMLC_TASK_ID": "5",
                                          "SLURM_PROCID": "9"}) == 5
        assert launcher.task_id_from_env({"OMPI_COMM_WORLD_RANK": "3"}) == 3
        assert launcher.task_id_from_env({"PMI_RANK": "1",
                                          "JOB_COMPLETION_INDEX": "8"}) == 1
        assert launcher.task_id_from_env({"SLURM_PROCID": "2"}) == 2
        assert launcher.task_id_from_env({"JOB_COMPLETION_INDEX": "7"}) == 7
        # full precedence chain: earlier var always wins
        env = {v: str(i) for i, v in enumerate(launcher._RANK_VARS)}
        for i, var in enumerate(launcher._RANK_VARS):
            assert launcher.task_id_from_env(env) == i
            del env[var]
        assert launcher.task_id_from_env({}) == 0
        assert launcher.task_id_from_env({"DMLC_TASK_ID": "  "}) == 0

    def test_task_id_required_checks(self):
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error, match="no rank variable"):
            launcher.task_id_from_env({}, required=True)
        assert launcher.task_id_from_env({"PMI_RANK": "4"},
                                         required=True) == 4

    def test_prepare_env_fills_abi(self):
        env = launcher.prepare_env({"PMI_RANK": "4"})
        assert env["DMLC_TASK_ID"] == "4"
        assert env["DMLC_ROLE"] == "worker"
        assert env["DMLC_NUM_ATTEMPT"] == "0"


class TestHostFile:
    def test_comments_blanks_and_slots(self, tmp_path):
        from dmlc_core_tpu.tracker.ssh import read_host_file
        hf = tmp_path / "hosts"
        hf.write_text("# edge pool\n\nh0:2\nh1\nuser@h2:1 extra-col\n")
        assert read_host_file(str(hf)) == ["h0", "h0", "h1", "user@h2"]

    def test_empty_file_errors(self, tmp_path):
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.tracker.ssh import read_host_file
        hf = tmp_path / "hosts"
        hf.write_text("# only comments\n\n")
        with pytest.raises(Error, match="no hosts"):
            read_host_file(str(hf))

    def test_bad_slot_count_errors(self, tmp_path):
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.tracker.ssh import read_host_file
        hf = tmp_path / "hosts"
        hf.write_text("h0:0\n")
        with pytest.raises(Error, match="bad slot count"):
            read_host_file(str(hf))


class TestOpts:
    def test_all_reference_clusters_present(self):
        # SURVEY.md §2c: local, ssh, mpi, sge, slurm, yarn, mesos, kubernetes
        assert set(CLUSTERS) == {"local", "ssh", "mpi", "sge", "slurm",
                                 "yarn", "mesos", "kubernetes"}

    @pytest.mark.parametrize("cluster", CLUSTERS)
    def test_cluster_accepted(self, cluster):
        opts, cmd = get_opts(["--cluster", cluster, "-n", "2", "--", "echo", "hi"])
        assert opts.cluster == cluster and cmd == ["echo", "hi"]

    def test_resource_opts(self):
        opts, _ = get_opts(["-n", "4", "--queue", "q", "--worker-cores", "8",
                            "--worker-memory", "1024", "--image", "img",
                            "--max-attempts", "5", "--", "x"])
        assert (opts.queue, opts.worker_cores, opts.worker_memory,
                opts.image, opts.max_attempts) == ("q", 8, 1024, "img", 5)


@pytest.mark.slow
def test_dmlc_submit_cli_local_end_to_end(tmp_path):
    """The real CLI, as a user runs it: fork workers via --cluster=local,
    each worker connects to the tracker and reports its rank to a file."""
    import subprocess
    import sys

    worker = tmp_path / "worker.py"
    worker.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {str(REPO)!r})\n"
        "from dmlc_core_tpu.tracker.tracker import RabitTracker\n"
        "uri = os.environ['DMLC_TRACKER_URI']\n"
        "port = int(os.environ['DMLC_LEGACY_TRACKER_PORT'])\n"
        "info = RabitTracker.worker_connect(uri, port)\n"
        f"open(os.path.join({str(tmp_path)!r}, f\"rank{{info['rank']}}\"), 'w')"
        ".write(str(info['num_worker']))\n"
        "RabitTracker.worker_connect(uri, port, cmd='shutdown')\n"
    )
    out = subprocess.run(
        [sys.executable, str(REPO / "dmlc-submit"), "--cluster=local",
         "--num-workers=4", "--start-legacy-tracker",
         sys.executable, str(worker)],
        capture_output=True, text=True, timeout=120, cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    ranks = sorted(p.name for p in tmp_path.glob("rank*"))
    assert ranks == ["rank0", "rank1", "rank2", "rank3"], ranks
    assert all((tmp_path / r).read_text() == "4" for r in ranks)


# ---------------------------------------------------------------------------
# Elastic YARN restart (VERDICT round-1 item 6): fake RM REST server
# ---------------------------------------------------------------------------

class _FakeYarnRM:
    """In-process ResourceManager REST fake: /ws/v1/cluster/apps/{id}.

    App lifecycle is scripted by the test: each app id maps to a list of
    (state, finalStatus) snapshots consumed one per poll (last one sticks).
    """

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import threading

        rm = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                parts = self.path.rstrip("/").split("/")
                app_id = parts[-1]
                states = rm.apps.get(app_id)
                if states is None:
                    body = b"{}"
                    self.send_response(404)
                else:
                    state, final = states[0] if len(states) == 1 else states.pop(0)
                    body = json.dumps(
                        {"app": {"id": app_id, "state": state,
                                 "finalStatus": final}}).encode()
                    self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.apps = {}
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.uri = f"http://127.0.0.1:{self.server.server_port}"
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def fake_rm():
    rm = _FakeYarnRM()
    yield rm
    rm.close()


class TestElasticYarn:
    def test_failed_container_resubmitted_with_attempt_env(self, fake_rm):
        submitted = []  # (task_id, env) in submission order

        def submit_fn(task_id, env):
            submitted.append((task_id, dict(env)))
            app_id = f"application_1_{task_id}_{env['DMLC_NUM_ATTEMPT']}"
            if task_id == 1 and env["DMLC_NUM_ATTEMPT"] == "0":
                # first attempt of task 1 dies after one RUNNING poll
                fake_rm.apps[app_id] = [("RUNNING", "UNDEFINED"),
                                        ("FINISHED", "FAILED")]
            else:
                fake_rm.apps[app_id] = [("FINISHED", "SUCCEEDED")]
            return app_id

        job = yarn.ElasticYarnJob(
            nworker=3, envs={"DMLC_TRACKER_URI": "10.0.0.1"},
            submit_fn=submit_fn, rest=yarn.YarnRestClient(fake_rm.uri),
            max_attempts=3, poll_interval=0.01)
        attempts = job.run(job_timeout=30)

        assert attempts == {0: 1, 1: 2, 2: 1}
        assert len(job.restarts) == 1 and job.restarts[0]["task"] == 1
        # the resubmission exported the incremented DMLC_NUM_ATTEMPT
        resub = [env for t, env in submitted if t == 1]
        assert [e["DMLC_NUM_ATTEMPT"] for e in resub] == ["0", "1"]
        assert all(env["DMLC_TASK_ID"] == str(t) for t, env in submitted)

    def test_max_attempts_exhausted_aborts(self, fake_rm):
        def submit_fn(task_id, env):
            app_id = f"application_2_{task_id}_{env['DMLC_NUM_ATTEMPT']}"
            fake_rm.apps[app_id] = [("FAILED", "FAILED")]
            return app_id

        from dmlc_core_tpu.base.logging import Error
        job = yarn.ElasticYarnJob(
            nworker=1, envs={}, submit_fn=submit_fn,
            rest=yarn.YarnRestClient(fake_rm.uri),
            max_attempts=2, poll_interval=0.01)
        with pytest.raises(Error, match="failed 2 times"):
            job.run(job_timeout=30)
        assert job.attempts[0] == 2
