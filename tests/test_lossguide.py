"""Leaf-wise (lossguide) tree growth: the ISSUE 12 oracle contracts.

The oracle: with an unlimited leaf budget, gain-priority leaf-wise
expansion visits exactly the set of nodes depth-wise growth splits
(every split it records has gain > gamma, and expansion order cannot
change which splits are profitable), and the single-node histogram
builds are bit-identical to the level-batched ones — so tree STRUCTURE
(feat/thr arrays) must match depth-wise exactly.  Leaf values may
differ at last-ulp in UNREACHABLE leaves: depth-wise materializes a
degenerate right-subtraction chain under pruned nodes (hist − hist of
identical row sets is not exactly 0 after the parent was itself
subtracted), where lossguide leaves a clean −0.0; no rows reach those
leaves, so predictions agree to float tolerance.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.base.logging import Error  # noqa: E402
from dmlc_core_tpu.models import HistGBT  # noqa: E402
from dmlc_core_tpu.ops.histogram import leaves_built_per_round  # noqa: E402

KW = dict(n_trees=4, max_depth=4, n_bins=32,
          objective="binary:logistic", learning_rate=0.3)


def _xy(n=2003, F=7, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[:, 2] = rng.integers(0, 3, n)
    y = ((X[:, 0] + 0.5 * X[:, 2] - X[:, 1] * X[:, 3]) > 0
         ).astype(np.float32)
    return X, y


class TestLossguideOracle:
    def test_unlimited_budget_matches_depthwise(self, monkeypatch):
        X, y = _xy()
        m0 = HistGBT(**KW)
        m0.fit(X, y)
        monkeypatch.setenv("DMLC_GROW_POLICY", "lossguide")
        m1 = HistGBT(**KW)
        m1.fit(X, y)
        for i, (t0, t1) in enumerate(zip(m0.trees, m1.trees)):
            assert np.array_equal(t0["feat"], t1["feat"]), i
            assert np.array_equal(t0["thr"], t1["thr"]), i
            np.testing.assert_allclose(t0["gain"], t1["gain"],
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(t0["leaf"], t1["leaf"],
                                       rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(m0.predict(X), m1.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_max_leaves_budget_respected(self, monkeypatch):
        X, y = _xy(seed=1)
        monkeypatch.setenv("DMLC_GROW_POLICY", "lossguide")
        monkeypatch.setenv("DMLC_MAX_LEAVES", "6")
        m = HistGBT(**KW)
        m.fit(X, y)
        for t in m.trees:
            # ≤ max_leaves − 1 realized splits per tree (gain > 0 only
            # where a split was recorded; degenerate nodes record 0)
            assert int((np.asarray(t["gain"]) > 0).sum()) <= 5
        acc = ((m.predict(X) > 0.5) == y).mean()
        assert acc > 0.8

    def test_default_policy_is_depthwise_byte_parity(self, tmp_path,
                                                     monkeypatch):
        X, y = _xy(seed=2)
        m0 = HistGBT(**KW)
        m0.fit(X, y)
        monkeypatch.setenv("DMLC_GROW_POLICY", "depthwise")
        m1 = HistGBT(**KW)
        m1.fit(X, y)
        u0, u1 = str(tmp_path / "a.ubj"), str(tmp_path / "b.ubj")
        m0.save_model(u0)
        m1.save_model(u1)
        assert open(u0, "rb").read() == open(u1, "rb").read()

    def test_invalid_policy_rejected(self, monkeypatch):
        X, y = _xy(n=203)
        monkeypatch.setenv("DMLC_GROW_POLICY", "bogus")
        with pytest.raises(Error):
            HistGBT(**KW).fit(X, y)

    def test_packed_lossguide_structure(self, monkeypatch):
        # both levers together: packed storage + leaf-wise growth
        X, y = _xy(seed=3)
        m0 = HistGBT(**KW)
        m0.fit(X, y)
        monkeypatch.setenv("DMLC_GROW_POLICY", "lossguide")
        monkeypatch.setenv("DMLC_BIN_PACK", "1")
        m1 = HistGBT(**KW)
        m1.fit(X, y)
        for t0, t1 in zip(m0.trees, m1.trees):
            assert np.array_equal(t0["feat"], t1["feat"])
            assert np.array_equal(t0["thr"], t1["thr"])


class TestLeavesAccounting:
    def test_leaves_built_per_round(self):
        # depth-wise: root + left children only (sibling subtraction)
        assert leaves_built_per_round(1) == 1
        assert leaves_built_per_round(6) == 32
        # lossguide: one build per expansion, depth-independent
        assert leaves_built_per_round(6, "lossguide", 8) == 8
        assert leaves_built_per_round(6, "lossguide", 0) == 64
