"""Metrics registry + hot-path instrumentation tests.

Covers the observability acceptance surface: thread-safety under
concurrent updates, the Prometheus text exposition format (golden +
parse check), JSON snapshot round-trip, disabled-mode no-op (guarded at
call sites), and the ThreadedIter integration — a 2-thread pipeline run
must populate queue-occupancy/stall metrics and, with tracing on,
``Tracer.save`` must emit valid Chrome-trace JSON containing the new
scopes.
"""

import json
import re
import threading
import time

import pytest

from dmlc_core_tpu.base import metrics as M
from dmlc_core_tpu.io.threaded_iter import ThreadedIter
from dmlc_core_tpu.utils.profiler import (Tracer, global_tracer,
                                          set_tracing, tracing_enabled)


@pytest.fixture(autouse=True)
def _metrics_isolation():
    """Every test sees enabled collection and a clean default registry;
    process-wide switches are restored afterwards."""
    M.set_enabled(True)
    M.default_registry().reset()
    was_tracing = tracing_enabled()
    yield
    M.set_enabled(True)
    set_tracing(was_tracing)
    M.default_registry().reset()


class TestPrimitives:
    def test_counter_labels_and_value(self):
        r = M.MetricsRegistry(namespace="t")
        c = r.counter("reqs_total", "requests", labels=("op",))
        c.inc(op="a")
        c.inc(2.5, op="a")
        c.inc(op="b")
        assert c.value(op="a") == 3.5
        assert c.value(op="b") == 1.0
        assert c.value(op="never") == 0.0

    def test_counter_rejects_negative_and_bad_labels(self):
        r = M.MetricsRegistry(namespace="t")
        c = r.counter("n_total", labels=("op",))
        with pytest.raises(ValueError):
            c.inc(-1, op="a")
        with pytest.raises(ValueError):
            c.inc(1, wrong="a")
        with pytest.raises(ValueError):
            c.inc(1)  # missing declared label

    def test_gauge_set_inc_dec(self):
        r = M.MetricsRegistry(namespace="t")
        g = r.gauge("depth")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6.0

    def test_histogram_buckets_sum_count_quantiles(self):
        r = M.MetricsRegistry(namespace="t")
        h = r.histogram("lat", labels=("op",), buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v, op="x")
        assert h.count(op="x") == 5
        assert h.sum(op="x") == pytest.approx(56.05)
        q50 = h.quantile(0.5, op="x")
        assert q50 in (0.5, 5.0)  # reservoir midpoint of the samples
        snap = h._snap()[0]
        # cumulative buckets: ≤0.1 → 1, ≤1 → 3, ≤10 → 4, +Inf → 5
        assert snap["buckets"] == [[0.1, 1], [1.0, 3], [10.0, 4],
                                   ["+Inf", 5]]
        assert snap["min"] == 0.05 and snap["max"] == 50.0

    def test_histogram_timer_context(self):
        r = M.MetricsRegistry(namespace="t")
        h = r.histogram("span", labels=())
        with h.time():
            time.sleep(0.01)
        assert h.count() == 1
        assert h.sum() >= 0.009

    def test_declare_is_idempotent_but_kind_conflict_raises(self):
        r = M.MetricsRegistry(namespace="t")
        a = r.counter("x_total", labels=("op",))
        assert r.counter("x_total", labels=("op",)) is a
        with pytest.raises(ValueError):
            r.gauge("x_total")
        with pytest.raises(ValueError):
            r.counter("x_total", labels=("other",))


class TestConcurrency:
    def test_concurrent_counter_and_histogram_updates(self):
        """N threads hammer one counter + one histogram; totals must be
        exact (no lost updates)."""
        r = M.MetricsRegistry(namespace="t")
        c = r.counter("hits_total", labels=("op",))
        h = r.histogram("obs", labels=("op",), buckets=(0.5, 1.5))
        n_threads, per_thread = 8, 2000

        def work(i):
            op = "even" if i % 2 == 0 else "odd"
            for _ in range(per_thread):
                c.inc(1, op=op)
                h.observe(1.0, op=op)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        half = n_threads // 2 * per_thread
        assert c.value(op="even") == half
        assert c.value(op="odd") == half
        assert h.count(op="even") == half
        assert h.sum(op="odd") == half  # every observation was 1.0


_GOLDEN = """\
# HELP t_lat_seconds latency
# TYPE t_lat_seconds histogram
t_lat_seconds_bucket{op="read",le="0.01"} 1
t_lat_seconds_bucket{op="read",le="1"} 2
t_lat_seconds_bucket{op="read",le="+Inf"} 3
t_lat_seconds_sum{op="read"} 5.505
t_lat_seconds_count{op="read"} 3
# TYPE t_queue_depth gauge
t_queue_depth 4
# HELP t_rows_total rows seen
# TYPE t_rows_total counter
t_rows_total{format="csv"} 12
t_rows_total{format="libsvm"} 30
"""


_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? '
    r'[-+]?(\d+(\.\d+)?([eE][-+]?\d+)?|Inf|NaN)$')
_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def _assert_prometheus_parses(text):
    """Every exposition line must match the text-format grammar — the
    check a real scraper effectively performs."""
    for line in text.strip().split("\n"):
        assert _COMMENT_RE.match(line) or _SAMPLE_RE.match(line), line


#: hostile label values — exactly what lands in label position once model
#: names and checkpoint URIs are labels on the serving /metrics endpoint
_HOSTILE_GOLDEN = """\
# HELP e_info source has a \\\\ backslash\\nand a newline
# TYPE e_info gauge
e_info{source="back\\\\slash"} 1
e_info{source="mem:///models/\\"quoted\\" v2"} 1
e_info{source="multi\\nline"} 1
"""


class TestLabelEscaping:
    """The exposition format's escaping rules, pinned against values a
    serving deployment actually produces (URIs, model names)."""

    @staticmethod
    def _hostile_registry():
        r = M.MetricsRegistry(namespace="e")
        g = r.gauge("info", 'source has a \\ backslash\nand a newline',
                    labels=("source",))
        g.set(1, source='mem:///models/"quoted" v2')
        g.set(1, source="back\\slash")
        g.set(1, source="multi\nline")
        return r

    def test_hostile_label_values_golden(self):
        assert self._hostile_registry().to_prometheus() == _HOSTILE_GOLDEN

    def test_hostile_label_values_parse(self):
        _assert_prometheus_parses(self._hostile_registry().to_prometheus())

    def test_escape_order_backslash_first(self):
        # escaping backslash last would double-escape the other escapes:
        # '"' -> '\\"' -> '\\\\"' (wrong).  Pin the composition.
        assert M._escape_label('a"b') == 'a\\"b'
        assert M._escape_label("a\\nb") == "a\\\\nb"   # literal \n chars
        assert M._escape_label("a\nb") == "a\\nb"      # real newline
        assert M._escape_help("h\\x\ny") == "h\\\\x\\ny"


class TestExporters:
    @staticmethod
    def _golden_registry():
        r = M.MetricsRegistry(namespace="t")
        c = r.counter("rows_total", "rows seen", labels=("format",))
        c.inc(30, format="libsvm")
        c.inc(12, format="csv")
        r.gauge("queue_depth").set(4)
        h = r.histogram("lat_seconds", "latency", labels=("op",),
                        buckets=(0.01, 1.0))
        for v in (0.005, 0.5, 5.0):
            h.observe(v, op="read")
        return r

    def test_prometheus_golden(self):
        assert self._golden_registry().to_prometheus() == _GOLDEN

    def test_prometheus_format_parses(self):
        _assert_prometheus_parses(self._golden_registry().to_prometheus())

    def test_default_registry_export_parses_after_pipeline_run(self):
        """Acceptance: the PROCESS-WIDE registry — populated by real
        instrumented code paths — must export parseable text."""
        _run_pipeline(n_items=16)
        text = M.default_registry().to_prometheus()
        assert "dmlc_threaded_iter_queue_occupancy_bucket" in text
        _assert_prometheus_parses(text)

    def test_json_snapshot_round_trip(self, tmp_path):
        r = self._golden_registry()
        snap = r.snapshot()
        assert json.loads(json.dumps(snap)) == snap
        p = r.save_json(str(tmp_path / "metrics.json"))
        with open(p) as f:
            assert json.load(f) == snap
        hist = snap["metrics"]["t_lat_seconds"]
        assert hist["kind"] == "histogram"
        assert hist["series"][0]["count"] == 3
        assert "p50" in hist["series"][0]["quantiles"]


def _run_pipeline(n_items=32, name="test_pipe", consumer_sleep=0.002):
    """A 2-thread producer/consumer ThreadedIter run (producer thread +
    consuming test thread) with a deliberately slow consumer so the
    queue banks items (nonzero occupancy) and the producer hits the
    capacity wall (nonzero stall)."""
    produced = iter(range(n_items))

    def next_fn(_cell):
        try:
            return next(produced) + 1  # avoid falsy 0
        except StopIteration:
            return None

    it = ThreadedIter(max_capacity=4, name=name)
    it.init(next_fn)
    got = []
    while True:
        item = it.next(timeout=10.0)
        if item is None:
            break
        got.append(item)
        time.sleep(consumer_sleep)
    it.destroy()
    assert got == list(range(1, n_items + 1))


class TestThreadedIterIntegration:
    def test_pipeline_populates_queue_and_stall_metrics(self):
        _run_pipeline(name="integration")
        r = M.default_registry()
        occ = r.histogram("threaded_iter_queue_occupancy", labels=("iter",))
        stall = r.histogram("threaded_iter_producer_stall_seconds",
                            labels=("iter",))
        wait = r.histogram("threaded_iter_consumer_wait_seconds",
                           labels=("iter",))
        items = r.counter("threaded_iter_items_total", labels=("iter",))
        assert items.value(iter="integration") == 32
        # queue occupancy was sampled, and — with a slow consumer — the
        # producer banked items, so the samples are not all zero
        assert occ.count(iter="integration") >= 32
        assert occ.sum(iter="integration") > 0
        # the producer hit the capacity-4 wall at least once
        assert stall.count(iter="integration") == 32
        assert stall.sum(iter="integration") > 0
        assert wait.count(iter="integration") >= 32

    def test_disabled_mode_is_a_noop_at_call_sites(self):
        M.set_enabled(False)
        try:
            _run_pipeline(name="disabled_run")
            r = M.default_registry()
            snap = r.snapshot()["metrics"]
            for m in snap.values():
                for series in m["series"]:
                    assert series["labels"].get("iter") != "disabled_run"
            # and direct instrument calls are no-ops too
            c = r.counter("noop_total")
            c.inc(5)
            assert c.value() == 0.0
            h = r.histogram("noop_seconds")
            h.observe(1.0)
            assert h.count() == 0
        finally:
            M.set_enabled(True)

    def test_tracer_records_pipeline_scopes(self, tmp_path):
        tr = global_tracer()
        tr.clear()
        set_tracing(True)
        try:
            _run_pipeline(name="traced")
        finally:
            set_tracing(False)
        path = tr.save(str(tmp_path / "trace.json"))
        with open(path) as f:
            payload = json.load(f)  # valid Chrome-trace JSON
        events = payload["traceEvents"]
        names = {e["name"] for e in events}
        assert "threaded_iter.produce" in names
        produce = [e for e in events if e["name"] == "threaded_iter.produce"]
        assert all(e["ph"] == "X" and e["dur"] >= 0 for e in produce)
        # producer events carry the producer thread's id — distinct from
        # the consuming (test) thread, so the two pipeline rows separate
        assert any(e["tid"] != threading.get_ident() for e in produce)


class TestTracerBounds:
    def test_event_cap_drops_instead_of_growing(self, tmp_path):
        tr = Tracer(max_events=10)
        for i in range(25):
            tr.instant(f"e{i}")
        assert len(tr.events()) == 10
        assert tr.dropped == 15
        path = tr.save(str(tmp_path / "t.json"))
        with open(path) as f:
            payload = json.load(f)
        assert payload["otherData"]["dropped_events"] == 15
