"""Tests for L0-L1 base layer: logging/CHECK, timer, registry, parameter,
config, env.  Mirrors the reference's unittest_logging / unittest_param /
unittest_config / unittest_env coverage (SURVEY.md §4)."""


import pytest

from dmlc_core_tpu import (
    CHECK,
    CHECK_EQ,
    CHECK_GE,
    CHECK_LT,
    CHECK_NOTNULL,
    Error,
    LOG,
    Parameter,
    Registry,
    field,
    get_env,
    get_time,
)
from dmlc_core_tpu.base.common import split
from dmlc_core_tpu.base.config import Config
from dmlc_core_tpu.base.logging import LogMessage
from dmlc_core_tpu.base.timer import Timer


class TestLogging:
    def test_check_pass(self):
        CHECK(True)
        CHECK_EQ(1, 1)
        CHECK_LT(1, 2)
        CHECK_GE(2, 2)

    def test_check_fail_raises_error(self):
        with pytest.raises(Error):
            CHECK(False, "boom")
        with pytest.raises(Error, match="=="):
            CHECK_EQ(1, 2)
        with pytest.raises(Error, match="<"):
            CHECK_LT(3, 2)

    def test_check_notnull_chains(self):
        assert CHECK_NOTNULL(42) == 42
        with pytest.raises(Error):
            CHECK_NOTNULL(None)

    def test_log_fatal_raises(self):
        with pytest.raises(Error, match="bad"):
            LOG("FATAL", "bad")

    def test_log_message_stream_style(self):
        with LogMessage("INFO") as log:
            log << "read " << 5 << " records"

    def test_error_carries_stack(self):
        try:
            LOG("FATAL", "x")
        except Error as e:
            assert e.stack_trace


class TestTimer:
    def test_get_time_monotonic(self):
        a = get_time()
        b = get_time()
        assert b >= a

    def test_timer_context(self):
        with Timer() as t:
            pass
        assert t.elapsed >= 0.0


class TestRegistry:
    def test_register_find_list(self):
        reg = Registry("test_things")

        @reg.register("alpha")
        def make_alpha():
            """makes an alpha"""
            return "A"

        assert reg.find("alpha") is not None
        assert reg.find("missing") is None
        assert reg["alpha"]() == "A"
        assert reg["alpha"].description == "makes an alpha"
        assert "alpha" in reg
        assert reg.list_all_names() == ["alpha"]

    def test_duplicate_register_fatal(self):
        reg = Registry("dups")
        reg.register("x", entry=1)
        with pytest.raises(Error):
            reg.register("x", entry=2)
        reg.register("x", entry=2, override=True)
        assert reg.find("x") == 2

    def test_unknown_lookup_fatal(self):
        reg = Registry("empty")
        with pytest.raises(Error, match="unknown entry"):
            reg["nope"]

    def test_global_get_singleton(self):
        a = Registry.get("shared_kind")
        b = Registry.get("shared_kind")
        assert a is b
        # direct construction returns the same per-kind singleton
        c = Registry("shared_kind")
        assert c is a
        a.register("thing", entry=1)
        assert Registry.get("shared_kind").find("thing") == 1


class MyParam(Parameter):
    num_hidden = field(int, default=100, lower_bound=1, description="hidden units")
    learning_rate = field(float, default=0.01, lower_bound=0.0, upper_bound=1.0)
    name = field(str, default="net")
    act = field(str, default="relu", enum=["relu", "gelu", "tanh"])
    use_bias = field(bool, default=True)
    required_dim = field(int, description="no default -> required")


class TestParameter:
    def test_defaults_and_init(self):
        p = MyParam()
        assert p.num_hidden == 100
        unknown = p.init({"num_hidden": "256", "required_dim": "4"})
        assert unknown == []
        assert p.num_hidden == 256 and isinstance(p.num_hidden, int)
        assert p.required_dim == 4

    def test_missing_required_raises(self):
        with pytest.raises(Error, match="required"):
            MyParam().init({})

    def test_unknown_key_raises_unless_allowed(self):
        p = MyParam()
        with pytest.raises(Error, match="unknown parameter"):
            p.init({"required_dim": 1, "bogus": 2})
        unknown = p.init({"required_dim": 1, "bogus": 2}, allow_unknown=True)
        assert unknown == [("bogus", 2)]

    def test_init_options(self):
        from dmlc_core_tpu.base.parameter import ParamInitOption

        p = MyParam()
        # strict default tolerates only hidden __key__ entries
        assert p.init({"required_dim": 1, "__hidden__": "x"}) == [("__hidden__", "x")]
        with pytest.raises(Error, match="unknown parameter"):
            p.init({"required_dim": 1, "__notclosed": "x"})
        # kAllMatch raises even on hidden keys
        with pytest.raises(Error, match="unknown parameter"):
            p.init({"required_dim": 1, "__hidden__": "x"}, option=ParamInitOption.kAllMatch)

    def test_range_violation(self):
        with pytest.raises(Error, match="bound"):
            MyParam().init({"required_dim": 1, "learning_rate": "1.5"})
        with pytest.raises(Error, match="bound"):
            MyParam().init({"required_dim": 1, "num_hidden": "0"})

    def test_enum_violation(self):
        with pytest.raises(Error, match="allowed set"):
            MyParam().init({"required_dim": 1, "act": "swish"})

    def test_bool_parsing(self):
        p = MyParam()
        p.init({"required_dim": 1, "use_bias": "false"})
        assert p.use_bias is False
        p.init({"use_bias": "1"})
        assert p.use_bias is True

    def test_setattr_validates(self):
        p = MyParam()
        with pytest.raises(Error):
            p.learning_rate = 2.0
        p.learning_rate = "0.5"
        assert p.learning_rate == 0.5

    def test_dict_fields_docs(self):
        p = MyParam(required_dim=3)
        d = p.to_dict()
        assert d["num_hidden"] == 100 and d["required_dim"] == 3
        assert "num_hidden" in MyParam.fields()
        doc = MyParam.doc_string()
        assert "hidden units" in doc and "default=100" in doc

    def test_update_dict(self):
        p = MyParam()
        cfg = {"required_dim": "7", "extra": "keepme"}
        p.update_dict(cfg)
        assert cfg["num_hidden"] == 100
        assert cfg["extra"] == "keepme"
        assert cfg["required_dim"] == 7

    def test_json_round_trip(self):
        p = MyParam(required_dim=9, act="gelu")
        text = p.save_json()
        q = MyParam()
        q.load_json(text)
        assert q == p

    def test_hashable_for_jit_static_arg(self):
        a = MyParam(required_dim=2)
        b = MyParam(required_dim=2)
        assert hash(a) == hash(b) and a == b

    def test_kwargs_ctor(self):
        p = MyParam(required_dim=5, num_hidden=10)
        assert p.num_hidden == 10


class TestGetEnv:
    def test_typed_env(self, monkeypatch):
        monkeypatch.setenv("DMLC_TEST_NUM", "32")
        assert get_env("DMLC_TEST_NUM", 4) == 32
        monkeypatch.setenv("DMLC_TEST_F", "0.5")
        assert get_env("DMLC_TEST_F", 1.0) == 0.5
        monkeypatch.setenv("DMLC_TEST_B", "true")
        assert get_env("DMLC_TEST_B", False) is True
        assert get_env("DMLC_TEST_ABSENT", "d") == "d"


class TestConfig:
    def test_basic_and_comments(self):
        cfg = Config("a = 1\n# comment\nb = hello # trailing\n\nc= \"x = 1\"\n")
        assert cfg["a"] == "1"
        assert cfg["b"] == "hello"
        assert cfg["c"] == "x = 1"

    def test_multi_value(self):
        text = "k = 1\nk = 2\n"
        assert Config(text).items() == [("k", "2")]
        assert Config(text, multi_value=True).items() == [("k", "1"), ("k", "2")]

    def test_errors(self):
        with pytest.raises(Error):
            Config("novalue\n")
        with pytest.raises(Error):
            Config("ok = 1\n")["missing"]


def test_split_getline_semantics():
    # dmlc::Split keeps interior empties, drops only trailing empty
    assert split("a,,b,", ",") == ["a", "", "b"]
    assert split("", ",") == []
    assert split("a", ",") == ["a"]


def test_param_hashable_with_list_field():
    class Q(Parameter):
        dims = field(list, default=())

    q = Q()
    q.init({"dims": "1, 2, 3"})
    assert q.dims == ["1", "2", "3"]  # items stripped
    hash(q)  # must not raise


def test_log_unknown_severity_raises_error():
    with pytest.raises(Error, match="severity"):
        LOG("TRACE", "x")


def test_get_env_unparseable_raises_error(monkeypatch):
    monkeypatch.setenv("DMLC_BAD", "notanint")
    with pytest.raises(Error, match="DMLC_BAD"):
        get_env("DMLC_BAD", 3)


class TestMemoryPool:
    def test_object_pool_reuses(self):
        from dmlc_core_tpu.utils.memory import MemoryPool

        made = []
        pool = MemoryPool(lambda: made.append(1) or {"v": 0},
                          reset=lambda o: o.update(v=0))
        a = pool.alloc()
        a["v"] = 7
        pool.free(a)
        b = pool.alloc()
        assert b is a and b["v"] == 0        # recycled + reset
        assert pool.allocated == 1 and len(made) == 1

    def test_max_free_bound(self):
        from dmlc_core_tpu.utils.memory import MemoryPool

        pool = MemoryPool(dict, max_free=1)
        x, y = pool.alloc(), pool.alloc()
        pool.free(x)
        pool.free(y)                          # dropped, over bound
        assert pool.free_count() == 1

    def test_buffer_pool_keyed_by_shape_dtype(self):
        import numpy as np
        from dmlc_core_tpu.utils.memory import BufferPool

        bp = BufferPool()
        a = bp.take((4, 3), np.float32)
        bp.give(a)
        b = bp.take((4, 3), np.float32)
        assert b is a
        c = bp.take((4, 3), np.int32)         # different dtype → fresh
        assert c is not a and c.dtype == np.int32


def test_param_doc_string():
    class D(Parameter):
        depth = field(int, default=3, lower_bound=1, upper_bound=10,
                      description="tree depth")
        act = field(str, default="relu", enum=["relu", "tanh"])

    doc = D.doc_string()
    assert "depth" in doc and "tree depth" in doc and ">=1" in doc
    assert "relu" in doc
