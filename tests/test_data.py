"""Tests for the data layer: RowBlock CSR, parsers (native + python paths),
row iterators.  Mirrors the reference's unittest_parser and the agaricus
smoke config (BASELINE.md config 0)."""

import os

import numpy as np
import pytest

from dmlc_core_tpu.base.logging import Error
from dmlc_core_tpu.data import Parser, RowBlock, RowBlockContainer, RowBlockIter
from dmlc_core_tpu.data import _native
from dmlc_core_tpu.data.parsers import parse_uri_spec
from dmlc_core_tpu.io import MemoryStringStream, TemporaryDirectory


def make_block():
    # rows: [0: (1, {0:1.0, 3:2.5}), 1: (0, {}), 2: (1, {1:-1})]
    return RowBlock(
        offset=[0, 2, 2, 3],
        label=[1, 0, 1],
        index=[0, 3, 1],
        value=[1.0, 2.5, -1.0],
    )


class TestRowBlock:
    def test_basic_shape(self):
        b = make_block()
        assert b.size == 3 and b.nnz == 3 and b.max_index == 3

    def test_row_view_and_sdot(self):
        b = make_block()
        r0 = b[0]
        assert r0.label == 1.0 and list(r0.index) == [0, 3]
        w = np.array([1.0, 10.0, 100.0, 1000.0], np.float32)
        assert r0.sdot(w) == pytest.approx(1.0 * 1 + 2.5 * 1000)
        assert b[1].sdot(w) == 0.0

    def test_value_none_means_ones(self):
        b = RowBlock(offset=[0, 2], label=[1], index=[1, 2])
        w = np.array([5.0, 7.0, 9.0], np.float32)
        assert b[0].sdot(w) == pytest.approx(16.0)

    def test_slice_zero_copy_offsets(self):
        b = make_block()
        s = b.slice(1, 3)
        assert s.size == 2 and s.nnz == 1
        assert list(s.offset) == [0, 0, 1]
        assert s[1].index.tolist() == [1]

    def test_to_dense(self):
        d = make_block().to_dense()
        expected = np.zeros((3, 4), np.float32)
        expected[0, 0], expected[0, 3], expected[2, 1] = 1.0, 2.5, -1.0
        np.testing.assert_array_equal(d, expected)

    def test_shape_validation(self):
        with pytest.raises(Error):
            RowBlock(offset=[0, 5], label=[1], index=[1, 2])


class TestRowBlockContainer:
    def test_push_and_to_block(self):
        c = RowBlockContainer()
        c.push(1.0, [0, 2], [1.0, 3.0])
        c.push(0.0, [], None)
        c.push(2.0, [5], [7.0], weight=0.5, qid=3)
        b = c.to_block()
        assert b.size == 3 and b.nnz == 3
        assert c.max_index == 5
        assert b.weight is not None and b.weight[2] == 0.5
        assert b.qid is not None and b.qid[2] == 3

    def test_save_load_round_trip(self):
        c = RowBlockContainer()
        rng = np.random.default_rng(1)
        for _ in range(50):
            n = int(rng.integers(0, 6))
            c.push(float(rng.normal()), rng.integers(0, 100, n), rng.normal(size=n))
        s = MemoryStringStream()
        c.save(s)
        s.seek(0)
        c2 = RowBlockContainer()
        assert c2.load(s)
        b1, b2 = c.to_block(), c2.to_block()
        np.testing.assert_array_equal(b1.offset, b2.offset)
        np.testing.assert_allclose(b1.label, b2.label)
        np.testing.assert_array_equal(b1.index, b2.index)
        np.testing.assert_allclose(b1.value, b2.value, rtol=1e-6)
        assert c2.max_index == c.max_index
        assert not c2.load(s)  # clean EOF

    def test_multi_page_stream(self):
        s = MemoryStringStream()
        for page in range(3):
            c = RowBlockContainer()
            c.push(float(page), [page], [1.0])
            c.save(s)
        s.seek(0)
        labels = []
        c = RowBlockContainer()
        while c.load(s):
            labels.append(float(c.to_block().label[0]))
        assert labels == [0.0, 1.0, 2.0]


AGARICUS = """1 3:1 9:1 19:1
0 1:0.5 13:1 27:1
0 3:1 7:1
1 9:1 19:2.5 101:1
"""

CSV_DATA = """1,0.5,2.25,3
0,1.5,0,4
1,0,0,5.5
"""

LIBFM = """1 0:3:1 1:9:0.5
0 0:1:1 2:7:2
"""


def test_parse_uri_spec():
    path, args, cache = parse_uri_spec("/a/b.csv?format=csv&label_column=2#/tmp/c.bin")
    assert path == "/a/b.csv" and args == {"format": "csv", "label_column": "2"}
    assert cache == "/tmp/c.bin"
    path, args, cache = parse_uri_spec("/plain/file")
    assert path == "/plain/file" and args == {} and cache is None


@pytest.fixture(params=["native", "python"])
def parse_mode(request, monkeypatch):
    if request.param == "native":
        if not _native.native_available():
            pytest.skip("native library not built")
    else:
        monkeypatch.setattr(_native, "native_available", lambda: False)
    return request.param


class TestParsers:
    def test_libsvm(self, parse_mode):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "a.libsvm")
            with open(path, "w") as f:
                f.write(AGARICUS)
            blocks = list(Parser.create(path, format="libsvm"))
            b = blocks[0] if len(blocks) == 1 else None
            assert b is not None
            assert b.size == 4
            np.testing.assert_allclose(b.label, [1, 0, 0, 1])
            assert b[0].index.tolist() == [3, 9, 19]
            assert b[1].value.tolist() == [0.5, 1.0, 1.0]
            assert b[3].value.tolist() == [1.0, 2.5, 1.0]
            assert b.max_index == 101

    def test_libsvm_qid(self, parse_mode):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "q.libsvm")
            with open(path, "w") as f:
                f.write("1 qid:7 1:1\n0 qid:8 2:1\n")
            b = next(iter(Parser.create(path, format="libsvm")))
            assert b.qid is not None and b.qid.tolist() == [7, 8]

    def test_csv(self, parse_mode):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "d.csv")
            with open(path, "w") as f:
                f.write(CSV_DATA)
            b = next(iter(Parser.create(path + "?format=csv")))
            assert b.size == 3
            np.testing.assert_allclose(b.label, [1, 0, 1])
            # 3 feature columns, zeros kept
            assert b.nnz == 9
            np.testing.assert_allclose(b[0].value, [0.5, 2.25, 3.0])
            assert b[2].index.tolist() == [0, 1, 2]

    def test_csv_label_weight_columns(self, parse_mode):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "d.csv")
            with open(path, "w") as f:
                f.write("5,1,0.25\n6,0,0.75\n")
            b = next(iter(Parser.create(path + "?format=csv&label_column=1&weight_column=2")))
            np.testing.assert_allclose(b.label, [1, 0])
            np.testing.assert_allclose(b.weight, [0.25, 0.75])
            np.testing.assert_allclose(b[0].value, [5.0])

    def test_libfm(self, parse_mode):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "d.libfm")
            with open(path, "w") as f:
                f.write(LIBFM)
            b = next(iter(Parser.create(path, format="libfm")))
            assert b.field is not None
            assert b.field.tolist() == [0, 1, 0, 2]
            assert b.index.tolist() == [3, 9, 1, 7]
            np.testing.assert_allclose(b.value, [1, 0.5, 1, 2])

    def test_parse_error_surfaces(self, parse_mode):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "bad.libsvm")
            with open(path, "w") as f:
                f.write("notanumber 1:1\n")
            with pytest.raises(Error):
                list(Parser.create(path, format="libsvm"))

    def test_sharded_parse_coverage(self, parse_mode):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "big.libsvm")
            with open(path, "w") as f:
                for i in range(500):
                    f.write(f"{i % 2} {i % 50}:{i * 0.5} {50 + i % 30}:1\n")
            labels = []
            for part in range(4):
                for block in Parser.create(path, part, 4, "libsvm"):
                    labels.extend(block.label.tolist())
            assert len(labels) == 500

    def test_plus_signed_labels_and_empty_value(self, parse_mode):
        # canonical LibSVM '+1' labels and 'idx:' empty values
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "p.libsvm")
            with open(path, "w") as f:
                f.write("+1 3:+2.5 7:\n-1 2:1\n")
            b = next(iter(Parser.create(path, format="libsvm")))
            np.testing.assert_allclose(b.label, [1, -1])
            np.testing.assert_allclose(b.value, [2.5, 1.0, 1.0])

    def test_weight_column_presence_survives_cache(self):
        # schema presence (all-1.0 weights) must survive container round trip
        c = RowBlockContainer()
        c.push(1.0, [0], [1.0], weight=1.0)
        c.push(0.0, [1], [2.0], weight=1.0)
        s = MemoryStringStream()
        c.save(s)
        s.seek(0)
        c2 = RowBlockContainer()
        assert c2.load(s)
        assert c2.to_block().weight is not None

    def test_native_matches_python(self):
        if not _native.native_available():
            pytest.skip("native library not built")
        from dmlc_core_tpu.data.parsers import _py_parse_libsvm

        chunk = AGARICUS.encode()
        a = _native.parse_libsvm(chunk)
        b = _py_parse_libsvm(chunk)
        np.testing.assert_array_equal(a["offset"], b["offset"])
        np.testing.assert_allclose(a["label"], b["label"])
        np.testing.assert_array_equal(a["index"], b["index"])
        np.testing.assert_allclose(a["value"], b["value"])


class TestRowBlockIter:
    def _write_libsvm(self, path, n=200):
        with open(path, "w") as f:
            for i in range(n):
                f.write(f"{i % 2} {i % 10}:1 {10 + i % 5}:{i * 0.25}\n")

    def test_basic_iter(self):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "d.libsvm")
            self._write_libsvm(path)
            it = RowBlockIter.create(path, format="libsvm")
            blocks = list(it)
            assert sum(b.size for b in blocks) == 200
            assert it.num_col == 15
            # rewind works
            assert sum(b.size for b in it) == 200

    def test_disk_iter_pages_and_rewind(self):
        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "d.libsvm")
            self._write_libsvm(path, n=300)
            cache = os.path.join(tmp.path, "cache.bin")
            it = RowBlockIter.create(f"{path}#{cache}", format="libsvm")
            # force small pages for multi-page coverage
            assert os.path.exists(cache)
            total1 = sum(b.size for b in it)
            total2 = sum(b.size for b in it)
            assert total1 == total2 == 300
            assert it.num_col == 15
            it.close()

    def test_disk_iter_multi_page(self):
        from dmlc_core_tpu.data.iter import DiskRowIter

        with TemporaryDirectory() as tmp:
            path = os.path.join(tmp.path, "d.libsvm")
            self._write_libsvm(path, n=500)
            cache = os.path.join(tmp.path, "c.bin")
            parser = Parser.create(path, format="libsvm")
            parser.hint_chunk_size(4096)
            it = DiskRowIter(parser, cache, page_bytes=1024)
            assert it._num_pages > 1
            assert sum(b.size for b in it) == 500
            it.close()
