"""Factorization-machine tests (models/fm.py) — the LibFM consumer.

Oracles: the margin formula vs a naive pairwise-interaction loop; a
synthetic rank-2 interaction dataset the FM must fit far better than a
linear model can; end-to-end from a .libfm file through the parser /
RowBlockIter path; 8-device-mesh vs 1-device equivalence."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dmlc_core_tpu.models.fm import FM, _fm_margin


def _pairwise_oracle(params, X):
    """Naive O(F²) FM margin."""
    w0 = float(params["w0"])
    w = np.asarray(params["w"])
    v = np.asarray(params["v"])
    out = []
    for x in X:
        s = w0 + float(x @ w)
        F = len(x)
        for i in range(F):
            for j in range(i + 1, F):
                s += float(v[i] @ v[j]) * x[i] * x[j]
        out.append(s)
    return np.asarray(out, np.float32)


def _interaction_data(rng, n=4000, F=8):
    X = rng.normal(size=(n, F)).astype(np.float32)
    # purely pairwise signal: no linear model can fit it
    margin = 1.5 * X[:, 0] * X[:, 1] - 2.0 * X[:, 2] * X[:, 3]
    y = (margin > 0).astype(np.float32)
    return X, y, margin


class TestFMMargin:
    def test_identity_matches_pairwise_loop(self, rng):
        F, K = 6, 3
        params = {
            "w0": jnp.asarray(0.3, jnp.float32),
            "w": jnp.asarray(rng.normal(size=F).astype(np.float32)),
            "v": jnp.asarray(rng.normal(size=(F, K)).astype(np.float32)),
        }
        X = rng.normal(size=(20, F)).astype(np.float32)
        got = np.asarray(_fm_margin(params, jnp.asarray(X)))
        want = _pairwise_oracle(params, X)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-4)


class TestFMTraining:
    def test_learns_pairwise_interactions(self, rng):
        X, y, _ = _interaction_data(rng)
        m = FM(n_factors=8, n_epochs=30, learning_rate=0.1,
               batch_size=2048)
        m.fit(X, y)
        acc = float(((m.predict(X) > 0.5) == (y > 0.5)).mean())
        assert acc > 0.9, acc
        # a linear-only FM (k tiny + zero init keeps v ≈ 0 useless)
        lin = FM(n_factors=1, init_scale=0.0, n_epochs=30,
                 learning_rate=0.1, batch_size=2048)
        lin.fit(X, y)
        acc_lin = float(((lin.predict(X) > 0.5) == (y > 0.5)).mean())
        assert acc_lin < 0.6, acc_lin          # interactions were the signal

    def test_save_load_roundtrip(self, rng, tmp_path):
        """Checkpoint restores weights AND Adam state: the reloaded
        model predicts identically and continues training the exact
        trajectory (step count preserved, no bias-correction reset)."""
        X = rng.normal(size=(512, 6)).astype(np.float32)
        y = (X[:, 0] * X[:, 1] > 0).astype(np.float32)
        m = FM(n_factors=4, n_epochs=2, seed=0)
        m.fit(X, y)
        uri = str(tmp_path / "fm.ckpt")
        m.save_model(uri)
        m2 = FM.load_model(uri)
        np.testing.assert_allclose(m2.predict(X), m.predict(X), rtol=1e-6)
        m.fit(X, y)        # continue both one more round
        m2.fit(X, y)
        np.testing.assert_allclose(m2.predict(X), m.predict(X), rtol=1e-5)

    def test_regression_objective(self, rng):
        X, _, margin = _interaction_data(rng, n=3000)
        m = FM(objective="reg:squarederror", n_factors=8, n_epochs=40,
               learning_rate=0.1, batch_size=1024)
        m.fit(X, margin.astype(np.float32))
        pred = m.predict(X)
        resid = np.mean((pred - margin) ** 2) / np.mean(margin ** 2)
        assert resid < 0.1, resid

    def test_mesh_matches_single_device(self, rng):
        X, y, _ = _interaction_data(rng, n=1024)
        kw = dict(n_factors=4, n_epochs=3, batch_size=256, seed=3)
        m8 = FM(**kw)                       # conftest: 8-device mesh
        m8.fit(X, y)
        m1 = FM(mesh=Mesh(np.asarray(jax.devices()[:1]), ("data",)), **kw)
        m1.fit(X, y)
        # identical batching/seeds → identical parameters up to psum order
        np.testing.assert_allclose(np.asarray(m8.params["v"]),
                                   np.asarray(m1.params["v"]),
                                   rtol=1e-3, atol=1e-4)

    def test_libfm_file_end_to_end(self, rng, tmp_path):
        from dmlc_core_tpu.data.iter import RowBlockIter

        X, y, _ = _interaction_data(rng, n=2000, F=5)
        path = tmp_path / "train.libfm"
        with open(path, "w") as f:
            for i in range(len(X)):
                feats = " ".join(f"{j % 3}:{j}:{X[i, j]:.5f}"
                                 for j in range(X.shape[1]))
                f.write(f"{y[i]:.0f} {feats}\n")
        m = FM(n_factors=6, n_epochs=25, learning_rate=0.1,
               batch_size=1024)
        it = RowBlockIter.create(str(path), 0, 1, "libfm")
        m.fit_iter(it, num_col=5)
        it.close()
        acc = float(((m.predict(X) > 0.5) == (y > 0.5)).mean())
        assert acc > 0.85, acc
