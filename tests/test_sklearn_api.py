"""sklearn-style estimator wrappers (XGBClassifier-family analog).

Oracles: accuracy/R2/ndcg on learnable synthetics for both boosters;
label-code round-trips with non-contiguous class labels; param
round-trip; composition with a real sklearn Pipeline + GridSearchCV
(sklearn is in the image)."""

import numpy as np
import pytest

from dmlc_core_tpu.models.sklearn import (GBTClassifier, GBTRanker,
                                          GBTRegressor)


def _cls_data(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0)
    return X, y


class TestClassifier:
    def test_eval_set_early_stopping_with_string_labels(self):
        """XGBClassifier semantics: eval_set labels are encoded with the
        SAME class mapping as y — string labels + early stopping must
        work end to end, and unknown eval classes must fail loudly."""
        X, yb = _cls_data(n=1500)
        y = np.where(yb, "pos", "neg")
        Xv, ybv = _cls_data(n=500, seed=3)
        yv = np.where(ybv, "pos", "neg")
        # n_bins=64: what's under test is label encoding + early
        # stopping, not bin resolution — the smaller program compiles
        # ~4x faster on the 1-core CI host (256-bin default coverage
        # lives in the other classifier tests)
        est = GBTClassifier(n_estimators=60, max_depth=3,
                            learning_rate=0.4, n_bins=64)
        # XGBClassifier's list-of-pairs form (early stopping watches
        # the last pair); the bare-tuple form is covered below
        est.fit(X, y, eval_set=[(Xv, yv)], early_stopping_rounds=5)
        assert est.model.best_iteration is not None
        assert est.model.best_score is not None
        acc = (est.predict(Xv) == yv).mean()
        assert acc > 0.9, acc
        est2 = GBTClassifier(n_estimators=20, max_depth=3,
                             learning_rate=0.4, n_bins=64)
        est2.fit(X, y, eval_set=(Xv, yv))     # bare-tuple form
        assert est2.model.best_score is not None
        bad = np.where(ybv, "pos", "UNSEEN")
        with pytest.raises(Exception, match="classes not present"):
            GBTClassifier(n_estimators=5).fit(X, y, eval_set=(Xv, bad))

    def test_feature_importances_and_apply(self):
        """sklearn-ensemble surface: normalized feature_importances_
        (gain) and apply() leaf embeddings; gblinear falls back to |w|
        importances and rejects apply()."""
        X, yb = _cls_data(n=1500)
        est = GBTClassifier(n_estimators=15, max_depth=3).fit(X, yb)
        imp = est.feature_importances_
        assert imp.shape == (X.shape[1],)
        assert abs(float(imp.sum()) - 1.0) < 1e-5
        # the informative features (0, 1, 2 drive the label via
        # X0 + 0.5·X1·X2) dominate the pure-noise tail
        assert imp[:3].sum() > imp[3:].sum()
        leaves = est.apply(X[:64])
        assert leaves.shape == (64, 15)
        assert leaves.max() < 2 ** 3
        lin = GBTClassifier(booster="gblinear", n_estimators=20).fit(X, yb)
        limp = lin.feature_importances_
        assert limp.shape == (X.shape[1],)
        assert abs(float(limp.sum()) - 1.0) < 1e-5
        with pytest.raises(Exception, match="gbtree"):
            lin.apply(X[:4])

    @pytest.mark.parametrize("booster", ["gbtree", "gblinear"])
    @pytest.mark.slow
    def test_binary_with_string_ish_labels(self, booster):
        X, yb = _cls_data()
        y = np.where(yb, "pos", "neg")        # non-numeric labels
        clf = GBTClassifier(booster=booster, n_estimators=40, max_depth=4)
        clf.fit(X, y)
        assert set(np.unique(clf.predict(X))) <= {"pos", "neg"}
        assert clf.score(X, y) > (0.93 if booster == "gbtree" else 0.80)
        proba = clf.predict_proba(X)
        assert proba.shape == (len(X), 2)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)

    @pytest.mark.slow
    def test_multiclass_noncontiguous_labels(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(1500, 5)).astype(np.float32)
        y = np.select([X[:, 0] > 0.5, X[:, 0] < -0.5], [7, 3], default=42)
        clf = GBTClassifier(n_estimators=20, max_depth=3)
        clf.fit(X, y)
        assert sorted(clf.classes_) == [3, 7, 42]
        assert clf.score(X, y) > 0.95
        assert set(np.unique(clf.predict(X))) <= {3, 7, 42}

    def test_set_params_invalid_booster_rejected_at_fit(self):
        # set_params (e.g. a GridSearchCV grid) bypasses __init__; a
        # typo'd booster must fail loudly at fit, not silently train
        # the wrong model family
        from dmlc_core_tpu.base.logging import Error

        X, y = _cls_data(n=64)
        clf = GBTClassifier(n_estimators=2).set_params(booster="dart")
        with pytest.raises(Error, match="gbtree|gblinear"):
            clf.fit(X, y)

    def test_param_roundtrip(self):
        clf = GBTClassifier(n_estimators=7, gamma=0.5)
        params = clf.get_params()
        assert params["n_estimators"] == 7 and params["gamma"] == 0.5
        clf.set_params(n_estimators=9, gamma=0.1)
        assert clf.get_params()["n_estimators"] == 9
        assert clf.get_params()["gamma"] == 0.1


class TestRegressor:
    @pytest.mark.parametrize("booster", ["gbtree", "gblinear"])
    @pytest.mark.slow
    def test_r2(self, booster):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(2000, 5)).astype(np.float32)
        y = 2 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=2000)
        reg = GBTRegressor(booster=booster, n_estimators=80)
        reg.fit(X, y)
        assert reg.score(X, y) > 0.95


class TestRanker:
    @pytest.mark.slow
    def test_ndcg(self):
        rng = np.random.default_rng(3)
        w = rng.normal(size=5)
        Xs, ys, qs = [], [], []
        for q in range(200):
            nd = int(rng.integers(6, 14))
            Xq = rng.normal(size=(nd, 5)).astype(np.float32)
            rel = np.zeros(nd, np.float32)
            rel[np.argmax(Xq @ w)] = 2.0
            Xs.append(Xq)
            ys.append(rel)
            qs.append(np.full(nd, q))
        X, y, qid = (np.concatenate(Xs), np.concatenate(ys),
                     np.concatenate(qs))
        rk = GBTRanker(n_estimators=40, max_depth=3, learning_rate=0.3)
        rk.fit(X, y, qid=qid)
        assert rk.score(X, y, qid=qid, k=5) > 0.85


class TestWrapperCheckpoint:
    def test_save_model_passthrough(self, tmp_path):
        """wrapper.save_model writes the native booster's checkpoint;
        the native load_model reads it back and predicts identically."""
        from dmlc_core_tpu.models import HistGBT

        X, yb = _cls_data(n=400)
        clf = GBTClassifier(n_estimators=5, max_depth=3)
        clf.fit(X, yb.astype(int))
        uri = str(tmp_path / "wrapped.ckpt")
        clf.save_model(uri)
        native = HistGBT.load_model(uri)
        np.testing.assert_allclose(
            native.predict(X, output_margin=True),
            clf.model.predict(X, output_margin=True), rtol=1e-6)


class TestSklearnComposition:
    @pytest.mark.slow
    def test_pipeline_and_grid_search(self):
        sklearn = pytest.importorskip("sklearn")  # noqa: F841
        from sklearn.model_selection import GridSearchCV
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        X, y = _cls_data(n=800)
        pipe = Pipeline([
            ("scale", StandardScaler()),
            ("gbt", GBTClassifier(n_estimators=15, max_depth=3)),
        ])
        pipe.fit(X, y)
        assert pipe.score(X, y) > 0.9
        gs = GridSearchCV(
            GBTClassifier(n_estimators=10, max_depth=3),
            {"max_depth": [2, 3]}, cv=2, scoring="accuracy")
        gs.fit(X, y)
        assert gs.best_params_["max_depth"] in (2, 3)


class TestEvalsResult:
    def test_xgboost_shaped_curve(self):
        X, yb = _cls_data(n=1200)
        Xv, ybv = _cls_data(n=400, seed=9)
        # 60 estimators -> 3 dispatch chunks -> a 3-point curve
        est = GBTClassifier(n_estimators=60, max_depth=3, n_bins=32,
                            eval_metric="logloss")
        est.fit(X, yb, eval_set=(Xv, ybv))
        res = est.evals_result()
        curve = res["validation_0"]["logloss"]
        assert len(curve) >= 3
        # logloss on a learnable problem must improve over the fit
        assert curve[-1] < curve[0]
        # x-axis rounds are recorded on the native model
        rounds = [r for r, _ in est.model.eval_history]
        assert rounds == sorted(rounds) and rounds[-1] <= 60
        # XGBoost list form: the WATCHED (last) pair keeps its position
        # as the key — validation_1 here, and validation_0 is a loud
        # KeyError rather than silently serving the wrong curve
        est2 = GBTClassifier(n_estimators=30, max_depth=3, n_bins=32,
                             eval_metric="logloss")
        est2.fit(X, yb, eval_set=[(X, yb), (Xv, ybv)])
        res2 = est2.evals_result()
        assert list(res2) == ["validation_1"]

    def test_requires_eval_set(self):
        import pytest
        from dmlc_core_tpu.base.logging import Error

        X, yb = _cls_data(n=600)
        est = GBTClassifier(n_estimators=3, max_depth=2, n_bins=16)
        est.fit(X, yb)
        with pytest.raises(Error):
            est.evals_result()

    def test_regressor_eval_set_list_form(self):
        rng = np.random.default_rng(11)
        X = rng.normal(size=(1200, 5)).astype(np.float32)
        y = (2 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=1200)).astype(
            np.float32)
        reg = GBTRegressor(n_estimators=60, max_depth=3, n_bins=32,
                           eval_metric="rmse")
        reg.fit(X[:900], y[:900], eval_set=[(X[:900], y[:900]),
                                            (X[900:], y[900:])],
                early_stopping_rounds=10)
        res = reg.evals_result()
        assert list(res) == ["validation_1"]
        curve = res["validation_1"]["rmse"]
        assert len(curve) >= 2 and curve[-1] <= curve[0]

    def test_bare_pair_spelled_as_list(self):
        """eval_set=[Xv, yv] (a single pair spelled as a list) must be
        treated as one pair, not misread as a two-pair list."""
        X, yb = _cls_data(n=800)
        Xv, ybv = _cls_data(n=200, seed=4)
        est = GBTClassifier(n_estimators=5, max_depth=2, n_bins=16)
        est.fit(X, yb, eval_set=[Xv, ybv])
        assert list(est.evals_result()) == ["validation_0"]


class TestScipySparseInput:
    """XGBClassifier/XGBRegressor accept scipy.sparse X; the wrappers
    route it to SparseHistGBT (absent ≡ missing — XGBoost's sparse
    DMatrix semantics, NOT densify-to-zero)."""

    def _csr_problem(self, n=500, F=60, seed=0):
        import scipy.sparse as sp
        rng = np.random.default_rng(seed)
        mask = rng.random((n, F)) < 0.15
        mask[:, 0] |= rng.random(n) < 0.5
        vals = rng.normal(size=(n, F)).astype(np.float32)
        y = (np.where(mask[:, 0], vals[:, 0], -0.5) > 0).astype(int)
        X = sp.csr_matrix(np.where(mask, vals, 0.0))
        return X, y

    def test_classifier_sparse_fit_predict(self):
        from dmlc_core_tpu.models.histgbt_sparse import SparseHistGBT
        X, y = self._csr_problem()
        clf = GBTClassifier(n_estimators=15, max_depth=3, n_bins=16,
                            learning_rate=0.4)
        clf.fit(X, y)
        assert isinstance(clf.model, SparseHistGBT)
        assert (clf.predict(X) == y).mean() > 0.9
        proba = clf.predict_proba(X)
        assert proba.shape == (X.shape[0], 2)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
        imp = clf.feature_importances_
        assert imp.shape == (X.shape[1],)
        assert imp.argmax() == 0          # the signal feature dominates

    def test_regressor_sparse(self):
        import scipy.sparse as sp
        rng = np.random.default_rng(3)
        X, _ = self._csr_problem(seed=3)
        d = np.asarray(X.todense())
        target = (np.where(d[:, 0] != 0, d[:, 0], -1.0)).astype(np.float32)
        reg = GBTRegressor(n_estimators=25, max_depth=3, n_bins=32,
                           learning_rate=0.3)
        reg.fit(X, target)
        pred = reg.predict(X)
        rmse = float(np.sqrt(np.mean((pred - target) ** 2)))
        assert rmse < 0.45 * target.std()

    def test_dense_model_rejects_sparse_predict(self):
        import scipy.sparse as sp
        from dmlc_core_tpu.base.logging import Error
        rng = np.random.default_rng(9)
        Xd = rng.normal(size=(200, 8)).astype(np.float32)
        yd = (Xd[:, 0] > 0).astype(int)
        clf = GBTClassifier(n_estimators=4, max_depth=2, n_bins=16)
        clf.fit(Xd, yd)
        with pytest.raises(Error, match="densify"):
            clf.predict(sp.csr_matrix(Xd))

    def test_sparse_model_rejects_dense_predict(self):
        from dmlc_core_tpu.base.logging import Error
        X, y = self._csr_problem(seed=5)
        clf = GBTClassifier(n_estimators=4, max_depth=2, n_bins=16)
        clf.fit(X, y)
        with pytest.raises(Error, match="sparse"):
            clf.predict(np.asarray(X.todense()))
        with pytest.raises(Error, match="sparse"):
            clf.apply(X)

    def test_sparse_rejections(self):
        from dmlc_core_tpu.base.logging import Error
        X, y = self._csr_problem(seed=7)
        y3 = y.copy()
        y3[:5] = 2
        with pytest.raises(Error, match="binary"):
            GBTClassifier(n_estimators=2).fit(X, y3)
        with pytest.raises(Error, match="eval_set|does not support"):
            GBTClassifier(n_estimators=2).fit(
                X, y, eval_set=(np.zeros((2, 60)), np.zeros(2)))
        with pytest.raises(Error, match="tree booster"):
            GBTClassifier(booster="gblinear", n_estimators=2).fit(X, y)

    def test_duplicates_summed_by_canonicalization(self):
        import scipy.sparse as sp
        # COO with duplicate (row, col) entries: scipy keeps them until
        # sum_duplicates; the wrapper canonicalizes so the sparse
        # engine's no-duplicate contract holds
        rows = np.array([0, 0, 1, 1, 1])
        cols = np.array([0, 0, 1, 1, 2])
        vals = np.array([1.0, 2.0, 0.5, 0.5, 3.0], np.float32)
        X = sp.coo_matrix((vals, (rows, cols)), shape=(2, 3))
        y = np.array([0, 1])
        clf = GBTClassifier(n_estimators=1, max_depth=1, n_bins=4)
        clf.fit(X, y)                      # must not raise
        assert clf.predict(X.tocsr()).shape == (2,)
