"""Packed narrow bins (int4) + exclusive feature bundling: layout unit
tests and the bit-parity contracts of ISSUE 12.

The seed's eps-bumped quantile sketch SPREADS a low-cardinality
feature's bin ids across [0, n_bins) — a 3-valued feature lands at e.g.
{0, 11, 22} — so the layout compact-remaps occupied ids to dense
[0, count).  The parity oracle: the remap only RELABELS histogram
cells, so after ``unbundle_hist`` scatters them back to original
positions, every histogram method must reproduce the plain build
bit-for-bit (gradients chosen bf16-exact so even the MXU methods'
reduction-order differences cannot produce last-ulp drift).
"""

import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.models import HistGBT  # noqa: E402
from dmlc_core_tpu.ops import binlayout as bl  # noqa: E402
from dmlc_core_tpu.ops.histogram import (build_histogram,  # noqa: E402
                                         hist_psum_bytes_per_round,
                                         select_feature_bins)


def _spread_bins(rng, n, F, B, narrow=()):
    """[F, n] bin matrix mimicking the eps-bumped sketch: narrow
    features occupy FEW, SPREAD-OUT ids (not a dense prefix); wide
    features cover every bin deterministically."""
    bins = np.zeros((F, n), np.uint8)
    for f in range(F):
        if f in narrow:
            k = int(rng.integers(2, 7))
            ids = np.sort(rng.choice(B, size=k, replace=False))
            bins[f] = ids[rng.integers(0, k, n)]
        else:
            bins[f] = (np.arange(n) + f) % B
    return bins


def _counts(bins, B):
    return bl.bin_counts(jnp.asarray(bins), B)


class TestLayout:
    def test_all_wide_is_trivial(self, rng):
        bins = _spread_bins(rng, 500, 4, 32, narrow=())
        assert bl.compute_layout(_counts(bins, 32), 4, 32) is None

    def test_pack_off_is_trivial(self, rng):
        bins = _spread_bins(rng, 500, 6, 32, narrow=(1, 3, 5))
        assert bl.compute_layout(_counts(bins, 32), 6, 32,
                                 pack=False) is None

    def test_narrow_features_pair(self, rng):
        bins = _spread_bins(rng, 500, 9, 32, narrow=(1, 4, 7, 8))
        lay = bl.compute_layout(_counts(bins, 32), 9, 32)
        assert lay is not None
        assert len(lay.pairs) == 2 and lay.storage_features == 9
        assert lay.sync_bins == 32            # wide features keep width
        # every narrow feature carries a compact remap of its used ids
        for f in (1, 4, 7, 8):
            occ = lay.bin_maps[f]
            assert occ is not None and len(occ) <= bl.PACK_WIDTH
            assert set(occ) == set(np.unique(bins[f]))

    def test_counts_mask_padding_rows(self, rng):
        bins = _spread_bins(rng, 500, 3, 32, narrow=(1,))
        padded = np.concatenate([bins, np.zeros((3, 36), np.uint8)], axis=1)
        c_real = bl.bin_counts(jnp.asarray(bins), 32)
        c_mask = bl.bin_counts(jnp.asarray(padded), 32, n_valid=500)
        assert np.array_equal(c_real, c_mask)

    def test_select_bins_roundtrip(self, rng):
        bins = _spread_bins(rng, 603, 9, 32, narrow=(1, 4, 7, 8))
        lay = bl.compute_layout(_counts(bins, 32), 9, 32)
        phys = bl.pack_matrix(jnp.asarray(bins), lay)
        assert phys.shape[0] == lay.phys_rows
        for f in range(9):
            sel = jnp.full(603, f, jnp.int32)
            got = np.asarray(select_feature_bins(phys, sel, layout=lay))
            assert np.array_equal(got, bins[f]), f

    def test_psum_model_shrinks_with_layout(self, rng):
        bins = _spread_bins(rng, 500, 8, 32, narrow=(0, 1, 2, 3, 4, 5))
        lay = bl.compute_layout(_counts(bins, 32), 8, 32)
        base = hist_psum_bytes_per_round(3, 8, 32)
        packed = hist_psum_bytes_per_round(3, 8, 32, layout=lay)
        assert packed == base                  # S and Bs unchanged: 8, 32
        # lossguide builds one node per expansion instead of 2^(l-1)
        lg = hist_psum_bytes_per_round(6, 8, 32, grow_policy="lossguide",
                                       max_leaves=8)
        assert lg == 8 * 2 * 8 * 32 * 4
        assert lg < hist_psum_bytes_per_round(6, 8, 32)


class TestPackedParity:
    @pytest.mark.parametrize("method", ["segment", "matmul", "pallas"])
    def test_bit_parity_vs_plain(self, method, rng):
        n, F, B, N = 1021, 9, 32, 3            # odd row count on purpose
        bins = _spread_bins(rng, n, F, B, narrow=(1, 4, 7, 8))
        node = rng.integers(0, N, n).astype(np.int32)
        node[::7] = -1                         # padding rows drop out
        # bf16-exact gradients: sums are exact in f32, so ANY
        # reduction order must reproduce them bit-for-bit
        g = rng.choice([-1.0, -0.5, 0.5, 1.0], n).astype(np.float32)
        h = rng.choice([0.5, 1.0], n).astype(np.float32)
        plain = np.asarray(build_histogram(
            jnp.asarray(bins), jnp.asarray(node), jnp.asarray(g),
            jnp.asarray(h), N, B, method, transposed=True))
        lay = bl.compute_layout(_counts(bins, B), F, B)
        phys = bl.pack_matrix(jnp.asarray(bins), lay)
        hs = build_histogram(phys, jnp.asarray(node), jnp.asarray(g),
                             jnp.asarray(h), N, B, method,
                             transposed=True, layout=lay)
        got = np.asarray(bl.unbundle_hist(hs, lay, B))
        assert got.shape == plain.shape
        assert np.array_equal(got, plain), method


class TestBundling:
    def _exclusive_bins(self, rng, n, B=32):
        """Two near-one-hot features whose DEFAULT bin is NOT 0 (the
        quantile sketch maps the common value wherever it likes) plus a
        wide feature; the one-hots never fire on the same row."""
        bins = np.zeros((3, n), np.uint8)
        bins[0] = np.arange(n) % B
        onehot = rng.integers(0, 3, n)
        bins[1] = np.where(onehot == 1, 20, 5)
        bins[2] = np.where(onehot == 2, 25, 7)
        return bins

    def test_detect_and_exact_roundtrip(self, rng):
        n, B = 1021, 32
        bins = self._exclusive_bins(rng, n, B)
        counts = _counts(bins, B)
        bundles = bl.detect_bundles(bins, np.asarray(counts), B)
        assert bundles == ((1, 2),)
        lay = bl.compute_layout(counts, 3, B, pack=False, bundles=bundles)
        assert lay is not None and lay.has_bundles
        assert lay.storage_features == 2       # 3 features -> 2 rows
        # default (most frequent) bin leads each member's compact map
        assert lay.bin_maps[1][0] == 5 and lay.bin_maps[2][0] == 7
        # decode round-trip through the fused row
        phys = bl.pack_matrix(jnp.asarray(bins), lay)
        for f in range(3):
            sel = jnp.full(n, f, jnp.int32)
            got = np.asarray(bl.select_bins(phys, sel, lay))
            assert np.array_equal(got, bins[f]), f

    def test_bundle_hist_parity(self, rng):
        n, B, N = 1021, 32, 2
        bins = self._exclusive_bins(rng, n, B)
        node = rng.integers(0, N, n).astype(np.int32)
        g = rng.choice([-1.0, -0.5, 0.5, 1.0], n).astype(np.float32)
        h = rng.choice([0.5, 1.0], n).astype(np.float32)
        counts = _counts(bins, B)
        bundles = bl.detect_bundles(bins, np.asarray(counts), B)
        lay = bl.compute_layout(counts, 3, B, pack=False, bundles=bundles)
        plain = np.asarray(build_histogram(
            jnp.asarray(bins), jnp.asarray(node), jnp.asarray(g),
            jnp.asarray(h), N, B, "segment", transposed=True))
        hs = build_histogram(bl.pack_matrix(jnp.asarray(bins), lay),
                             jnp.asarray(node), jnp.asarray(g),
                             jnp.asarray(h), N, B, "segment",
                             transposed=True, layout=lay)
        got = np.asarray(bl.unbundle_hist(hs, lay, B))
        # bf16-exact gradients make even the tot − Σsegment default-bin
        # reconstruction exact (sums of halves are exact f32)
        assert np.array_equal(got, plain)

    def test_conflicting_features_not_bundled(self, rng):
        n, B = 800, 32
        bins = np.zeros((2, n), np.uint8)
        bins[0] = np.where(rng.random(n) < 0.3, 20, 5)
        bins[1] = np.where(rng.random(n) < 0.3, 25, 7)   # overlaps feat 0
        counts = _counts(bins, B)
        assert bl.detect_bundles(bins, np.asarray(counts), B) == ()


def _narrow_xy(n=1503, F=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[:, 1] = rng.integers(0, 3, n)
    X[:, 3] = rng.integers(0, 2, n)
    X[:, 5] = rng.integers(0, 5, n)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 3]) > 0).astype(np.float32)
    return X, y


MODEL_KW = dict(n_trees=3, max_depth=3, n_bins=32,
                objective="binary:logistic", learning_rate=0.3)


class TestModelParity:
    def test_pack_on_off_byte_parity(self, tmp_path, monkeypatch):
        X, y = _narrow_xy()
        m0 = HistGBT(**MODEL_KW)
        m0.fit(X, y)
        monkeypatch.setenv("DMLC_BIN_PACK", "1")
        m1 = HistGBT(**MODEL_KW)
        m1.fit(X, y)
        assert m1._bin_layout is not None      # the lever actually fired
        u0, u1 = str(tmp_path / "a.ubj"), str(tmp_path / "b.ubj")
        m0.save_model(u0)
        m1.save_model(u1)
        assert open(u0, "rb").read() == open(u1, "rb").read()

    def test_no_bundle_fires_byte_parity(self, tmp_path, monkeypatch):
        # dense gaussian features: nothing is exclusive, bundling must
        # decline and leave the seed path byte-identical
        rng = np.random.default_rng(3)
        X = rng.normal(size=(900, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        m0 = HistGBT(**MODEL_KW)
        m0.fit(X, y)
        monkeypatch.setenv("DMLC_FEATURE_BUNDLE", "1")
        m1 = HistGBT(**MODEL_KW)
        m1.fit(X, y)
        assert m1._bin_layout is None
        u0, u1 = str(tmp_path / "a.ubj"), str(tmp_path / "b.ubj")
        m0.save_model(u0)
        m1.save_model(u1)
        assert open(u0, "rb").read() == open(u1, "rb").read()

    def test_bundle_fires_same_structure(self, monkeypatch):
        rng = np.random.default_rng(4)
        n = 1404
        X = rng.normal(size=(n, 5)).astype(np.float32)
        onehot = rng.integers(0, 3, n)
        X[:, 2] = (onehot == 1).astype(np.float32)
        X[:, 3] = (onehot == 2).astype(np.float32)
        y = ((X[:, 0] + X[:, 2] - X[:, 3]) > 0).astype(np.float32)
        m0 = HistGBT(**MODEL_KW)
        m0.fit(X, y)
        monkeypatch.setenv("DMLC_FEATURE_BUNDLE", "1")
        m1 = HistGBT(**MODEL_KW)
        m1.fit(X, y)
        assert m1._bin_layout is not None and m1._bin_layout.has_bundles
        for t0, t1 in zip(m0.trees, m1.trees):
            assert np.array_equal(t0["feat"], t1["feat"])
            assert np.array_equal(t0["thr"], t1["thr"])
        np.testing.assert_allclose(m0.predict(X), m1.predict(X),
                                   rtol=1e-5, atol=1e-6)
