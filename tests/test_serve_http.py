"""HTTP serving end-to-end: train → checkpoint → registry load →
concurrent ``/predict`` bit-identical to direct ``model.predict``,
versioned hot-swap with zero dropped in-flight requests, ``/metrics``
exposition, admission control, and a slow-marked multi-client soak."""

import json
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.serve import (ModelRegistry, ServeFrontend,
                                 checkpoint_model)

F = 6


def _make_data(n, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


def _fit(n_trees, X, y):
    return HistGBT(n_trees=n_trees, max_depth=3, n_bins=16).fit(X, y)


def _post(url, rows, timeout=30):
    body = json.dumps({"rows": np.asarray(rows).tolist()}).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _get(url, path, timeout=10):
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _client_loop(url, X, direct_by_version, out, stop, seed):
    """Issue random-size predicts until ``stop``; record verdicts."""
    rng = np.random.default_rng(seed)
    while not stop.is_set():
        k = int(rng.integers(1, 9))
        lo = int(rng.integers(0, len(X) - k))
        code, resp = _post(url, X[lo:lo + k])
        if code != 200:
            out.append(("error", code, resp))
            continue
        got = np.asarray(resp["predictions"], np.float32)
        want = direct_by_version[resp["version"]][lo:lo + k]
        out.append(("ok", resp["version"], bool(np.array_equal(got, want))))


class TestServeHTTP:
    def test_end_to_end_with_hot_swap(self):
        """The acceptance demo: checkpointed model served over HTTP with
        bit-identical predictions, hot-swapped under live concurrent
        traffic with zero dropped requests, metrics non-zero, compiled
        shapes within the pow-2 bound."""
        X, y = _make_data(400)
        m1 = _fit(3, X, y)
        m2 = _fit(6, X, y)
        direct = {1: m1.predict(X), 2: m2.predict(X)}
        assert not np.array_equal(direct[1], direct[2])  # swap is visible
        checkpoint_model("mem:///serve-http/v1", m1, version=1)
        checkpoint_model("mem:///serve-http/v2", m2, version=2)

        reg = ModelRegistry(name="http-e2e", max_batch=32, min_bucket=8)
        assert reg.load("mem:///serve-http/v1") == 1
        with ServeFrontend(reg, max_batch=32, max_delay=0.002,
                           max_queue=128) as fe:
            # phase 1: concurrent clients against v1, all bit-identical
            out, stop = [], threading.Event()
            threads = [threading.Thread(
                target=_client_loop,
                args=(fe.url, X, direct, out, stop, 100 + t))
                for t in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.7)
            # hot-swap UNDER TRAFFIC: v2 becomes current atomically
            assert reg.load("mem:///serve-http/v2") == 2
            time.sleep(0.7)
            stop.set()
            for t in threads:
                t.join(timeout=30)

            errors = [r for r in out if r[0] == "error"]
            oks = [r for r in out if r[0] == "ok"]
            assert not errors, f"dropped/failed requests: {errors[:5]}"
            assert len(oks) > 20
            # every response matches the version it claims, exactly
            assert all(match for _, _, match in oks)
            versions = {v for _, v, _ in oks}
            assert versions == {1, 2}       # both versions served traffic

            # /healthz + /metrics evidence
            code, body = _get(fe.url, "/healthz")
            health = json.loads(body)
            assert code == 200 and health["version"] == 2
            code, body = _get(fe.url, "/metrics")
            assert code == 200
            text = body.decode()
            m = re.search(
                r'dmlc_serve_batch_rows_count\{batcher="http-e2e"\} (\d+)',
                text)
            assert m and int(m.group(1)) > 0       # batch-size histogram
            m = re.search(
                r'dmlc_serve_request_seconds_count\{path="/predict"\} (\d+)',
                text)
            assert m and int(m.group(1)) >= len(oks)    # latency histogram
            assert 'dmlc_serve_version_requests_total{version="1"}' in text
            assert 'dmlc_serve_version_requests_total{version="2"}' in text
            assert 'dmlc_serve_queue_wait_seconds_count' in text

            # compiled-shape bound under the randomized request sizes
            for v in (1, 2):
                runner = reg.get(v)
                assert len(runner.compiled_shapes) <= runner.shape_bound
                assert runner.shape_bound <= 32 .bit_length()  # log2+1 = 6

    def test_error_codes(self):
        reg = ModelRegistry(name="http-err", max_batch=8, min_bucket=1)
        with ServeFrontend(reg, max_batch=8) as fe:
            code, resp = _post(fe.url, [[0.0] * F])
            assert code == 503 and "no model" in resp["error"]

            class _One:
                def predict(self, Z):
                    return Z[:, 0]

            reg.publish(_One())
            body = b'{"rows": "not-a-matrix"}'
            req = urllib.request.Request(
                fe.url + "/predict", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    code = r.status
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 400
            code, resp = _post(fe.url, np.zeros((9, F)))  # > max_batch
            assert code == 400
            code, _ = _get(fe.url, "/nope")
            assert code == 404
            try:
                code, _ = _get(fe.url, "/predict")        # GET not POST
            except urllib.error.HTTPError as e:
                code = e.code
            assert code == 405

    def test_admission_control_503_on_full_queue(self):
        class _Slow:
            def predict(self, Z):
                time.sleep(0.3)
                return Z[:, 0]

        reg = ModelRegistry(name="http-full", max_batch=1, min_bucket=1)
        reg.publish(_Slow())
        with ServeFrontend(reg, max_batch=1, max_delay=0.0,
                           max_queue=1, request_timeout=5.0) as fe:
            codes = []
            lock = threading.Lock()

            def hit():
                code, _ = _post(fe.url, [[1.0] * F])
                with lock:
                    codes.append(code)

            threads = [threading.Thread(target=hit) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert codes.count(200) >= 1
            assert codes.count(503) >= 1          # load actually shed
            assert set(codes) <= {200, 503}


@pytest.mark.slow
class TestServeSoak:
    def test_multithreaded_soak_with_double_hot_swap(self):
        """Sustained multi-client load with two hot-swaps: every request
        either succeeds bit-identically against the version it claims or
        is shed with 503 — never dropped, never wrong."""
        X, y = _make_data(1000)
        models = {v: _fit(v + 2, X, y) for v in (1, 2, 3)}
        direct = {v: m.predict(X) for v, m in models.items()}
        for v, m in models.items():
            checkpoint_model(f"mem:///serve-soak/v{v}", m, version=v)

        reg = ModelRegistry(name="http-soak", max_batch=64, min_bucket=8)
        reg.load("mem:///serve-soak/v1")
        with ServeFrontend(reg, max_batch=64, max_delay=0.002,
                           max_queue=512) as fe:
            out, stop = [], threading.Event()
            threads = [threading.Thread(
                target=_client_loop,
                args=(fe.url, X, direct, out, stop, 500 + t))
                for t in range(8)]
            for t in threads:
                t.start()
            for v in (2, 3):
                time.sleep(1.2)
                reg.load(f"mem:///serve-soak/v{v}")
            time.sleep(1.2)
            stop.set()
            for t in threads:
                t.join(timeout=60)

        oks = [r for r in out if r[0] == "ok"]
        errors = [r for r in out if r[0] == "error"]
        shed = [e for e in errors if e[1] == 503]
        assert errors == shed, f"hard failures: {errors[:5]}"
        assert len(oks) > 100
        assert all(match for _, _, match in oks)
        assert {v for _, v, _ in oks} == {1, 2, 3}

    def test_bench_serve_mode_subprocess(self):
        """``python bench.py --serve`` emits a final well-formed JSON
        record with throughput + latency percentiles + batch evidence."""
        import os

        env = dict(os.environ, BENCH_FORCE_CPU="1", JAX_PLATFORMS="cpu",
                   SERVE_SECONDS="2", SERVE_QPS="80",
                   SERVE_TRAIN_ROWS="5000", SERVE_TREES="3")
        proc = subprocess.run(
            [sys.executable, "bench.py", "--serve"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-2000:]
        last = json.loads(proc.stdout.strip().splitlines()[-1])
        assert last["metric"] == "serve_requests_per_sec"
        assert last["provisional"] is False
        assert last["completed"] > 0 and last["value"] > 0
        assert last["latency_p99_ms"] is not None
        assert last["compiled_shapes"]
        assert len(last["compiled_shapes"]) <= last["shape_bound"]
