"""SLO scorecard engine (base/slo) contracts.

The scorecard gates CI drills GREEN, so its failure semantics must be
exact: malformed committed specs raise at load (not at gate time), an
objective whose value cannot be resolved FAILS (absent counters read 0;
absent quantiles/evidence never pass silently), and every row carries
the evidence pointer a reader needs to audit the verdict.
"""

import json

import pytest

from dmlc_core_tpu.base import metrics as M
from dmlc_core_tpu.base import slo


def _snapshot():
    r = M.MetricsRegistry(namespace="dmlc")
    reqs = r.counter("requests_total", labels=("code",))
    reqs.inc(90, code="200")
    reqs.inc(10, code="500")
    r.gauge("replicas").set(3)
    h = r.histogram("wait_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.02, 0.02, 0.5):
        h.observe(v)
    return r.snapshot()


def _spec(*objectives):
    return slo.SLOSpec("t", objectives)


class TestSpecValidation:
    def test_missing_fields_raise(self):
        with pytest.raises(ValueError, match="needs name/op"):
            _spec({"name": "x", "op": "<=", "threshold": 1})

    def test_unknown_op_raises(self):
        with pytest.raises(ValueError, match="unknown op"):
            _spec({"name": "x", "op": "~=", "threshold": 1,
                   "source": {"evidence": "a"}})

    def test_source_must_have_exactly_one_kind(self):
        for src in ({}, {"metric": "m", "evidence": "e"}, {"other": 1}):
            with pytest.raises(ValueError, match="exactly one"):
                _spec({"name": "x", "op": "<=", "threshold": 1,
                       "source": src})

    def test_ratio_wants_two_valid_sources(self):
        with pytest.raises(ValueError, match="ratio"):
            _spec({"name": "x", "op": "<=", "threshold": 1,
                   "source": {"ratio": [{"evidence": "a"}]}})
        with pytest.raises(ValueError, match="exactly one"):
            _spec({"name": "x", "op": "<=", "threshold": 1,
                   "source": {"ratio": [{"evidence": "a"}, {"bad": 1}]}})

    def test_load_roundtrip(self, tmp_path):
        p = tmp_path / "spec.json"
        p.write_text(json.dumps({"name": "fleet", "objectives": [
            {"name": "ok", "op": ">=", "threshold": 1,
             "source": {"metric": "dmlc_requests_total"}}]}))
        spec = slo.SLOSpec.load(str(p))
        assert spec.name == "fleet" and len(spec.objectives) == 1


class TestResolution:
    def test_counter_sum_with_label_filter(self):
        card = slo.evaluate(_spec(
            {"name": "errors", "op": "<=", "threshold": 10,
             "source": {"metric": "dmlc_requests_total",
                        "labels": {"code": "500"}}}), _snapshot())
        obj = card["objectives"][0]
        assert obj["observed"] == 10 and obj["pass"]

    def test_gauge_value_and_scale(self):
        card = slo.evaluate(_spec(
            {"name": "replicas", "op": "==", "threshold": 300,
             "source": {"metric": "dmlc_replicas", "stat": "value",
                        "scale": 100}}), _snapshot())
        assert card["objectives"][0]["pass"]

    def test_histogram_stats(self):
        snap = _snapshot()
        for stat, op, threshold in (("count", "==", 4), ("max", "<=", 0.5),
                                    ("min", ">=", 0.005), ("p99", "<", 1.0)):
            card = slo.evaluate(_spec(
                {"name": stat, "op": op, "threshold": threshold,
                 "source": {"metric": "dmlc_wait_seconds",
                            "stat": stat}}), snap)
            assert card["objectives"][0]["pass"], stat

    def test_any_pnn_quantile_selector(self):
        # p<nn> resolves ANY two-digit quantile over the pooled
        # reservoir, not just the p50/p95/p99 the summaries print
        r = M.MetricsRegistry(namespace="dmlc")
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
        for v in range(1, 101):
            h.observe(v / 100.0)            # 0.01 .. 1.00 uniformly
        snap = r.snapshot()

        def q(stat):
            card = slo.evaluate(_spec(
                {"name": stat, "op": ">=", "threshold": 0,
                 "source": {"metric": "dmlc_lat_seconds",
                            "stat": stat}}), snap)
            return card["objectives"][0]["observed"]

        assert q("p10") == pytest.approx(0.10, abs=0.02)
        assert q("p25") == pytest.approx(0.25, abs=0.02)
        assert q("p75") == pytest.approx(0.75, abs=0.02)
        assert q("p90") == pytest.approx(0.90, abs=0.02)
        assert q("p10") < q("p25") < q("p75") < q("p90")

    def test_bogus_quantile_stat_fails_not_passes(self):
        # "p999" matches no selector: the value is unresolvable, and an
        # unresolvable objective FAILS (never silently passes)
        card = slo.evaluate(_spec(
            {"name": "x", "op": "<=", "threshold": 1e9,
             "source": {"metric": "dmlc_wait_seconds",
                        "stat": "p999"}}), _snapshot())
        obj = card["objectives"][0]
        assert not obj["pass"] and obj["observed"] is None

    def test_evidence_dotted_path(self):
        card = slo.evaluate(
            _spec({"name": "dropped", "op": "==", "threshold": 0,
                   "source": {"evidence": "loadgen.dropped"}}),
            {}, evidence={"loadgen": {"dropped": 0, "ok": 7}})
        obj = card["objectives"][0]
        assert obj["pass"] and obj["observed"] == 0
        assert "loadgen.dropped" in obj["evidence"]

    def test_ratio(self):
        card = slo.evaluate(
            _spec({"name": "availability", "op": ">=", "threshold": 0.85,
                   "source": {"ratio": [
                       {"metric": "dmlc_requests_total",
                        "labels": {"code": "200"}},
                       {"metric": "dmlc_requests_total"}]}}),
            _snapshot())
        obj = card["objectives"][0]
        assert obj["observed"] == pytest.approx(0.9) and obj["pass"]


class TestFailureSemantics:
    def test_absent_counter_reads_zero(self):
        card = slo.evaluate(_spec(
            {"name": "none_dropped", "op": "==", "threshold": 0,
             "source": {"metric": "dmlc_never_declared_total"}}),
            _snapshot())
        obj = card["objectives"][0]
        assert obj["observed"] == 0 and obj["pass"]

    def test_absent_quantile_fails_not_passes(self):
        card = slo.evaluate(_spec(
            {"name": "latency", "op": "<=", "threshold": 1e9,
             "source": {"metric": "dmlc_never_declared_seconds",
                        "stat": "p99"}}), _snapshot())
        obj = card["objectives"][0]
        assert obj["observed"] is None and not obj["pass"]
        assert not card["pass"]

    def test_absent_evidence_fails(self):
        card = slo.evaluate(
            _spec({"name": "x", "op": "==", "threshold": 0,
                   "source": {"evidence": "missing.path"}}),
            {}, evidence={"present": 1})
        assert not card["objectives"][0]["pass"]

    def test_zero_denominator_ratio_fails(self):
        card = slo.evaluate(
            _spec({"name": "x", "op": ">=", "threshold": 0,
                   "source": {"ratio": [
                       {"evidence": "a"}, {"evidence": "b"}]}}),
            {}, evidence={"a": 1, "b": 0})
        assert not card["objectives"][0]["pass"]

    def test_one_failed_objective_fails_the_card(self):
        card = slo.evaluate(_spec(
            {"name": "good", "op": ">=", "threshold": 1,
             "source": {"metric": "dmlc_requests_total"}},
            {"name": "bad", "op": "<=", "threshold": 5,
             "source": {"metric": "dmlc_requests_total"}}), _snapshot())
        assert [o["pass"] for o in card["objectives"]] == [True, False]
        assert not card["pass"]
        assert card["spec"] == "t"


class TestCommittedSpecs:
    """The specs the drills gate on must always validate."""

    @pytest.mark.parametrize("name", ["fleet.json", "ps.json",
                                      "tenancy.json", "prodsim.json"])
    def test_committed_spec_validates(self, name):
        import os
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "slo", name)
        spec = slo.SLOSpec.load(path)
        assert spec.objectives
