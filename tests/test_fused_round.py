"""Fully-fused round kernel: byte parity, quant accuracy, analytics.

The ISSUE 18 contracts:

* ``DMLC_FUSED_ROUND=1`` (one Pallas program per level / expansion:
  bin-read -> descend -> g/h accumulate -> sibling subtraction, all
  VMEM-resident) serializes byte-identically to the staged
  three-dispatch path across {depthwise, lossguide} x {packed bins,
  feature bundling} — with ``hist_method="pallas"`` pinned, since byte
  parity of f32 sums requires BOTH paths to share the pallas
  accumulation order (tree 0's g/h are bf16-exact so any order matches;
  later trees are order-sensitive);
* ``DMLC_FUSED_ROUND=0`` restores the seed path exactly (same bytes as
  an unset knob on a non-TPU backend, where ``auto`` never engages);
* the int8 quantized histogram sync (``DMLC_HIST_QUANT``) keeps
  per-column grad/hess totals EXACT and bounds per-cell error by
  ``n_chips * scale``;
* the analytic traffic model (``hist_psum_bytes_per_round(quant=...)``,
  ``bins_bytes_per_round(fused=...)``) matches the live
  ``dmlc_histogram_psum_bytes_total`` counter under the quant lever.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.models import HistGBT  # noqa: E402
from dmlc_core_tpu.ops.histogram import (bins_bytes_per_round,  # noqa: E402
                                         dequantize_hist_sum,
                                         fused_round_ok,
                                         hist_psum_bytes_per_round,
                                         quantize_hist_partial)
from dmlc_core_tpu.parallel.mesh import local_mesh  # noqa: E402

# hist_method pinned to pallas: the fused kernel accumulates in pallas
# tile order, and f32 byte parity beyond tree 0 requires the unfused
# reference to sum in the SAME order ("auto" resolves to segment on CPU)
MODEL_KW = dict(n_trees=3, max_depth=3, n_bins=32, hist_method="pallas",
                objective="binary:logistic", learning_rate=0.3)


def _narrow_xy(n=1503, F=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[:, 1] = rng.integers(0, 3, n)
    X[:, 3] = rng.integers(0, 2, n)
    X[:, 5] = rng.integers(0, 5, n)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 3]) > 0).astype(np.float32)
    return X, y


def _bundle_xy(n=1404, seed=4):
    # two mutually-exclusive one-hot columns so DMLC_FEATURE_BUNDLE fires
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5)).astype(np.float32)
    onehot = rng.integers(0, 3, n)
    X[:, 2] = (onehot == 1).astype(np.float32)
    X[:, 3] = (onehot == 2).astype(np.float32)
    y = ((X[:, 0] + X[:, 2] - X[:, 3]) > 0).astype(np.float32)
    return X, y


def _fit_bytes(path, X, y):
    m = HistGBT(mesh=local_mesh(1), **MODEL_KW)
    m.fit(X, y)
    m.save_model(str(path))
    return path.read_bytes(), m


class TestFusedByteParity:
    # every lever combo the fused kernel composes with; lossguide rides
    # DMLC_MAX_LEAVES so the expansion loop (not the level loop) is hit
    CASES = [
        ("depthwise_plain", {}, _narrow_xy),
        ("depthwise_pack", {"DMLC_BIN_PACK": "1"}, _narrow_xy),
        ("depthwise_bundle", {"DMLC_FEATURE_BUNDLE": "1"}, _bundle_xy),
        ("lossguide_plain", {"DMLC_GROW_POLICY": "lossguide",
                             "DMLC_MAX_LEAVES": "6"}, _narrow_xy),
        ("lossguide_pack", {"DMLC_GROW_POLICY": "lossguide",
                            "DMLC_MAX_LEAVES": "6",
                            "DMLC_BIN_PACK": "1"}, _narrow_xy),
        ("lossguide_bundle", {"DMLC_GROW_POLICY": "lossguide",
                              "DMLC_MAX_LEAVES": "6",
                              "DMLC_FEATURE_BUNDLE": "1"}, _bundle_xy),
    ]

    @pytest.mark.parametrize("name,env,mk", CASES,
                             ids=[c[0] for c in CASES])
    def test_fused_matches_unfused_bytes(self, name, env, mk,
                                         monkeypatch, tmp_path):
        X, y = mk()
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        monkeypatch.setenv("DMLC_FUSED_ROUND", "0")
        b0, _ = _fit_bytes(tmp_path / "unfused.gbt", X, y)
        monkeypatch.setenv("DMLC_FUSED_ROUND", "1")
        b1, m1 = _fit_bytes(tmp_path / "fused.gbt", X, y)
        assert b0 == b1
        if "DMLC_BIN_PACK" in env or "DMLC_FEATURE_BUNDLE" in env:
            assert m1._bin_layout is not None    # the lever actually fired

    def test_fused_round_0_restores_seed_path(self, monkeypatch, tmp_path):
        # the off switch IS the seed path: on a non-TPU backend "auto"
        # never engages, so unset-knob bytes == explicit-0 bytes
        X, y = _narrow_xy(seed=7)
        monkeypatch.delenv("DMLC_FUSED_ROUND", raising=False)
        b_auto, _ = _fit_bytes(tmp_path / "auto.gbt", X, y)
        monkeypatch.setenv("DMLC_FUSED_ROUND", "0")
        b_off, _ = _fit_bytes(tmp_path / "off.gbt", X, y)
        assert b_auto == b_off


class TestQuantAccuracy:
    def _chip_partials(self, n_chips=8, shape=(2, 4, 6, 16), seed=3):
        rng = np.random.default_rng(seed)
        return [rng.normal(size=shape).astype(np.float32) * 7.0
                for _ in range(n_chips)]

    def test_column_totals_exact_cell_error_bounded(self):
        # emulate the hist_sync quant branch: shared pmax scale, int32
        # psum of int8 codes, f32 psum of exact column totals
        parts = self._chip_partials()
        n_chips = len(parts)
        gmax = np.max([np.max(np.abs(p), axis=-1, keepdims=True)
                       for p in parts], axis=0)
        q_sum = np.zeros(parts[0].shape, np.int32)
        tot_sum = np.zeros(gmax.shape, np.float32)
        scale = None
        for p in parts:
            q, scale, tot = quantize_hist_partial(p, gmax)
            q_sum += np.asarray(q, np.int32)
            tot_sum += np.asarray(tot)
        out = np.asarray(dequantize_hist_sum(q_sum, scale, tot_sum))
        exact = np.sum(parts, axis=0)
        scale = np.asarray(scale)
        # the correction term makes per-(plane, node, feature) totals
        # exact — leaf weights at a fixed split carry NO quant error
        np.testing.assert_allclose(out.sum(-1, keepdims=True), tot_sum,
                                   rtol=1e-5, atol=1e-4)
        # per-cell: each chip rounds within scale/2 and the correction
        # redistributes at most the same again — n_chips * scale overall
        assert (np.abs(out - exact) <= n_chips * scale + 1e-5).all()

    def test_shared_scale_never_clips(self):
        # gmax is the GLOBAL pmax, so |hist/scale| <= 127 on every chip
        parts = self._chip_partials(seed=9)
        gmax = np.max([np.max(np.abs(p), axis=-1, keepdims=True)
                       for p in parts], axis=0)
        for p in parts:
            q, scale, _ = quantize_hist_partial(p, gmax)
            raw = np.round(np.asarray(p) / np.asarray(scale))
            assert (np.abs(raw) <= 127).all()
            np.testing.assert_array_equal(np.asarray(q, np.int32),
                                          raw.astype(np.int32))

    def test_quant_fit_close_to_exact(self, monkeypatch):
        # end to end on the 8-chip mesh: the quantized sync must not
        # move the margins materially (splits may flip on near-ties,
        # the loss surface must not)
        X, y = _narrow_xy(n=768, seed=11)
        kw = dict(MODEL_KW, hist_method="segment")
        base = HistGBT(mesh=local_mesh(8), **kw)
        base.fit(X, y)
        monkeypatch.setenv("DMLC_HIST_QUANT", "1")
        quant = HistGBT(mesh=local_mesh(8), **kw)
        quant.fit(X, y)
        p0 = base.predict(X, output_margin=True)
        p1 = quant.predict(X, output_margin=True)
        assert float(np.max(np.abs(p0 - p1))) < 0.15
        assert float(np.mean(np.abs(p0 - p1))) < 0.02


class TestQuantTraffic:
    def _psum_total(self):
        from dmlc_core_tpu.base.metrics import default_registry
        snap = default_registry().snapshot()["metrics"]
        m = snap.get("dmlc_histogram_psum_bytes_total")
        return (sum(s["value"] for s in m["series"]
                    if s["labels"].get("engine") == "incore")
                if m else 0.0)

    def test_counter_matches_quant_model(self, monkeypatch):
        # the live counter must price the int8 sync the chips actually
        # pay: 2*F*(B+8) per built node, not 2*F*B*4
        monkeypatch.setenv("DMLC_HIST_QUANT", "1")
        X, y = _narrow_xy(n=512, seed=12)
        kw = dict(MODEL_KW, hist_method="segment")
        before = self._psum_total()
        m8 = HistGBT(mesh=local_mesh(8), **kw)
        m8.fit(X, y)
        expect = kw["n_trees"] * hist_psum_bytes_per_round(
            kw["max_depth"], X.shape[1], kw["n_bins"], quant=True)
        assert self._psum_total() - before == expect

    def test_quant_model_cuts_bytes(self):
        full = hist_psum_bytes_per_round(6, 28, 256)
        quant = hist_psum_bytes_per_round(6, 28, 256, quant=True)
        # 2*S*(Bs+8) vs 2*S*Bs*4: ~3.9x at Bs=256
        assert quant * 3 < full < quant * 4


class TestAnalyticModel:
    def test_bins_bytes_fused_passes(self):
        rows, rb = 10_000_000, 28
        # depthwise: 2*depth-1 staged passes collapse to depth
        assert bins_bytes_per_round(6, rows, rb) == 11 * rows * rb
        assert bins_bytes_per_round(6, rows, rb, fused=True) \
            == 6 * rows * rb
        # lossguide: 2*leaves-1 -> leaves
        assert bins_bytes_per_round(
            6, rows, rb, grow_policy="lossguide", max_leaves=8,
            fused=True) == 8 * rows * rb
        assert bins_bytes_per_round(
            6, rows, rb, grow_policy="lossguide", max_leaves=8) \
            == 15 * rows * rb
        # degenerate depth never prices zero passes
        assert bins_bytes_per_round(1, rows, rb, fused=True) \
            == rows * rb

    def test_fused_round_ok_vmem_gate(self):
        # flagship shape fits; a pathological node count does not
        assert fused_round_ok(256, 28, n_prev=16)
        assert not fused_round_ok(256, 2048, n_prev=4096)
