"""Out-of-core training path (BASELINE config 3): streaming sketch +
external-memory hist-GBT over CSR pages.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from dmlc_core_tpu.io.filesystem import TemporaryDirectory
from dmlc_core_tpu.data.iter import RowBlockIter
from dmlc_core_tpu.models.histgbt import HistGBT
from dmlc_core_tpu.ops.quantile import (
    SketchAccumulator,
    apply_bins,
    compute_cuts,
)


def _synth(n, F, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.5).astype(np.float32)
    return X, y


def _rank_error(X, cuts, n_bins):
    """Max |empirical CDF at cut − target quantile| over features/cuts."""
    target = np.arange(1, n_bins) / n_bins
    errs = []
    for f in range(X.shape[1]):
        ecdf = np.searchsorted(np.sort(X[:, f]), cuts[f],
                               side="right") / len(X)
        errs.append(np.abs(ecdf - target))
    return float(np.max(errs))


def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for i in range(len(X)):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(X.shape[1]))
            f.write(f"{y[i]:.0f} {feats}\n")


class TestSketchAccumulator:
    def test_streaming_matches_full(self):
        X, _ = _synth(20_000, 5)
        full_cuts = np.asarray(compute_cuts(X, n_bins=32))
        acc = SketchAccumulator(5, n_summary=512, buffer_pages=4)
        for page in np.array_split(X, 23):  # uneven pages force collapses
            acc.add(page)
        stream_cuts = np.asarray(acc.finalize(32))
        # the operative sketch metric: rank (quantile) error of each cut,
        # which must stay well below a bin width (1/32 ≈ 3.1%; XGBoost's
        # default sketch_eps is 3%)
        err = _rank_error(X, stream_cuts, 32)
        assert err < 0.01, err
        assert _rank_error(X, full_cuts, 32) < 0.002  # oracle sanity

    def test_bounded_memory(self):
        acc = SketchAccumulator(3, n_summary=64, buffer_pages=4)
        for _ in range(40):
            acc.add(np.random.default_rng(1).normal(size=(100, 3)))
        assert len(acc._summaries) <= 4  # hierarchical collapse bounds state

    def test_weighted(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(5_000, 1)).astype(np.float32)
        w = (x[:, 0] > 0).astype(np.float32) * 9 + 1  # positives weigh 10x
        acc = SketchAccumulator(1, n_summary=512, buffer_pages=8)
        for xs, ws in zip(np.array_split(x, 7), np.array_split(w, 7)):
            acc.add(xs, ws)
        cuts = np.asarray(acc.finalize(4))[0]  # 3 interior cuts
        # with positives outweighing 10:1, the weighted median is positive
        assert cuts[1] > 0

    def test_distributed_merge(self):
        X, _ = _synth(10_000, 3, seed=5)
        halves = [X[:5_000], X[5_000:]]
        summaries = []
        for h in halves:
            acc = SketchAccumulator(3, n_summary=512, buffer_pages=4)
            for page in np.array_split(h, 5):
                acc.add(page)
            summaries.append(acc)

        def fake_allgather(arr):
            # mimic collectives.allgather: stack rank values on axis 0
            if arr.ndim == 2:  # summary [F, S]
                return np.stack([summaries[0].summary()[0],
                                 summaries[1].summary()[0]])
            return np.asarray([summaries[0].summary()[1],
                               summaries[1].summary()[1]], np.float32)

        dist_cuts = np.asarray(summaries[0].finalize(16, fake_allgather))
        err = _rank_error(X, dist_cuts, 16)
        assert err < 0.015, err  # well under a bin width (1/16 ≈ 6.3%)


class TestFitExternal:
    def test_matches_in_core(self):
        """Same cuts + data → external page loop reproduces in-core trees."""
        X, y = _synth(4_000, 6, seed=3)
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "train.libsvm")
            cache = os.path.join(tmp.path, "cache")
            _write_libsvm(data, X, y)

            common = dict(n_trees=5, max_depth=3, n_bins=32,
                          hist_method="segment")
            incore = HistGBT(**common)
            incore.fit(X, y)

            it = RowBlockIter.create(f"{data}#{cache}", 0, 1, "libsvm")
            ext = HistGBT(**common)
            ext.fit_external(it, cuts=incore.cuts)
            it.close()

            for t_in, t_ext in zip(incore.trees, ext.trees):
                np.testing.assert_array_equal(t_in["feat"], t_ext["feat"])
                np.testing.assert_array_equal(t_in["thr"], t_ext["thr"])
                np.testing.assert_allclose(t_in["leaf"], t_ext["leaf"],
                                           rtol=2e-4, atol=2e-5)
            p_in = incore.predict(X[:256])
            p_ext = ext.predict(X[:256])
            np.testing.assert_allclose(p_in, p_ext, rtol=2e-3, atol=2e-4)

    def test_streaming_cuts_loss_decreases(self):
        X, y = _synth(3_000, 4, seed=9)
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "t.libsvm")
            _write_libsvm(data, X, y)
            it = RowBlockIter.create(data, 0, 1, "libsvm")
            m = HistGBT(n_trees=8, max_depth=3, n_bins=16,
                        hist_method="segment")
            m.fit_external(it)
            it.close()
            margins = m.predict(X, output_margin=True)
            # logloss of the trained model clearly beats the 0-margin start
            eps = 1e-7
            prob = 1 / (1 + np.exp(-margins))
            ll = -np.mean(y * np.log(prob + eps) + (1 - y) * np.log(1 - prob + eps))
            assert ll < 0.55, ll

    def test_multipage_cache(self):
        """Tiny page budget → many pages; results stay consistent."""
        X, y = _synth(2_000, 4, seed=11)
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "t.libsvm")
            cache = os.path.join(tmp.path, "c")
            _write_libsvm(data, X, y)
            from dmlc_core_tpu.data.iter import DiskRowIter
            from dmlc_core_tpu.data.parsers import Parser

            parser = Parser.create(data, 0, 1, "libsvm")
            parser.hint_chunk_size(8 << 10)  # small chunks → multiple pages
            it = DiskRowIter(parser, cache, page_bytes=16 << 10)
            assert it._num_pages > 3  # genuinely multi-page
            m = HistGBT(n_trees=3, max_depth=2, n_bins=16,
                        hist_method="segment")
            m.fit_external(it)
            it.close()
            assert len(m.trees) == 3


def test_external_memory_multiclass(tmp_path):
    """fit_external with multi:softmax must match in-core fit() given the
    same cuts (same data, single worker, deterministic splits)."""
    from dmlc_core_tpu.data.iter import RowBlockIter
    from dmlc_core_tpu.models import HistGBT

    rng = np.random.default_rng(0)
    K, n, F = 3, 3000, 6
    centers = np.random.default_rng(42).normal(scale=3.0, size=(K, 2))
    y = rng.integers(0, K, n)
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[:, :2] += centers[y]

    svm = tmp_path / "mc.svm"
    _write_libsvm(svm, X, y)

    ext = HistGBT(n_trees=8, max_depth=3, n_bins=32,
                  objective="multi:softmax", num_class=K)
    it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
    ext.fit_external(it, num_col=F)
    acc_ext = (ext.predict(X) == y).mean()
    assert acc_ext > 0.9, acc_ext

    core = HistGBT(n_trees=8, max_depth=3, n_bins=32,
                   objective="multi:softmax", num_class=K)
    core.fit(X, y.astype(np.float32), cuts=ext.cuts)
    for te, tc in zip(ext.trees, core.trees):
        np.testing.assert_array_equal(te["feat"], tc["feat"])
        np.testing.assert_array_equal(te["thr"], tc["thr"])
        np.testing.assert_allclose(te["leaf"], tc["leaf"],
                                   rtol=1e-3, atol=1e-4)


def test_cache_device_matches_default(tmp_path):
    from dmlc_core_tpu.data.iter import RowBlockIter
    from dmlc_core_tpu.models import HistGBT

    X, y = _synth(2000, 5)
    svm = tmp_path / "c.svm"
    _write_libsvm(svm, X, y)

    models = {}
    for cache in (False, True):
        m = HistGBT(n_trees=5, max_depth=3, n_bins=32)
        it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
        m.fit_external(it, num_col=5, cache_device=cache)
        it.close()
        models[cache] = m
    for t0, t1 in zip(models[False].trees, models[True].trees):
        np.testing.assert_array_equal(t0["feat"], t1["feat"])
        np.testing.assert_array_equal(t0["thr"], t1["thr"])
        # cache_device=True runs the in-core engine whose leaf sums come
        # from the histogram cumsum (histgbt precision note), not the
        # page loop's segment_sum — identical splits, ~1e-4 leaf drift
        np.testing.assert_allclose(t0["leaf"], t1["leaf"],
                                   rtol=1e-3, atol=1e-5)
