"""Out-of-core training path (BASELINE config 3): streaming sketch +
external-memory hist-GBT over CSR pages.
"""

import os

import pytest
import numpy as np

from dmlc_core_tpu.io.filesystem import TemporaryDirectory
from dmlc_core_tpu.data.iter import RowBlockIter
from dmlc_core_tpu.models.histgbt import HistGBT
from dmlc_core_tpu.ops.quantile import (
    SketchAccumulator,
    compute_cuts,
)


def _synth(n, F, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.5).astype(np.float32)
    return X, y


def _rank_error(X, cuts, n_bins):
    """Max |empirical CDF at cut − target quantile| over features/cuts."""
    target = np.arange(1, n_bins) / n_bins
    errs = []
    for f in range(X.shape[1]):
        ecdf = np.searchsorted(np.sort(X[:, f]), cuts[f],
                               side="right") / len(X)
        errs.append(np.abs(ecdf - target))
    return float(np.max(errs))


def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for i in range(len(X)):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(X.shape[1]))
            f.write(f"{y[i]:.0f} {feats}\n")


def _weighted_rank_interval_error(x, w, cuts, n_bins):
    """Max distance from each cut's target rank to its achievable rank
    interval ``[P(X < c), P(X ≤ c)]``.

    Atoms (duplicated values) make the rank set-valued, so interval
    distance is the honest metric for discrete mass.  Cuts that
    merge_summaries ε-bumped apart to stay strictly increasing (a run of
    targets landing on one atom) are scored as ONE cluster at the run's
    first cut — the bumped copies route rows identically, so they are a
    representation detail, not sketch error."""
    order = np.argsort(x, kind="stable")
    xs, ws = x[order], w[order]
    cw = np.cumsum(ws)
    total = cw[-1]
    target = np.arange(1, n_bins) / n_bins
    err = 0.0
    rep = cuts[0]
    for q, c in zip(target, cuts):
        tol = max(abs(rep), 1.0) * 1e-6 * (n_bins + 1)
        if c - rep > tol:
            rep = c                       # genuinely new cut value
        lo = np.searchsorted(xs, rep, side="left")
        hi = np.searchsorted(xs, rep, side="right")
        r_lo = (cw[lo - 1] if lo > 0 else 0.0) / total
        r_hi = (cw[hi - 1] if hi > 0 else 0.0) / total
        if q < r_lo:
            err = max(err, r_lo - q)
        elif q > r_hi:
            err = max(err, q - r_hi)
    return err


def _sketch_eps(n_summary, pages, cap):
    """The documented bound from ops/quantile.py: (⌈log_C P⌉+4)/(S−1).
    Integer ladder depth — float log rounds exact powers of C up a level
    and would silently test a looser bound."""
    levels = 1
    while cap ** levels < max(pages, 2):
        levels += 1
    return (levels + 4) / (n_summary - 1)


class TestSketchErrorBound:
    """Adversarial-distribution property tests of the documented
    eps(S, P, C) rank-error bound (SURVEY.md §7 hard part (c): the
    reference world's GK sketches carry provable guarantees — so must
    the fixed-size replacement)."""

    N_BINS = 32
    S = 512
    CAP = 4          # tiny buffer → maximal ladder depth for the bound

    def _stream(self, x, w, pages):
        acc = SketchAccumulator(1, n_summary=self.S, buffer_pages=self.CAP)
        for xs, ws in zip(np.array_split(x, pages),
                          np.array_split(w, pages)):
            acc.add(xs.reshape(-1, 1), ws)
        cuts = np.asarray(acc.finalize(self.N_BINS))[0]
        bound = _sketch_eps(self.S, acc.pages_seen, self.CAP)
        err = _weighted_rank_interval_error(x, w, cuts, self.N_BINS)
        assert err <= bound, (err, bound)
        return err, bound

    def test_heavy_tail(self):
        rng = np.random.default_rng(10)
        x = rng.pareto(0.5, size=30_000).astype(np.float32)  # infinite mean
        self._stream(x, np.ones_like(x), pages=37)

    def test_lognormal_wide(self):
        rng = np.random.default_rng(11)
        x = np.exp(rng.normal(0, 6, size=30_000)).astype(np.float32)
        self._stream(x, np.ones_like(x), pages=29)

    def test_near_duplicate_atoms(self):
        rng = np.random.default_rng(12)
        x = np.full(30_000, 3.25, np.float32)       # 99.9% one atom
        idx = rng.choice(len(x), 30, replace=False)
        x[idx] = rng.normal(size=30).astype(np.float32)
        self._stream(x, np.ones_like(x), pages=23)

    def test_massive_weight_skew(self):
        rng = np.random.default_rng(13)
        x = rng.normal(size=20_000).astype(np.float32)
        w = np.full_like(x, 1e-6)
        w[x > 1.5] = 1e6                            # 10^12 dynamic range
        self._stream(x, w, pages=31)

    def test_sorted_stream_order(self):
        # pages arrive sorted: every page summarizes a disjoint value
        # range — the worst case for naive averaging of summaries
        rng = np.random.default_rng(14)
        x = np.sort(rng.normal(size=30_000).astype(np.float32))
        self._stream(x, np.ones_like(x), pages=41)

    def test_many_pages_log_growth(self):
        # 400 pages through a 4-ary ladder: the flat collapse-all design
        # would compound ~100 merge stages of error; the ladder stays
        # within the log-depth bound
        rng = np.random.default_rng(15)
        x = rng.normal(size=40_000).astype(np.float32)
        err, bound = self._stream(x, np.ones_like(x), pages=400)
        assert bound < 0.02, bound   # the bound itself stays tight


class TestSketchAccumulator:
    def test_streaming_matches_full(self):
        X, _ = _synth(20_000, 5)
        full_cuts = np.asarray(compute_cuts(X, n_bins=32))
        acc = SketchAccumulator(5, n_summary=512, buffer_pages=4)
        for page in np.array_split(X, 23):  # uneven pages force collapses
            acc.add(page)
        stream_cuts = np.asarray(acc.finalize(32))
        # the operative sketch metric: rank (quantile) error of each cut,
        # which must stay well below a bin width (1/32 ≈ 3.1%; XGBoost's
        # default sketch_eps is 3%)
        err = _rank_error(X, stream_cuts, 32)
        assert err < 0.01, err
        assert _rank_error(X, full_cuts, 32) < 0.002  # oracle sanity

    def test_bounded_memory(self):
        acc = SketchAccumulator(3, n_summary=64, buffer_pages=4)
        for _ in range(40):
            acc.add(np.random.default_rng(1).normal(size=(100, 3)))
        # C-ary ladder: ≤ C−1 summaries per level, O(log_C P) levels
        per_level = [len(lv) for lv in acc._levels]
        assert max(per_level) <= 3, per_level
        assert len(per_level) <= 4, per_level

    def test_weighted(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(5_000, 1)).astype(np.float32)
        w = (x[:, 0] > 0).astype(np.float32) * 9 + 1  # positives weigh 10x
        acc = SketchAccumulator(1, n_summary=512, buffer_pages=8)
        for xs, ws in zip(np.array_split(x, 7), np.array_split(w, 7)):
            acc.add(xs, ws)
        cuts = np.asarray(acc.finalize(4))[0]  # 3 interior cuts
        # with positives outweighing 10:1, the weighted median is positive
        assert cuts[1] > 0

    def test_distributed_merge(self):
        X, _ = _synth(10_000, 3, seed=5)
        halves = [X[:5_000], X[5_000:]]
        summaries = []
        for h in halves:
            acc = SketchAccumulator(3, n_summary=512, buffer_pages=4)
            for page in np.array_split(h, 5):
                acc.add(page)
            summaries.append(acc)

        def fake_allgather(arr):
            # mimic collectives.allgather: stack rank values on axis 0
            if arr.ndim == 2:  # summary [F, S]
                return np.stack([summaries[0].summary()[0],
                                 summaries[1].summary()[0]])
            return np.asarray([summaries[0].summary()[1],
                               summaries[1].summary()[1]], np.float32)

        dist_cuts = np.asarray(summaries[0].finalize(16, fake_allgather))
        err = _rank_error(X, dist_cuts, 16)
        assert err < 0.015, err  # well under a bin width (1/16 ≈ 6.3%)


class TestFitExternal:
    def test_matches_in_core(self):
        """Same cuts + data → external page loop reproduces in-core trees."""
        X, y = _synth(4_000, 6, seed=3)
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "train.libsvm")
            cache = os.path.join(tmp.path, "cache")
            _write_libsvm(data, X, y)

            common = dict(n_trees=5, max_depth=3, n_bins=32,
                          hist_method="segment")
            incore = HistGBT(**common)
            incore.fit(X, y)

            it = RowBlockIter.create(f"{data}#{cache}", 0, 1, "libsvm")
            ext = HistGBT(**common)
            ext.fit_external(it, cuts=incore.cuts)
            it.close()

            for t_in, t_ext in zip(incore.trees, ext.trees):
                np.testing.assert_array_equal(t_in["feat"], t_ext["feat"])
                np.testing.assert_array_equal(t_in["thr"], t_ext["thr"])
                np.testing.assert_allclose(t_in["leaf"], t_ext["leaf"],
                                           rtol=2e-4, atol=2e-5)
            p_in = incore.predict(X[:256])
            p_ext = ext.predict(X[:256])
            np.testing.assert_allclose(p_in, p_ext, rtol=2e-3, atol=2e-4)

    def test_streaming_cuts_loss_decreases(self):
        X, y = _synth(3_000, 4, seed=9)
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "t.libsvm")
            _write_libsvm(data, X, y)
            it = RowBlockIter.create(data, 0, 1, "libsvm")
            m = HistGBT(n_trees=8, max_depth=3, n_bins=16,
                        hist_method="segment")
            m.fit_external(it)
            it.close()
            margins = m.predict(X, output_margin=True)
            # logloss of the trained model clearly beats the 0-margin start
            eps = 1e-7
            prob = 1 / (1 + np.exp(-margins))
            ll = -np.mean(y * np.log(prob + eps) + (1 - y) * np.log(1 - prob + eps))
            assert ll < 0.55, ll

    def test_multipage_cache(self):
        """Tiny page budget → many pages; results stay consistent."""
        X, y = _synth(2_000, 4, seed=11)
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "t.libsvm")
            cache = os.path.join(tmp.path, "c")
            _write_libsvm(data, X, y)
            from dmlc_core_tpu.data.iter import DiskRowIter
            from dmlc_core_tpu.data.parsers import Parser

            parser = Parser.create(data, 0, 1, "libsvm")
            parser.hint_chunk_size(8 << 10)  # small chunks → multiple pages
            it = DiskRowIter(parser, cache, page_bytes=16 << 10)
            assert it._num_pages > 3  # genuinely multi-page
            m = HistGBT(n_trees=3, max_depth=2, n_bins=16,
                        hist_method="segment")
            m.fit_external(it)
            it.close()
            assert len(m.trees) == 3


class TestChunkedStreamingEngine:
    """The over-budget path: pages stack into >1 fixed-shape chunks and
    stream per level (VERDICT r3 #3's O(depth·chunks) restructure).
    Small datasets normally auto-route to the cached engine, so these
    tests shrink DMLC_TPU_EXTERNAL_DEVICE_BUDGET until residency is
    impossible and the streaming engine must run."""

    def test_forced_chunked_matches_in_core(self, monkeypatch):
        X, y = _synth(4_000, 6, seed=3)
        # row state 4000·24 B; bins 4000·6 B — 110 kB forces ≥2 chunks
        monkeypatch.setenv("DMLC_TPU_EXTERNAL_DEVICE_BUDGET", "110000")
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "train.libsvm")
            cache = os.path.join(tmp.path, "cache")
            _write_libsvm(data, X, y)
            common = dict(n_trees=5, max_depth=3, n_bins=32,
                          hist_method="segment")
            incore = HistGBT(**common)
            incore.fit(X, y)
            it = RowBlockIter.create(f"{data}#{cache}", 0, 1, "libsvm")
            ext = HistGBT(**common)
            ext.fit_external(it, cuts=incore.cuts)
            it.close()
            for t_in, t_ext in zip(incore.trees, ext.trees):
                np.testing.assert_array_equal(t_in["feat"], t_ext["feat"])
                np.testing.assert_array_equal(t_in["thr"], t_ext["thr"])
                np.testing.assert_allclose(t_in["leaf"], t_ext["leaf"],
                                           rtol=2e-4, atol=2e-5)
            np.testing.assert_allclose(incore.predict(X[:256]),
                                       ext.predict(X[:256]),
                                       rtol=2e-3, atol=2e-4)

    @pytest.mark.slow
    def test_forced_chunked_multiclass(self, monkeypatch):
        rng = np.random.default_rng(5)
        X = rng.normal(size=(3_000, 5)).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32) + (
            X[:, 2] > 0.8).astype(np.float32)
        # row state 3000·48 B = 144000; 150000 leaves 6000 B for bins →
        # 1200 rows/chunk → 3 chunks: genuinely multi-chunk multiclass
        monkeypatch.setenv("DMLC_TPU_EXTERNAL_DEVICE_BUDGET", "150000")
        with TemporaryDirectory() as tmp:
            data = os.path.join(tmp.path, "t.libsvm")
            _write_libsvm(data, X, y)
            common = dict(n_trees=4, max_depth=3, n_bins=16,
                          num_class=3, objective="multi:softmax",
                          hist_method="segment")
            incore = HistGBT(**common)
            incore.fit(X, y)
            it = RowBlockIter.create(data, 0, 1, "libsvm")
            ext = HistGBT(**common)
            ext.fit_external(it, num_col=5, cuts=incore.cuts)
            it.close()
            for t_in, t_ext in zip(incore.trees, ext.trees):
                np.testing.assert_array_equal(t_in["feat"], t_ext["feat"])
                np.testing.assert_array_equal(t_in["thr"], t_ext["thr"])
                np.testing.assert_allclose(t_in["leaf"], t_ext["leaf"],
                                           rtol=2e-4, atol=2e-5)
            assert (ext.predict(X) == incore.predict(X)).mean() > 0.99

    def test_forced_chunked_sampling_and_eval(self, monkeypatch, caplog):
        """Sampling + eval_every run through the streaming engine; draws
        are deterministic (two runs → identical trees) and training
        still learns."""
        X, y = _synth(3_000, 4, seed=9)
        # row state 3000·24 B = 72000; 80000 leaves 8000 B for bins →
        # 2000 rows/chunk → 2 chunks: the per-page keep-mask scatter
        # must spill across a chunk boundary
        monkeypatch.setenv("DMLC_TPU_EXTERNAL_DEVICE_BUDGET", "80000")
        runs = []
        for _ in range(2):
            with TemporaryDirectory() as tmp:
                data = os.path.join(tmp.path, "t.libsvm")
                _write_libsvm(data, X, y)
                it = RowBlockIter.create(data, 0, 1, "libsvm")
                m = HistGBT(n_trees=6, max_depth=3, n_bins=16, seed=7,
                            subsample=0.8, colsample_bytree=0.75,
                            hist_method="segment")
                m.fit_external(it, eval_every=3)
                it.close()
                runs.append(m)
        for ta, tb in zip(runs[0].trees, runs[1].trees):
            np.testing.assert_array_equal(ta["feat"], tb["feat"])
            np.testing.assert_array_equal(ta["thr"], tb["thr"])
            np.testing.assert_allclose(ta["leaf"], tb["leaf"],
                                       rtol=1e-5, atol=1e-6)
        margins = runs[0].predict(X, output_margin=True)
        prob = 1 / (1 + np.exp(-margins))
        eps = 1e-7
        ll = -np.mean(y * np.log(prob + eps)
                      + (1 - y) * np.log(1 - prob + eps))
        assert ll < 0.55, ll


class TestPredictIter:
    """Streaming inference: a model trained out-of-core must SCORE
    out-of-core — predictions over RowBlockIter pages must equal the
    dense predict, with host memory bounded by one staging slab."""

    def test_histgbt_matches_dense(self, tmp_path):
        X, y = _synth(3_000, 5, seed=21)
        m = HistGBT(n_trees=6, max_depth=3, n_bins=32,
                    hist_method="segment")
        m.fit(X, y)
        data = os.path.join(str(tmp_path), "p.libsvm")
        _write_libsvm(data, X, y)
        it = RowBlockIter.create(data, 0, 1, "libsvm")
        # tiny slab: forces many flushes and page-straddling slices
        got = m.predict_iter(it, batch_rows=257)
        it.close()
        # libsvm text round-trips at 6 decimals; the quantized bins are
        # almost always identical, but a value sitting exactly on a cut
        # may flip — compare through the text round-trip oracle
        X_rt = np.zeros_like(X)
        it = RowBlockIter.create(data, 0, 1, "libsvm")
        lo = 0
        for b in it:
            b.to_dense_into(X_rt[lo:lo + b.size])
            lo += b.size
        it.close()
        np.testing.assert_allclose(got, m.predict(X_rt),
                                   rtol=1e-6, atol=1e-7)
        # margins too
        it = RowBlockIter.create(data, 0, 1, "libsvm")
        gm = m.predict_iter(it, output_margin=True, batch_rows=1024)
        it.close()
        np.testing.assert_allclose(
            gm, m.predict(X_rt, output_margin=True), rtol=1e-6, atol=1e-7)

    def test_histgbt_feature_width_mismatch_fails(self, tmp_path):
        X, y = _synth(500, 3, seed=22)
        m = HistGBT(n_trees=2, max_depth=2, n_bins=16,
                    hist_method="segment")
        m.fit(X, y)
        wide, yw = _synth(100, 6, seed=23)
        data = os.path.join(str(tmp_path), "wide.libsvm")
        _write_libsvm(data, wide, yw)
        it = RowBlockIter.create(data, 0, 1, "libsvm")
        with pytest.raises(Exception, match="expects 3 features"):
            np.asarray(m.predict_iter(it))
        it.close()

    def test_gblinear_matches_dense(self, tmp_path):
        from dmlc_core_tpu.models.linear import GBLinear

        X, y = _synth(2_000, 4, seed=24)
        m = GBLinear(n_rounds=20, objective="binary:logistic")
        m.fit(X, y)
        data = os.path.join(str(tmp_path), "lp.libsvm")
        _write_libsvm(data, X, y)
        it = RowBlockIter.create(data, 0, 1, "libsvm")
        got = m.predict_iter(it, batch_rows=300)
        it.close()
        X_rt = np.zeros_like(X)
        it = RowBlockIter.create(data, 0, 1, "libsvm")
        lo = 0
        for b in it:
            b.to_dense_into(X_rt[lo:lo + b.size])
            lo += b.size
        it.close()
        np.testing.assert_allclose(got, m.predict(X_rt),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_external_memory_multiclass(tmp_path):
    """fit_external with multi:softmax must match in-core fit() given the
    same cuts (same data, single worker, deterministic splits)."""
    from dmlc_core_tpu.data.iter import RowBlockIter
    from dmlc_core_tpu.models import HistGBT

    rng = np.random.default_rng(0)
    K, n, F = 3, 3000, 6
    centers = np.random.default_rng(42).normal(scale=3.0, size=(K, 2))
    y = rng.integers(0, K, n)
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[:, :2] += centers[y]

    svm = tmp_path / "mc.svm"
    _write_libsvm(svm, X, y)

    ext = HistGBT(n_trees=8, max_depth=3, n_bins=32,
                  objective="multi:softmax", num_class=K)
    it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
    ext.fit_external(it, num_col=F)
    acc_ext = (ext.predict(X) == y).mean()
    assert acc_ext > 0.9, acc_ext

    core = HistGBT(n_trees=8, max_depth=3, n_bins=32,
                   objective="multi:softmax", num_class=K)
    core.fit(X, y.astype(np.float32), cuts=ext.cuts)
    for te, tc in zip(ext.trees, core.trees):
        np.testing.assert_array_equal(te["feat"], tc["feat"])
        np.testing.assert_array_equal(te["thr"], tc["thr"])
        np.testing.assert_allclose(te["leaf"], tc["leaf"],
                                   rtol=1e-3, atol=1e-4)


def test_host_pinned_passes_match_default(tmp_path, monkeypatch):
    """DMLC_TPU_SKETCH_BACKEND / DMLC_TPU_BIN_BACKEND pin the streaming
    passes to the host backend (the remote-tunnel mode bench_external
    uses).  Same cuts, same trees as the default path."""
    from dmlc_core_tpu.data.iter import RowBlockIter
    from dmlc_core_tpu.models import HistGBT

    X, y = _synth(1500, 5)
    svm = tmp_path / "p.svm"
    _write_libsvm(svm, X, y)

    # conftest pins jax to CPU devices, so both branches compute on the
    # same backend and exact tree equality is deterministic (this test
    # checks the PINNING CODE PATH, not cross-backend float parity)
    models = {}
    for pinned in (False, True):
        if pinned:
            monkeypatch.setenv("DMLC_TPU_SKETCH_BACKEND", "cpu")
            monkeypatch.setenv("DMLC_TPU_BIN_BACKEND", "cpu")
        else:
            # ambient env (e.g. a bench_external debug session) must not
            # turn this into a vacuous pinned-vs-pinned comparison
            monkeypatch.delenv("DMLC_TPU_SKETCH_BACKEND", raising=False)
            monkeypatch.delenv("DMLC_TPU_BIN_BACKEND", raising=False)
        m = HistGBT(n_trees=4, max_depth=3, n_bins=32)
        it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
        m.fit_external(it, num_col=5)
        it.close()
        models[pinned] = m
    np.testing.assert_allclose(np.asarray(models[True].cuts),
                               np.asarray(models[False].cuts),
                               rtol=1e-6)
    for t0, t1 in zip(models[False].trees, models[True].trees):
        np.testing.assert_array_equal(t0["feat"], t1["feat"])
        np.testing.assert_array_equal(t0["thr"], t1["thr"])
        np.testing.assert_allclose(t0["leaf"], t1["leaf"], rtol=1e-4)


def test_cache_device_matches_default(tmp_path):
    from dmlc_core_tpu.data.iter import RowBlockIter
    from dmlc_core_tpu.models import HistGBT

    X, y = _synth(2000, 5)
    svm = tmp_path / "c.svm"
    _write_libsvm(svm, X, y)

    models = {}
    for cache in (False, True):
        m = HistGBT(n_trees=5, max_depth=3, n_bins=32)
        it = RowBlockIter.create(str(svm), 0, 1, "libsvm")
        m.fit_external(it, num_col=5, cache_device=cache)
        it.close()
        models[cache] = m
    for t0, t1 in zip(models[False].trees, models[True].trees):
        np.testing.assert_array_equal(t0["feat"], t1["feat"])
        np.testing.assert_array_equal(t0["thr"], t1["thr"])
        # cache_device=True runs the in-core engine whose leaf sums come
        # from the histogram cumsum (histgbt precision note), not the
        # page loop's segment_sum — identical splits, ~1e-4 leaf drift
        np.testing.assert_allclose(t0["leaf"], t1["leaf"],
                                   rtol=1e-3, atol=1e-5)
    # post-fit contract parity with fit(): the cached path must leave
    # train_margins() usable (real rows only, padding sliced off)
    tm = models[True].train_margins()
    assert tm.shape[0] == len(y)
    np.testing.assert_allclose(
        tm, models[True].predict(X, output_margin=True), rtol=1e-4,
        atol=1e-5)
