"""bench.py anomaly machinery + rank-objective autodiff oracle.

The official BENCH record's trustworthiness rests on chunk_stats
flagging tunnel-degraded captures; that logic must be tested, not just
shipped.  The second half verifies the RankNet pairwise gradients
against jax.grad/jax.hessian of the explicitly-summed pairwise loss —
an oracle stronger than the learning tests."""

import os
import sys

import pytest
import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import chunk_stats, scaling_summary  # noqa: E402


class TestChunkStats:
    def test_uniform_chunks_no_anomaly(self):
        ct = [(25, 3.0), (50, 6.1), (75, 9.1), (100, 12.2)]
        s = chunk_stats(ct, 100, 12.2)
        assert s["anomaly"] is False
        assert abs(s["rounds_per_sec_median_chunk"] - 25 / 3.05) < 0.2
        assert len(s["chunk_seconds_per_round"]) == 4

    def test_degraded_chunk_flags_anomaly(self):
        # one wedged dispatch: 25 rounds took 40s instead of ~3s —
        # the round-2 capture signature
        ct = [(25, 3.0), (50, 43.0), (75, 46.0), (100, 49.0)]
        s = chunk_stats(ct, 100, 49.0)
        assert s["anomaly"] is True
        # best-chunk still reports the healthy rate
        assert s["rounds_per_sec_best_chunk"] > 8.0

    def test_single_chunk_cannot_flag(self):
        s = chunk_stats([(25, 3.0)], 25, 3.0)
        assert s["anomaly"] is False

    def test_empty_falls_back_to_wall(self):
        s = chunk_stats([], 100, 50.0)
        assert s["anomaly"] is False
        assert s["rounds_per_sec_best_chunk"] == 2.0

    def test_zero_delta_clamps(self):
        # coarse timer on a fast local fit: two chunks arrive at the
        # SAME timestamp — must neither divide by zero nor spuriously
        # flag anomaly against a normal sibling chunk
        s = chunk_stats([(25, 1.0), (50, 1.0), (75, 2.0)], 75, 2.0)
        assert np.isfinite(s["rounds_per_sec_best_chunk"])
        assert np.isfinite(s["rounds_per_sec_median_chunk"])
        # the artifact makes normal siblings look 40000x "slower" than
        # the zero-delta chunk, but nothing is actually slow (40ms/round
        # < the 50ms/round tunnel-stall floor) — must not flag
        assert s["anomaly"] is False

    def test_threshold_boundary(self):
        # exactly 3.0x is NOT an anomaly; just above is
        at = chunk_stats([(10, 1.0), (20, 4.0)], 20, 4.0)
        assert at["anomaly"] is False            # ratio == 3.0
        above = chunk_stats([(10, 1.0), (20, 4.2)], 20, 4.2)
        assert above["anomaly"] is True


class TestScalingSummary:
    def test_perfect_linear_scaling(self):
        s = scaling_summary(8, per_chip_rate=2.0, baseline_rate=2.0)
        assert s["scaling_efficiency"] == 1.0
        assert s["aggregate_rounds_per_sec"] == 16.0
        assert s["chips"] == 8 and s["baseline_chips"] == 1

    def test_issue7_acceptance_bar(self):
        # 8 chips at 70% of the 1-chip per-chip rate = the 0.7 bar
        s = scaling_summary(8, per_chip_rate=1.4, baseline_rate=2.0)
        assert abs(s["scaling_efficiency"] - 0.7) < 1e-9
        assert s["baseline_rounds_per_sec_per_chip"] == 2.0

    def test_superlinear_allowed(self):
        # out-of-core relief: N chips can beat N x 1-chip when the
        # 1-chip run was HBM-thrashing — the summary must not clamp
        s = scaling_summary(4, per_chip_rate=2.5, baseline_rate=2.0)
        assert s["scaling_efficiency"] == 1.25

    def test_degenerate_baseline_returns_none(self):
        assert scaling_summary(8, 2.0, 0.0) is None
        assert scaling_summary(8, 2.0, None) is None
        assert scaling_summary(0, 2.0, 2.0) is None


class TestPairwiseRankAutodiffOracle:
    @pytest.mark.slow
    def test_grad_and_hessian_match_autodiff(self):
        """g must equal jax.grad of the summed pairwise loss and h the
        exact diagonal of its Hessian (RankNet's per-pair rho sums ARE
        the diagonal, not an approximation)."""
        from dmlc_core_tpu.models.histgbt import _PairwiseRank

        rng = np.random.default_rng(0)
        G, Q = 5, 3
        obj = _PairwiseRank(G, block_queries=2)  # exercises query padding
        pred = jnp.asarray(rng.normal(size=Q * G).astype(np.float32))
        rel = rng.integers(0, 3, size=Q * G).astype(np.float32)
        rel[::7] = -1.0                          # pad docs must drop out
        rel_j = jnp.asarray(rel)

        def total_loss(s):
            sq = s.reshape(Q, G)
            rq = rel_j.reshape(Q, G)
            loss = 0.0
            for q in range(Q):
                for i in range(G):
                    for j in range(G):
                        better = ((rq[q, i] > rq[q, j])
                                  & (rq[q, i] >= 0) & (rq[q, j] >= 0))
                        loss = loss + jnp.where(
                            better,
                            jnp.logaddexp(0.0, -(sq[q, i] - sq[q, j])),
                            0.0)
            return loss

        g, h = obj.grad_hess(pred, rel_j)
        g_ref = jax.grad(total_loss)(pred)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-5, atol=1e-6)
        h_ref = jnp.diag(jax.hessian(total_loss)(pred))
        # h floors at 1e-16 for pairless docs; the oracle's true 0s
        # compare within atol
        np.testing.assert_allclose(np.asarray(h), np.asarray(h_ref),
                                   rtol=1e-4, atol=1e-5)
