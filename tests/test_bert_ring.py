"""Tests for ring attention and the BERT dp×tp×sp trainer (config 4).

Oracles: single-device full-softmax attention; sharded-equals-replicated
training (the tp/sp/dp correctness check); KVStore dist_sync vs fused
psum equivalence on the first step."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_core_tpu.models.bert import BERT
from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh, local_mesh
from dmlc_core_tpu.parallel.ring_attention import (
    reference_attention, ring_attention)

TINY = dict(n_layers=2, d_model=32, n_heads=4, d_ff=64, vocab_size=64,
            max_len=32, learning_rate=0.1)


def _batch(B=4, S=32, V=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, V, size=(B, S))
    mask = (rng.uniform(size=(B, S)) < 0.3).astype(np.float32)
    mask[:, 0] = 1.0  # never fully unmasked
    return tokens, tokens.copy(), mask


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("n_seq", [2, 4, 8])
    def test_matches_full_softmax(self, causal, n_seq, rng):
        mesh = Mesh(np.asarray(jax.devices()[:n_seq]), ("seq",))
        B, S, H, D = 2, 8 * n_seq, 3, 8
        q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
                   for _ in range(3))
        f = jax.jit(shard_map(
            partial(ring_attention, axis_name="seq", causal=causal),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False))
        out = np.asarray(f(q, k, v))
        ref = np.asarray(reference_attention(q, k, v, causal=causal))
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_single_device_axis(self, rng):
        # size-1 seq axis: ring degenerates to local attention
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("seq",))
        q = jnp.asarray(rng.normal(size=(1, 8, 2, 4)).astype(np.float32))
        f = jax.jit(shard_map(partial(ring_attention, axis_name="seq"),
                              mesh=mesh, in_specs=(P(None, "seq"),) * 3,
                              out_specs=P(None, "seq"), check_vma=False))
        np.testing.assert_allclose(np.asarray(f(q, q, q)),
                                   np.asarray(reference_attention(q, q, q)),
                                   atol=2e-5)


class TestBERT:
    def test_trains_and_loss_decreases(self):
        mesh = create_mesh(MeshSpec(data=2, model=2, seq=2))
        m = BERT(mesh=mesh, **TINY)
        m.init_params(0)
        tokens, labels, mask = _batch()
        losses = [m.train_step(tokens, labels, mask) for _ in range(10)]
        assert losses[-1] < losses[0]

    def test_sharded_equals_replicated(self):
        """THE tp/sp/dp oracle: an 8-way (2,2,2) mesh must reproduce the
        1-device loss trajectory (bf16 tolerance)."""
        tokens, labels, mask = _batch(seed=5)
        trajs = []
        for mesh in (create_mesh(MeshSpec(data=2, model=2, seq=2)),
                     local_mesh(1)):
            m = BERT(mesh=mesh, **TINY)
            m.init_params(7)
            trajs.append([m.train_step(tokens, labels, mask) for _ in range(4)])
        np.testing.assert_allclose(trajs[0], trajs[1], rtol=2e-2)

    def test_kvstore_first_step_matches_fused(self):
        mesh = create_mesh(MeshSpec(data=4, seq=2))
        tokens, labels, mask = _batch(seed=2)
        lf = BERT(mesh=mesh, grad_sync="fused", **TINY)
        lf.init_params(3)
        lk = BERT(mesh=mesh, grad_sync="kvstore", **TINY)
        lk.init_params(3)
        # loss is computed before the update → step-0 losses match exactly
        assert lf.train_step(tokens, labels, mask) == pytest.approx(
            lk.train_step(tokens, labels, mask), rel=1e-5)
        # and the *second* losses agree too (kvstore = plain SGD vs fused
        # SGD-momentum: first update identical, so second loss matches)
        assert lf.train_step(tokens, labels, mask) == pytest.approx(
            lk.train_step(tokens, labels, mask), rel=2e-2)

    def test_head_divisibility_validated(self):
        from dmlc_core_tpu.base.logging import Error

        mesh = create_mesh(MeshSpec(data=2, model=4))
        with pytest.raises(Error):
            BERT(mesh=mesh, n_layers=1, d_model=24, n_heads=6, d_ff=32,
                 vocab_size=32, max_len=16)
