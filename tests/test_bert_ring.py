"""Tests for ring attention and the BERT dp×tp×sp trainer (config 4).

Oracles: single-device full-softmax attention; sharded-equals-replicated
training (the tp/sp/dp correctness check); KVStore dist_sync vs fused
psum equivalence on the first step."""

from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from dmlc_core_tpu.base.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_core_tpu.models.bert import BERT
from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh, local_mesh
from dmlc_core_tpu.parallel.ring_attention import (
    reference_attention, ring_attention)

TINY = dict(n_layers=2, d_model=32, n_heads=4, d_ff=64, vocab_size=64,
            max_len=32, learning_rate=0.1)


def _batch(B=4, S=32, V=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, V, size=(B, S))
    mask = (rng.uniform(size=(B, S)) < 0.3).astype(np.float32)
    mask[:, 0] = 1.0  # never fully unmasked
    return tokens, tokens.copy(), mask


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("n_seq", [2, 4, 8])
    def test_matches_full_softmax(self, causal, n_seq, rng):
        mesh = Mesh(np.asarray(jax.devices()[:n_seq]), ("seq",))
        B, S, H, D = 2, 8 * n_seq, 3, 8
        q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
                   for _ in range(3))
        f = jax.jit(shard_map(
            partial(ring_attention, axis_name="seq", causal=causal),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False))
        out = np.asarray(f(q, k, v))
        ref = np.asarray(reference_attention(q, k, v, causal=causal))
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_single_device_axis(self, rng):
        # size-1 seq axis: ring degenerates to local attention
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("seq",))
        q = jnp.asarray(rng.normal(size=(1, 8, 2, 4)).astype(np.float32))
        f = jax.jit(shard_map(partial(ring_attention, axis_name="seq"),
                              mesh=mesh, in_specs=(P(None, "seq"),) * 3,
                              out_specs=P(None, "seq"), check_vma=False))
        np.testing.assert_allclose(np.asarray(f(q, q, q)),
                                   np.asarray(reference_attention(q, q, q)),
                                   atol=2e-5)


class TestBERT:
    def test_trains_and_loss_decreases(self):
        mesh = create_mesh(MeshSpec(data=2, model=2, seq=2))
        m = BERT(mesh=mesh, **TINY)
        m.init_params(0)
        tokens, labels, mask = _batch()
        losses = [m.train_step(tokens, labels, mask) for _ in range(10)]
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_sharded_equals_replicated(self):
        """THE tp/sp/dp oracle: an 8-way (2,2,2) mesh must reproduce the
        1-device loss trajectory (bf16 tolerance)."""
        tokens, labels, mask = _batch(seed=5)
        trajs = []
        for mesh in (create_mesh(MeshSpec(data=2, model=2, seq=2)),
                     local_mesh(1)):
            m = BERT(mesh=mesh, **TINY)
            m.init_params(7)
            trajs.append([m.train_step(tokens, labels, mask) for _ in range(4)])
        np.testing.assert_allclose(trajs[0], trajs[1], rtol=2e-2)

    @pytest.mark.slow
    def test_fit_chunked_matches_per_step(self):
        """The scan-chunked multi-step program (fit_chunked, the
        remote-tunnel bench path) must reproduce the per-step train_step
        trajectory exactly: same batch, same 4 steps, same final loss."""
        tokens, labels, mask = _batch(seed=9)
        mesh = create_mesh(MeshSpec(data=2, model=2, seq=2))
        m1 = BERT(mesh=mesh, **TINY)
        m1.init_params(3)
        per_step = [m1.train_step(tokens, labels, mask) for _ in range(4)]
        m2 = BERT(mesh=mesh, **TINY)
        m2.init_params(3)
        loss, secs, chunk_times = m2.fit_chunked(
            tokens, labels, mask, n_steps=4, chunk=2, warmup_chunks=0)
        # warmup_chunks=0 still runs one warm chunk (compile); with
        # chunk=2 the timed region then covers steps 3-6 of the model's
        # life... so compare trajectories by rebuilding: a fresh model
        # with warmup disabled isn't possible — instead check the FIRST
        # chunk's losses against per_step directly via a third model.
        m3 = BERT(mesh=mesh, **TINY)
        m3.init_params(3)
        fn = m3._make_multi(4)
        import jax as _jax
        from jax.sharding import NamedSharding as _NS
        sh = _NS(mesh, P("data", "seq"))
        t = _jax.device_put(np.asarray(tokens, np.int32), sh)
        y = _jax.device_put(np.asarray(labels, np.int32), sh)
        mk = _jax.device_put(np.asarray(mask, np.float32), sh)
        _, _, losses = fn(m3.params, m3.opt_state, t, y, mk)
        np.testing.assert_allclose(np.asarray(losses), per_step, rtol=1e-5)
        assert np.isfinite(loss)
        assert secs > 0
        assert chunk_times[-1][0] == 4      # all steps accounted for

    @pytest.mark.slow
    def test_save_load_roundtrip(self, tmp_path):
        """Checkpoint (Stream/serializer layer) must restore params AND
        momentum so a resumed model continues the exact trajectory."""
        tokens, labels, mask = _batch(seed=11)
        mesh = create_mesh(MeshSpec(data=2, model=2, seq=2))
        m = BERT(mesh=mesh, **TINY)
        m.init_params(5)
        m.train_step(tokens, labels, mask)     # non-zero momentum
        uri = str(tmp_path / "bert.ckpt")
        m.save_model(uri)
        m2 = BERT.load_model(uri, mesh=mesh)
        l_orig = m.train_step(tokens, labels, mask)
        l_load = m2.train_step(tokens, labels, mask)
        np.testing.assert_allclose(l_load, l_orig, rtol=1e-6)
        # wrong-magic file fails loudly
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.parallel.pipeline import PipelineLM
        with pytest.raises(Error, match="magic"):
            PipelineLM.load_model(uri)

    @pytest.mark.slow
    def test_kvstore_first_step_matches_fused(self):
        mesh = create_mesh(MeshSpec(data=4, seq=2))
        tokens, labels, mask = _batch(seed=2)
        lf = BERT(mesh=mesh, grad_sync="fused", **TINY)
        lf.init_params(3)
        lk = BERT(mesh=mesh, grad_sync="kvstore", **TINY)
        lk.init_params(3)
        # loss is computed before the update → step-0 losses match exactly
        assert lf.train_step(tokens, labels, mask) == pytest.approx(
            lk.train_step(tokens, labels, mask), rel=1e-5)
        # and the *second* losses agree too (kvstore = plain SGD vs fused
        # SGD-momentum: first update identical, so second loss matches)
        assert lf.train_step(tokens, labels, mask) == pytest.approx(
            lk.train_step(tokens, labels, mask), rel=2e-2)

    def test_head_divisibility_validated(self):
        from dmlc_core_tpu.base.logging import Error

        mesh = create_mesh(MeshSpec(data=2, model=4))
        with pytest.raises(Error):
            BERT(mesh=mesh, n_layers=1, d_model=24, n_heads=6, d_ff=32,
                 vocab_size=32, max_len=16)


class TestBERTMoE:
    """ffn_type='moe': expert-parallel Switch FFN inside the BERT stack.

    Oracle: a dp×ep mesh must track the unsharded single-device run
    exactly (same params, same tokens — the all_to_all dispatch and the
    expert-axis grad bookkeeping must not change the math)."""

    KW = dict(n_layers=2, d_model=32, n_heads=4, d_ff=64, vocab_size=64,
              max_len=16, learning_rate=0.1, ffn_type="moe", n_experts=4,
              capacity_factor=8.0)

    @pytest.mark.parametrize("partial_mask", [False, True])
    @pytest.mark.slow
    def test_ep_matches_unsharded(self, partial_mask):
        """dp×ep must track the unsharded run exactly — including under
        PARTIAL masks, where the aux must weight routing stats by tokens
        routed, not loss positions (it is computed from globally psummed
        stats).  Capacity is loose here: the drop RULE is per dispatch
        group by design (see test_capacity_pressure_sharded)."""
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
        if partial_mask:
            # skewed density: first half of the batch mostly masked-in,
            # second half mostly masked-out — exactly the case where a
            # mask-weighted LOCAL aux diverges from the global aux
            mask = (rng.random((8, 16)) <
                    np.linspace(0.9, 0.1, 8)[:, None]).astype(np.float32)
        else:
            mask = np.ones((8, 16), np.float32)
        mesh = create_mesh(MeshSpec(data=2, expert=2),
                           devices=jax.devices()[:4])
        m1 = BERT(mesh=mesh, **self.KW)
        m1.init_params(0)
        m0 = BERT(mesh=Mesh(np.asarray(jax.devices()[:1]), ("data",)),
                  **self.KW)
        m0.init_params(0)
        losses = []
        for _ in range(4):
            l1 = m1.train_step(tokens, tokens.copy(), mask)
            l0 = m0.train_step(tokens, tokens.copy(), mask)
            assert abs(l1 - l0) < 2e-4, (l1, l0)
            losses.append(l1)
        if not partial_mask:
            assert losses[-1] < losses[0] - 0.1   # and it learns

    @pytest.mark.slow
    def test_capacity_pressure_sharded(self):
        """Under capacity pressure exact sharded/unsharded parity is NOT
        a contract: capacity binds per dispatch group (each token shard
        keeps its first cap-per-expert tokens — standard Switch), so the
        surviving sets differ.  The contract is: training stays finite
        and learns."""
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
        mask = np.ones((8, 16), np.float32)
        mesh = create_mesh(MeshSpec(data=2, expert=2),
                           devices=jax.devices()[:4])
        m = BERT(mesh=mesh, **{**self.KW, "capacity_factor": 1.0})
        m.init_params(0)
        losses = [m.train_step(tokens, tokens.copy(), mask)
                  for _ in range(6)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0] - 0.05, losses

    def test_moe_requires_fused_sync(self):
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error):
            BERT(grad_sync="kvstore", **{**self.KW, "learning_rate": 0.1})


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_softmax(self, causal, rng):
        from functools import partial

        from dmlc_core_tpu.base.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh
        from dmlc_core_tpu.parallel.ulysses import ulysses_attention
        from dmlc_core_tpu.parallel.ring_attention import reference_attention

        B, S, H, D = 2, 64, 8, 16
        mesh = create_mesh(MeshSpec(seq=8))
        q = jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))

        fn = shard_map(
            partial(ulysses_attention, axis_name="seq", causal=causal),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
        out = np.asarray(jax.jit(fn)(q, k, v))
        want = np.asarray(reference_attention(q, k, v, causal=causal))
        np.testing.assert_allclose(out, want, atol=2e-5, rtol=1e-4)

    def test_head_divisibility_rejected(self, rng):
        from functools import partial

        from dmlc_core_tpu.base.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh
        from dmlc_core_tpu.parallel.ulysses import ulysses_attention

        mesh = create_mesh(MeshSpec(seq=8))
        x = jnp.zeros((1, 64, 6, 8))       # 6 heads, 8 devices
        fn = shard_map(
            partial(ulysses_attention, axis_name="seq"),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"),
            check_vma=False,
        )
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(fn)(x, x, x)

    def test_matches_ring(self, rng):
        """Both SP formulations must agree on the same sharded inputs."""
        from functools import partial

        from dmlc_core_tpu.base.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh
        from dmlc_core_tpu.parallel.ring_attention import ring_attention
        from dmlc_core_tpu.parallel.ulysses import ulysses_attention

        B, S, H, D = 1, 32, 8, 8
        mesh = create_mesh(MeshSpec(seq=4))
        q = jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32))

        def mk(fn):
            return jax.jit(shard_map(
                partial(fn, axis_name="seq", causal=True), mesh=mesh,
                in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
                check_vma=False))

        out_u = np.asarray(mk(ulysses_attention)(q, k, v))
        out_r = np.asarray(mk(ring_attention)(q, k, v))
        np.testing.assert_allclose(out_u, out_r, atol=2e-5, rtol=1e-4)

    def test_bert_trains_with_ulysses(self):
        from dmlc_core_tpu.models.bert import BERT
        from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh

        mesh = create_mesh(MeshSpec(data=2, model=2, seq=2))
        bert = BERT(n_layers=2, d_model=32, n_heads=4, d_ff=64,
                    vocab_size=64, max_len=32, learning_rate=0.1,
                    sp_method="ulysses", mesh=mesh)
        bert.init_params(0)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 64, size=(4, 16))
        mask = np.ones((4, 16), np.float32)
        losses = [bert.train_step(tokens, tokens.copy(), mask)
                  for _ in range(8)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]      # actually learns

    @pytest.mark.slow
    def test_bert_ring_vs_ulysses_first_step(self):
        """Same init, same batch: the two SP methods must produce the same
        first-step loss (both are exact attention)."""
        from dmlc_core_tpu.models.bert import BERT
        from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh

        rng = np.random.default_rng(1)
        tokens = rng.integers(0, 64, size=(2, 16))
        mask = np.ones((2, 16), np.float32)
        losses = {}
        for method in ("ring", "ulysses"):
            mesh = create_mesh(MeshSpec(seq=4))
            b = BERT(n_layers=1, d_model=16, n_heads=4, d_ff=32,
                     vocab_size=64, max_len=32, sp_method=method, mesh=mesh)
            b.init_params(7)
            losses[method] = b.train_step(tokens, tokens.copy(), mask)
        np.testing.assert_allclose(losses["ring"], losses["ulysses"],
                                   rtol=2e-4)

    def test_ulysses_head_check_at_construction(self):
        from dmlc_core_tpu.base.logging import Error
        from dmlc_core_tpu.models.bert import BERT
        from dmlc_core_tpu.parallel.mesh import MeshSpec, create_mesh

        mesh = create_mesh(MeshSpec(model=2, seq=4))
        with pytest.raises(Error, match="n_heads=6"):
            BERT(n_layers=1, d_model=24, n_heads=6, d_ff=32, vocab_size=32,
                 max_len=16, sp_method="ulysses", mesh=mesh)


class TestLocalAttention:
    def test_dispatch_and_correctness_cpu(self, rng):
        from dmlc_core_tpu.ops.attention import flash_eligible, local_attention
        from dmlc_core_tpu.parallel.ring_attention import reference_attention

        # CPU: never flash-eligible; dense path must be exact
        if jax.default_backend() != "tpu":
            assert not flash_eligible(2, 512, 4, 64)
        q = jnp.asarray(rng.normal(size=(2, 64, 4, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 64, 4, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 64, 4, 16)).astype(np.float32))
        out = np.asarray(local_attention(q, k, v, causal=True))
        want = np.asarray(reference_attention(q, k, v, causal=True))
        np.testing.assert_allclose(out, want, atol=1e-6)

    def test_eligibility_rules(self):
        from dmlc_core_tpu.ops.attention import flash_eligible
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("flash eligibility rules are TPU-only")
        assert flash_eligible(2, 512, 4, 64)
        assert not flash_eligible(2, 200, 4, 64)    # seq not /128
        assert not flash_eligible(2, 128, 4, 64)    # too short
        assert not flash_eligible(2, 512, 4, 32)    # head_dim too small
