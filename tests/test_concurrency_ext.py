"""ThreadGroup / ThreadLocalStore / Tracer tests.

Reference test models: thread_group + thread_local behavior mirrors
test/unittest/unittest_thread_group.cc's lifecycle checks (SURVEY.md §4);
Tracer is the §5 tracing superset (no reference counterpart — asserted on
its own contract: Chrome trace JSON).
"""

import json
import threading
import time

import pytest

from dmlc_core_tpu.base.thread_local import ThreadLocalStore
from dmlc_core_tpu.io.thread_group import ThreadGroup
from dmlc_core_tpu.utils.profiler import Tracer, annotate, step_annotation


def test_thread_group_runs_and_joins():
    results = []
    grp = ThreadGroup()
    for i in range(4):
        grp.create(f"w{i}", lambda sd, i=i: results.append(i))
    grp.join_all()
    assert sorted(results) == [0, 1, 2, 3]
    assert grp.size() == 4
    assert sorted(grp.names()) == ["w0", "w1", "w2", "w3"]


def test_thread_group_shutdown_signal():
    started = threading.Event()

    def loop(sd):
        started.set()
        while not sd.requested:
            sd.wait(0.01)

    grp = ThreadGroup()
    t = grp.create("looper", loop)
    assert started.wait(5.0)
    assert t.is_alive()
    grp.request_shutdown_all()
    grp.join_all(timeout=5.0)
    assert not t.is_alive()


def test_thread_group_duplicate_name_rejected():
    grp = ThreadGroup()
    grp.create("dup", lambda sd: None)
    with pytest.raises(Exception):
        grp.create("dup", lambda sd: None)
    grp.join_all()


def test_thread_group_propagates_worker_exception():
    def boom(sd):
        raise ValueError("worker died")

    grp = ThreadGroup()
    grp.create("boom", boom)
    with pytest.raises(ValueError, match="worker died"):
        grp.join_all()


def test_thread_group_context_manager():
    stopped = []

    def loop(sd):
        sd.wait(10.0)
        stopped.append(sd.requested)

    with ThreadGroup() as grp:
        grp.create("cm", loop)
        time.sleep(0.02)
    assert stopped == [True]


def test_thread_local_store_per_thread_instances():
    store = ThreadLocalStore(list)
    main = store.get()
    assert store.get() is main
    seen = {}

    barrier = threading.Barrier(2)

    def worker_waits():
        seen["other"] = store.get()
        barrier.wait()   # registered while alive
        barrier.wait()   # released after the assertion below

    t = threading.Thread(target=worker_waits)
    t.start()
    barrier.wait()
    assert seen["other"] is not main
    assert len(store.instances()) == 2  # both threads still alive
    barrier.wait()
    t.join()
    # dead threads are pruned: their instances are not pinned forever
    assert len(store.instances()) == 1
    store.clear()
    assert store.instances() == []
    assert store.get() is not main  # re-created after clear


def test_tracer_chrome_json(tmp_path):
    tr = Tracer()
    with tr.scope("parse", file="a.rec"):
        tr.instant("mark")
        tr.counter("queue_depth", 3)
    path = tr.save(str(tmp_path / "trace.json"))
    data = json.load(open(path))
    phases = {e["ph"] for e in data["traceEvents"]}
    assert {"X", "i", "C"} <= phases
    x = [e for e in data["traceEvents"] if e["ph"] == "X"][0]
    assert x["name"] == "parse" and x["dur"] >= 0
    assert x["args"]["file"] == "a.rec"


def test_tracer_threads_have_distinct_rows():
    tr = Tracer()

    def work(name):
        with tr.scope(name):
            time.sleep(0.001)

    ts = [threading.Thread(target=work, args=(f"t{i}",)) for i in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    tids = {e["tid"] for e in tr.events()}
    assert len(tids) == 2


def test_annotations_are_safe_noops_anywhere():
    # must never raise, profiler active or not
    with annotate("region"):
        with step_annotation(0):
            pass
