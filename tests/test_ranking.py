"""rank:pairwise objective + ranking metrics.

Oracles: a synthetic learning-to-rank problem with a known scoring
function (pairwise accuracy and ndcg must rise well above chance);
numpy metric cross-checks; 8-device-mesh vs 1-device equivalence (the
shard-local-pairs design claim — groups never straddle shards, so the
mesh trajectory must match single-device bit-for-bit up to f32 psum
rounding); padding/truncation bookkeeping."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.models.ranking import (mean_average_precision, ndcg,
                                          pairwise_accuracy)
from dmlc_core_tpu.parallel.mesh import local_mesh


def _ltr_problem(n_queries=64, docs_lo=5, docs_hi=12, F=6, seed=0):
    """Docs with features; relevance = rank of a hidden linear score."""
    rng = np.random.default_rng(seed)
    Xs, ys, qids = [], [], []
    wtrue = rng.normal(size=F)
    for q in range(n_queries):
        nd = int(rng.integers(docs_lo, docs_hi + 1))
        X = rng.normal(size=(nd, F)).astype(np.float32)
        s = X @ wtrue
        rel = np.zeros(nd, np.float32)
        rel[np.argsort(s)[-2:]] = 1.0        # top-2 docs are relevant
        rel[np.argsort(s)[-1]] = 2.0         # best doc doubly so
        Xs.append(X)
        ys.append(rel)
        qids.append(np.full(nd, q, np.int64))
    return (np.concatenate(Xs), np.concatenate(ys),
            np.concatenate(qids))


class TestRankingMetrics:
    def test_ndcg_perfect_and_inverted(self):
        y = np.array([2.0, 1.0, 0.0, 0.0])
        qid = np.zeros(4, np.int64)
        assert ndcg(y, np.array([4.0, 3.0, 2.0, 1.0]), qid) == 1.0
        inv = ndcg(y, np.array([1.0, 2.0, 3.0, 4.0]), qid)
        assert 0.0 < inv < 0.7
        # all-zero relevance query scores 1.0 (unjudgeable)
        assert ndcg(np.zeros(3), np.arange(3.0), np.zeros(3, np.int64)) == 1.0

    def test_map_and_pairwise_accuracy(self):
        y = np.array([1.0, 0.0, 1.0, 0.0])
        qid = np.array([0, 0, 1, 1], np.int64)
        assert mean_average_precision(y, np.array([2., 1., 1., 2.]), qid) == 0.75
        assert pairwise_accuracy(y, np.array([2., 1., 1., 2.]), qid) == 0.5

    def test_ndcg_at_k_truncates(self):
        y = np.array([0.0, 0.0, 2.0])
        qid = np.zeros(3, np.int64)
        # relevant doc ranked last: ndcg@2 sees only irrelevant docs
        sc = np.array([3.0, 2.0, 1.0])
        assert ndcg(y, sc, qid, k=2) == 0.0
        assert ndcg(y, sc, qid) > 0.0


class TestPairwiseRankObjective:
    @pytest.mark.slow
    def test_learns_to_rank(self):
        X, y, qid = _ltr_problem()
        m = HistGBT(n_trees=40, max_depth=3, n_bins=32,
                    objective="rank:pairwise", learning_rate=0.3)
        m.fit(X, y, qid=qid)
        scores = m.predict(X)
        acc = pairwise_accuracy(y, scores, qid)
        nd = ndcg(y, scores, qid, k=5)
        assert acc > 0.85, acc               # chance = 0.5
        assert nd > 0.85, nd

    @pytest.mark.slow
    def test_mesh_matches_single_device(self):
        """Groups never straddle shards, so pairwise grads are
        shard-local and the 8-way mesh must reproduce the 1-device
        model.  This is the mesh-parity oracle for the in-loss-psum
        gradient bug class (a broken gradient diverges in round 1 by
        O(1), verified during development; the residual mesh-vs-single
        difference is f32 psum summation-order rounding ~1e-7 in leaf
        values, which can flip a near-tie split only after gradients
        shrink — same property as the reference's rabit allreduce — so
        exact tree equality is asserted over the early rounds and
        margin agreement at f32 tolerance)."""
        X, y, qid = _ltr_problem(n_queries=48, seed=3)
        kw = dict(n_trees=4, max_depth=3, n_bins=32,
                  objective="rank:pairwise")
        m8 = HistGBT(mesh=local_mesh(), **kw)       # conftest: 8 devices
        m8.fit(X, y, qid=qid)
        m1 = HistGBT(mesh=Mesh(np.asarray(jax.devices()[:1]), ("data",)),
                     **kw)
        m1.fit(X, y, qid=qid)
        # round 1 sees bit-identical gradients → identical tree
        t8, t1 = m8.trees[0], m1.trees[0]
        np.testing.assert_array_equal(t8["feat"], t1["feat"])
        np.testing.assert_array_equal(t8["thr"], t1["thr"])
        np.testing.assert_allclose(t8["leaf"], t1["leaf"],
                                   rtol=1e-5, atol=1e-6)
        # a shard-count gradient bug would diverge margins O(1) here;
        # legitimate psum rounding stays at f32 epsilon scale
        np.testing.assert_allclose(m8.train_margins(), m1.train_margins(),
                                   atol=1e-4)

    def test_train_margins_unwind_and_truncation(self):
        X, y, qid = _ltr_problem(n_queries=16, docs_lo=3, docs_hi=9,
                                 seed=5)
        m = HistGBT(n_trees=5, max_depth=2, n_bins=16,
                    objective="rank:pairwise", max_group_size=6)
        m.fit(X, y, qid=qid)
        tm = m.train_margins()
        assert tm.shape == y.shape
        kept = ~np.isnan(tm)
        # truncated docs (beyond 6 per query) are NaN; kept ones match
        # predict() on the same rows
        pred = m.predict(X, output_margin=True)
        np.testing.assert_allclose(tm[kept], pred[kept], rtol=1e-4,
                                   atol=1e-5)
        lens = np.bincount(qid.astype(int))
        assert (~kept).sum() == np.maximum(lens - 6, 0).sum()

    def test_qid_validation(self):
        X = np.zeros((4, 2), np.float32)
        y = np.zeros(4, np.float32)
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error, match="needs qid"):
            HistGBT(objective="rank:pairwise").fit(X, y)
        with pytest.raises(Error, match="only valid for rank"):
            HistGBT().fit(X, y, qid=np.zeros(4, np.int64))


def _brute_delta(scores, rel, kind):
    """|Δmetric| of swapping each doc pair's positions in the ranking
    induced by ``scores`` (desc, stable) — the oracle for the vectorized
    ``_pair_weight`` closed forms."""
    G = len(scores)
    order = np.argsort(-scores, kind="stable")

    def metric(ord_):
        r = rel[ord_]
        if kind == "ndcg":
            disc = 1.0 / np.log2(np.arange(2, G + 2))
            dcg = ((2.0 ** r - 1.0) * disc).sum()
            ideal = np.sort(rel)[::-1]
            idcg = ((2.0 ** ideal - 1.0) * disc).sum()
            return dcg / idcg if idcg > 0 else 0.0
        b = (r > 0).astype(np.float64)
        R = b.sum()
        if R == 0:
            return 0.0
        prec = np.cumsum(b) / np.arange(1, G + 1)
        return (prec * b).sum() / R

    base = metric(order)
    pos_of = np.argsort(order)               # rank of each doc
    out = np.zeros((G, G))
    for i in range(G):
        for j in range(G):
            if i == j:
                continue
            o = order.copy()
            o[pos_of[i]], o[pos_of[j]] = o[pos_of[j]], o[pos_of[i]]
            out[i, j] = abs(metric(o) - base)
    return out


class TestLambdaWeights:
    """The LambdaMART pair weights must equal brute-force
    swap-and-rescore |Δmetric| — the closed forms have enough index
    algebra (rank gathers, prefix sums, a/b selection) to deserve an
    oracle."""

    @pytest.mark.parametrize("kind", ["ndcg", "map"])
    def test_matches_brute_force(self, kind):
        import jax.numpy as jnp
        from dmlc_core_tpu.models.gbt_objectives import (_MAPRank,
                                                         _NDCGRank)
        rng = np.random.default_rng(11)
        G = 9
        obj = (_NDCGRank if kind == "ndcg" else _MAPRank)(G)
        for trial in range(5):
            scores = rng.normal(size=G).astype(np.float32)
            rel = rng.integers(0, 4, size=G).astype(np.float32)
            if kind == "map":
                rel = (rel > 1).astype(np.float32)
            sb = jnp.asarray(scores[None])
            rb = jnp.asarray(rel[None])
            better = (rb[:, :, None] > rb[:, None, :])
            w = np.asarray(obj._pair_weight(sb, rb, better))[0]
            brute = _brute_delta(scores, rel.astype(np.float64), kind)
            np.testing.assert_allclose(w, brute, rtol=2e-4, atol=1e-6)

    def test_pads_carry_zero_weight(self):
        import jax.numpy as jnp
        from dmlc_core_tpu.models.gbt_objectives import _NDCGRank
        # two pad docs (rel −1): weights involving them must be 0 and
        # the real docs' weights must equal the pad-free computation at
        # the same rank positions (pads rank last via the +inf key)
        scores = np.array([0.3, -1.2, 2.0, 0.9, -0.5], np.float32)
        rel = np.array([2.0, 0.0, 1.0, -1.0, -1.0], np.float32)
        sb, rb = jnp.asarray(scores[None]), jnp.asarray(rel[None])
        vb = rb >= 0
        better = ((rb[:, :, None] > rb[:, None, :])
                  & vb[:, :, None] & vb[:, None, :])
        w = np.asarray(_NDCGRank(5)._pair_weight(sb, rb, better))[0]
        w = w * np.asarray(better[0])        # weights are consumed masked
        assert (w[3:, :] == 0).all() and (w[:, 3:] == 0).all()
        sb3, rb3 = jnp.asarray(scores[None, :3]), jnp.asarray(rel[None, :3])
        b3 = (rb3[:, :, None] > rb3[:, None, :])
        w3 = np.asarray(_NDCGRank(3)._pair_weight(sb3, rb3, b3))[0]
        np.testing.assert_allclose(w[:3, :3], w3 * np.asarray(b3[0]),
                                   rtol=1e-6)


def _graded_ltr_problem(n_queries=128, docs=30, F=6, seed=0):
    """Head doc (rel 3) identified by a clean feature; rel-1 labels on
    half the tail assigned with NO feature signal.  The tail's ~200
    unlearnable pairs per query dominate RankNet's uniform gradient and
    pull capacity into noise; |ΔNDCG| weighting concentrates on the
    learnable head pairs.  Measured margin (held-out ndcg@10, 40 trees):
    +0.05 to +0.09 across seeds."""
    rng = np.random.default_rng(seed)
    Xs, ys, qids = [], [], []
    for q in range(n_queries):
        X = rng.normal(size=(docs, F)).astype(np.float32)
        rel = np.zeros(docs, np.float32)
        head = int(np.argmax(X[:, 0]))
        rel[head] = 3.0
        tail = [i for i in range(docs) if i != head]
        rel[rng.permutation(tail)[: (docs - 1) // 2]] = 1.0
        Xs.append(X)
        ys.append(rel)
        qids.append(np.full(docs, q, np.int64))
    return (np.concatenate(Xs), np.concatenate(ys),
            np.concatenate(qids))


class TestLambdaMARTObjectives:
    def test_ndcg_and_map_learn(self):
        X, y, qid = _ltr_problem(n_queries=32, seed=2)
        for objective in ("rank:ndcg", "rank:map"):
            m = HistGBT(n_trees=15, max_depth=3, n_bins=32,
                        objective=objective, learning_rate=0.3)
            m.fit(X, y, qid=qid)
            nd = ndcg(y, m.predict(X), qid, k=5)
            assert nd > 0.8, (objective, nd)

    @pytest.mark.slow
    def test_ndcg_beats_pairwise_on_held_out_ndcg10(self):
        Xtr, ytr, qtr = _graded_ltr_problem(seed=0)
        Xte, yte, qte = _graded_ltr_problem(n_queries=64, seed=1)
        kw = dict(n_trees=40, max_depth=3, n_bins=32, learning_rate=0.3)
        m_nd = HistGBT(objective="rank:ndcg", **kw)
        m_nd.fit(Xtr, ytr, qid=qtr)
        m_pw = HistGBT(objective="rank:pairwise", **kw)
        m_pw.fit(Xtr, ytr, qid=qtr)
        nd_nd = ndcg(yte, m_nd.predict(Xte), qte, k=10)
        nd_pw = ndcg(yte, m_pw.predict(Xte), qte, k=10)
        # measured: 0.739 vs 0.650 at these seeds; margin +0.05..+0.09
        # across other seed pairs
        assert nd_nd > nd_pw + 0.02, (nd_nd, nd_pw)
        assert nd_nd > 0.7, nd_nd

    def test_gbtranker_objective_passthrough(self):
        from dmlc_core_tpu.models.sklearn import GBTRanker
        X, y, qid = _ltr_problem(n_queries=16, seed=4)
        r = GBTRanker(n_estimators=8, max_depth=2, n_bins=16,
                      objective="rank:ndcg")
        r.fit(X, y, qid=qid)
        assert r.model.param.objective == "rank:ndcg"
        assert r.score(X, y, qid=qid, k=5) > 0.6
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error, match="rank"):
            GBTRanker(objective="binary:logistic").fit(X, y, qid=qid)
