"""rank:pairwise objective + ranking metrics.

Oracles: a synthetic learning-to-rank problem with a known scoring
function (pairwise accuracy and ndcg must rise well above chance);
numpy metric cross-checks; 8-device-mesh vs 1-device equivalence (the
shard-local-pairs design claim — groups never straddle shards, so the
mesh trajectory must match single-device bit-for-bit up to f32 psum
rounding); padding/truncation bookkeeping."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.models.ranking import (mean_average_precision, ndcg,
                                          pairwise_accuracy)
from dmlc_core_tpu.parallel.mesh import local_mesh


def _ltr_problem(n_queries=64, docs_lo=5, docs_hi=12, F=6, seed=0):
    """Docs with features; relevance = rank of a hidden linear score."""
    rng = np.random.default_rng(seed)
    Xs, ys, qids = [], [], []
    wtrue = rng.normal(size=F)
    for q in range(n_queries):
        nd = int(rng.integers(docs_lo, docs_hi + 1))
        X = rng.normal(size=(nd, F)).astype(np.float32)
        s = X @ wtrue
        rel = np.zeros(nd, np.float32)
        rel[np.argsort(s)[-2:]] = 1.0        # top-2 docs are relevant
        rel[np.argsort(s)[-1]] = 2.0         # best doc doubly so
        Xs.append(X)
        ys.append(rel)
        qids.append(np.full(nd, q, np.int64))
    return (np.concatenate(Xs), np.concatenate(ys),
            np.concatenate(qids))


class TestRankingMetrics:
    def test_ndcg_perfect_and_inverted(self):
        y = np.array([2.0, 1.0, 0.0, 0.0])
        qid = np.zeros(4, np.int64)
        assert ndcg(y, np.array([4.0, 3.0, 2.0, 1.0]), qid) == 1.0
        inv = ndcg(y, np.array([1.0, 2.0, 3.0, 4.0]), qid)
        assert 0.0 < inv < 0.7
        # all-zero relevance query scores 1.0 (unjudgeable)
        assert ndcg(np.zeros(3), np.arange(3.0), np.zeros(3, np.int64)) == 1.0

    def test_map_and_pairwise_accuracy(self):
        y = np.array([1.0, 0.0, 1.0, 0.0])
        qid = np.array([0, 0, 1, 1], np.int64)
        assert mean_average_precision(y, np.array([2., 1., 1., 2.]), qid) == 0.75
        assert pairwise_accuracy(y, np.array([2., 1., 1., 2.]), qid) == 0.5

    def test_ndcg_at_k_truncates(self):
        y = np.array([0.0, 0.0, 2.0])
        qid = np.zeros(3, np.int64)
        # relevant doc ranked last: ndcg@2 sees only irrelevant docs
        sc = np.array([3.0, 2.0, 1.0])
        assert ndcg(y, sc, qid, k=2) == 0.0
        assert ndcg(y, sc, qid) > 0.0


class TestPairwiseRankObjective:
    @pytest.mark.slow
    def test_learns_to_rank(self):
        X, y, qid = _ltr_problem()
        m = HistGBT(n_trees=40, max_depth=3, n_bins=32,
                    objective="rank:pairwise", learning_rate=0.3)
        m.fit(X, y, qid=qid)
        scores = m.predict(X)
        acc = pairwise_accuracy(y, scores, qid)
        nd = ndcg(y, scores, qid, k=5)
        assert acc > 0.85, acc               # chance = 0.5
        assert nd > 0.85, nd

    @pytest.mark.slow
    def test_mesh_matches_single_device(self):
        """Groups never straddle shards, so pairwise grads are
        shard-local and the 8-way mesh must reproduce the 1-device
        model.  This is the mesh-parity oracle for the in-loss-psum
        gradient bug class (a broken gradient diverges in round 1 by
        O(1), verified during development; the residual mesh-vs-single
        difference is f32 psum summation-order rounding ~1e-7 in leaf
        values, which can flip a near-tie split only after gradients
        shrink — same property as the reference's rabit allreduce — so
        exact tree equality is asserted over the early rounds and
        margin agreement at f32 tolerance)."""
        X, y, qid = _ltr_problem(n_queries=48, seed=3)
        kw = dict(n_trees=4, max_depth=3, n_bins=32,
                  objective="rank:pairwise")
        m8 = HistGBT(mesh=local_mesh(), **kw)       # conftest: 8 devices
        m8.fit(X, y, qid=qid)
        m1 = HistGBT(mesh=Mesh(np.asarray(jax.devices()[:1]), ("data",)),
                     **kw)
        m1.fit(X, y, qid=qid)
        # round 1 sees bit-identical gradients → identical tree
        t8, t1 = m8.trees[0], m1.trees[0]
        np.testing.assert_array_equal(t8["feat"], t1["feat"])
        np.testing.assert_array_equal(t8["thr"], t1["thr"])
        np.testing.assert_allclose(t8["leaf"], t1["leaf"],
                                   rtol=1e-5, atol=1e-6)
        # a shard-count gradient bug would diverge margins O(1) here;
        # legitimate psum rounding stays at f32 epsilon scale
        np.testing.assert_allclose(m8.train_margins(), m1.train_margins(),
                                   atol=1e-4)

    def test_train_margins_unwind_and_truncation(self):
        X, y, qid = _ltr_problem(n_queries=16, docs_lo=3, docs_hi=9,
                                 seed=5)
        m = HistGBT(n_trees=5, max_depth=2, n_bins=16,
                    objective="rank:pairwise", max_group_size=6)
        m.fit(X, y, qid=qid)
        tm = m.train_margins()
        assert tm.shape == y.shape
        kept = ~np.isnan(tm)
        # truncated docs (beyond 6 per query) are NaN; kept ones match
        # predict() on the same rows
        pred = m.predict(X, output_margin=True)
        np.testing.assert_allclose(tm[kept], pred[kept], rtol=1e-4,
                                   atol=1e-5)
        lens = np.bincount(qid.astype(int))
        assert (~kept).sum() == np.maximum(lens - 6, 0).sum()

    def test_qid_validation(self):
        X = np.zeros((4, 2), np.float32)
        y = np.zeros(4, np.float32)
        from dmlc_core_tpu.base.logging import Error
        with pytest.raises(Error, match="needs qid"):
            HistGBT(objective="rank:pairwise").fit(X, y)
        with pytest.raises(Error, match="only valid for rank"):
            HistGBT().fit(X, y, qid=np.zeros(4, np.int64))
