"""Remote filesystem backends against in-process fake servers (no egress).

Each fake implements the minimal REST surface its backend speaks (S3 XML,
WebHDFS JSON, Azure blob XML, GCS JSON), backed by a shared dict — so the
whole URI-driven stack (Stream.create → InputSplit sharding → RecordIO)
is exercised over "remote" storage hermetically, mirroring how the
reference left S3/HDFS untested in CI but we do better.
"""

import datetime
import json
import os
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_core_tpu.base.metrics import default_registry
from dmlc_core_tpu.io.input_split import InputSplit
from dmlc_core_tpu.io.recordio import encode_records
from dmlc_core_tpu.io.s3_filesys import sigv4_headers
from dmlc_core_tpu.io.stream import Stream


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------

class _FakeBase(BaseHTTPRequestHandler):
    store: dict  # class attr: key "container/blob" -> bytes
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # silence
        pass

    def _send(self, status, body=b"", headers=None):
        self.send_response(status)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""

    def _range(self, blob, header="Range"):
        rng = self.headers.get(header)
        if not rng:
            return 200, blob
        lo, _, hi = rng.split("=")[1].partition("-")
        lo = int(lo)
        hi = int(hi) if hi else len(blob) - 1
        return 206, blob[lo:hi + 1]


class _S3Fake(_FakeBase):
    """GET/HEAD/PUT objects, ListObjectsV2, multipart upload."""

    uploads: dict = {}

    def do_HEAD(self):
        key = self.path.lstrip("/").split("?")[0]
        key = urllib.parse.unquote(key)
        if key in self.store:
            # HEAD: Content-Length advertises the blob size, no body follows
            self.send_response(200)
            self.send_header("Content-Length", str(len(self.store[key])))
            self.end_headers()
        else:
            self._send(404)

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
        key = urllib.parse.unquote(parsed.path.lstrip("/"))
        if "list-type" in q:  # bucket listing: path is "/bucket"
            bucket = key.split("/")[0]
            prefix = q.get("prefix", "")
            items = sorted(k for k in self.store
                           if k.startswith(f"{bucket}/")
                           and k[len(bucket) + 1:].startswith(prefix))
            contents = "".join(
                f"<Contents><Key>{k[len(bucket) + 1:]}</Key>"
                f"<Size>{len(self.store[k])}</Size></Contents>"
                for k in items)
            xml = (f'<ListBucketResult xmlns="http://s3.amazonaws.com/doc/'
                   f'2006-03-01/">{contents}</ListBucketResult>')
            self._send(200, xml.encode())
            return
        if key in self.store:
            status, body = self._range(self.store[key])
            self._send(status, body)
        else:
            self._send(404)

    def do_PUT(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
        key = urllib.parse.unquote(parsed.path.lstrip("/"))
        body = self._body()
        if "partNumber" in q:
            self.uploads.setdefault(q["uploadId"], {})[int(q["partNumber"])] = body
            self._send(200, b"", {"ETag": f'"part{q["partNumber"]}"'})
            return
        self.store[key] = body
        self._send(200)

    def do_POST(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True))
        key = urllib.parse.unquote(parsed.path.lstrip("/"))
        if "uploads" in q:
            uid = f"up{len(self.uploads)}"
            self.uploads[uid] = {}
            self._send(200, (f"<InitiateMultipartUploadResult><UploadId>{uid}"
                             f"</UploadId></InitiateMultipartUploadResult>").encode())
            return
        if "uploadId" in q:
            self._body()
            parts = self.uploads.pop(q["uploadId"])
            self.store[key] = b"".join(parts[i] for i in sorted(parts))
            self._send(200, b"<CompleteMultipartUploadResult/>")
            return
        self._send(400)


class _HDFSFake(_FakeBase):
    """WebHDFS: GETFILESTATUS, LISTSTATUS, OPEN, CREATE/APPEND w/ redirect."""

    def _q(self):
        parsed = urllib.parse.urlsplit(self.path)
        return (urllib.parse.unquote(parsed.path.replace("/webhdfs/v1", "", 1)),
                dict(urllib.parse.parse_qsl(parsed.query)))

    def do_GET(self):
        path, q = self._q()
        op = q.get("op", "").upper()
        key = path.lstrip("/")
        if op == "GETFILESTATUS":
            if key in self.store:
                st = {"type": "FILE", "length": len(self.store[key])}
            elif any(k.startswith(key.rstrip("/") + "/") for k in self.store):
                st = {"type": "DIRECTORY", "length": 0}
            else:
                self._send(404, b'{"RemoteException":{}}')
                return
            self._send(200, json.dumps({"FileStatus": st}).encode())
        elif op == "LISTSTATUS":
            prefix = key.rstrip("/") + "/" if key else ""
            children = sorted({k[len(prefix):].split("/")[0]
                               for k in self.store if k.startswith(prefix)})
            sts = [{"pathSuffix": c, "type": "FILE",
                    "length": len(self.store[prefix + c])}
                   for c in children if (prefix + c) in self.store]
            self._send(200, json.dumps(
                {"FileStatuses": {"FileStatus": sts}}).encode())
        elif op == "OPEN":
            blob = self.store.get(key)
            if blob is None:
                self._send(404)
                return
            off = int(q.get("offset", 0))
            length = int(q.get("length", len(blob) - off))
            self._send(200, blob[off:off + length])
        else:
            self._send(400)

    def do_PUT(self):
        path, q = self._q()
        if q.get("op", "").upper() == "CREATE":
            if "redirected" not in q:
                loc = (f"http://{self.headers['Host']}/webhdfs/v1{path}"
                       f"?op=CREATE&redirected=1")
                self._send(307, b"", {"Location": loc})
                return
            self.store[path.lstrip("/")] = self._body()
            self._send(201)
        else:
            self._send(400)

    def do_POST(self):
        path, q = self._q()
        if q.get("op", "").upper() == "APPEND":
            if "redirected" not in q:
                loc = (f"http://{self.headers['Host']}/webhdfs/v1{path}"
                       f"?op=APPEND&redirected=1")
                self._send(307, b"", {"Location": loc})
                return
            self.store[path.lstrip("/")] += self._body()
            self._send(200)
        else:
            self._send(400)


class _AzureFake(_FakeBase):
    blocks: dict = {}

    def do_HEAD(self):
        key = urllib.parse.unquote(
            urllib.parse.urlsplit(self.path).path.lstrip("/"))
        if key in self.store:
            self.send_response(200)
            self.send_header("Content-Length", str(len(self.store[key])))
            self.end_headers()
        else:
            self._send(404)

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        key = urllib.parse.unquote(parsed.path.lstrip("/"))
        if q.get("comp") == "list":
            container = key.split("/")[0]
            prefix = q.get("prefix", "")
            blobs = "".join(
                f"<Blob><Name>{k[len(container) + 1:]}</Name><Properties>"
                f"<Content-Length>{len(self.store[k])}</Content-Length>"
                f"</Properties></Blob>"
                for k in sorted(self.store)
                if k.startswith(f"{container}/")
                and k[len(container) + 1:].startswith(prefix))
            xml = (f"<EnumerationResults><Blobs>{blobs}</Blobs>"
                   f"<NextMarker/></EnumerationResults>")
            self._send(200, xml.encode())
            return
        if key in self.store:
            status, body = self._range(self.store[key], "x-ms-range")
            self._send(status, body)
        else:
            self._send(404)

    def do_PUT(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        key = urllib.parse.unquote(parsed.path.lstrip("/"))
        body = self._body()
        if q.get("comp") == "block":
            self.blocks.setdefault(key, {})[q["blockid"]] = body
            self._send(201)
        elif q.get("comp") == "blocklist":
            import re
            ids = re.findall(rb"<Latest>(.*?)</Latest>", body)
            blocks = self.blocks.pop(key, {})
            self.store[key] = b"".join(blocks[i.decode()] for i in ids)
            self._send(201)
        else:
            self.store[key] = body
            self._send(201)


class _GCSFake(_FakeBase):
    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        path = parsed.path
        if path.startswith("/download/storage/v1/b/"):
            _, _, rest = path.partition("/b/")
            bucket, _, obj = rest.partition("/o/")
            key = f"{bucket}/{urllib.parse.unquote(obj)}"
            if key not in self.store:
                self._send(404)
                return
            status, body = self._range(self.store[key])
            self._send(status, body)
            return
        if path.startswith("/storage/v1/b/") and "/o/" in path:
            _, _, rest = path.partition("/b/")
            bucket, _, obj = rest.partition("/o/")
            key = f"{bucket}/{urllib.parse.unquote(obj)}"
            if key in self.store:
                self._send(200, json.dumps(
                    {"name": urllib.parse.unquote(obj),
                     "size": str(len(self.store[key]))}).encode())
            else:
                self._send(404)
            return
        if path.startswith("/storage/v1/b/"):  # list
            bucket = path.split("/b/")[1].split("/")[0]
            prefix = q.get("prefix", "")
            items = [{"name": k[len(bucket) + 1:],
                      "size": str(len(self.store[k]))}
                     for k in sorted(self.store)
                     if k.startswith(f"{bucket}/")
                     and k[len(bucket) + 1:].startswith(prefix)]
            self._send(200, json.dumps({"items": items}).encode())
            return
        self._send(400)

    def do_POST(self):
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query))
        if parsed.path.startswith("/upload/storage/v1/b/"):
            bucket = parsed.path.split("/b/")[1].split("/")[0]
            self.store[f"{bucket}/{q['name']}"] = self._body()
            self._send(200, b"{}")
            return
        self._send(400)


def _flaky(handler_cls, every=3):
    """Wrap a fake so every ``every``-th request fails first: writes get
    a 503 + ``Retry-After: 0`` (server answered → not applied → any
    method retries), reads rotate 503 / connection-reset-before-body /
    connection-cut-mid-body (ambiguous transport failures only an
    idempotent request may retry).  Deterministic: one shared counter."""
    counter = {"n": 0}

    class Flaky(handler_cls):
        def _fault_due(self):
            counter["n"] += 1
            if counter["n"] % every == 0:
                self.close_connection = True
                return counter["n"] // every
            return 0

        def _reject(self):
            self._send(503, b"busy", {"Retry-After": "0"})

        def do_GET(self):  # noqa: N802
            k = self._fault_due()
            if not k:
                super().do_GET()
            elif k % 3 == 1:
                self._reject()
            elif k % 3 == 2:
                # reset before any response bytes
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            else:
                # connection cut mid-body: promise 64 bytes, send half
                self.send_response(206)
                self.send_header("Content-Length", "64")
                self.end_headers()
                self.wfile.write(b"x" * 32)
                self.wfile.flush()
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

        def do_HEAD(self):  # noqa: N802
            if self._fault_due():
                self._reject()
            else:
                super().do_HEAD()

        def do_PUT(self):  # noqa: N802
            if self._fault_due():
                self._body()  # drain, then reject without applying
                self._reject()
            else:
                super().do_PUT()

        def do_POST(self):  # noqa: N802
            if self._fault_due():
                self._body()
                self._reject()
            else:
                super().do_POST()

    return Flaky


def _retries_total():
    c = default_registry().counter("retries_total", labels=("op",))
    return sum(s["value"] for s in c._snap())


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

@pytest.fixture()
def serve():
    servers = []

    def start(handler_cls, store):
        handler = type("H", (handler_cls,), {"store": store})
        srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_address[1]}"

    yield start
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def _roundtrip(uri_of, monkeypatch):
    """Shared backend exercise: write/read/list/split over the fake."""
    # write + read back
    payload = os.urandom(100_000)
    with Stream.create(uri_of("dir/blob.bin"), "w") as s:
        s.write(payload[:40_000])
        s.write(payload[40_000:])
    with Stream.create(uri_of("dir/blob.bin"), "r") as s:
        assert s.read_all() == payload
    # seek/ranged read
    s = Stream.create_for_read(uri_of("dir/blob.bin"))
    s.seek(99_990)
    assert s.read(100) == payload[99_990:]
    s.close()
    # recordio shards + sharded InputSplit over the remote listing
    all_recs = []
    for k in range(3):
        recs = [f"r{k}-{i}".encode() * (i % 5 + 1) for i in range(200)]
        all_recs += recs
        with Stream.create(uri_of(f"shards/part-{k}.rec"), "w") as s:
            s.write(encode_records(recs))
    seen = []
    for part in range(4):
        sp = InputSplit.create(uri_of("shards"), part, 4, "recordio",
                               threaded=False)
        seen += list(sp)
        sp.close()
    assert sorted(seen) == sorted(all_recs)


def test_s3(serve, monkeypatch):
    store = {}
    endpoint = serve(_S3Fake, store)
    monkeypatch.setenv("S3_ENDPOINT", endpoint)
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIDEXAMPLE")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "secret")
    _roundtrip(lambda p: f"s3://bkt/{p}", monkeypatch)


def test_s3_multipart(serve, monkeypatch):
    store = {}
    endpoint = serve(_S3Fake, store)
    monkeypatch.setenv("S3_ENDPOINT", endpoint)
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    big = os.urandom(20 << 20)  # > 2 parts at 8 MiB
    with Stream.create("s3://bkt/big.bin", "w") as s:
        s.write(big)
    assert store["bkt/big.bin"] == big


def test_hdfs(serve, monkeypatch):
    store = {}
    endpoint = serve(_HDFSFake, store)
    monkeypatch.setenv("DMLC_HDFS_NAMENODE", endpoint)
    _roundtrip(lambda p: f"hdfs:///{p}", monkeypatch)


def test_azure(serve, monkeypatch):
    store = {}
    endpoint = serve(_AzureFake, store)
    monkeypatch.setenv("AZURE_BLOB_ENDPOINT", endpoint)
    _roundtrip(lambda p: f"azure://ctr/{p}", monkeypatch)


def test_gcs(serve, monkeypatch):
    store = {}
    endpoint = serve(_GCSFake, store)
    monkeypatch.setenv("GCS_ENDPOINT", endpoint)
    _roundtrip(lambda p: f"gs://bkt/{p}", monkeypatch)


# ---------------------------------------------------------------------------
# fault matrix: the same round trips over deliberately lossy fakes
# ---------------------------------------------------------------------------

def _fault_roundtrip(serve, monkeypatch, handler_cls, endpoint_var, uri_of):
    """Full backend exercise against a flaky fake: results must be
    byte-identical to the fault-free run and the retry layer must have
    actually worked (nonzero ``dmlc_retries_total`` delta)."""
    monkeypatch.setenv("DMLC_RETRY_BASE_S", "0.002")
    monkeypatch.setenv("DMLC_RETRY_MAX_ATTEMPTS", "6")
    store = {}
    endpoint = serve(_flaky(handler_cls), store)
    monkeypatch.setenv(endpoint_var, endpoint)
    before = _retries_total()
    _roundtrip(uri_of, monkeypatch)
    assert _retries_total() > before, "flaky fake never triggered a retry"


def test_s3_fault_matrix(serve, monkeypatch):
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    _fault_roundtrip(serve, monkeypatch, _S3Fake, "S3_ENDPOINT",
                     lambda p: f"s3://bkt/{p}")


def test_hdfs_fault_matrix(serve, monkeypatch):
    _fault_roundtrip(serve, monkeypatch, _HDFSFake, "DMLC_HDFS_NAMENODE",
                     lambda p: f"hdfs:///{p}")


def test_azure_fault_matrix(serve, monkeypatch):
    _fault_roundtrip(serve, monkeypatch, _AzureFake, "AZURE_BLOB_ENDPOINT",
                     lambda p: f"azure://ctr/{p}")


def test_gcs_fault_matrix(serve, monkeypatch):
    _fault_roundtrip(serve, monkeypatch, _GCSFake, "GCS_ENDPOINT",
                     lambda p: f"gs://bkt/{p}")


def test_s3_multipart_part_retry(serve, monkeypatch):
    """Every few part PUTs are rejected with a 503 first; the per-part
    retry must reassemble the exact object (no duplicated or dropped
    parts)."""
    monkeypatch.setenv("DMLC_RETRY_BASE_S", "0.002")
    monkeypatch.setenv("DMLC_RETRY_MAX_ATTEMPTS", "6")
    store = {}
    endpoint = serve(_flaky(_S3Fake, every=2), store)
    monkeypatch.setenv("S3_ENDPOINT", endpoint)
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    big = os.urandom(20 << 20)  # 3 parts at 8 MiB
    before = _retries_total()
    with Stream.create("s3://bkt/big.bin", "w") as s:
        s.write(big)
    assert store["bkt/big.bin"] == big
    assert _retries_total() > before


def test_client_side_fault_injection_roundtrip(serve, monkeypatch):
    """The deterministic injector (http error/reset + stream truncate)
    against a WELL-BEHAVED fake: byte-identical results, faults counted."""
    from dmlc_core_tpu.base import faultinject as fi

    monkeypatch.setenv("DMLC_RETRY_BASE_S", "0.002")
    monkeypatch.setenv("DMLC_RETRY_MAX_ATTEMPTS", "8")
    store = {}
    endpoint = serve(_S3Fake, store)
    monkeypatch.setenv("S3_ENDPOINT", endpoint)
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    payload = os.urandom(400_000)
    with fi.inject("http:error=503:p=0.2,stream:truncate:p=0.3", seed=5):
        with Stream.create("s3://bkt/f.bin", "w") as s:
            s.write(payload)
        with Stream.create("s3://bkt/f.bin", "r") as s:
            assert s.read_all() == payload
        assert fi.fired_total() > 0


def test_write_aborts_on_exception(serve, monkeypatch):
    """An exception inside `with Stream.create(..., 'w')` must not publish
    a truncated object."""
    store = {}
    endpoint = serve(_S3Fake, store)
    monkeypatch.setenv("S3_ENDPOINT", endpoint)
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    with pytest.raises(RuntimeError):
        with Stream.create("s3://bkt/partial.bin", "w") as s:
            s.write(b"x" * 1000)
            raise RuntimeError("consumer failure mid-write")
    assert "bkt/partial.bin" not in store


def test_sigv4_known_vector():
    """AWS SigV4 test vector (GET, us-east-1, service 'service')."""
    now = datetime.datetime(2015, 8, 30, 12, 36, 0,
                            tzinfo=datetime.timezone.utc)
    hdrs = sigv4_headers(
        "GET", "https://example.amazonaws.com/?Param1=value1&Param2=value2",
        {}, b"",
        access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        region="us-east-1", service="service", now=now)
    # the signature from the published aws-sig-v4-test-suite
    # (get-vanilla-query-order-key-case) with these exact inputs
    assert hdrs["x-amz-date"] == "20150830T123600Z"
    assert "Credential=AKIDEXAMPLE/20150830/us-east-1/service/aws4_request" \
        in hdrs["Authorization"]
    assert hdrs["Authorization"].endswith(
        "Signature=b97d918cfa904a5beff61c982a1b6f458b799221646efd99d3219ec94cdf2500")


class _PlainHttpHandler(_FakeBase):
    """Static file server with HEAD + Range support (http_filesys tests)."""

    def _blob(self):
        return self.store.get(self.path.lstrip("/"))

    def do_HEAD(self):  # noqa: N802
        blob = self._blob()
        if blob is None:
            self._send(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(blob)))
        self.send_header("Accept-Ranges", "bytes")
        self.end_headers()

    def do_GET(self):  # noqa: N802
        blob = self._blob()
        if blob is None:
            self._send(404)
            return
        status, body = self._range(blob)
        self._send(status, body)


def test_http_readonly(serve):
    payload = os.urandom(50_000)
    base = serve(_PlainHttpHandler, {"data/f.bin": payload})
    uri = f"{base}/data/f.bin"
    with Stream.create(uri, "r") as s:
        assert s.read_all() == payload
    s = Stream.create_for_read(uri)
    s.seek(49_000)
    assert s.read(2000) == payload[49_000:]
    s.close()
    # writes rejected
    from dmlc_core_tpu.base.logging import Error
    with pytest.raises(Error):
        Stream.create(uri, "w")


class _NoRangeHandler(_FakeBase):
    """Server that advertises nothing and ignores Range (probe must fatal)."""

    def _blob(self):
        return self.store.get(self.path.lstrip("/"))

    def do_HEAD(self):  # noqa: N802
        blob = self._blob()
        if blob is None:
            self._send(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()

    def do_GET(self):  # noqa: N802
        blob = self._blob()
        self._send(200, blob if blob is not None else b"")


def test_http_range_probe_rejects_nonranged_server(serve):
    from dmlc_core_tpu.base.logging import Error

    base = serve(_NoRangeHandler, {"f.bin": b"x" * 1000})
    with pytest.raises(Error):
        Stream.create(f"{base}/f.bin", "r")
