"""dmlc_core_tpu — a TPU-native infrastructure substrate with the capabilities
of dmlc-core (the common library under XGBoost / MXNet / TVM).

This is NOT a port of the C++ reference.  It keeps dmlc-core's *contracts* —
URI-dispatched ``Stream`` I/O, sharded ``InputSplit`` + RecordIO, LibSVM/CSV/
LibFM parsers producing CSR ``RowBlock``s, threaded prefetch iterators,
binary/JSON serialization, the typed ``Parameter``/``Registry`` system and the
``DMLC_*`` distributed-launch ABI — while re-founding the *engines* on
JAX/XLA/Pallas:

* parsed row blocks become ``jax.Array`` device buffers on a named mesh,
* the ThreadedIter/InputSplit pipeline feeds TPU infeed (double-buffered
  ``device_put``),
* the Rabit socket allreduce/broadcast engine is replaced by XLA collectives
  (``psum`` / ``all_gather`` / ``ppermute``) over a GSPMD mesh — ICI within a
  slice, DCN across hosts,

so XGBoost-style histogram sync and an MXNet-KVStore-shaped API ride TPU
interconnect with no CUDA in the build.

Reference parity map (see SURVEY.md §2 for the full inventory):

==========================  =================================================
reference (dmlc-core)        here
==========================  =================================================
include/dmlc/logging.h       dmlc_core_tpu.base.logging
include/dmlc/timer.h         dmlc_core_tpu.base.timer
include/dmlc/parameter.h     dmlc_core_tpu.base.parameter  (+ get_env)
include/dmlc/registry.h      dmlc_core_tpu.base.registry
include/dmlc/config.h        dmlc_core_tpu.base.config
include/dmlc/io.h            dmlc_core_tpu.io.stream
include/dmlc/memory_io.h     dmlc_core_tpu.io.memory_io
include/dmlc/serializer.h    dmlc_core_tpu.io.serializer
include/dmlc/json.h          dmlc_core_tpu.io.json_io
include/dmlc/recordio.h      dmlc_core_tpu.io.recordio
include/dmlc/threadediter.h  dmlc_core_tpu.io.threaded_iter
include/dmlc/concurrency.h   dmlc_core_tpu.io.concurrency
src/io/*filesys*             dmlc_core_tpu.io.filesystem
src/io/*split*               dmlc_core_tpu.io.input_split
include/dmlc/data.h          dmlc_core_tpu.data.row_block / .iter
src/data/*parser*            dmlc_core_tpu.data.parsers (+ cpp/fastparse.cc)
tracker/dmlc_tracker/        dmlc_core_tpu.tracker
(rabit, consumer-side)       dmlc_core_tpu.parallel.collectives
(ps-lite, consumer-side)     dmlc_core_tpu.parallel.kvstore
(none — TPU-first additions) dmlc_core_tpu.ops, dmlc_core_tpu.models
==========================  =================================================
"""

__version__ = "0.3.0"          # keep in sync with pyproject.toml

import os as _os

_force_n = _os.environ.get("DMLC_TPU_FORCE_CPU", "").strip()
if _force_n and _force_n != "0":
    # opt-in env hook: pin jax to N virtual CPU devices BEFORE anything
    # touches a backend.  Lets examples/tools run safely on TPU
    # terminals (where the platform plugin overrides JAX_PLATFORMS)
    # without per-script code — CI smoke-runs every example this way.
    # "0"/empty = disabled; anything else must be a device count.
    if not _force_n.isdigit():
        raise ValueError(
            f"DMLC_TPU_FORCE_CPU={_force_n!r}: expected a device count "
            f"(e.g. 2) or 0/unset to disable")
    from dmlc_core_tpu.utils import force_cpu_devices as _force_cpu

    _force_cpu(int(_force_n))

from dmlc_core_tpu.base import lockcheck as _lockcheck

if _lockcheck.env_enabled():
    # DMLC_LOCKCHECK=1: every threading.Lock/RLock created after this
    # point participates in the cross-thread lock-order graph; cycles
    # are reported via base.lockcheck.violations()/check() (see
    # doc/static_analysis.md).
    _lockcheck.install()

from dmlc_core_tpu.base import racecheck as _racecheck

if _racecheck.env_enabled():
    # DMLC_RACECHECK=1: vector-clock happens-before race detection over
    # the opt-in classes (tracker/router/batcher/autoscaler/registry/
    # ConcurrentBlockingQueue); implies lockcheck (traced locks are the
    # HB vocabulary).  Races are reported via base.racecheck.races()/
    # check() (see doc/static_analysis.md).
    _racecheck.install()

from dmlc_core_tpu.base import leakcheck as _leakcheck

if _leakcheck.env_enabled():
    # DMLC_LEAKCHECK=1: every socket/thread/subprocess/tempfile created
    # through repo code after this point is traced with its creation
    # stack; whatever is still live at drill exit is reported via
    # base.leakcheck.leaks()/check() (see doc/static_analysis.md).
    # Installed AFTER racecheck so the Thread.start hooks chain.
    _leakcheck.install()

from dmlc_core_tpu.base import jitcheck as _jitcheck

if _jitcheck.env_enabled():
    # DMLC_JITCHECK=1: every XLA compilation after this point is traced
    # with its repo-frame stack and phase tag (warmup until
    # base.jitcheck.steady() is called); steady-state compiles fail
    # base.jitcheck.check() (see doc/static_analysis.md).
    _jitcheck.install()

from dmlc_core_tpu.base.logging import (  # noqa: F401
    Error,
    LOG,
    CHECK,
    CHECK_EQ,
    CHECK_NE,
    CHECK_LT,
    CHECK_GT,
    CHECK_LE,
    CHECK_GE,
    CHECK_NOTNULL,
    set_log_level,
)
from dmlc_core_tpu.base.timer import get_time  # noqa: F401
from dmlc_core_tpu.base.parameter import Parameter, field, get_env  # noqa: F401
from dmlc_core_tpu.base.registry import Registry  # noqa: F401
