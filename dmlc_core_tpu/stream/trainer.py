"""Long-running online trainer: tail → warm-start boost → publish.

:class:`OnlineTrainer` turns the batch HistGBT engine into a continuous
learner without touching its kernels: each **refresh** gathers one chunk
of fresh events from a :class:`~dmlc_core_tpu.stream.tail.
RecordIOTailer`, rebuilds the sliding training window, and calls the
model's ordinary ``fit`` — which, on a model that already has trees, is
xgb_model-semantics **continued training**: bin cuts are kept (the
existing trees' thresholds are only meaningful against them), margins
replay from the current ensemble on device, and ``param.n_trees`` new
trees are boosted on the window.

Recency weighting: the window holds the last ``window_chunks`` chunks;
chunk age ``a`` (0 = newest) carries sample weight ``decay^a``.  With
``decay == 1.0`` no weights are passed at all, which pins the documented
**warm-start parity contract** (tests/test_stream.py): an OnlineTrainer
with ``window_chunks=1, decay=1.0`` fed chunks A then B produces
*bit-identical* predictions to ``model.fit(A); model.fit(B)`` on the
same parameterization — online learning is exactly repeated continued
fits, not a new training algorithm.

Compile behavior: refreshes deliberately keep shapes stable.  A refresh
only fits on a **full** chunk of exactly ``chunk_rows`` rows — a partial
gather (timeout/stop mid-chunk) stays in a pending buffer, counts toward
the next refresh, and ``refresh`` returns ``None``, so every chunk in
the window has the same row count by construction.  The window grows
chunk by chunk until it holds ``window_chunks`` chunks and then stays at
that row count forever: after the first ``window_chunks`` refreshes
every ``fit`` re-dispatches the already-compiled (and AOT/
persistent-cache warmed — doc/performance.md) round programs with zero
trace/compile work.  Steady-state refresh cost is boost + publish only
— ``DMLC_JITCHECK=1`` (base/jitcheck) verifies exactly this in
``bench.py --stream`` / ``--prodsim``; before the full-chunk policy a
timeout-starved partial window (591 rows instead of 1024) recompiled
the whole round-program set mid-stream.  Pending rows are consumed from
the tailer but **uncommitted** (commits only happen on a fitting
refresh), so a crash replays them — at-least-once is preserved.  A
finite stream's partial tail can be trained explicitly with
:meth:`~OnlineTrainer.flush`.

Each refresh optionally flows through a :class:`~dmlc_core_tpu.stream.
publisher.ModelPublisher` (staged registry publish, holdout eval gate,
rollback on regression) and then commits the tailer cursor — commit
AFTER publish, so a crash between the two re-trains and re-publishes the
chunk instead of silently dropping it (at-least-once end to end).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base import knobs as _knobs
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import CHECK, LOG
from dmlc_core_tpu.stream.dataset import decode_dense_events
from dmlc_core_tpu.stream.tail import RecordIOTailer

__all__ = ["OnlineTrainer"]

_TM = None


def _trainer_metrics():
    global _TM
    if _TM is None:
        r = _metrics.default_registry()
        _TM = {
            "refresh_s": r.histogram(
                "stream_refresh_seconds",
                "wall seconds per online refresh (gather + boost + "
                "publish)", labels=("trainer",)),
            "rows": r.counter(
                "stream_refresh_rows_total",
                "fresh event rows consumed by online refreshes",
                labels=("trainer",)),
        }
    return _TM


class OnlineTrainer:
    """Drive continuous warm-start boosting over a tailed event stream.

    ``model`` is any trainer with batch-continuation ``fit(X, y,
    weight=…)`` semantics (HistGBT and family); its ``param.n_trees`` is
    the number of trees added per refresh.  ``decode`` maps a list of
    raw records to ``(X, y)`` — default is the dense event codec
    (:func:`~dmlc_core_tpu.stream.dataset.decode_dense_events`) with
    ``n_features``.
    """

    def __init__(self, model: Any, tailer: RecordIOTailer,
                 n_features: Optional[int] = None,
                 decode: Optional[Callable[[List[bytes]],
                                           Tuple[np.ndarray,
                                                 np.ndarray]]] = None,
                 chunk_rows: Optional[int] = None,
                 window_chunks: Optional[int] = None,
                 decay: Optional[float] = None,
                 publisher: Optional[Any] = None,
                 commit_cursor: bool = True,
                 name: str = "online"):
        CHECK(decode is not None or n_features is not None,
              "OnlineTrainer: pass decode= or n_features= (for the "
              "default dense event codec)")
        self.model = model
        self.tailer = tailer
        self.name = name
        self._decode = decode or (
            lambda recs: decode_dense_events(recs, n_features))
        self.chunk_rows = int(chunk_rows
                              if chunk_rows is not None
                              else _knobs.value("DMLC_STREAM_CHUNK_ROWS"))
        self.window_chunks = int(
            window_chunks if window_chunks is not None
            else _knobs.value("DMLC_STREAM_WINDOW_CHUNKS"))
        self.decay = float(decay if decay is not None
                           else _knobs.value("DMLC_STREAM_DECAY"))
        CHECK(self.chunk_rows > 0, "OnlineTrainer: chunk_rows must be > 0")
        CHECK(self.window_chunks > 0,
              "OnlineTrainer: window_chunks must be > 0")
        CHECK(0.0 < self.decay <= 1.0,
              f"OnlineTrainer: decay must be in (0, 1], got {self.decay}")
        self.publisher = publisher
        self.commit_cursor = commit_cursor
        self._window: Deque[Tuple[np.ndarray, np.ndarray]] = deque(
            maxlen=self.window_chunks)
        #: records gathered but short of a full chunk — consumed from
        #: the tailer, not yet trained on, not yet committed
        self._pending: List[bytes] = []
        self.refreshes = 0
        self.last_refresh: Optional[Dict[str, Any]] = None

    # -- window assembly -------------------------------------------------
    def _window_matrix(self) -> Tuple[np.ndarray, np.ndarray,
                                      Optional[np.ndarray]]:
        """Concatenate the window chunks (oldest first) with per-chunk
        decay weights.  ``decay == 1.0`` returns ``weight=None`` so the
        single-chunk case is bit-identical to an unweighted batch fit
        (the parity contract)."""
        chunks = list(self._window)
        X = (np.concatenate([c[0] for c in chunks])
             if len(chunks) > 1 else chunks[0][0])
        y = (np.concatenate([c[1] for c in chunks])
             if len(chunks) > 1 else chunks[0][1])
        if self.decay == 1.0:
            return X, y, None
        ages = range(len(chunks) - 1, -1, -1)     # oldest chunk first
        w = np.concatenate([
            np.full(len(c[1]), self.decay ** a, np.float32)
            for c, a in zip(chunks, ages)])
        return X, y, w

    # -- the refresh loop ------------------------------------------------
    def refresh(self, timeout: Optional[float] = None,
                stop: Optional[Callable[[], bool]] = None
                ) -> Optional[Dict[str, Any]]:
        """One refresh: gather fresh records until a full chunk of
        exactly ``chunk_rows`` exists (bounded by ``timeout``), boost,
        publish, commit.  A partial gather stays pending for the next
        call — fixed fit shapes — and returns None, as does an empty
        one (timeout/stop)."""
        t0 = time.monotonic()
        got = self.tailer.wait_records(
            self.chunk_rows - len(self._pending),
            timeout=timeout, stop=stop)
        self._pending.extend(got)
        if len(self._pending) < self.chunk_rows:
            return None
        records, self._pending = self._pending, []
        return self._fit_chunk(records, t0)

    def flush(self) -> Optional[Dict[str, Any]]:
        """Train on the pending partial chunk (finite-stream tail).
        The fit shape is off-grid, so under ``DMLC_JITCHECK=1`` call
        this before ``steady()`` or accept the recompile."""
        if not self._pending:
            return None
        records, self._pending = self._pending, []
        return self._fit_chunk(records, time.monotonic())

    def _fit_chunk(self, records: List[bytes],
                   t0: float) -> Dict[str, Any]:
        X, y = self._decode(records)
        self._window.append((X, y))
        Xw, yw, ww = self._window_matrix()
        t_fit = time.monotonic()
        self.model.fit(Xw, yw, weight=ww)
        out: Dict[str, Any] = {
            "refresh": self.refreshes + 1,
            "rows": len(records),
            "window_rows": len(yw),
            "records_total": self.tailer.records_seen,
            "trees_total": len(getattr(self.model, "trees", ())),
            "fit_seconds": round(time.monotonic() - t_fit, 4),
        }
        if self.publisher is not None:
            out.update(self.publisher.publish(
                self.model, source=f"stream:{self.name}"))
        if self.commit_cursor:
            out["cursor_version"] = self.tailer.commit()
        out["refresh_seconds"] = round(time.monotonic() - t0, 4)
        self.refreshes += 1
        self.last_refresh = out
        if _metrics.enabled():
            m = _trainer_metrics()
            m["refresh_s"].observe(out["refresh_seconds"],
                                   trainer=self.name)
            m["rows"].inc(len(records), trainer=self.name)
        LOG("INFO", "stream.trainer %s: refresh %d — %d rows (window %d), "
            "%d trees%s", self.name, out["refresh"], out["rows"],
            out["window_rows"], out["trees_total"],
            (f", v{out['version']} "
             f"{'activated' if out.get('activated') else 'ROLLED BACK'}"
             if "version" in out else ""))
        return out

    def run(self, max_refreshes: Optional[int] = None,
            timeout: Optional[float] = None,
            stop: Optional[Callable[[], bool]] = None
            ) -> List[Dict[str, Any]]:
        """Refresh until ``stop()``, ``max_refreshes``, or a refresh
        that gathers nothing within ``timeout``.  Returns the per-
        refresh summaries."""
        out: List[Dict[str, Any]] = []
        while max_refreshes is None or len(out) < max_refreshes:
            if stop is not None and stop():
                break
            r = self.refresh(timeout=timeout, stop=stop)
            if r is None:
                break
            out.append(r)
        return out
