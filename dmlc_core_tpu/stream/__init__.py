"""Streaming / online learning: the continuous train→serve loop.

The paper's data plane (RecordIO shards + ``input_split`` + threaded
prefetch) existed here only as a batch path; this package closes ROADMAP
item 5 — a live event stream becomes continuously-updated low-latency
predictions — by composing shelf parts the repo already had:

* :mod:`dataset` — the enabler refactor: ONE streaming
  :class:`Dataset` abstraction over ``io/threaded_iter`` +
  ``data/parsers`` + ``data/device_feed``, shared by batch trainers
  (``data/iter.iter_dense_slabs`` is now an adapter over it) and the
  online path; plus the dense event codec.
* :mod:`tail` — :class:`RecordIOTailer`: follow a growing append-only
  RecordIO shard set with torn-tail tolerance, magic-marker resync past
  corruption, jittered idle backoff, and a crash-safe cursor persisted
  through ``parallel.checkpoint`` atomic writes.
* :mod:`trainer` — :class:`OnlineTrainer`: warm-start-boost the
  existing HistGBT ensemble on fresh chunks (sliding window /
  exponentially-decayed sample weights; steady-state shapes stay fixed
  so refreshes never recompile).
* :mod:`publisher` — :class:`ModelPublisher`: snapshot each refresh,
  stage it into ``serve.ModelRegistry``, eval-gate on a holdout window,
  atomically activate — or roll back on regression.

One command takes a synthetic live stream to served predictions
(``examples/stream_gbt.py``); ``bench.py --stream`` measures staleness
(event appended → servable prediction).  See doc/streaming.md.
"""

from dmlc_core_tpu.stream.dataset import (Dataset,  # noqa: F401
                                          decode_dense_events,
                                          encode_dense_event,
                                          encode_dense_events)
from dmlc_core_tpu.stream.publisher import ModelPublisher  # noqa: F401
from dmlc_core_tpu.stream.tail import (RecordIOTailer,  # noqa: F401
                                       TailCursor)
from dmlc_core_tpu.stream.trainer import OnlineTrainer  # noqa: F401

__all__ = [
    "Dataset", "RecordIOTailer", "TailCursor", "OnlineTrainer",
    "ModelPublisher", "encode_dense_event", "encode_dense_events",
    "decode_dense_events",
]
