"""Tail an append-only, growing RecordIO shard set.

The live-ingest end of the train→serve loop (doc/streaming.md): a
:class:`RecordIOTailer` follows a file, directory, glob pattern or
``';'`` list of RecordIO shards that one or more writers keep appending
to (and may extend with new shard files), delivering each record exactly
once per process in (file, offset) order.

Three failure realities of tailing live files, and their handling:

* **torn tail** — a writer mid-append leaves a partial header or payload
  at EOF.  The scanner only consumes *complete* records; torn bytes stay
  unconsumed and are re-examined on the next poll once the append lands
  (:mod:`~dmlc_core_tpu.io.recordio`'s reader got the same tolerance for
  the non-tailing case).
* **corruption** — a byte range that is not a valid record part.  The
  scanner resyncs by searching 4-byte-aligned offsets for the RecordIO
  magic with a record-*start* cflag (the escaped-payload guarantee makes
  aligned magic an unambiguous boundary), skips the garbage, and counts
  it on ``dmlc_stream_resyncs_total``.
* **crash** — the consumer dies mid-refresh.  :meth:`commit` persists
  the ``{file: offset}`` cursor through ``parallel.checkpoint``'s
  atomic-write path (temp + rename, CRC sidecar, previous-version
  retention), so a SIGKILL during the commit itself leaves the prior
  cursor intact and a restart re-delivers only the uncommitted suffix —
  at-least-once delivery with an atomically-advancing floor.

Idle polling backs off through
:class:`~dmlc_core_tpu.base.resilience.RetryPolicy` (exponential + full
jitter from ``DMLC_STREAM_POLL_S`` up to ``DMLC_STREAM_MAX_BACKOFF_S``),
resetting to the base interval the moment data arrives.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base import knobs as _knobs
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import CHECK, LOG
from dmlc_core_tpu.base.resilience import RetryPolicy
from dmlc_core_tpu.io.filesystem import FileInfo, FileSystem, URI
from dmlc_core_tpu.io.recordio import (RECORDIO_MAGIC_BYTES, decode_chunk,
                                       decode_flag, decode_length)
from dmlc_core_tpu.io.stream import SeekStream

__all__ = ["RecordIOTailer", "TailCursor"]

#: the ``like`` structure of a persisted cursor: one JSON-bytes leaf
_CURSOR_LIKE = {"cursor": np.zeros(0, np.uint8)}

_SM = None


def _stream_metrics():
    global _SM
    if _SM is None:
        r = _metrics.default_registry()
        _SM = {
            "records": r.counter(
                "stream_records_total",
                "records delivered by RecordIO tailers", labels=("tail",)),
            "resyncs": r.counter(
                "stream_resyncs_total",
                "magic-marker resyncs past corrupt/unparseable tail bytes",
                labels=("tail",)),
            "commits": r.counter(
                "stream_cursor_commits_total",
                "tail cursor checkpoints persisted", labels=("tail",)),
        }
    return _SM


class TailCursor:
    """The durable position of a tailer: consumed byte offset per file
    plus the running record count.  Serialized as JSON inside one
    checkpoint leaf (``parallel.checkpoint`` handles atomicity/CRC)."""

    def __init__(self, offsets: Optional[Dict[str, int]] = None,
                 records: int = 0):
        self.offsets: Dict[str, int] = dict(offsets or {})
        self.records = records

    def to_leaf(self) -> np.ndarray:
        blob = json.dumps({"files": self.offsets,
                           "records": self.records}).encode()
        return np.frombuffer(blob, np.uint8)

    @classmethod
    def from_leaf(cls, leaf: np.ndarray) -> "TailCursor":
        d = json.loads(np.asarray(leaf, np.uint8).tobytes().decode())
        return cls(offsets={str(k): int(v)
                            for k, v in d.get("files", {}).items()},
                   records=int(d.get("records", 0)))


def _pad4(n: int) -> int:
    return ((n + 3) >> 2) << 2


class RecordIOTailer:
    """Follow a growing RecordIO shard set, delivering complete records.

    ``uri`` may name a single file, a directory (its files sorted by
    path — shard writers must name new shards lexicographically after
    old ones), a glob pattern, or a ``';'``-separated list.  The set is
    re-listed on every poll, so shards that appear later are picked up.

    Single-consumer by design: all methods must be called from one
    thread (the online trainer's loop).  Delivery is at-least-once
    across process restarts — records delivered after the last
    :meth:`commit` are re-delivered on resume — and exactly-once within
    a process lifetime.
    """

    def __init__(self, uri: str, cursor_uri: Optional[str] = None,
                 poll_s: Optional[float] = None,
                 max_backoff_s: Optional[float] = None,
                 name: str = "tail"):
        self.name = name
        self._paths = [p for p in uri.split(";") if p]
        CHECK(len(self._paths) > 0, f"RecordIOTailer: empty uri {uri!r}")
        self._fs = FileSystem.get_instance(URI(self._paths[0]))
        CHECK(self._fs is not None,
              f"RecordIOTailer: no filesystem for {uri!r}")
        if poll_s is None:
            poll_s = float(_knobs.value("DMLC_STREAM_POLL_S"))
        if max_backoff_s is None:
            max_backoff_s = float(_knobs.value("DMLC_STREAM_MAX_BACKOFF_S"))
        CHECK(poll_s > 0, "RecordIOTailer: poll_s must be positive")
        #: jittered idle backoff: attempt k sleeps ≤ poll_s·2^(k-1),
        #: capped — the RetryPolicy backoff curve without its retry loop
        self._backoff = RetryPolicy(max_attempts=1 << 30,
                                    deadline_s=float("inf"),
                                    base_backoff_s=poll_s,
                                    max_backoff_s=max_backoff_s)
        self._cursor_uri = (cursor_uri if cursor_uri is not None
                            else str(_knobs.value("DMLC_STREAM_CURSOR")))
        self._streams: Dict[str, SeekStream] = {}
        self._commits = 0
        self.resyncs = 0
        cur = TailCursor()
        if self._cursor_uri:
            from dmlc_core_tpu.parallel.checkpoint import load_checkpoint

            version, state = load_checkpoint(self._cursor_uri, _CURSOR_LIKE)
            if version > 0:
                cur = TailCursor.from_leaf(state["cursor"])
                LOG("INFO", "stream.tail %s: resuming from cursor v%d "
                    "(%d records, %d files)", name, version, cur.records,
                    len(cur.offsets))
                self._commits = version
        #: consumed byte offset per file path (advances only over
        #: complete records and skipped garbage)
        self._offsets: Dict[str, int] = cur.offsets
        #: records delivered since the cursor epoch began (persisted)
        self.records_seen = cur.records

    # -- discovery -------------------------------------------------------
    def _list_files(self) -> List[FileInfo]:
        out: List[FileInfo] = []
        for path in self._paths:
            try:
                out += self._fs.list_directory_ex(URI(path))
            except (OSError, IOError, FileNotFoundError):
                continue  # shard dir not created yet — normal at startup
        return sorted((f for f in out if f.size > 0), key=lambda f: f.path)

    # -- scanning --------------------------------------------------------
    def _find_record_start(self, buf: bytes, pos: int,
                           base_off: int) -> Optional[int]:
        """Next 4-byte-aligned (in file coordinates) offset ≥ ``pos``
        holding the magic with a record-start cflag and a fully readable
        header.  None when no verifiable candidate exists in ``buf``."""
        n = len(buf)
        p = buf.find(RECORDIO_MAGIC_BYTES, pos)
        while p >= 0:
            if (base_off + p) % 4 == 0 and p + 8 <= n:
                lrec = int.from_bytes(buf[p + 4:p + 8], "little")
                if decode_flag(lrec) in (0, 1):
                    return p
            p = buf.find(RECORDIO_MAGIC_BYTES, p + 1)
        return None

    def _scan(self, buf: bytes, base_off: int,
              max_records: Optional[int] = None) -> Tuple[int, List[bytes],
                                                          int]:
        """Extract complete records from ``buf`` (whose first byte sits
        at file offset ``base_off``), at most ``max_records`` of them.

        Returns ``(consumed, records, skipped)``: ``consumed`` bytes may
        be advanced past (complete records + resync'd garbage); a torn
        trailing record — and everything beyond ``max_records`` — is
        left unconsumed, so the cursor never runs ahead of what was
        actually delivered."""
        n = len(buf)
        pos = 0
        consumed = 0
        skipped = 0
        cur_start: Optional[int] = None
        spans: List[Tuple[int, int]] = []   # complete-record byte ranges
        while pos + 8 <= n:
            if max_records is not None and len(spans) >= max_records:
                break
            if buf[pos:pos + 4] != RECORDIO_MAGIC_BYTES:
                # corruption at what should be a record boundary: resync
                cur_start = None
                q = self._find_record_start(buf, pos + 1, base_off)
                if q is None:
                    # garbage to (near) the end; keep a 7-byte tail so a
                    # header straddling the next append is still found
                    tail_keep = min(n - pos, 7)
                    skipped += n - tail_keep - pos
                    consumed = max(consumed, n - tail_keep)
                    pos = n
                    break
                skipped += q - pos
                consumed = max(consumed, q)
                pos = q
                continue
            lrec = int.from_bytes(buf[pos + 4:pos + 8], "little")
            clen, cflag = decode_length(lrec), decode_flag(lrec)
            part_end = pos + 8 + _pad4(clen)
            if part_end > n:
                break                       # torn tail — wait for append
            if cflag in (0, 1):
                cur_start = pos
            if cflag in (2, 3) and cur_start is None:
                # continuation without a start (resync landed mid-record)
                skipped += part_end - pos
                consumed = max(consumed, part_end)
            elif cflag in (0, 3):
                spans.append((cur_start, part_end))  # type: ignore[arg-type]
                consumed = max(consumed, part_end)
                cur_start = None
            pos = part_end
        if skipped:
            self.resyncs += 1
            LOG("WARNING", "stream.tail %s: resync skipped %d corrupt "
                "bytes near offset %d", self.name, skipped,
                base_off + consumed)
            if _metrics.enabled():
                _stream_metrics()["resyncs"].inc(1, tail=self.name)
        records: List[bytes] = []
        # merge contiguous spans so decode_chunk runs once per clean run
        i = 0
        while i < len(spans):
            s, e = spans[i]
            while i + 1 < len(spans) and spans[i + 1][0] == e:
                e = spans[i + 1][1]
                i += 1
            records.extend(decode_chunk(buf[s:e]))
            i += 1
        return consumed, records, skipped

    # -- reading ---------------------------------------------------------
    def _open(self, path: str) -> SeekStream:
        s = self._streams.get(path)
        if s is None:
            s = self._fs.open_for_read(URI(path))
            self._streams[path] = s
        return s

    def poll(self, max_records: Optional[int] = None) -> List[bytes]:
        """Deliver complete unseen records available right now
        (non-blocking beyond the storage reads), at most
        ``max_records``.  Undelivered surplus stays unconsumed — the
        cursor floor only ever covers delivered records."""
        out: List[bytes] = []
        for info in self._list_files():
            if max_records is not None and len(out) >= max_records:
                break
            path = info.path
            off = self._offsets.get(path, 0)
            if info.size < off:
                # shrunk file = truncated/rewritten shard; restart it
                LOG("WARNING", "stream.tail %s: %s shrank (%d < %d) — "
                    "re-reading from 0", self.name, path, info.size, off)
                self._streams.pop(path, None)
                off = 0
            if info.size <= off:
                continue
            try:
                stream = self._open(path)
                stream.seek(off)
                buf = stream.read(info.size - off)
            except (OSError, IOError):
                self._streams.pop(path, None)
                continue                   # transient — retry next poll
            consumed, records, _skipped = self._scan(
                buf, off, None if max_records is None
                else max_records - len(out))
            if consumed:
                self._offsets[path] = off + consumed
            out.extend(records)
        if out:
            self.records_seen += len(out)
            if _metrics.enabled():
                _stream_metrics()["records"].inc(len(out), tail=self.name)
        return out

    def wait_records(self, n: int = 1, timeout: Optional[float] = None,
                     stop: Optional[Callable[[], bool]] = None
                     ) -> List[bytes]:
        """Poll (with jittered exponential idle backoff) until exactly
        ``n`` records are gathered, ``timeout`` seconds pass, or
        ``stop()`` goes true.  Never returns more than ``n`` (surplus
        stays unconsumed for the next call); may return fewer on
        timeout/stop, possibly none."""
        out: List[bytes] = []
        deadline = None if timeout is None else time.monotonic() + timeout
        idle = 0
        while len(out) < n:
            if stop is not None and stop():
                break
            got = self.poll(max_records=n - len(out))
            if got:
                out.extend(got)
                idle = 0
                continue
            if deadline is not None and time.monotonic() >= deadline:
                break
            idle += 1
            delay = self._backoff.backoff_for(idle)
            if deadline is not None:
                delay = min(delay, max(deadline - time.monotonic(), 0.0))
            if delay > 0:
                time.sleep(delay)
        return out

    # -- durability ------------------------------------------------------
    def cursor(self) -> TailCursor:
        """The current (in-memory) position."""
        return TailCursor(self._offsets, self.records_seen)

    def commit(self) -> int:
        """Atomically persist the cursor (monotone version); returns the
        committed version.  Requires a ``cursor_uri``.  A crash during
        the commit leaves the previous cursor intact (checkpoint's
        temp-file + rename semantics), so resume never skips records."""
        CHECK(self._cursor_uri != "",
              "RecordIOTailer.commit: no cursor_uri configured")
        from dmlc_core_tpu.parallel.checkpoint import checkpoint

        self._commits += 1
        checkpoint(self._cursor_uri, {"cursor": self.cursor().to_leaf()},
                   version=self._commits)
        if _metrics.enabled():
            _stream_metrics()["commits"].inc(1, tail=self.name)
        return self._commits

    def close(self) -> None:
        for s in self._streams.values():
            try:
                s.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._streams.clear()

    def __enter__(self) -> "RecordIOTailer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
