"""Auto-publish online refreshes into the serve registry, safely.

The last hop of the train→serve loop: after each refresh the trainer
hands its (continuously mutated) model here, and :class:`ModelPublisher`

1. **snapshots** it — a byte-level ``save_model``/``load_model`` round
   trip via :func:`~dmlc_core_tpu.serve.registry.clone_model`, because
   the registry must never hold a reference the next refresh will
   mutate under in-flight batches;
2. **stages** the snapshot — ``ModelRegistry.publish(…,
   activate=False)`` retains it under a monotone version without moving
   the current pointer, so live traffic never sees an unvetted model;
3. **eval-gates** it on the holdout window: candidate score vs the
   score of the version traffic is currently served from, with relative
   tolerance ``DMLC_STREAM_EVAL_GATE`` (scores are lower-is-better;
   default metric is mean squared error of ``predict`` vs labels);
4. **activates** on pass (the registry's atomic hot-swap — in-flight
   batches finish on the old version) or **rolls back** on regression:
   the current pointer simply never moves, the poisoned candidate stays
   retained for postmortem, and ``dmlc_stream_refreshes_total{outcome=
   "rolled_back"}`` counts the save.

With ``checkpoint_uri`` set, every *activated* snapshot is also written
as a versioned serving checkpoint (atomic, CRC'd, previous version
retained — ``parallel.checkpoint`` semantics), so a crashed process
restarts by ``registry.load(checkpoint_uri)`` into bit-identical
predictions for the last good version.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base import knobs as _knobs
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import LOG
from dmlc_core_tpu.serve.registry import (ModelRegistry, checkpoint_model,
                                          clone_model)

__all__ = ["ModelPublisher"]

_PM = None


def _pub_metrics():
    global _PM
    if _PM is None:
        r = _metrics.default_registry()
        _PM = {
            "refreshes": r.counter(
                "stream_refreshes_total",
                "model refreshes published to the serve registry, by "
                "gate outcome (activated|rolled_back)",
                labels=("publisher", "outcome")),
        }
    return _PM


def _mse_metric(model: Any, X: np.ndarray, y: np.ndarray) -> float:
    """Default eval-gate score: mean squared error of ``predict``
    against labels (lower is better; works for every model family the
    registry serves)."""
    pred = np.asarray(model.predict(X), np.float64).reshape(len(y), -1)
    if pred.shape[1] > 1:                      # multiclass: 0/1 error
        return float(np.mean(pred.argmax(axis=1) != y))
    return float(np.mean((pred[:, 0] - np.asarray(y, np.float64)) ** 2))


class ModelPublisher:
    """Staged publish + eval gate + rollback over a
    :class:`~dmlc_core_tpu.serve.registry.ModelRegistry`.

    ``holdout=(Xh, yh)`` enables the gate; without it every snapshot
    activates unconditionally.  ``metric(model, Xh, yh) -> float``
    overrides the score (lower is better).  ``gate`` is the relative
    regression tolerance (default ``DMLC_STREAM_EVAL_GATE``): a
    candidate is rejected when ``score > active_score · (1 + gate) +
    1e-12``.  The first publish always activates (there is nothing to
    regress against)."""

    def __init__(self, registry: ModelRegistry,
                 holdout: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                 metric: Optional[Callable[[Any, np.ndarray, np.ndarray],
                                           float]] = None,
                 gate: Optional[float] = None,
                 checkpoint_uri: Optional[str] = None,
                 name: str = "stream"):
        self.registry = registry
        self.holdout = holdout
        self.metric = metric or _mse_metric
        self.gate = float(gate if gate is not None
                          else _knobs.value("DMLC_STREAM_EVAL_GATE"))
        self.checkpoint_uri = checkpoint_uri
        self.name = name
        #: score of the version currently serving traffic (None before
        #: the first activation or when no holdout is configured)
        self.active_score: Optional[float] = None
        self.activations = 0
        self.rollbacks = 0

    def publish(self, model: Any,
                source: Optional[str] = None) -> Dict[str, Any]:
        """Snapshot → staged publish → gate → activate or roll back.
        Returns ``{version, activated, score, baseline}``."""
        snapshot = clone_model(model)
        version = self.registry.publish(snapshot, source=source or self.name,
                                        activate=False)
        score = baseline = None
        activated = True
        if self.holdout is not None:
            Xh, yh = self.holdout
            score = self.metric(snapshot, Xh, yh)
            baseline = self.active_score
            if baseline is not None and not (
                    score <= baseline * (1.0 + self.gate) + 1e-12):
                activated = False
        if activated:
            self.registry.activate(version)
            self.activations += 1
            if score is not None:
                self.active_score = score
            if self.checkpoint_uri:
                checkpoint_model(self.checkpoint_uri, snapshot, version)
        else:
            self.rollbacks += 1
            LOG("WARNING", "stream.publisher %s: v%d REJECTED by eval "
                "gate (score %.6g vs active %.6g, tolerance %.3g) — "
                "traffic stays on v%s", self.name, version, score,
                baseline, self.gate, self.registry.current_version())
        if _metrics.enabled():
            _pub_metrics()["refreshes"].inc(
                1, publisher=self.name,
                outcome="activated" if activated else "rolled_back")
        return {"version": version, "activated": activated,
                "score": score, "baseline": baseline}
