"""One streaming ``Dataset`` abstraction for batch AND online paths.

ROADMAP item 5's enabler refactor: before this module, every consumer
wired the data plane by hand — ``data/iter.py`` batch iterators stitched
parsers to slab staging, ``data/device_feed.py`` wrapped ad-hoc host
iterators, and an online path would have needed a third copy of the same
plumbing.  ``Dataset`` is the shared composition layer over the three
existing primitives:

* **source** — a rewindable record/block producer: a
  :class:`~dmlc_core_tpu.data.parsers.Parser` over an
  :class:`~dmlc_core_tpu.io.input_split.InputSplit`
  (:meth:`Dataset.from_uri`), a
  :class:`~dmlc_core_tpu.data.iter.RowBlockIter`
  (:meth:`Dataset.from_row_iter`), an in-memory iterable, or a live
  :class:`~dmlc_core_tpu.stream.tail.RecordIOTailer` chunk stream
  (single-pass, for the online trainer);
* **transform** — :meth:`map` per-item, :meth:`dense_slabs` (CSR row
  blocks → bounded dense ``(X, y, w)`` staging slabs — the logic that
  used to live privately in ``data/iter.iter_dense_slabs``, which is now
  a one-line adapter over this method);
* **pipeline** — :meth:`prefetch` moves production onto a
  :class:`~dmlc_core_tpu.io.threaded_iter.ThreadedIter` producer thread,
  :meth:`device_feed` hands the whole dataset to
  :class:`~dmlc_core_tpu.data.device_feed.DeviceFeed` for double-
  buffered ``device_put`` onto a mesh sharding.

Batch trainers and the online ``stream.trainer`` consume the same object
— the refactor the train→serve loop needed (doc/streaming.md).

The module also defines the **dense event codec** the streaming examples,
bench and tests share: one event = one RecordIO record holding
``[label, f0 … f{F-1}]`` as little-endian f32 — trivially appendable,
seekable by the tailer, and decodable as one ``np.frombuffer`` per chunk.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base.logging import CHECK

__all__ = ["Dataset", "encode_dense_event", "encode_dense_events",
           "decode_dense_events"]


def _dense_slab_iter(blocks: Iterable[Any], num_col: int,
                     batch_rows: int) -> Iterator[Tuple[np.ndarray,
                                                        np.ndarray,
                                                        np.ndarray]]:
    """RowBlock stream → dense ``(X, y, w)`` slabs of ≤ ``batch_rows``
    rows, staged into reused buffers (yielded arrays are VIEWS — copy
    before advancing).  Shared by ``Dataset.dense_slabs`` and the
    ``data/iter.iter_dense_slabs`` adapter."""
    CHECK(batch_rows > 0, f"dense_slabs: batch_rows must be "
                          f"positive, got {batch_rows}")
    stage = np.empty((batch_rows, num_col), np.float32)
    ys = np.empty(batch_rows, np.float32)
    ws = np.empty(batch_rows, np.float32)
    filled = 0
    for b in blocks:
        CHECK(b.nnz == 0 or b.max_index < num_col,
              f"dense_slabs: page has feature index {b.max_index} "
              f"but the consumer expects {num_col} features")
        done = 0
        while done < b.size:
            take = min(b.size - done, batch_rows - filled)
            b.slice(done, done + take).to_dense_into(
                stage[filled:filled + take])
            ys[filled:filled + take] = b.label[done:done + take]
            if b.weight is not None:
                ws[filled:filled + take] = b.weight[done:done + take]
            else:
                ws[filled:filled + take] = 1.0
            filled += take
            done += take
            if filled == batch_rows:
                yield stage, ys, ws
                filled = 0
    if filled:
        yield stage[:filled], ys[:filled], ws[:filled]


class Dataset:
    """A composable, re-iterable stream of items (records, row blocks,
    slabs, batches …).

    Construction wraps a ``make_iter`` thunk; every ``iter(ds)`` call
    invokes it again, so epoch rewind is "make a fresh iterator" — the
    contract :class:`~dmlc_core_tpu.data.device_feed.DeviceFeed` already
    expects.  Single-pass sources (a live tailer) simply raise or return
    empty on the second pass; batch sources (parsers, row iters) rewind
    via their own ``before_first``.
    """

    def __init__(self, make_iter: Callable[[], Iterator[Any]],
                 name: str = "dataset"):
        self._make_iter = make_iter
        #: metrics/threaded-iter label for pipelined stages
        self.name = name

    def __iter__(self) -> Iterator[Any]:
        return self._make_iter()

    # -- sources ---------------------------------------------------------
    @classmethod
    def from_uri(cls, uri: str, part: int = 0, nparts: int = 1,
                 format: Optional[str] = None,
                 nthread: int = 0) -> "Dataset":
        """Parse a (sharded) text URI into CSR
        :class:`~dmlc_core_tpu.data.row_block.RowBlock` items via the
        ``data_parser`` registry (``?format=`` URI key, libsvm default).
        Rewind re-reads through ``Parser.before_first``."""
        from dmlc_core_tpu.data.parsers import Parser

        parser = Parser.create(uri, part, nparts, format, nthread)
        first = [True]

        def make_iter() -> Iterator[Any]:
            if not first[0]:
                parser.before_first()
            first[0] = False
            return iter(parser)

        return cls(make_iter, name=f"uri:{format or 'auto'}")

    @classmethod
    def from_row_iter(cls, row_iter: Any) -> "Dataset":
        """Wrap a :class:`~dmlc_core_tpu.data.iter.RowBlockIter` (its
        ``__iter__`` rewinds via ``before_first``)."""
        return cls(lambda: iter(row_iter), name="row_iter")

    @classmethod
    def from_iterable(cls, src: Iterable[Any] | Callable[[], Iterator[Any]],
                      name: str = "iterable") -> "Dataset":
        """Wrap any iterable (re-iterated per epoch) or iterator factory."""
        make = src if callable(src) else (lambda: iter(src))
        return cls(make, name=name)

    @classmethod
    def from_tailer(cls, tailer: Any, chunk_records: int,
                    timeout: Optional[float] = None,
                    stop: Optional[Callable[[], bool]] = None) -> "Dataset":
        """Single-pass dataset of raw-record chunks pulled from a
        :class:`~dmlc_core_tpu.stream.tail.RecordIOTailer`: each item is
        a list of ≥ 1 records (up to ``chunk_records``, sooner on
        ``timeout``).  Ends when ``stop()`` goes true or a timeout poll
        returns nothing."""

        def make_iter() -> Iterator[List[bytes]]:
            while not (stop is not None and stop()):
                recs = tailer.wait_records(chunk_records, timeout=timeout,
                                           stop=stop)
                if not recs:
                    return
                yield recs

        return cls(make_iter, name=f"tail:{tailer.name}")

    # -- transforms ------------------------------------------------------
    def map(self, fn: Callable[[Any], Any],
            name: Optional[str] = None) -> "Dataset":
        """Lazily apply ``fn`` to every item."""
        src = self._make_iter
        return Dataset(lambda: (fn(x) for x in src()),
                       name=name or self.name)

    def dense_slabs(self, num_col: int, batch_rows: int) -> "Dataset":
        """CSR RowBlock items → dense ``(X, y, w)`` float32 slabs of
        ≤ ``batch_rows`` rows.

        Pages densify straight into one reused staging buffer; pages
        straddling a slab boundary split transparently.  Host memory
        stays bounded by one slab regardless of the dataset; the yielded
        arrays are VIEWS of the reused buffers, so consumers must copy
        (or upload with an explicit host copy) before advancing."""
        src = self._make_iter
        return Dataset(lambda: _dense_slab_iter(src(), num_col, batch_rows),
                       name=self.name)

    # -- pipelining ------------------------------------------------------
    def prefetch(self, capacity: int = 8,
                 name: Optional[str] = None) -> "Dataset":
        """Move production onto a
        :class:`~dmlc_core_tpu.io.threaded_iter.ThreadedIter` producer
        thread (bounded buffer of ``capacity`` items).  The threaded
        stage is created per-iteration and destroyed when the iterator
        is exhausted or closed."""
        from dmlc_core_tpu.io.threaded_iter import ThreadedIter

        src = self._make_iter
        label = name or self.name

        def make_iter() -> Iterator[Any]:
            inner = src()

            def next_fn(_cell):
                return next(inner, None)

            tit: ThreadedIter = ThreadedIter(max_capacity=capacity,
                                             name=label)
            tit.init(next_fn)
            try:
                while (item := tit.next()) is not None:
                    yield item
            finally:
                tit.destroy()

        return Dataset(make_iter, name=label)

    def device_feed(self, sharding: Any, depth: int = 2,
                    host_prefetch: int = 4) -> Any:
        """Hand the dataset to
        :class:`~dmlc_core_tpu.data.device_feed.DeviceFeed`: host
        parsing on a producer thread, ``device_put`` onto ``sharding``
        dispatched ``depth`` batches ahead."""
        from dmlc_core_tpu.data.device_feed import DeviceFeed

        return DeviceFeed(self._make_iter, sharding, depth=depth,
                          host_prefetch=host_prefetch)


# ---------------------------------------------------------------------------
# dense event codec (examples / bench / tests / online trainer default)
# ---------------------------------------------------------------------------

def encode_dense_event(features: np.ndarray, label: float) -> bytes:
    """One live event → RecordIO payload bytes: ``[label, f0 … f{F-1}]``
    little-endian float32."""
    row = np.empty(len(features) + 1, dtype="<f4")
    row[0] = label
    row[1:] = features
    return row.tobytes()


def encode_dense_events(X: np.ndarray, y: np.ndarray) -> List[bytes]:
    """Vectorized :func:`encode_dense_event` over a batch."""
    X = np.asarray(X, dtype="<f4")
    y = np.asarray(y, dtype="<f4")
    CHECK(len(X) == len(y), "encode_dense_events: X/y length mismatch")
    packed = np.concatenate([y[:, None], X], axis=1).astype("<f4")
    return [packed[i].tobytes() for i in range(len(packed))]


def decode_dense_events(records: List[bytes],
                        n_features: int) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`encode_dense_event` over a chunk of records:
    ``(X [n, F] float32, y [n] float32)``."""
    width = (n_features + 1) * 4
    for r in records:
        CHECK(len(r) == width,
              f"decode_dense_events: record of {len(r)} bytes, expected "
              f"{width} (n_features={n_features})")
    flat = np.frombuffer(b"".join(records), dtype="<f4")
    mat = flat.reshape(len(records), n_features + 1)
    return np.ascontiguousarray(mat[:, 1:]), np.ascontiguousarray(mat[:, 0])
