"""Tracing & profiling — a strict superset of the reference's timing story.

The reference's only primitive is ``include/dmlc/timer.h :: GetTime()``
(SURVEY.md §5: "tracing/profiling: essentially none").  The TPU substrate
owes more: step time vs infeed stall is THE number that decides whether
the host pipeline (ThreadedIter → device_put) keeps the chip busy.  This
module provides

* :func:`device_trace` — context manager around ``jax.profiler.trace``:
  captures an XLA/TensorBoard profile (HLO timelines, TPU utilization)
  into a logdir;
* :func:`annotate` / :func:`step_annotation` — named regions that show up
  inside the device trace (thin wrappers over jax.profiler annotations,
  no-ops if unavailable);
* :class:`Tracer` — a dependency-free host-side event tracer writing
  Chrome ``chrome://tracing`` / Perfetto JSON, so host pipeline phases
  (read, parse, device_put, step) can be eyeballed against each other
  without TensorBoard.

All host events go through ``base.timer.get_time`` so Tracer timestamps
line up with the rest of the framework's timing.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Any, Dict, Iterator, List, Optional

from dmlc_core_tpu.base.timer import get_time

__all__ = ["device_trace", "annotate", "step_annotation", "Tracer",
           "global_tracer"]


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Capture a JAX/XLA device profile into ``logdir``.

    View with TensorBoard's profile plugin.  Degrades to a no-op if the
    profiler cannot start (e.g. another trace is active).
    """
    import jax

    try:
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
        started = True
    except Exception:  # noqa: BLE001 — profiling must never break training
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region visible in the device trace (TraceAnnotation)."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        ctx = contextlib.nullcontext()
    with ctx:
        yield


@contextlib.contextmanager
def step_annotation(step: int, name: str = "train") -> Iterator[None]:
    """Step marker so the profile viewer groups per-step activity."""
    try:
        import jax

        ctx = jax.profiler.StepTraceAnnotation(name, step_num=step)
    except Exception:  # noqa: BLE001
        ctx = contextlib.nullcontext()
    with ctx:
        yield


class Tracer:
    """Host-side event tracer → Chrome/Perfetto trace JSON.

    >>> tr = Tracer()
    >>> with tr.scope("parse"):
    ...     ...
    >>> tr.counter("queue_depth", 3)
    >>> tr.save("/tmp/trace.json")   # open in chrome://tracing / Perfetto

    Thread-safe; events carry real thread ids so producer/consumer
    overlap (the ThreadedIter pipeline) is visible on separate rows.
    """

    def __init__(self) -> None:
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._t0 = get_time()

    def _us(self) -> float:
        return (get_time() - self._t0) * 1e6

    @contextlib.contextmanager
    def scope(self, name: str, **args: Any) -> Iterator[None]:
        """A complete ("X") duration event on the calling thread's row."""
        start = self._us()
        try:
            yield
        finally:
            end = self._us()
            with self._lock:
                self._events.append({
                    "name": name, "ph": "X", "ts": start,
                    "dur": end - start, "pid": os.getpid(),
                    "tid": threading.get_ident(),
                    "args": args or {},
                })

    def instant(self, name: str, **args: Any) -> None:
        with self._lock:
            self._events.append({
                "name": name, "ph": "i", "ts": self._us(), "s": "t",
                "pid": os.getpid(), "tid": threading.get_ident(),
                "args": args or {},
            })

    def counter(self, name: str, value: float, series: str = "value") -> None:
        with self._lock:
            self._events.append({
                "name": name, "ph": "C", "ts": self._us(),
                "pid": os.getpid(), "args": {series: value},
            })

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def save(self, path: str) -> str:
        with self._lock:
            payload = {"traceEvents": list(self._events),
                       "displayTimeUnit": "ms"}
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


_global: Optional[Tracer] = None
_global_lock = threading.Lock()


def global_tracer() -> Tracer:
    """Process-wide Tracer (created on first use)."""
    global _global
    with _global_lock:
        if _global is None:
            _global = Tracer()
        return _global
