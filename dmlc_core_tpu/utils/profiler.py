"""Tracing & profiling — a strict superset of the reference's timing story.

The reference's only primitive is ``include/dmlc/timer.h :: GetTime()``
(SURVEY.md §5: "tracing/profiling: essentially none").  The TPU substrate
owes more: step time vs infeed stall is THE number that decides whether
the host pipeline (ThreadedIter → device_put) keeps the chip busy.  This
module provides

* :func:`device_trace` — context manager around ``jax.profiler.trace``:
  captures an XLA/TensorBoard profile (HLO timelines, TPU utilization)
  into a logdir;
* :func:`annotate` / :func:`step_annotation` — named regions that show up
  inside the device trace (thin wrappers over jax.profiler annotations,
  no-ops if unavailable);
* :class:`Tracer` — a dependency-free host-side event tracer writing
  Chrome ``chrome://tracing`` / Perfetto JSON, so host pipeline phases
  (read, parse, device_put, step) can be eyeballed against each other
  without TensorBoard.

All host events go through ``base.timer.get_time`` so Tracer timestamps
line up with the rest of the framework's timing.

Hot-path integration (PR: observability substrate): the instrumented
pipelines (ThreadedIter, parsers, collectives, the GBT engines) emit
scopes/instants to :func:`global_tracer` ONLY while host tracing is
switched on (:func:`set_tracing` / ``DMLC_TRACE=1``) — tracing is
event-per-item and unbounded-ish in volume, so unlike the aggregate
metrics layer (``base.metrics``) it defaults OFF.  The Tracer buffer is
capped (``max_events``) so a scope left enabled for a long run degrades
to dropped events, never to unbounded host memory.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from dmlc_core_tpu.base.timer import get_time

__all__ = ["device_trace", "annotate", "step_annotation", "Tracer",
           "global_tracer", "tracing_enabled", "set_tracing"]

_TRACING = os.environ.get("DMLC_TRACE", "0").lower() in ("1", "true", "on",
                                                         "yes")


def tracing_enabled() -> bool:
    """Fast global switch read by hot-path call sites before they touch
    :func:`global_tracer` — one global read + branch when off."""
    return _TRACING


def set_tracing(on: bool) -> None:
    """Enable/disable host-event tracing process-wide (also:
    ``DMLC_TRACE=1``)."""
    global _TRACING
    _TRACING = bool(on)


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Capture a JAX/XLA device profile into ``logdir``.

    View with TensorBoard's profile plugin.  Degrades to a no-op if the
    profiler cannot start (e.g. another trace is active).
    """
    import jax

    try:
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
        started = True
    except Exception:  # noqa: BLE001 — profiling must never break training
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region visible in the device trace (TraceAnnotation)."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        ctx = contextlib.nullcontext()
    with ctx:
        yield


@contextlib.contextmanager
def step_annotation(step: int, name: str = "train") -> Iterator[None]:
    """Step marker so the profile viewer groups per-step activity."""
    try:
        import jax

        ctx = jax.profiler.StepTraceAnnotation(name, step_num=step)
    except Exception:  # noqa: BLE001
        ctx = contextlib.nullcontext()
    with ctx:
        yield


class Tracer:
    """Host-side event tracer → Chrome/Perfetto trace JSON.

    >>> tr = Tracer()
    >>> with tr.scope("parse"):
    ...     ...
    >>> tr.counter("queue_depth", 3)
    >>> tr.save("/tmp/trace.json")   # open in chrome://tracing / Perfetto

    Thread-safe; events carry real thread ids so producer/consumer
    overlap (the ThreadedIter pipeline) is visible on separate rows.
    The buffer is bounded: past ``max_events`` new events are dropped
    (and counted — ``dropped`` rides into the saved trace's metadata)
    rather than growing host memory without limit.
    """

    def __init__(self, max_events: int = 200_000) -> None:
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        # _t0 (monotonic) timestamps events; _wall0 is the SAME instant
        # on the wall clock, so cross-process merges (trace_collect) can
        # line shards up on a shared epoch despite per-process _t0s
        self._t0 = get_time()
        self._wall0 = time.time()
        self._max_events = max_events
        self.dropped = 0
        #: process identity stamped into saved traces (set_meta)
        self.role = ""
        self.rank = -1

    def set_meta(self, role: Optional[str] = None,
                 rank: Optional[int] = None) -> None:
        """Stamp this process's fleet identity (role/rank) into every
        subsequent :meth:`save` — the Perfetto ``process_name`` row and
        the merge metadata ``trace_collect`` keys shards by."""
        with self._lock:
            if role is not None:
                self.role = str(role)
            if rank is not None:
                self.rank = int(rank)

    def _us(self) -> float:
        return (get_time() - self._t0) * 1e6

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    @contextlib.contextmanager
    def scope(self, name: str, **args: Any) -> Iterator[None]:
        """A complete ("X") duration event on the calling thread's row."""
        start = self._us()
        try:
            yield
        finally:
            end = self._us()
            self._append({
                "name": name, "ph": "X", "ts": start,
                "dur": end - start, "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": args or {},
            })

    def instant(self, name: str, **args: Any) -> None:
        self._append({
            "name": name, "ph": "i", "ts": self._us(), "s": "t",
            "pid": os.getpid(), "tid": threading.get_ident(),
            "args": args or {},
        })

    def counter(self, name: str, value: float, series: str = "value") -> None:
        self._append({
            "name": name, "ph": "C", "ts": self._us(),
            "pid": os.getpid(), "args": {series: value},
        })

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    @staticmethod
    def _metadata_events(events: List[Dict[str, Any]], role: str,
                         rank: int) -> List[Dict[str, Any]]:
        """Chrome-trace "M" metadata rows: without them, two processes'
        traces opened together in Perfetto are indistinguishable."""
        pid = os.getpid()
        pname = (f"{role}-{rank}" if role else "process")
        meta: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{pname} pid={pid}"},
        }]
        tids = {ev["tid"] for ev in events if "tid" in ev}
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid in sorted(tids):
            meta.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": names.get(tid, f"thread-{tid}")},
            })
        return meta

    def save(self, path: str) -> str:
        with self._lock:
            events = list(self._events)
            payload: Dict[str, Any] = {
                "traceEvents": self._metadata_events(
                    events, self.role, self.rank) + events,
                "displayTimeUnit": "ms",
                "otherData": {
                    "dropped_events": self.dropped,
                    "epoch_us": self._wall0 * 1e6,
                    "pid": os.getpid(),
                    "role": self.role,
                    "rank": self.rank,
                },
            }
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


_global: Optional[Tracer] = None
_global_lock = threading.Lock()


def global_tracer() -> Tracer:
    """Process-wide Tracer (created on first use)."""
    global _global
    with _global_lock:
        if _global is None:
            _global = Tracer()
        return _global
